"""Worker entrypoint for :class:`ddw_tpu.runtime.launcher.Launcher` multi-process mode.

Each spawned process: initialize the distributed runtime (the ``hvd.init()`` /
mpirun-rendezvous analog), unpickle and run the train fn, and — rank 0 only — write
the return value back for the driver (the HorovodRunner return contract,
reference ``03_model_training_distributed.py:375``).
"""

from __future__ import annotations

import pickle
import sys
import traceback


def main() -> int:
    payload_path, result_path = sys.argv[1], sys.argv[2]
    from ddw_tpu.runtime.mesh import initialize_distributed, is_coordinator

    initialize_distributed()  # reads DDW_COORDINATOR / DDW_NUM_PROCESSES / DDW_PROCESS_ID
    with open(payload_path, "rb") as f:
        fn_spec, args, kwargs = pickle.load(f)
    kind, blob, qualname = fn_spec
    if kind == "pickled":
        fn = pickle.loads(blob)
    else:  # "by_file": re-import the driver script under a non-__main__ name
        import importlib.util

        spec = importlib.util.spec_from_file_location("ddw_launched_main", blob)
        mod = importlib.util.module_from_spec(spec)
        sys.modules["ddw_launched_main"] = mod
        spec.loader.exec_module(mod)
        fn = mod
        for part in qualname.split("."):
            fn = getattr(fn, part)
    try:
        value = fn(*args, **kwargs)
        status = ("ok", value)
    except Exception:
        status = ("error", traceback.format_exc())
    if is_coordinator():
        try:
            blob = pickle.dumps(status)
        except Exception as e:  # unpicklable return value: report, don't mask
            status = ("error", f"rank-0 return value is not picklable: {e!r}")
            blob = pickle.dumps(status)
        with open(result_path, "wb") as f:
            f.write(blob)
    return 0 if status[0] == "ok" else 1


if __name__ == "__main__":
    sys.exit(main())
