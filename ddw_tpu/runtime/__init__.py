from ddw_tpu.runtime.mesh import (  # noqa: F401
    HybridMeshSpec,
    MeshSpec,
    device_slice_index,
    make_data_mesh,
    make_hybrid_mesh,
    make_mesh,
    initialize_distributed,
    process_index,
    process_count,
    is_coordinator,
    local_device_count,
    global_device_count,
)
from ddw_tpu.runtime.collectives import (  # noqa: F401
    all_reduce_mean,
    all_reduce_sum,
    broadcast_from,
    all_gather_axis,
    ring_all_reduce,
)
from ddw_tpu.runtime.launcher import (  # noqa: F401
    ElasticEvent,
    GangError,
    Launcher,
)
from ddw_tpu.runtime.elastic import (  # noqa: F401
    ElasticRestart,
    GangRendezvous,
    elastic_barrier,
    elastic_enabled,
    host_all_reduce,
    maybe_elastic_restart,
)
from ddw_tpu.runtime.faults import (  # noqa: F401
    FaultInjected,
    Preempted,
    install_preemption_handler,
    maybe_fault,
    preemption_requested,
    request_preemption,
    reset_preemption,
)
from ddw_tpu.runtime.supervisor import (  # noqa: F401
    AttemptReport,
    GangFailure,
    GangSupervisor,
    restart_generation,
)
