"""Deterministic fault injection + graceful-preemption primitives.

The gang's failure modes (worker crash, stall, silent exit, torn checkpoint,
preemption, coordinator-port collision) are rare and timing-dependent in
production but must be *reproducible in CI on CPU* for the recovery machinery
(:mod:`ddw_tpu.runtime.supervisor`, checkpoint quarantine) to stay tested.
This module turns each of them into an env-var knob:

    DDW_FAULT=<kind>[:key=value]*

Kinds (and the hook site each fires at):

========== ============ ==========================================================
kind        site         effect when the spec matches
========== ============ ==========================================================
crash       step         ``os._exit(EXIT_FAULT_CRASH)`` — a hard SIGKILL-like death
kill        step         ``SIGKILL`` to this process — a true signal death (exit
                         code ``-9``): the "one rank dies mid-epoch" arm the
                         elastic drills key on, with signal forensics intact
raise       step         raise :class:`FaultInjected` — the worker writes an error
                         result and exits nonzero (exercises the rank-0-traceback
                         surfacing path)
stall       step         sleep forever — exercises the gang deadline
exit0_early step         ``os._exit(0)`` before writing a result — a "successful"
                         exit that leaves the driver with no result.pkl
preempt     step         deliver SIGTERM to this process (the cluster-manager
                         preemption analog); the installed handler sets the flag
                         the trainers' step loops check
ckpt_torn   step         drop a torn (partial, non-atomic) step dir into the
                         checkpoint directory, then crash — exercises quarantine
ckpt_async_torn
            ckpt_async   fires INSIDE the background checkpoint writer thread:
                         publishes a torn step dir for the step being written
                         (as a filesystem that lost the atomic discipline
                         would), then dies mid-write — exercises async-write
                         quarantine across restart generations
bind_fail   coord_bind   ``os._exit(EXIT_COORD_BIND)`` before the coordinator
                         binds — the port-collision (TOCTOU) analog
host_lost   step         ``os._exit(EXIT_HOST_LOST)`` — the permanent-loss
                         verdict: the rank dies AND its respawn always fails
                         (``egen`` defaults to ``*`` for this kind only, so a
                         respawned incarnation dies again at the same step).
                         The launcher treats the exit code as "host gone for
                         good", skips the respawn budget, and goes straight
                         to the shrink ladder (see ``min_world_size``)
shrink_veto shrink_vote  raise :class:`ShrinkVeto` inside a survivor's vote on
                         a shrink record: the vote is recorded as ``veto``,
                         the proposal is pinned, and the driver retries at a
                         bumped generation or falls back to whole-world
                         restart. ``step`` matches the per-process vote
                         ordinal and defaults to 0 — veto the first proposal,
                         ack the retry; ``step=*`` vetoes every proposal (the
                         abort arm)
========== ============ ==========================================================

Match keys (all optional): ``rank=N`` (default: any rank; read from
``DDW_PROCESS_ID``), ``step=N`` (default: first check of the site),
``gen=N|*`` (restart generation, from ``DDW_RESTART_GEN``; default 0 so a
fault fires in the first generation only and the restarted gang runs clean),
``egen=N|*`` (ELASTIC generation, from ``DDW_ELASTIC_GEN``; default 0 so the
single rank an elastic recovery respawned runs clean — ``egen=*`` makes the
fault chase every respawn, the deterministic "re-rendezvous keeps failing"
drill that forces the whole-world fallback), ``attempt=N|*`` (spawn attempt
within one generation, from ``DDW_SPAWN_ATTEMPT``; default 0 so a bind
failure clears on the launcher's respawn). ``*`` means "any".

Several specs can be chained with ``;`` —
``DDW_FAULT=host_lost:rank=2:step=3;shrink_veto:rank=0`` — and each hook
site fires the first chained spec that matches it, so one drill can combine
a permanent rank death with a shrink-vote veto. ``rank`` always matches the
process's *spawn-time* rank for faults that fire before a shrink is adopted
(the shrink remap updates ``DDW_PROCESS_ID`` only at adoption).

Example: ``DDW_FAULT=crash:rank=1:step=3`` kills rank 1 at global step 3 of
the first generation; every other process/step/generation is untouched. With
no ``DDW_FAULT`` set, :func:`maybe_fault` is a near-free no-op — the hooks are
safe to leave in production step loops.

Serve scope
-----------

The serving stack (:mod:`ddw_tpu.serve`, :mod:`ddw_tpu.gateway`) has its own
failure geometry: replicas are *threads in one process*, so a "crash" must
kill an engine loop, not the interpreter, and the match keys are per-replica
rather than per-rank. A ``serve:``-prefixed spec targets those hooks and is
invisible to the gang sites (and vice versa):

    DDW_FAULT=serve:<kind>[:site=prefill|decode|admit|batch|*][:replica=N|*]
                           [:after=N][:gen=N|*]

The ``batch`` site fires at the batch lane's admission boundary (an engine
about to backfill queued ``lm_batch``/``image_batch`` work into idle
capacity) — the drill point for killing a replica mid-job and asserting the
host-side job ledger resumes with no duplicated or lost items.

Serve kinds: ``crash`` (raise :class:`ServeCrash` — the engine loop dies,
transitions the replica to its terminal FAILED state and fails every pending
future with a structured ``ReplicaFailed``), ``raise`` (raise
:class:`FaultInjected` — one recoverable loop error; the replica degrades and
its consecutive-error budget decides), ``stall`` (the hook blocks while the
spec stays configured — exercises last-tick-age stall detection and the
circuit breaker; clearing ``DDW_FAULT`` resumes the tick cleanly, so a test
can hold an engine mid-decode and release it, while the engine's stop/fail
signal aborts hard so a force-failed thread always stays joinable).

Defaults mirror the gang scope's single-shot-drill safety: ``replica=0``
(one of N replicas dies, the siblings keep serving), ``site=*`` (first hook
reached), ``after=0`` (the first matching check fires), ``gen=0`` (the
supervisor-restarted replica runs clean). The ``after=N`` key counts
invocations of the matching site *within one replica generation*, so
"die mid-stream on the 5th decode tick" is deterministic on CPU.

Deploy scope
------------

Rollouts (:mod:`ddw_tpu.deploy`) get their own arms — a ``deploy:`` spec
is invisible to both the gang and the serve sites:

    DDW_FAULT=deploy:degrade_canary[:replica=N|*][:ttft_ms=F][:errors=K]
    DDW_FAULT=deploy:crash_mid_roll[:after=N]

========= ========== ========================================================
kind       site       effect when the spec matches
========= ========== ========================================================
degrade_   judge      the canary judge's measurement of the new-checkpoint
canary                replica is degraded exactly as a bad checkpoint would
                      degrade it: ``ttft_ms`` of real latency is injected
                      into each judge probe against the canary (the probe IS
                      a request to that replica) and ``errors`` synthetic
                      probe failures are charged against it — driving the
                      reject verdict deterministically with zero client
                      impact
crash_     mid_roll   raise :class:`DeployCrash` at the journal boundary
mid_roll              BEFORE rolling the ``after``-th replica — the control
                      thread dies without finalizing the rollout journal,
                      the in-process stand-in for a gateway SIGKILL
                      mid-rollout (the reconciler drills key on it)
========= ========== ========================================================

``replica`` defaults to ``*`` (any — the judge passes the canary's index);
``ttft_ms`` defaults to 250; ``errors`` to 0; ``after`` to 0 (crash before
the first replica rolls).

Autoscale scope
---------------

The autoscaler (:mod:`ddw_tpu.autoscale`) gets its own arms — an
``autoscale:`` spec is invisible to the gang, serve, and deploy sites:

    DDW_FAULT=autoscale:spawn_fail[:after=N]
    DDW_FAULT=autoscale:stall_drain
    DDW_FAULT=autoscale:flap
    DDW_FAULT=autoscale:crash_mid_scale[:after=N]

=============== ========= ===================================================
kind             site      effect when the spec matches
=============== ========= ===================================================
spawn_fail       spawn     raise :class:`FaultInjected` where the controller
                           spawns a surge child — the scale-out must abort
                           with the journal finalized and ZERO capacity
                           consumed (the cold replica was never admitted)
stall_drain      drain     block while the spec stays configured (clearing
                           ``DDW_FAULT`` resumes; the controller's abort
                           signal raises) — holds a scale-in's drain wait
                           open so the drain deadline fires and the victim
                           is re-admitted instead of killed with work aboard
flap             decide    RETURNED for the controller to apply: synthetic
                           pressure alternating out/in every decide tick —
                           the hysteresis band + per-direction cooldowns
                           must absorb it into a bounded number of real
                           scale events
crash_mid_scale  mid_scale raise :class:`AutoscaleCrash` at the journal
                           boundary after ``after`` journaled steps — the
                           reconciler at ``Gateway.start()`` drills on the
                           unfinalized scale journal it leaves behind
=============== ========= ===================================================
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time

# Worker exit codes with supervisor/launcher meaning. Chosen in the 64..113
# user range so they can't collide with shell/signal conventions.
EXIT_FAULT_CRASH = 77   # injected hard crash (deterministic stand-in for SIGKILL)
EXIT_PREEMPTED = 83     # graceful preemption: checkpointed, then clean exit
EXIT_COORD_BIND = 84    # coordinator could not bind its port (spawn-time race)
EXIT_HOST_LOST = 85     # permanent host loss: respawn is futile, shrink instead

KINDS = ("crash", "kill", "raise", "stall", "exit0_early", "preempt",
         "ckpt_torn", "ckpt_async_torn", "bind_fail", "host_lost",
         "shrink_veto")

_SITE_BY_KIND = {k: ("coord_bind" if k == "bind_fail"
                     else "ckpt_async" if k == "ckpt_async_torn"
                     else "shrink_vote" if k == "shrink_veto"
                     else "step")
                 for k in KINDS}


class FaultInjected(RuntimeError):
    """Raised by the ``raise`` fault kind — an injected application error."""


class ShrinkVeto(RuntimeError):
    """Raised by the ``shrink_veto`` kind inside a survivor's vote on a
    shrink record (:meth:`~ddw_tpu.runtime.elastic.GangRendezvous._cast_vote`
    catches it and records the veto) — the deterministic "one survivor
    refuses the new topology" arm that pins the driver's retry/abort path."""


class ServeCrash(RuntimeError):
    """Raised by the ``serve:crash`` kind (and by an aborted ``serve:stall``)
    — the serving-engine analog of a hard rank death: the engine loop must
    die, fail its pending futures, and leave the replica FAILED."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Parsed ``DDW_FAULT`` value. ``None`` fields match anything."""

    kind: str
    rank: int | None = None
    step: int | None = None
    gen: int | None = 0
    egen: int | None = 0
    attempt: int | None = 0

    @property
    def site(self) -> str:
        return _SITE_BY_KIND[self.kind]

    def matches(self, site: str, step: int | None = None,
                rank: int | None = None, gen: int | None = None,
                attempt: int | None = None,
                egen: int | None = None) -> bool:
        """Pure matching logic (env-independent — unit-testable)."""
        if site != self.site:
            return False
        if self.rank is not None and rank != self.rank:
            return False
        if self.gen is not None and gen != self.gen:
            return False
        if self.egen is not None and (egen or 0) != self.egen:
            return False
        if self.attempt is not None and attempt != self.attempt:
            return False
        if self.step is not None and step != self.step:
            return False
        return True


def parse_fault(spec: str) -> FaultSpec | None:
    """Parse a ``DDW_FAULT`` value; empty/None -> None. Malformed specs raise
    (a typo'd fault that silently never fires would "pass" every CI run).
    A ``serve:``-scoped spec parses as None here — it targets the serving
    hooks (:func:`parse_serve_fault`), not the gang sites — but still
    validates, so a typo'd serve spec fails loudly at the first gang hook
    too."""
    if not spec:
        return None
    if spec.startswith("serve:"):
        parse_serve_fault(spec)     # validate, then ignore at gang sites
        return None
    if spec.startswith("deploy:"):
        parse_deploy_fault(spec)    # validate, then ignore at gang sites
        return None
    if spec.startswith("autoscale:"):
        parse_autoscale_fault(spec)  # validate, then ignore at gang sites
        return None
    parts = spec.split(":")
    kind = parts[0].strip()
    if kind not in KINDS:
        raise ValueError(f"unknown DDW_FAULT kind {kind!r}; expected one of "
                         f"{KINDS}")
    fields: dict[str, int | None] = {}
    for part in parts[1:]:
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        if key not in ("rank", "step", "gen", "egen", "attempt"):
            raise ValueError(f"unknown DDW_FAULT key {key!r} in {spec!r}")
        val = val.strip()
        fields[key] = None if val == "*" else int(val)
    # Per-kind defaults: host_lost means "the respawn always fails too", so
    # it chases every elastic generation unless pinned; shrink_veto means
    # "reject ONCE" (vote ordinal 0), so the driver's retry gets an ack.
    egen_default = None if kind == "host_lost" else 0
    step_default = 0 if kind == "shrink_veto" else None
    return FaultSpec(kind=kind, rank=fields.get("rank"),
                     step=fields["step"] if "step" in fields
                     else step_default,
                     gen=fields.get("gen", 0),
                     egen=fields["egen"] if "egen" in fields
                     else egen_default,
                     attempt=fields.get("attempt", 0))


def _env_int(name: str, default: int = 0) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _fault_parts() -> list[str]:
    """The ``;``-chained raw spec strings in ``DDW_FAULT`` (possibly one)."""
    raw = os.environ.get("DDW_FAULT", "")
    return [p.strip() for p in raw.split(";") if p.strip()]


def active_faults() -> list[FaultSpec]:
    """Every gang-scope fault currently configured (``;``-chained specs all
    parse; scoped serve/deploy/autoscale entries validate but drop out)."""
    specs = []
    for part in _fault_parts():
        spec = parse_fault(part)
        if spec is not None:
            specs.append(spec)
    return specs


def active_fault() -> FaultSpec | None:
    """The first currently configured gang-scope fault, re-read from the env
    on every call (tests monkeypatch ``DDW_FAULT`` mid-process)."""
    specs = active_faults()
    return specs[0] if specs else None


def maybe_fault(site: str, step: int | None = None,
                ckpt_dir: str | None = None) -> None:
    """Hook call: fire the first configured fault whose spec matches this
    site / step / rank / generation / spawn attempt. No-op without
    ``DDW_FAULT``."""
    if "DDW_FAULT" not in os.environ:  # fast path for production step loops
        return
    for spec in active_faults():
        if spec.matches(
                site, step=step,
                rank=_env_int("DDW_PROCESS_ID", 0),
                gen=_env_int("DDW_RESTART_GEN", 0),
                egen=_env_int("DDW_ELASTIC_GEN", 0),
                attempt=_env_int("DDW_SPAWN_ATTEMPT", 0)):
            _fire(spec, step, ckpt_dir)
            return


def _fire(spec: FaultSpec, step: int | None, ckpt_dir: str | None) -> None:
    where = f"rank {_env_int('DDW_PROCESS_ID', 0)}, step {step}, " \
            f"gen {_env_int('DDW_RESTART_GEN', 0)}"
    if spec.kind == "crash":
        os._exit(EXIT_FAULT_CRASH)
    if spec.kind == "kill":
        # A true signal death (waitpid code -SIGKILL): the launcher's
        # forensics record the signal, and no atexit/finally runs — the
        # closest CPU-reproducible stand-in for a preempted/OOM-killed host.
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(60.0)    # pending-signal window; never survives it
    if spec.kind == "raise":
        raise FaultInjected(f"injected fault ({where})")
    if spec.kind == "stall":
        while True:  # hold the gang hostage until the deadline kill
            time.sleep(0.5)
    if spec.kind == "exit0_early":
        os._exit(0)
    if spec.kind == "preempt":
        # The cluster-manager SIGTERM, delivered to ourselves: the installed
        # handler sets the flag; the step loop notices and checkpoints.
        # Install first so an in-process (np=-1) test doesn't die to the
        # default SIGTERM disposition.
        install_preemption_handler()
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if spec.kind == "ckpt_torn":
        if ckpt_dir:
            _write_torn_step_dir(ckpt_dir, (step or 0) + 1000)
        os._exit(EXIT_FAULT_CRASH)
    if spec.kind == "ckpt_async_torn":
        # Fires on the BACKGROUND WRITER THREAD (the ckpt_async site lives
        # inside the async checkpoint writers): publish a torn dir for the
        # very step being written — what a non-atomic filesystem could leave
        # after losing the rename/fsync discipline — then die mid-write.
        # latest_step()/latest_complete_step() must quarantine it on restart.
        if ckpt_dir:
            _write_torn_step_dir(ckpt_dir, step or 0)
        os._exit(EXIT_FAULT_CRASH)
    if spec.kind == "bind_fail":
        os._exit(EXIT_COORD_BIND)
    if spec.kind == "host_lost":
        # The permanent-loss verdict, deterministically: the distinguished
        # exit code tells the launcher respawning is futile (a real lost
        # host earns the same verdict via the transport probe / exhausted
        # respawn budget), so it goes straight to shrink-or-whole-world.
        os._exit(EXIT_HOST_LOST)
    if spec.kind == "shrink_veto":
        raise ShrinkVeto(f"injected shrink veto ({where})")


def _write_torn_step_dir(ckpt_dir: str, step: int) -> str:
    """A partial step dir as a non-atomic writer killed mid-write would leave:
    truncated state bytes, no metadata sidecar. ``latest_step``/``restore``
    must quarantine it and fall back to the previous good step."""
    d = os.path.join(ckpt_dir, f"step_{step:010d}")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "state.msgpack"), "wb") as f:
        f.write(b"torn")
    return d


# ---------------------------------------------------------------------------
# Serve scope: per-replica fault injection for the online serving stack.
# ---------------------------------------------------------------------------

SERVE_KINDS = ("crash", "raise", "stall")
SERVE_SITES = ("prefill", "decode", "admit", "batch")


@dataclasses.dataclass(frozen=True)
class ServeFaultSpec:
    """Parsed ``DDW_FAULT=serve:...`` value. ``None`` fields match anything;
    defaults make a bare ``serve:crash`` a safe single-replica drill (replica
    0, first hook reached, first generation only)."""

    kind: str
    site: str | None = None       # None = any serve site
    replica: int | None = 0
    after: int = 0                # fire on the Nth matching check (per gen)
    gen: int | None = 0

    def matches(self, site: str, replica: int, n: int, gen: int) -> bool:
        """Pure matching logic. ``n`` is the engine's own invocation count
        for this site within its current generation (0-based)."""
        if self.site is not None and site != self.site:
            return False
        if self.replica is not None and replica != self.replica:
            return False
        if self.gen is not None and gen != self.gen:
            return False
        return n >= self.after


def parse_serve_fault(spec: str) -> ServeFaultSpec | None:
    """Parse a ``serve:``-scoped ``DDW_FAULT`` value; non-serve specs (and
    empty) -> None. Malformed serve specs raise, same rule as
    :func:`parse_fault`."""
    if not spec or not spec.startswith("serve:"):
        return None
    parts = spec.split(":")[1:]
    if not parts or parts[0].strip() not in SERVE_KINDS:
        raise ValueError(f"unknown DDW_FAULT serve kind "
                         f"{parts[0].strip() if parts else ''!r}; expected "
                         f"one of {SERVE_KINDS}")
    kind = parts[0].strip()
    fields: dict[str, object] = {}
    for part in parts[1:]:
        if not part:
            continue
        key, _, val = part.partition("=")
        key, val = key.strip(), val.strip()
        if key == "site":
            if val != "*" and val not in SERVE_SITES:
                raise ValueError(f"unknown DDW_FAULT serve site {val!r}; "
                                 f"expected one of {SERVE_SITES} or '*'")
            fields["site"] = None if val == "*" else val
        elif key in ("replica", "gen"):
            fields[key] = None if val == "*" else int(val)
        elif key == "after":
            fields[key] = int(val)
        else:
            raise ValueError(f"unknown DDW_FAULT serve key {key!r} in "
                             f"{spec!r}")
    return ServeFaultSpec(kind=kind, **fields)


def active_serve_fault() -> ServeFaultSpec | None:
    """The currently configured serve fault, re-read from the env on every
    call (tests monkeypatch ``DDW_FAULT`` mid-process)."""
    for part in _fault_parts():
        spec = parse_serve_fault(part)
        if spec is not None:
            return spec
    return None


def maybe_serve_fault(site: str, replica: int, n: int, gen: int,
                      should_abort=None) -> None:
    """Serving-engine hook: fire the configured ``serve:`` fault iff its
    spec matches this site / replica / invocation count / generation.
    No-op without ``DDW_FAULT``. ``should_abort`` (a nullary bool callable —
    the engine's stop-or-fail signal) lets an injected stall end without
    leaking an unjoinable thread: the stall raises :class:`ServeCrash` the
    moment the engine is told to die."""
    if "DDW_FAULT" not in os.environ:   # fast path for the serving hot loop
        return
    spec = active_serve_fault()
    if spec is None or not spec.matches(site, replica=replica, n=n, gen=gen):
        return
    where = f"replica {replica}, site {site}, n {n}, gen {gen}"
    if spec.kind == "crash":
        raise ServeCrash(f"injected serve crash ({where})")
    if spec.kind == "raise":
        raise FaultInjected(f"injected serve fault ({where})")
    if spec.kind == "stall":
        # stall WHILE CONFIGURED: clearing/changing DDW_FAULT resumes the
        # tick cleanly (a test can hold an engine mid-decode and release
        # it); the engine's stop/fail signal instead aborts hard — the
        # supervisor's force_fail path, where the thread must die joinable
        while should_abort is None or not should_abort():
            if active_serve_fault() != spec:
                return
            time.sleep(0.01)
        raise ServeCrash(f"injected serve stall aborted ({where})")


# ---------------------------------------------------------------------------
# Deploy scope: deterministic arms for the rollout subsystem (ddw_tpu.deploy).
# ---------------------------------------------------------------------------

DEPLOY_KINDS = ("degrade_canary", "crash_mid_roll")
DEPLOY_SITES = ("judge", "mid_roll")


class DeployCrash(RuntimeError):
    """Raised by ``deploy:crash_mid_roll`` — the rollout control thread dies
    at a journal boundary WITHOUT finalizing the journal, the in-process
    stand-in for a gateway SIGKILL mid-rollout. The reconciler
    (``Gateway.start``) must converge the half-rolled fleet on restart."""


@dataclasses.dataclass(frozen=True)
class DeployFaultSpec:
    """Parsed ``DDW_FAULT=deploy:...`` value. ``None`` fields match anything;
    a bare ``deploy:degrade_canary`` degrades whichever replica the judge is
    measuring, and a bare ``deploy:crash_mid_roll`` dies before the first
    replica rolls."""

    kind: str
    replica: int | None = None    # degrade target (None = any; the judge
    #                               passes the canary's index)
    after: int = 0                # mid_roll: journaled steps completed
    #                               before the crash; judge: Nth probe
    ttft_ms: float = 250.0        # degrade: latency injected per judge probe
    errors: int = 0               # degrade: synthetic probe failures charged

    @property
    def site(self) -> str:
        return "judge" if self.kind == "degrade_canary" else "mid_roll"

    def matches(self, site: str, replica: int = 0, n: int = 0) -> bool:
        """Pure matching logic. ``n`` is the caller's invocation count for
        the site (journaled steps for ``mid_roll``, probes for ``judge``)."""
        if site != self.site:
            return False
        if self.replica is not None and replica != self.replica:
            return False
        return n >= self.after


def parse_deploy_fault(spec: str) -> DeployFaultSpec | None:
    """Parse a ``deploy:``-scoped ``DDW_FAULT`` value; non-deploy specs (and
    empty) -> None. Malformed deploy specs raise, same rule as
    :func:`parse_fault`."""
    if not spec or not spec.startswith("deploy:"):
        return None
    parts = spec.split(":")[1:]
    if not parts or parts[0].strip() not in DEPLOY_KINDS:
        raise ValueError(f"unknown DDW_FAULT deploy kind "
                         f"{parts[0].strip() if parts else ''!r}; expected "
                         f"one of {DEPLOY_KINDS}")
    kind = parts[0].strip()
    fields: dict[str, object] = {}
    for part in parts[1:]:
        if not part:
            continue
        key, _, val = part.partition("=")
        key, val = key.strip(), val.strip()
        if key == "replica":
            fields[key] = None if val == "*" else int(val)
        elif key in ("after", "errors"):
            fields[key] = int(val)
        elif key == "ttft_ms":
            fields[key] = float(val)
        else:
            raise ValueError(f"unknown DDW_FAULT deploy key {key!r} in "
                             f"{spec!r}")
    return DeployFaultSpec(kind=kind, **fields)


def active_deploy_fault() -> DeployFaultSpec | None:
    """The currently configured deploy fault, re-read from the env on every
    call (tests monkeypatch ``DDW_FAULT`` mid-process)."""
    for part in _fault_parts():
        spec = parse_deploy_fault(part)
        if spec is not None:
            return spec
    return None


def maybe_deploy_fault(site: str, replica: int = 0,
                       n: int = 0) -> DeployFaultSpec | None:
    """Rollout hook: at ``mid_roll`` a matching ``crash_mid_roll`` raises
    :class:`DeployCrash`; at ``judge`` a matching ``degrade_canary`` is
    RETURNED for the caller to apply (the judge injects ``ttft_ms`` into its
    canary probe and charges ``errors`` against the canary — the
    perturbation happens where the measurement happens, so no client request
    is ever touched). No-op (None) without ``DDW_FAULT``."""
    if "DDW_FAULT" not in os.environ:   # fast path
        return None
    spec = active_deploy_fault()
    if spec is None or not spec.matches(site, replica=replica, n=n):
        return None
    if spec.kind == "crash_mid_roll":
        raise DeployCrash(f"injected mid-roll crash (step {n}): journal "
                          f"left unfinalized")
    return spec


# ---------------------------------------------------------------------------
# Autoscale scope: deterministic arms for the fleet autoscaler
# (ddw_tpu.autoscale) — spawn failure, stuck drain, oscillating pressure,
# and the mid-scale gateway death the scale journal exists for.
# ---------------------------------------------------------------------------

AUTOSCALE_KINDS = ("spawn_fail", "stall_drain", "flap", "crash_mid_scale")
AUTOSCALE_SITES = ("spawn", "drain", "decide", "mid_scale")

_AUTOSCALE_SITE_BY_KIND = {"spawn_fail": "spawn", "stall_drain": "drain",
                           "flap": "decide", "crash_mid_scale": "mid_scale"}


class AutoscaleCrash(RuntimeError):
    """Raised by ``autoscale:crash_mid_scale`` — the scale event's control
    flow dies at a journal boundary WITHOUT finalizing the scale journal,
    the in-process stand-in for a gateway SIGKILL mid-scale. The autoscale
    reconciler (``Gateway.start``) must converge the fleet on restart."""


@dataclasses.dataclass(frozen=True)
class AutoscaleFaultSpec:
    """Parsed ``DDW_FAULT=autoscale:...`` value. A bare spec fires on the
    first matching site check (``after=0``)."""

    kind: str
    after: int = 0                # fire on the Nth matching check

    @property
    def site(self) -> str:
        return _AUTOSCALE_SITE_BY_KIND[self.kind]

    def matches(self, site: str, n: int = 0) -> bool:
        """Pure matching logic. ``n`` is the caller's invocation count for
        the site (journaled steps for ``mid_scale``, decide ticks for
        ``decide``, spawn attempts for ``spawn``)."""
        return site == self.site and n >= self.after


def parse_autoscale_fault(spec: str) -> AutoscaleFaultSpec | None:
    """Parse an ``autoscale:``-scoped ``DDW_FAULT`` value; non-autoscale
    specs (and empty) -> None. Malformed specs raise, same rule as
    :func:`parse_fault`."""
    if not spec or not spec.startswith("autoscale:"):
        return None
    parts = spec.split(":")[1:]
    if not parts or parts[0].strip() not in AUTOSCALE_KINDS:
        raise ValueError(f"unknown DDW_FAULT autoscale kind "
                         f"{parts[0].strip() if parts else ''!r}; expected "
                         f"one of {AUTOSCALE_KINDS}")
    kind = parts[0].strip()
    fields: dict[str, int] = {}
    for part in parts[1:]:
        if not part:
            continue
        key, _, val = part.partition("=")
        key, val = key.strip(), val.strip()
        if key == "after":
            fields[key] = int(val)
        else:
            raise ValueError(f"unknown DDW_FAULT autoscale key {key!r} in "
                             f"{spec!r}")
    return AutoscaleFaultSpec(kind=kind, **fields)


def active_autoscale_fault() -> AutoscaleFaultSpec | None:
    """The currently configured autoscale fault, re-read from the env on
    every call (tests monkeypatch ``DDW_FAULT`` mid-process)."""
    for part in _fault_parts():
        spec = parse_autoscale_fault(part)
        if spec is not None:
            return spec
    return None


def maybe_autoscale_fault(site: str, n: int = 0,
                          should_abort=None) -> AutoscaleFaultSpec | None:
    """Autoscaler hook: at ``spawn`` a matching ``spawn_fail`` raises
    :class:`FaultInjected`; at ``mid_scale`` a matching ``crash_mid_scale``
    raises :class:`AutoscaleCrash`; at ``drain`` a matching ``stall_drain``
    BLOCKS while the spec stays configured (clearing ``DDW_FAULT`` resumes
    the drain wait cleanly; ``should_abort`` — the controller's stop signal
    — raises so the wait always stays joinable); at ``decide`` a matching
    ``flap`` is RETURNED for the controller to apply as synthetic
    alternating pressure. No-op (None) without ``DDW_FAULT``."""
    if "DDW_FAULT" not in os.environ:   # fast path for the reconcile tick
        return None
    spec = active_autoscale_fault()
    if spec is None or not spec.matches(site, n=n):
        return None
    if spec.kind == "spawn_fail":
        raise FaultInjected(f"injected autoscale spawn failure (attempt {n})")
    if spec.kind == "crash_mid_scale":
        raise AutoscaleCrash(f"injected mid-scale crash (step {n}): scale "
                             f"journal left unfinalized")
    if spec.kind == "stall_drain":
        while should_abort is None or not should_abort():
            if active_autoscale_fault() != spec:
                return None     # fault cleared: the drain wait resumes
            time.sleep(0.01)
        raise AutoscaleCrash(f"injected drain stall aborted (n {n})")
    return spec                 # flap: the controller applies it


# ---------------------------------------------------------------------------
# Graceful preemption: SIGTERM -> flag -> checkpoint-and-clean-exit.
# ---------------------------------------------------------------------------

_preempt_flag = threading.Event()


class Preempted(Exception):
    """Raised by a step loop after it checkpointed in response to SIGTERM.

    In-process (np=-1) runs see it directly; gang workers convert it to
    ``EXIT_PREEMPTED`` (:mod:`ddw_tpu.runtime._launch_worker`), which the
    :class:`~ddw_tpu.runtime.supervisor.GangSupervisor` treats as restartable
    without consuming the crash-restart budget.
    """

    def __init__(self, step: int | None = None):
        self.step = step
        super().__init__(f"preempted at step {step}")


def install_preemption_handler(signum: int = signal.SIGTERM) -> None:
    """Route ``signum`` (default SIGTERM — what cluster managers send before
    reclaiming a node) to the preemption flag instead of immediate death.
    Main-thread only (a CPython signal constraint); idempotent."""
    signal.signal(signum, lambda _sig, _frame: _preempt_flag.set())


def preemption_requested() -> bool:
    """Checked by the trainers once per step: True after SIGTERM arrived."""
    return _preempt_flag.is_set()


def request_preemption() -> None:
    """Set the flag directly (signal-free path for tests/embedding hosts)."""
    _preempt_flag.set()


def reset_preemption() -> None:
    _preempt_flag.clear()
