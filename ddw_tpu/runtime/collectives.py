"""Collective communication primitives — the Horovod-core role, compiled into the step.

The reference's three collective uses (SURVEY.md §2b/§5 "Distributed communication
backend") map 1:1 onto XLA collectives over ICI/DCN:

- gradient averaging: ``hvd.DistributedOptimizer(optimizer)``
  (``Part 1 - Distributed Training/03_model_training_distributed.py:302``)
  -> :func:`all_reduce_mean` of the grad pytree inside the jitted step;
- rank-0 weight broadcast: ``BroadcastGlobalVariablesCallback(0)`` (``:308``)
  -> :func:`broadcast_from` (psum of a rank-masked tree) — though under SPMD,
  identical-seed init usually makes it unnecessary;
- metric averaging: ``MetricAverageCallback`` (``:313``) -> :func:`all_reduce_mean`
  on the epoch metrics.

There is no daemon, no tensor-fusion buffer, no background coordinator thread:
everything here is traced into the XLA program, which fuses and schedules the
collectives itself (Horovod's Tensor Fusion falls out of XLA fusion). The in-tree
"native collective" exists at two levels: :func:`ring_all_reduce` (``ppermute``
ring — XLA emits the transfers) and :func:`ring_all_reduce_pallas`
(:mod:`ddw_tpu.ops.ring_reduce` — hand-written RDMA hops, the Horovod-core
analog all the way down to the semaphores).

All functions take an ``axis_name`` and must be called under ``shard_map``/``pmap``
binding that name.
"""

from __future__ import annotations

from typing import Any, TypeVar

import jax
import jax.numpy as jnp
from jax import lax

from ddw_tpu.utils.compat import axis_size

T = TypeVar("T")


def all_reduce_sum(tree: T, axis_name: str, impl: str = "psum") -> T:
    """Sum a pytree across ``axis_name`` (allreduce-sum on every participant).

    ``impl``: ``psum`` (XLA collective, production default), ``ring`` (in-tree
    ``ppermute`` ring), or ``pallas`` (RDMA ring kernel,
    :func:`ring_all_reduce_pallas`).
    """
    if impl == "psum":
        return jax.tree.map(lambda x: lax.psum(x, axis_name), tree)
    if impl == "ring":
        return jax.tree.map(lambda x: ring_all_reduce(x, axis_name), tree)
    if impl == "pallas":
        # All leaf kernels share one collective_id (hence one barrier
        # semaphore), so two of them must never be in flight at once: chain
        # each leaf's input on the previous leaf's output through
        # lax.optimization_barrier — the same data-edge serialization the
        # segmented path inside ring_all_reduce_pallas uses. Without it the
        # leaves have no data dependency and XLA may overlap them on real TPU,
        # cross-signaling barrier/DMA semaphores (interpret-mode CPU tests run
        # kernels serially and cannot catch that).
        leaves, treedef = jax.tree.flatten(tree)
        reduced = []
        for leaf in leaves:
            if reduced:
                leaf, _ = lax.optimization_barrier((leaf, reduced[-1]))
            reduced.append(ring_all_reduce_pallas(leaf, axis_name))
        return jax.tree.unflatten(treedef, reduced)
    raise KeyError(f"unknown allreduce impl {impl!r} (have psum, ring, pallas)")


def all_reduce_mean(tree: T, axis_name: str) -> T:
    """Mean a pytree across ``axis_name`` — gradient averaging / MetricAverage role."""
    return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)


def broadcast_from(tree: T, axis_name: str, root: int = 0) -> T:
    """Broadcast ``root``'s values to every participant along ``axis_name``.

    The ``BroadcastGlobalVariablesCallback(0)`` analog: mask all but ``root`` to zero
    and psum. Under SPMD this is only needed when per-rank state may have diverged
    (e.g. after independent host-side restores from different files).
    """
    idx = lax.axis_index(axis_name)

    def _bcast(x):
        mask = (idx == root).astype(x.dtype)
        return lax.psum(x * mask, axis_name)

    return jax.tree.map(_bcast, tree)


def all_gather_axis(x: jax.Array, axis_name: str, tiled: bool = False) -> jax.Array:
    """Gather shards from every participant along ``axis_name``."""
    return lax.all_gather(x, axis_name, tiled=tiled)


def ring_all_reduce(x: jax.Array, axis_name: str) -> jax.Array:
    """Explicit ring allreduce via ``ppermute`` — Horovod's ring algorithm, in-tree.

    Reduce-scatter phase then all-gather phase, each N-1 ``ppermute`` steps around
    the ring; communication-optimal (2·(N-1)/N · bytes). XLA's native ``psum``
    already lowers to this class of algorithm on TPU ICI, so this exists as the
    first-class, testable "native collective" component (SURVEY.md §2c Horovod row),
    and as the substrate for overlap experiments. Numerically identical to
    ``lax.psum`` up to summation order.

    Arrays whose size is not divisible by the axis size are zero-padded for the
    ring and sliced back; returns the full reduced array on every participant.
    """
    from ddw_tpu.ops.ring_reduce import ring_chunks

    n = axis_size(axis_name)
    if n == 1:
        return x
    me = lax.axis_index(axis_name)
    orig_shape = x.shape
    chunks = ring_chunks(x, n)  # chunk c is reduced by rank (c-1) % n

    perm = [(i, (i + 1) % n) for i in range(n)]

    # Reduce-scatter: n-1 ppermute steps around the ring (python loop — n is static
    # at trace time, it's a mesh axis size). At step k each rank forwards its running
    # partial sum and folds in its own copy of the chunk that just arrived.
    acc = jnp.take(chunks, me, axis=0)
    for k in range(n - 1):
        acc = lax.ppermute(acc, axis_name, perm)
        acc = acc + jnp.take(chunks, (me - k - 1) % n, axis=0)
    # acc on rank r is now the full sum of chunk (r + 1) % n.

    # All-gather phase: circulate each completed chunk n-1 hops so every rank ends
    # with all chunks, then restore chunk order (chunk c completed on rank (c-1)%n).
    gathered = [acc]
    block = acc
    for _ in range(n - 1):
        block = lax.ppermute(block, axis_name, perm)
        gathered.append(block)
    # gathered[k] on rank r is the chunk completed by rank (r - k) % n, i.e. chunk
    # (r - k + 1) % n. Scatter into chunk order.
    out = jnp.zeros_like(chunks)
    for k in range(n):
        out = out.at[(me - k + 1) % n].set(gathered[k])
    from ddw_tpu.ops.ring_reduce import ring_unchunk

    return ring_unchunk(out, orig_shape, x.size)


def ring_all_reduce_pallas(x: jax.Array, axis_name: str, **kwargs) -> jax.Array:
    """RDMA-level ring allreduce (Pallas kernel) — see
    :func:`ddw_tpu.ops.ring_reduce.ring_all_reduce_pallas`."""
    from ddw_tpu.ops.ring_reduce import ring_all_reduce_pallas as _impl

    return _impl(x, axis_name, **kwargs)


def host_all_reduce(tag, value, op: str = "sum", timeout_s: float = 120.0):
    """Host-level cross-RANK reduction over the elastic gang's explicit
    rendezvous topology (:mod:`ddw_tpu.runtime.elastic`) — the MapReduce
    ``reduce`` primitive of DrJAX's framing (PAPERS.md), living OUTSIDE the
    XLA program on purpose.

    Everything above in this module is traced into the jitted step and rides
    the implicit ``jax.distributed`` world: fast, but a dead rank wedges
    every peer inside the collective and the world can only be rebuilt by
    restarting it whole. This primitive is the opposite trade: a
    deterministic, rank-ordered fold over the shared-filesystem control
    plane that PARKS instead of wedging — a dead peer aborts it with
    :class:`~ddw_tpu.runtime.elastic.ElasticRestart`, the survivor re-joins
    the re-formed gang, and a respawned rank participates with no device
    runtime surgery. Use it for the elastic gang's cross-rank sync
    (per-chain metrics, small host gradients, agreement values); keep the
    per-layer hot path on the in-step collectives above. Outside elastic
    mode it degenerates to the identity, so the same fn body runs under the
    launcher's ``np=-1`` smoke mode unchanged."""
    from ddw_tpu.runtime.elastic import host_all_reduce as _impl

    return _impl(tag, value, op=op, timeout_s=timeout_s)
