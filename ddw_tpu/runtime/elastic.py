"""Elastic gang recovery — re-rendezvous as a first-class topology object.

PR-1's :class:`~ddw_tpu.runtime.supervisor.GangSupervisor` restarts the
*whole world* on any failure: at N hosts one preempted rank throws away N-1
healthy processes' warm state (imports, compiled programs, loader position).
Horovod's elastic mode (arXiv:1802.05799 lineage) showed that single-rank
recovery is the difference between "fault tolerant" and "fault tolerant at
scale". The obstacle in JAX is that the gang's membership is an *implicit
side effect* of ``jax.distributed.initialize``: the coordination service
admits each process id exactly once, so a respawned rank can never rejoin
the world it fell out of — the only recovery the implicit topology supports
IS the whole-world restart.

This module follows DrJAX's MapReduce-primitive framing (arXiv:2403.07128)
and makes the rendezvous/reduce topology an **explicit object** instead:

- :class:`GangRendezvous` owns membership (who is in the gang, at which
  *elastic generation*), the re-rendezvous **barrier** ranks park on at
  chain boundaries, and a deterministic host-level **all-reduce** — the
  MapReduce ``reduce`` primitive — over the same shared-filesystem control
  plane (one host in tests, NFS/GCS-style shared storage on a pod). Device
  compute stays jitted per process; only the *topology* lives here, which
  is exactly what makes it reshardable: a generation bump re-forms the gang
  without touching any process's XLA runtime.
- When the :class:`~ddw_tpu.runtime.launcher.Launcher` (elastic mode)
  observes a single dead rank it respawns **only that rank** and posts a
  recovery record. Surviving ranks discover it at their next chain
  boundary (:func:`maybe_elastic_restart`) or while parked in a
  barrier/reduce, raise :class:`ElasticRestart`, and the worker entrypoint
  re-runs the train fn *in the same process* — PID, imports, compiled
  programs and loader machinery all survive; only the model state is
  re-read from the latest durable checkpoint, which is the same resume
  contract the whole-world path already guarantees.
- Whole-world restart remains the **fallback**: if re-rendezvous itself
  fails (the respawned rank dies again, a survivor cannot park, the budget
  is exhausted) the launcher kills the gang and raises the classic
  ``GangError`` — the supervisor's existing restart-from-checkpoint loop
  engages unchanged.

Recovery records come in three kinds. ``respawn`` (PR 6) re-forms the gang
at the *same* world size after the dead rank is restarted. ``shrink``
re-forms it at N−1: the driver judges a member permanently lost (respawn
budget exhausted, host unreachable via the ``deploy/transport`` probe, or an
explicit ``host_lost`` fault), proposes a **contiguous rank assignment** for
the survivors plus a fresh coordinator port, and every survivor votes
(ack/veto) before adopting — a veto pins the proposal and the driver retries
at a bumped generation or falls back to whole-world restart. ``grow`` is the
inverse: a healthy host rejoins at the next generation boundary and the gang
re-expands N−1→N through the same record/adopt machinery (no vote — growth
never strands anyone's state). :meth:`GangRendezvous.advance` applies the
record's assignment, so membership (``rank``/``world_size`` and therefore
every ``range(self.world_size)`` barrier/reduce scan) is generation-aware:
a post-shrink reduce never waits on an evicted rank's part file.

Layout of the control directory (``DDW_RENDEZVOUS_DIR``)::

    member_g<gen>_r<rank>.json   # membership: pid + start time, per generation
    recover_g<gen>.json          # driver-posted recovery record -> generation g
    vote_g<gen>_r<rank>.json     # survivor ack/veto of a shrink record
    commit_g<gen>                # driver's commit of a unanimously-acked shrink
    arrive_g<gen>_<tag>_r<rank>  # barrier arrival markers
    reduce_g<gen>_<tag>_r<rank>.json  # host all-reduce contributions

Every file is written atomically (tmp + ``os.replace``) so readers never
observe a torn record. Each rank deletes its *own* stale markers one
barrier behind the current one — a rank can be at most one barrier ahead of
any peer, so the window it keeps is exactly what a slow peer may still
read.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

__all__ = ["GangRendezvous", "ElasticRestart", "elastic_enabled", "context",
           "reset_context", "maybe_elastic_restart", "elastic_barrier",
           "host_all_reduce", "process_topology", "maybe_reinit_distributed"]


class ElasticRestart(Exception):
    """A recovery record newer than this rank's generation exists: park,
    then re-run the train fn at ``generation`` (restoring from the latest
    durable checkpoint). Raised by the chain-boundary hook, by a parked
    barrier, or by a host all-reduce that was aborted by a recovery; the
    worker entrypoint (:mod:`ddw_tpu.runtime._launch_worker`) catches it
    and re-enters the fn in the same process."""

    def __init__(self, generation: int, record: dict | None = None,
                 step: int | None = None):
        self.generation = generation
        self.record = dict(record or {})
        self.step = step
        super().__init__(
            f"elastic re-rendezvous requested: generation {generation} "
            f"(dead rank {self.record.get('dead_rank')}, parked at step "
            f"{step})")


def _atomic_write_json(path: str, payload: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class GangRendezvous:
    """The explicit gang topology: membership, barrier, host reduce.

    One instance per rank (and one driver-side instance in the launcher).
    ``generation`` is the *elastic* generation — 0 at gang launch, bumped by
    every single-rank recovery; it is independent of the supervisor's
    whole-world ``DDW_RESTART_GEN`` (a whole-world restart gets a fresh
    control directory and starts back at elastic generation 0).
    """

    def __init__(self, root: str, world_size: int, rank: int,
                 generation: int = 0, poll_s: float = 0.02):
        self.root = root
        self.world_size = int(world_size)
        self.rank = int(rank)
        self.generation = int(generation)
        self.poll_s = poll_s
        self._votes: dict[int, str] = {}     # generation -> "ack" | "veto"
        self._vote_ordinal = 0               # per-process count of votes cast
        os.makedirs(root, exist_ok=True)

    # -- membership ----------------------------------------------------------
    def announce(self) -> None:
        """Record this rank's membership for the current generation (pid +
        start time) — the forensic evidence that elastic recovery kept the
        survivors' processes alive (their pid is identical across
        generations) while the dead rank's changed."""
        _atomic_write_json(
            os.path.join(self.root,
                         f"member_g{self.generation}_r{self.rank}.json"),
            {"pid": os.getpid(), "rank": self.rank,
             "generation": self.generation, "started_unix": time.time()})

    def member(self, generation: int, rank: int) -> dict | None:
        path = os.path.join(self.root, f"member_g{generation}_r{rank}.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    # -- recovery ledger -----------------------------------------------------
    def post_recovery(self, generation: int, dead_rank: int | None,
                      exit_code: int | None = None,
                      reason: str = "rank-death", kind: str = "respawn",
                      assignment: dict | None = None,
                      world_size: int | None = None,
                      coordinator: str | None = None) -> dict:
        """Driver side: publish 'the gang re-forms at ``generation``'.
        Idempotent per generation (one recovery record per bump).

        ``kind`` is ``respawn`` (same world, dead rank restarted),
        ``shrink`` (``assignment`` maps each survivor's *current* rank to
        its new contiguous rank and ``world_size`` names the reduced size),
        or ``grow`` (identity assignment, world grows by one). Shrink/grow
        records also carry a fresh ``coordinator`` address so gangs running
        a real ``jax.distributed`` world can re-initialize per generation
        (the coordination service admits each process id exactly once, so a
        re-formed world needs a fresh port)."""
        record = {"generation": int(generation),
                  "dead_rank": None if dead_rank is None else int(dead_rank),
                  "exit_code": exit_code, "reason": reason, "kind": kind,
                  "world_size": int(self.world_size if world_size is None
                                    else world_size),
                  "posted_unix": time.time()}
        if assignment is not None:
            record["assignment"] = {str(k): int(v)
                                    for k, v in assignment.items()}
        if coordinator is not None:
            record["coordinator"] = coordinator
        _atomic_write_json(
            os.path.join(self.root, f"recover_g{generation}.json"), record)
        return record

    def post_shrink(self, generation: int, dead_rank: int,
                    assignment: dict, world_size: int,
                    exit_code: int | None = None,
                    coordinator: str | None = None,
                    reason: str = "host-lost") -> dict:
        """Driver side: propose re-forming the gang WITHOUT ``dead_rank`` at
        the reduced ``world_size``. Survivors vote (:meth:`wait_votes`)
        before the driver commits the eviction."""
        return self.post_recovery(generation, dead_rank, exit_code=exit_code,
                                  reason=reason, kind="shrink",
                                  assignment=assignment,
                                  world_size=world_size,
                                  coordinator=coordinator)

    def post_grow(self, generation: int, current_ranks: list[int],
                  world_size: int, coordinator: str | None = None,
                  reason: str = "regrow") -> dict:
        """Driver side: re-expand the gang to ``world_size`` (a new rank is
        being spawned at ``world_size - 1``). Identity assignment for the
        incumbents; no vote — growth never strands anyone's state."""
        return self.post_recovery(
            generation, None, reason=reason, kind="grow",
            assignment={str(r): int(r) for r in current_ranks},
            world_size=world_size, coordinator=coordinator)

    def commit_recovery(self, generation: int) -> None:
        """Driver side: commit a voted shrink record. Survivors adopt a
        shrink only after this marker lands (two-phase), so a proposal the
        driver abandons — veto, vote timeout — strands nobody halfway into
        a world that never forms."""
        _atomic_write_json(
            os.path.join(self.root, f"commit_g{generation}"),
            {"generation": int(generation), "committed_unix": time.time()})

    def recovery_committed(self, generation: int) -> bool:
        return os.path.exists(
            os.path.join(self.root, f"commit_g{generation}"))

    def record_for(self, generation: int) -> dict | None:
        """The recovery record that created ``generation``, or None (gen 0
        has no record — it is the spawn-time world)."""
        path = os.path.join(self.root, f"recover_g{generation}.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def coordinator_for(self, generation: int) -> str | None:
        """Coordinator address for ``generation``: the record's fresh port,
        or the spawn-time ``DDW_COORDINATOR`` for generation 0 / records
        that did not rotate the port."""
        rec = self.record_for(generation)
        if rec is not None and rec.get("coordinator"):
            return rec["coordinator"]
        return os.environ.get("DDW_COORDINATOR") or None

    # -- shrink voting -------------------------------------------------------
    def _cast_vote(self, record: dict) -> str:
        """Survivor side: ack or veto a shrink record, exactly once per
        generation (memoized + durable vote file). The ``shrink_veto``
        fault arm hooks the ``shrink_vote`` site with ``step`` equal to the
        per-process vote ordinal, so ``shrink_veto:rank=0`` vetoes only the
        first proposal this process ever votes on (the retry then acks)."""
        gen = int(record["generation"])
        if gen in self._votes:
            return self._votes[gen]
        ordinal = self._vote_ordinal
        self._vote_ordinal += 1
        vote = "ack"
        try:
            from ddw_tpu.runtime.faults import ShrinkVeto, maybe_fault
            try:
                maybe_fault("shrink_vote", step=ordinal)
            except ShrinkVeto:
                vote = "veto"
        except ImportError:     # pragma: no cover - faults always present
            pass
        _atomic_write_json(
            os.path.join(self.root, f"vote_g{gen}_r{self.rank}.json"),
            {"vote": vote, "rank": self.rank, "pid": os.getpid(),
             "ordinal": ordinal, "voted_unix": time.time()})
        self._votes[gen] = vote
        return vote

    def read_votes(self, generation: int) -> dict[int, str]:
        """Driver side: rank -> "ack"/"veto" votes cast so far for the
        shrink record at ``generation`` (keyed by pre-shrink ranks)."""
        votes: dict[int, str] = {}
        prefix = f"vote_g{generation}_r"
        try:
            names = os.listdir(self.root)
        except OSError:
            return votes
        for name in names:
            if not (name.startswith(prefix) and name.endswith(".json")):
                continue
            try:
                rank = int(name[len(prefix):-len(".json")])
                with open(os.path.join(self.root, name)) as f:
                    votes[rank] = json.load(f).get("vote", "ack")
            except (OSError, ValueError):
                continue
        return votes

    def wait_votes(self, generation: int, ranks: list[int],
                   timeout_s: float = 30.0) -> dict[int, str] | None:
        """Driver side: park until every survivor in ``ranks`` voted on the
        shrink record at ``generation`` (or any veto arrives — a single
        veto decides immediately). None on timeout: a survivor that cannot
        vote cannot adopt either, so the driver falls back to whole-world
        restart."""
        deadline = time.monotonic() + timeout_s
        want = set(int(r) for r in ranks)
        while True:
            votes = self.read_votes(generation)
            if any(v == "veto" for r, v in votes.items() if r in want):
                return votes
            if want.issubset(votes.keys()):
                return votes
            if time.monotonic() > deadline:
                return None
            time.sleep(self.poll_s)

    def recovery_pending(self) -> dict | None:
        """The newest recovery record addressing a generation beyond this
        rank's, or None. One directory scan — cheap at chain granularity."""
        newest = None
        try:
            names = os.listdir(self.root)
        except OSError:
            return None
        for name in names:
            if not (name.startswith("recover_g")
                    and name.endswith(".json")):
                continue
            try:
                gen = int(name[len("recover_g"):-len(".json")])
            except ValueError:
                continue
            if gen > self.generation and (newest is None
                                          or gen > newest):
                newest = gen
        if newest is None:
            return None
        try:
            with open(os.path.join(self.root,
                                   f"recover_g{newest}.json")) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None     # racing the atomic publish: next check sees it

    def current_generation(self) -> int:
        """Newest generation named by any recovery record (>= own)."""
        gen = self.generation
        rec = self.recovery_pending()
        if rec is not None:
            gen = max(gen, int(rec["generation"]))
        return gen

    def advance(self, generation: int) -> None:
        """Adopt a new generation (after catching :class:`ElasticRestart`).
        Mirrors it into ``DDW_ELASTIC_GEN`` so env-keyed machinery
        (fault-injection ``egen`` matching) sees the survivor's true
        generation, not its spawn-time one. A shrink/grow record's rank
        ``assignment`` and ``world_size`` are applied here — membership is
        generation-aware, so every subsequent ``range(self.world_size)``
        barrier/reduce scan covers exactly the re-formed gang and never
        waits on an evicted rank's part file. The remapped rank/world are
        mirrored into ``DDW_PROCESS_ID``/``DDW_NUM_PROCESSES`` so the
        result-writer gate, checkpoint writer election and fault matching
        all follow the survivor's new identity."""
        rec = self.record_for(int(generation))
        if rec is not None and rec.get("assignment") is not None:
            new_rank = rec["assignment"].get(str(self.rank))
            if new_rank is None:
                raise RuntimeError(
                    f"rank {self.rank} was evicted by the recovery record "
                    f"at generation {generation}; it cannot adopt it")
            self.rank = int(new_rank)
            self.world_size = int(rec.get("world_size", self.world_size))
            os.environ["DDW_PROCESS_ID"] = str(self.rank)
            os.environ["DDW_NUM_PROCESSES"] = str(self.world_size)
        self.generation = int(generation)
        os.environ["DDW_ELASTIC_GEN"] = str(generation)

    def _check_recovery(self, step: int | None = None) -> None:
        rec = self.recovery_pending()
        if rec is None:
            return
        if rec.get("kind") == "shrink" and rec.get("assignment") is not None:
            gen = int(rec["generation"])
            if rec["assignment"].get(str(self.rank)) is None:
                # Evicted by this record (a zombie the driver gave up on):
                # adopting would be wrong, parking forever is worse. Raise;
                # advance() refuses and the worker exits via its error path.
                raise ElasticRestart(gen, rec, step=step)
            if self._cast_vote(rec) == "veto":
                return      # pinned: keep parking until a retry supersedes it
            if not self.recovery_committed(gen):
                return      # voted ack; adopt only once the driver commits
        raise ElasticRestart(int(rec["generation"]), rec, step=step)

    # -- barrier -------------------------------------------------------------
    def barrier(self, tag, timeout_s: float = 120.0) -> None:
        """Park until every rank of this generation arrives at ``tag`` (a
        step number or a label like ``"start"``). A recovery record
        addressing a newer generation aborts the park with
        :class:`ElasticRestart` — this is exactly where survivors sit while
        the dead rank is respawned. Raises TimeoutError when the gang never
        forms (the caller should exit and let the launcher fall back to
        whole-world restart)."""
        me = os.path.join(
            self.root, f"arrive_g{self.generation}_{tag}_r{self.rank}")
        _atomic_write_json(me, {"pid": os.getpid()})
        deadline = time.monotonic() + timeout_s
        step = tag if isinstance(tag, int) else None
        while True:
            present = sum(
                1 for r in range(self.world_size)
                if os.path.exists(os.path.join(
                    self.root, f"arrive_g{self.generation}_{tag}_r{r}")))
            if present == self.world_size:
                break
            self._check_recovery(step)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic barrier {tag!r} (gen {self.generation}): only "
                    f"{present}/{self.world_size} ranks arrived within "
                    f"{timeout_s}s")
            time.sleep(self.poll_s)
        self._gc_markers(tag)

    def _gc_markers(self, tag) -> None:
        """Drop this rank's OWN markers from earlier integer steps (keep the
        immediately preceding one: a peer can be at most one barrier behind,
        so older markers are unreadable by anyone)."""
        if not isinstance(tag, int):
            return
        prefix = f"_g{self.generation}_"
        for kind in ("arrive", "reduce"):
            try:
                names = os.listdir(self.root)
            except OSError:
                return
            for name in names:
                if not name.startswith(kind + prefix):
                    continue
                rest = name[len(kind + prefix):]
                stem = rest.split("_r")[0]
                if not rest.endswith(f"_r{self.rank}"
                                     + (".json" if kind == "reduce" else "")):
                    continue
                try:
                    s = int(stem)
                except ValueError:
                    continue
                if s < tag - 1:
                    try:
                        os.remove(os.path.join(self.root, name))
                    except OSError:
                        pass

    # -- host-level all-reduce (the MapReduce `reduce` primitive) ------------
    def all_reduce(self, tag, value, op: str = "sum",
                   timeout_s: float = 120.0) -> np.ndarray:
        """Deterministic cross-rank reduction over the control plane: each
        rank publishes its contribution, waits for all peers of the same
        generation, and folds them in rank order (bit-identical on every
        rank). This is the gang's *data* barrier in elastic mode — metrics,
        small gradients, agreement values — and it parks/aborts exactly
        like :meth:`barrier`, so a dead peer never wedges the gang the way
        an in-flight XLA collective would."""
        arr = np.asarray(value, np.float64)
        me = os.path.join(
            self.root,
            f"reduce_g{self.generation}_{tag}_r{self.rank}.json")
        _atomic_write_json(me, {"shape": list(arr.shape),
                                "data": arr.reshape(-1).tolist()})
        deadline = time.monotonic() + timeout_s
        step = tag if isinstance(tag, int) else None
        parts: dict[int, np.ndarray] = {}
        while len(parts) < self.world_size:
            for r in range(self.world_size):
                if r in parts:
                    continue
                path = os.path.join(
                    self.root, f"reduce_g{self.generation}_{tag}_r{r}.json")
                try:
                    with open(path) as f:
                        rec = json.load(f)
                except (OSError, ValueError):
                    continue
                parts[r] = np.asarray(rec["data"], np.float64).reshape(
                    rec["shape"])
            if len(parts) < self.world_size:
                self._check_recovery(step)
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"elastic all_reduce {tag!r} (gen {self.generation})"
                        f": only {len(parts)}/{self.world_size} "
                        f"contributions within {timeout_s}s")
                time.sleep(self.poll_s)
        out = parts[0].copy()
        for r in range(1, self.world_size):
            out = out + parts[r]    # fixed rank order: deterministic
        if op == "mean":
            out = out / self.world_size
        elif op != "sum":
            raise ValueError(f"unknown reduce op {op!r} (have sum, mean)")
        self._gc_markers(tag)
        return out.astype(np.asarray(value).dtype
                          if np.asarray(value).dtype.kind == "f"
                          else np.float64)


# ---------------------------------------------------------------------------
# Process-level context: the worker's own rendezvous, built from the env.
# ---------------------------------------------------------------------------

_ctx: GangRendezvous | None = None


def elastic_enabled() -> bool:
    """True inside an elastic gang (the launcher exported the control dir)."""
    return bool(os.environ.get("DDW_RENDEZVOUS_DIR"))


def context() -> GangRendezvous | None:
    """This process's rendezvous (lazily built from ``DDW_RENDEZVOUS_DIR`` /
    ``DDW_NUM_PROCESSES`` / ``DDW_PROCESS_ID`` / ``DDW_ELASTIC_GEN``), or
    None outside elastic mode. A respawned rank starts at the generation the
    driver stamped into its env; survivors advance theirs in-process."""
    global _ctx
    root = os.environ.get("DDW_RENDEZVOUS_DIR")
    if not root:
        return None
    if _ctx is None or _ctx.root != root:
        _ctx = GangRendezvous(
            root,
            world_size=int(os.environ.get("DDW_NUM_PROCESSES", "1")),
            rank=int(os.environ.get("DDW_PROCESS_ID", "0")),
            generation=int(os.environ.get("DDW_ELASTIC_GEN", "0") or 0))
    return _ctx


def reset_context() -> None:
    global _ctx
    _ctx = None


def maybe_elastic_restart(step: int | None = None) -> None:
    """The trainers' chain-boundary hook (free no-op outside elastic mode):
    if a recovery record addresses a newer generation, raise
    :class:`ElasticRestart` so the surviving rank parks HERE — at a chain
    boundary, before it enters another cross-rank operation with a dead
    peer — and re-runs its train fn from the latest durable checkpoint."""
    if "DDW_RENDEZVOUS_DIR" not in os.environ:     # fast path
        return
    ctx = context()
    if ctx is not None:
        ctx._check_recovery(step)


def elastic_barrier(tag, timeout_s: float = 120.0) -> None:
    """Module-level convenience over :meth:`GangRendezvous.barrier`; no-op
    outside elastic mode. Train fns call ``elastic_barrier("start")`` after
    restoring so the whole (re-formed) gang resumes in lockstep."""
    ctx = context()
    if ctx is not None:
        ctx.barrier(tag, timeout_s=timeout_s)


def host_all_reduce(tag, value, op: str = "sum", timeout_s: float = 120.0):
    """Module-level convenience over :meth:`GangRendezvous.all_reduce`.
    Outside elastic mode this degenerates to the identity (world of one) —
    the same fn body runs under ``np=-1`` smoke mode unchanged."""
    ctx = context()
    if ctx is None:
        arr = np.asarray(value, np.float64)
        return arr if op in ("sum", "mean") else None
    return ctx.all_reduce(tag, value, op=op, timeout_s=timeout_s)


def process_topology() -> tuple[int, int]:
    """``(rank, world_size)`` of this process in the *current* generation.

    The one topology query data sharding and writer election should use:
    a real multi-process ``jax.distributed`` world wins (its mesh IS the
    topology); otherwise the elastic rendezvous context supplies the
    generation-aware rank/world (elastic workers skip ``jax.distributed``,
    so ``jax.process_count()`` is 1 in every member); otherwise a world of
    one. After a shrink, :meth:`GangRendezvous.advance` has already
    remapped the context, so loaders/trainers that re-enter their fn pick
    up the N−1 topology with no further plumbing."""
    import jax
    if jax.process_count() > 1:
        return jax.process_index(), jax.process_count()
    ctx = context()
    if ctx is not None and ctx.world_size > 0:
        return ctx.rank, ctx.world_size
    return 0, 1


def maybe_reinit_distributed() -> bool:
    """Re-initialize ``jax.distributed`` for the current elastic generation
    on the generation's fresh coordinator port. Opt-in via
    ``DDW_ELASTIC_JAX_DIST=1``: elastic workers normally skip
    ``jax.distributed`` entirely (host-level topology only), but a gang
    that wants a real global mesh can tear the coordination service down
    and re-form it each generation — this is what lets global-mesh
    trainers survive single-rank loss, since the service admits each
    process id exactly once per incarnation. Returns True when a (re)init
    happened. Best-effort: on failure the gang still has its host-level
    topology and the whole-world fallback."""
    if os.environ.get("DDW_ELASTIC_JAX_DIST", "") not in ("1", "true"):
        return False
    ctx = context()
    if ctx is None or ctx.world_size < 2:
        return False
    coord = ctx.coordinator_for(ctx.generation)
    if not coord:
        return False
    import jax

    from ddw_tpu.runtime.mesh import initialize_distributed
    try:
        jax.distributed.shutdown()
    except Exception:
        pass        # not initialized yet (generation 0) — nothing to tear down
    try:
        initialize_distributed(coordinator_address=coord,
                               num_processes=ctx.world_size,
                               process_id=ctx.rank)
    except Exception:
        return False
    try:        # jax.distributed.initialize replaces signal dispositions
        from ddw_tpu.runtime.faults import install_preemption_handler
        install_preemption_handler()
    except Exception:
        pass
    return True
