"""Sharded binary-image table store — the Delta Lake / Parquet role.

The reference stores training data as Delta tables of JPEG bytes: a *bronze* table of
``(path, content)`` rows written by the binaryFile reader
(``Part 1 - Distributed Training/01_data_prep.py:61-95``) and *silver* train/val
tables adding ``label`` and ``label_idx`` columns (``:216-222``), stored as
uncompressed parquet (``:92`` — JPEG bytes don't recompress).

In-tree TPU-native equivalent: a table is a directory of fixed-schema binary shard
files plus a JSON manifest; versions are append-only subdirectories with a ``latest``
pointer, giving Delta's versioned-table semantics without a JVM. The record codec is
deliberately trivial — length-prefixed fields, no compression (same rationale as
``:92``) — so a C++ reader (``ddw_tpu/native``) can mmap/stream shards when the
Python loader becomes the bottleneck.

Shard file format (little-endian):
    magic ``DDWS`` | u32 format_version | u32 nrecords
    then per record: u32 path_len, path, u32 content_len, content,
                     u32 label_len, label, i32 label_idx   (label_idx -1 = unlabeled)

Shards are the unit of parallelism for the loader (``cur_shard``/``shard_count``
selection, Petastorm role) and for the distributed batch scorer.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import struct
import time
from typing import Iterable, Iterator

_MAGIC = b"DDWS"
_FORMAT_VERSION = 1


@dataclasses.dataclass
class RecordSchema:
    """Fixed schema shared by bronze (label empty, label_idx -1) and silver tables."""

    fields: tuple[str, ...] = ("path", "content", "label", "label_idx")


@dataclasses.dataclass
class Record:
    path: str
    content: bytes
    label: str = ""
    label_idx: int = -1


def _write_shard(path: str, records: list[Record]) -> dict:
    h = hashlib.sha256()
    with open(path, "wb") as f:
        head = _MAGIC + struct.pack("<II", _FORMAT_VERSION, len(records))
        f.write(head)
        h.update(head)
        for r in records:
            pb, lb = r.path.encode(), r.label.encode()
            buf = (
                struct.pack("<I", len(pb)) + pb
                + struct.pack("<I", len(r.content)) + r.content
                + struct.pack("<I", len(lb)) + lb
                + struct.pack("<i", r.label_idx)
            )
            f.write(buf)
            h.update(buf)
    return {
        "file": os.path.basename(path),
        "num_records": len(records),
        "bytes": os.path.getsize(path),
        "sha256": h.hexdigest(),
    }


def _native_reader():
    """Resolve the native codec module, or None (unavailable / disabled via
    ``DDW_NATIVE_CODEC=0``). Only resolution failures select the Python
    fallback; parse errors from an available native codec propagate."""
    if os.environ.get("DDW_NATIVE_CODEC", "1") == "0":
        return None
    try:
        from ddw_tpu.native import codec as native_codec

        return native_codec if native_codec.native_available() else None
    except Exception:
        return None


def read_shard(path: str) -> Iterator[Record]:
    """Stream records from one shard file.

    Prefers the C++ codec (``ddw_tpu/native``, one index pass over the buffer)
    when it builds/loads; falls back to the pure-Python framing. Disable with
    ``DDW_NATIVE_CODEC=0``."""
    native = _native_reader()
    if native is not None:
        # Errors from an available native parser propagate: swallowing them
        # would double-read corrupt shards through the Python path and mask
        # codec divergence.
        yield from native.read_shard_native(path)
        return
    for rec in _walk_shard(path, full=True):
        yield rec


def read_shard_contents(path: str) -> Iterator[tuple[bytes, int]]:
    """Loader hot path: yield (content, label_idx) only — no path/label string
    decoding, no Record objects. Native C++ index pass when available."""
    native = _native_reader()
    if native is not None:
        yield from native.read_shard_contents_native(path)
        return
    for pair in _walk_shard(path, full=False):
        yield pair


def _walk_shard(path: str, full: bool):
    """Single pure-Python walker over the DDWS record framing (the only other
    framing implementation is the C++ codec). ``full=True`` yields ``Record``s;
    ``full=False`` skips path/label decoding and yields ``(content, label_idx)``."""
    with open(path, "rb") as f:
        head = f.read(12)
        if head[:4] != _MAGIC:
            raise ValueError(f"{path}: bad magic {head[:4]!r}")
        fmt, n = struct.unpack("<II", head[4:])
        if fmt != _FORMAT_VERSION:
            raise ValueError(f"{path}: unsupported format version {fmt}")
        for _ in range(n):
            (plen,) = struct.unpack("<I", f.read(4))
            p = f.read(plen).decode() if full else f.seek(plen, 1)
            (clen,) = struct.unpack("<I", f.read(4))
            content = f.read(clen)
            (llen,) = struct.unpack("<I", f.read(4))
            label = f.read(llen).decode() if full else f.seek(llen, 1)
            (idx,) = struct.unpack("<i", f.read(4))
            yield Record(p, content, label, idx) if full else (content, idx)


class Table:
    """One immutable version of a table: manifest + shard files."""

    def __init__(self, version_dir: str):
        self.version_dir = version_dir
        with open(os.path.join(version_dir, "manifest.json")) as f:
            self.manifest = json.load(f)

    @property
    def num_records(self) -> int:
        return self.manifest["num_records"]

    @property
    def shard_paths(self) -> list[str]:
        return [os.path.join(self.version_dir, "shards", s["file"]) for s in self.manifest["shards"]]

    @property
    def meta(self) -> dict:
        return self.manifest.get("meta", {})

    def iter_records(self) -> Iterator[Record]:
        for sp in self.shard_paths:
            yield from read_shard(sp)

    def take(self, n: int) -> list[Record]:
        out = []
        for r in self.iter_records():
            out.append(r)
            if len(out) >= n:
                break
        return out


class TableWriter:
    """Incremental single-writer handle for one new table version.

    Lets callers stream records into several tables in one pass (e.g. routing a
    bronze scan into silver_train/silver_val simultaneously) instead of
    re-reading the source per destination. Finalize with :meth:`close` (or use as
    a context manager); the version only becomes visible (manifest + ``latest``
    pointer) at close."""

    def __init__(self, store: "TableStore", name: str, shard_size: int = 256,
                 meta: dict | None = None):
        self.store = store
        self.name = name
        self.shard_size = shard_size
        self.meta = meta or {}
        tdir = store._table_dir(name)
        os.makedirs(tdir, exist_ok=True)
        existing = sorted(d for d in os.listdir(tdir) if d.startswith("v"))
        self.vnum = 1 + (int(existing[-1][1:]) if existing else 0)
        self.vdir = os.path.join(tdir, f"v{self.vnum:04d}")
        self.shards_dir = os.path.join(self.vdir, "shards")
        os.makedirs(self.shards_dir)
        self._buf: list[Record] = []
        self._shard_metas: list[dict] = []
        self._total = 0
        self._closed = False

    def append(self, rec: Record) -> None:
        self._buf.append(rec)
        if len(self._buf) >= self.shard_size:
            self._flush()

    def extend(self, records: Iterable[Record]) -> None:
        for rec in records:
            self.append(rec)

    def _flush(self) -> None:
        if not self._buf:
            return
        path = os.path.join(self.shards_dir, f"shard-{len(self._shard_metas):05d}.ddws")
        self._shard_metas.append(_write_shard(path, self._buf))
        self._total += len(self._buf)
        self._buf = []

    def add_shard_file(self, src_path: str, shard_meta: dict) -> None:
        """Adopt an existing shard file verbatim (hardlink, copy fallback) —
        the zero-copy building block of :meth:`TableStore.merge_shards`.
        ``shard_meta`` is the source manifest entry; its checksum carries over
        because the bytes do. Must not interleave with buffered ``append``s
        (flushes them first to keep shard numbering in write order)."""
        import shutil

        self._flush()
        fn = f"shard-{len(self._shard_metas):05d}.ddws"
        dst = os.path.join(self.shards_dir, fn)
        try:
            os.link(src_path, dst)
        except OSError:
            shutil.copy2(src_path, dst)
        self._shard_metas.append({**shard_meta, "file": fn})
        self._total += shard_meta["num_records"]

    def close(self) -> Table:
        if self._closed:
            return Table(self.vdir)
        self._flush()
        manifest = {
            "name": self.name,
            "version": self.vnum,
            "schema": list(RecordSchema().fields),
            "num_records": self._total,
            "shards": self._shard_metas,
            "created_unix": time.time(),
            "meta": self.meta,
        }
        with open(os.path.join(self.vdir, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=2)
        tdir = self.store._table_dir(self.name)
        # Atomic-enough latest pointer (single-writer discipline, rank 0 only).
        with open(os.path.join(tdir, "latest.tmp"), "w") as f:
            f.write(f"v{self.vnum:04d}")
        os.replace(os.path.join(tdir, "latest.tmp"), os.path.join(tdir, "latest"))
        self._closed = True
        return Table(self.vdir)

    def __enter__(self) -> "TableWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            self.close()


class TableStore:
    """Versioned table namespace rooted at a directory (the database_name role,
    reference ``00_setup.py:3-9``)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _table_dir(self, name: str) -> str:
        return os.path.join(self.root, name)

    def writer(self, name: str, shard_size: int = 256, meta: dict | None = None) -> TableWriter:
        return TableWriter(self, name, shard_size, meta)

    def write(
        self,
        name: str,
        records: Iterable[Record],
        shard_size: int = 256,
        meta: dict | None = None,
    ) -> Table:
        """Write a new version of table ``name`` (append-only versioning)."""
        w = TableWriter(self, name, shard_size, meta)
        w.extend(records)
        return w.close()

    def table(self, name: str, version: int | None = None) -> Table:
        """Open a table — ``spark.table(name)`` analog; latest version by default."""
        tdir = self._table_dir(name)
        if version is None:
            with open(os.path.join(tdir, "latest")) as f:
                vstr = f.read().strip()
        else:
            vstr = f"v{version:04d}"
        return Table(os.path.join(tdir, vstr))

    def exists(self, name: str) -> bool:
        return os.path.exists(os.path.join(self._table_dir(name), "latest"))

    @staticmethod
    def run_token(*components) -> str:
        """Deterministic 16-hex run token from the run's actual inputs — THE
        derivation every shared-nothing part/merge flow uses (distributed
        prep, batch scorer, distributed featurization), so coordinator and
        workers always agree on the fence :meth:`await_parts` checks."""
        import hashlib

        h = hashlib.sha256()
        for c in components:
            h.update(repr(c).encode())
            h.update(b"\x00")
        return h.hexdigest()[:16]

    def await_parts(self, part_names: list[str], run_id: str,
                    timeout_s: float = 300.0, abort=None) -> list[Table]:
        """Wait (bounded) for every part table's LATEST version to carry
        ``meta.run_id == run_id``, then return those validated versions.

        ``exists()`` alone is not enough: a previous run's version also
        satisfies it, and a coordinator would silently merge stale parts while
        slower workers are still writing the current run's (the classic
        shared-filesystem rendezvous race). The run token — identical on every
        worker by construction, caller-derived from the run's inputs — is the
        fence. The returned ``Table`` objects are the very versions that passed
        validation (re-opening ``latest`` afterwards would reintroduce the
        race against an even newer commit).

        ``abort``: optional zero-arg callable polled each round; a non-None
        return value (a reason string) raises RuntimeError immediately — the
        hook coordinators use to fail fast when a worker process dies instead
        of burning the whole timeout.
        """
        import time as _time

        deadline = _time.monotonic() + timeout_s
        good: dict[str, Table] = {}
        while True:
            pending = []
            for n in part_names:
                if n in good:
                    continue
                if not self.exists(n):
                    pending.append(n)
                    continue
                t = self.table(n)
                if t.meta.get("run_id") == run_id:
                    good[n] = t
                else:
                    pending.append(f"{n} (stale run_id)")
            if not pending:
                return [good[n] for n in part_names]
            if abort is not None:
                reason = abort()
                if reason:
                    raise RuntimeError(
                        f"await_parts aborted for run {run_id!r}: {reason} "
                        f"(still pending: {pending})")
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"parts never appeared for run {run_id!r}: {pending}")
            _time.sleep(0.2)

    def merge_shards(self, name: str, parts: list[Table],
                     meta: dict | None = None) -> Table:
        """Coordinator-side merge: a new version of ``name`` whose shards ARE the
        parts' shard files (hardlinked when the filesystem allows, else copied)
        — manifests concatenate, record bytes never re-encode. The multi-worker
        ETL analog of Spark executors writing partition files and the driver
        committing one table (reference ``01_data_prep.py:61-95``: the scan
        parallelizes across executors, the table commit is single)."""
        w = TableWriter(self, name, meta=meta)
        for t in parts:
            for sm, sp in zip(t.manifest["shards"], t.shard_paths):
                w.add_shard_file(sp, sm)
        return w.close()

    def list_tables(self) -> list[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(d for d in os.listdir(self.root) if os.path.isdir(self._table_dir(d)))
