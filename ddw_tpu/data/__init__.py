from ddw_tpu.data.store import Table, TableStore, RecordSchema  # noqa: F401
from ddw_tpu.data.prep import prepare_flowers, generate_synthetic_flowers  # noqa: F401
from ddw_tpu.data.loader import ShardedLoader, preprocess_image  # noqa: F401
