"""Per-host sharded loader — the Petastorm SparkDatasetConverter role.

The reference feeds training via Petastorm: a parquet cache materialized from the
table, then ``make_tf_dataset(batch_size, cur_shard=hvd.rank(),
shard_count=hvd.size(), num_epochs=None)`` with a reader thread pool
(``Part 1 - Distributed Training/03_model_training_distributed.py:137-144,200,332-337``).
Two semantics are load-bearing (SURVEY.md §2b.8, §7 hard-part 2):

- **shard selection by rank**: each worker reads a disjoint shard subset;
- **infinite repeat** (``num_epochs=None``): every worker can take the same floor
  -divided number of steps despite unequal shard sizes — the identical-step-count
  guarantee that under SPMD becomes "fixed shapes, same batch count on every host".

This loader reads ddw_tpu table shards directly (no intermediate cache: the store's
codec *is* the cache format), decodes/resizes JPEGs per batch in the native C++
pipeline (:mod:`ddw_tpu.native.decode` — libjpeg + std::thread pool, one GIL
release per batch; PIL thread-pool fallback — the tf.data/petastorm worker-pool
role), and prefetches batches to device HBM on a background thread (double
buffering), so the TPU never waits on host IO.

Preprocessing is THE shared implementation for training and serving —
:func:`preprocess_image` is the single decode path ``ddw_tpu.serving`` packages with
models — deliberately fixing the reference's train/serve skew (tf.image in training,
``02_model_training_single_node.py:119-126``, vs PIL at inference,
``03_pyfunc_distributed_inference.py:231-234``).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from io import BytesIO
from typing import Iterator

import numpy as np

from ddw_tpu.data.store import Table, read_shard_contents


def bounded_map(pool: ThreadPoolExecutor, fn, iterable, window: int):
    """Ordered parallel map with a bounded in-flight window.

    ``Executor.map`` eagerly submits the whole iterable (decoding an entire shard
    set into memory); this keeps at most ``window`` items pending. Shared by the
    training loader and the batch scorer."""
    from collections import deque

    pending: deque = deque()
    for item in iterable:
        pending.append(pool.submit(fn, item))
        if len(pending) >= window:
            yield pending.popleft().result()
    while pending:
        yield pending.popleft().result()


def _preprocess_image_pil(content: bytes, height: int, width: int) -> np.ndarray:
    from PIL import Image

    img = Image.open(BytesIO(content))
    # JPEG DCT-scaled decode (decode directly at 1/2, 1/4, 1/8 scale when the
    # source is larger than the target) — the same trick the native pipeline's
    # libjpeg scale_denom uses; no-op for non-JPEG or already-small images.
    img.draft("RGB", (width, height))
    if img.mode != "RGB":
        img = img.convert("RGB")
    img = img.resize((width, height), Image.BILINEAR)
    arr = np.asarray(img, dtype=np.float32)
    return arr / 127.5 - 1.0


def raw_u8_view(content: bytes, height: int, width: int) -> np.ndarray:
    """Reinterpret a ``raw_u8`` record (prep.materialize_decoded) as a
    [H, W, 3] uint8 array — zero-copy view over the record bytes."""
    return np.frombuffer(content, np.uint8).reshape(height, width, 3)


def dequantize_raw_u8(batch: np.ndarray) -> None:
    """In-place inverse of materialize_decoded's quantization: a float batch
    holding uint8 pixel values becomes [-1, 1]. THE single definition of the
    raw_u8 scheme — loader, batch scorer, and bench all call this, so a
    change to the quantization can never reintroduce train/serve skew (the
    bug class ``preprocess_image`` exists to prevent on the JPEG path).
    :func:`dequantize_raw_u8_device` is the jit-side twin — change BOTH or
    the equivalence test fails."""
    batch /= 127.5
    batch -= 1.0


def dequantize_raw_u8_device(x):
    """The same scheme as a jittable device op (u8 -> f32 in [-1, 1]).

    The prefetching loader transfers raw uint8 batches and dequantizes ON
    DEVICE: 4x fewer bytes over host->HBM (the usual input-pipeline
    bottleneck); the cast+scale then runs as one tiny fused device program on
    the prefetch thread, overlapped with training like the transfer itself.
    Same arithmetic as :func:`dequantize_raw_u8` up to 1 ULP (XLA lowers the
    divide to multiply-by-reciprocal), pinned by
    ``test_loader.py::test_raw_u8_device_dequant_matches_host``."""
    import jax.numpy as jnp

    return x.astype(jnp.float32) / 127.5 - 1.0


_DEQUANT_JIT = None


def _dequant_jitted():
    """Process-wide jitted dequantize — one compilation shared by every loader
    iterator (a fresh val-loader per epoch must not re-trace)."""
    global _DEQUANT_JIT
    if _DEQUANT_JIT is None:
        import jax

        _DEQUANT_JIT = jax.jit(dequantize_raw_u8_device)
    return _DEQUANT_JIT


def active_decoder() -> str:
    """Which decode impl :func:`preprocess_image` dispatches to here: ``native``
    (libjpeg pipeline) or ``pil``. Serving packages record this at save time and
    warn when the serving environment resolves differently (decoder skew)."""
    from ddw_tpu.native.decode import native_available

    return "native" if native_available() else "pil"


def preprocess_image(content: bytes, height: int, width: int) -> np.ndarray:
    """JPEG bytes -> float32 [H, W, 3] in [-1, 1].

    decode -> resize (bilinear) -> MobileNetV2-style scaling ``x/127.5 - 1``
    (the ``tf.image.decode_jpeg`` + ``resize`` + ``preprocess_input`` chain,
    reference ``02_model_training_single_node.py:119-126``). Single
    implementation shared by the training loader and the packaged model's
    predict path. Dispatches to the native libjpeg pipeline
    (:mod:`ddw_tpu.native.decode` — point-sampled bilinear, the
    ``tf.image.resize`` semantics of the reference) when built, else PIL
    (area-filtered bilinear); both sides of train/serve go through this same
    dispatch, so train and serve agree whenever both environments resolve the
    same impl; :func:`active_decoder` + the serving package manifest surface
    the case where they don't.
    """
    from ddw_tpu.native.decode import decode_one_native

    out = decode_one_native(content, height, width)
    if out is not None:
        return out
    return _preprocess_image_pil(content, height, width)


class ShardedLoader:
    """Iterate (images, labels) batches from a table, sharded by worker rank.

    Args:
      table: silver table with ``label_idx`` set.
      batch_size: per-worker batch size (reference semantics — global batch is
        ``batch_size * shard_count``).
      image_size: (height, width).
      cur_shard / shard_count: worker rank / world size (``make_tf_dataset``
        parameters, reference ``:332-337``). Defaults to 0/1 (single worker).
      num_epochs: None = infinite repeat (training default, reference ``:199-200``);
        an int for finite passes (eval).
      shuffle: shuffle shard order and a record-level buffer, seeded; epoch-varying.
      drop_remainder: keep shapes static for XLA (always True under jit).
      workers: decode thread pool size (petastorm ``workers_count`` role, ``:200``).
      prefetch_to: optional ``jax.sharding.Sharding`` — batches are transferred to
        device(s) on a background thread, ``prefetch`` deep.
      skip_records: fast-forward the (deterministic, seeded) record stream this
        many records before the first batch — exact resume of a consumed-batch
        position without decoding the skipped images. A trainer that consumed
        ``k`` batches before checkpointing resumes the identical stream with
        ``skip_records = k * batch_size``.
      super_batch: fused-dispatch super-batches (``TrainCfg.steps_per_dispatch``):
        an int K or a cyclic plan tuple (``ddw_tpu.train.step.chain_plan`` —
        e.g. ``(K, K, tail)`` covering one epoch). Successive already-
        transferred batches are stacked ON DEVICE on the prefetch thread into
        ``[k, B, ...]`` arrays (chain dim unsharded), so host->HBM bytes are
        exactly the per-batch path's — only the Python dispatch granularity
        changes. Requires ``prefetch_to``; ``None``/all-ones means plain
        per-step batches.
    """

    def __init__(
        self,
        table: Table,
        batch_size: int,
        image_size: tuple[int, int] = (224, 224),
        cur_shard: int = 0,
        shard_count: int = 1,
        num_epochs: int | None = None,
        shuffle: bool = True,
        seed: int = 0,
        shuffle_buffer: int = 1024,
        workers: int = 4,
        prefetch: int = 2,
        prefetch_to=None,
        skip_records: int = 0,
        super_batch=None,
    ):
        if not 0 <= cur_shard < shard_count:
            raise ValueError(f"cur_shard {cur_shard} out of range for shard_count {shard_count}")
        if super_batch is not None:
            plan = ((int(super_batch),) if isinstance(super_batch, int)
                    else tuple(int(k) for k in super_batch))
            if not plan or any(k < 1 for k in plan):
                raise ValueError(f"super_batch must be a positive int or a "
                                 f"tuple of positive chain lengths, got "
                                 f"{super_batch!r}")
            if all(k == 1 for k in plan):
                plan = None  # K=1 everywhere: plain per-step batches
            elif prefetch_to is None:
                # refuse-loudly: the super-batch contract is DEVICE-side
                # stacking on the prefetch thread; silently stacking on host
                # would 1:1 change the H2D transfer granularity it promises
                # not to touch
                raise ValueError("super_batch needs prefetch_to (batches are "
                                 "stacked on device on the prefetch thread)")
            self._super_plan = plan
        else:
            self._super_plan = None
        self.table = table
        self.batch_size = batch_size
        self.height, self.width = image_size
        self.cur_shard = cur_shard
        self.shard_count = shard_count
        self.num_epochs = num_epochs
        self.shuffle = shuffle
        self.seed = seed
        self.shuffle_buffer = shuffle_buffer
        self.workers = workers
        self.prefetch = prefetch
        self.prefetch_to = prefetch_to
        self.skip_records = skip_records

        # Cached-feature table (train.transfer.materialize_features): content is
        # the frozen backbone's pooled feature vector (f32 bytes); batches are
        # (B, feature_dim) — the loader feeds a head-only model.
        self._feature_dim = (table.meta.get("feature_dim")
                             if table.meta.get("encoding") == "features_f32"
                             else None)

        # Token table (prep.write_token_table): content is an int32 [S+1]
        # sequence; batches are next-token pairs (inputs, targets) for the
        # LM family — a memcpy per record, no image work.
        self._token_len = (table.meta.get("seq_plus_one")
                           if table.meta.get("encoding") == "tokens_i32"
                           else None)

        # Pre-decoded table (prep.materialize_decoded): content is raw uint8
        # [H, W, 3] pixels; batches come from a memcpy + scale, no JPEG work.
        self._raw_u8 = table.meta.get("encoding") == "raw_u8"
        if self._raw_u8:
            th, tw = table.meta["height"], table.meta["width"]
            if (th, tw) != (self.height, self.width):
                raise ValueError(
                    f"loader image_size {(self.height, self.width)} != "
                    f"materialized table size {(th, tw)} — re-materialize or "
                    f"match DataCfg.img_height/img_width")
            # The record-count shuffle buffer was sized for ~KB JPEG records;
            # raw_u8 records are H*W*3 bytes (150 KB at 224²), so bound the
            # buffer by bytes (64 MB) instead of pinning shuffle_buffer
            # records of decoded pixels in host RAM.
            record_bytes = th * tw * 3
            self.shuffle_buffer = max(
                2, min(self.shuffle_buffer, (64 << 20) // record_bytes))

        shards = list(table.shard_paths)
        if len(shards) >= shard_count:
            # Shard-level selection (petastorm semantics): disjoint round-robin.
            plan = self.shard_plan(len(shards), shard_count)
            self._my_shards = [shards[i] for i in plan[cur_shard]]
            self._record_stride = None
        else:
            # Fewer shards than workers: fall back to record-level modulo sharding
            # (the reference instead repartitions >= worker count,
            # ``03_model_training_distributed.py:110-111``; prep normally makes
            # enough shards, this keeps small tables correct).
            self._my_shards = shards
            self._record_stride = (cur_shard, shard_count)

    @staticmethod
    def shard_plan(n_shards: int, shard_count: int) -> list[list[int]]:
        """Round-robin assignment of ``n_shards`` table shards to
        ``shard_count`` workers: worker ``r`` owns shard indices
        ``range(r, n_shards, shard_count)``. The plan is a partition — every
        shard index appears in exactly one worker's list — which is what makes
        an elastic shrink (re-deriving loaders at world size N−1) cover every
        sample exactly once per epoch: the N−1 plan re-partitions the same
        shard set, leaving no shard orphaned on the evicted rank."""
        if shard_count < 1:
            raise ValueError(f"shard_count must be >= 1, got {shard_count}")
        return [list(range(r, n_shards, shard_count)) for r in range(shard_count)]

    # -- sizing ----------------------------------------------------------------
    @property
    def records_per_worker(self) -> int:
        """Lower-bound records this worker owns (for step accounting; the trainer
        uses the *global* table size // (batch * world), reference ``:350-351``)."""
        if self._record_stride is None:
            # exact: manifest carries per-shard counts
            counts = {m["file"]: m["num_records"] for m in self.table.manifest["shards"]}
            import os

            return sum(counts[os.path.basename(p)] for p in self._my_shards)
        n, (r, k) = self.table.num_records, self._record_stride
        return n // k + (1 if r < n % k else 0)

    def steps_per_epoch(self) -> int:
        """Global-size floor accounting: ``table_size // (batch * shard_count)``
        (reference ``03_model_training_distributed.py:350-351``)."""
        return max(1, self.table.num_records // (self.batch_size * self.shard_count))

    # -- host pipeline ---------------------------------------------------------
    def _iter_raw(self) -> Iterator[tuple[bytes, int]]:
        """Infinite (or num_epochs-bounded) stream of raw (content, label_idx)
        records for this worker, with epoch-varying shard shuffle + record-level
        shuffle buffer. Shuffling raw bytes (not decoded arrays) keeps the
        buffer ~KB/record instead of ~MB/record."""
        epoch = 0
        while self.num_epochs is None or epoch < self.num_epochs:
            rng = np.random.RandomState((self.seed * 100003 + epoch * 7919 + self.cur_shard) & 0x7FFFFFFF)
            shards = list(self._my_shards)
            if self.shuffle:
                rng.shuffle(shards)

            def records():
                for sp in shards:
                    if self._record_stride is None:
                        yield from read_shard_contents(sp)
                    else:
                        r, k = self._record_stride
                        for i, entry in enumerate(read_shard_contents(sp)):
                            if i % k == r:
                                yield entry

            if not self.shuffle:
                yield from records()
            else:
                buf = []
                for item in records():
                    buf.append(item)
                    if len(buf) >= self.shuffle_buffer:
                        j = rng.randint(len(buf))
                        buf[j], buf[-1] = buf[-1], buf[j]
                        yield buf.pop()
                rng.shuffle(buf)
                yield from buf
            epoch += 1

    def _iter_raw_resumed(self) -> Iterator[tuple[bytes, int]]:
        """The raw stream, fast-forwarded ``skip_records`` records. Skipping
        advances the shuffle RNG identically to consuming, so the resumed
        stream is byte-for-byte the continuation of the original one; skipped
        records are never decoded (raw-bytes cost only)."""
        it = self._iter_raw()
        for _ in range(self.skip_records):
            next(it)
        return it

    def _iter_batches(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        from ddw_tpu.native.decode import decode_batch_native, native_available

        if self._token_len:
            # Token fast path: yield next-token pairs [B, S] — the LM step's
            # exact (inputs, targets) contract.
            t = self._token_len
            toks = np.empty((self.batch_size, t), np.int32)
            i = 0
            for content, _ in self._iter_raw_resumed():
                toks[i] = np.frombuffer(content, np.int32, count=t)
                i += 1
                if i == self.batch_size:
                    yield toks[:, :-1].copy(), toks[:, 1:].copy()
                    i = 0
            return  # drop remainder: static shapes for XLA

        if self._feature_dim:
            # Cached-feature fast path: batches are (B, D) f32 vectors — a
            # memcpy per record, no image work at all.
            d = self._feature_dim
            feats = np.empty((self.batch_size, d), np.float32)
            flbls = np.empty((self.batch_size,), np.int32)
            i = 0
            for content, label_idx in self._iter_raw_resumed():
                feats[i] = np.frombuffer(content, np.float32, count=d)
                flbls[i] = label_idx
                i += 1
                if i == self.batch_size:
                    yield feats.copy(), flbls.copy()
                    i = 0
            return  # drop remainder: static shapes for XLA

        lbls = np.empty((self.batch_size,), np.int32)

        if self._raw_u8:
            # Materialized fast path: reinterpret + dequantize, no JPEG work.
            # With a device prefetcher downstream, batches stay uint8 (pure
            # memcpy here; 4x smaller host->device transfer) and the
            # dequantize runs on device (see __iter__/transfer).
            device_side = self.prefetch_to is not None
            buf = np.empty((self.batch_size, self.height, self.width, 3),
                           np.uint8 if device_side else np.float32)
            i = 0
            for content, label_idx in self._iter_raw_resumed():
                buf[i] = raw_u8_view(content, self.height, self.width)
                lbls[i] = label_idx
                i += 1
                if i == self.batch_size:
                    if not device_side:
                        dequantize_raw_u8(buf)
                    yield buf.copy(), lbls.copy()
                    i = 0
            return  # drop remainder: static shapes for XLA

        imgs = np.empty((self.batch_size, self.height, self.width, 3), np.float32)

        if native_available():
            # Native batch path: one C++ thread-pool call per batch (one GIL
            # release, real OS-thread decode parallelism); per-image failures
            # fall back to PIL.
            contents: list[bytes] = []
            for content, label_idx in self._iter_raw_resumed():
                lbls[len(contents)] = label_idx
                contents.append(content)
                if len(contents) == self.batch_size:
                    _, ok = decode_batch_native(
                        contents, self.height, self.width,
                        threads=self.workers, out=imgs)
                    for j in np.nonzero(~ok)[0]:
                        imgs[j] = _preprocess_image_pil(
                            contents[j], self.height, self.width)
                    yield imgs.copy(), lbls.copy()
                    contents = []
            return  # drop remainder: static shapes for XLA

        # PIL path: decode on a Python thread pool (PIL releases the GIL in its
        # C decode, so threads still overlap).
        pool = ThreadPoolExecutor(max_workers=self.workers)
        try:
            def decode(entry):
                content, label_idx = entry
                return (
                    preprocess_image(content, self.height, self.width),
                    np.int32(label_idx),
                )

            i = 0
            for img, lbl in bounded_map(pool, decode, self._iter_raw_resumed(),
                                        self.workers * 4):
                imgs[i], lbls[i] = img, lbl
                i += 1
                if i == self.batch_size:
                    yield imgs.copy(), lbls.copy()
                    i = 0
            # drop remainder: static shapes for XLA
        finally:
            pool.shutdown(wait=False, cancel_futures=True)

    def __iter__(self):
        """Yield batches; when ``prefetch_to`` is set, a background thread runs the
        host pipeline + device transfer ``prefetch`` batches ahead."""
        if self.prefetch_to is None:
            yield from self._iter_batches()
            return

        import jax

        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        _SENTINEL = object()

        multihost = jax.process_count() > 1
        # raw_u8 tables arrive as uint8 (4x smaller transfer); dequantize on
        # device — one process-wide compilation (_dequant_jitted).
        dequant = _dequant_jitted() if self._raw_u8 else None

        def transfer(imgs, lbls):
            if multihost:
                # Per-host local batches assemble into one global sharded array
                # (global batch = local batch * process_count along dim 0).
                imgs = jax.make_array_from_process_local_data(self.prefetch_to, imgs)
                lbls = jax.make_array_from_process_local_data(self.prefetch_to, lbls)
            else:
                imgs, lbls = jax.device_put((imgs, lbls), self.prefetch_to)
            if dequant is not None:
                imgs = dequant(imgs)
            return imgs, lbls

        def put_or_stop(item) -> bool:
            # Never block forever on a full queue: an abandoned consumer (e.g. the
            # trainer dropping a val iterator after val_steps) sets `stop`; re-check
            # it between bounded put attempts so the thread can exit.
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        plan = self._super_plan
        stack_fn = None
        if plan is not None:
            # Device-side super-batch stacking (steps_per_dispatch): K
            # already-transferred batches concatenate into [k, B, ...] with
            # the chain dim unsharded — one tiny fused device program per
            # chain, on the prefetch thread like the transfer itself. Jitted
            # once per distinct k (at most two: full chain + trailing tail).
            from jax.sharding import NamedSharding, PartitionSpec

            mesh = getattr(self.prefetch_to, "mesh", None)
            spec = getattr(self.prefetch_to, "spec", None)
            if mesh is None or spec is None:
                raise ValueError(
                    f"super_batch needs a NamedSharding prefetch_to to derive "
                    f"the stacked [k, B, ...] sharding, got "
                    f"{type(self.prefetch_to).__name__}")
            sup_sh = NamedSharding(mesh, PartitionSpec(None, *spec))
            stack_fn = jax.jit(
                lambda g: jax.tree.map(lambda *xs: jax.numpy.stack(xs), *g),
                out_shardings=(sup_sh, sup_sh))

        def producer():
            try:
                if plan is None:
                    for imgs, lbls in self._iter_batches():
                        if stop.is_set():
                            return
                        if not put_or_stop(transfer(imgs, lbls)):
                            return
                else:
                    group: list = []
                    ci = 0
                    for imgs, lbls in self._iter_batches():
                        if stop.is_set():
                            return
                        group.append(transfer(imgs, lbls))
                        if len(group) == plan[ci % len(plan)]:
                            if not put_or_stop(stack_fn(tuple(group))):
                                return
                            group = []
                            ci += 1
                    # finite stream: a trailing incomplete group is dropped
                    # (drop_remainder semantics at chain granularity)
                put_or_stop(_SENTINEL)
            except Exception as e:  # surface errors on the consumer side
                put_or_stop(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
        finally:
            stop.set()
            # Drain so device-resident batches are released promptly.
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
