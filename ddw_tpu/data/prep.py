"""Data-prep / ETL pipeline — the ``01_data_prep.py`` contract.

Reproduces the reference ETL (``Part 1 - Distributed Training/01_data_prep.py``):
raw JPEG directory tree -> *bronze* binary table (recursive ``*.jpg`` scan with a
seeded fractional sample, ``:61-66``; 50% at ``:65``) -> label extracted from the
parent directory name (pandas_udf regex on the path, ``:125-130``) -> seeded 90/10
train/val split (seed 42, ``:162``) -> ``label_to_idx`` built from **sorted distinct
labels** (``:179-181``; sorting makes the index deterministic) -> silver_train /
silver_val tables with a ``label_idx`` column (``:187-197,213-222``).

The reference parallelizes the scan across Spark executors; here the hot loop —
per-file read IO — runs on a bounded thread pool (reads release the GIL; the
ETL data-parallelism role, SURVEY.md §2d) with order-preserving windows.
Determinism contract: same source tree + seeds => identical split membership
and identical label index, independent of worker count or filesystem
enumeration order (we sort scanned paths before sampling; parallel reads keep
path order).

Zero-egress testing: :func:`generate_synthetic_flowers` draws a 5-class synthetic
"flowers" JPEG tree (tf_flowers layout: ``<root>/<class_name>/*.jpg``) with
class-distinctive geometry so models genuinely learn (>90% separable), letting every
pipeline stage run without the real dataset.
"""

from __future__ import annotations

import math
import os
import random
from typing import Sequence

import numpy as np

from ddw_tpu.data.store import Record, Table, TableStore

# The reference's class list, ``Part 2 - Distributed Tuning & Inference/
# 03_pyfunc_distributed_inference.py:62``.
FLOWER_CLASSES = ["daisy", "dandelion", "roses", "sunflowers", "tulips"]


def scan_jpeg_tree(source_dir: str, sample_fraction: float = 1.0, seed: int = 12345) -> list[str]:
    """Recursive ``*.jpg``/``*.jpeg`` scan with a seeded fractional sample.

    Mirrors ``binaryFile`` + ``pathGlobFilter='*.jpg'`` + ``recursiveFileLookup`` +
    ``.sample(frac, seed)`` (reference ``01_data_prep.py:61-66``). Paths are sorted
    before sampling so the sample is enumeration-order independent.
    """
    paths = []
    for dirpath, _dirnames, filenames in os.walk(source_dir):
        for fn in filenames:
            if fn.lower().endswith((".jpg", ".jpeg")):
                paths.append(os.path.join(dirpath, fn))
    paths.sort()
    if sample_fraction < 1.0:
        rng = random.Random(seed)
        paths = [p for p in paths if rng.random() < sample_fraction]
    return paths


def label_from_path(path: str) -> str:
    """Label = parent directory name — the pandas_udf regex
    ``'.*/(\\w+)/\\d+[_\\w]*.jpg'`` role (reference ``01_data_prep.py:125-130``)."""
    return os.path.basename(os.path.dirname(path))


def build_label_index(labels: Sequence[str]) -> dict[str, int]:
    """Sorted-distinct label -> index map (reference ``01_data_prep.py:179-181``)."""
    return {lbl: i for i, lbl in enumerate(sorted(set(labels)))}


def _prep_plan(source_dir: str, sample_fraction: float, train_fraction: float,
               split_seed: int):
    """The deterministic global ETL plan — identical on every worker.

    (sorted+sampled paths, label_to_idx, train-membership index set). Because
    the plan depends only on the source tree and seeds, distributed workers
    can each compute it locally and agree without communicating (the Spark
    driver's query plan role, reference ``01_data_prep.py:61-66,162``).
    """
    paths = scan_jpeg_tree(source_dir, sample_fraction)
    if not paths:
        raise FileNotFoundError(f"no JPEGs under {source_dir}")
    label_to_idx = build_label_index([label_from_path(p) for p in paths])
    rng = np.random.RandomState(split_seed)
    perm = rng.permutation(len(paths))
    n_train = int(math.floor(train_fraction * len(paths)))
    train_ids = set(perm[:n_train].tolist())
    return paths, label_to_idx, train_ids


def prepare_flowers(
    source_dir: str,
    store: TableStore,
    sample_fraction: float = 0.5,
    train_fraction: float = 0.9,
    split_seed: int = 42,
    shard_size: int = 256,
    bronze_name: str = "flowers_bronze",
    train_name: str = "silver_train",
    val_name: str = "silver_val",
    io_workers: int = 8,
) -> tuple[Table, Table, dict[str, int]]:
    """Full 01_data_prep pipeline: scan -> bronze -> label/split/index -> silver.

    Returns (silver_train, silver_val, label_to_idx). Split uses a seeded
    permutation of the bronze rows (the ``randomSplit([.9,.1], seed=42)`` role,
    reference ``01_data_prep.py:162``). ``io_workers`` parallelizes the raw
    file reads (executor-scan role) without changing record order. For
    multi-process prep see :func:`prepare_flowers_distributed`.
    """
    from concurrent.futures import ThreadPoolExecutor

    from ddw_tpu.data.loader import bounded_map

    paths, label_to_idx, train_ids = _prep_plan(
        source_dir, sample_fraction, train_fraction, split_seed)

    def read_one(p: str) -> Record:
        with open(p, "rb") as f:
            return Record(path=p, content=f.read())

    def bronze_records():
        with ThreadPoolExecutor(max_workers=io_workers) as pool:
            yield from bounded_map(pool, read_one, paths, io_workers * 4)

    bronze = store.write(bronze_name, bronze_records(), shard_size=shard_size,
                         meta={"source_dir": source_dir, "sample_fraction": sample_fraction})

    # Single pass over bronze, routing each record to its split writer (re-reading
    # the bronze table once per destination would double prep IO at scale).
    t_meta = {"label_to_idx": label_to_idx, "split": "train", "split_seed": split_seed}
    v_meta = {"label_to_idx": label_to_idx, "split": "val", "split_seed": split_seed}
    with store.writer(train_name, shard_size, t_meta) as tw, \
         store.writer(val_name, shard_size, v_meta) as vw:
        for i, rec in enumerate(bronze.iter_records()):
            lbl = label_from_path(rec.path)
            silver_rec = Record(rec.path, rec.content, lbl, label_to_idx[lbl])
            (tw if i in train_ids else vw).append(silver_rec)
    return tw.close(), vw.close(), label_to_idx


def prepare_flowers_distributed(
    source_dir: str,
    store: TableStore,
    worker_index: int,
    worker_count: int,
    sample_fraction: float = 0.5,
    train_fraction: float = 0.9,
    split_seed: int = 42,
    shard_size: int = 256,
    bronze_name: str = "flowers_bronze",
    train_name: str = "silver_train",
    val_name: str = "silver_val",
    io_workers: int = 8,
    merge_timeout_s: float = 600.0,
    abort=None,
) -> tuple[Table, Table, dict[str, int]] | None:
    """Multi-worker 01_data_prep: the Spark-executors ETL role, shared-nothing.

    Every worker computes the identical deterministic plan (:func:`_prep_plan`),
    takes the round-robin slice ``paths[worker_index::worker_count]``, reads its
    files on a thread pool, and writes per-worker part tables
    (``<name>_p<w>``). Worker 0 then waits for all parts and commits the final
    tables via zero-copy manifest merge (:meth:`TableStore.merge_shards`) —
    the executors-scan / driver-commits split of the reference
    (``01_data_prep.py:61-95``). Same split membership and label index as
    :func:`prepare_flowers` (the plan is shared); record order differs
    (per-worker striping), which the shuffling loader never observes.

    Returns (silver_train, silver_val, label_to_idx) on worker 0, None on
    other workers. Workers must share ``store``'s filesystem. ``abort`` (an
    optional zero-arg callable returning a reason string, polled while
    waiting) lets the coordinator fail fast when a worker process dies
    instead of sleeping out ``merge_timeout_s``.
    """
    from concurrent.futures import ThreadPoolExecutor

    from ddw_tpu.data.loader import bounded_map

    if not 0 <= worker_index < worker_count:
        raise ValueError(f"worker_index {worker_index} out of range "
                         f"for worker_count {worker_count}")
    paths, label_to_idx, train_ids = _prep_plan(
        source_dir, sample_fraction, train_fraction, split_seed)
    my = list(range(worker_index, len(paths), worker_count))

    # Run token: every worker derives the identical id from the run's actual
    # inputs (config + the sampled files' identity), with no communication.
    # The coordinator only merges parts carrying this id, so a re-run against
    # changed data can never silently mix a previous run's parts
    # (TableStore.await_parts). Same data + config => same id, and then stale
    # parts are byte-identical to fresh ones, so matching them is harmless.
    def _stat(p):
        st = os.stat(p)
        return f"{p}|{st.st_size}|{st.st_mtime_ns}"

    run_id = TableStore.run_token(
        (worker_count, sample_fraction, train_fraction, split_seed, shard_size),
        [_stat(p) for p in paths])

    def read_one(i: int) -> tuple[int, Record]:
        with open(paths[i], "rb") as f:
            return i, Record(path=paths[i], content=f.read())

    part = f"_p{worker_index}"
    b_meta = {"source_dir": source_dir, "sample_fraction": sample_fraction,
              "worker": worker_index, "run_id": run_id}
    t_meta = {"label_to_idx": label_to_idx, "split": "train",
              "split_seed": split_seed, "worker": worker_index,
              "run_id": run_id}
    v_meta = {**t_meta, "split": "val"}
    with store.writer(bronze_name + part, shard_size, b_meta) as bw, \
         store.writer(train_name + part, shard_size, t_meta) as tw, \
         store.writer(val_name + part, shard_size, v_meta) as vw, \
         ThreadPoolExecutor(max_workers=io_workers) as pool:
        for i, rec in bounded_map(pool, read_one, my, io_workers * 4):
            bw.append(rec)
            lbl = label_from_path(rec.path)
            silver = Record(rec.path, rec.content, lbl, label_to_idx[lbl])
            (tw if i in train_ids else vw).append(silver)

    if worker_index != 0:
        return None

    # Coordinator: wait for every worker's current-run parts, then commit
    # merged tables (zero-copy manifest concat).
    def merge(name, meta):
        parts = store.await_parts([f"{name}_p{w}" for w in range(worker_count)],
                                  run_id, merge_timeout_s, abort=abort)
        return store.merge_shards(name, parts,
                                  meta={**meta, "worker_count": worker_count,
                                        "run_id": run_id})

    merge(bronze_name, {"source_dir": source_dir,
                        "sample_fraction": sample_fraction})
    train_tbl = merge(train_name, {"label_to_idx": label_to_idx,
                                   "split": "train", "split_seed": split_seed})
    val_tbl = merge(val_name, {"label_to_idx": label_to_idx,
                               "split": "val", "split_seed": split_seed})
    return train_tbl, val_tbl, label_to_idx


def materialize_decoded(
    table: Table,
    store: TableStore,
    out_name: str,
    height: int,
    width: int,
    shard_size: int = 256,
    io_workers: int = 4,
) -> Table:
    """Materialize a silver table into a pre-decoded ``raw_u8`` table.

    The Petastorm materialized-cache role (the reference converts the Spark
    table into a decoded parquet cache before training,
    ``03_model_training_distributed.py:137-144``): decode + resize every JPEG
    ONCE at prep time and store raw uint8 [H, W, 3] pixels, so the training
    loader's per-batch work drops from JPEG decode (~1.7 ms/img on a 1-core
    host — measured in ``bench.py``, where live decode starves the chip ~65x)
    to a memcpy + scale. Pixels are produced by the SAME shared
    ``preprocess_image`` path training/serving use, then quantized to uint8
    (max quantization error 1/255 of the [-1, 1] range — the JPEG already
    quantized harder). The loader detects ``meta.encoding == 'raw_u8'`` and
    skips decode.

    Size: ~H*W*3 bytes/record (150 KB at 224²) vs ~20-40 KB JPEG — the
    standard decode-once/store-big tradeoff the reference's cache makes too.
    """
    from concurrent.futures import ThreadPoolExecutor

    from ddw_tpu.data.loader import bounded_map, preprocess_image

    def decode(rec: Record) -> Record:
        arr = preprocess_image(rec.content, height, width)  # f32 [-1, 1]
        u8 = np.clip(np.round((arr + 1.0) * 127.5), 0, 255).astype(np.uint8)
        return Record(rec.path, u8.tobytes(), rec.label, rec.label_idx)

    meta = {**table.meta, "encoding": "raw_u8", "height": height,
            "width": width, "source_table": table.manifest["name"],
            "source_version": table.manifest["version"]}
    with ThreadPoolExecutor(max_workers=io_workers) as pool:
        return store.write(
            out_name,
            bounded_map(pool, decode, table.iter_records(), io_workers * 4),
            shard_size=shard_size, meta=meta)


def write_token_table(
    store: TableStore,
    name: str,
    tokens,
    shard_size: int = 2048,
) -> Table:
    """Materialize a token corpus ``[N, S+1]`` int32 as a ``tokens_i32``
    table — the LM family's storage format, completing the same
    store -> loader -> trainer path the vision families train through
    (the reference's only corpus is images, ``01_data_prep.py``; the LM
    stack is beyond parity and gets the same data discipline). The loader
    detects ``meta.encoding == 'tokens_i32'`` and yields next-token pairs
    ``(batch[:, :-1], batch[:, 1:])`` with zero decode work.
    """
    tokens = np.asarray(tokens, np.int32)
    if tokens.ndim != 2 or tokens.shape[1] < 2 or tokens.shape[0] < 1:
        raise ValueError(f"tokens must be a non-empty [num_seqs, seq_len+1], "
                         f"got {tokens.shape}")
    meta = {"encoding": "tokens_i32", "seq_plus_one": int(tokens.shape[1])}
    recs = (Record(path=f"seq/{i:08d}", content=np.ascontiguousarray(row).tobytes())
            for i, row in enumerate(tokens))
    return store.write(name, recs, shard_size=shard_size, meta=meta)


# ---------------------------------------------------------------------------
# Synthetic flowers (zero-egress stand-in for tf_flowers)
# ---------------------------------------------------------------------------

def _draw_class_image(rng: np.random.RandomState, cls_idx: int, size: int) -> "np.ndarray":
    """Class-distinctive synthetic image: each class gets a distinct dominant hue and
    petal-count geometry, with noise, random rotation/position/scale so the task is
    learnable but not trivial."""
    img = (rng.rand(size, size, 3) * 60).astype(np.float32)  # dark noise background
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    cx, cy = rng.uniform(size * 0.3, size * 0.7, 2)
    r = np.hypot(xx - cx, yy - cy)
    theta = np.arctan2(yy - cy, xx - cx) + rng.uniform(0, 2 * np.pi)
    petals = 3 + cls_idx * 2                      # 3,5,7,9,11 petals by class
    radius = size * rng.uniform(0.18, 0.30) * (1 + 0.45 * np.cos(petals * theta))
    mask = r < radius
    hue = np.zeros(3, np.float32)
    hue[cls_idx % 3] = 200 + rng.uniform(0, 55)
    hue[(cls_idx + 1) % 3] = 60 * (cls_idx // 3) + rng.uniform(0, 40)
    img[mask] = hue + rng.randn(int(mask.sum()), 3).astype(np.float32) * 12
    return np.clip(img, 0, 255).astype(np.uint8)


def generate_synthetic_flowers(
    root: str,
    images_per_class: int = 40,
    size: int = 64,
    classes: Sequence[str] = tuple(FLOWER_CLASSES),
    seed: int = 0,
) -> str:
    """Write a tf_flowers-layout JPEG tree (``<root>/<class>/<i>.jpg``)."""
    from PIL import Image

    rng = np.random.RandomState(seed)
    for ci, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        os.makedirs(cdir, exist_ok=True)
        for i in range(images_per_class):
            arr = _draw_class_image(rng, ci, size)
            Image.fromarray(arr).save(os.path.join(cdir, f"{i:04d}.jpg"), quality=90)
    return root
