"""ReplicaSet — one front door over N serving-engine replicas.

A single :class:`~ddw_tpu.serve.ServingEngine` is bounded by its slot pool:
``n_slots`` sequences decode per dispatch and everyone else queues. The
fleet answer is horizontal — more engine replicas, each with its own
compiled programs and KV pool — and this class is the piece that makes N
replicas look like one engine to the transport layer above it:

- **routing is admission-aware and cache-aware**: every submission goes to
  the replica with the lowest projected wait — queue depth + busy slots
  weighted by the engine's own decaying per-request service estimate
  (``ServingEngine.load()``), falling back to the outstanding-futures
  count for replicas that don't expose load. Generate submissions also
  credit expected prefill savings: the fleet prefix index
  (:class:`~ddw_tpu.gateway.prefix_index.PrefixIndex`) reports each
  replica's longest cached prefix of the prompt, and matched tokens x that
  replica's per-prefilled-token EWMA are subtracted from its projected
  wait — requests chase their warm prefix only while the holder's queue
  stays cheaper than a cold prefill elsewhere. Routing never changes
  results, only placement: every replica computes bit-identical tokens.
  Outstanding counts are kept here, incremented at submit and decremented
  by a future done-callback, so routing needs no cross-thread peeking
  into engine internals;
- **roles disaggregate prefill from decode**: a replica advertising
  ``role="prefill"`` never takes decode-bearing traffic directly. A
  generate submission against a mixed fleet is split instead: the
  TTFT-aware splitter prefills on the replica whose prefix credit +
  projected wait is lowest, exports the prompt's KV blocks over the
  versioned wire (:meth:`~ddw_tpu.serve.blocks.BlockPool.export_blocks`),
  imports them into the decode replica chosen by projected wait +
  block-pool headroom, and submits the full request there — the prefix
  index doubles as the transfer directory, so blocks the receiver already
  holds warm never cross the wire. Any handoff failure falls back to
  colocated routing on a decode-capable replica; clients never see a
  migration error;
- **every replica sits behind a circuit breaker**
  (:class:`CircuitBreaker`): consecutive :class:`~ddw_tpu.serve.admission.
  ReplicaFailed` outcomes — or the engine's own death report — open the
  circuit and routing skips the replica entirely; after a cooldown (or the
  supervisor's explicit warmed-rejoin gate) ONE half-open probe request is
  admitted, and its outcome closes or re-opens the circuit. When every
  circuit is open the set refuses with a structured
  :class:`~ddw_tpu.serve.admission.Unavailable` (503 + ``Retry-After`` at
  the gateway) — never a hang;
- **backpressure spills sideways once**: a submission refused with
  :class:`~ddw_tpu.serve.Overloaded` by the best replica is retried on the
  next candidate before the refusal surfaces. A dead replica
  (``ReplicaFailed`` at submit) does NOT consume that budget — routing
  walks past corpses to any live sibling;
- **failover adopts a dead replica's queue**: when an engine dies it hands
  its queued, nothing-emitted requests to :meth:`_on_replica_failure` (the
  engine's ``on_failure`` hook); each is resubmitted to a healthy sibling
  *with its original future intact* when its deadline (and the sibling's
  projected wait) allows, else completed with the structured refusal —
  callers see tokens or a clean 503/504, never a hang. Requests that had
  already streamed tokens fail with ``ReplicaFailed`` (re-running them
  would duplicate the stream; the client's retry policy owns that call);
- **metrics aggregate** (:func:`ddw_tpu.serve.metrics.merge_metrics`):
  ``snapshot()`` and ``prometheus()`` reduce over every replica's records,
  with per-replica outstanding/circuit/restart gauges alongside.

The submission surface mirrors the engine (``submit_generate`` /
``submit_predict`` / ``warmup`` / ``start`` / ``stop`` / context manager),
so anything written against one engine — the HTTP gateway, the load
generator, the tests — serves a fleet by swapping the object. Restarting
dead replicas is not this class's job: :class:`~ddw_tpu.gateway.supervisor.
ReplicaSupervisor` watches the same health surface and owns recovery.
"""

from __future__ import annotations

import concurrent.futures
import threading
import time

from ddw_tpu.gateway.prefix_index import PrefixIndex
from ddw_tpu.serve.admission import (DeadlineExceeded, Overloaded,
                                     ReplicaFailed, Unavailable)
from ddw_tpu.serve.metrics import (EngineMetrics, merge_metrics,
                                   render_prometheus)

__all__ = ["ReplicaSet", "CircuitBreaker",
           "CIRCUIT_CLOSED", "CIRCUIT_HALF_OPEN", "CIRCUIT_OPEN"]

CIRCUIT_CLOSED = "closed"
CIRCUIT_HALF_OPEN = "half_open"
CIRCUIT_OPEN = "open"

# numeric encodings for the flat snapshot / Prometheus gauge
_CIRCUIT_CODE = {CIRCUIT_CLOSED: 0.0, CIRCUIT_HALF_OPEN: 1.0,
                 CIRCUIT_OPEN: 2.0}


class CircuitBreaker:
    """Per-replica request-outcome FSM: CLOSED (routing) → OPEN (skipped)
    → HALF_OPEN (one probe) → CLOSED, the classic pattern.

    OPENs on ``failure_threshold`` consecutive replica-fault outcomes, on a
    failed half-open probe, or on an explicit :meth:`trip` (the engine's
    death report / the supervisor's stall verdict). After ``cooldown_s`` it
    lapses to HALF_OPEN by itself; the supervisor's :meth:`half_open` opens
    the probe window immediately after a warmed restart instead of waiting
    out the clock. Only replica faults count — ``Overloaded`` and deadline
    sheds are honest load answers from a *live* replica, not failures."""

    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 5.0,
                 clock=time.monotonic):
        if failure_threshold < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}")
        self.failure_threshold = failure_threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CIRCUIT_CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probing = False
        self.opened = 0             # total trips (telemetry)

    def _state_locked(self) -> str:
        if (self._state == CIRCUIT_OPEN
                and self._clock() - self._opened_at >= self.cooldown_s):
            self._state = CIRCUIT_HALF_OPEN
            self._probing = False
        return self._state

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def available(self) -> bool:
        """Peek: would a submission be routed here right now? (CLOSED, or
        HALF_OPEN with the probe slot free.)"""
        with self._lock:
            s = self._state_locked()
            return s == CIRCUIT_CLOSED or (s == CIRCUIT_HALF_OPEN
                                           and not self._probing)

    def begin_probe(self) -> None:
        """Claim the single HALF_OPEN probe slot (no-op when CLOSED)."""
        with self._lock:
            if self._state_locked() == CIRCUIT_HALF_OPEN:
                self._probing = True

    def abort_probe(self) -> None:
        """Release the probe slot on a neutral outcome (deadline shed,
        cancel) that proves nothing about replica health."""
        with self._lock:
            self._probing = False

    def record_success(self) -> None:
        with self._lock:
            if self._state == CIRCUIT_OPEN:
                return      # a straggler finishing does not close an
            #                 opened circuit — only a probe can
            self._state = CIRCUIT_CLOSED
            self._consecutive = 0
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            s = self._state_locked()
            self._consecutive += 1
            if (s == CIRCUIT_HALF_OPEN
                    or self._consecutive >= self.failure_threshold):
                self._trip_locked()

    def trip(self) -> None:
        """Force OPEN now — the engine reported itself dead; waiting for
        request outcomes to accumulate would route traffic into a corpse."""
        with self._lock:
            self._trip_locked()

    def _trip_locked(self) -> None:
        if self._state != CIRCUIT_OPEN:
            self.opened += 1
        self._state = CIRCUIT_OPEN
        self._opened_at = self._clock()
        self._probing = False

    def half_open(self) -> None:
        """Open the probe window immediately (the supervisor's rejoin gate
        after a warmed restart) instead of waiting out the cooldown."""
        with self._lock:
            if self._state == CIRCUIT_OPEN:
                self._state = CIRCUIT_HALF_OPEN
                self._probing = False

    def close(self) -> None:
        """Close the circuit on EXTERNAL evidence of health — the
        supervisor's shadow warmup probe succeeded against the replica
        directly, so no live client request has to play guinea pig in the
        half-open window."""
        with self._lock:
            self._state = CIRCUIT_CLOSED
            self._consecutive = 0
            self._probing = False

    def retry_after_ms(self) -> float:
        """How long until this circuit's next probe window (0 when not
        OPEN) — the honest Retry-After hint for a fleet-wide refusal."""
        with self._lock:
            if self._state_locked() != CIRCUIT_OPEN:
                return 0.0
            return max(0.0, (self._opened_at + self.cooldown_s
                             - self._clock()) * 1e3)


class ReplicaSet:
    """Admission-aware, circuit-breaking router over engine replicas."""

    def __init__(self, replicas, failure_threshold: int = 3,
                 cooldown_s: float = 5.0, route_by_prefix: bool = True):
        if hasattr(replicas, "submit_generate"):   # a bare engine
            replicas = [replicas]
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("ReplicaSet needs at least one engine replica")
        n = len(self.replicas)
        self._failure_threshold = failure_threshold   # for added slots
        self._cooldown_s = cooldown_s
        self._outstanding = [0] * n
        self._where: dict = {}      # future -> replica index (for the
        #                             done-callback and failover moves)
        self._lock = threading.Lock()
        self.breakers = [CircuitBreaker(failure_threshold, cooldown_s)
                         for _ in range(n)]
        self.restarts = [0] * n     # supervisor restarts, via note_restart
        self.replica_failures = 0   # terminal engine deaths observed
        self.failed_over = 0        # requests adopted by a sibling
        self.retried_429 = 0        # refusals absorbed by a sibling retry
        self.failure_event = threading.Event()   # supervisor wake-up
        self.prefix_index = PrefixIndex()   # fleet prefix map: fed from
        #                                     the pools' event logs on the
        #                                     routing path, read by the
        #                                     supervisor's warm replay
        self.route_by_prefix = route_by_prefix   # False = pure projected-
        #                                          wait (least-outstanding)
        #                                          routing, the A/B baseline
        #                                          tools/serving_curve.py
        #                                          measures against
        self.tracer = None          # obs.Tracer installed by the Gateway
        #                             when tracing: routing decisions become
        #                             spans (projected wait, prefix credit,
        #                             chosen replica, spill/failover)
        self.telemetry = None       # obs.FleetTelemetry installed by the
        #                             Gateway when sampling: replace() must
        #                             clear the dead engine's cached series
        #                             so merged windows don't mix epochs
        self.fleet_metrics = EngineMetrics()    # fleet-level counters (the
        #                             rollout lifecycle: canary verdicts,
        #                             surge spawns, journal resumes) — owned
        #                             here, not by a replica, so replace()
        #                             can't lose them; merged into
        #                             snapshot()/prometheus() with the rest
        self._canary = None         # (replica index, traffic fraction)
        #                             while a canary deploy holds one
        #                             replica at a weighted share
        self._canary_count = 0      # deterministic diversion counter
        self.adapter_digests: dict[str, str] = {}   # adapter_id -> sha256
        #                             hex, fed by the gateway's
        #                             /admin/adapters staged load — the
        #                             salt source for adapter-aware prefix
        #                             routing (an unknown adapter routes
        #                             by load alone; its salted chains
        #                             can't match base keys anyway)
        for i, eng in enumerate(self.replicas):
            self._wire(i, eng)

    def _wire(self, i: int, eng) -> None:
        """Attach the fleet identity + failover hook (best-effort: plain
        fakes without the attributes still route)."""
        try:
            eng.replica_id = i
            eng.on_failure = (lambda failure, salvage, _i=i:
                              self._on_replica_failure(_i, failure, salvage))
        except AttributeError:
            pass

    # -- lifecycle (fan-out) ------------------------------------------------
    def start(self) -> "ReplicaSet":
        for eng in self.replicas:
            eng.start()
        return self

    def stop(self) -> None:
        for eng in self.replicas:
            eng.stop()

    def warmup(self, prompt_lens=(8,)) -> None:
        for eng in self.replicas:
            eng.warmup(prompt_lens)

    def __enter__(self) -> "ReplicaSet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def replace(self, i: int, eng) -> None:
        """Swap in a replacement replica (the clone_fresh recovery path for
        a wedged thread). Outstanding futures of the old engine keep their
        accounting — they resolve through the same done-callback."""
        self._wire(i, eng)
        self.replicas[i] = eng
        self.prefix_index.drop_replica(i)   # a fresh engine holds nothing
        if self.telemetry is not None:
            self.telemetry.drop_replica(f"replica{i}")

    def note_restart(self, i: int) -> None:
        with self._lock:
            if 0 <= i < len(self.restarts):
                self.restarts[i] += 1

    # -- elastic membership (the autoscaler's actuation surface) -------------
    #
    # Membership changes REPLACE the per-slot lists instead of mutating
    # them in place: a router thread that captured the old lists under the
    # lock keeps a mutually consistent (replicas, breakers, outstanding)
    # view for the rest of its submission — it can still route to a
    # retiring replica (which refuses and spills sideways, never a torn
    # IndexError), but it can never observe lists of different lengths.
    # fleet_metrics is untouched by construction: it is owned here, not
    # per-slot, so scale events can't lose canary/handoff/journal counters.

    def add_replica(self, eng) -> int:
        """Admit an ALREADY-WARM replica into the routed fleet (the
        autoscale controller spawns, warms, and shadow-probes it first —
        capacity is never consumed by a cold replica). Returns the new
        slot index."""
        self._wire(len(self.replicas), eng)
        with self._lock:
            i = len(self.replicas)
            self.replicas = self.replicas + [eng]
            self._outstanding = self._outstanding + [0]
            self.breakers = self.breakers + [CircuitBreaker(
                self._failure_threshold, self._cooldown_s)]
            self.restarts = self.restarts + [0]
        return i

    def remove_replica(self, i: int):
        """Retire slot ``i`` from the routed fleet: pop every per-slot
        structure, renumber the slots above it (in-flight futures keep
        their accounting through the renumbered ``_where`` map), and clear
        the router-side caches — :meth:`PrefixIndex.drop_replica` and
        :meth:`FleetTelemetry.drop_replica` — for every source whose slot
        identity changed, so repeated scale cycles leak nothing. Returns
        the removed engine; the CALLER owns its drain/stop discipline (by
        the time this runs the victim should hold no outstanding work)."""
        with self._lock:
            n = len(self.replicas)
            if n <= 1:
                raise ValueError("cannot remove the last replica")
            if not 0 <= i < n:
                raise IndexError(f"replica slot {i} out of range 0..{n - 1}")
            eng = self.replicas[i]
            self.replicas = self.replicas[:i] + self.replicas[i + 1:]
            self._outstanding = (self._outstanding[:i]
                                 + self._outstanding[i + 1:])
            self.breakers = self.breakers[:i] + self.breakers[i + 1:]
            self.restarts = self.restarts[:i] + self.restarts[i + 1:]
            for fut, j in list(self._where.items()):
                if j == i:          # victim stragglers: accounting already
                    self._where.pop(fut)        # popped with the slot
                elif j > i:
                    self._where[fut] = j - 1
            can = self._canary
            if can is not None:
                ci, frac = can
                if ci == i:
                    self._canary = None
                elif ci > i:
                    self._canary = (ci - 1, frac)
        # every slot >= i changed identity: drop the router-side caches
        # keyed by the OLD slot numbers (the prefix feed's since=0 re-poll
        # and the telemetry re-ingest rebuild them for the new numbering)
        for old in range(i, n):
            self.prefix_index.drop_replica(old)
            if self.telemetry is not None:
                self.telemetry.drop_replica(f"replica{old}")
        for j in range(i, len(self.replicas)):
            self._wire(j, self.replicas[j])
        return eng

    # -- routing ------------------------------------------------------------
    def outstanding(self) -> list[int]:
        with self._lock:
            return list(self._outstanding)

    def fleet_health(self) -> list[dict]:
        """Per-replica health + circuit view (the /stats payload)."""
        with self._lock:
            replicas = self.replicas
            breakers = self.breakers
            restarts = list(self.restarts)
            outs = list(self._outstanding)
        out = []
        for i, eng in enumerate(replicas):
            h = (eng.health() if hasattr(eng, "health")
                 else {"state": "unknown", "replica": i})
            h["circuit"] = breakers[i].state
            h["restarts"] = restarts[i]
            h["outstanding"] = outs[i]
            out.append(h)
        return out

    def _score(self, i: int, outstanding: int, saved_tokens: int = 0,
               replicas=None):
        """Projected-wait routing key: (estimated wait ms, pending work,
        index). Engines exposing ``load()`` are scored on queue depth +
        busy slots x their own EWMA service estimate — the ROADMAP's
        admission-aware routing; anything else falls back to the
        outstanding-futures count (ties by index keep it deterministic).
        ``saved_tokens`` is this replica's cached-prefix match for the
        prompt being routed: matched tokens x its per-prefilled-token EWMA
        are credited against the wait, so a warm replica wins exactly
        until its queue costs more than the cold prefill elsewhere.
        ``replicas`` is the caller's captured membership view (elastic
        fleets replace the list on scale events)."""
        eng = (replicas if replicas is not None else self.replicas)[i]
        if hasattr(eng, "load"):
            try:
                ld = eng.load()
                pending = float(ld["depth"] + ld["busy"])
                wait = pending * float(ld.get("service_ms") or 0.0)
                if saved_tokens:
                    wait -= (saved_tokens
                             * float(ld.get("prefill_token_ms") or 0.0))
                return (wait, pending, i)
            except Exception:
                pass
        return (0.0 if not saved_tokens else -float(saved_tokens),
                float(outstanding), i)

    def _scored(self, exclude=(), matched=None, weighted=True) -> list:
        """``weighted=False`` skips the canary reorder (and its diversion
        counter) — the telemetry sampler's read-only view."""
        with self._lock:
            # one consistent membership view: the per-slot lists are
            # replaced (never resized in place) on scale events, so
            # capturing them together under the lock can't tear
            outs = list(self._outstanding)
            replicas = self.replicas
            breakers = self.breakers
        scored = [self._score(i, outs[i],
                              matched.get(i, 0) if matched else 0,
                              replicas=replicas)
                  for i in range(len(replicas))
                  if i not in exclude and breakers[i].available()]
        scored.sort()
        return self._canary_reorder(scored) if weighted else scored

    # -- canary weighting ----------------------------------------------------
    def set_canary(self, i: int, fraction: float) -> None:
        """Hold replica ``i`` at ``fraction`` of eligible traffic while a
        canary deploy judges it. ``fraction=0`` is a *dark* canary: no
        client traffic unless every sibling refuses (the canary stays a
        last-resort spill target — a 429 to the client would be a worse
        outcome than a canary-served request)."""
        with self._lock:
            self._canary = (i, max(0.0, min(1.0, float(fraction))))
            self._canary_count = 0

    def clear_canary(self) -> None:
        with self._lock:
            self._canary = None

    def _canary_reorder(self, scored: list) -> list:
        """Weighted canary routing over the projected-wait order: a
        deterministic counter diverts ≈``fraction`` of eligible requests to
        the canary; everything else prefers the siblings (canary demoted to
        last-resort spill). The PR 11 tie-break discipline carries over —
        a diverted request still loses the canary if its projected wait is
        GENUINELY longer than the best sibling's, so holding a fraction
        never queues clients behind a struggling canary."""
        with self._lock:
            can = self._canary
            if can is None:
                return scored
            self._canary_count += 1
            n = self._canary_count
        ci, frac = can
        canary = [s for s in scored if s[-1] == ci]
        rest = [s for s in scored if s[-1] != ci]
        if not canary or not rest:
            return scored
        if (int(n * frac) > int((n - 1) * frac)
                and canary[0][0] <= rest[0][0]):
            return canary + rest
        return rest + canary

    def _order(self, exclude=(), matched=None) -> list[int]:
        """Healthy replica indices, best candidate first. ``matched`` is
        the prefix index's slot -> matched-prefix-tokens map for the
        prompt being routed (None for non-generate submissions)."""
        return [s[-1] for s in self._scored(exclude, matched)]

    def _min_retry_ms(self) -> float:
        hints = [b.retry_after_ms() for b in self.breakers]
        live = [h for h in hints if h > 0]
        return min(live) if live else 1000.0

    def _dec(self, i: int) -> None:
        with self._lock:
            if 0 <= i < len(self._outstanding):
                self._outstanding[i] -= 1

    def _on_done(self, fut) -> None:
        """Every routed future lands here exactly once — the accounting
        decrement AND the breaker's outcome feed. Submission paths that
        raise never registered the future, so the counter can't leak.
        ``_where`` is renumbered by ``remove_replica``, so the slot read
        here tracks membership changes that happened mid-flight."""
        with self._lock:
            i = self._where.pop(fut, None)
            if i is not None and i < len(self._outstanding):
                self._outstanding[i] -= 1
            breakers = self.breakers
        if i is None or i >= len(breakers):
            return
        try:
            exc = None if fut.cancelled() else fut.exception()
        except Exception:
            exc = None
        if exc is None:
            breakers[i].record_success()
        elif isinstance(exc, ReplicaFailed):
            breakers[i].record_failure()
        else:
            # Overloaded/DeadlineExceeded are honest load answers from a
            # live replica — neutral for health, but a claimed probe slot
            # must not leak
            breakers[i].abort_probe()

    def _submit(self, method: str, args, kwargs, prompt=None):
        tracer = self.tracer
        t_route = time.monotonic() if tracer is not None else 0.0
        matched = None
        hexes: list = []
        adapter_id = kwargs.get("adapter_id")
        salt = b""
        if adapter_id is not None:
            dg = self.adapter_digests.get(adapter_id)
            if dg:
                salt = bytes.fromhex(dg)
            else:
                # digest unknown at the routing layer: the request's
                # salted chains can't match any base key, so a base match
                # would route it to warmth it cannot use — skip matching
                prompt = None
        if prompt is not None and self.route_by_prefix:
            try:        # index staleness/unavailability must never block
                self.prefix_index.poll(self.replicas)
                matched, hexes = self.prefix_index.match(
                    prompt, with_hashes=True, salt=salt)
                matched = matched or None
            except Exception:
                matched, hexes = None, []
        exclude = ()
        if method in ("submit_generate", "submit_batch_item"):
            # a pure prefill worker finishes every generate at its first
            # emitted token — decode-bearing requests must not land there
            # while a decode-capable sibling exists
            exclude = self._prefill_only()
        if method == "submit_generate" and exclude and adapter_id is None:
            # adapter-tagged requests never take the prefill→decode
            # handoff: adapter residency (slot + salt) is replica-local
            # and salted blocks are excluded from KV export by design
            fut = self._try_handoff(args, kwargs, matched, hexes)
            if fut is not None:
                return fut
        scored = self._scored(exclude=exclude, matched=matched)
        order = [s[-1] for s in scored]
        if not order:
            raise Unavailable("all replica circuits open",
                              retry_after_ms=self._min_retry_ms())
        with self._lock:
            replicas = self.replicas       # consistent membership view for
            breakers = self.breakers       # the rest of this submission
        # the routing span is allocated up front so the engine's own chain
        # (queue -> prefill -> decode) can parent on it across the hop
        route_sid = None
        if tracer is not None and "trace_id" in kwargs:
            route_sid = tracer._next_span_id()
            parent = kwargs.get("parent_span")
            kwargs = dict(kwargs, parent_span=route_sid)
        last = None
        overloads = 0
        for i in order:
            if overloads >= 2:
                break               # the single-sideways-spill budget
            if i >= len(replicas):
                continue            # slot retired between score and submit
            with self._lock:
                if i < len(self._outstanding):
                    self._outstanding[i] += 1
            try:
                fut = getattr(replicas[i], method)(*args, **kwargs)
            except Overloaded as e:
                self._dec(i)
                last = e
                overloads += 1
                if overloads < 2 and i != order[-1]:
                    with self._lock:
                        self.retried_429 += 1
                continue
            except ReplicaFailed as e:
                self._dec(i)        # a corpse doesn't consume the 429
                last = e            # budget — walk to any live sibling
                breakers[i].record_failure()
                continue
            except BaseException:
                self._dec(i)     # validation errors etc. must not leak
                raise            # an outstanding count into the router
            if matched:
                self._count_routing(i, matched)
            if route_sid is not None:
                wait, pending, _ = next(s for s in scored if s[-1] == i)
                tracer.record_span(
                    "route", "gateway", t_route, time.monotonic(),
                    trace=kwargs.get("trace_id"), parent=parent,
                    tid="router", span=route_sid,
                    args={"replica": i, "projected_wait_ms": round(wait, 3),
                          "prefix_tokens": (matched.get(i, 0)
                                            if matched else 0),
                          "spills": overloads})
            breakers[i].begin_probe()
            with self._lock:
                self._where[fut] = i
            fut.add_done_callback(self._on_done)
            return fut
        raise last

    def _count_routing(self, i: int, matched: dict[int, int]) -> None:
        """Feed the routing counters on the replica that took the request:
        a cache hit when it held any prefix of the prompt, a wait override
        when the longest holder's queue priced it out of its own prefix
        and the request prefilled cold (or colder) elsewhere."""
        best = max(matched.values())
        try:
            m = self.replicas[i].metrics
            if matched.get(i, 0) > 0:
                m.count("routed_cache_hit")
            if matched.get(i, 0) < best:
                m.count("routed_wait_override")
        except Exception:
            pass        # fakes without metrics still route

    # -- disaggregated prefill/decode ----------------------------------------
    @staticmethod
    def _role(eng) -> str:
        """The replica's serving role (duck-typed; plain fakes and older
        engines are full-service ``both``)."""
        try:
            return str(getattr(eng, "role", "both") or "both")
        except Exception:
            return "both"

    def _prefill_only(self) -> tuple:
        """Slots holding pure prefill workers — excluded from
        decode-bearing submissions whenever a decode-capable sibling
        exists (a ``role="prefill"`` engine finishes every generate at
        its first emitted token, which would truncate a multi-step
        request routed there). With no decode-capable sibling nothing is
        excluded: a degenerate all-prefill fleet still answers."""
        pre, dec = [], False
        for i, eng in enumerate(self.replicas):
            if self._role(eng) == "prefill":
                pre.append(i)
            else:
                dec = True
        return tuple(pre) if (pre and dec) else ()

    def _decode_score(self, i: int, outstanding: int, replicas=None):
        """Decode-placement key: projected wait first, then block-pool
        headroom (``free_block_frac`` from ``load()``) — between equally
        idle decode replicas the request lands where the KV pool has the
        most room, so imported blocks don't reclaim someone else's warm
        prefix."""
        eng = (replicas if replicas is not None else self.replicas)[i]
        wait, free = float(outstanding), 1.0
        if hasattr(eng, "load"):
            try:
                ld = eng.load()
                wait = (float(ld["depth"] + ld["busy"])
                        * float(ld.get("service_ms") or 0.0))
                free = float(ld.get("free_block_frac", 1.0))
            except Exception:
                pass
        return (wait, -free, i)

    def _try_handoff(self, args, kwargs, matched, hexes):
        """Disaggregated submit: prefill on P, migrate the prompt's KV
        blocks, decode on D. Returns the decode replica's future, or
        ``None`` to fall back to colocated routing — no viable pair, P
        and D collapse to the same replica, or ANY migration step failed
        (the fallback is the zero-client-visible-failure guarantee the
        chaos drill pins). Runs synchronously on the submitting thread:
        the handoff IS the request's prefill phase, so its latency is
        TTFT, not hidden queueing."""
        prompt, num_steps = args[0], args[1]
        try:
            if int(num_steps) <= 1:
                return None     # a 1-step request is pure prefill —
            #                     nothing to disaggregate
        except Exception:
            return None
        try:
            with self._lock:
                replicas = self.replicas    # one consistent membership view
                breakers = self.breakers
                outs = list(self._outstanding)
            avail = [i for i in range(len(replicas))
                     if breakers[i].available()]
            pcap = [i for i in avail
                    if self._role(replicas[i]) in ("prefill", "both")]
            dcap = [i for i in avail
                    if self._role(replicas[i]) != "prefill"]
            if not pcap or not dcap:
                return None
            # TTFT-aware split: P chases the warm prefix (prefix credit
            # against projected wait, the _score discipline), D weighs
            # projected wait + pool headroom.
            pi = min(self._score(i, outs[i],
                                 matched.get(i, 0) if matched else 0,
                                 replicas=replicas)
                     for i in pcap)[-1]
            di = min(self._decode_score(i, outs[i], replicas=replicas)
                     for i in dcap)[-1]
            if pi == di:
                return None     # one replica wins both phases: colocated
            p_eng, d_eng = replicas[pi], replicas[di]
            if (not hasattr(p_eng, "kv_export")
                    or not hasattr(d_eng, "kv_import")):
                return None
            t0 = time.monotonic()
            # Phase 1 — prefill on P: a synthetic one-step GREEDY request
            # (the sampled token is discarded; KV is sampling-independent)
            # that finishes through the normal release path, leaving the
            # prompt's blocks registered in P's prefix cache.
            p_eng.submit_generate(prompt, 1,
                                  temperature=0.0).result(timeout=60.0)
            # Phase 2 — migrate. The prefix index doubles as the transfer
            # directory: blocks D already holds warm are named in
            # skip_hashes and never cross the wire.
            bs = self.prefix_index.block_size
            skip = (hexes[:matched.get(di, 0) // bs]
                    if (matched and hexes and bs) else ())
            wire = p_eng.kv_export(prompt, skip_hashes=skip)
            if wire is not None:
                d_eng.kv_import(wire)
            # Phase 3 — the full request on D, with the router's normal
            # accounting; D's admission prefix-hits the imported blocks
            # and re-derives the first token bit-identically.
            with self._lock:
                if di < len(self._outstanding):
                    self._outstanding[di] += 1
            try:
                fut = d_eng.submit_generate(*args, **kwargs)
            except BaseException:
                self._dec(di)
                raise
            self.fleet_metrics.count("handoffs")
            self.fleet_metrics.count(
                "handoff_ms", int((time.monotonic() - t0) * 1e3))
            if matched:
                self._count_routing(di, matched)
            if self.tracer is not None:
                self.tracer.instant(
                    "handoff", "gateway",
                    trace=kwargs.get("trace_id"), tid="router",
                    args={"prefill": pi, "decode": di,
                          "skip_blocks": len(skip),
                          "ms": round((time.monotonic() - t0) * 1e3, 3)})
            breakers[di].begin_probe()
            with self._lock:
                self._where[fut] = di
            fut.add_done_callback(self._on_done)
            return fut
        except Exception:
            return None     # ANY handoff failure → colocated fallback

    # -- failover (the dead replica's on_failure hook) -----------------------
    def _on_replica_failure(self, i: int, failure: ReplicaFailed,
                            salvage) -> None:
        """Runs on the dying engine's (or the supervisor's) thread: open
        the circuit immediately, then re-home every salvaged queued request
        — original futures intact — or complete it with a structured
        refusal. Nothing may leave here unresolved."""
        self.breakers[i].trip()
        with self._lock:
            self.replica_failures += 1
        for kind, req in salvage:
            try:
                self._failover(i, kind, req, failure)
            except Exception:
                self._complete(req, ReplicaFailed(
                    failure.kind, replica=i, phase="queued",
                    forensics=failure.forensics))
        self.failure_event.set()

    def _failover(self, src: int, kind: str, req,
                  failure: ReplicaFailed) -> None:
        now = time.monotonic()
        deadline = getattr(req, "deadline", None)
        if deadline is not None and now > deadline:
            waited = (now - req.times.submitted) * 1e3
            self._complete(req, DeadlineExceeded(
                kind, waited, (deadline - req.times.submitted) * 1e3))
            return
        exclude = (src,) + (self._prefill_only()
                            if kind == "generate" else ())
        with self._lock:
            replicas = self.replicas        # consistent membership view
        for j in self._order(exclude=exclude):
            if j >= len(replicas):
                continue        # slot retired between score and adopt
            eng = replicas[j]
            if not hasattr(eng, "adopt"):
                continue
            if deadline is not None and hasattr(eng, "load"):
                ld = eng.load()
                est_s = ((ld["depth"] + ld["busy"])
                         * (ld.get("service_ms") or 0.0)) / 1e3
                if now + est_s > deadline:
                    continue    # deadline-aware: don't queue where the
                #                 wait already busts the SLO
            try:
                eng.adopt(kind, req)
            except (Overloaded, ReplicaFailed, ValueError):
                continue
            with self._lock:
                fut = req.future
                prev = self._where.get(fut)
                if prev is not None:    # move the outstanding count with it
                    if prev < len(self._outstanding):
                        self._outstanding[prev] -= 1
                    if j < len(self._outstanding):
                        self._outstanding[j] += 1
                    self._where[fut] = j
                self.failed_over += 1
            if self.tracer is not None:
                self.tracer.instant(
                    "failover", "gateway",
                    trace=getattr(req, "trace_id", None), tid="router",
                    args={"from": src, "to": j, "kind": kind})
            return
        self._complete(req, Unavailable(
            "no sibling could adopt the request before its deadline",
            retry_after_ms=self._min_retry_ms()))

    @staticmethod
    def _complete(req, exc: Exception) -> None:
        if not req.future.done():
            try:
                req.future.set_exception(exc)
            except Exception:
                pass

    # -- submission (engine surface) ----------------------------------------
    def submit_generate(self, prompt, num_steps: int, **kw):
        return self._submit("submit_generate", (prompt, num_steps), kw,
                            prompt=prompt)

    def submit_predict(self, item, **kw):
        return self._submit("submit_predict", (item,), kw)

    def submit_batch_item(self, prompt, num_steps: int, **kw):
        """Batch-lane generate item, routed like any other submission —
        per-item routing is what makes a bulk job job-aware at the fleet
        level: outstanding counts, breakers, and the sideways-429 spill
        all apply per item, and a dead replica's items fail fast for the
        job pump to resubmit."""
        return self._submit("submit_batch_item", (prompt, num_steps), kw)

    def submit_batch_predict(self, item, **kw):
        return self._submit("submit_batch_predict", (item,), kw)

    def submit_batch_items(self, items, indices, kind: str = "generate",
                           num_steps: int | None = None,
                           temperature: float = 0.0,
                           seed: int | None = None,
                           timeout_s: float = 0.0) -> list:
        """Route a GROUP of batch-lane items to ONE replica — the pump's
        per-replica batching: a process replica takes the whole group in a
        single HTTP exchange (``submit_batch_items`` on the engine), an
        in-thread engine takes a per-item loop. Returns one future per
        item, every one registered with this set's accounting + breaker
        feed. Items a mid-group refusal strands come back as pre-failed
        futures carrying the refusal (the pump requeues them); the
        group-level spill budget matches ``_submit``'s."""
        indices = list(indices)
        order = self._order(exclude=(self._prefill_only()
                                     if kind == "generate" else ()))
        if not order:
            raise Unavailable("all replica circuits open",
                              retry_after_ms=self._min_retry_ms())
        with self._lock:
            replicas = self.replicas        # consistent membership view
            breakers = self.breakers
        last: Exception | None = None
        overloads = 0
        for i in order:
            if overloads >= 2:
                break
            if i >= len(replicas):
                continue        # slot retired between score and submit
            eng = replicas[i]
            try:
                if hasattr(eng, "submit_batch_items"):
                    futs = eng.submit_batch_items(
                        items, indices, kind=kind, num_steps=num_steps,
                        temperature=temperature, seed=seed,
                        timeout_s=timeout_s)
                else:
                    futs = self._batch_item_loop(
                        eng, items, indices, kind, num_steps, temperature,
                        seed, timeout_s)
            except Overloaded as e:
                last = e
                overloads += 1
                if overloads < 2 and i != order[-1]:
                    with self._lock:
                        self.retried_429 += 1
                continue
            except ReplicaFailed as e:
                last = e
                breakers[i].record_failure()
                continue
            breakers[i].begin_probe()
            with self._lock:
                ok = i < len(self._outstanding)
                for fut in futs:
                    if ok and not fut.done():   # pre-failed stragglers stay
                        self._outstanding[i] += 1   # out of the breaker
                        self._where[fut] = i        # feed — the replica
            #                                         never saw them
            for fut in futs:
                if not fut.done():
                    fut.add_done_callback(self._on_done)
            return futs
        raise last

    def _batch_item_loop(self, eng, items, indices, kind, num_steps,
                         temperature, seed, timeout_s) -> list:
        """Per-item submission of a group against ONE in-thread engine.
        The first item's refusal propagates (the group spills sideways);
        a refusal mid-group pre-fails the REMAINING items' futures locally
        so the landed prefix keeps its engine slots."""
        base = None
        if kind == "generate" and temperature > 0.0 and seed is not None:
            import jax

            base = jax.random.PRNGKey(seed)
        futs: list = []
        pending_exc: Exception | None = None
        for pos, (item, idx) in enumerate(zip(items, indices)):
            if pending_exc is None:
                try:
                    if kind == "generate":
                        import jax

                        rng = (jax.random.fold_in(base, idx)
                               if base is not None else None)
                        fut = eng.submit_batch_item(
                            item, num_steps, temperature=temperature,
                            rng=rng, timeout_s=timeout_s)
                    else:
                        fut = eng.submit_batch_predict(
                            item, timeout_s=timeout_s)
                    futs.append(fut)
                    continue
                except (Overloaded, ReplicaFailed) as e:
                    if pos == 0:
                        raise
                    pending_exc = e
            fut = concurrent.futures.Future()
            fut.set_running_or_notify_cancel()
            fut.set_exception(pending_exc)
            futs.append(fut)
        return futs

    def submit_batch(self, items, kind: str = "generate", **kw):
        """Start a host-side :class:`~ddw_tpu.serve.lanes.BatchJob` whose
        items route across this set (see :func:`~ddw_tpu.serve.lanes.
        start_batch_job` for the knobs)."""
        from ddw_tpu.serve.lanes import start_batch_job
        return start_batch_job(self, items, kind=kind, **kw)

    def generate(self, prompt, num_steps: int, **kw):
        return self.submit_generate(prompt, num_steps, **kw).result()

    def predict(self, items, timeout_s: float | None = None):
        futs = [self.submit_predict(x, timeout_s=timeout_s) for x in items]
        return [f.result() for f in futs]

    # -- fleet metrics -------------------------------------------------------
    def merged_metrics(self):
        return merge_metrics([eng.metrics for eng in self.replicas]
                             + [self.fleet_metrics])

    def snapshot(self) -> dict[str, float]:
        """Fleet SLO view: the merged engine snapshot plus the routing
        layer's own numbers (replica count, sideways retries, outstanding /
        circuit state / restart count per replica)."""
        out = self.merged_metrics().snapshot()
        with self._lock:
            outstanding = list(self._outstanding)
            restarts = list(self.restarts)
            breakers = self.breakers
            out["gateway.retried_429"] = float(self.retried_429)
            out["gateway.replica_failures"] = float(self.replica_failures)
            out["gateway.failed_over"] = float(self.failed_over)
        out["gateway.replicas"] = float(len(outstanding))
        for i, n in enumerate(outstanding):
            out[f"gateway.outstanding_r{i}"] = float(n)
            out[f"gateway.circuit_r{i}"] = _CIRCUIT_CODE[breakers[i].state]
            out[f"gateway.restarts_r{i}"] = float(restarts[i])
        return out

    def prometheus(self) -> str:
        with self._lock:
            replicas = self.replicas
            breakers = self.breakers
            gauges = {f'ddw_gateway_outstanding{{replica="{i}"}}': float(n)
                      for i, n in enumerate(self._outstanding)}
            gauges["ddw_gateway_retried_429"] = float(self.retried_429)
            gauges["ddw_gateway_replica_failures"] = float(
                self.replica_failures)
            for i, n in enumerate(self.restarts):
                gauges[f'ddw_gateway_restarts{{replica="{i}"}}'] = float(n)
        for i, b in enumerate(breakers):
            gauges[f'ddw_gateway_circuit_state{{replica="{i}"}}'] = \
                _CIRCUIT_CODE[b.state]
        gauges["ddw_gateway_replicas"] = float(len(replicas))
        return render_prometheus([eng.metrics for eng in replicas]
                                 + [self.fleet_metrics],
                                 extra_gauges=gauges)
