"""ReplicaSet — one front door over N serving-engine replicas.

A single :class:`~ddw_tpu.serve.ServingEngine` is bounded by its slot pool:
``n_slots`` sequences decode per dispatch and everyone else queues. The
fleet answer is horizontal — more engine replicas, each with its own
compiled programs and KV pool — and this class is the piece that makes N
replicas look like one engine to the transport layer above it:

- **routing** is least-outstanding-requests: every submission goes to the
  replica with the fewest requests in flight *through this set* (queued or
  decoding), ties broken by replica index. Outstanding counts are kept
  here, incremented at submit and decremented by a future done-callback,
  so routing needs no cross-thread peeking into engine internals;
- **backpressure spills sideways once**: a submission refused with
  :class:`~ddw_tpu.serve.Overloaded` by the least-loaded replica is
  retried on the next-least-loaded sibling before the refusal surfaces —
  one replica's full queue must not turn away traffic a sibling has room
  for. A second refusal propagates to the caller (the gateway maps it to
  429): when the whole fleet is full, the honest answer is still no;
- **metrics aggregate** (:func:`ddw_tpu.serve.metrics.merge_metrics`):
  ``snapshot()`` and ``prometheus()`` reduce over every replica's records,
  so the SLO view and the ``/metrics`` scrape are fleet totals, with
  per-replica outstanding gauges alongside.

The submission surface mirrors the engine (``submit_generate`` /
``submit_predict`` / ``warmup`` / ``start`` / ``stop`` / context manager),
so anything written against one engine — the HTTP gateway, the load
generator, the tests — serves a fleet by swapping the object.
"""

from __future__ import annotations

import threading

from ddw_tpu.serve.admission import Overloaded
from ddw_tpu.serve.metrics import merge_metrics, render_prometheus

__all__ = ["ReplicaSet"]


class ReplicaSet:
    """Least-outstanding-requests router over engine replicas."""

    def __init__(self, replicas):
        if hasattr(replicas, "submit_generate"):   # a bare engine
            replicas = [replicas]
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("ReplicaSet needs at least one engine replica")
        self._outstanding = [0] * len(self.replicas)
        self._lock = threading.Lock()
        self.retried_429 = 0    # refusals absorbed by a sibling retry

    # -- lifecycle (fan-out) ------------------------------------------------
    def start(self) -> "ReplicaSet":
        for eng in self.replicas:
            eng.start()
        return self

    def stop(self) -> None:
        for eng in self.replicas:
            eng.stop()

    def warmup(self, prompt_lens=(8,)) -> None:
        for eng in self.replicas:
            eng.warmup(prompt_lens)

    def __enter__(self) -> "ReplicaSet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- routing ------------------------------------------------------------
    def outstanding(self) -> list[int]:
        with self._lock:
            return list(self._outstanding)

    def _route(self) -> list[int]:
        """Replica indices to try, in order: least outstanding first, then
        ONE sibling (the 429-retry budget)."""
        with self._lock:
            order = sorted(range(len(self.replicas)),
                           key=lambda i: (self._outstanding[i], i))
        return order[:2]

    def _dec(self, i: int) -> None:
        with self._lock:
            self._outstanding[i] -= 1

    def _submit(self, method: str, args, kwargs):
        route, last = self._route(), None
        for attempt, i in enumerate(route):
            with self._lock:
                self._outstanding[i] += 1
            try:
                fut = getattr(self.replicas[i], method)(*args, **kwargs)
            except Overloaded as e:
                self._dec(i)
                last = e
                if attempt + 1 < len(route):
                    with self._lock:
                        self.retried_429 += 1
                    continue
                raise
            except BaseException:
                self._dec(i)     # validation errors etc. must not leak
                raise            # an outstanding count into the router
            fut.add_done_callback(lambda _f, i=i: self._dec(i))
            return fut
        raise last  # single-replica set: the one refusal surfaces

    # -- submission (engine surface) ----------------------------------------
    def submit_generate(self, prompt, num_steps: int, **kw):
        return self._submit("submit_generate", (prompt, num_steps), kw)

    def submit_predict(self, item, **kw):
        return self._submit("submit_predict", (item,), kw)

    def generate(self, prompt, num_steps: int, **kw):
        return self.submit_generate(prompt, num_steps, **kw).result()

    def predict(self, items, timeout_s: float | None = None):
        futs = [self.submit_predict(x, timeout_s=timeout_s) for x in items]
        return [f.result() for f in futs]

    # -- fleet metrics -------------------------------------------------------
    def merged_metrics(self):
        return merge_metrics([eng.metrics for eng in self.replicas])

    def snapshot(self) -> dict[str, float]:
        """Fleet SLO view: the merged engine snapshot plus the routing
        layer's own numbers (replica count, sideways retries, outstanding
        per replica)."""
        out = self.merged_metrics().snapshot()
        with self._lock:
            outstanding = list(self._outstanding)
            out["gateway.retried_429"] = float(self.retried_429)
        out["gateway.replicas"] = float(len(self.replicas))
        for i, n in enumerate(outstanding):
            out[f"gateway.outstanding_r{i}"] = float(n)
        return out

    def prometheus(self) -> str:
        with self._lock:
            gauges = {f'ddw_gateway_outstanding{{replica="{i}"}}': float(n)
                      for i, n in enumerate(self._outstanding)}
            gauges["ddw_gateway_retried_429"] = float(self.retried_429)
        gauges["ddw_gateway_replicas"] = float(len(self.replicas))
        return render_prometheus([eng.metrics for eng in self.replicas],
                                 extra_gauges=gauges)
