"""Fleet-wide prefix-cache index — who holds which prompt prefix warm.

PR 7's chain-hashed prefix cache is per-engine: each
:class:`~ddw_tpu.serve.blocks.BlockPool` knows which prompt blocks IT
holds, so an N-replica fleet re-prefills the same system prompt N times —
O(fleet) prefill work for what is one cached computation. This module is
the control-plane half of closing that gap: a content-hash index over
:class:`~ddw_tpu.gateway.ReplicaSet` members mapping the SAME per-block
chain hashes the pools compute (:func:`chain_hash_hexes` reproduces them
bit-for-bit) to the replica slots holding them warm.

The index is fed by the pools' register/evict event logs
(:meth:`~ddw_tpu.serve.blocks.BlockPool.prefix_events`), pulled through a
duck-typed ``prefix_events(since)`` on each replica — a direct method call
for in-thread engines, one HTTP delta fetch (``GET /v1/prefix/events``)
relayed by :class:`~ddw_tpu.deploy.ProcessReplica` for child processes.
Polling is rate-limited per replica and driven from the routing path
itself, so the index is freshest exactly when traffic is flowing. The
seq/reset protocol makes holder loss self-healing: a pool that restarted
(or compacted past the poller) answers with a full snapshot and ``reset``
set, and the index simply replaces everything it believed about that slot.

Two consumers:

- **cache-aware routing** (:meth:`~ddw_tpu.gateway.ReplicaSet._order`):
  :meth:`PrefixIndex.match` returns each replica's longest cached prefix
  for a prompt; the router credits the expected prefill savings (matched
  tokens x the replica's per-prefilled-token EWMA) against its projected
  wait, so requests chase their prefix only while the holder's queue
  stays cheaper than a cold prefill elsewhere;
- **warm replay** (:meth:`~ddw_tpu.gateway.ReplicaSupervisor.recycle`):
  the index retains the TOKEN prefixes behind its keys (even after the
  last holder died), so a recycled/deployed replica re-warms by replaying
  the top-K hot prefixes through its normal prefill path — bit-identical
  by construction, no KV shipping.
"""

from __future__ import annotations

import hashlib
import threading
import time

import numpy as np

__all__ = ["PrefixIndex", "chain_hash_hexes"]


def chain_hash_hexes(tokens, block_size: int, salt: bytes = b"") -> list[str]:
    """Per-full-block chain hashes of ``tokens``, hex-encoded — the exact
    keys :meth:`BlockPool._chain_hashes` computes (SHA1 over the previous
    digest + the block's int32 token bytes), so index lookups and pool
    registrations can never disagree about what a prefix is. ``salt``
    seeds the chain exactly as the pool's adapter salting does (the
    adapter digest bytes): salted and unsalted chains over the same
    tokens share no keys, so adapter-tagged lookups can only ever match
    blocks prefilled under the SAME adapter."""
    arr = np.asarray(tokens, np.int32).reshape(-1)
    out, h = [], bytes(salt)
    for j in range(len(arr) // block_size):
        h = hashlib.sha1(
            h + arr[j * block_size:(j + 1) * block_size].tobytes()).digest()
        out.append(h.hex())
    return out


class PrefixIndex:
    """Content-hash prefix index over a replica fleet.

    Thread-safe; all methods may be called from routing, supervisor, and
    HTTP threads concurrently. Replica identity is the ReplicaSet SLOT
    (list position) — stable across restarts and replacement, which is
    exactly the identity routing decisions need.
    """

    MAX_KEYS = 4096               # coldest keys drop past this bound

    def __init__(self, hot_k: int = 8, poll_interval_s: float = 0.2):
        self.hot_k = hot_k
        self.poll_interval_s = poll_interval_s
        self._lock = threading.Lock()
        self._holders: dict[str, set[int]] = {}    # key -> replica slots
        self._tokens: dict[str, list[int]] = {}    # key -> token prefix
        self._hits: dict[str, int] = {}            # routing-time matches
        self._recency: dict[str, int] = {}         # key -> last-touch tick
        self._touch = 0
        self._block_size = 0      # learned from the feed: the shortest
        #                           registered prefix IS one block
        self._seq: dict[int, int] = {}             # slot -> last feed seq
        self._last_poll: dict[int, float] = {}

    # -- feed -----------------------------------------------------------------
    def poll(self, replicas) -> None:
        """Pull each replica's register/evict delta feed (duck-typed
        ``prefix_events(since)``; replicas without one stay invisible).
        Rate-limited per slot so the routing path can call this on every
        request — process replicas answer over HTTP."""
        now = time.monotonic()
        for slot, eng in enumerate(list(replicas)):
            fetch = getattr(eng, "prefix_events", None)
            if fetch is None:
                continue
            with self._lock:
                if now - self._last_poll.get(slot, -1e9) \
                        < self.poll_interval_s:
                    continue
                self._last_poll[slot] = now
                since = self._seq.get(slot, 0)
            try:
                feed = fetch(since)
            except Exception:
                continue            # unreachable replica: stale is fine
            if feed:
                self.observe(slot, feed)

    def observe(self, slot: int, feed: dict) -> None:
        """Apply one replica's feed (``{"seq", "reset", "events"}``).
        ``reset`` drops everything believed about the slot first — the
        pool restarted under the poller, or compacted past it."""
        with self._lock:
            if feed.get("reset"):
                for holders in self._holders.values():
                    holders.discard(slot)
            for ev in feed.get("events", ()):
                kind, key = ev[0], ev[1]
                toks = ev[2] if len(ev) > 2 else None
                if kind == "register":
                    self._holders.setdefault(key, set()).add(slot)
                    if toks:
                        self._tokens[key] = [int(t) for t in toks]
                        if (not self._block_size
                                or len(toks) < self._block_size):
                            self._block_size = len(toks)
                    self._hits.setdefault(key, 0)
                    self._touch += 1
                    self._recency[key] = self._touch
                elif kind == "evict":
                    holders = self._holders.get(key)
                    if holders is not None:
                        holders.discard(slot)
                    # tokens/hits stay: a key every holder evicted is
                    # precisely what warm replay exists to restore
            self._seq[slot] = int(feed.get("seq", self._seq.get(slot, 0)))
            self._compact_locked()

    def drop_replica(self, slot: int) -> None:
        """Forget a slot's holdings (replica replaced/abandoned). Token
        prefixes are retained for warm replay."""
        with self._lock:
            for holders in self._holders.values():
                holders.discard(slot)
            self._seq.pop(slot, None)
            self._last_poll.pop(slot, None)

    def _compact_locked(self) -> None:
        over = len(self._tokens) - self.MAX_KEYS
        if over <= 0:
            return
        coldest = sorted(self._tokens,
                         key=lambda h: (self._hits.get(h, 0),
                                        self._recency.get(h, 0)))[:over]
        for key in coldest:
            self._tokens.pop(key, None)
            self._holders.pop(key, None)
            self._hits.pop(key, None)
            self._recency.pop(key, None)

    # -- consumers ------------------------------------------------------------
    def match(self, prompt, count_hit: bool = True,
              with_hashes: bool = False, salt: bytes = b""):
        """Longest cached prefix (tokens) of ``prompt`` per replica slot —
        empty until the feed has taught the index its block size. Matches
        are capped at ``len(prompt) - 1`` (the pool always prefills at
        least one real token, so savings can never exceed that). With
        ``count_hit`` the longest matched key is credited for the hot
        list.

        ``with_hashes`` returns ``(matches, hexes)`` instead, where
        ``hexes`` is the prompt's full-block chain-hash list (hex, block
        order) this match walked — the migration plane's transfer
        directory reads it to name warm blocks a receiver can skip, so
        router and directory hash each prompt ONCE per route instead of
        twice. ``hexes`` is ``[]`` when matching was impossible (no feed
        yet / prompt too short)."""
        with self._lock:
            bs = self._block_size
            have = bool(self._holders)
        p = int(np.asarray(prompt).reshape(-1).shape[0])
        if not bs or not have or p < 2:
            return ({}, []) if with_hashes else {}
        hexes = chain_hash_hexes(prompt, bs, salt)
        out: dict[int, int] = {}
        with self._lock:
            best = None
            for j in range(len(hexes), 0, -1):
                holders = self._holders.get(hexes[j - 1])
                if not holders:
                    continue
                if best is None:
                    best = hexes[j - 1]
                for slot in holders:
                    if slot not in out:
                        out[slot] = min(j * bs, p - 1)
            if best is not None and count_hit:
                self._hits[best] = self._hits.get(best, 0) + 1
                self._touch += 1
                self._recency[best] = self._touch
        return (out, hexes) if with_hashes else out

    @property
    def block_size(self) -> int:
        """The fleet's KV block size as learned from the feed (0 until
        the first registration arrives) — the unit ``match`` hashes in
        and the migration router converts token credits to block counts
        with."""
        with self._lock:
            return self._block_size

    def hot(self, k: int | None = None) -> list[list[int]]:
        """The top-K hottest prefixes as TOKEN lists, hottest first, each
        chain reduced to its longest retained prefix (replaying the long
        one re-registers every block under it). This is what a recycled
        replica replays through its normal prefill path to rejoin warm."""
        n = k if k is not None else self.hot_k
        with self._lock:
            cands = sorted(
                self._tokens.items(),
                key=lambda kv: (self._hits.get(kv[0], 0),
                                self._recency.get(kv[0], 0), len(kv[1])),
                reverse=True)
        chosen: list[list[int]] = []
        for _, toks in cands:
            if len(chosen) >= n:
                break
            if any(sel[:len(toks)] == toks for sel in chosen):
                continue        # covered by a hotter, longer prefix
            chosen.append(list(toks))
        return chosen

    def summary(self) -> dict:
        """The ``/stats`` view: key count, per-slot holdings, hot list."""
        with self._lock:
            per: dict[int, int] = {}
            for holders in self._holders.values():
                for slot in holders:
                    per[slot] = per.get(slot, 0) + 1
            hot = sorted(self._tokens,
                         key=lambda h: (self._hits.get(h, 0),
                                        self._recency.get(h, 0)),
                         reverse=True)[:self.hot_k]
            return {
                "keys": len(self._tokens),
                "block_size": self._block_size,
                "holders": {str(s): n for s, n in sorted(per.items())},
                "hot": [{"key": h[:12],
                         "tokens": len(self._tokens[h]),
                         "hits": self._hits.get(h, 0),
                         "holders": sorted(self._holders.get(h, ()))}
                        for h in hot],
            }
