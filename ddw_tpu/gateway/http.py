"""HTTP front door for the serving engine — stdlib, JSON, streaming.

PR 3's engine ends at a Python futures API in the caller's process; the
ROADMAP's open serving item names what's missing: "a thin HTTP transport in
front of the in-process engine". This module is that transport — a
``ThreadingHTTPServer`` (one thread per connection, stdlib only: the
container rule is no new dependencies) whose handlers translate between
HTTP and the engine's structured types. No serving policy lives here:
admission, batching, deadlines, and metrics stay in :mod:`ddw_tpu.serve`;
routing and fleet aggregation in :class:`~ddw_tpu.gateway.ReplicaSet`;
readiness/drain in :class:`~ddw_tpu.gateway.ServerLifecycle`. The gateway
only maps.

API (JSON request/response; errors are the engine's own ``to_dict()``
forms, never free-text parsing):

====================  ======================================================
``POST /v1/generate`` ``{"prompt": [ints], "num_steps": N, "temperature":
                      t?, "seed": s?, "timeout_s": d?, "stream": false?}``
                      → ``{"tokens": [...], queue_ms, ttft_ms, total_ms,
                      tokens_per_sec}``. With ``"stream": true`` the reply
                      is chunked NDJSON: one ``{"index": i, "token": t}``
                      line per token the moment its decode tick fetches
                      (the engine's ``on_token`` hook), then a final
                      ``{"done": true, ...}`` line with the SLO numbers.
``POST /v1/predict``  ``{"image": [[[floats]]], "timeout_s": d?,
                      "return_logits": false?}`` → ``{label, index,
                      queue_ms, total_ms}``
``POST /v1/batch``    ``{"kind": "generate"|"predict", "items": [...],
                      "num_steps": N?, "temperature": t?, "seed": s?,
                      "window": w?}`` → ``{"job_id", "kind", "total"}``.
                      Submits a batch-LANE job: items backfill idle
                      capacity behind the interactive reserve and are
                      preempted first under interactive pressure (see
                      docs/serving.md). The job is tracked host-side in
                      the gateway's :class:`~ddw_tpu.serve.lanes.
                      JobLedger` — it survives replica restarts.
``GET /v1/batch/<id>``            poll: the job's ``progress()`` dict.
``GET /v1/batch/<id>/results``    completed rows, NDJSON, index order.
``DELETE /v1/batch/<id>``         cancel (completed rows are kept).
``GET /healthz``      process liveness — 200 from listener-up onward.
``GET /readyz``       load-balancer readiness — 200 only between warmup
                      completion and drain start, else 503.
``GET /metrics``      Prometheus text exposition, merged across replicas.
``GET /stats``        the fleet SLO snapshot as JSON (includes the fleet
                      ``prefix_index`` summary: keys, holders, hot list).
``GET /v1/prefix/events``  one replica's prefix-cache register/evict delta
                      feed (``?since=N&replica=R``) — the relay a parent
                      gateway's fleet index polls to follow a process
                      replica's child pool.
====================  ======================================================

Status-code mapping (docs/serving.md has the full table): ``Overloaded`` →
**429** with a ``Retry-After`` header and the structured body (capacity,
depth, ``retry_after_ms``); ``DeadlineExceeded`` → **504**; validation
errors → **400**; ``ReplicaFailed``/``Unavailable`` (the replica died, or
every circuit is open) → **503** + ``Retry-After`` (retryable: a sibling
or the supervisor's restart may serve it); not-ready or draining → **503**
+ ``Retry-After``; anything else → **500**. A rejection that happens after
streaming began arrives as a final NDJSON ``{"error": ...}`` line instead
(the status line already went out — HTTP has no second chance).

Transport hardening: connections are **HTTP/1.1 keep-alive** (the client
reuses them — a chaos drill's reconnect storm must not re-handshake per
request), bounded by ``max_connections``: past the cap the server answers
a minimal 503 + ``Retry-After`` and closes, instead of letting unbounded
accept threads pile up — the connection analog of the engine's bounded
admission queues.
"""

from __future__ import annotations

import copy
import json
import math
import os
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ddw_tpu.gateway.lifecycle import ServerLifecycle
from ddw_tpu.gateway.replica import ReplicaSet
from ddw_tpu.gateway.supervisor import ReplicaSupervisor
from ddw_tpu.obs.slo import SLOMonitor
from ddw_tpu.obs.telemetry import FleetTelemetry, TelemetryHub
from ddw_tpu.obs.trace import Tracer, gen_id
from ddw_tpu.serve.admission import (DeadlineExceeded, Overloaded, Rejected,
                                     ReplicaFailed, Unavailable)
from ddw_tpu.serve.adapters import UnknownAdapter
from ddw_tpu.serve.lanes import JobLedger
from ddw_tpu.serve.tenancy import QuotaExceeded

__all__ = ["Gateway"]


class _GatewayHTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    allow_reuse_address = True
    # the stdlib default accept backlog (5) drops/retries SYNs under a
    # connection burst — the engine's admission control is the bounded
    # queue here, not the kernel's
    request_queue_size = 128
    # keep-alive makes connections long-lived, so bound how many may be
    # open at once; past the cap we answer a fast 503 (a structured refusal
    # the client's backoff understands) rather than piling up threads
    max_connections = 256

    def __init__(self, addr, gateway: "Gateway"):
        self.gateway = gateway
        self._conn_lock = threading.Lock()
        self.active_connections = 0
        super().__init__(addr, _Handler)

    def process_request_thread(self, request, client_address):
        with self._conn_lock:
            over = self.active_connections >= self.max_connections
            if not over:
                self.active_connections += 1
        if over:
            body = b'{"error":"unavailable","reason":"connections"}\n'
            try:
                request.sendall(
                    b"HTTP/1.1 503 Service Unavailable\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                    b"Retry-After: 1\r\nConnection: close\r\n\r\n" + body)
            except OSError:
                pass
            self.shutdown_request(request)
            return
        try:
            super().process_request_thread(request, client_address)
        finally:
            with self._conn_lock:
                self.active_connections -= 1


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"   # required for chunked streaming
    server_version = "ddw-gateway"

    def log_message(self, *args) -> None:
        pass                        # request logs are the engine's jsonl

    # -- plumbing ------------------------------------------------------------
    def _send_json(self, status: int, obj: dict,
                   extra_headers: dict | None = None) -> None:
        data = (json.dumps(obj) + "\n").encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def _send_rejected(self, e: Rejected) -> None:
        body = e.to_dict()
        if isinstance(e, QuotaExceeded):
            # per-tenant refusal: same 429 backoff contract as engine
            # overload, but the body names the tenant and the exhausted
            # resource so the caller (and the drill's offline recount)
            # can attribute the shed
            ms = body.get("retry_after_ms")
            secs = max(1, math.ceil(ms / 1e3)) if ms else 1
            self._send_json(429, body, {"Retry-After": str(secs)})
        elif isinstance(e, Overloaded):
            ms = body.get("retry_after_ms")
            # delay-seconds is an integer per RFC 9110; the exact ms hint
            # rides in the body for clients that can honor it precisely
            secs = max(1, math.ceil(ms / 1e3)) if ms else 1
            self._send_json(429, body, {"Retry-After": str(secs)})
        elif isinstance(e, DeadlineExceeded):
            self._send_json(504, body)
        elif isinstance(e, (ReplicaFailed, Unavailable)):
            # the replica died under it / every circuit is open: retryable —
            # a sibling or the supervisor's restart may serve the retry
            ms = getattr(e, "retry_after_ms", None)
            secs = max(1, math.ceil(ms / 1e3)) if ms else 1
            self._send_json(503, body, {"Retry-After": str(secs)})
        else:
            self._send_json(500, body)

    def _read_body(self) -> dict | None:
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            return body
        except (ValueError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": f"malformed JSON body: {e}"})
            return None

    # chunked writing (Transfer-Encoding: chunked framing by hand —
    # BaseHTTPRequestHandler gives us the socket, not the framing)
    def _start_stream(self) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()

    def _write_chunk(self, obj: dict) -> None:
        data = (json.dumps(obj) + "\n").encode()
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _end_stream(self) -> None:
        self.wfile.write(b"0\r\n\r\n")

    # -- GET: health / metrics ----------------------------------------------
    def do_GET(self) -> None:  # noqa: N802 (stdlib handler contract)
        gw = self.server.gateway
        try:
            if self.path == "/healthz":
                self._send_json(200, {"status": "alive",
                                      "state": gw.lifecycle.state})
            elif self.path == "/readyz":
                ready, body = gw.lifecycle.readiness()
                try:
                    body["lanes"] = gw.lane_stats()
                except Exception:
                    pass     # readiness must answer even if a replica's
                #              health probe is mid-death
                dep = gw.deploy_view()
                body["deploying"] = dep["deploying"]
                body["fleet_generation"] = dep["fleet_generation"]
                # the abort asymmetry made visible: True whenever live
                # replica digests disagree (half-rolled fleet, kept-new
                # winners after an abort) — the same signal the startup
                # reconciler keys on
                live = {c for c in dep.get("checkpoints", ()) if c}
                body["mixed_checkpoints"] = len(live) > 1
                # SLO degradation detail: a burning objective flips the
                # "degraded" flag and names itself, but the gateway stays
                # ready (200) — load balancers weight it down, they don't
                # eject it; only replica loss flips readiness itself
                if gw.slo_monitor is not None:
                    deg = gw.slo_monitor.degraded()
                    if deg:
                        body["degraded"] = True
                        body["slo_degraded"] = deg
                a = gw.autoscale_view()
                if a is not None:
                    last = a["last_decision"] or {}
                    body["autoscale"] = {
                        "enabled": a["enabled"], "desired": a["desired"],
                        "actual": a["actual"],
                        "last_action": last.get("action"),
                        "last_reason": last.get("reason"),
                        "cooldown_remaining_s": a["cooldown_remaining_s"]}
                if ready:
                    self._send_json(200, body)
                else:
                    self._send_json(503, body, {"Retry-After": "1"})
            elif self.path == "/metrics":
                text = (gw.replica_set.prometheus()
                        + gw.slo_prometheus()).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(text)))
                self.end_headers()
                self.wfile.write(text)
            elif self.path == "/stats":
                out = {"state": gw.lifecycle.state,
                       "inflight": gw.lifecycle.inflight,
                       "connections": (gw._httpd.active_connections
                                       if gw._httpd else 0),
                       **gw.replica_set.snapshot(),
                       "replica_health": gw.replica_set.fleet_health(),
                       "lanes": gw.lane_stats(),
                       "deploy": gw.deploy_view()}
                try:
                    out["prefix_index"] = \
                        gw.replica_set.prefix_index.summary()
                except Exception:
                    pass     # plain engine sets without an index still
                #              answer /stats
                try:
                    adp = gw.adapters_view()
                    if adp["registry"] or adp["replicas"] or adp["ops"]:
                        out["adapters"] = adp
                except Exception:
                    pass     # fakes without adapter pools still answer
                if gw.supervisor is not None:
                    out["supervisor"] = gw.supervisor.report()
                a = gw.autoscale_view()
                if a is not None:
                    out["autoscale"] = a
                ts = gw.trace_summary()
                if ts is not None:
                    out["trace"] = ts
                tm = gw.telemetry_summary()
                if tm is not None:
                    out["telemetry"] = tm
                if gw.slo_monitor is not None:
                    out["slo"] = gw.slo_monitor.status()
                self._send_json(200, out)
            elif self.path.startswith("/v1/telemetry"):
                self._telemetry_get(gw)
            elif self.path.startswith("/v1/trace"):
                self._trace_get(gw)
            elif self.path.startswith("/v1/prefix/events"):
                self._prefix_events(gw)
            elif self.path.startswith("/v1/batch/"):
                self._batch_get(gw)
            else:
                self._send_json(404, {"error": "not_found",
                                      "path": self.path})
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True

    # -- POST: the data plane -------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802
        gw = self.server.gateway
        if self.path == "/admin/deploy":
            self._admin_deploy(gw)
            return
        if self.path == "/admin/autoscale":
            self._admin_autoscale(gw)
            return
        if self.path == "/admin/adapters":
            self._admin_adapters(gw)
            return
        if self.path in ("/v1/kv/export", "/v1/kv/import"):
            # migration plane, not client data plane: ungated by the
            # lifecycle ledger (a draining gateway may still donate its
            # KV blocks), POST because prompts are token arrays far too
            # long for a query string
            self._kv_migrate(gw)
            return
        if self.path not in ("/v1/generate", "/v1/predict", "/v1/batch",
                             "/v1/batch/items"):
            self._send_json(404, {"error": "not_found", "path": self.path})
            return
        # admission into the lifecycle ledger FIRST: a draining or not-yet-
        # warm gateway refuses before reading a byte of payload semantics
        if not gw.lifecycle.try_begin_request():
            self._send_json(503, {"error": "unavailable",
                                  "state": gw.lifecycle.state},
                            {"Retry-After": "1"})
            return
        try:
            body = self._read_body()
            if body is None:
                return
            if self.path == "/v1/generate":
                self._generate(gw, body)
            elif self.path == "/v1/batch":
                self._batch_submit(gw, body)
            elif self.path == "/v1/batch/items":
                self._batch_items(gw, body)
            else:
                self._predict(gw, body)
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True   # client went away; slot finishes
        finally:
            gw.lifecycle.end_request()

    def _generate(self, gw: "Gateway", body: dict) -> None:
        # trace identity: honor an incoming x-ddw-trace-id (the caller —
        # a client or a parent gateway — owns the id), mint one only when
        # this gateway traces; the id rides the response either way so
        # jsonl forensics and traces stay joinable
        tracer = gw.tracer
        trace_id = self.headers.get("x-ddw-trace-id") or None
        hspan = None
        t_http = 0.0
        if tracer is not None:
            trace_id = trace_id or gen_id()
            hspan = tracer._next_span_id()
            t_http = time.monotonic()
        try:
            prompt = np.asarray(body["prompt"], np.int32)
            num_steps = int(body["num_steps"])
            timeout_s = body.get("timeout_s")
            kw = {"temperature": float(body.get("temperature", 0.0)),
                  "timeout_s": None if timeout_s is None
                  else float(timeout_s)}
            if body.get("tenant") is not None:
                kw["tenant"] = str(body["tenant"])
            if body.get("adapter_id") is not None:
                kw["adapter_id"] = str(body["adapter_id"])
            if trace_id is not None:
                kw["trace_id"] = trace_id
                if hspan is not None:
                    kw["parent_span"] = hspan
                else:
                    # relayed hop: a parent gateway's route span id rides
                    # x-ddw-parent-span so the child engine chain parents
                    # onto the fleet-level route decision
                    parent_hdr = self.headers.get("x-ddw-parent-span")
                    if parent_hdr:
                        kw["parent_span"] = parent_hdr
            if body.get("seed") is not None:
                import jax

                kw["rng"] = jax.random.PRNGKey(int(body["seed"]))
            elif body.get("key_data") is not None:
                # a raw PRNG key relayed by a parent-process proxy (the
                # ProcessReplica transport): the same uint32 words the
                # in-thread path would pass, so sampling stays bit-identical
                # across the process hop
                import jax.numpy as jnp

                kw["rng"] = jnp.asarray(body["key_data"], dtype=jnp.uint32)
        except (KeyError, TypeError, ValueError) as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": f"bad field: {e}"})
            return
        stream = bool(body.get("stream", False))
        toks_q: queue.SimpleQueue | None = None
        if stream:
            toks_q = queue.SimpleQueue()
            kw["on_token"] = lambda i, t: toks_q.put((i, t))
        def _finish_http(status: int) -> None:
            if hspan is not None:
                tracer.record_span(
                    "http", "gateway", t_http, time.monotonic(),
                    trace=trace_id, tid="http", span=hspan,
                    args={"path": "/v1/generate", "num_steps": num_steps,
                          "status": status, "stream": stream})

        try:
            fut = gw.replica_set.submit_generate(prompt, num_steps, **kw)
        except Rejected as e:       # Overloaded / Unavailable / Quota / dead
            self._send_rejected(e)
            _finish_http(0)
            return
        except UnknownAdapter as e:
            # structured 400: names the missing adapter and what IS
            # resident, so a client can distinguish a typo from a
            # not-yet-staged adapter
            self._send_json(400, {"error": "unknown_adapter",
                                  "adapter_id": e.adapter_id,
                                  "loaded": sorted(e.loaded)})
            _finish_http(400)
            return
        except ValueError as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": str(e)})
            _finish_http(400)
            return
        if not stream:
            try:
                res = fut.result()
            except Rejected as e:
                self._send_rejected(e)
                _finish_http(0)
                return
            except UnknownAdapter as e:
                # a process replica's refusal arrives via the future
                self._send_json(400, {"error": "unknown_adapter",
                                      "adapter_id": e.adapter_id,
                                      "loaded": sorted(e.loaded)})
                _finish_http(400)
                return
            except Exception as e:
                self._send_json(500, {"error": "internal",
                                      "message": repr(e)})
                _finish_http(500)
                return
            out = {
                "tokens": [int(t) for t in res.tokens],
                "queue_ms": res.queue_ms, "ttft_ms": res.ttft_ms,
                "total_ms": res.total_ms,
                "tokens_per_sec": res.tokens_per_sec}
            hdrs = None
            if trace_id is not None:
                out["trace_id"] = trace_id
                hdrs = {"x-ddw-trace-id": trace_id}
            self._send_json(200, out, hdrs)
            _finish_http(200)
            return
        self._stream_generate(fut, toks_q, trace_id=trace_id)
        _finish_http(200)

    def _stream_generate(self, fut, toks_q: queue.SimpleQueue,
                         trace_id: str | None = None) -> None:
        """Relay the engine's on_token stream as chunked NDJSON. Headers are
        deferred until the first token (or terminal error), so a request
        shed before any device work still gets its proper status code."""
        started = False

        def relay_available(block: bool) -> None:
            nonlocal started
            timeout = 0.05 if block else 0.0
            while True:
                try:
                    i, t = toks_q.get(timeout=timeout)
                except queue.Empty:
                    return
                if not started:
                    started = True
                    self._start_stream()
                self._write_chunk({"index": i, "token": int(t)})
                timeout = 0.0    # drain the rest of the burst non-blocking

        while not fut.done():
            relay_available(block=True)
        relay_available(block=False)       # the tail emitted before done
        try:
            res = fut.result()
            final = {"done": True, "num_tokens": len(res.tokens),
                     "queue_ms": res.queue_ms, "ttft_ms": res.ttft_ms,
                     "total_ms": res.total_ms,
                     "tokens_per_sec": res.tokens_per_sec}
            if trace_id is not None:
                final["trace_id"] = trace_id
            if not started:                # num_steps >= 1 makes this rare,
                started = True             # but a zero-token reply is still
                self._start_stream()       # a well-formed stream
        except Rejected as e:
            if not started:
                self._send_rejected(e)     # clean 429/504 — nothing sent yet
                return
            final = e.to_dict()
        except Exception as e:
            if not started:
                self._send_json(500, {"error": "internal",
                                      "message": repr(e)})
                return
            final = {"error": "internal", "message": repr(e)}
        self._write_chunk(final)
        self._end_stream()
        self.close_connection = True

    def _predict(self, gw: "Gateway", body: dict) -> None:
        try:
            image = np.asarray(body["image"], np.float32)
            timeout_s = body.get("timeout_s")
            timeout_s = None if timeout_s is None else float(timeout_s)
        except (KeyError, TypeError, ValueError) as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": f"bad field: {e}"})
            return
        try:
            fut = gw.replica_set.submit_predict(image, timeout_s=timeout_s)
        except Rejected as e:       # Overloaded / Unavailable / ReplicaFailed
            self._send_rejected(e)
            return
        except ValueError as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": str(e)})
            return
        try:
            res = fut.result()
        except Rejected as e:
            self._send_rejected(e)
            return
        except Exception as e:
            self._send_json(500, {"error": "internal", "message": repr(e)})
            return
        out = {"label": res.label, "index": res.index,
               "queue_ms": res.queue_ms, "total_ms": res.total_ms}
        if body.get("return_logits"):
            out["logits"] = [float(x) for x in res.logits]
        self._send_json(200, out)

    # -- batch lane (job submit / poll / results / cancel) --------------------
    def _batch_submit(self, gw: "Gateway", body: dict) -> None:
        try:
            kind = str(body.get("kind", "generate"))
            raw = body["items"]
            if not isinstance(raw, list) or not raw:
                raise ValueError("items must be a non-empty list")
            if kind == "generate":
                items = [np.asarray(x, np.int32) for x in raw]
            else:
                items = [np.asarray(x, np.float32) for x in raw]
            kw = {"kind": kind,
                  "temperature": float(body.get("temperature", 0.0)),
                  "window": int(body.get("window", 0)),
                  "timeout_s": float(body.get("timeout_s", 0.0))}
            if body.get("num_steps") is not None:
                kw["num_steps"] = int(body["num_steps"])
            if body.get("seed") is not None:
                kw["seed"] = int(body["seed"])
            if body.get("group_size") is not None:
                kw["group_size"] = int(body["group_size"])
        except (KeyError, TypeError, ValueError) as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": f"bad field: {e}"})
            return
        try:
            job = gw.replica_set.submit_batch(items, ledger=gw.jobs, **kw)
        except Rejected as e:
            self._send_rejected(e)
            return
        except ValueError as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": str(e)})
            return
        self._send_json(200, {"job_id": job.job_id, "kind": job.kind,
                              "total": job.total})

    def _batch_items(self, gw: "Gateway", body: dict) -> None:
        """One POST, N batch-lane items — the per-replica grouped
        submission a parent-process pump uses to cut per-item HTTP
        overhead. All items are submitted first (the engine pipelines the
        group), then awaited; each row answers individually (``ok`` +
        result, or the structured refusal), so one refused item never
        poisons its groupmates."""
        try:
            kind = str(body.get("kind", "generate"))
            raw = body["items"]
            indices = [int(i) for i in body.get("indices",
                                                range(len(raw)))]
            if not isinstance(raw, list) or not raw:
                raise ValueError("items must be a non-empty list")
            if len(indices) != len(raw):
                raise ValueError("indices must match items 1:1")
            temperature = float(body.get("temperature", 0.0))
            timeout_s = float(body.get("timeout_s", 0.0))
            num_steps = (int(body["num_steps"])
                         if body.get("num_steps") is not None else None)
            seed = (int(body["seed"])
                    if body.get("seed") is not None else None)
            key_data = body.get("key_data")   # pre-split keys, one per item
            if key_data is not None and len(key_data) != len(raw):
                raise ValueError("key_data must match items 1:1")
            if kind == "generate":
                items = [np.asarray(x, np.int32) for x in raw]
            else:
                items = [np.asarray(x, np.float32) for x in raw]
        except (KeyError, TypeError, ValueError) as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": f"bad field: {e}"})
            return
        base = None
        if kind == "generate" and temperature > 0.0 and seed is not None \
                and key_data is None:
            import jax

            base = jax.random.PRNGKey(seed)
        futs: list = []
        for pos, (item, idx) in enumerate(zip(items, indices)):
            try:
                if kind == "generate":
                    import jax
                    import jax.numpy as jnp

                    if key_data is not None:
                        rng = jnp.asarray(key_data[pos], dtype=jnp.uint32)
                    else:
                        rng = (jax.random.fold_in(base, idx)
                               if base is not None else None)
                    fut = gw.replica_set.submit_batch_item(
                        item, num_steps, temperature=temperature, rng=rng,
                        timeout_s=timeout_s)
                else:
                    fut = gw.replica_set.submit_batch_predict(
                        item, timeout_s=timeout_s)
            except Rejected as e:
                futs.append((idx, None, e.to_dict()))
                continue
            except ValueError as e:
                futs.append((idx, None, {"error": "invalid_request",
                                         "message": str(e)}))
                continue
            futs.append((idx, fut, None))
        rows = []
        for idx, fut, err in futs:
            if fut is None:
                rows.append({"index": idx, "ok": False, "error": err})
                continue
            try:
                res = fut.result()
            except Rejected as e:
                rows.append({"index": idx, "ok": False,
                             "error": e.to_dict()})
                continue
            except Exception as e:
                rows.append({"index": idx, "ok": False,
                             "error": {"error": "internal",
                                       "message": repr(e)}})
                continue
            if kind == "generate":
                row = {"tokens": [int(t) for t in res.tokens]}
            else:
                row = {"label": res.label, "class_index": int(res.index)}
            rows.append({"index": idx, "ok": True, "row": row})
        self._send_json(200, {"rows": rows})

    def _trace_get(self, gw: "Gateway") -> None:
        """``GET /v1/trace`` — the fleet's merged trace (gateway ring +
        every replica's drained ring; process replicas relay their child's
        over HTTP). ``?format=chrome`` renders Perfetto-loadable Chrome
        trace JSON directly. ``?replica=R&since=N`` is the single-replica
        relay form a PARENT gateway polls on a child's own gateway —
        mirrors ``/v1/prefix/events``."""
        import urllib.parse
        q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
        if "replica" in q:
            try:
                since = int(q.get("since", ["0"])[0])
                r = int(q["replica"][0])
            except ValueError:
                self._send_json(400, {"error": "invalid_request",
                                      "message": "since/replica must be "
                                                 "ints"})
                return
            replicas = gw.replica_set.replicas
            if not 0 <= r < len(replicas):
                self._send_json(404, {"error": "not_found", "replica": r})
                return
            fetch = getattr(replicas[r], "trace_events", None)
            if fetch is None:
                self._send_json(200, {"replica": r, "dropped": 0,
                                      "events": []})
                return
            self._send_json(200, fetch(since))
            return
        dump = gw.trace_dump()
        if q.get("format", [""])[0] == "chrome":
            from ddw_tpu.obs.trace import chrome_trace
            self._send_json(200, chrome_trace(dump["events"]))
            return
        self._send_json(200, dump)

    def _telemetry_get(self, gw: "Gateway") -> None:
        """``GET /v1/telemetry`` — the fleet's merged windowed time-series
        plus SLO status. ``?replica=R&since=N`` is the single-replica relay
        form a PARENT gateway polls on a child's own gateway (a process
        replica's child serves its engine's feed here regardless of the
        child gateway's own telemetry flag) — mirrors ``/v1/trace``."""
        import urllib.parse
        q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
        if "replica" in q:
            try:
                since = int(q.get("since", ["0"])[0])
                r = int(q["replica"][0])
            except ValueError:
                self._send_json(400, {"error": "invalid_request",
                                      "message": "since/replica must be "
                                                 "ints"})
                return
            replicas = gw.replica_set.replicas
            if not 0 <= r < len(replicas):
                self._send_json(404, {"error": "not_found", "replica": r})
                return
            fetch = getattr(replicas[r], "telemetry_events", None)
            if fetch is None:
                self._send_json(200, {"source": f"replica{r}", "replica": r,
                                      "dropped": 0, "samples": [],
                                      "last_seq": since})
                return
            self._send_json(200, fetch(since))
            return
        if not gw._telemetry:
            self._send_json(404, {"error": "not_found",
                                  "message": "gateway telemetry disabled "
                                             "(Gateway(telemetry=True))"})
            return
        self._send_json(200, gw.telemetry_view())

    def _prefix_events(self, gw: "Gateway") -> None:
        """``GET /v1/prefix/events?since=N&replica=R`` — one replica's
        prefix-cache register/evict delta feed (:meth:`~ddw_tpu.serve.
        ServingEngine.prefix_events`). This is how a parent gateway's
        fleet index follows a :class:`~ddw_tpu.deploy.ProcessReplica`
        child: the child's own single-replica gateway serves this path,
        the parent polls it with the last sequence number it applied."""
        import urllib.parse
        q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
        try:
            since = int(q.get("since", ["0"])[0])
            r = int(q.get("replica", ["0"])[0])
        except ValueError:
            self._send_json(400, {"error": "invalid_request",
                                  "message": "since/replica must be ints"})
            return
        replicas = gw.replica_set.replicas
        if not 0 <= r < len(replicas):
            self._send_json(404, {"error": "not_found", "replica": r})
            return
        fetch = getattr(replicas[r], "prefix_events", None)
        if fetch is None:
            self._send_json(200, {"seq": since, "reset": False,
                                  "events": []})
            return
        self._send_json(200, fetch(since))

    def _kv_migrate(self, gw: "Gateway") -> None:
        """``POST /v1/kv/export`` / ``POST /v1/kv/import`` — the KV
        migration plane's relay surface. A parent gateway's disaggregated
        router calls these against a :class:`~ddw_tpu.deploy.
        ProcessReplica` child's own gateway: export answers
        ``{"wire": ...}`` with the prompt's registered blocks on the
        versioned wire (``null`` when nothing is cached), import lands a
        wire into the replica's pool and answers the import summary.
        A malformed wire is a **400** (:class:`~ddw_tpu.serve.blocks.
        KVWireError` rejects before any pool mutation); pool exhaustion
        surfaces as the structured refusal it is."""
        body = self._read_body()
        if body is None:
            return
        try:
            r = int(body.get("replica", 0))
        except (TypeError, ValueError):
            self._send_json(400, {"error": "invalid_request",
                                  "message": "replica must be an int"})
            return
        replicas = gw.replica_set.replicas
        if not 0 <= r < len(replicas):
            self._send_json(404, {"error": "not_found", "replica": r})
            return
        eng = replicas[r]
        try:
            if self.path == "/v1/kv/export":
                fn = getattr(eng, "kv_export", None)
                if fn is None:      # non-paged/fake replica: nothing to
                    self._send_json(200, {"wire": None})    # export
                    return
                prompt = np.asarray(body.get("prompt", ()), np.int32)
                skip = [str(h) for h in body.get("skip", ())]
                self._send_json(200, {"wire": fn(prompt, skip_hashes=skip)})
            else:
                fn = getattr(eng, "kv_import", None)
                if fn is None:
                    self._send_json(200, {"imported": 0, "skipped": 0,
                                          "bytes": 0})
                    return
                self._send_json(200, fn(body.get("wire")))
        except Rejected as e:
            self._send_rejected(e)
        except (TypeError, ValueError) as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": str(e)})
        except Exception as e:
            self._send_json(500, {"error": "internal",
                                  "message": str(e)})

    def _admin_deploy(self, gw: "Gateway") -> None:
        """Kick a weight rollout across this gateway's fleet — the
        ``tools/rolling_deploy.py`` control plane. ``strategy`` picks
        rolling (default) / canary / surge; canary takes
        ``canary_fraction`` (traffic share the held canary receives) and
        ``judge_window_s`` (how long the judge compares it to the fleet).
        The rollout runs on its own thread; progress — including the
        canary verdict timeline — is read back from ``/stats``."""
        body = self._read_body()
        if body is None:
            return
        model_dir = body.get("model_dir")
        if not model_dir or not isinstance(model_dir, str):
            self._send_json(400, {"error": "invalid_request",
                                  "message": "model_dir (str) is required"})
            return
        # "draft_dir" absent = leave the spec-decode draft alone;
        # present (a path, or null to drop it) = stage it with the target
        kw = {}
        if "draft_dir" in body:
            draft_dir = body["draft_dir"]
            if draft_dir is not None and not isinstance(draft_dir, str):
                self._send_json(400, {"error": "invalid_request",
                                      "message": "draft_dir must be a "
                                                 "string or null"})
                return
            kw["draft_dir"] = draft_dir
        strategy = body.get("strategy", "rolling")
        if strategy not in ("rolling", "canary", "surge"):
            self._send_json(400, {"error": "invalid_request",
                                  "message": "strategy must be one of "
                                             "rolling|canary|surge"})
            return
        kw["strategy"] = strategy
        for key, lo, hi in (("canary_fraction", 0.0, 1.0),
                            ("judge_window_s", 0.0, None)):
            if key not in body:
                continue
            v = body[key]
            if (not isinstance(v, (int, float)) or isinstance(v, bool)
                    or v < lo or (hi is not None and v > hi)
                    or (key == "judge_window_s" and v <= 0)):
                self._send_json(400, {"error": "invalid_request",
                                      "message": f"{key} out of range"})
                return
            kw[key] = float(v)
        try:
            started = gw.start_deploy(model_dir,
                                      rollback=bool(body.get("rollback",
                                                             True)), **kw)
        except Exception as e:
            self._send_json(500, {"error": "internal", "message": repr(e)})
            return
        if not started:
            self._send_json(409, {"error": "deploy_in_progress",
                                  **gw.deploy_view()})
            return
        self._send_json(200, gw.deploy_view())

    def _admin_adapters(self, gw: "Gateway") -> None:
        """Operate the fleet's LoRA adapter pool: ``op="load"`` stages the
        adapter file at ``path`` onto EVERY replica (each load shadow-
        probed with one off-path generate; any failure rolls the whole
        stage back), ``op="unload"`` drops it fleet-wide, ``op="list"``
        returns residency. Same 409-under-lock discipline as
        ``/admin/deploy`` — adapter churn and weight rollouts never
        interleave — and every op lands in the adapter journal."""
        body = self._read_body()
        if body is None:
            return
        op = body.get("op", "list")
        if op not in ("load", "unload", "list"):
            self._send_json(400, {"error": "invalid_request",
                                  "message": "op must be one of "
                                             "load|unload|list"})
            return
        if op == "list":
            self._send_json(200, gw.adapters_view())
            return
        adapter_id = body.get("adapter_id")
        if not adapter_id or not isinstance(adapter_id, str):
            self._send_json(400, {"error": "invalid_request",
                                  "message": "adapter_id (str) is "
                                             "required"})
            return
        kw = {}
        if op == "load":
            path = body.get("path")
            if not path or not isinstance(path, str):
                self._send_json(400, {"error": "invalid_request",
                                      "message": "path (str) is required "
                                                 "for op=load"})
                return
            kw["path"] = path
            if body.get("alpha") is not None:
                kw["alpha"] = float(body["alpha"])
            if body.get("rank") is not None:
                kw["rank"] = int(body["rank"])
            if body.get("digest") is not None:
                kw["digest"] = str(body["digest"])
        with gw._deploy_lock:
            busy = bool(gw.deploy_status.get("deploying"))
        if busy:
            self._send_json(409, {"error": "deploy_in_progress",
                                  **gw.deploy_view()})
            return
        try:
            out = gw.admin_adapters(op, adapter_id, **kw)
        except ValueError as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": str(e)})
            return
        except Exception as e:
            self._send_json(500, {"error": "internal", "message": repr(e)})
            return
        status = out.get("status")
        if status in ("loaded", "unloaded"):
            self._send_json(200, out)
        elif status == "pinned":
            self._send_json(409, {"error": "adapter_busy", **out})
        else:                       # rolled_back / partial
            self._send_json(500, {"error": "stage_failed", **out})

    def _admin_autoscale(self, gw: "Gateway") -> None:
        """Operate the autoscaler: enable/disable the loop and move the
        policy's min/max bounds. Same 409-under-lock semantics as
        ``/admin/deploy`` — while a rollout (or a scale event) holds the
        deploy lock, reconfiguration is refused, not raced."""
        body = self._read_body()
        if body is None:
            return
        ctrl = gw.autoscaler
        if ctrl is None:
            self._send_json(404, {"error": "not_found",
                                  "message": "autoscaler disabled "
                                             "(Gateway(autoscale=True))"})
            return
        cfg = {}
        if "enabled" in body:
            if not isinstance(body["enabled"], bool):
                self._send_json(400, {"error": "invalid_request",
                                      "message": "enabled must be a bool"})
                return
            cfg["enabled"] = body["enabled"]
        for key in ("min_replicas", "max_replicas"):
            if key not in body:
                continue
            v = body[key]
            if not isinstance(v, int) or isinstance(v, bool) or v < 1:
                self._send_json(400, {"error": "invalid_request",
                                      "message": f"{key} must be a "
                                                 f"positive int"})
                return
            cfg[key] = v
        with gw._deploy_lock:
            busy = bool(gw.deploy_status.get("deploying"))
        if busy:
            self._send_json(409, {"error": "deploy_in_progress",
                                  **gw.deploy_view()})
            return
        try:
            out = ctrl.configure(**cfg)
        except ValueError as e:
            self._send_json(400, {"error": "invalid_request",
                                  "message": str(e)})
            return
        self._send_json(200, out)

    def _batch_job(self, gw: "Gateway"):
        """Resolve ``/v1/batch/<id>[/results]`` → (job, tail) or None after
        answering 404."""
        parts = self.path.split("/")          # '', 'v1', 'batch', id[, tail]
        job_id = parts[3] if len(parts) > 3 else ""
        tail = parts[4] if len(parts) > 4 else ""
        job = gw.jobs.get(job_id)
        if job is None:
            self._send_json(404, {"error": "not_found", "job_id": job_id})
            return None
        return job, tail

    def _batch_get(self, gw: "Gateway") -> None:
        hit = self._batch_job(gw)
        if hit is None:
            return
        job, tail = hit
        if tail == "results":
            # completed rows so far, index order, one JSON object per line
            data = "".join(json.dumps(r) + "\n"
                           for r in job.result_rows()).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif tail == "":
            self._send_json(200, job.progress())
        else:
            self._send_json(404, {"error": "not_found", "path": self.path})

    def do_DELETE(self) -> None:  # noqa: N802
        gw = self.server.gateway
        try:
            if not self.path.startswith("/v1/batch/"):
                self._send_json(404, {"error": "not_found",
                                      "path": self.path})
                return
            hit = self._batch_job(gw)
            if hit is None:
                return
            job, tail = hit
            if tail:
                self._send_json(404, {"error": "not_found",
                                      "path": self.path})
                return
            job.cancel()
            self._send_json(200, job.progress())
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True


class Gateway:
    """One serving process: HTTP listener + replica fleet + lifecycle.

    ``replicas`` is a :class:`ReplicaSet`, one engine, or a list of engines.
    ``grace_s`` defaults to the runtime layer's ``preempt_grace_s``
    (:func:`ddw_tpu.gateway.lifecycle.runtime_grace_s`). ``port=0`` binds an
    ephemeral port (read it back from :attr:`port` — the TOCTOU-free
    pattern, same reason the Launcher respawns on fresh ports).

    ``supervise=True`` (default) runs a :class:`~ddw_tpu.gateway.
    ReplicaSupervisor` over the fleet for the gateway's lifetime: failed
    replicas restart within budget and rejoin warm; ``supervisor_kw``
    forwards its knobs (``max_restarts``, ``stall_timeout_s``, ...).
    """

    def __init__(self, replicas, host: str = "127.0.0.1", port: int = 0,
                 grace_s: float | None = None, supervise: bool = True,
                 supervisor_kw: dict | None = None,
                 job_ledger_dir: str | None = None, trace: bool = False,
                 trace_capacity: int = 8192, telemetry: bool = False,
                 telemetry_interval_s: float = 0.25,
                 telemetry_capacity: int = 4096, slos=None,
                 slo_kw: dict | None = None,
                 degradation_dir: str | None = None,
                 deploy_journal_dir: str | None = None,
                 autoscale: bool = False,
                 autoscale_kw: dict | None = None,
                 autoscale_journal_dir: str | None = None):
        self.replica_set = (replicas if isinstance(replicas, ReplicaSet)
                            else ReplicaSet(replicas))
        # end-to-end tracing (docs/observability.md): the gateway mints
        # trace ids, records http + routing spans, and /v1/trace merges
        # its ring with every replica's into one Perfetto file
        self.tracer = (Tracer(capacity=trace_capacity, process="gateway")
                       if trace else None)
        self.replica_set.tracer = self.tracer
        self.lifecycle = ServerLifecycle(grace_s)
        self.lifecycle.health_fn = self.replica_set.fleet_health
        self._host, self._want_port = host, port
        self._httpd: _GatewayHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._drain_lock = threading.Lock()
        self.drained_clean: bool | None = None   # last drain's verdict
        self._supervise = supervise
        self._supervisor_kw = dict(supervisor_kw or {})
        self.supervisor: ReplicaSupervisor | None = None
        # batch-lane job registry: host-side, above the replicas, so jobs
        # survive engine restarts/recycles (the pump resubmits; results
        # recorded here are never lost). With ``job_ledger_dir`` the ledger
        # is DURABLE: specs and completed rows persist to disk and a
        # restarted gateway resumes every unfinished job mid-flight.
        self.jobs = JobLedger(ledger_dir=job_ledger_dir)
        # live telemetry plane (docs/observability.md): the gateway samples
        # its own routing state into a hub, polls every replica's feed, and
        # merges the fleet into aligned windows the SLO monitor evaluates
        self._telemetry = bool(telemetry)
        self.telem = (TelemetryHub(capacity=telemetry_capacity,
                                   interval_s=telemetry_interval_s,
                                   source="gateway")
                      if telemetry else None)
        self.fleet_telemetry = FleetTelemetry() if telemetry else None
        self.replica_set.telemetry = self.fleet_telemetry
        self._telemetry_interval_s = float(telemetry_interval_s)
        self._telemetry_thread: threading.Thread | None = None
        self._telemetry_stop = threading.Event()
        self.slo_monitor: SLOMonitor | None = None
        if telemetry:
            self.telem.add_collector(self._telemetry_collector)
            if slos:
                self.slo_monitor = SLOMonitor(
                    slos, tracer=self.tracer, dump_dir=degradation_dir,
                    flight_fn=self._flight_tail, **(slo_kw or {}))
        # rollout state, surfaced through /stats and /readyz; the
        # DeployController thread (start_deploy) mutates it under the lock.
        # With ``deploy_journal_dir`` every rollout journals its plan +
        # per-step progress there (fsync'd), and start() runs a reconciler
        # that converges whatever a dead gateway left half-rolled.
        self._deploy_lock = threading.Lock()
        self._deploy_thread: threading.Thread | None = None
        self._deploy_journal_dir = deploy_journal_dir
        self.deploy_status: dict = {"deploying": False, "status": "idle",
                                    "fleet_generation": 0, "steps": []}
        # adapter-op journal (the /admin/adapters side of the deploy
        # discipline): every staged load/unload lands here with its
        # per-replica step record; with ``deploy_journal_dir`` each entry
        # is also appended to adapters.jsonl for post-crash forensics
        self._adapter_ops: list[dict] = []
        # traffic-driven autoscaling (docs/serving.md): a reconciler loop
        # over the telemetry plane's windows, sharing the deploy lock so a
        # rollout and a scale event can never interleave. Constructed in
        # start() (it wants the supervisor); ``autoscale_kw`` forwards the
        # controller/policy knobs; ``autoscale_journal_dir`` makes every
        # scale event crash-recoverable the same way deploys are.
        self._autoscale = bool(autoscale)
        self._autoscale_kw = dict(autoscale_kw or {})
        self._autoscale_journal_dir = autoscale_journal_dir
        self.autoscaler = None

    # -- lifecycle -----------------------------------------------------------
    def start(self, warmup_prompt_lens=(8,), on_listening=None) -> "Gateway":
        """Bring the listener up FIRST (``/healthz`` answers while XLA
        compiles), then warm every replica's program lattice, then flip
        ``/readyz`` — readiness is gated on warmup by construction.
        ``on_listening(port)`` fires the moment the socket is bound (before
        warmup) — the process-replica child uses it to hand its port to the
        parent so health is observable through the compile."""
        if self._httpd is not None:
            return self
        self.replica_set.start()
        self._httpd = _GatewayHTTPServer((self._host, self._want_port), self)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="ddw-gateway-http",
            daemon=True)
        self._http_thread.start()
        if on_listening is not None:
            on_listening(self.port)
        if warmup_prompt_lens:
            self.replica_set.warmup(warmup_prompt_lens)
        if self._supervise and self.supervisor is None:
            kw = dict(warmup_prompt_lens=warmup_prompt_lens or (),
                      lifecycle=self.lifecycle)
            kw.update(self._supervisor_kw)
            self.supervisor = ReplicaSupervisor(self.replica_set,
                                                **kw).start()
        self.jobs.resume(self.replica_set)   # durable ledger: restart any
        #                                      job a dead gateway left behind
        self._reconcile_deploy()             # rollout journal: converge a
        #                                      half-rolled fleet the same way
        if self._autoscale and self.autoscaler is None:
            from ddw_tpu.autoscale.controller import AutoscaleController
            kw = dict(
                merged_fn=(self.fleet_telemetry.merged
                           if self.fleet_telemetry is not None else None),
                slo_status_fn=(self.slo_monitor.status
                               if self.slo_monitor is not None else None),
                lifecycle=self.lifecycle)
            kw.update(self._autoscale_kw)   # tests inject their own inputs
            self.autoscaler = AutoscaleController(
                self.replica_set, supervisor=self.supervisor,
                journal_dir=self._autoscale_journal_dir,
                deploy_lock=self._deploy_lock,
                deploy_status=self.deploy_status, **kw)
            self.autoscaler.reconcile()      # scale journal: finalize what
            #                                  a dead gateway left mid-scale
            self.autoscaler.start()
        if self._telemetry and self._telemetry_thread is None:
            self.telem.start()
            self._telemetry_stop.clear()
            self._telemetry_thread = threading.Thread(
                target=self._telemetry_loop, name="ddw-gateway-telemetry",
                daemon=True)
            self._telemetry_thread.start()
        self.lifecycle.mark_ready()
        return self

    # -- weight rollouts ------------------------------------------------------
    def deploy_view(self) -> dict:
        """The /stats deploy block: rollout state + per-replica checkpoint
        ids (what a load balancer or drill needs to observe a rollout).
        Nested values (steps, the canary verdict forensics, per-replica
        end states) are deep-copied so readers never alias the
        controller's live dicts."""
        with self._deploy_lock:
            out = copy.deepcopy(self.deploy_status)
        out["checkpoints"] = [h.get("checkpoint")
                              for h in self.replica_set.fleet_health()]
        return out

    def start_deploy(self, model_dir: str, rollback: bool = True,
                     **kw) -> bool:
        """Launch a weight rollout across the fleet on a control thread
        (the ``POST /admin/deploy`` implementation; ``kw`` carries
        ``strategy`` / ``canary_fraction`` / ``judge_window_s`` /
        ``draft_dir`` through to the controller). Returns False when a
        rollout is already in flight. Requires the supervisor (its recycle
        path IS the per-replica roll).

        The whole check → validate → construct → dispatch sequence holds
        ONE lock: the guard flag and the strategy dispatch used to be two
        critical sections, so two concurrent POSTs could both pass the
        guard — and a constructor that raised (bad strategy) left
        ``deploying`` stuck True with no controller behind it. Now exactly
        one caller wins, and a failed construction restores the idle
        state before re-raising."""
        from ddw_tpu.deploy.controller import DeployController
        from ddw_tpu.deploy.journal import RolloutJournal

        if self.supervisor is None:
            raise RuntimeError("rolling deploy needs supervise=True "
                               "(the supervisor owns the recycle path)")
        with self._deploy_lock:
            if self.deploy_status.get("deploying"):
                return False
            prev = dict(self.deploy_status)
            self.deploy_status.update(deploying=True, status="starting",
                                      target_dir=model_dir, steps=[])
            self.deploy_status.pop("canary", None)
            self.deploy_status.pop("replica_end_state", None)
            self.deploy_status.pop("resumed", None)
            try:
                journal = (RolloutJournal(self._deploy_journal_dir)
                           if self._deploy_journal_dir else None)
                ctrl = DeployController(self.replica_set, self.supervisor,
                                        model_dir, rollback=rollback,
                                        status=self.deploy_status,
                                        status_lock=self._deploy_lock,
                                        tracer=self.tracer,
                                        journal=journal, **kw)
                self._deploy_thread = threading.Thread(
                    target=ctrl.run, name="ddw-deploy", daemon=True)
                self._deploy_thread.start()
            except BaseException:
                self.deploy_status.clear()
                self.deploy_status.update(prev)
                raise
        return True

    def _reconcile_deploy(self) -> None:
        """Startup reconciler (the journal's read side): an unfinished
        rollout journal — or a mixed-digest fleet with no journal — from a
        previous gateway life converges on a deploy thread, exactly as a
        fresh ``start_deploy`` would run it. Best-effort: reconciliation
        must never block or kill startup."""
        if not self._deploy_journal_dir or self.supervisor is None:
            return
        from ddw_tpu.deploy.controller import resume_rollout

        try:
            ctrl = resume_rollout(self.replica_set, self.supervisor,
                                  self._deploy_journal_dir,
                                  status=self.deploy_status,
                                  status_lock=self._deploy_lock,
                                  tracer=self.tracer)
        except Exception:
            return
        if ctrl is None:
            return
        with self._deploy_lock:
            if self.deploy_status.get("deploying"):
                return
            self.deploy_status.update(deploying=True, status="resuming",
                                      steps=[])
            self._deploy_thread = threading.Thread(
                target=ctrl.run, name="ddw-deploy", daemon=True)
            self._deploy_thread.start()

    # -- adapter staging ------------------------------------------------------
    def adapters_view(self) -> dict:
        """The /stats adapters block: the gateway's digest registry (the
        routing salt source), each replica's residency view, and the op
        journal tail."""
        per: dict[str, dict] = {}
        for i, eng in enumerate(list(self.replica_set.replicas)):
            fn = getattr(eng, "adapter_view", None)
            if fn is None:
                continue
            try:
                v = fn()
                if v:               # {} = this replica has no adapter pool
                    per[str(i)] = v
            except Exception:
                per[str(i)] = {"error": "unreachable"}
        with self._deploy_lock:
            ops = copy.deepcopy(self._adapter_ops[-16:])
        return {"registry": dict(self.replica_set.adapter_digests),
                "replicas": per, "ops": ops}

    def _journal_adapter_op(self, entry: dict) -> None:
        with self._deploy_lock:
            self._adapter_ops.append(entry)
            del self._adapter_ops[:-64]
        if not self._deploy_journal_dir:
            return
        try:
            os.makedirs(self._deploy_journal_dir, exist_ok=True)
            with open(os.path.join(self._deploy_journal_dir,
                                   "adapters.jsonl"), "a") as f:
                f.write(json.dumps(entry) + "\n")
                f.flush()
        except OSError:
            pass                    # forensics, not correctness

    def admin_adapters(self, op: str, adapter_id: str,
                       path: str | None = None, alpha: float = 16.0,
                       rank: int | None = None,
                       digest: str | None = None) -> dict:
        """Stage (``op="load"``) or drop (``op="unload"``) a LoRA adapter
        across the fleet — the ``POST /admin/adapters`` implementation.

        Loads are STAGED like weight rollouts: replica by replica, each
        load followed by a shadow probe (one real 1-step generate under
        the adapter, off the routed path); the first failure unloads the
        adapter from every replica that took it and the entry records
        ``rolled_back`` — the fleet never ends half-resident. The adapter
        rides a FILE (``save_adapter``'s npz), the same shared-disk
        contract checkpoints use, so process replicas stage it the same
        way in-thread ones do. On success the adapter's digest lands in
        the ReplicaSet registry, which is what turns on adapter-salted
        prefix routing for it."""
        entry: dict = {"op": op, "adapter_id": adapter_id,
                       "t": time.time(), "steps": []}
        replicas = list(self.replica_set.replicas)
        if op == "load":
            entry["path"] = path
            staged: list[int] = []
            out_digest = None
            for i, eng in enumerate(replicas):
                step: dict = {"replica": i}
                entry["steps"].append(step)
                fn = getattr(eng, "load_adapter", None)
                if fn is None:
                    step.update(status="unsupported")
                else:
                    try:
                        info = fn(adapter_id, path=path, alpha=alpha,
                                  rank=rank, digest=digest)
                        step.update(status="loaded",
                                    slot=info.get("slot"),
                                    digest=info.get("digest"))
                        staged.append(i)
                        out_digest = info.get("digest") or out_digest
                        self._probe_adapter(eng, adapter_id)
                        step["probe"] = "ok"
                    except Exception as e:
                        step.update(status="failed", error=repr(e))
                if step.get("probe") != "ok":
                    # roll the stage back: every replica that took the
                    # adapter drops it, so routing state stays uniform
                    for j in staged:
                        try:
                            replicas[j].unload_adapter(adapter_id)
                        except Exception:
                            pass
                    entry["status"] = "rolled_back"
                    self._journal_adapter_op(entry)
                    return entry
            entry["status"] = "loaded"
            entry["digest"] = out_digest
            if out_digest:
                self.replica_set.adapter_digests[adapter_id] = out_digest
            self._journal_adapter_op(entry)
            return entry
        if op != "unload":
            raise ValueError(f"unknown adapter op {op!r}")
        pinned = failed = False
        for i, eng in enumerate(replicas):
            step = {"replica": i}
            entry["steps"].append(step)
            fn = getattr(eng, "unload_adapter", None)
            if fn is None:
                step.update(status="unsupported")
                continue
            try:
                fn(adapter_id)
                step.update(status="unloaded")
            except Exception as e:
                msg = repr(e)
                step.update(status=("pinned" if "pinned" in msg
                                    else "failed"), error=msg)
                pinned = pinned or step["status"] == "pinned"
                failed = True
        entry["status"] = ("pinned" if pinned
                           else "partial" if failed else "unloaded")
        if not failed:
            self.replica_set.adapter_digests.pop(adapter_id, None)
        self._journal_adapter_op(entry)
        return entry

    @staticmethod
    def _probe_adapter(eng, adapter_id: str, timeout_s: float = 30.0):
        """Shadow probe for a staged load: one real 1-step generate under
        the adapter, off the routed path (mirrors ProcessReplica.probe).
        Raises on any failure — the caller rolls the stage back."""
        fut = eng.submit_generate(np.asarray([1, 2, 3, 4], np.int32), 1,
                                  temperature=0.0, adapter_id=adapter_id)
        res = fut.result(timeout=timeout_s)
        if not len(res.tokens):
            raise RuntimeError(f"adapter probe for {adapter_id!r} "
                               f"returned no tokens")

    def autoscale_view(self) -> dict | None:
        """The /stats autoscale block (None when autoscaling is off):
        enabled flag, desired vs actual, last decision + reason,
        per-direction cooldown remaining, policy knobs, event counters."""
        ctrl = self.autoscaler
        return ctrl.view() if ctrl is not None else None

    # -- tracing --------------------------------------------------------------
    def trace_summary(self) -> dict | None:
        """The /stats trace block: gateway-ring summary + per-replica ring
        summaries, with fleet-total ``spans_dropped`` (truncation is never
        silent). None when this gateway does not trace."""
        if self.tracer is None:
            return None
        out = {"gateway": self.tracer.summary(), "replicas": [],
               "spans_dropped": self.tracer.spans_dropped}
        for i, eng in enumerate(self.replica_set.replicas):
            fetch = getattr(eng, "trace_summary", None)
            if fetch is None:
                h = (eng.health() if hasattr(eng, "health") else {})
                s = h.get("trace")
            else:
                s = fetch()
            if s:
                out["replicas"].append({"replica": i, **s})
                out["spans_dropped"] += int(s.get("dropped", 0) or 0)
        return out

    def trace_dump(self) -> dict:
        """Merged fleet trace — the gateway's ring plus every replica's
        drained ring (a :class:`~ddw_tpu.deploy.ProcessReplica` relays its
        child's over HTTP), events in timestamp order on the shared
        epoch-anchored timeline."""
        events: list[dict] = []
        dropped = 0
        sources: list[str] = []
        if self.tracer is not None:
            events.extend(self.tracer.drain())
            dropped += self.tracer.spans_dropped
            sources.append(self.tracer.process)
        for i, eng in enumerate(self.replica_set.replicas):
            fetch = getattr(eng, "trace_events", None)
            if fetch is None:
                continue
            try:
                d = fetch(0)
            except Exception:
                continue    # a mid-death replica must not break the dump
            evs = d.get("events", [])
            if evs:
                events.extend(evs)
                sources.append(f"replica{i}")
            dropped += int(d.get("dropped", 0) or 0)
        events.sort(key=lambda e: e.get("ts", 0.0))
        return {"events": events, "dropped": dropped, "sources": sources}

    def lane_stats(self) -> dict:
        """Per-lane fleet view for ``/stats`` and ``/readyz``: queue depths
        summed across replicas, the worst reserve occupancy (one saturated
        replica is the one a new interactive arrival might land on), and
        the job ledger's accounting."""
        interactive = batch = 0
        occupancy = 0.0
        for h in self.replica_set.fleet_health():
            interactive += int(h.get("interactive_depth", 0) or 0)
            batch += int(h.get("batch_depth", 0) or 0)
            occupancy = max(occupancy,
                            float(h.get("reserve_occupancy_pct", 0.0) or 0.0))
        return {"interactive_depth": interactive, "batch_depth": batch,
                "reserve_occupancy_pct": round(occupancy, 2),
                **self.jobs.summary()}

    # -- telemetry / SLOs -----------------------------------------------------
    def _telemetry_collector(self) -> dict:
        """The gateway's own signals, sampled each hub tick: connection and
        in-flight load, routing state (outstanding work, open breakers, the
        best replica's projected wait — what :meth:`ReplicaSet._score`
        ranks on), and the retry/failover counters."""
        rs = self.replica_set
        out = {
            "gateway.connections": ("gauge", float(
                self._httpd.active_connections if self._httpd else 0)),
            "gateway.inflight": ("gauge", float(self.lifecycle.inflight)),
            "gateway.outstanding": ("gauge", float(sum(rs._outstanding))),
            "gateway.breaker_open": ("gauge", float(sum(
                1 for b in rs.breakers if b.state != "closed"))),
            "gateway.retried_429": ("counter", float(rs.retried_429)),
            "gateway.replica_failures": ("counter",
                                         float(rs.replica_failures)),
            "gateway.failed_over": ("counter", float(rs.failed_over)),
        }
        try:
            scored = rs._scored(weighted=False)
            if scored:
                out["gateway.projected_wait_ms"] = ("gauge",
                                                    float(scored[0][0]))
        except Exception:
            pass    # a mid-death replica must not kill the sampler tick
        return out

    def _flight_tail(self) -> list:
        """Last trace events across the fleet — the flight-recorder tail a
        degradation dump freezes alongside the offending windows."""
        if self.tracer is None:
            return []
        try:
            return self.trace_dump()["events"][-64:]
        except Exception:
            return []

    def _telemetry_loop(self) -> None:
        while not self._telemetry_stop.wait(self._telemetry_interval_s):
            try:
                self._telemetry_tick()
            except Exception:
                pass    # the plane observes the fleet; it never takes it down

    def _telemetry_tick(self) -> None:
        """One merge cycle: ingest the gateway hub's fresh samples plus
        every replica's drained feed into the fleet store (seq-watermarked,
        like the trace relay), hand the fresh samples to the SLO monitor's
        budget accounting, then evaluate burn rates over the merged
        windows."""
        fleet = self.fleet_telemetry
        fresh_by_src = {}
        fresh = fleet.ingest("gateway",
                             self.telem.drain(fleet.watermark("gateway")))
        if fresh:
            fresh_by_src["gateway"] = fresh
        for i, eng in enumerate(self.replica_set.replicas):
            fetch = getattr(eng, "telemetry_events", None)
            if fetch is None:
                continue
            src = f"replica{i}"
            try:
                feed = fetch(fleet.watermark(src))
            except Exception:
                continue    # a mid-death replica freezes, it doesn't break
            fresh = fleet.ingest(src, feed)
            if fresh:
                fresh_by_src[src] = fresh
        if self.slo_monitor is not None:
            for src, samples in fresh_by_src.items():
                self.slo_monitor.ingest(src, samples)
            self.slo_monitor.evaluate(fleet.feeds())

    def telemetry_summary(self) -> dict | None:
        """The /stats telemetry block: gateway-hub summary + per-source
        sample counts in the fleet store, with fleet-total
        ``samples_dropped`` (truncation is never silent). None when this
        gateway does not sample."""
        if not self._telemetry:
            return None
        out = {"gateway": self.telem.summary(),
               "sources": self.fleet_telemetry.sources(),
               "samples_dropped": self.telem.samples_dropped}
        for i, eng in enumerate(self.replica_set.replicas):
            fetch = getattr(eng, "health", None)
            if fetch is None:
                continue
            try:
                s = fetch().get("telemetry")
            except Exception:
                continue
            if s:
                out["samples_dropped"] += int(s.get("dropped", 0) or 0)
        return out

    def telemetry_view(self) -> dict:
        """The bare ``GET /v1/telemetry`` body: the fleet's merged windowed
        aggregates plus the SLO monitor's status (objectives, states, error
        budgets, recent transitions)."""
        merged = self.fleet_telemetry.merged()
        out = {"now": merged["now"], "sources": merged["sources"],
               "windows": merged["windows"]}
        if self.slo_monitor is not None:
            out["slo"] = self.slo_monitor.status()
        return out

    def slo_prometheus(self) -> str:
        """Prometheus exposition lines appended to ``/metrics`` when the
        telemetry plane is on: per-objective alert state (0 ok / 1 warning
        / 2 page), budget consumption, attainment, and the hub's dropped-
        sample counter. Empty string otherwise — the base exposition is
        untouched for a telemetry-off gateway."""
        if not self._telemetry:
            return ""
        lines = [
            "# HELP ddw_telemetry_samples_dropped Telemetry samples lost "
            "to ring overflow (gateway hub).",
            "# TYPE ddw_telemetry_samples_dropped counter",
            f"ddw_telemetry_samples_dropped {self.telem.samples_dropped}",
        ]
        if self.slo_monitor is not None:
            from ddw_tpu.obs.slo import _LEVEL
            st = self.slo_monitor.status()
            lines += ["# HELP ddw_slo_state Alert FSM level per objective "
                      "(0 ok, 1 warning, 2 page).",
                      "# TYPE ddw_slo_state gauge"]
            for name, obj in st["objectives"].items():
                lines.append(f'ddw_slo_state{{objective="{name}"}} '
                             f'{_LEVEL[obj["state"]]}')
            lines += ["# HELP ddw_slo_budget_consumed_pct Error budget "
                      "consumed per objective (cumulative, percent).",
                      "# TYPE ddw_slo_budget_consumed_pct gauge"]
            for name, obj in st["objectives"].items():
                lines.append(
                    f'ddw_slo_budget_consumed_pct{{objective="{name}"}} '
                    f'{obj["budget"]["budget_consumed_pct"]}')
            lines += ["# HELP ddw_slo_attainment Fraction of events that "
                      "met the objective (cumulative).",
                      "# TYPE ddw_slo_attainment gauge"]
            for name, obj in st["objectives"].items():
                lines.append(f'ddw_slo_attainment{{objective="{name}"}} '
                             f'{obj["budget"]["attainment"]}')
        return "\n".join(lines) + "\n"

    @property
    def port(self) -> int:
        if self._httpd is None:
            raise RuntimeError("gateway not started")
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self.port}"

    def drain(self, grace_s: float | None = None) -> bool:
        """Graceful shutdown: stop admission (new requests 503), wait out
        in-flight responses up to the grace window, stop the engines, close
        the listener. Returns True when every in-flight request finished
        inside the window. Idempotent — a second caller blocks until the
        first drain completes, then reports its verdict."""
        with self._drain_lock:
            if not self.lifecycle.begin_drain():
                return bool(self.drained_clean)
            self.jobs.shutdown()   # stop the batch pumps first — nothing
            #                        may resubmit into a closing fleet
            clean = self.lifecycle.await_drained(
                grace_s if grace_s is not None else self.lifecycle.grace_s)
            if self.autoscaler is not None:
                self.autoscaler.stop()   # no scale events during teardown
                self.autoscaler = None
            if self.supervisor is not None:
                self.supervisor.stop()   # no resurrections during teardown
                self.supervisor = None
            if self._telemetry_thread is not None:
                self._telemetry_stop.set()
                self._telemetry_thread.join(timeout=5.0)
                self._telemetry_thread = None
            if self.telem is not None:
                self.telem.stop()
            self.replica_set.stop()   # stragglers' futures fail loudly here
            if self._httpd is not None:
                self._httpd.shutdown()
                if self._http_thread is not None:
                    self._http_thread.join(timeout=10.0)
                self._httpd.server_close()
                self._httpd = None
            self.lifecycle.restore_sigterm()
            self.lifecycle.mark_stopped()
            self.drained_clean = clean
            return clean

    def stop(self) -> bool:
        return self.drain()

    def install_sigterm(self) -> None:
        """SIGTERM → drain, the serving analog of the training gang's
        graceful preemption (main thread only)."""
        self.lifecycle.install_sigterm(self.drain)

    def __enter__(self) -> "Gateway":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.drain()
