"""ReplicaSupervisor — bounded auto-restart over a serving replica fleet.

The :class:`~ddw_tpu.gateway.ReplicaSet` is the *containment* half of
serving fault tolerance: a dead replica's circuit opens, its queued work
fails over to siblings, and routing walks around the corpse. This module is
the *recovery* half, the serving analog of
:class:`~ddw_tpu.runtime.supervisor.GangSupervisor` — same discipline,
different failure geometry (threads in one process, restart one replica,
keep serving on the rest):

- a monitor thread watches every replica's :meth:`~ddw_tpu.serve.
  ServingEngine.health` — woken immediately by the set's ``failure_event``,
  polling otherwise — and classifies two conditions: **failed** (the engine
  reported terminal death: crash, stall-abort, error-budget exhaustion) and
  **stalled** (the loop heartbeat's ``last_tick_age_s`` exceeded
  ``stall_timeout_s`` — a wedged device op or an injected
  ``DDW_FAULT=serve:stall``; the supervisor declares it dead via
  ``force_fail``, which also fails its futures so no client hangs);
- recovery is **bounded restart with backoff + jitter**, mirroring the gang
  supervisor's budgets: up to ``max_restarts`` per replica, delay
  ``backoff_base_s * 2**(n-1)`` capped at ``backoff_max_s`` plus uniform
  jitter (decorrelates a fleet-wide event from stampeding the device). A
  replica over budget stays dark — its circuit stays open, the fleet keeps
  serving degraded, and the per-attempt forensics are kept;
- the **rejoin is warmup-gated** through the same discipline as
  :class:`~ddw_tpu.gateway.ServerLifecycle` readiness: the restarted engine
  re-compiles nothing in place (:meth:`~ddw_tpu.serve.ServingEngine.
  restart` keeps program caches) but is still driven through
  ``warmup(prompt_lens)`` before its breaker half-opens — no live request
  pays a cold path behind a circuit that claimed the replica was back. A
  thread wedged in real device work cannot be joined; that replica is
  **replaced** (``clone_fresh`` + ``ReplicaSet.replace``) and the
  replacement pays its compile inside the warmup gate, not on traffic;
- the rejoin is also **cache-warmed**: before the shadow probe, the fleet
  prefix index's top-K hot prefixes (:meth:`~ddw_tpu.gateway.
  prefix_index.PrefixIndex.hot`) are replayed through the restarted
  replica's normal prefill path — one-step greedy generates, bit-identical
  by construction, no KV shipping — so a recycled or hot-swapped replica
  rejoins holding the fleet's hot set instead of re-prefilling it on live
  traffic (``warm_replay_k`` sizes the replay; 0 disables).

Per-attempt records (:class:`ReplicaAttempt`) mirror ``AttemptReport``:
which replica, which generation, what killed it, how recovery went —
queryable via :meth:`ReplicaSupervisor.report` and surfaced through the
gateway's ``/stats``.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time

__all__ = ["ReplicaSupervisor", "ReplicaAttempt"]


@dataclasses.dataclass
class ReplicaAttempt:
    """One observed replica death + the recovery attempted for it."""

    replica: int
    generation: int
    kind: str                   # crash | stalled | errors | error | degraded
    action: str                 # restarted | replaced | drained_restarted
    #                             | drain_timeout | budget_exhausted
    elapsed_s: float            # detection -> serving again (0 if not)
    forensics: dict
    readmit: str = ""           # probed_closed | probe_failed | half_open
    #                             (how the replica re-entered routing)

    def __str__(self) -> str:
        via = f" [{self.readmit}]" if self.readmit else ""
        return (f"replica {self.replica} gen {self.generation}: "
                f"{self.kind} -> {self.action}{via} "
                f"({self.elapsed_s:.2f}s)")


class ReplicaSupervisor:
    """Watch a :class:`~ddw_tpu.gateway.ReplicaSet`, restart dead replicas
    within budget, and gate their rejoin on warmup.

    ``lifecycle`` (a :class:`~ddw_tpu.gateway.ServerLifecycle`) scopes the
    supervisor to the serving process's own state machine: once the process
    is draining or stopped, dead replicas stay dead — restarting an engine
    the drain is about to stop would race it back to life.
    """

    def __init__(self, replica_set, max_restarts: int = 2,
                 backoff_base_s: float = 0.25, backoff_max_s: float = 30.0,
                 jitter: float = 0.25, stall_timeout_s: float = 30.0,
                 poll_interval_s: float = 0.25,
                 warmup_prompt_lens=(8,), lifecycle=None,
                 shadow_probe: bool = True, probe_timeout_s: float = 30.0,
                 recycle_degraded_after_s: float | None = None,
                 drain_timeout_s: float = 30.0,
                 warm_replay_k: int = 8):
        self.rs = replica_set
        self.max_restarts = max_restarts
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.jitter = jitter
        self.stall_timeout_s = stall_timeout_s
        self.poll_interval_s = poll_interval_s
        self.warmup_prompt_lens = tuple(warmup_prompt_lens or ())
        self.lifecycle = lifecycle
        # Shadow probing (docs/serving.md): a replica rejoining behind an
        # open circuit is verified with a supervisor-issued warmup request
        # straight against the engine — success CLOSES the circuit, so no
        # live client request is ever spent as the half-open guinea pig.
        # Engines without a probe surface fall back to the half-open gate.
        self.shadow_probe = shadow_probe
        self.probe_timeout_s = probe_timeout_s
        # Graceful recycle: a replica continuously degraded for this long is
        # drained (in-slot requests run to completion, queue preserved) and
        # restarted in place, instead of waiting for its error budget to
        # fail it the hard way. None = only explicit recycle() calls.
        self.recycle_degraded_after_s = recycle_degraded_after_s
        self.drain_timeout_s = drain_timeout_s
        # Warm replay: how many of the fleet's hottest prefixes a restarted
        # replica replays (through its normal prefill) before readmission.
        self.warm_replay_k = warm_replay_k
        self.probes = 0             # shadow probes issued (telemetry)
        self.attempts: list[ReplicaAttempt] = []
        self._next_attempt_at = [0.0] * len(replica_set.replicas)
        self._degraded_since = [None] * len(replica_set.replicas)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReplicaSupervisor":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="ddw-replica-supervisor", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.rs.failure_event.set()     # unblock the wait
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    def __enter__(self) -> "ReplicaSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def report(self) -> dict:
        """The forensic record: restart counts per replica + every attempt
        (the GangFailure-style story, queryable instead of buried in
        logs)."""
        with self._lock:
            return {"max_restarts": self.max_restarts,
                    "restarts": list(self.rs.restarts),
                    "shadow_probes": self.probes,
                    "attempts": [dataclasses.asdict(a)
                                 for a in self.attempts]}

    # -- elastic membership (the autoscaler's bookkeeping hooks) -------------
    def note_added(self) -> None:
        """A slot was appended to the fleet (autoscale scale-out): grow the
        per-slot recovery state in step."""
        with self._lock:
            self._next_attempt_at.append(0.0)
            self._degraded_since.append(None)

    def note_removed(self, i: int) -> None:
        """Slot ``i`` was retired (scale-in): drop its recovery state — the
        slots above renumber exactly as ``ReplicaSet.remove_replica`` did,
        and their backoff clocks travel with them."""
        with self._lock:
            if 0 <= i < len(self._next_attempt_at):
                self._next_attempt_at.pop(i)
                self._degraded_since.pop(i)

    # -- monitor loop --------------------------------------------------------
    def _draining(self) -> bool:
        return (self.lifecycle is not None
                and self.lifecycle.state in ("draining", "stopped"))

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.rs.failure_event.wait(timeout=self.poll_interval_s)
            self.rs.failure_event.clear()
            if self._stop.is_set() or self._draining():
                continue
            now = time.monotonic()
            for i, eng in enumerate(list(self.rs.replicas)):
                try:
                    if not hasattr(eng, "health"):
                        continue
                    while i >= len(self._next_attempt_at):
                        self.note_added()   # fleet grew under the monitor
                    h = eng.health()
                    if (h["state"] in ("alive", "degraded") and h["running"]
                            and h["last_tick_age_s"] > self.stall_timeout_s):
                        # the loop's heartbeat went stale: declare it dead
                        # so its futures resolve and its circuit opens; the
                        # restart below reclaims (or replaces) the thread
                        eng.force_fail("stalled")
                        h = eng.health()
                    if (h["state"] == "failed"
                            and now >= self._next_attempt_at[i]):
                        self._recover(i, eng)
                        continue
                    # degraded-too-long: graceful recycle BEFORE the error
                    # budget fails it the hard way — in-slot work completes
                    # instead of being failed over
                    if (self.recycle_degraded_after_s is not None
                            and h["state"] == "degraded" and h["running"]):
                        if self._degraded_since[i] is None:
                            self._degraded_since[i] = now
                        elif (now - self._degraded_since[i]
                                >= self.recycle_degraded_after_s
                                and now >= self._next_attempt_at[i]):
                            self.recycle(i)
                            self._degraded_since[i] = None
                    else:
                        self._degraded_since[i] = None
                except Exception:
                    continue    # a monitor bug must never kill the monitor

    def _recover(self, i: int, eng) -> None:
        n_prior = self.rs.restarts[i]
        failure = getattr(eng, "failure", None)
        kind = failure.kind if failure is not None else "error"
        forensics = dict(failure.forensics) if failure is not None else {}
        gen = getattr(eng, "generation", 0)
        if n_prior >= self.max_restarts:
            with self._lock:
                if not any(a.replica == i and a.action == "budget_exhausted"
                           for a in self.attempts):
                    self.attempts.append(ReplicaAttempt(
                        replica=i, generation=gen, kind=kind,
                        action="budget_exhausted", elapsed_s=0.0,
                        forensics=forensics))
            return                  # stays dark; circuit stays open
        t0 = time.monotonic()
        action = "restarted"
        try:
            try:
                eng.restart()
            except RuntimeError:
                # thread wedged in device work — abandon it, swap in a
                # fresh engine over the same handles (compiles inside the
                # warmup gate below, not on live traffic)
                eng = eng.clone_fresh()
                self.rs.replace(i, eng)
                eng.start()
                action = "replaced"
            if self.warmup_prompt_lens:
                eng.warmup(self.warmup_prompt_lens)
        except Exception as e:      # the restart itself died: try again
            self._next_attempt_at[i] = time.monotonic() + self._backoff(
                n_prior + 1)
            self.rs.note_restart(i)
            with self._lock:
                self.attempts.append(ReplicaAttempt(
                    replica=i, generation=gen, kind=kind,
                    action=f"restart_failed: {e!r}"[:200], elapsed_s=0.0,
                    forensics=forensics))
            self.rs.failure_event.set()
            return
        self.rs.note_restart(i)
        self._next_attempt_at[i] = time.monotonic() + self._backoff(
            n_prior + 1)
        # Record the attempt BEFORE the (blocking) shadow probe, then fill
        # in how the replica re-entered routing once the probe resolves —
        # the restart is a fact the moment the engine is serving again.
        att = ReplicaAttempt(
            replica=i, generation=getattr(eng, "generation", gen),
            kind=kind, action=action, elapsed_s=time.monotonic() - t0,
            forensics=forensics)
        with self._lock:
            self.attempts.append(att)
        self._warm_replay(i, eng)
        att.readmit = self._readmit(i, eng)     # warmed: probe, then admit

    # -- warm replay: rejoin holding the fleet's hot prefixes -----------------
    def _warm_replay(self, i: int, eng) -> int:
        """Replay the fleet prefix index's top-K hot prefixes through a
        restarted replica's NORMAL prefill path (one-step greedy generates
        — bit-identical by construction, no KV shipping) so it rejoins
        holding the fleet's hot set instead of re-prefilling it on live
        traffic. Runs behind the still-open circuit, before the shadow
        probe. Best effort: a failed replay leaves the replica cold,
        never dark."""
        if not self.warm_replay_k:
            return 0
        idx = getattr(self.rs, "prefix_index", None)
        if idx is None or not hasattr(eng, "submit_generate"):
            return 0
        n = 0
        for toks in idx.hot(self.warm_replay_k):
            try:
                eng.submit_generate(
                    toks, 1, temperature=0.0,
                    timeout_s=self.probe_timeout_s).result(
                        self.probe_timeout_s)
                n += 1
            except Exception:
                break       # a cold rejoin beats blocking recovery
        if n:
            try:
                eng.metrics.count("warm_replays", n)
            except Exception:
                pass        # fakes without metrics still recycle
        return n

    # -- rejoin gate: shadow probe > live half-open probe ---------------------
    def _readmit(self, i: int, eng) -> str:
        """Bring a warmed replica back into routing. With shadow probing a
        supervisor-issued request (never a client's) verifies the replica
        end to end: success closes the circuit outright; failure re-trips
        it and the next backoff window applies. Engines without a probe
        surface keep the classic half-open single-live-probe gate."""
        probe = None
        if self.shadow_probe:
            if hasattr(eng, "probe"):
                # an explicit probe surface (process replicas: one real
                # request through the child's own HTTP door) beats guessing
                # from engine internals
                probe = lambda: eng.probe(  # noqa: E731
                    timeout_s=self.probe_timeout_s)
            elif getattr(eng, "pool", None) is not None and \
                    hasattr(eng, "generate"):
                probe = lambda: eng.generate(  # noqa: E731
                    [1, 2, 3, 4], 1, timeout_s=self.probe_timeout_s)
            elif getattr(eng, "_image", None) is not None and \
                    hasattr(eng, "submit_predict"):
                import numpy as _np

                h = eng._image
                probe = lambda: eng.submit_predict(  # noqa: E731
                    _np.zeros((h.height, h.width, 3), _np.float32),
                    timeout_s=self.probe_timeout_s).result(
                        self.probe_timeout_s)
        if probe is None:
            self.rs.breakers[i].half_open()
            return "half_open"
        self.probes += 1
        try:
            probe()
        except Exception:
            self.rs.breakers[i].trip()
            self.rs.failure_event.set()     # revisit after backoff
            return "probe_failed"
        self.rs.breakers[i].close()
        return "probed_closed"

    # -- graceful recycle (drain-then-restart; never fails in-slot work) -----
    def recycle(self, i: int, kind: str = "degraded") -> bool:
        """Drain replica ``i``'s in-slot requests to completion, restart it
        in place (queued work preserved, served by the next generation),
        re-warm, shadow-probe, and readmit. The operator-facing building
        block for rolling restarts / weight hot-swap (the
        :class:`~ddw_tpu.deploy.DeployController` calls this with
        ``kind="deploy"`` after staging a checkpoint swap), and the
        automatic path for degraded-too-long replicas. Falls back to
        ``force_fail`` (today's hard path — futures failed over) when the
        drain times out. Returns True on a clean recycle."""
        eng = self.rs.replicas[i]
        if not hasattr(eng, "recycle"):
            return False
        t0 = time.monotonic()
        gen = getattr(eng, "generation", 0)
        # stop routing new work at it while it drains (honest refusals at
        # the engine door would spill anyway; the open circuit is cheaper)
        self.rs.breakers[i].trip()
        ok = False
        try:
            ok = eng.recycle(drain_timeout_s=self.drain_timeout_s)
        except Exception:
            ok = False
        if not ok:
            with self._lock:
                self.attempts.append(ReplicaAttempt(
                    replica=i, generation=gen, kind=kind,
                    action="drain_timeout", elapsed_s=time.monotonic() - t0,
                    forensics={}))
            try:
                eng.force_fail("stalled")   # escalate: the hard path
            except Exception:
                pass
            self.rs.failure_event.set()
            return False
        try:
            if self.warmup_prompt_lens:
                eng.warmup(self.warmup_prompt_lens)
        except Exception:
            pass
        self.rs.note_restart(i)
        self._next_attempt_at[i] = time.monotonic() + self._backoff(1)
        att = ReplicaAttempt(
            replica=i, generation=getattr(eng, "generation", gen),
            kind=kind, action="drained_restarted",
            elapsed_s=time.monotonic() - t0, forensics={})
        with self._lock:
            self.attempts.append(att)
        self._warm_replay(i, eng)
        att.readmit = self._readmit(i, eng)
        return True

    # -- surge swap (spawn-before-drain; capacity never dips) -----------------
    def surge_swap(self, i: int, new_eng) -> bool:
        """Swap ``new_eng`` (already started AND warmed by the caller — its
        compile happened off-traffic) into slot ``i`` and retire the old
        engine by draining it: :meth:`~ddw_tpu.gateway.ReplicaSet.replace`
        is the atomic cutover, so the slot serves continuously and fleet
        capacity never dips below N; the old generation's ``stop()`` lets
        in-flight work run to completion before the process exits (the
        Horovod-elastic membership-change framing: grow first, shrink
        after). The building block :class:`~ddw_tpu.deploy.
        DeployController` uses per replica with ``strategy="surge"``.
        Returns False (old engine force-failed, swap still landed) only if
        the retire path raised."""
        old = self.rs.replicas[i]
        gen = getattr(new_eng, "generation", 0)
        t0 = time.monotonic()
        self.rs.replace(i, new_eng)
        ok = True
        try:
            old.stop()          # SIGTERM path: drains in-flight, then exits
        except Exception:
            ok = False
            try:
                old.force_fail("surge_retire")
            except Exception:
                pass
        self.rs.note_restart(i)
        with self._lock:
            self.attempts.append(ReplicaAttempt(
                replica=i, generation=gen, kind="deploy",
                action="surged" if ok else "surge_retire_failed",
                elapsed_s=time.monotonic() - t0, forensics={}))
        return ok

    def _backoff(self, nth_restart: int) -> float:
        delay = min(self.backoff_max_s,
                    self.backoff_base_s * (2 ** max(0, nth_restart - 1)))
        return delay + random.uniform(0.0, self.jitter * delay)
