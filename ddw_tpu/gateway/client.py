"""Python client for the HTTP gateway — retries that honor backpressure.

The gateway's 429 reply is not an error so much as a scheduling hint: the
body carries the engine's own ``retry_after_ms`` estimate (queue depth x
the decaying per-request service time) and the header carries the RFC
``Retry-After`` seconds. A client that retries immediately converts
backpressure into a thundering herd; this one sleeps exactly what the
server asked (the precise ms from the body when present, the coarser
header otherwise, capped exponential backoff when neither is given) and
gives up after ``max_retries`` with the structured refusal intact.

Stdlib only (``http.client``), deliberately: it runs inside the test suite
and ``tools/load_gen.py``, and is the reference for what any real client
(another language, a sidecar) must implement — the protocol is plain
enough that this file IS the spec: JSON bodies, NDJSON streaming lines,
and the status table in :mod:`ddw_tpu.gateway.http`.

Retryable: 429 (engine queue full) and 503 (gateway starting, draining, a
replica died mid-request, or every circuit is open — a fleet peer or the
supervisor's restarted replica may answer; the balancer decides). Not
retryable: 504 (the request's own deadline died — retrying re-spends it),
400, 500.

Connections are HTTP/1.1 keep-alive and REUSED: completed unary exchanges
return their connection to a small per-client pool, so a retry storm (the
chaos drill: one replica dies, every client backs off and re-asks) does
not re-handshake per attempt and the gateway's ``max_connections`` guard
is not eaten by churn. Streaming responses close their connection (the
server ends the chunked stream with ``Connection: close``). A pooled
connection the server quietly closed between requests is detected on use
and replayed once on a fresh one. One client per thread is the intended
shape (the pool makes sharing safe, not fast).
"""

from __future__ import annotations

import http.client
import json
import threading
import time

__all__ = ["GatewayClient", "GatewayError", "GatewayOverloaded",
           "GatewayUnavailable", "GatewayDeadline"]


class GatewayError(RuntimeError):
    """Non-2xx reply, structured body preserved."""

    def __init__(self, status: int, body: dict):
        self.status = status
        self.body = body
        super().__init__(f"gateway returned {status}: {body}")


class GatewayOverloaded(GatewayError):
    """429 survived every retry — the fleet really is full."""


class GatewayUnavailable(GatewayError):
    """503 survived every retry — not ready, or draining for good."""


class GatewayDeadline(GatewayError):
    """504 — the request's deadline passed while it was queued."""


_RETRYABLE = (429, 503)


class GatewayClient:
    """Thin blocking client; one connection per request (the gateway is
    thread-per-connection — holding sockets open across calls buys nothing
    a benchmark would notice and costs drain determinism)."""

    def __init__(self, host: str, port: int, timeout_s: float = 60.0,
                 max_retries: int = 4, backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0, pool_size: int = 4):
        self.host, self.port = host, port
        self.timeout_s = timeout_s
        self.max_retries = max_retries
        self.backoff_s = backoff_s
        self.max_backoff_s = max_backoff_s
        self.pool_size = pool_size
        self.retries = 0            # total backoff sleeps taken (telemetry)
        self.reused = 0             # keep-alive connections reused
        self._pool: list[http.client.HTTPConnection] = []
        self._pool_lock = threading.Lock()

    # -- transport -----------------------------------------------------------
    def _connect(self) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout_s)

    def _acquire(self) -> tuple[http.client.HTTPConnection, bool]:
        """A pooled keep-alive connection when one is idle, else fresh.
        The bool says "pooled" — a stale pooled socket gets one replay."""
        with self._pool_lock:
            if self._pool:
                self.reused += 1
                return self._pool.pop(), True
        return self._connect(), False

    def _done(self, conn: http.client.HTTPConnection, resp) -> None:
        """Return a fully-read connection to the pool (keep-alive) or close
        it (server said close / stream / pool full)."""
        reusable = (resp is not None and not resp.will_close
                    and resp.isclosed())
        if reusable:
            with self._pool_lock:
                if len(self._pool) < self.pool_size:
                    self._pool.append(conn)
                    return
        conn.close()

    def close(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, []
        for conn in pool:
            conn.close()

    def _retry_delay(self, resp_headers, body: dict, attempt: int) -> float:
        ms = body.get("retry_after_ms") if isinstance(body, dict) else None
        if ms:
            return float(ms) / 1e3
        ra = resp_headers.get("Retry-After")
        if ra:
            try:
                return float(ra)
            except ValueError:
                pass
        return min(self.backoff_s * (2 ** attempt), self.max_backoff_s)

    def _request(self, method: str, path: str, body: dict | None = None,
                 retry: bool = True, headers: dict | None = None):
        """One exchange with retry-on-backpressure. Returns
        ``(status, headers, response, connection)``; the caller reads the
        body and closes the connection."""
        payload = json.dumps(body).encode() if body is not None else None
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        attempt = 0
        while True:
            conn, pooled = self._acquire()
            try:
                conn.request(method, path, body=payload, headers=hdrs)
                resp = conn.getresponse()
            except (OSError, http.client.BadStatusLine,
                    http.client.CannotSendRequest) as e:
                conn.close()
                if pooled:      # the server closed the idle keep-alive
                    continue    # socket between requests; replay fresh once
                raise e
            except Exception:
                conn.close()
                raise
            try:
                if retry and resp.status in _RETRYABLE \
                        and attempt < self.max_retries:
                    parsed = json.loads(resp.read() or b"{}")
                    delay = self._retry_delay(resp.headers, parsed, attempt)
                    self._done(conn, resp)
                    self.retries += 1
                    attempt += 1
                    time.sleep(delay)
                    continue
                return resp.status, resp.headers, resp, conn
            except Exception:
                conn.close()
                raise

    def _json_call(self, method: str, path: str, body: dict | None = None,
                   headers: dict | None = None) -> dict:
        status, _headers, resp, conn = self._request(method, path, body,
                                                     headers=headers)
        try:
            parsed = json.loads(resp.read() or b"{}")
            self._done(conn, resp)
        except Exception:
            conn.close()
            raise
        if status == 429:
            raise GatewayOverloaded(status, parsed)
        if status == 503:
            raise GatewayUnavailable(status, parsed)
        if status == 504:
            raise GatewayDeadline(status, parsed)
        if status != 200:
            raise GatewayError(status, parsed)
        return parsed

    # -- data plane ----------------------------------------------------------
    def generate(self, prompt, num_steps: int, temperature: float = 0.0,
                 seed: int | None = None, timeout_s: float | None = None,
                 stream: bool = False, on_token=None,
                 key_data=None, trace_id: str | None = None,
                 parent_span: str | None = None,
                 tenant: str | None = None,
                 adapter_id: str | None = None) -> dict:
        """One LM continuation. Returns the final reply dict (``tokens``
        plus the SLO numbers). ``stream=True`` reads the chunked NDJSON
        reply line by line, invoking ``on_token(index, token)`` as each
        arrives — the tokens list in the return value is assembled from
        the stream and identical to the non-streaming reply.
        ``key_data`` carries a pre-split PRNG key as raw uint32 words, so a
        caller that already folded its own key (the batch pump, a process
        replica relaying an in-thread submission) gets bit-identical
        sampling across the HTTP hop. ``trace_id`` rides the
        ``x-ddw-trace-id`` header — the server honors it (or mints one
        when tracing) and echoes it back in the reply. ``tenant`` names
        the quota/fair-share account this request bills to;
        ``adapter_id`` selects a hot-loaded LoRA adapter (absent = base
        model). A quota refusal comes back as the same 429 backoff shape
        as engine overload — the body names the tenant and resource."""
        body = {"prompt": [int(t) for t in prompt], "num_steps": num_steps,
                "temperature": temperature}
        if tenant is not None:
            body["tenant"] = tenant
        if adapter_id is not None:
            body["adapter_id"] = adapter_id
        if seed is not None:
            body["seed"] = seed
        if key_data is not None:
            body["key_data"] = [int(w) for w in key_data]
        if timeout_s is not None:
            body["timeout_s"] = timeout_s
        hdrs = {"x-ddw-trace-id": trace_id} if trace_id else None
        if parent_span:
            hdrs = dict(hdrs or {})
            hdrs["x-ddw-parent-span"] = parent_span
        if not stream:
            return self._json_call("POST", "/v1/generate", body,
                                   headers=hdrs)
        body["stream"] = True
        status, _headers, resp, conn = self._request(
            "POST", "/v1/generate", body, headers=hdrs)
        try:
            if status != 200:       # refused before the stream began
                parsed = json.loads(resp.read() or b"{}")
                if status == 429:
                    raise GatewayOverloaded(status, parsed)
                if status == 503:
                    raise GatewayUnavailable(status, parsed)
                if status == 504:
                    raise GatewayDeadline(status, parsed)
                raise GatewayError(status, parsed)
            tokens: list[int] = []
            final: dict = {}
            while True:
                line = resp.readline()   # http.client de-chunks for us
                if not line:
                    break
                row = json.loads(line)
                if "token" in row:
                    tokens.append(int(row["token"]))
                    if on_token is not None:
                        on_token(int(row["index"]), int(row["token"]))
                else:
                    final = row
                    break
            if "error" in final:     # mid-stream rejection rides the body
                raise GatewayError(200, final)
            final["tokens"] = tokens
            return final
        finally:
            conn.close()

    def predict(self, image, timeout_s: float | None = None,
                return_logits: bool = False) -> dict:
        body: dict = {"image": np_tolist(image)}
        if timeout_s is not None:
            body["timeout_s"] = timeout_s
        if return_logits:
            body["return_logits"] = True
        return self._json_call("POST", "/v1/predict", body)

    # -- batch lane ----------------------------------------------------------
    def submit_batch(self, items, kind: str = "generate",
                     num_steps: int | None = None, temperature: float = 0.0,
                     seed: int | None = None, window: int = 0) -> dict:
        """Submit a batch-lane job; returns ``{"job_id", "kind", "total"}``.
        The 429/503 backoff of :meth:`_request` applies to the submission
        itself; item-level retry lives server-side in the job's pump."""
        body: dict = {"kind": kind,
                      "items": [np_tolist(x) for x in items],
                      "temperature": temperature, "window": window}
        if num_steps is not None:
            body["num_steps"] = num_steps
        if seed is not None:
            body["seed"] = seed
        return self._json_call("POST", "/v1/batch", body)

    def batch_items(self, items, indices=None, kind: str = "generate",
                    num_steps: int | None = None, temperature: float = 0.0,
                    seed: int | None = None,
                    timeout_s: float | None = None) -> list[dict]:
        """Synchronous grouped submission (``POST /v1/batch/items``): the
        whole group runs on the ONE engine behind this gateway and the
        reply carries a per-row verdict — ``{"index", "ok": True, "row"}``
        or ``{"index", "ok": False, "error"}`` — so one refused item does
        not poison its groupmates. This is the wire form of the batch
        pump's per-replica grouping; ``indices`` are the caller's item
        indices (for rng folding and result placement), defaulting to
        ``0..n-1``."""
        body: dict = {"kind": kind,
                      "items": [np_tolist(x) for x in items],
                      "temperature": temperature}
        if indices is not None:
            body["indices"] = [int(i) for i in indices]
        if num_steps is not None:
            body["num_steps"] = num_steps
        if seed is not None:
            body["seed"] = seed
        if timeout_s is not None:
            body["timeout_s"] = timeout_s
        return self._json_call("POST", "/v1/batch/items", body)["rows"]

    def batch_status(self, job_id: str) -> dict:
        return self._json_call("GET", f"/v1/batch/{job_id}")

    def batch_cancel(self, job_id: str) -> dict:
        return self._json_call("DELETE", f"/v1/batch/{job_id}")

    def batch_results(self, job_id: str) -> list[dict]:
        """Completed rows (NDJSON body parsed), sorted by item index."""
        status, _h, resp, conn = self._request(
            "GET", f"/v1/batch/{job_id}/results")
        try:
            data = resp.read()
            self._done(conn, resp)
        except Exception:
            conn.close()
            raise
        if status != 200:
            raise GatewayError(status, json.loads(data or b"{}"))
        return [json.loads(line) for line in data.splitlines() if line]

    def batch_wait(self, job_id: str, timeout_s: float = 600.0,
                   poll_s: float = 0.25) -> dict:
        """Poll :meth:`batch_status` until the job is terminal; returns the
        final progress dict, raises ``TimeoutError`` otherwise."""
        deadline = time.monotonic() + timeout_s
        while True:
            st = self.batch_status(job_id)
            if st["state"] in ("done", "cancelled"):
                return st
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"batch job {job_id} not terminal after {timeout_s}s: "
                    f"{st}")
            time.sleep(poll_s)

    # -- control plane -------------------------------------------------------
    def healthz(self) -> dict:
        return self._json_call("GET", "/healthz")

    def deploy(self, model_dir: str, rollback: bool = True,
               strategy: str | None = None,
               canary_fraction: float | None = None,
               judge_window_s: float | None = None) -> dict:
        """Kick off a weight rollout (``POST /admin/deploy``). ``strategy``
        picks ``rolling`` (default) / ``canary`` / ``surge``;
        ``canary_fraction`` and ``judge_window_s`` tune the canary hold.
        Returns the initial deploy view; 409 (a rollout is already in
        flight) surfaces as :class:`GatewayError` with the live view in
        the body. Poll :meth:`stats` (the ``deploy`` block) for progress."""
        body: dict = {"model_dir": model_dir, "rollback": rollback}
        if strategy is not None:
            body["strategy"] = strategy
        if canary_fraction is not None:
            body["canary_fraction"] = canary_fraction
        if judge_window_s is not None:
            body["judge_window_s"] = judge_window_s
        return self._json_call("POST", "/admin/deploy", body)

    def adapters(self, op: str = "list", adapter_id: str | None = None,
                 path: str | None = None, alpha: float | None = None,
                 rank: int | None = None,
                 digest: str | None = None) -> dict:
        """Operate the fleet's LoRA adapter pool (``POST /admin/adapters``).
        ``op="load"`` stages the adapter at ``path`` onto every replica
        (shadow-probed, rolled back on any failure), ``op="unload"`` drops
        it fleet-wide, ``op="list"`` returns the per-replica residency
        view. 409 (a deploy holds the lock) surfaces as
        :class:`GatewayError` with the live deploy view in the body."""
        body: dict = {"op": op}
        if adapter_id is not None:
            body["adapter_id"] = adapter_id
        if path is not None:
            body["path"] = path
        if alpha is not None:
            body["alpha"] = alpha
        if rank is not None:
            body["rank"] = rank
        if digest is not None:
            body["digest"] = digest
        return self._json_call("POST", "/admin/adapters", body)

    def readyz(self) -> tuple[int, dict]:
        status, _h, resp, conn = self._request("GET", "/readyz",
                                               retry=False)
        try:
            body = json.loads(resp.read() or b"{}")
            self._done(conn, resp)
            return status, body
        except Exception:
            conn.close()
            raise

    def stats(self) -> dict:
        return self._json_call("GET", "/stats")

    def trace(self, replica: int | None = None, since: int = 0,
              chrome: bool = False) -> dict:
        """Fetch ``GET /v1/trace``: the merged fleet trace (default), the
        Perfetto-loadable Chrome form (``chrome=True``), or one replica's
        incremental relay feed (``replica=R, since=N`` — what a parent
        gateway polls on a child's gateway)."""
        if replica is not None:
            return self._json_call(
                "GET", f"/v1/trace?replica={replica}&since={since}")
        path = "/v1/trace?format=chrome" if chrome else "/v1/trace"
        return self._json_call("GET", path)

    def telemetry(self, replica: int | None = None, since: int = 0) -> dict:
        """Fetch ``GET /v1/telemetry``: the fleet's merged windowed
        aggregates + SLO status (default), or one replica's incremental
        sample feed (``replica=R, since=N`` — what a parent gateway's
        fleet store polls on a child's gateway)."""
        if replica is not None:
            return self._json_call(
                "GET", f"/v1/telemetry?replica={replica}&since={since}")
        return self._json_call("GET", "/v1/telemetry")

    def metrics_text(self) -> str:
        status, _h, resp, conn = self._request("GET", "/metrics")
        try:
            data = resp.read().decode()
            self._done(conn, resp)
        except Exception:
            conn.close()
            raise
        if status != 200:
            raise GatewayError(status, {"body": data})
        return data

    def wait_ready(self, timeout_s: float = 30.0) -> bool:
        """Poll ``/readyz`` until 200 (True) or the timeout (False) —
        what a load balancer health check does, for tests and tools."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                status, _ = self.readyz()
                if status == 200:
                    return True
            except OSError:
                pass                 # listener not even up yet
            time.sleep(0.02)
        return False


def np_tolist(image):
    """Accept a numpy array or nested lists for the predict payload."""
    return image.tolist() if hasattr(image, "tolist") else image
