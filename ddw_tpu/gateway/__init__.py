"""HTTP serving gateway — the network front door over engine replicas.

Layering (docs/serving.md "The HTTP gateway"):

- :mod:`~ddw_tpu.gateway.http` — ``Gateway``: stdlib ThreadingHTTPServer
  JSON API with chunked per-token streaming, 429/504 mapping from the
  engine's structured refusals;
- :mod:`~ddw_tpu.gateway.replica` — ``ReplicaSet``: least-outstanding
  routing across N engine replicas, one sideways retry on a full queue,
  fleet-merged metrics;
- :mod:`~ddw_tpu.gateway.lifecycle` — ``ServerLifecycle``: readiness gated
  on warmup, SIGTERM drain within the runtime layer's grace window;
- :mod:`~ddw_tpu.gateway.client` — ``GatewayClient``: reference client
  whose backoff honors ``Retry-After``.
"""

from ddw_tpu.gateway.client import (  # noqa: F401
    GatewayClient,
    GatewayDeadline,
    GatewayError,
    GatewayOverloaded,
    GatewayUnavailable,
)
from ddw_tpu.gateway.http import Gateway  # noqa: F401
from ddw_tpu.gateway.lifecycle import (  # noqa: F401
    DRAINING,
    READY,
    STARTING,
    STOPPED,
    ServerLifecycle,
    runtime_grace_s,
)
from ddw_tpu.gateway.replica import ReplicaSet  # noqa: F401
