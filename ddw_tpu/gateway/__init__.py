"""HTTP serving gateway — the network front door over engine replicas.

Layering (docs/serving.md "The HTTP gateway"):

- :mod:`~ddw_tpu.gateway.http` — ``Gateway``: stdlib ThreadingHTTPServer
  JSON API with chunked per-token streaming, keep-alive with a bounded
  connection guard, 429/503/504 mapping from the engine's structured
  refusals;
- :mod:`~ddw_tpu.gateway.replica` — ``ReplicaSet``: admission- and
  cache-aware routing across N engine replicas behind per-replica circuit
  breakers, one sideways retry on a full queue, failover of a dead
  replica's queued work, fleet-merged metrics;
- :mod:`~ddw_tpu.gateway.prefix_index` — ``PrefixIndex``: fleet-wide
  content-hash map of which replica holds which prompt prefix warm, fed
  by the pools' register/evict event logs; drives cache-aware routing and
  the supervisor's warm replay after recycle/deploy;
- :mod:`~ddw_tpu.gateway.supervisor` — ``ReplicaSupervisor``: bounded
  auto-restart of failed/stalled replicas with warmup-gated rejoin;
- :mod:`~ddw_tpu.gateway.lifecycle` — ``ServerLifecycle``: readiness gated
  on warmup (and on having live replicas), SIGTERM drain within the
  runtime layer's grace window;
- :mod:`~ddw_tpu.gateway.client` — ``GatewayClient``: reference client
  whose backoff honors ``Retry-After`` and reuses keep-alive connections.
"""

from ddw_tpu.gateway.client import (  # noqa: F401
    GatewayClient,
    GatewayDeadline,
    GatewayError,
    GatewayOverloaded,
    GatewayUnavailable,
)
from ddw_tpu.gateway.http import Gateway  # noqa: F401
from ddw_tpu.gateway.lifecycle import (  # noqa: F401
    DRAINING,
    READY,
    STARTING,
    STOPPED,
    ServerLifecycle,
    runtime_grace_s,
)
from ddw_tpu.gateway.prefix_index import (  # noqa: F401
    PrefixIndex,
    chain_hash_hexes,
)
from ddw_tpu.gateway.replica import (  # noqa: F401
    CIRCUIT_CLOSED,
    CIRCUIT_HALF_OPEN,
    CIRCUIT_OPEN,
    CircuitBreaker,
    ReplicaSet,
)
from ddw_tpu.gateway.supervisor import (  # noqa: F401
    ReplicaAttempt,
    ReplicaSupervisor,
)
