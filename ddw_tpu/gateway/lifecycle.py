"""Serving lifecycle — readiness gating and graceful drain.

A serving process has exactly four states a load balancer cares about, and
the transitions between them are where requests get dropped if nobody owns
them. This module owns them:

::

    STARTING ──warmup done──► READY ──SIGTERM/drain()──► DRAINING ──► STOPPED
       │                        │                           │
    /healthz 200            /readyz 200                /readyz 503
    /readyz 503             admit requests             refuse new (503),
                                                       finish in-flight

- **readiness is gated on warmup**: the HTTP listener comes up first (so
  ``/healthz`` answers and orchestrators don't kill a compiling process),
  but ``/readyz`` stays 503 and requests are refused until every replica's
  program lattice is compiled — no live request ever pays XLA compile time
  behind a load balancer that believed the pod was ready.
- **drain is the serving half of preemption**: the same SIGTERM contract
  the runtime layer gives training gangs (``Launcher.preempt_grace_s``
  forwards the signal and allows a grace window to checkpoint —
  docs/fault_tolerance.md) applies to serving: stop admission immediately
  (new requests see 503 + ``Retry-After`` so the balancer respills them),
  let in-flight slots run to completion within the grace window, then stop
  the engines. :func:`runtime_grace_s` reads the default straight from the
  runtime layer so the two drains cannot drift apart silently.

The in-flight ledger is a plain counted critical section
(:meth:`ServerLifecycle.try_begin_request` / :meth:`end_request`) held for
the WHOLE response — including the chunked streaming tail — so
``await_drained`` returning True means every byte of every admitted
response has been written, not merely that the engines went idle.
"""

from __future__ import annotations

import inspect
import signal
import threading

__all__ = ["ServerLifecycle", "runtime_grace_s",
           "STARTING", "READY", "DRAINING", "STOPPED"]

STARTING = "starting"
READY = "ready"
DRAINING = "draining"
STOPPED = "stopped"


def runtime_grace_s() -> float:
    """The runtime layer's preemption grace window
    (``Launcher.preempt_grace_s`` default) — read from the signature so the
    serving drain and the training-gang drain share one number by
    construction."""
    from ddw_tpu.runtime.launcher import Launcher

    return float(inspect.signature(Launcher.__init__)
                 .parameters["preempt_grace_s"].default)


class ServerLifecycle:
    """State machine + in-flight request ledger for one serving process.

    ``health_fn`` (optional, set by the gateway) reports fleet degradation
    *within* READY: a process whose replicas are partially dead is still
    ready — it serves on the survivors — but a load balancer weighing
    backends and an operator reading ``/readyz`` both want the distinction,
    so :meth:`readiness` carries it alongside the FSM state. The same
    principle as warmup gating: readiness tells the truth about what is
    behind the socket."""

    def __init__(self, grace_s: float | None = None):
        self.grace_s = runtime_grace_s() if grace_s is None else grace_s
        self._cv = threading.Condition()
        self._state = STARTING
        self._inflight = 0
        self._prev_sigterm = None
        self.health_fn = None       # () -> list[per-replica health dicts]

    # -- state ---------------------------------------------------------------
    @property
    def state(self) -> str:
        with self._cv:
            return self._state

    @property
    def is_ready(self) -> bool:
        return self.state == READY

    def readiness(self) -> tuple[bool, dict]:
        """The /readyz truth: (ready, body). Ready as long as the process
        is READY and at least one replica can take traffic; the body names
        the degradation (replicas up / total) so a fleet running on
        survivors is visible without scraping /metrics."""
        state = self.state
        body: dict = {"status": "ready" if state == READY else state}
        if self.health_fn is None:
            return state == READY, body
        try:
            health = self.health_fn()
        except Exception:
            return state == READY, body
        up = sum(1 for h in health
                 if h.get("state") in ("alive", "degraded"))
        body["replicas_up"] = up
        body["replicas"] = len(health)
        if up < len(health):
            body["degraded"] = True
        if state == READY and health and up == 0:
            # every replica is dead: admitting traffic would only shed —
            # tell the balancer to send it elsewhere until one rejoins
            body["status"] = "no_replicas"
            return False, body
        return state == READY, body

    def mark_ready(self) -> None:
        with self._cv:
            if self._state == STARTING:
                self._state = READY

    def begin_drain(self) -> bool:
        """Stop admission. Returns False if drain already began."""
        with self._cv:
            if self._state in (DRAINING, STOPPED):
                return False
            self._state = DRAINING
            self._cv.notify_all()
            return True

    def mark_stopped(self) -> None:
        with self._cv:
            self._state = STOPPED
            self._cv.notify_all()

    # -- in-flight ledger ----------------------------------------------------
    def try_begin_request(self) -> bool:
        """Admit one request into the in-flight ledger; False means refuse
        (not ready yet, or draining) — the caller answers 503."""
        with self._cv:
            if self._state != READY:
                return False
            self._inflight += 1
            return True

    def end_request(self) -> None:
        with self._cv:
            self._inflight -= 1
            if self._inflight == 0:
                self._cv.notify_all()

    @property
    def inflight(self) -> int:
        with self._cv:
            return self._inflight

    def await_drained(self, timeout_s: float | None = None) -> bool:
        """Block until every admitted response has fully written (the
        ledger hits zero) or the grace window runs out. True = clean."""
        deadline = timeout_s if timeout_s is not None else self.grace_s
        with self._cv:
            return self._cv.wait_for(lambda: self._inflight == 0,
                                     timeout=deadline)

    # -- SIGTERM wiring ------------------------------------------------------
    def install_sigterm(self, drain_fn) -> None:
        """Route SIGTERM to ``drain_fn`` (run on a fresh thread — signal
        handlers must not block, and the drain waits out the grace window).
        Main-thread only, like every signal.signal call; the previous
        handler is kept for :meth:`restore_sigterm`."""
        def _handler(_sig, _frame):
            threading.Thread(target=drain_fn, name="ddw-gateway-drain",
                             daemon=True).start()

        self._prev_sigterm = signal.signal(signal.SIGTERM, _handler)

    def restore_sigterm(self) -> None:
        """Best-effort: a drain triggered BY the signal runs off the main
        thread, where re-installing handlers is forbidden — keep the saved
        handler so a main-thread caller (test teardown) can retry."""
        if self._prev_sigterm is not None:
            try:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
            except ValueError:
                return               # not the main thread; handler kept
            self._prev_sigterm = None
