"""Host/device utilization sampler — the Ganglia role (SURVEY.md §2c/§5).

The reference points users at Ganglia cluster dashboards to diagnose
under-utilization (``Part 1 - Distributed Training/04_monitoring_and_optimization.py:25-29``).
TPU-native equivalent: an in-process background sampler that records host CPU /
RAM and device HBM usage as ``sys.*`` metric series into the tracker run, so
utilization lives next to the training curves instead of on a separate platform
dashboard.

Samples are cheap (psutil counters + PJRT ``memory_stats``); the default 10 s
cadence adds no measurable overhead to a training loop. Used by the Trainer when
``TrainCfg.monitor_interval_s > 0`` (process 0 only — the rank-0-writer
discipline, SURVEY §5).
"""

from __future__ import annotations

import threading
import time
from typing import Any

try:
    import psutil

    # psutil.cpu_percent(interval=None) returns 0.0 on its first call in a
    # process (no prior sample to diff against); prime it so real samples
    # never report that placeholder.
    psutil.cpu_percent(interval=None)
except ImportError:  # pragma: no cover - psutil is in the base image
    psutil = None


def sample_system(device=None) -> dict[str, float]:
    """One utilization snapshot. Keys are stable; device entries appear only
    when the backend reports memory statistics (TPU does, CPU does not)."""
    out: dict[str, float] = {}
    if psutil is not None:
        out["sys.host_cpu_percent"] = float(psutil.cpu_percent(interval=None))
        vm = psutil.virtual_memory()
        out["sys.host_mem_percent"] = float(vm.percent)
        out["sys.host_mem_used_gb"] = vm.used / 2**30
        out["sys.proc_rss_gb"] = psutil.Process().memory_info().rss / 2**30
    if device is None:
        import jax

        device = jax.local_devices()[0]
    stats: Any = None
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats:
        if "bytes_in_use" in stats:
            out["sys.device_hbm_used_gb"] = stats["bytes_in_use"] / 2**30
        if "bytes_limit" in stats:
            out["sys.device_hbm_limit_gb"] = stats["bytes_limit"] / 2**30
            if stats["bytes_limit"]:
                out["sys.device_hbm_percent"] = (
                    100.0 * stats.get("bytes_in_use", 0) / stats["bytes_limit"])
    return out


class SystemMonitor:
    """Background thread logging ``sample_system()`` into a tracker run every
    ``interval_s`` seconds. Use as a context manager around the training loop."""

    def __init__(self, run, interval_s: float = 10.0, device=None):
        self.run = run
        self.interval_s = interval_s
        self.device = device
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._n = 0

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                metrics = sample_system(self.device)
                if self.run is not None and metrics:
                    self.run.log_metrics(metrics, step=self._n)
                self._n += 1
            except Exception:
                pass  # sampling must never take down training
            self._stop.wait(self.interval_s)

    def start(self) -> "SystemMonitor":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="ddw-sysmon", daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            if not self._thread.is_alive():
                self._thread = None
            # else: keep the handle so a restart can't spawn a second
            # concurrent sampler double-logging into the run

    def __enter__(self) -> "SystemMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
