"""jax API-surface compatibility shims.

The codebase targets the modern public jax surface; older jax spells two of
the primitives it leans on differently. One import site keeps every step
builder and collective working across both, resolved once at import time:

- ``shard_map``: public ``jax.shard_map`` (replication checking via
  ``check_vma``) vs ``jax.experimental.shard_map.shard_map`` (``check_rep``).
- ``axis_size``: ``jax.lax.axis_size(name)`` vs ``jax.core.axis_frame(name)``
  (which returns the static mesh-axis extent on the older surface).
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma)


if hasattr(jax.lax, "axis_size"):
    def axis_size(axis_name) -> int:
        """Static extent of a bound mesh/pmap axis, inside the mapped fn."""
        return jax.lax.axis_size(axis_name)
else:
    def axis_size(axis_name) -> int:
        """Static extent of a bound mesh/pmap axis, inside the mapped fn."""
        return int(jax.core.axis_frame(axis_name))
