"""Typed config tree + CLI overrides.

The reference's configuration story is three ad-hoc idioms (SURVEY.md §5 "Config / flag
system"): module-level UPPERCASE globals per notebook
(reference ``Part 1 - Distributed Training/02_model_training_single_node.py:41-46``),
env bootstrap (``00_setup.py:3-17``), and exactly one typed dataclass, ``DataCfg``
(``Part 2 - Distributed Tuning & Inference/03_pyfunc_distributed_inference.py:85-95``).
We generalize the dataclass idiom into a small config tree with dotted-path CLI
overrides (``train.batch_size=256``), which every example script and the trainer share.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any


@dataclass
class DataCfg:
    """Dataset + preprocessing config.

    Mirrors the reference ``DataCfg``
    (``03_pyfunc_distributed_inference.py:85-95``: img height/width, batch sizes) and
    the data-prep constants (``01_data_prep.py:61-66,162``: 50% sample, 90/10 split,
    seed 42).
    """

    table_root: str = "/tmp/ddw_tpu/tables"
    source_dir: str = ""                # raw JPEG class-dir tree (tf_flowers layout)
    img_height: int = 224
    img_width: int = 224
    channels: int = 3
    sample_fraction: float = 0.5        # reference samples 50% of the raw images
    train_fraction: float = 0.9         # 90/10 split
    split_seed: int = 42                # reference seed
    shard_size: int = 256               # records per shard file in the table store
    shuffle_buffer: int = 1024
    prefetch: int = 2                   # host->device double buffering depth
    loader_workers: int = 4             # decode thread pool (petastorm workers_count role)

    @property
    def image_shape(self) -> tuple[int, int, int]:
        return (self.img_height, self.img_width, self.channels)


@dataclass
class ModelCfg:
    """Model factory config.

    The reference model: MobileNetV2 ImageNet-pretrained frozen base + GAP ->
    Dropout(0.5) -> Dense(num_classes) head
    (``02_model_training_single_node.py:159-178``).
    """

    name: str = "mobilenet_v2"          # key into ddw_tpu.models.registry
    num_classes: int = 5
    dropout: float = 0.5
    freeze_base: bool = True            # transfer-learning mode: only the head trains
    width_mult: float = 1.0
    num_heads: int = 0                  # attention heads (ViT); 0 = model default.
                                        # Param shapes depend on it — set it when
                                        # restoring a package saved with a
                                        # non-default head count.
    hidden: int = 0                     # encoder width (ViT); 0 = model default
                                        # (192). The v5e MXU is a 128x128 array:
                                        # hidden=256 with num_heads=2 puts every
                                        # projection and attention dot on full
                                        # 128-wide tiles (tools/mxu_roofline.py
                                        # quantifies the default's 59% ceiling).
                                        # Param shapes depend on it — set it when
                                        # restoring a non-default package.
    pretrained_path: str = ""           # optional converted-weights artifact
    allow_frozen_random: bool = False   # opt-in: keep freeze_base=True even with
                                        # no pretrained_path (build_model otherwise
                                        # auto-unfreezes — a frozen random backbone
                                        # trains the head over noise). For
                                        # mechanism tests and throughput benches.
    bn_momentum: float = 0.9            # BatchNorm running-stat momentum. Default
                                        # 0.9 suits short from-scratch runs; set
                                        # 0.99 (the Keras MobileNetV2 value) for
                                        # parity runs finetuning an unfrozen
                                        # pretrained base.
    dtype: str = "bfloat16"             # compute dtype on the MXU; params stay f32
    stem_s2d: bool = False              # compute the stride-2 stem conv via 2x2
                                        # space-to-depth (identical math, same
                                        # params; deepens the MXU contraction
                                        # over the 3-channel image input).
                                        # CNN families only (mobilenet/resnet).
    dw_impl: str = "xla"                # depthwise-conv implementation for the
                                        # MobileNet family: "xla" grouped conv
                                        # or "pallas" (in-tree VMEM-resident
                                        # kernel, ddw_tpu.ops.depthwise_conv;
                                        # stride-2 layers stay on XLA)
    lora_rank: int = 0                  # >0 (ViT): rank-r LoRA adapters on
                                        # lora_targets; the trainer freezes
                                        # everything but adapters+head
                                        # (mutually exclusive w/ freeze_base)
    lora_alpha: float = 16.0
    lora_targets: tuple[str, ...] = ("query", "value")


@dataclass
class TrainCfg:
    """Training loop + distribution config.

    Mirrors the single-node constants (batch 32, 3 epochs, Adam 1e-3,
    ``02_model_training_single_node.py:45-46,201-203``) and the distributed contract
    (batch 256/worker, LR x world, 5-epoch warmup, plateau patience 10,
    ``03_model_training_distributed.py:81-82,301,318-321``).
    """

    batch_size: int = 32                # per-worker batch (reference semantics)
    epochs: int = 3
    optimizer: str = "adam"             # adam | adamw | adadelta | sgd
                                        # (HPO space includes Adadelta)
    learning_rate: float = 1e-3
    weight_decay: float = 0.0           # adamw decoupled weight decay
    grad_clip_norm: float = 0.0         # >0: clip grads by global norm before
                                        # the optimizer update
    scale_lr_by_world: bool = True      # Adam(0.001 * hvd.size()) semantics
    warmup_epochs: int = 5              # LearningRateWarmupCallback(warmup_epochs=5)
    plateau_patience: int = 10          # ReduceLROnPlateau(patience=10)
    plateau_factor: float = 0.5
    lr_schedule: str = "plateau"        # "plateau" (reference semantics) or
                                        # "cosine" (per-batch half-cycle decay
                                        # after warmup; plateau callback off)
    cosine_final_lr_frac: float = 0.0   # cosine floor as a fraction of the
                                        # scaled target LR
    ema_decay: float = 0.0              # >0: Polyak shadow of the params in
                                        # the opt state (train/step.EmaState);
                                        # the trainer evaluates with the
                                        # shadow; read it via
                                        # ddw_tpu.train.step.ema_params
    early_stop_patience: int = 0        # 0 = disabled; pyfunc notebook uses 3
    seed: int = 0
    grad_accum_steps: int = 1           # >1: split each per-worker batch into N
                                        # sequential microbatches inside the jitted
                                        # step (lax.scan), accumulating gradients —
                                        # same optimizer math, 1/N activation
                                        # memory; batches far beyond HBM fit.
    steps_per_dispatch: int = 1         # >1: fuse K optimizer steps into ONE
                                        # jitted program (lax.scan over a
                                        # stacked [K, B, ...] super-batch the
                                        # loader assembles on device;
                                        # train/step.make_train_chain) — ~1/K
                                        # the host dispatches and metric
                                        # fetches; same training result.
                                        # Fault hooks, preemption checks and
                                        # per-batch LR writes move to chain
                                        # boundaries (docs/performance.md).
                                        # Composes with grad_accum_steps and
                                        # zero/fsdp; refused with
                                        # pipeline_stages (the pipeline step
                                        # already fuses its microbatches).
    moment_dtype: str = "float32"       # "bfloat16": store Adam/SGD first
                                        # moments (mu) in bf16 — halves mu
                                        # bytes; nu stays f32 (feeds rsqrt).
                                        # adadelta refuses (both its
                                        # accumulators are nu-like)
    data_axis: str = "data"             # mesh axis name for DP psum
    num_devices: int = 0                # 0 = all visible devices
    zero: bool = False                  # ZeRO-1: shard optimizer moments over
                                        # the data axis (parallel/zero.py);
                                        # checkpoints switch to the sharded
                                        # per-process format (no full gather).
                                        # Composes with grad_accum_steps and
                                        # with async_checkpoint (per-process
                                        # background writers run the same
                                        # collective commit protocol).
    fsdp: bool = False                  # ZeRO-3/FSDP: shard params AND
                                        # optimizer state over the data axis
                                        # (~1/N model residency per device;
                                        # GSPMD inserts per-layer all-gathers).
                                        # Same checkpoint format and flag
                                        # incompatibilities as zero; zero and
                                        # fsdp are mutually exclusive.
    pipeline_stages: int = 0            # >0: LMTrainer trains the LM through
                                        # the pipeline step (parallel/
                                        # pipeline.py) over a (data, pipe)
                                        # mesh — pipe=stages, data absorbs
                                        # the remaining devices. Requires
                                        # lm.dropout == 0 and divides depth.
    pipeline_schedule: str = "gpipe"    # "gpipe" | "interleaved" (virtual
                                        # stages; ~v-fold smaller bubble,
                                        # microbatches <= stages)
    pipeline_microbatches: int = 4      # must divide the per-replica batch
    pipeline_virtual_stages: int = 2    # interleaved only: chunks per device
    checkpoint_dir: str = ""            # "" = no per-epoch checkpoints
    async_checkpoint: bool = False      # serialize+write checkpoints on a
                                        # background thread (device snapshot is
                                        # still synchronous) so IO overlaps the
                                        # next epoch's compute; works for the
                                        # classic AND the sharded (zero/fsdp)
                                        # formats
    async_checkpoint_inflight: int = 2  # bounded async write queue depth: a
                                        # save blocks only past this many
                                        # outstanding writes, so one slow
                                        # fsync never stalls a chain boundary
                                        # (1 = join-previous-before-new)
    checkpoint_every_epochs: int = 1
    checkpoint_keep_best: bool = False  # also keep the single best-val_loss
                                        # state under <checkpoint_dir>/best
                                        # (model selection; the resume stream's
                                        # newest-K retention would prune it)
    log_every_steps: int = 10
    trace_dir: str = ""                 # --trace flag role (jax.profiler), SURVEY §5
    debug_cross_host_checks: bool = False  # SPMD consistency sanitizer, SURVEY §5
    monitor_interval_s: float = 0.0     # >0: sys.* utilization sampler into the
                                        # tracker (Ganglia role, SURVEY §5)


@dataclass
class LMCfg:
    """Decoder-only LM config (:class:`ddw_tpu.models.lm.TransformerLM`).

    Not a reference-parity item (the reference has no language model — SURVEY.md
    §5 "Long-context ... Absent"); this is the long-context model family, trained
    via the DPxSP step in :mod:`ddw_tpu.train.lm_step`.
    """

    vocab_size: int = 256
    max_len: int = 2048                 # global sequence length bound
    hidden: int = 256
    depth: int = 4
    num_heads: int = 4
    mlp_dim: int = 1024
    dropout: float = 0.0
    dtype: str = "bfloat16"
    num_experts: int = 0                # >0: MoE MLP blocks
    capacity_factor: float = 1.25       # static expert capacity = cf*k*T/E
    moe_router: str = "top1"            # "top1" (Switch) or "top2" (GShard:
                                        # two experts/token, renormalized
                                        # pair gates)
    num_kv_heads: int = 0               # GQA: KV heads (0 = num_heads / MHA).
                                        # Shrinks k/v params and the decode
                                        # KV cache by num_heads/num_kv_heads;
                                        # K/V broadcast per query group at
                                        # compute
    lora_rank: int = 0                  # >0: rank-r LoRA adapters on
                                        # lora_targets (ddw_tpu.models.lora);
                                        # train with lora_optimizer so only
                                        # adapters (+head) update
    lora_alpha: float = 16.0
    lora_targets: tuple[str, ...] = ("query", "value")
    pos_encoding: str = "learned"       # "learned" absolute table or "rope"
                                        # rotary relative positions
                                        # (ddw_tpu.ops.rope — extrapolates
                                        # past max_len, SP/decode-composable)
    remat: str = "none"                 # per-block activation remat: "full"
                                        # (keep nothing; recompute block in
                                        # bwd) or "dots" (keep matmul outputs)
                                        # — long contexts past HBM at ~1/3
                                        # more FLOPs; decode unaffected


@dataclass
class TuneCfg:
    """Hyperparameter-search config.

    Mirrors fmin(max_evals=20, SparkTrials(parallelism=4))
    (``01_hyperopt_single_machine_model.py:226-238``) and the sequential distributed
    mode (``02_hyperopt_distributed_model.py:341-365``).
    """

    max_evals: int = 20
    parallelism: int = 4                # >1 = parallel trial executor; 1 = sequential
    seed: int = 0
    algo: str = "tpe"                   # tpe | random
    n_startup_trials: int = 5           # random trials before TPE kicks in
    gamma: float = 0.25                 # TPE good/bad split quantile
    prune: bool = False                 # trial pruning (beyond hyperopt):
                                        # stop hopeless trials early on their
                                        # per-epoch val_loss
    pruner: str = "median"              # "median" (Vizier/Optuna rule) or
                                        # "asha" (async successive halving)
    prune_warmup_epochs: int = 1        # median: never prune below this epoch
    prune_min_trials: int = 3           # median: peers needed before trusted
    asha_min_resource: int = 1          # asha: first rung (epochs)
    asha_reduction_factor: int = 3      # asha: eta — top 1/eta survive a rung


_TYPES = {"data": DataCfg, "model": ModelCfg, "train": TrainCfg, "tune": TuneCfg,
          "lm": LMCfg}


def require_tpu_or_exit(verb: str = "measure") -> str:
    """The one DDW_REQUIRE_TPU refusal contract every measurement tool and
    chip_queue.sh attempt accounting depend on: when the flag is set and the
    backend is not a TPU (axon fell back to CPU — tunnel down at connect),
    print the refusal to stderr and exit 4. Returns the device kind."""
    import sys

    import jax

    kind = jax.devices()[0].device_kind
    if env_flag("DDW_REQUIRE_TPU") and "TPU" not in kind:
        print(f"DDW_REQUIRE_TPU set but backend is {kind!r} (axon fell back "
              f"to CPU — tunnel down at connect); refusing to {verb}",
              file=sys.stderr)
        sys.exit(4)
    return kind


def env_flag(name: str) -> bool:
    """Boolean environment flag shared by bench.py and the perf tools.

    Accepts the common spellings both ways; anything else raises — a typo
    must not silently flip a flag in either direction (enabling
    DDW_BENCH_SMOKE degrades measurements; disabling DDW_REQUIRE_TPU records
    CPU timings as chip results)."""
    import os

    val = os.environ.get(name, "").strip().lower()
    if val in ("", "0", "false", "no", "off"):
        return False
    if val in ("1", "true", "yes", "on"):
        return True
    raise ValueError(f"{name} must be a boolean flag "
                     f"(1/true/yes/on or 0/false/no/off), got {val!r}")


def vit_geometry_env() -> dict:
    """``DDW_BENCH_VIT_HIDDEN`` / ``DDW_BENCH_VIT_HEADS`` → ModelCfg kwargs.

    The ONE parser for the tile-geometry A/B knobs, shared by ``bench.py``
    (the chip arm) and ``tools/attn_dispatch_evidence.py`` (the offline
    lowering ``tools/mxu_roofline.py`` analyzes) — the two must describe the
    same program by construction, not by hand-synced duplication. Empty or
    unset vars mean "model default"."""
    import os

    geo = {}
    if os.environ.get("DDW_BENCH_VIT_HIDDEN", "").strip():
        geo["hidden"] = int(os.environ["DDW_BENCH_VIT_HIDDEN"])
    if os.environ.get("DDW_BENCH_VIT_HEADS", "").strip():
        geo["num_heads"] = int(os.environ["DDW_BENCH_VIT_HEADS"])
    return geo


def lm_heads_env(default: int) -> int:
    """``DDW_BENCH_LM_HEADS`` override (tile-geometry A/B arm), shared like
    :func:`vit_geometry_env`. Empty or unset means ``default``."""
    import os

    val = os.environ.get("DDW_BENCH_LM_HEADS", "").strip()
    return int(val) if val else default


def apply_overrides(cfgs: dict[str, Any], overrides: list[str]) -> dict[str, Any]:
    """Apply ``section.key=value`` CLI overrides to a dict of config dataclasses.

    Values parse as JSON when possible (``train.batch_size=256`` -> int), else string.
    """
    for ov in overrides:
        if "=" not in ov or "." not in ov.split("=", 1)[0]:
            raise ValueError(f"override must look like section.key=value, got {ov!r}")
        path, raw = ov.split("=", 1)
        section, key = path.split(".", 1)
        if section not in cfgs:
            raise KeyError(f"unknown config section {section!r} (have {sorted(cfgs)})")
        cfg = cfgs[section]
        if not hasattr(cfg, key):
            raise KeyError(f"{type(cfg).__name__} has no field {key!r}")
        try:
            val = json.loads(raw)
        except json.JSONDecodeError:
            val = raw
        setattr(cfg, key, val)
    return cfgs


def to_dict(cfg: Any) -> dict[str, Any]:
    """Flatten a dataclass config to a JSON-able dict (for tracker param logging)."""
    return dataclasses.asdict(cfg)


def default_cfgs() -> dict[str, Any]:
    return {name: typ() for name, typ in _TYPES.items()}
