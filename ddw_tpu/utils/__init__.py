from ddw_tpu.utils.config import DataCfg, ModelCfg, TrainCfg, TuneCfg, apply_overrides  # noqa: F401
