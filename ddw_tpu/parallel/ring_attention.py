"""Ring attention — sequence/context parallelism over a ``seq`` mesh axis.

Not in the reference (its workload is a CNN; SURVEY.md §2d marks SP "not required
for parity"), but long-context is first-class here: this is the component that
lets attention scale past one device's memory by sharding the *sequence* axis.

Algorithm (Liu et al. 2023, blockwise ring attention): each of the N devices on
the ``seq`` axis holds Q/K/V shards of S/N tokens. Q stays put; K/V shards rotate
around the ring N times via ``ppermute`` (ICI neighbor exchange). Each hop, every
device attends its local Q against the visiting K/V block (blockwise XLA-fused
attention; block = the shard) and folds the result into a running (max,
normalizer, accumulator) — the same online softmax as the flash kernel, lifted to
the ring level, so the full S×S score matrix never exists anywhere. Communication overlaps compute under XLA's
scheduler; per-hop cost is the local block attention plus one neighbor exchange.

Causal masking works on *global* positions: rank r's Q block has offset r*S/N and
the visiting K block carries its own source offset — passed through to the local
kernel (``q_offset``/``k_offset``), so blocks that are entirely in the future are
fully masked and contribute exp(-inf)=0.

Use under ``shard_map`` with in_specs splitting the sequence dim over ``seq``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: float | None = None) -> jnp.ndarray:
    """Blockwise ring attention over ``axis_name``.

    Per-device shapes: q/k/v [B, H, S_local, D] (the local sequence shard);
    returns the local shard of the attention output. Must be called inside
    ``shard_map``/``pmap`` binding ``axis_name``.
    """
    n = lax.axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / float(d) ** 0.5

    # Running online-softmax state over ring hops, in f32. The per-hop local
    # attention is the blockwise jnp formulation (block = the S/N shard; XLA
    # fuses it); the Pallas flash kernel is the single-device fast path and can
    # slot in per-hop once it also returns (m, l) for the cross-hop combine.
    m = jnp.full((b, h, s_local, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_local, 1), jnp.float32)
    acc = jnp.zeros((b, h, s_local, d), jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = k, v
    q32 = q.astype(jnp.float32)
    q_off = me * s_local

    @jax.checkpoint
    def hop_update(m, l, acc, k_hop, v_hop, k_off):
        """One hop's blockwise-softmax fold. ``jax.checkpoint`` drops the
        S_local x S_local score/prob intermediates from the residuals —
        without it autodiff saves them for every hop (O(S_local * S_global)
        memory, exactly the blowup ring attention exists to avoid) and
        rematerializes them during backward instead."""
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_hop.astype(jnp.float32)) * sm_scale
        if causal:
            qpos = q_off + jnp.arange(s_local)[:, None]
            kpos = k_off + jnp.arange(s_local)[None, :]
            s = jnp.where((kpos <= qpos), s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                           v_hop.astype(jnp.float32))
        return m_new, l_new, acc_new

    for hop in range(n):
        src = (me - hop) % n                 # which rank's K/V block is visiting
        m, l, acc = hop_update(m, l, acc, k_cur, v_cur, src * s_local)
        if hop != n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)
