"""Ring attention — sequence/context parallelism over a ``seq`` mesh axis.

Not in the reference (its workload is a CNN; SURVEY.md §2d marks SP "not required
for parity"), but long-context is first-class here: this is the component that
lets attention scale past one device's memory by sharding the *sequence* axis.

Algorithm (Liu et al. 2023, blockwise ring attention): each of the N devices on
the ``seq`` axis holds Q/K/V shards of S/N tokens. Q stays put; K/V shards rotate
around the ring N times via ``ppermute`` (ICI neighbor exchange). Each hop, every
device runs the Pallas flash kernel (:func:`ddw_tpu.ops.flash_attention
.flash_attention_lse`) on its local Q against the visiting K/V block — O(S_local)
VMEM, the S_local x S_local score matrix never exists even per hop — and folds
the hop's (out, logsumexp) into a running softmax combine, the same online
softmax as inside the kernel, lifted to the ring level. Communication overlaps
compute under XLA's scheduler; per-hop cost is one flash call plus one neighbor
exchange.

Causal masking works on *global* positions, resolved per hop into one of three
static cases (the visiting block's offset relative to ours is ``me - hop``):
  - hop 0: the diagonal block -> causal flash with equal offsets;
  - visiting block strictly in the past (``hop <= me``) -> full (non-causal)
    flash, no mask;
  - visiting block strictly in the future -> fully masked; the hop is SKIPPED
    via ``lax.cond`` (the old einsum formulation paid full price to multiply
    by an all -inf mask).
This keeps the kernel's offsets static (Pallas grid masking needs Python ints)
while the rank-dependent choice stays dynamic.

Gradient path: ``flash_attention_lse``'s custom VJP carries cotangents for both
the output and the logsumexp, so the cross-hop combine backpropagates exactly
(the hop-vs-full equivalence test pins fwd AND grads). Residual memory is the
per-hop K/V copies (O(S_global) across hops per device — same as the forward
K/V rotation); the S^2 matrices never exist in any pass.

Use under ``shard_map`` with in_specs splitting the sequence dim over ``seq``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ddw_tpu.utils.compat import axis_size

from ddw_tpu.ops.flash_attention import flash_mha_lse

_NEG_INF = -1e30


def _combine(o1, lse1, o2, lse2):
    """Softmax-combine two partial attentions over disjoint key sets.

    Each o_i is normalized over its own keys with logsumexp lse_i; the combined
    result over the union is a convex combination weighted by exp(lse_i - lse).
    Safe at lse = -inf sentinels: logaddexp keeps the max's scale, weights stay
    finite, and an all-masked row yields the zero vector."""
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    return o1 * w1 + o2 * w2, lse


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: float | None = None,
                   block_q: int = 128, block_k: int = 128,
                   impl: str = "auto") -> jnp.ndarray:
    """Blockwise ring attention over ``axis_name``.

    Per-device shapes: q/k/v [B, H, S_local, D] (the local sequence shard);
    returns the local shard of the attention output. Must be called inside
    ``shard_map``/``pmap`` binding ``axis_name``. ``impl`` selects the per-hop
    attention arm (``auto``/``xla``/``xla_ckpt``/``pallas`` — see
    :func:`ddw_tpu.ops.flash_attention.flash_mha_lse`): auto picks by the
    LOCAL S_local x S_local score footprint, so moderate shards get the fused
    XLA arm and long-context shards the Pallas flash kernel.
    """
    n = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / float(d) ** 0.5

    perm = [(i, (i + 1) % n) for i in range(n)]
    k_cur, v_cur = k, v

    # Running combined (out f32, lse f32) over ring hops.
    out = jnp.zeros((b, h, s_local, d), jnp.float32)
    lse = jnp.full((b, h, s_local), _NEG_INF, jnp.float32)

    def flash(k_hop, v_hop, hop_causal):
        # flash_mha_lse pads non-tile-multiple s_local internally, so any
        # shard length works (parity with the einsum formulation it replaced).
        o, l = flash_mha_lse(q, k_hop, v_hop, hop_causal, sm_scale,
                             block_q, block_k, impl=impl)
        return o.astype(jnp.float32), l

    for hop in range(n):
        # Visiting block is rank (me - hop) % n's shard. Relative position in
        # the global order: hop 0 = our own (diagonal), otherwise strictly past
        # iff hop <= me, strictly future iff hop > me.
        if causal and hop == 0:
            o_h, lse_h = flash(k_cur, v_cur, True)
            out, lse = _combine(out, lse, o_h, lse_h)
        elif causal:
            def _attend(args):
                out, lse, k_hop, v_hop = args
                o_h, lse_h = flash(k_hop, v_hop, False)
                return _combine(out, lse, o_h, lse_h)

            def _skip(args):
                out, lse, _, _ = args
                return out, lse

            out, lse = lax.cond(hop <= me, _attend, _skip,
                                (out, lse, k_cur, v_cur))
        else:
            o_h, lse_h = flash(k_cur, v_cur, False)
            out, lse = _combine(out, lse, o_h, lse_h)
        if hop != n - 1:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)

    return out.astype(q.dtype)
