"""Rule-based parameter sharding + tensor-parallel train step (pjit path).

The reference has no model sharding (sequential CNN, SURVEY.md §2d: TP/PP "not
required for parity"), but the mesh design leaves the door open at zero cost
(§2d note) — this module is that door. Param shardings are declared as
(path-regex -> PartitionSpec) rules; the train step is compiled with
``jax.jit(in_shardings=..., out_shardings=...)`` and XLA GSPMD inserts the
tensor-parallel collectives (all-reduce of activations across ``model``) —
the idiomatic TPU approach per the scaling-book recipe: pick a mesh, annotate
shardings, let XLA place collectives.

``VIT_TP_RULES`` is the Megatron-style sharding for the in-tree ViT: MLP fc1
column-parallel / fc2 row-parallel; attention QKV head-parallel / output
projection row-parallel; embeddings and head replicated.
"""

from __future__ import annotations

import re
import warnings
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddw_tpu.runtime.mesh import DATA_AXIS, MODEL_AXIS
from ddw_tpu.train.step import TrainState, cross_entropy_loss


class PartitionRules:
    """Ordered (regex, PartitionSpec) rules; first match wins, default replicated."""

    def __init__(self, rules: Sequence[tuple[str, P]]):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]

    def spec_for(self, path: str, ndim: int) -> P:
        for pat, spec in self.rules:
            if pat.search(path):
                if len(spec) > ndim:
                    raise ValueError(f"rule {pat.pattern} spec {spec} rank > param rank {ndim} at {path}")
                return spec
        return P()


# Megatron-style TP for ddw_tpu.models.vit.ViT (param shapes from flax linen):
#   attn query/key/value kernel: [embed, heads, head_dim] -> shard heads
#   attn out kernel:             [heads, head_dim, embed] -> shard heads (row-par)
#   mlp fc1 kernel [embed, mlp] -> column-parallel; fc2 [mlp, embed] -> row-parallel
VIT_TP_RULES = PartitionRules([
    (r"attn/(query|key|value)/kernel", P(None, MODEL_AXIS, None)),
    (r"attn/(query|key|value)/bias", P(MODEL_AXIS, None)),
    (r"attn/out/kernel", P(MODEL_AXIS, None, None)),
    (r"mlp/fc1/kernel", P(None, MODEL_AXIS)),
    (r"mlp/fc1/bias", P(MODEL_AXIS)),
    (r"mlp/fc2/kernel", P(MODEL_AXIS, None)),
])

# Same Megatron layout for ddw_tpu.models.lm.TransformerLM (its attn submodule
# names match ViT's; its MLP lives directly in the block as fc1/fc2). Vocab
# matrices are column/row-parallel over the embedding dim's partner axis:
#   tok_embed [vocab, hidden] -> shard vocab; head kernel [hidden, vocab] -> shard vocab.
LM_TP_RULES = PartitionRules([
    (r"attn/(query|key|value)/kernel", P(None, MODEL_AXIS, None)),
    (r"attn/(query|key|value)/bias", P(MODEL_AXIS, None)),
    (r"attn/out/kernel", P(MODEL_AXIS, None, None)),
    (r"fc1/kernel", P(None, MODEL_AXIS)),
    (r"fc1/bias", P(MODEL_AXIS)),
    (r"fc2/kernel", P(MODEL_AXIS, None)),
    (r"tok_embed/embedding", P(MODEL_AXIS, None)),
    (r"head/kernel", P(None, MODEL_AXIS)),
    (r"head/bias", P(MODEL_AXIS)),
])

# GQA fallback layout: q stays head-sharded, k/v replicate. Correct for any
# num_kv_heads because the grouped-query broadcast (jnp.repeat over the head
# axis at compute time) then happens per-shard on a full KV copy.
LM_TP_RULES_REPLICATED_KV = PartitionRules([
    (r"attn/query/kernel", P(None, MODEL_AXIS, None)),
    (r"attn/query/bias", P(MODEL_AXIS, None)),
    (r"attn/(key|value)/(kernel|bias)", P()),
    (r"attn/out/kernel", P(MODEL_AXIS, None, None)),
    (r"fc1/kernel", P(None, MODEL_AXIS)),
    (r"fc1/bias", P(MODEL_AXIS)),
    (r"fc2/kernel", P(MODEL_AXIS, None)),
    (r"tok_embed/embedding", P(MODEL_AXIS, None)),
    (r"head/kernel", P(None, MODEL_AXIS)),
    (r"head/bias", P(MODEL_AXIS)),
])


def lm_tp_rules_for(num_heads: int, num_kv_heads: int,
                    tp: int) -> tuple[PartitionRules, bool]:
    """Resolve the serving-time TP layout for a TransformerLM.

    Returns ``(rules, kv_sharded)``. Query heads MUST divide by ``tp`` (the
    caller validates and raises before any program compiles); KV heads that
    don't divide fall back to replicated k/v params + a replicated KV cache
    with a RuntimeWarning — the same degrade-loudly posture as the
    ``kv_block_size`` divisor shrink in ``ServingEngine._build_block_pool``.
    """
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    kv_heads = num_kv_heads or num_heads
    if num_heads % tp:
        raise ValueError(
            f"tp {tp} does not divide num_heads {num_heads}: the attention "
            f"head axis is the TP shard axis, so the head count must be a "
            f"multiple of the model-axis size")
    if kv_heads % tp:
        warnings.warn(
            f"num_kv_heads {kv_heads} not divisible by tp {tp} (GQA/MQA): "
            f"replicating k/v params and the KV block pool instead of "
            f"sharding them on the heads axis — correct but forfeits the "
            f"KV-memory split across the mesh slice",
            RuntimeWarning, stacklevel=2)
        return LM_TP_RULES_REPLICATED_KV, False
    return LM_TP_RULES, True


def decode_cache_shardings(cache, mesh: Mesh, kv_sharded: bool = True):
    """NamedShardings for a paged-decode cache tree.

    The KV block pool leaves (``kv_block_key``/``kv_block_value``, shape
    ``[n_blocks+1, block_size, KV, head_dim]``) shard on the heads axis —
    block ids and offsets stay host/replicated so the allocator, prefix
    cache, CoW and preemption logic never see the mesh. Everything else
    (the ``tiles_computed`` scalar, contiguous-path leaves) replicates.
    With ``kv_sharded=False`` (GQA fallback) the whole cache replicates.
    """
    def to_sharding(path, leaf):
        key = _path_key(path)
        shape = tuple(getattr(leaf, "shape", ()))
        if kv_sharded and "kv_block_" in key and len(shape) == 4:
            spec = P(None, None, MODEL_AXIS, None)
            check_spec_divisibility(key, shape, spec, mesh)
            return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(to_sharding, cache)


def _path_key(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
                    for p in path)


def check_spec_divisibility(key: str, shape: tuple, spec: P, mesh: Mesh) -> None:
    """Refuse loudly where GSPMD would fail opaquely at compile time: every
    sharded dim must divide by its mesh-axis size. The common trip-wire is
    GQA/MQA (num_kv_heads < model-axis size shrinks the k/v head dim the TP
    rules shard). Shared by the 1D TP path and the 2D FSDP×TP path."""
    for d, axis in enumerate(spec):
        if axis is None or d >= len(shape):
            continue
        # a spec entry may name several mesh axes (P(("data","model"),...))
        names = axis if isinstance(axis, tuple) else (axis,)
        n = 1
        for a in names:
            n *= mesh.shape[a]
        if shape[d] % n:
            raise ValueError(
                f"cannot shard {key} dim {d} (size {shape[d]}) over mesh "
                f"axis {axis!r} (size {n}): not divisible. For GQA/MQA "
                f"models either keep num_kv_heads a multiple of the "
                f"model-axis size or override the k/v rules to replicate.")


def shardings_for_params(tree, mesh: Mesh, rules: PartitionRules):
    """Pytree of NamedShardings matching ``tree`` via the path rules.

    Works on a param tree OR a whole TrainState (shape) tree: optimizer moments
    (Adam mu/nu) mirror the param tree, so their paths end with the same
    ``.../mlp/fc1/kernel`` suffixes the rules match on; scalars (step, counts,
    hyperparams) match nothing and replicate."""
    def to_sharding(path, leaf):
        key = _path_key(path)
        shape = tuple(getattr(leaf, "shape", ()))
        spec = rules.spec_for(key, len(shape))
        check_spec_divisibility(key, shape, spec, mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(to_sharding, tree)


def make_sharded_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rules: PartitionRules,
    data_axis: str = DATA_AXIS,
) -> Callable:
    """Tensor+data-parallel train step via GSPMD.

    Params/opt-state shard per ``rules`` over the ``model`` axis; the batch
    shards over ``data``; gradients reduce over ``data`` automatically (XLA
    derives the all-reduce from the shardings — no explicit psum needed in the
    pjit formulation). Returns ``step(state, images, labels, rng) -> (state,
    metrics)``; call :func:`place_state` first so inputs are laid out correctly.
    """

    def _step(state: TrainState, images, labels, rng):
        dropout_rng = jax.random.fold_in(rng, state.step)

        def loss_fn(params):
            variables = {"params": params}
            logits = model.apply(variables, images, train=True,
                                 rngs={"dropout": dropout_rng})
            loss = cross_entropy_loss(logits, labels)
            acc = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
            return loss, acc

        (loss, acc), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(new_params, state.batch_stats, new_opt, state.step + 1)
        return new_state, {"loss": loss, "accuracy": acc}

    def place_state(state: TrainState) -> TrainState:
        state_sh = shardings_for_params(state, mesh, rules)
        return jax.tree.map(lambda x, s: jax.device_put(x, s), state, state_sh)

    step = jax.jit(_step, donate_argnums=(0,))
    step.place_state = place_state  # type: ignore[attr-defined]
    step.batch_sharding = NamedSharding(mesh, P(data_axis))  # type: ignore[attr-defined]
    return step
