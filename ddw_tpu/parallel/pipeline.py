"""Pipeline parallelism for the LM — GPipe microbatch schedule over a mesh axis.

Not a reference-parity item (the reference's parallelism inventory is
DP/trial/HPO/batch-inference, SURVEY.md §2d); this is the pipeline axis of the
framework, closing the tp/pp/dp/sp/ep set.

TPU-first formulation:

- the transformer's blocks are **stacked per stage**: block params become
  leaves ``[n_stages, blocks_per_stage, ...]`` sharded ``P('pipe')`` on the
  stage dim, so each device holds exactly its stage's weights (true model
  partitioning, not replication). Embed/head stay replicated (they are tiny).
- inside one ``shard_map``, a ``lax.scan`` runs the GPipe schedule: at tick
  ``t`` stage ``r`` processes microbatch ``t - r``; activations hop to the
  next stage over ICI via ``lax.ppermute``; ticks before/after a stage's
  window compute on masked garbage whose loss contribution is zeroed (SPMD
  ranks must run the same program — masking, not control flow, encodes the
  schedule).
- each stage applies its ``blocks_per_stage`` blocks with an inner
  ``lax.scan`` over the stacked block params, wrapped in ``jax.checkpoint``
  (per-tick rematerialization — GPipe's memory model).
- backward is plain ``jax.grad`` through the scan: XLA transposes the
  ``ppermute`` hops into the reverse-direction cotangent hops automatically.
  Stage grads stay stage-local (``P('pipe')`` out-spec); embed/head grads are
  ``psum``-ed (only the stages that actually use them contribute non-zeros).
- the optimizer update runs OUTSIDE the shard_map under ``jit``: stage
  params/moments arrive sharded, so GSPMD keeps the update sharded — the same
  split this framework uses for ZeRO (``parallel/zero.py``).

Scope: training/eval steps for :class:`ddw_tpu.models.lm.TransformerLM` with
``dropout == 0`` and ``seq_axis is None`` (PP composes with DP by adding a
data axis to the mesh; the batch dim shards over it transparently).

Two schedules (``make_pp_lm_train_step(schedule=...)``):

- ``"gpipe"`` — at tick ``t`` stage ``r`` processes microbatch ``t - r``;
  bubble fraction ``(n-1)/(m+n-1)``.
- ``"interleaved"`` — Megatron-style virtual stages: the depth splits into
  ``n * v`` chunks placed round-robin (chunk ``c`` on device ``c % n``), so
  every activation hop is still the same next-neighbor ``ppermute`` ring but
  each device re-enters the pipeline ``v`` times per microbatch. At tick
  ``t`` device ``r`` runs chunk ``k = (t-r) // n`` on microbatch
  ``j = (t-r) % n`` — a stall-free schedule exactly when ``m <= n`` (two
  chunks of one device would otherwise contend for the same tick; refused
  loudly). Ticks cost ``1/v`` of a GPipe tick, ``v*n + m - 1`` of them:
  bubble fraction ``(v*(n-m) + m-1 ... )`` — see :func:`bubble_fraction` —
  i.e. the GPipe bubble shrinks ~``v``-fold at equal microbatch count
  (n=4, m=4: 0.429 -> 0.273 at v=2). That matters in the real operating
  regime where ``m`` is pinned by per-microbatch memory, not free to grow.

Why no literal 1F1B: 1F1B's advantage over GPipe is peak activation memory
(O(n_stages) live microbatches instead of O(m)); its bubble fraction is the
same (n-1)/(m+n-1). Here every tick's stage application is
``jax.checkpoint``-ed, so the scan already retains only the [mb, S, H]
inter-stage activations per tick — 1F1B's memory profile — while backward
remains plain ``jax.grad`` (XLA transposes the schedule, ppermute hops
reverse automatically). A literal 1F1B would trade that for a hand-written
interleaved VJP schedule with no bubble improvement to show for it; the
interleaved virtual-stage schedule above is the variant that actually
reduces the bubble, and it keeps the plain-``jax.grad`` backward.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddw_tpu.models.lm import DecoderBlock, TransformerLM
from ddw_tpu.train.lm_step import lm_loss
from ddw_tpu.train.step import TrainState
from ddw_tpu.utils.compat import shard_map

PIPE_AXIS = "pipe"


def pp_params_from_lm(params: dict, n_stages: int, depth: int,
                      virtual_stages: int = 1) -> dict:
    """Restructure TransformerLM params for the pipeline step.

    ``virtual_stages == 1`` (GPipe): ``backbone_block{i}`` subtrees stack into
    ``stages`` leaves ``[n_stages, depth/n_stages, ...]`` — contiguous blocks
    per device. ``virtual_stages == v > 1`` (interleaved): the depth splits
    into ``n*v`` round-robin chunks (chunk ``c`` on device ``c % n``) and
    leaves stack ``[v, n_stages, depth/(n*v), ...]`` — ``leaf[k, r]`` is
    chunk ``k*n + r``. Everything else splits into the replicated ``embed``
    (token + position tables) and ``head`` (final LN + vocab projection)
    groups. Inverse: :func:`lm_params_from_pp`.
    """
    v = virtual_stages
    if depth % (n_stages * v):
        raise ValueError(f"depth {depth} not divisible by {n_stages} stages "
                         f"x {v} virtual stages")
    bpc = depth // (n_stages * v)
    blocks = [params[f"backbone_block{i}"] for i in range(depth)]

    def chunk_tree(c):
        return jax.tree.map(lambda *xs: jnp.stack(xs),
                            *blocks[c * bpc:(c + 1) * bpc])

    if v == 1:
        stages = jax.tree.map(lambda *xs: jnp.stack(xs),
                              *[chunk_tree(r) for r in range(n_stages)])
    else:
        rows = [jax.tree.map(lambda *xs: jnp.stack(xs),
                             *[chunk_tree(k * n_stages + r)
                               for r in range(n_stages)])
                for k in range(v)]
        stages = jax.tree.map(lambda *xs: jnp.stack(xs), *rows)
    embed = {"tok_embed": params["tok_embed"]}
    if "pos_embed" in params:  # absent for pos_encoding='rope' models
        embed["pos_embed"] = params["pos_embed"]
    return {
        "embed": embed,
        "stages": stages,
        "head": {"LayerNorm_0": params["LayerNorm_0"],
                 "head": params["head"]},
    }


def lm_params_from_pp(pp: dict, n_stages: int, depth: int,
                      virtual_stages: int = 1) -> dict:
    """Inverse of :func:`pp_params_from_lm` (checkpoints/serving interop)."""
    v = virtual_stages
    bpc = depth // (n_stages * v)
    out = {"tok_embed": pp["embed"]["tok_embed"],
           "LayerNorm_0": pp["head"]["LayerNorm_0"],
           "head": pp["head"]["head"]}
    if "pos_embed" in pp["embed"]:  # absent for pos_encoding='rope' models
        out["pos_embed"] = pp["embed"]["pos_embed"]
    for c in range(n_stages * v):
        k, r = divmod(c, n_stages)
        for b in range(bpc):
            out[f"backbone_block{c * bpc + b}"] = jax.tree.map(
                (lambda x, r=r, b=b: x[r, b]) if v == 1
                else (lambda x, k=k, r=r, b=b: x[k, r, b]),
                pp["stages"])
    return out


def bubble_fraction(n_stages: int, num_microbatches: int,
                    virtual_stages: int = 1) -> float:
    """Idle fraction of the pipeline schedule (per device, fwd and bwd alike).

    GPipe (v=1): ``m`` busy of ``m + n - 1`` stage-ticks. Interleaved: ``m*v``
    busy of ``v*n + m - 1`` chunk-ticks (each 1/v the cost — the fraction is
    cost-invariant because all ticks are equal).
    """
    n, m, v = n_stages, num_microbatches, virtual_stages
    if v == 1:
        return (n - 1) / (m + n - 1)
    if m > n:
        raise ValueError(
            f"interleaved schedule is only defined for num_microbatches "
            f"({m}) <= n_stages ({n}) — the stall-free window "
            f"make_pp_lm_train_step enforces")
    return (v * n + m - 1 - v * m) / (v * n + m - 1)


def _spec_tree(pp_params, pipe_axis: str, virtual_stages: int = 1):
    """P('pipe') on the device-stage dim of stacked blocks (dim 0 for GPipe,
    dim 1 after the virtual-chunk dim for interleaved), replicated elsewhere."""
    stage_spec = P(pipe_axis) if virtual_stages == 1 else P(None, pipe_axis)
    return {
        "embed": jax.tree.map(lambda _: P(), pp_params["embed"]),
        "stages": jax.tree.map(lambda _: stage_spec, pp_params["stages"]),
        "head": jax.tree.map(lambda _: P(), pp_params["head"]),
    }


def make_pp_lm_train_step(
    model: TransformerLM,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    pipe_axis: str = PIPE_AXIS,
    data_axis: str | None = None,
    num_microbatches: int = 4,
    donate: bool = False,
    aux_loss_weight: float = 0.01,
    schedule: str = "gpipe",
    virtual_stages: int = 2,
) -> Callable:
    """Build the pipelined LM train step.

    ``step(state, inputs, targets) -> (state, metrics)`` where ``state.params``
    is the :func:`pp_params_from_lm` layout placed via ``step.place_state``.
    ``num_microbatches`` must divide the per-data-shard batch (checked at call
    time). With ``data_axis`` set (DPxPP mesh) the batch dim additionally
    shards over it: each data-parallel pipeline replica runs the schedule on
    its shard and gradients ``pmean`` across replicas. MoE models are
    supported with all-local (dense) experts — their Switch aux loss is
    accumulated across stages/microbatches like the non-PP step's; an
    ``expert_axis`` is rejected (PPxEP routing across a second axis is not
    implemented).

    ``schedule='gpipe'`` runs contiguous stages; ``schedule='interleaved'``
    places ``virtual_stages`` round-robin chunks per device (module
    docstring), cutting the bubble ~``virtual_stages``-fold at equal
    microbatch count; it requires ``num_microbatches <= n_stages`` (the
    stall-free window) and ``depth % (n_stages * virtual_stages) == 0``.
    Every step's metrics carry the schedule's analytic
    ``pp_bubble_fraction`` (:func:`bubble_fraction`).
    """
    if model.dropout:
        raise ValueError("pipeline step supports dropout=0 models only")
    if model.seq_axis:
        raise ValueError("pipeline step composes with DP, not SP — build the "
                         "model with seq_axis=None")
    if getattr(model, "expert_axis", None):
        raise ValueError("pipeline step does not implement expert parallelism "
                         "— build the MoE model with expert_axis=None (dense "
                         "experts) or use make_lm_train_step for EP")
    if getattr(model, "lora_rank", 0):
        raise ValueError("pipeline step does not support LoRA adapters — use "
                         "make_lm_train_step")
    rope = getattr(model, "pos_encoding", "learned") == "rope"
    n = mesh.shape[pipe_axis]
    m = num_microbatches
    if schedule not in ("gpipe", "interleaved"):
        raise ValueError(f"schedule must be 'gpipe' or 'interleaved', "
                         f"got {schedule!r}")
    v = virtual_stages if schedule == "interleaved" else 1
    if v < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {v}")
    if model.depth % (n * v):
        raise ValueError(f"depth {model.depth} not divisible by pipe axis {n}"
                         + (f" x virtual_stages {v}" if v > 1 else ""))
    if schedule == "interleaved" and m > n:
        raise ValueError(
            f"interleaved schedule requires num_microbatches ({m}) <= "
            f"n_stages ({n}): beyond that window two chunks of one device "
            f"contend for the same tick (the stall-free property breaks) — "
            f"use schedule='gpipe' for large microbatch counts")
    moe = getattr(model, "num_experts", 0) > 0
    aux_w = aux_loss_weight
    bubble = bubble_fraction(n, m, v)

    block_mod = DecoderBlock(model.num_heads, model.mlp_dim, 0.0, model.dtype,
                             None, False, model.max_len,
                             num_experts=model.num_experts,
                             capacity_factor=model.capacity_factor,
                             moe_router=model.moe_router,
                             num_kv_heads=getattr(model, "num_kv_heads", 0))
    embed_mod = nn.Embed(model.vocab_size, model.hidden, dtype=model.dtype)
    ln_mod = nn.LayerNorm(dtype=jnp.float32)
    head_mod = nn.Dense(model.vocab_size, dtype=jnp.float32)

    @jax.checkpoint
    def stage_apply(stage_params, x):
        """Apply this stage's stacked blocks (inner scan over the block dim).
        Returns (out, aux_sum) — the stage's summed Switch aux loss (0 for
        dense models)."""
        def body(h, block_params):
            # RoPE: positions are global arange(S) — PP shards depth, not
            # sequence, so every stage sees the full sequence
            positions = jnp.arange(h.shape[-2]) if rope else None
            if moe:
                from ddw_tpu.models.moe import collect_sown

                out, mods = block_mod.apply({"params": block_params}, h, False,
                                            positions=positions,
                                            mutable=["intermediates"])
                # select the aux loss by name: blocks also sow routing
                # telemetry that must not enter the loss
                sown = collect_sown(mods, "moe_aux_loss")
                return out, sum(sown)
            return block_mod.apply({"params": block_params}, h, False,
                                   positions=positions), 0.0

        out, aux = lax.scan(body, x, stage_params)
        return out, jnp.sum(aux)

    def _forward(pp_params, inputs, targets):
        """Per-rank pipeline forward: the schedule scan, shared by the train
        step (under ``value_and_grad``) and the eval step (called plain).
        Returns ``(total_loss, (ce, acc, aux))``."""
        r = lax.axis_index(pipe_axis)
        b, s = inputs.shape
        if b % m:
            raise ValueError(f"per-shard batch {b} not divisible by "
                             f"num_microbatches {m}")
        mb = b // m
        perm = [(i, (i + 1) % n) for i in range(n)]

        def loss_fn(p):
            emb = embed_mod.apply({"params": p["embed"]["tok_embed"]}, inputs)
            if not rope:
                pos = p["embed"]["pos_embed"][:s].astype(model.dtype)[None]
                emb = emb + pos
            emb = emb.reshape(m, mb, s, model.hidden)
            targ = targets.reshape(m, mb, s)
            if v == 1:
                stage_params = jax.tree.map(lambda x: x[0], p["stages"])
            else:
                # local stages leaves are [v, 1, bpc, ...]: v round-robin
                # chunks resident on this device.
                local_chunks = jax.tree.map(lambda x: x[:, 0], p["stages"])

            def tick(carry, t):
                recv, ce_sum, acc_sum, aux_sum = carry
                if v == 1:
                    j = t - r
                    valid = (j >= 0) & (j < m)
                    first_chunk, last_chunk = r == 0, r == n - 1
                    sp = stage_params
                else:
                    # interleaved: device r runs chunk k = (t-r)//n on
                    # microbatch j = (t-r) % n — stall-free for m <= n.
                    q = t - r
                    k = jnp.clip(q // n, 0, v - 1)
                    j = q % n
                    valid = (q >= 0) & (q // n < v) & (j < m)
                    first_chunk = (r == 0) & (k == 0)
                    last_chunk = (r == n - 1) & (k == v - 1)
                    sp = jax.tree.map(
                        lambda x: lax.dynamic_index_in_dim(
                            x, k, keepdims=False), local_chunks)
                j_c = jnp.clip(j, 0, m - 1)
                x0 = lax.dynamic_index_in_dim(emb, j_c, keepdims=False)
                x_in = jnp.where(first_chunk, x0.astype(model.dtype),
                                 recv.astype(model.dtype))
                y, aux = stage_apply(sp, x_in)
                tgt = lax.dynamic_index_in_dim(targ, j_c, keepdims=False)

                # Head + CE only materialize on the last chunk: the head
                # projection has no collectives, so lax.cond is legal inside
                # shard_map and skips (n-1)/n of the vocab-matmul work.
                def head_ce(y):
                    logits = head_mod.apply(
                        {"params": p["head"]["head"]},
                        ln_mod.apply({"params": p["head"]["LayerNorm_0"]},
                                     y.astype(jnp.float32)))
                    ce = lm_loss(logits, tgt)
                    acc = jnp.mean(
                        (jnp.argmax(logits, -1) == tgt).astype(jnp.float32))
                    return ce, acc

                ce, acc = lax.cond(last_chunk, head_ce,
                                   lambda _: (jnp.zeros(()), jnp.zeros(())), y)
                use = (valid & last_chunk).astype(jnp.float32)
                # every chunk contributes its own aux for its valid ticks
                aux_use = valid.astype(jnp.float32)
                recv_next = lax.ppermute(y, pipe_axis, perm)
                return (recv_next, ce_sum + use * ce, acc_sum + use * acc,
                        aux_sum + aux_use * aux), None

            z = jnp.zeros((mb, s, model.hidden), model.dtype)
            n_ticks = (m + n - 1) if v == 1 else (v * n + m - 1)
            (_, ce_sum, acc_sum, aux_sum), _ = lax.scan(
                tick, (z, jnp.zeros(()), jnp.zeros(()), jnp.zeros(())),
                jnp.arange(n_ticks))
            # only the last stage accumulated CE; psum broadcasts the global
            # mean. Aux: every stage's blocks contributed once per microbatch
            # — mean over (microbatches x blocks) matches make_lm_train_step.
            loss = lax.psum(ce_sum, pipe_axis) / m
            acc = lax.psum(acc_sum, pipe_axis) / m
            aux = lax.psum(aux_sum, pipe_axis) / (m * model.depth)
            return loss + aux_w * aux, (loss, acc, aux)

        return loss_fn(pp_params)

    def grad_fn(pp_params, inputs, targets):
        """Per-rank pipeline forward+backward. inputs/targets [B, S] replicated
        over the pipe axis (shard them over a data axis for DPxPP)."""
        (_, (loss, acc, aux)), grads = jax.value_and_grad(
            lambda p: _forward(p, inputs, targets), has_aux=True)(pp_params)
        # The loss comes out of a psum, replicated on every rank; under
        # shard_map AD each rank's unit cotangent flows through the psum
        # transpose, so raw grads are n_stages x the true gradient (verified
        # empirically: every leaf exactly n x). Scale back.
        grads = jax.tree.map(lambda g: g / n, grads)
        # embed/head params are replicated but only some stages produce
        # non-zero grads — psum makes every rank's grad the true global one.
        grads["embed"] = lax.psum(grads["embed"], pipe_axis)
        grads["head"] = lax.psum(grads["head"], pipe_axis)
        metrics = _metrics(loss, acc, aux)
        if data_axis is not None:
            # DPxPP: average gradients across pipeline replicas (metrics
            # already pmean-ed in _metrics).
            grads = lax.pmean(grads, data_axis)
        return grads, metrics

    def _metrics(loss, acc, aux):
        """ONE metrics assembly for the train and eval halves — a metric
        added to one cannot silently miss the other."""
        metrics = {"loss": loss, "accuracy": acc}
        if moe:
            metrics["aux_loss"] = aux
        if data_axis is not None:
            metrics = lax.pmean(metrics, data_axis)
        return metrics

    def metrics_fn(pp_params, inputs, targets):
        """Forward-only pipeline metrics (the eval half of the step)."""
        _, (loss, acc, aux) = _forward(pp_params, inputs, targets)
        return _metrics(loss, acc, aux)

    def _build(template_params):
        specs = _spec_tree(template_params, pipe_axis, v)
        tok_spec = P() if data_axis is None else P(data_axis)
        smapped = shard_map(
            grad_fn, mesh=mesh,
            in_specs=(specs, tok_spec, tok_spec),
            out_specs=(specs, P()),
            check_vma=False)
        smapped_eval = shard_map(
            metrics_fn, mesh=mesh,
            in_specs=(specs, tok_spec, tok_spec),
            out_specs=P(),
            check_vma=False)

        def _step(state: TrainState, inputs, targets):
            grads, metrics = smapped(state.params, inputs, targets)
            # Analytic idle fraction of this schedule — surfaced per step so
            # trainers/trackers log the bubble beside throughput.
            metrics["pp_bubble_fraction"] = jnp.float32(bubble)
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            return TrainState(new_params, {}, new_opt, state.step + 1), metrics

        def _eval(state: TrainState, inputs, targets):
            return smapped_eval(state.params, inputs, targets)

        return (jax.jit(_step, donate_argnums=(0,) if donate else ()),
                jax.jit(_eval))

    bpc = model.depth // (n * v)

    def _check_layout(params):
        # A state built with the wrong virtual_stages fails far from the
        # mistake (opaque sharding/rank errors) — refuse here instead.
        leaf = jax.tree.leaves(params["stages"])[0]
        want = (n, bpc) if v == 1 else (v, n, bpc)
        if tuple(leaf.shape[:len(want)]) != want:
            raise ValueError(
                f"stages layout mismatch: leaf leading dims "
                f"{tuple(leaf.shape[:len(want)])} != {want} expected by "
                f"schedule={schedule!r} (virtual_stages={v}) — build the "
                f"state with init_pp_state(..., virtual_stages={v}) / "
                f"pp_params_from_lm(..., virtual_stages={v})")

    _jits: dict = {}

    def _fns(state: TrainState):
        key = jax.tree.structure(state)
        fns = _jits.get(key)
        if fns is None:
            _check_layout(state.params)
            fns = _jits[key] = _build(state.params)
        return fns

    def stepper(state: TrainState, inputs, targets):
        return _fns(state)[0](state, inputs, targets)

    def eval_step(state: TrainState, inputs, targets):
        """Forward-only metrics over the same schedule (no update, no
        donation — the state is reused across the whole eval pass)."""
        return _fns(state)[1](state, inputs, targets)

    stepper.eval_step = eval_step  # type: ignore[attr-defined]

    def place_state(state: TrainState) -> TrainState:
        _check_layout(state.params)
        specs = _spec_tree(state.params, pipe_axis, v)
        psh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
        repl = NamedSharding(mesh, P())

        def opt_sharding(leaf):
            # Optimizer moments mirror the params tree; stacked stage leaves
            # are exactly the ones whose leading dims match the stacked-chunk
            # layout — shard those with the stages, replicate everything else
            # (including adam's count scalar).
            shape = getattr(leaf, "shape", ())
            if v == 1:
                if len(shape) >= 2 and tuple(shape[:2]) == (n, bpc):
                    return NamedSharding(mesh, P(pipe_axis))
            elif len(shape) >= 3 and tuple(shape[:3]) == (v, n, bpc):
                return NamedSharding(mesh, P(None, pipe_axis))
            return repl

        return TrainState(
            params=jax.tree.map(jax.device_put, state.params, psh),
            batch_stats={},
            opt_state=jax.tree.map(
                lambda leaf: jax.device_put(leaf, opt_sharding(leaf)),
                state.opt_state),
            step=jax.device_put(state.step, repl),
        )

    stepper.place_state = place_state  # type: ignore[attr-defined]
    return stepper


def init_pp_state(model: TransformerLM, tx: optax.GradientTransformation,
                  mesh: Mesh, rng: jax.Array,
                  pipe_axis: str = PIPE_AXIS,
                  virtual_stages: int = 1) -> TrainState:
    """Init a TransformerLM and restructure into placed pipeline TrainState.
    ``virtual_stages`` must match the step's (1 for ``schedule='gpipe'``)."""
    from ddw_tpu.train.lm_step import init_lm_state

    base = init_lm_state(model, tx, rng)
    n = mesh.shape[pipe_axis]
    pp = pp_params_from_lm(base.params, n, model.depth, virtual_stages)
    state = TrainState(pp, {}, tx.init(pp), jnp.zeros((), jnp.int32))
    return state
