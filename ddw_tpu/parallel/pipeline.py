"""Pipeline parallelism for the LM — GPipe microbatch schedule over a mesh axis.

Not a reference-parity item (the reference's parallelism inventory is
DP/trial/HPO/batch-inference, SURVEY.md §2d); this is the pipeline axis of the
framework, closing the tp/pp/dp/sp/ep set.

TPU-first formulation:

- the transformer's blocks are **stacked per stage**: block params become
  leaves ``[n_stages, blocks_per_stage, ...]`` sharded ``P('pipe')`` on the
  stage dim, so each device holds exactly its stage's weights (true model
  partitioning, not replication). Embed/head stay replicated (they are tiny).
- inside one ``shard_map``, a ``lax.scan`` runs the GPipe schedule: at tick
  ``t`` stage ``r`` processes microbatch ``t - r``; activations hop to the
  next stage over ICI via ``lax.ppermute``; ticks before/after a stage's
  window compute on masked garbage whose loss contribution is zeroed (SPMD
  ranks must run the same program — masking, not control flow, encodes the
  schedule).
- each stage applies its ``blocks_per_stage`` blocks with an inner
  ``lax.scan`` over the stacked block params, wrapped in ``jax.checkpoint``
  (per-tick rematerialization — GPipe's memory model).
- backward is plain ``jax.grad`` through the scan: XLA transposes the
  ``ppermute`` hops into the reverse-direction cotangent hops automatically.
  Stage grads stay stage-local (``P('pipe')`` out-spec); embed/head grads are
  ``psum``-ed (only the stages that actually use them contribute non-zeros).
- the optimizer update runs OUTSIDE the shard_map under ``jit``: stage
  params/moments arrive sharded, so GSPMD keeps the update sharded — the same
  split this framework uses for ZeRO (``parallel/zero.py``).

Scope: training/eval steps for :class:`ddw_tpu.models.lm.TransformerLM` with
``dropout == 0`` and ``seq_axis is None`` (PP composes with DP by adding a
data axis to the mesh; the batch dim shards over it transparently).

Why GPipe-with-remat rather than 1F1B: 1F1B's advantage over GPipe is peak
activation memory (O(n_stages) live microbatches instead of O(m)); its bubble
fraction is the same (n-1)/(m+n-1). Here every tick's stage application is
``jax.checkpoint``-ed, so the scan already retains only the [mb, S, H]
inter-stage activations per tick — 1F1B's memory profile — while backward
remains plain ``jax.grad`` (XLA transposes the schedule, ppermute hops
reverse automatically). A literal 1F1B would trade that for a hand-written
interleaved VJP schedule with no bubble improvement to show for it.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddw_tpu.models.lm import DecoderBlock, TransformerLM
from ddw_tpu.train.lm_step import lm_loss
from ddw_tpu.train.step import TrainState

PIPE_AXIS = "pipe"


def pp_params_from_lm(params: dict, n_stages: int, depth: int) -> dict:
    """Restructure TransformerLM params for the pipeline step.

    ``backbone_block{i}`` subtrees stack into ``stages`` leaves
    ``[n_stages, depth/n_stages, ...]``; everything else splits into the
    replicated ``embed`` (token + position tables) and ``head`` (final LN +
    vocab projection) groups. Inverse: :func:`lm_params_from_pp`.
    """
    if depth % n_stages:
        raise ValueError(f"depth {depth} not divisible by {n_stages} stages")
    bps = depth // n_stages
    blocks = [params[f"backbone_block{i}"] for i in range(depth)]
    stage_trees = [
        jax.tree.map(lambda *xs: jnp.stack(xs), *blocks[r * bps:(r + 1) * bps])
        for r in range(n_stages)
    ]
    stages = jax.tree.map(lambda *xs: jnp.stack(xs), *stage_trees)
    embed = {"tok_embed": params["tok_embed"]}
    if "pos_embed" in params:  # absent for pos_encoding='rope' models
        embed["pos_embed"] = params["pos_embed"]
    return {
        "embed": embed,
        "stages": stages,
        "head": {"LayerNorm_0": params["LayerNorm_0"],
                 "head": params["head"]},
    }


def lm_params_from_pp(pp: dict, n_stages: int, depth: int) -> dict:
    """Inverse of :func:`pp_params_from_lm` (checkpoints/serving interop)."""
    bps = depth // n_stages
    out = {"tok_embed": pp["embed"]["tok_embed"],
           "LayerNorm_0": pp["head"]["LayerNorm_0"],
           "head": pp["head"]["head"]}
    if "pos_embed" in pp["embed"]:  # absent for pos_encoding='rope' models
        out["pos_embed"] = pp["embed"]["pos_embed"]
    for r in range(n_stages):
        for b in range(bps):
            out[f"backbone_block{r * bps + b}"] = jax.tree.map(
                lambda x, r=r, b=b: x[r, b], pp["stages"])
    return out


def _spec_tree(pp_params, pipe_axis: str):
    """P('pipe') on the stage dim of stacked blocks, replicated elsewhere."""
    return {
        "embed": jax.tree.map(lambda _: P(), pp_params["embed"]),
        "stages": jax.tree.map(lambda _: P(pipe_axis), pp_params["stages"]),
        "head": jax.tree.map(lambda _: P(), pp_params["head"]),
    }


def make_pp_lm_train_step(
    model: TransformerLM,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    pipe_axis: str = PIPE_AXIS,
    data_axis: str | None = None,
    num_microbatches: int = 4,
    donate: bool = False,
    aux_loss_weight: float = 0.01,
) -> Callable:
    """Build the pipelined LM train step.

    ``step(state, inputs, targets) -> (state, metrics)`` where ``state.params``
    is the :func:`pp_params_from_lm` layout placed via ``step.place_state``.
    ``num_microbatches`` must divide the per-data-shard batch (checked at call
    time). With ``data_axis`` set (DPxPP mesh) the batch dim additionally
    shards over it: each data-parallel pipeline replica runs the schedule on
    its shard and gradients ``pmean`` across replicas. MoE models are
    supported with all-local (dense) experts — their Switch aux loss is
    accumulated across stages/microbatches like the non-PP step's; an
    ``expert_axis`` is rejected (PPxEP routing across a second axis is not
    implemented).
    """
    if model.dropout:
        raise ValueError("pipeline step supports dropout=0 models only")
    if model.seq_axis:
        raise ValueError("pipeline step composes with DP, not SP — build the "
                         "model with seq_axis=None")
    if getattr(model, "expert_axis", None):
        raise ValueError("pipeline step does not implement expert parallelism "
                         "— build the MoE model with expert_axis=None (dense "
                         "experts) or use make_lm_train_step for EP")
    if getattr(model, "lora_rank", 0):
        raise ValueError("pipeline step does not support LoRA adapters — use "
                         "make_lm_train_step")
    rope = getattr(model, "pos_encoding", "learned") == "rope"
    n = mesh.shape[pipe_axis]
    if model.depth % n:
        raise ValueError(f"depth {model.depth} not divisible by pipe axis {n}")
    m = num_microbatches
    moe = getattr(model, "num_experts", 0) > 0
    aux_w = aux_loss_weight

    block_mod = DecoderBlock(model.num_heads, model.mlp_dim, 0.0, model.dtype,
                             None, False, model.max_len,
                             num_experts=model.num_experts,
                             capacity_factor=model.capacity_factor,
                             moe_router=model.moe_router,
                             num_kv_heads=getattr(model, "num_kv_heads", 0))
    embed_mod = nn.Embed(model.vocab_size, model.hidden, dtype=model.dtype)
    ln_mod = nn.LayerNorm(dtype=jnp.float32)
    head_mod = nn.Dense(model.vocab_size, dtype=jnp.float32)

    @jax.checkpoint
    def stage_apply(stage_params, x):
        """Apply this stage's stacked blocks (inner scan over the block dim).
        Returns (out, aux_sum) — the stage's summed Switch aux loss (0 for
        dense models)."""
        def body(h, block_params):
            # RoPE: positions are global arange(S) — PP shards depth, not
            # sequence, so every stage sees the full sequence
            positions = jnp.arange(h.shape[-2]) if rope else None
            if moe:
                from ddw_tpu.models.moe import collect_sown

                out, mods = block_mod.apply({"params": block_params}, h, False,
                                            positions=positions,
                                            mutable=["intermediates"])
                # select the aux loss by name: blocks also sow routing
                # telemetry that must not enter the loss
                sown = collect_sown(mods, "moe_aux_loss")
                return out, sum(sown)
            return block_mod.apply({"params": block_params}, h, False,
                                   positions=positions), 0.0

        out, aux = lax.scan(body, x, stage_params)
        return out, jnp.sum(aux)

    def grad_fn(pp_params, inputs, targets):
        """Per-rank pipeline forward+backward. inputs/targets [B, S] replicated
        over the pipe axis (shard them over a data axis for DPxPP)."""
        r = lax.axis_index(pipe_axis)
        b, s = inputs.shape
        if b % m:
            raise ValueError(f"per-shard batch {b} not divisible by "
                             f"num_microbatches {m}")
        mb = b // m
        perm = [(i, (i + 1) % n) for i in range(n)]

        def loss_fn(p):
            emb = embed_mod.apply({"params": p["embed"]["tok_embed"]}, inputs)
            if not rope:
                pos = p["embed"]["pos_embed"][:s].astype(model.dtype)[None]
                emb = emb + pos
            emb = emb.reshape(m, mb, s, model.hidden)
            targ = targets.reshape(m, mb, s)
            stage_params = jax.tree.map(lambda x: x[0], p["stages"])

            def tick(carry, t):
                recv, ce_sum, acc_sum, aux_sum = carry
                j = t - r
                valid = (j >= 0) & (j < m)
                j_c = jnp.clip(j, 0, m - 1)
                x0 = lax.dynamic_index_in_dim(emb, j_c, keepdims=False)
                x_in = jnp.where(r == 0, x0.astype(model.dtype),
                                 recv.astype(model.dtype))
                y, aux = stage_apply(stage_params, x_in)
                tgt = lax.dynamic_index_in_dim(targ, j_c, keepdims=False)

                # Head + CE only materialize on the last stage: the head
                # projection has no collectives, so lax.cond is legal inside
                # shard_map and skips (n-1)/n of the vocab-matmul work.
                def head_ce(y):
                    logits = head_mod.apply(
                        {"params": p["head"]["head"]},
                        ln_mod.apply({"params": p["head"]["LayerNorm_0"]},
                                     y.astype(jnp.float32)))
                    ce = lm_loss(logits, tgt)
                    acc = jnp.mean(
                        (jnp.argmax(logits, -1) == tgt).astype(jnp.float32))
                    return ce, acc

                ce, acc = lax.cond(r == n - 1, head_ce,
                                   lambda _: (jnp.zeros(()), jnp.zeros(())), y)
                use = (valid & (r == n - 1)).astype(jnp.float32)
                # every stage contributes its own aux for its valid ticks
                aux_use = valid.astype(jnp.float32)
                recv_next = lax.ppermute(y, pipe_axis, perm)
                return (recv_next, ce_sum + use * ce, acc_sum + use * acc,
                        aux_sum + aux_use * aux), None

            z = jnp.zeros((mb, s, model.hidden), model.dtype)
            (_, ce_sum, acc_sum, aux_sum), _ = lax.scan(
                tick, (z, jnp.zeros(()), jnp.zeros(()), jnp.zeros(())),
                jnp.arange(m + n - 1))
            # only the last stage accumulated CE; psum broadcasts the global
            # mean. Aux: every stage's blocks contributed once per microbatch
            # — mean over (microbatches x blocks) matches make_lm_train_step.
            loss = lax.psum(ce_sum, pipe_axis) / m
            acc = lax.psum(acc_sum, pipe_axis) / m
            aux = lax.psum(aux_sum, pipe_axis) / (m * model.depth)
            return loss + aux_w * aux, (loss, acc, aux)

        (_, (loss, acc, aux)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(pp_params)
        # The loss comes out of a psum, replicated on every rank; under
        # shard_map AD each rank's unit cotangent flows through the psum
        # transpose, so raw grads are n_stages x the true gradient (verified
        # empirically: every leaf exactly n x). Scale back.
        grads = jax.tree.map(lambda g: g / n, grads)
        # embed/head params are replicated but only some stages produce
        # non-zero grads — psum makes every rank's grad the true global one.
        grads["embed"] = lax.psum(grads["embed"], pipe_axis)
        grads["head"] = lax.psum(grads["head"], pipe_axis)
        metrics = {"loss": loss, "accuracy": acc}
        if moe:
            metrics["aux_loss"] = aux
        if data_axis is not None:
            # DPxPP: average gradients and metrics across pipeline replicas.
            grads = lax.pmean(grads, data_axis)
            metrics = lax.pmean(metrics, data_axis)
        return grads, metrics

    def _build(template_params):
        specs = _spec_tree(template_params, pipe_axis)
        tok_spec = P() if data_axis is None else P(data_axis)
        smapped = jax.shard_map(
            grad_fn, mesh=mesh,
            in_specs=(specs, tok_spec, tok_spec),
            out_specs=(specs, P()),
            check_vma=False)

        def _step(state: TrainState, inputs, targets):
            grads, metrics = smapped(state.params, inputs, targets)
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
            return TrainState(new_params, {}, new_opt, state.step + 1), metrics

        return jax.jit(_step, donate_argnums=(0,) if donate else ())

    _jits: dict = {}

    def stepper(state: TrainState, inputs, targets):
        key = jax.tree.structure(state)
        fn = _jits.get(key)
        if fn is None:
            fn = _jits[key] = _build(state.params)
        return fn(state, inputs, targets)

    def place_state(state: TrainState) -> TrainState:
        specs = _spec_tree(state.params, pipe_axis)
        psh = jax.tree.map(lambda s: NamedSharding(mesh, s), specs)
        repl = NamedSharding(mesh, P())
        bps = model.depth // n

        def opt_sharding(leaf):
            # Optimizer moments mirror the params tree; stacked stage leaves
            # are exactly the ones whose leading dims are (n_stages, bps) —
            # shard those with the stages, replicate everything else
            # (including adam's count scalar).
            shape = getattr(leaf, "shape", ())
            if len(shape) >= 2 and tuple(shape[:2]) == (n, bps):
                return NamedSharding(mesh, P(pipe_axis))
            return repl

        return TrainState(
            params=jax.tree.map(jax.device_put, state.params, psh),
            batch_stats={},
            opt_state=jax.tree.map(
                lambda leaf: jax.device_put(leaf, opt_sharding(leaf)),
                state.opt_state),
            step=jax.device_put(state.step, repl),
        )

    stepper.place_state = place_state  # type: ignore[attr-defined]
    return stepper


def init_pp_state(model: TransformerLM, tx: optax.GradientTransformation,
                  mesh: Mesh, rng: jax.Array,
                  pipe_axis: str = PIPE_AXIS) -> TrainState:
    """Init a TransformerLM and restructure into placed pipeline TrainState."""
    from ddw_tpu.train.lm_step import init_lm_state

    base = init_lm_state(model, tx, rng)
    n = mesh.shape[pipe_axis]
    pp = pp_params_from_lm(base.params, n, model.depth)
    state = TrainState(pp, {}, tx.init(pp), jnp.zeros((), jnp.int32))
    return state
