from ddw_tpu.parallel.ring_attention import ring_attention  # noqa: F401
from ddw_tpu.parallel.zero import (  # noqa: F401
    make_fsdp_train_chain,
    make_fsdp_train_step,
    make_zero_train_chain,
    make_zero_train_step,
)
from ddw_tpu.parallel.sharding import (  # noqa: F401
    PartitionRules,
    VIT_TP_RULES,
    shardings_for_params,
    make_sharded_train_step,
)
