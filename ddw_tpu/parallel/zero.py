"""ZeRO-family sharding over the data axis (GSPMD formulation).

Not in the reference — its optimizer state is fully replicated (SURVEY.md §2d
"ZeRO/FSDP-style optimizer sharding: NO") — but sharded training state is a
first-class capability of this framework: Adam moments are 2x the param bytes,
and on a data-parallel mesh each replica only needs 1/N of them.

TPU-idiomatic formulation (the scaling-book recipe): annotate the state leaves
with shardings that split their largest divisible dimension over the data
axis, and let XLA's GSPMD partitioner derive the communication schedule
instead of hand-writing it:

- **ZeRO-1** (``make_zero_train_step``): params and batch replicated,
  optimizer-state leaves sharded. The gradient all-reduce becomes
  reduce-scatter into the moment shards, each device updates only its slice,
  and the parameter update all-gathers back to replicated. Because the
  reduce-scatter happens as gradients feed the sharded moments *inside* the
  compiled step, full gradients never persist per-device — the formulation
  also delivers ZeRO-2's gradient-memory behavior for free.
- **ZeRO-3 / FSDP** (``make_fsdp_train_step``): params AND optimizer state
  sharded; each device holds 1/N of the model. GSPMD inserts per-layer
  all-gathers where the forward/backward consume full weights (weights are
  transient, not resident) and reduce-scatters gradients into the param/
  moment shards — the FSDP schedule, compiler-emitted.

Leaves with no dimension divisible by the axis size (e.g. 3x3 conv kernels
with leading dim 3) stay replicated — correctness is unaffected, only their
memory saving is forfeited. ``zero_fraction_sharded`` reports the coverage.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddw_tpu.runtime.mesh import DATA_AXIS
from ddw_tpu.train.step import TrainState, apply_gradients, forward_and_grads


def _leaf_spec(shape: tuple[int, ...], n: int, axis: str,
               exclude: frozenset[int] = frozenset()) -> P:
    """Shard the largest dimension divisible by ``n``; replicate if none.
    ``exclude`` marks dims already owned by another axis (the 2D path)."""
    best = None
    for d, s in enumerate(shape):
        if d in exclude:
            continue
        if s % n == 0 and s >= n and (best is None or s > shape[best]):
            best = d
    if best is None:
        return P()
    spec = [None] * len(shape)
    spec[best] = axis
    return P(*spec)


def zero_state_shardings(state: TrainState, mesh: Mesh,
                         axis: str = DATA_AXIS) -> TrainState:
    """Shardings for a TrainState under ZeRO-1: params/batch_stats/step
    replicated, optimizer-state leaves sharded over ``axis``."""
    n = mesh.shape[axis]
    repl = NamedSharding(mesh, P())

    def opt_spec(leaf):
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, _leaf_spec(tuple(shape), n, axis))

    return TrainState(
        params=jax.tree.map(lambda _: repl, state.params),
        batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
        opt_state=jax.tree.map(opt_spec, state.opt_state),
        step=repl,
    )


def fsdp_state_shardings(state: TrainState, mesh: Mesh,
                         axis: str = DATA_AXIS) -> TrainState:
    """Shardings for a TrainState under ZeRO-3/FSDP: params and optimizer
    state sharded over ``axis`` (moments land on the same spec as their param
    because they share its shape), batch_stats/step replicated (they are tiny
    and BN stats are all-reduced anyway)."""
    n = mesh.shape[axis]
    repl = NamedSharding(mesh, P())

    def spec(leaf):
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, _leaf_spec(tuple(shape), n, axis))

    return TrainState(
        params=jax.tree.map(spec, state.params),
        batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
        opt_state=jax.tree.map(spec, state.opt_state),
        step=repl,
    )


def _fraction_sharded(tree, mesh: Mesh, axis: str) -> float:
    n = mesh.shape[axis]
    total = sharded = 0
    for leaf in jax.tree.leaves(tree):
        size = getattr(leaf, "size", 0)
        if not size:
            continue
        total += size
        if _leaf_spec(tuple(leaf.shape), n, axis) != P():
            sharded += size
    return sharded / total if total else 0.0


def zero_fraction_sharded(state: TrainState, mesh: Mesh,
                          axis: str = DATA_AXIS) -> float:
    """Fraction of optimizer-state elements whose leaves actually shard."""
    return _fraction_sharded(state.opt_state, mesh, axis)


def fsdp_fraction_sharded(state: TrainState, mesh: Mesh,
                          axis: str = DATA_AXIS) -> float:
    """Fraction of parameter elements whose leaves actually shard."""
    return _fraction_sharded(state.params, mesh, axis)


def fsdp_tp_state_shardings(state: TrainState, mesh: Mesh, rules,
                            axis: str = DATA_AXIS) -> TrainState:
    """2D shardings: tensor-parallel dims per ``rules`` (model axis), then
    FSDP over ``axis`` on the largest still-unsharded divisible dim of every
    param/opt leaf — the scaling-book 2D recipe (params live as [data x
    model] tiles; GSPMD emits per-layer all-gathers over ``axis`` and the
    Megatron activation reductions over the model axis).

    Works on any tree whose leaf paths end with the rule suffixes — Adam
    moments and the EMA shadow mirror param paths, so they tile identically.
    """
    from ddw_tpu.parallel.sharding import _path_key, check_spec_divisibility

    n = mesh.shape[axis]
    repl = NamedSharding(mesh, P())

    def to_sharding(path, leaf):
        key = _path_key(path)
        shape = tuple(getattr(leaf, "shape", ()))
        base = rules.spec_for(key, len(shape))
        check_spec_divisibility(key, shape, base, mesh)
        spec = list(base) + [None] * (len(shape) - len(base))
        taken = frozenset(d for d, ax in enumerate(spec) if ax is not None)
        fsdp = _leaf_spec(shape, n, axis, exclude=taken)
        for d, ax in enumerate(fsdp):
            if ax is not None:
                spec[d] = ax
        return NamedSharding(mesh, P(*spec))

    def tree_sh(tree):
        return jax.tree_util.tree_map_with_path(to_sharding, tree)

    return TrainState(
        params=tree_sh(state.params),
        batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
        opt_state=tree_sh(state.opt_state),
        step=repl,
    )


def make_fsdp_tp_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    rules,
    axis: str = DATA_AXIS,
    donate: bool = True,
    grad_accum_steps: int = 1,
) -> Callable:
    """2D FSDP x TP train step over a ``(data, model)`` mesh.

    Same call contract as :func:`make_fsdp_train_step`; params and optimizer
    state tile over BOTH axes (:func:`fsdp_tp_state_shardings` with e.g.
    ``ddw_tpu.parallel.sharding.VIT_TP_RULES``), the batch shards over
    ``axis``. XLA inserts the Megatron collectives over the model axis and
    the FSDP gather/reduce-scatter over the data axis from the annotations
    alone. Numerics pinned against the plain DP step.
    """
    def shardings_fn(state, mesh_, axis_):
        return fsdp_tp_state_shardings(state, mesh_, rules, axis_)

    return _make_sharded_state_step(shardings_fn, model, tx, mesh,
                                    axis, donate, grad_accum_steps)


def _global_microbatches(x, accum: int, mesh: Mesh, axis: str):
    """Split a globally-sharded batch into ``accum`` interleaved microbatches
    ``[accum, B/accum, ...]``.

    Interleaved (row i goes to microbatch ``i % accum``), not contiguous:
    the batch dim is block-sharded over ``axis``, so interleaving keeps every
    device contributing ``B/(accum*n)`` of each microbatch — the sharding
    constraint below is then a device-local transpose, no cross-device
    data movement. Any equal-size partition gives identical optimizer math
    (mean of microbatch means == full-batch mean)."""
    b = x.shape[0]
    if b % accum:
        raise ValueError(f"global batch {b} not divisible by "
                         f"grad_accum_steps {accum}")
    mb = b // accum
    n_dev = mesh.shape[axis]
    if mb % n_dev:
        raise ValueError(
            f"microbatch size {mb} (global batch {b} / grad_accum_steps "
            f"{accum}) not divisible by the '{axis}' axis size {n_dev}; the "
            f"interleaved split would force uneven sharding instead of the "
            f"device-local transpose this path guarantees")
    x = jnp.moveaxis(x.reshape(mb, accum, *x.shape[1:]), 1, 0)
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(None, axis)))


def _make_sharded_step_body(model, tx: optax.GradientTransformation,
                            mesh: Mesh, axis: str, grad_accum_steps: int):
    """The single-update GSPMD body shared by the per-step stepper
    (:func:`_make_sharded_state_step`) and the fused K-step chain
    (:func:`_make_sharded_state_chain`)."""

    def _step(state: TrainState, images, labels, rng):
        dropout_rng = jax.random.fold_in(rng, state.step)
        if grad_accum_steps > 1:
            from ddw_tpu.train.step import scan_microbatches

            im = _global_microbatches(images, grad_accum_steps, mesh, axis)
            lb = _global_microbatches(labels, grad_accum_steps, mesh, axis)
            loss, acc, new_bs, grads = scan_microbatches(
                model, state, im, lb, dropout_rng)
        else:
            loss, acc, new_bs, grads = forward_and_grads(
                model, state, images, labels, dropout_rng)
        # No explicit psum: GSPMD derives the collective schedule from the
        # state shardings. ZeRO-1 (params replicated, moments sharded):
        # gradients reduce-scatter into the moment shards, the param update
        # all-gathers back to replicated. FSDP (params sharded too): per-layer
        # all-gathers where fwd/bwd consume full weights, reduce-scatter of
        # gradients into the param/moment shards.
        new_state = apply_gradients(state, tx, grads, new_bs)
        return new_state, {"loss": loss, "accuracy": acc}

    return _step


def _make_sharded_state_step(
    shardings_fn,
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    donate: bool = True,
    grad_accum_steps: int = 1,
) -> Callable:
    """Shared factory behind the ZeRO-1 and FSDP steps: a jit'd DP step whose
    TrainState in/out shardings come from ``shardings_fn(state, mesh, axis)``;
    GSPMD derives the collective schedule from those annotations.
    ``grad_accum_steps > 1`` scans interleaved global microbatches
    (:func:`_global_microbatches`) — 1/accum the activation memory, the same
    optimizer math, and each microbatch's gradients reduce-scatter straight
    into the sharded accumulator."""
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(axis))

    _step = _make_sharded_step_body(model, tx, mesh, axis, grad_accum_steps)

    def place_state(state: TrainState) -> TrainState:
        sh = shardings_fn(state, mesh, axis)
        return jax.tree.map(jax.device_put, state, sh)

    # Built per state structure+shapes: the in/out shardings are derived from
    # the concrete TrainState, so a structurally different state (different
    # optimizer/model, restored checkpoint with extra leaves) must get its own
    # jit instead of hitting a stale-sharding pytree mismatch.
    _jits: dict = {}

    def stepper(state, images, labels, rng):
        key = (jax.tree.structure(state),
               tuple(tuple(l.shape) for l in jax.tree.leaves(state)))
        fn = _jits.get(key)
        if fn is None:
            state_sh = shardings_fn(state, mesh, axis)
            fn = _jits[key] = jax.jit(
                _step,
                in_shardings=(state_sh, batch_sh, batch_sh, repl),
                out_shardings=(state_sh, repl),
                donate_argnums=(0,) if donate else (),
            )
        return fn(state, images, labels, rng)

    stepper.place_state = place_state  # type: ignore[attr-defined]
    stepper.batch_sharding = batch_sh  # type: ignore[attr-defined]
    return stepper


def _make_sharded_state_chain(
    shardings_fn,
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    donate: bool = True,
    grad_accum_steps: int = 1,
) -> Callable:
    """Fused K-step chain over a sharded TrainState — the ZeRO/FSDP analog of
    :func:`ddw_tpu.train.step.make_train_chain`. ``lax.scan`` iterates the
    GSPMD step body K times inside one jit; each scanned step's gradients
    reduce-scatter straight into the sharded moments (and, under FSDP, the
    sharded params) exactly as the per-step program's do. The super-batch
    ``[K, B, ...]`` shards its batch dim over ``axis`` (chain dim unsharded);
    the TrainState donates (in-place param/moment aliasing — the buffers that
    matter at ZeRO scale). K comes from the input shape — one callable serves
    the full and the trailing partial chain lengths."""
    repl = NamedSharding(mesh, P())
    sup_sh = NamedSharding(mesh, P(None, axis))

    body = _make_sharded_step_body(model, tx, mesh, axis, grad_accum_steps)

    def _chain(state: TrainState, images, labels, rng):
        def scanned(st, xs):
            im, lb = xs
            return body(st, im, lb, rng)

        return jax.lax.scan(scanned, state, (images, labels))

    def place_state(state: TrainState) -> TrainState:
        sh = shardings_fn(state, mesh, axis)
        return jax.tree.map(jax.device_put, state, sh)

    # Keyed per state structure+shapes like the per-step stepper: the in/out
    # shardings are derived from the concrete TrainState.
    _jits: dict = {}

    def chain(state, images, labels, rng):
        key = (jax.tree.structure(state),
               tuple(tuple(l.shape) for l in jax.tree.leaves(state)))
        fn = _jits.get(key)
        if fn is None:
            state_sh = shardings_fn(state, mesh, axis)
            # Donate the STATE only: under explicit in_shardings lowering,
            # scan xs (the super-batch) can never alias an output, so jit
            # would warn "donated buffers were not usable" on every compile
            # — the no-copy-on-donate contract tests/test_chain.py pins. The
            # state aliases fully (params/moments update in place).
            fn = _jits[key] = jax.jit(
                _chain,
                in_shardings=(state_sh, sup_sh, sup_sh, repl),
                out_shardings=(state_sh, repl),
                donate_argnums=(0,) if donate else (),
            )
        return fn(state, images, labels, rng)

    chain.place_state = place_state  # type: ignore[attr-defined]
    chain.batch_sharding = NamedSharding(mesh, P(axis))  # per-step batches
    chain.super_batch_sharding = sup_sh  # type: ignore[attr-defined]
    return chain


def make_zero_train_chain(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    donate: bool = True,
    grad_accum_steps: int = 1,
) -> Callable:
    """Fused K-step chain with ZeRO-1 sharded optimizer state — same call
    contract as :func:`ddw_tpu.train.step.make_train_chain` but the moments
    live sharded (call ``chain.place_state(state)`` once, or reuse the
    per-step stepper's placement). Training result is identical to K
    sequential :func:`make_zero_train_step` dispatches (tests/test_chain.py)."""
    return _make_sharded_state_chain(zero_state_shardings, model, tx, mesh,
                                     axis, donate, grad_accum_steps)


def make_fsdp_train_chain(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    donate: bool = True,
    grad_accum_steps: int = 1,
) -> Callable:
    """Fused K-step chain with ZeRO-3/FSDP fully-sharded params + optimizer
    state; the per-layer all-gather / reduce-scatter schedule repeats inside
    the scan exactly as across K separate dispatches."""
    return _make_sharded_state_chain(fsdp_state_shardings, model, tx, mesh,
                                     axis, donate, grad_accum_steps)


def make_zero_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    donate: bool = True,
    grad_accum_steps: int = 1,
) -> Callable:
    """DP train step with ZeRO-1 sharded optimizer state.

    Same call contract as :func:`ddw_tpu.train.step.make_train_step` (state,
    images, labels, rng) -> (state, metrics) with the batch sharded over
    ``axis`` — but optimizer moments live sharded; call
    ``step.place_state(state)`` once before the first step.

    Semantics differences vs the shard_map DP step: (1) BatchNorm models
    normalize over the **global** batch here (sync-BN — XLA inserts per-layer
    mean/var all-reduces), not per local shard; statistically stronger but
    costs per-layer collectives. (2) Dropout masks are drawn from one stream
    over the global batch, not per-replica folded streams. Both steps are
    correct DP training; bit-exact equivalence with ``make_train_step`` holds
    for stateless-norm models at dropout=0 (what the equivalence test pins).
    """
    return _make_sharded_state_step(zero_state_shardings, model, tx, mesh,
                                    axis, donate, grad_accum_steps)


def make_fsdp_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    donate: bool = True,
    grad_accum_steps: int = 1,
) -> Callable:
    """DP train step with ZeRO-3/FSDP fully-sharded params + optimizer state.

    Same call contract and sync-BN/dropout semantics as
    :func:`make_zero_train_step`; additionally every divisible parameter leaf
    lives sharded over ``axis``, so per-device residency is ~1/N of the model
    plus transient all-gathered weights during the step (GSPMD inserts the
    per-layer all-gather/reduce-scatter pairs). Numerically identical to the
    ZeRO-1 and plain-DP steps for stateless-norm models at dropout=0 (pinned
    by the equivalence tests) — sharding placement does not change the math.
    """
    return _make_sharded_state_step(fsdp_state_shardings, model, tx, mesh,
                                    axis, donate, grad_accum_steps)
