"""ZeRO-1 optimizer-state sharding over the data axis (GSPMD formulation).

Not in the reference — its optimizer state is fully replicated (SURVEY.md §2d
"ZeRO/FSDP-style optimizer sharding: NO") — but sharded optimizer state is a
first-class capability of this framework: Adam moments are 2x the param bytes,
and on a data-parallel mesh each replica only needs 1/N of them.

TPU-idiomatic formulation (the scaling-book recipe): keep params and batch
replicated-over-``data`` exactly as the plain DP step does, but annotate every
optimizer-state leaf with a sharding that splits its largest divisible dimension
over the data axis. XLA's GSPMD partitioner then derives the rest: the gradient
all-reduce becomes reduce-scatter into the moment shards, each device updates
only its slice, and the parameter update all-gathers back to replicated — the
ZeRO-1 communication schedule, emitted by the compiler instead of hand-written.

Leaves with no dimension divisible by the axis size (e.g. 3x3 conv kernels with
leading dim 3) stay replicated — correctness is unaffected, only their memory
saving is forfeited. ``zero_fraction_sharded`` reports the coverage.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddw_tpu.runtime.mesh import DATA_AXIS
from ddw_tpu.train.step import TrainState, apply_gradients, forward_and_grads


def _leaf_spec(shape: tuple[int, ...], n: int, axis: str) -> P:
    """Shard the largest dimension divisible by ``n``; replicate if none."""
    best = None
    for d, s in enumerate(shape):
        if s % n == 0 and s >= n and (best is None or s > shape[best]):
            best = d
    if best is None:
        return P()
    spec = [None] * len(shape)
    spec[best] = axis
    return P(*spec)


def zero_state_shardings(state: TrainState, mesh: Mesh,
                         axis: str = DATA_AXIS) -> TrainState:
    """Shardings for a TrainState under ZeRO-1: params/batch_stats/step
    replicated, optimizer-state leaves sharded over ``axis``."""
    n = mesh.shape[axis]
    repl = NamedSharding(mesh, P())

    def opt_spec(leaf):
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, _leaf_spec(tuple(shape), n, axis))

    return TrainState(
        params=jax.tree.map(lambda _: repl, state.params),
        batch_stats=jax.tree.map(lambda _: repl, state.batch_stats),
        opt_state=jax.tree.map(opt_spec, state.opt_state),
        step=repl,
    )


def zero_fraction_sharded(state: TrainState, mesh: Mesh,
                          axis: str = DATA_AXIS) -> float:
    """Fraction of optimizer-state elements whose leaves actually shard."""
    n = mesh.shape[axis]
    total = sharded = 0
    for leaf in jax.tree.leaves(state.opt_state):
        size = getattr(leaf, "size", 0)
        if not size:
            continue
        total += size
        if _leaf_spec(tuple(leaf.shape), n, axis) != P():
            sharded += size
    return sharded / total if total else 0.0


def make_zero_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = DATA_AXIS,
    donate: bool = True,
) -> Callable:
    """DP train step with ZeRO-1 sharded optimizer state.

    Same call contract as :func:`ddw_tpu.train.step.make_train_step` (state,
    images, labels, rng) -> (state, metrics) with the batch sharded over
    ``axis`` — but optimizer moments live sharded; call
    ``step.place_state(state)`` once before the first step.

    Semantics differences vs the shard_map DP step: (1) BatchNorm models
    normalize over the **global** batch here (sync-BN — XLA inserts per-layer
    mean/var all-reduces), not per local shard; statistically stronger but
    costs per-layer collectives. (2) Dropout masks are drawn from one stream
    over the global batch, not per-replica folded streams. Both steps are
    correct DP training; bit-exact equivalence with ``make_train_step`` holds
    for stateless-norm models at dropout=0 (what the equivalence test pins).
    """
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(axis))

    def _step(state: TrainState, images, labels, rng):
        dropout_rng = jax.random.fold_in(rng, state.step)
        loss, acc, new_bs, grads = forward_and_grads(
            model, state, images, labels, dropout_rng)
        # No explicit psum: the batch is sharded and params are replicated, so
        # GSPMD inserts the gradient reduction — reduce-scatter into the
        # sharded moments, all-gather after the update (the ZeRO-1 schedule).
        new_state = apply_gradients(state, tx, grads, new_bs)
        return new_state, {"loss": loss, "accuracy": acc}

    def place_state(state: TrainState) -> TrainState:
        sh = zero_state_shardings(state, mesh, axis)
        return jax.tree.map(jax.device_put, state, sh)

    # Built per state structure+shapes: the in/out shardings are derived from
    # the concrete TrainState, so a structurally different state (different
    # optimizer/model, restored checkpoint with extra leaves) must get its own
    # jit instead of hitting a stale-sharding pytree mismatch.
    _jits: dict = {}

    def stepper(state, images, labels, rng):
        key = (jax.tree.structure(state),
               tuple(tuple(l.shape) for l in jax.tree.leaves(state)))
        fn = _jits.get(key)
        if fn is None:
            state_sh = zero_state_shardings(state, mesh, axis)
            fn = _jits[key] = jax.jit(
                _step,
                in_shardings=(state_sh, batch_sh, batch_sh, repl),
                out_shardings=(state_sh, repl),
                donate_argnums=(0,) if donate else (),
            )
        return fn(state, images, labels, rng)

    stepper.place_state = place_state  # type: ignore[attr-defined]
    stepper.batch_sharding = batch_sh  # type: ignore[attr-defined]
    return stepper
