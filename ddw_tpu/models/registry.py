"""Model registry: name -> flax module factory, driven by :class:`ModelCfg`.

The build_model role of the reference notebooks (a shared factory kept identical
across single-node and distributed variants — the equivalence-by-construction test
idiom, reference ``03_model_training_distributed.py:153-155``, SURVEY.md §4.2).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from ddw_tpu.utils.config import ModelCfg

MODEL_REGISTRY: dict[str, Callable] = {}


def register_model(name: str):
    def deco(fn):
        MODEL_REGISTRY[name] = fn
        return fn
    return deco


def build_model(cfg: ModelCfg):
    """Instantiate the flax module named by ``cfg.name``."""
    if cfg.name not in MODEL_REGISTRY:
        raise KeyError(f"unknown model {cfg.name!r}; have {sorted(MODEL_REGISTRY)}")
    model = MODEL_REGISTRY[cfg.name](cfg)
    if getattr(cfg, "lora_rank", 0) and not hasattr(model, "lora_rank"):
        # Only the attention families route lora_targets through
        # maybe_lora_dense; silently ignoring the field would full-fine-tune
        # while the user believes adapters are training.
        raise ValueError(f"{cfg.name!r} does not support LoRA "
                         f"(model.lora_rank); use the vit or LM families")
    if (getattr(cfg, "lora_rank", 0) and not cfg.pretrained_path):
        import warnings

        warnings.warn(
            f"{cfg.name}: lora_rank={cfg.lora_rank} with no pretrained_path "
            f"freezes a randomly initialized backbone under the adapters "
            f"(accuracy will stay near chance unless params are grafted "
            f"before training)", stacklevel=2)
    if (cfg.freeze_base and not cfg.pretrained_path
            and type(model).frozen_prefixes(True)):
        # freeze_base defaults True for the reference's transfer contract, but
        # a frozen *random* backbone trains only the head over noise features —
        # accuracy stays near chance. Unless the caller explicitly opts into
        # that (allow_frozen_random: mechanism tests, throughput benchmarks),
        # auto-unfreeze so the model actually trains.
        import dataclasses
        import warnings

        if cfg.allow_frozen_random:
            warnings.warn(
                f"{cfg.name}: freeze_base=True with no pretrained_path freezes "
                f"a randomly initialized backbone (accuracy will stay near "
                f"chance); allow_frozen_random=True keeps it frozen anyway",
                stacklevel=2)
        else:
            warnings.warn(
                f"{cfg.name}: freeze_base=True needs model.pretrained_path (a "
                f"converted-weights artifact; see ddw_tpu.models.convert) — "
                f"auto-unfreezing the randomly initialized backbone. Set "
                f"model.allow_frozen_random=true to keep it frozen.",
                stacklevel=2)
            model = MODEL_REGISTRY[cfg.name](
                dataclasses.replace(cfg, freeze_base=False))
    return model


def _dtype(cfg: ModelCfg):
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[cfg.dtype]


@register_model("mobilenet_v2")
def _mobilenet_v2(cfg: ModelCfg):
    from ddw_tpu.models.mobilenet_v2 import MobileNetV2

    return MobileNetV2(
        num_classes=cfg.num_classes,
        width_mult=cfg.width_mult,
        dropout=cfg.dropout,
        freeze_base=cfg.freeze_base,
        bn_momentum=cfg.bn_momentum,
        dtype=_dtype(cfg),
        stem_s2d=cfg.stem_s2d,
        dw_impl=cfg.dw_impl,
    )


@register_model("small_cnn")
def _small_cnn(cfg: ModelCfg):
    from ddw_tpu.models.cnn import SmallCNN

    return SmallCNN(num_classes=cfg.num_classes, dropout=cfg.dropout, dtype=_dtype(cfg))


@register_model("resnet18")
@register_model("resnet34")
@register_model("resnet50")
def _resnet(cfg: ModelCfg):
    from ddw_tpu.models.resnet import ResNet

    return ResNet(
        num_classes=cfg.num_classes,
        depth=int(cfg.name.removeprefix("resnet")),
        width_mult=cfg.width_mult,
        dropout=cfg.dropout,
        freeze_base=cfg.freeze_base,
        dtype=_dtype(cfg),
        stem_s2d=cfg.stem_s2d,
    )


@register_model("convnext_tiny")
@register_model("convnext_small")
def _convnext(cfg: ModelCfg):
    from ddw_tpu.models.convnext import ConvNeXt

    if cfg.dw_impl != "xla":
        # The in-tree Pallas depthwise kernel is 3x3-only; ConvNeXt's 7x7
        # depthwise rides XLA's grouped-conv lowering by design. Silently
        # ignoring the knob would make a dw_impl A/B compare identical
        # programs.
        raise ValueError(
            f"convnext ignores model.dw_impl={cfg.dw_impl!r}: its 7x7 "
            f"depthwise always lowers via XLA (the Pallas kernel is "
            f"3x3-only — see ddw_tpu/ops/depthwise_conv.py); drop the "
            f"setting or use mobilenet_v2 for the Pallas arm")
    return ConvNeXt(
        num_classes=cfg.num_classes,
        variant=cfg.name.removeprefix("convnext_"),
        width_mult=cfg.width_mult,
        dropout=cfg.dropout,
        freeze_base=cfg.freeze_base,
        dtype=_dtype(cfg),
    )


@register_model("vit")
def _vit(cfg: ModelCfg):
    from ddw_tpu.models.vit import ViT

    kwargs = {}
    if cfg.num_heads:
        kwargs["num_heads"] = cfg.num_heads
    if cfg.hidden:
        # mlp_dim keeps the 4x ratio the default geometry uses; everything
        # else (patch, depth) is shape-independent of width
        kwargs["hidden"] = cfg.hidden
        kwargs["mlp_dim"] = 4 * cfg.hidden
    return ViT(num_classes=cfg.num_classes, dropout=cfg.dropout, dtype=_dtype(cfg),
               lora_rank=cfg.lora_rank, lora_alpha=cfg.lora_alpha,
               lora_targets=tuple(cfg.lora_targets), **kwargs)
