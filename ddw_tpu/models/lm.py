"""Decoder-only transformer LM — the long-context model family.

The reference stack has no language model and no attention (SURVEY.md §5
"Long-context ... Absent"); this family exists because long-context and model
sharding are first-class axes of this framework, not parity items. The same
module runs three ways off one definition:

- single device: causal flash attention (:mod:`ddw_tpu.ops.flash_attention`);
- sequence parallel: construct with ``seq_axis='seq'`` and call inside
  ``shard_map`` with tokens sharded on the sequence dim — attention becomes
  ring attention (K/V shards rotating by ``ppermute``,
  :mod:`ddw_tpu.parallel.ring_attention`) and position embeddings are sliced at
  the shard's global offset (``lax.axis_index * S_local``);
- tensor parallel: submodule names (``attn/{query,key,value,out}``,
  ``mlp/{fc1,fc2}``) match :data:`ddw_tpu.parallel.sharding.LM_TP_RULES`, so the
  GSPMD path shards heads/MLP over the ``model`` axis with no model changes.

Pre-LN blocks, learned positional embeddings, weight-untied vocab head.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ddw_tpu.ops.flash_attention import flash_attention
from ddw_tpu.parallel.ring_attention import ring_attention


class CausalSelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    seq_axis: str | None = None

    @nn.compact
    def __call__(self, x):
        d = x.shape[-1]
        head_dim = d // self.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim), dtype=self.dtype, name=name)
        # [B, S, H, hd] -> [B, H, S, hd]
        q = dense("query")(x).transpose(0, 2, 1, 3)
        k = dense("key")(x).transpose(0, 2, 1, 3)
        v = dense("value")(x).transpose(0, 2, 1, 3)
        if self.seq_axis is not None:
            out = ring_attention(q, k, v, self.seq_axis, causal=True)
        else:
            out = flash_attention(q, k, v, causal=True)
        out = out.transpose(0, 2, 1, 3)  # [B, S, H, hd]
        return nn.DenseGeneral(d, axis=(-2, -1), dtype=self.dtype, name="out")(out)


class DecoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    seq_axis: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = CausalSelfAttention(self.num_heads, self.dtype, self.seq_axis,
                                name="attn")(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        d = x.shape[-1]
        h = nn.Dense(self.mlp_dim, dtype=self.dtype, name="fc1")(h)
        h = nn.gelu(h)
        h = nn.Dense(d, dtype=self.dtype, name="fc2")(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return x + h


class TransformerLM(nn.Module):
    """Decoder-only LM over integer token ids.

    ``__call__(tokens[B, S]) -> logits[B, S, vocab]``. With ``seq_axis`` set the
    module must run inside ``shard_map`` with ``tokens`` sharded along the
    sequence dim; S is then the local shard length and positions are offset by
    the shard index. ``max_len`` bounds the *global* sequence length.
    """

    vocab_size: int = 256
    max_len: int = 2048
    hidden: int = 256
    depth: int = 4
    num_heads: int = 4
    mlp_dim: int = 1024
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    seq_axis: str | None = None

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        b, s_local = tokens.shape
        x = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype,
                     name="tok_embed")(tokens)
        pos_table = self.param("pos_embed", nn.initializers.normal(0.02),
                               (self.max_len, self.hidden), jnp.float32)
        if self.seq_axis is not None:
            # Global length = s_local * axis_size must fit the position table:
            # dynamic_slice clamps out-of-range offsets, which would silently
            # reuse the last positions on trailing shards instead of failing.
            n_shards = lax.axis_size(self.seq_axis)
            if s_local * n_shards > self.max_len:
                raise ValueError(
                    f"global sequence {s_local}*{n_shards} exceeds max_len "
                    f"{self.max_len}")
            offset = lax.axis_index(self.seq_axis) * s_local
        else:
            offset = 0
        pos = lax.dynamic_slice_in_dim(pos_table, offset, s_local, axis=0)
        x = x + pos.astype(self.dtype)[None]
        for i in range(self.depth):
            x = DecoderBlock(self.num_heads, self.mlp_dim, self.dropout,
                             self.dtype, self.seq_axis,
                             name=f"backbone_block{i}")(x, train)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        # vocab head in f32: logits feed a softmax CE, keep full precision
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="head")(x)

    @staticmethod
    def frozen_prefixes(freeze_base: bool) -> tuple[str, ...]:
        return ()


def build_lm(cfg, seq_axis: str | None = None) -> TransformerLM:
    """Construct from an :class:`ddw_tpu.utils.config.LMCfg`."""
    return TransformerLM(
        vocab_size=cfg.vocab_size, max_len=cfg.max_len, hidden=cfg.hidden,
        depth=cfg.depth, num_heads=cfg.num_heads, mlp_dim=cfg.mlp_dim,
        dropout=cfg.dropout, dtype=jnp.dtype(cfg.dtype), seq_axis=seq_axis)
