"""Decoder-only transformer LM — the long-context model family.

The reference stack has no language model and no attention (SURVEY.md §5
"Long-context ... Absent"); this family exists because long-context and model
sharding are first-class axes of this framework, not parity items. The same
module runs three ways off one definition:

- single device: causal flash attention (:mod:`ddw_tpu.ops.flash_attention`);
- sequence parallel: construct with ``seq_axis='seq'`` and call inside
  ``shard_map`` with tokens sharded on the sequence dim — attention becomes
  ring attention (K/V shards rotating by ``ppermute``,
  :mod:`ddw_tpu.parallel.ring_attention`) and position embeddings are sliced at
  the shard's global offset (``lax.axis_index * S_local``);
- tensor parallel: submodule names (``attn/{query,key,value,out}``,
  ``mlp/{fc1,fc2}``) match :data:`ddw_tpu.parallel.sharding.LM_TP_RULES`, so the
  GSPMD path shards heads/MLP over the ``model`` axis with no model changes.

Pre-LN blocks, learned positional embeddings, weight-untied vocab head.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ddw_tpu.utils.compat import axis_size

from ddw_tpu.ops.flash_attention import flash_mha
from ddw_tpu.parallel.ring_attention import ring_attention


class CausalSelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    seq_axis: str | None = None
    decode: bool = False     # autoregressive mode: KV cache, one token per call
    max_len: int = 2048      # cache capacity in decode mode
    slot_decode: bool = False  # continuous-batching mode: the cache batch dim
                             # is a pool of serving slots, each at its OWN
                             # depth — cache_index becomes a [B] vector, K/V
                             # writes scatter per row, and masking/overflow
                             # go per-row (ddw_tpu.serve.slots). S must be 1.
    paged_decode: bool = False  # paged continuous batching: K/V live in a
                             # GLOBAL pool of kv_cache_blocks fixed-size
                             # blocks instead of per-row contiguous strips;
                             # each call takes per-row block tables (gather
                             # indices) and start positions as ARGUMENTS, so
                             # the cache tree is batch-independent — one pool
                             # serves prefill groups and the decode batch
                             # alike (ddw_tpu.serve.blocks). Any S works
                             # (S>1 = chunked/suffix prefill into blocks;
                             # speculative verify rides this same path — one
                             # S=k+1 call scores a row's draft block with
                             # intra-block causality, BlockPool.spec_verify).
    kv_cache_blocks: int = 0  # paged mode: usable blocks + 1 null block
    kv_block_size: int = 0   # paged mode: tokens per block; must divide the
                             # attention tile so the gathered view is laid
                             # out exactly like the contiguous cache (that
                             # layout equality is what makes paged outputs
                             # bit-identical to the sequential path)
    num_kv_heads: int = 0    # GQA (Ainslie et al. 2305.13245): 0 = num_heads
                             # (MHA); fewer KV heads shrink the k/v params and
                             # the decode cache by H/KV; K/V broadcast to the
                             # full head count at compute time
    lora_rank: int = 0       # >0: rank-r adapters on lora_targets projections
    lora_alpha: float = 16.0
    lora_targets: tuple[str, ...] = ("query", "value")

    @nn.compact
    def __call__(self, x, positions=None, block_tables=None, start_pos=None,
                 adapters=None):
        from ddw_tpu.models.lora import maybe_lora_dense, row_lora_delta

        b, s, d = x.shape
        head_dim = d // self.num_heads
        kv_heads = self.num_kv_heads or self.num_heads
        if self.num_heads % kv_heads:
            raise ValueError(f"num_heads {self.num_heads} not divisible by "
                             f"num_kv_heads {kv_heads}")
        groups = self.num_heads // kv_heads

        def dense(name, heads=self.num_heads):
            return maybe_lora_dense((heads, head_dim), name,
                                    rank=self.lora_rank, alpha=self.lora_alpha,
                                    targets=self.lora_targets, dtype=self.dtype)

        def with_delta(name, y, x_in, cn=1):
            # hot-swapped per-row adapter delta (serving path); the delta is
            # added where LoRADenseGeneral would add a trained one — before
            # RoPE and before the cache write
            ab = (adapters or {}).get(name)
            if ab is None:
                return y
            return y + row_lora_delta(x_in, ab[0], ab[1], cn).astype(y.dtype)

        q = with_delta("query", dense("query")(x), x)         # [B, S, H, hd]
        k = with_delta("key", dense("key", kv_heads)(x), x)   # [B, S, KV, hd]
        v = with_delta("value", dense("value", kv_heads)(x), x)
        if positions is not None:
            # RoPE: rotate q/k by ABSOLUTE position before any cache write or
            # ring hop — scores then depend only on relative distance, so the
            # cached/ring-shipped K needs no further position plumbing.
            from ddw_tpu.ops.rope import apply_rope

            q = apply_rope(q, positions, seq_axis=1)
            k = apply_rope(k, positions, seq_axis=1)

        if self.decode:
            # KV cache: accepts S tokens per call (S>1 = batched prefill, S=1 =
            # per-token decode). Attention runs TILED over the cache with
            # online softmax, and tiles past the filled position are skipped at
            # runtime (lax.cond) — per-token cost scales with the generated
            # length in TILE-sized increments instead of paying O(max_len)
            # every call (VERDICT r1 weak #4). Writes past max_len poison the
            # output with NaN (loud failure) instead of silently clamping.
            tile = min(256, self.max_len)
            cap = -(-self.max_len // tile) * tile  # capacity, tile multiple
            # GQA: the cache holds KV heads only — the H/KV memory saving is
            # exactly what grouped queries exist for at generation time
            if self.slot_decode and s != 1:
                raise ValueError(f"slot_decode processes one token per slot "
                                 f"per call, got S={s}")
            # cumulative count of KV tiles actually computed — observability
            # hook proving the skip logic works (test_lm pins it); costs one
            # scalar add per call.
            tiles = self.variable("cache", "tiles_computed",
                                  lambda: jnp.zeros((), jnp.int32))
            if self.paged_decode:
                # Paged KV (vLLM lineage, arXiv 2309.06180): the cache is a
                # GLOBAL pool of fixed-size blocks, and this row's K/V lives
                # wherever its block table points. The table is padded to
                # cap // block_size entries (unallocated tail -> block 0,
                # the reserved null block), so gathering blocks back in
                # table order reconstructs EXACTLY the contiguous [cap]
                # layout — the tile loop below then runs unchanged on the
                # gathered view, which is what keeps paged decode
                # bit-identical to the contiguous path.
                bs = self.kv_block_size
                if bs < 1 or tile % bs:
                    raise ValueError(
                        f"kv_block_size {bs} must be >= 1 and divide the "
                        f"attention tile {tile}")
                if self.kv_cache_blocks < 2:
                    raise ValueError("paged_decode needs kv_cache_blocks >= 2"
                                     " (block 0 is the reserved null block)")
                n_tbl = cap // bs
                if start_pos is None:
                    start_pos = jnp.zeros((b,), jnp.int32)
                if block_tables is None:
                    block_tables = jnp.zeros((b, n_tbl), jnp.int32)
                ck = self.variable("cache", "kv_block_key", jnp.zeros,
                                   (self.kv_cache_blocks, bs, kv_heads,
                                    head_dim), k.dtype)
                cv = self.variable("cache", "kv_block_value", jnp.zeros,
                                   (self.kv_cache_blocks, bs, kv_heads,
                                    head_dim), v.dtype)
                pos = start_pos                       # [B] per-row depths
                p = pos[:, None] + jnp.arange(s)      # [B, S] write positions
                # out-of-capacity writes (a finished row's chain overshoot)
                # are routed to the null block instead of clamp-corrupting a
                # real one; unallocated table entries are already 0
                safe = p < cap
                entry = jnp.take_along_axis(
                    block_tables, jnp.clip(p // bs, 0, n_tbl - 1), axis=1)
                bt = jnp.where(safe, entry, 0)
                off = jnp.where(safe, p % bs, 0)
                ck.value = ck.value.at[bt, off].set(k)
                cv.value = cv.value.at[bt, off].set(v)
                # gather-back: [B, n_tbl, bs, ...] -> contiguous [B, cap, ...]
                src_k = ck.value[block_tables].reshape(
                    b, cap, kv_heads, head_dim)
                src_v = cv.value[block_tables].reshape(
                    b, cap, kv_heads, head_dim)
            else:
                ck = self.variable("cache", "cached_key", jnp.zeros,
                                   (b, cap, kv_heads, head_dim), k.dtype)
                cv = self.variable("cache", "cached_value", jnp.zeros,
                                   (b, cap, kv_heads, head_dim), v.dtype)
                idx = self.variable(
                    "cache", "cache_index",
                    lambda: jnp.zeros((b,) if self.slot_decode else (),
                                      jnp.int32))
                pos = idx.value
                if self.slot_decode:
                    # per-row write: each slot appends at its own depth
                    row_write = jax.vmap(
                        lambda c, t, p: lax.dynamic_update_slice(
                            c, t, (p, 0, 0)))
                    ck.value = row_write(ck.value, k, pos)
                    cv.value = row_write(cv.value, v, pos)
                else:
                    ck.value = lax.dynamic_update_slice(
                        ck.value, k, (0, pos, 0, 0))
                    cv.value = lax.dynamic_update_slice(
                        cv.value, v, (0, pos, 0, 0))
                idx.value = pos + s
                src_k, src_v = ck.value, cv.value

            q32 = (q.astype(jnp.float32) / float(head_dim) ** 0.5
                   ).transpose(0, 2, 1, 3)          # [B, H, S, hd]
            if self.slot_decode or self.paged_decode:
                qpos = pos[:, None] + jnp.arange(s)  # [B, S] per-row positions
                last = jnp.max(pos) + s - 1          # deepest filled position
            else:
                qpos = pos + jnp.arange(s)          # [S] global query positions
                last = pos + s - 1                  # newest filled position
            # [B, S]: rows shallower than a tile mask it out entirely — the
            # masked tile's (m, l, o) update is an exact no-op (m carries over,
            # exp underflows to 0), so per-row results match a per-row skip.
            qpos_b = qpos if qpos.ndim == 2 else qpos[None]

            def tile_body(carry, t):
                start = t * tile

                def active(c):
                    m, l, o, cnt = c
                    k_t = lax.dynamic_slice_in_dim(
                        src_k, start, tile, axis=1).astype(jnp.float32)
                    v_t = lax.dynamic_slice_in_dim(
                        src_v, start, tile, axis=1).astype(jnp.float32)
                    if groups > 1:  # broadcast KV heads over their query group
                        k_t = jnp.repeat(k_t, groups, axis=2)
                        v_t = jnp.repeat(v_t, groups, axis=2)
                    s_t = jnp.einsum("bhqd,bkhd->bhqk", q32, k_t)  # [B,H,S,T]
                    kpos = start + jnp.arange(tile)
                    mask = kpos[None, None, None, :] <= qpos_b[:, None, :, None]
                    s_t = jnp.where(mask, s_t, -1e30)
                    m_new = jnp.maximum(m, s_t.max(-1))
                    p = jnp.exp(s_t - m_new[..., None])
                    scale = jnp.exp(m - m_new)
                    l_new = l * scale + p.sum(-1)
                    o_new = (o * scale[..., None]
                             + jnp.einsum("bhqk,bkhd->bhqd", p, v_t))
                    return m_new, l_new, o_new, cnt + 1

                return lax.cond(start <= last, active, lambda c: c, carry), None

            m0 = jnp.full((b, self.num_heads, s), -1e30, jnp.float32)
            l0 = jnp.zeros((b, self.num_heads, s), jnp.float32)
            o0 = jnp.zeros((b, self.num_heads, s, head_dim), jnp.float32)
            (m_f, l_f, o_f, n_tiles), _ = lax.scan(
                tile_body, (m0, l0, o0, jnp.zeros((), jnp.int32)),
                jnp.arange(cap // tile))
            tiles.value = tiles.value + n_tiles
            out = (o_f / l_f[..., None]).transpose(0, 2, 1, 3)  # [B,S,H,hd]
            # Hard failure on overflow: a write past max_len would have
            # clamp-overwritten the last cache rows; NaN-poison the result so
            # the caller cannot miss it (host-side raise is not possible for a
            # traced index). In slot mode only the overflowing ROW is poisoned
            # — other slots keep decoding.
            if self.paged_decode:
                # per-QUERY poison: a suffix prefill's padded bucket may
                # overshoot max_len while every real query is in range —
                # only the out-of-range (pad, discarded) queries go NaN
                overflow = (qpos >= self.max_len)[:, :, None, None]
            else:
                overflow = (pos + s) > self.max_len
                if self.slot_decode:
                    overflow = overflow[:, None, None, None]
            out = jnp.where(overflow, jnp.nan, out).astype(x.dtype)
        else:
            if groups > 1:
                # broadcast KV heads to the full head count: the flash/ring
                # kernels stay head-symmetric (the GQA win here is params,
                # not compute)
                k = jnp.repeat(k, groups, axis=2)
                v = jnp.repeat(v, groups, axis=2)
            # [B, S, H, hd] -> [B, H, S, hd] for the batched kernels
            qh, kh, vh = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
            if self.seq_axis is not None:
                out = ring_attention(qh, kh, vh, self.seq_axis, causal=True)
            else:
                # flash_mha auto-dispatches: fused XLA attention while the S²
                # score matrix fits (faster on TPU at moderate S — measured),
                # Pallas flash kernel for genuinely long context.
                out = flash_mha(qh, kh, vh, causal=True)
            out = out.transpose(0, 2, 1, 3)  # [B, S, H, hd]
        return with_delta(
            "out",
            maybe_lora_dense(d, "out", rank=self.lora_rank,
                             alpha=self.lora_alpha,
                             targets=self.lora_targets, dtype=self.dtype,
                             contract_ndim=2)(out),
            out, cn=2)


class DecoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    seq_axis: str | None = None
    decode: bool = False
    max_len: int = 2048
    slot_decode: bool = False
    num_experts: int = 0          # >0: MoE MLP (top-1/top-2) instead of dense
    expert_axis: str | None = None
    capacity_factor: float = 1.25
    moe_router: str = "top1"
    num_kv_heads: int = 0
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple[str, ...] = ("query", "value")
    paged_decode: bool = False
    kv_cache_blocks: int = 0
    kv_block_size: int = 0

    @nn.compact
    def __call__(self, x, train: bool, positions=None, block_tables=None,
                 start_pos=None, adapters=None):
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = CausalSelfAttention(self.num_heads, self.dtype, self.seq_axis,
                                self.decode, self.max_len,
                                slot_decode=self.slot_decode,
                                num_kv_heads=self.num_kv_heads,
                                lora_rank=self.lora_rank,
                                lora_alpha=self.lora_alpha,
                                lora_targets=self.lora_targets,
                                paged_decode=self.paged_decode,
                                kv_cache_blocks=self.kv_cache_blocks,
                                kv_block_size=self.kv_block_size,
                                name="attn")(h, positions=positions,
                                             block_tables=block_tables,
                                             start_pos=start_pos,
                                             adapters=adapters)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        if self.num_experts:
            from ddw_tpu.models.moe import MoEMlp

            h = MoEMlp(self.num_experts, self.mlp_dim,
                       capacity_factor=self.capacity_factor, dtype=self.dtype,
                       expert_axis=self.expert_axis, no_drop=self.decode,
                       router=self.moe_router, name="moe")(h)
        else:
            from ddw_tpu.models.lora import maybe_lora_dense, row_lora_delta

            d = x.shape[-1]

            def mlp_dense(feats, name, inp):
                y = maybe_lora_dense(feats, name, rank=self.lora_rank,
                                     alpha=self.lora_alpha,
                                     targets=self.lora_targets,
                                     dtype=self.dtype)(inp)
                ab = (adapters or {}).get(name)
                if ab is not None:
                    y = y + row_lora_delta(inp, ab[0], ab[1]).astype(y.dtype)
                return y

            h = mlp_dense(self.mlp_dim, "fc1", h)
            h = nn.gelu(h)
            h = mlp_dense(d, "fc2", h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return x + h


class TransformerLM(nn.Module):
    """Decoder-only LM over integer token ids.

    ``__call__(tokens[B, S]) -> logits[B, S, vocab]``. With ``seq_axis`` set the
    module must run inside ``shard_map`` with ``tokens`` sharded along the
    sequence dim; S is then the local shard length and positions are offset by
    the shard index. ``max_len`` bounds the *global* sequence length.
    """

    vocab_size: int = 256
    max_len: int = 2048
    hidden: int = 256
    depth: int = 4
    num_heads: int = 4
    mlp_dim: int = 1024
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16
    seq_axis: str | None = None
    decode: bool = False     # KV-cached autoregressive mode (see generate())
    slot_decode: bool = False  # continuous-batching decode: the batch dim is
                             # a serving slot pool, each row at its own depth
                             # (per-row cache/position indices; see
                             # ddw_tpu.serve.slots.SlotPool). Implies decode.
    paged_decode: bool = False  # paged continuous batching: K/V in a global
                             # fixed-size-block pool; per-row block tables
                             # and start positions are passed as ARGUMENTS
                             # (__call__(tokens, block_tables=, start_pos=))
                             # so the cache tree is batch-independent — the
                             # substrate of ddw_tpu.serve.blocks.BlockPool.
    kv_cache_blocks: int = 0  # paged: pool size (usable blocks + null)
    kv_block_size: int = 0   # paged: tokens per block (divides the tile)
    num_experts: int = 0     # >0: MoE MLP blocks (expert parallelism via
    expert_axis: str | None = None  # expert_axis inside shard_map)
    capacity_factor: float = 1.25
    moe_router: str = "top1"  # "top1" (Switch) or "top2" (GShard)
    num_kv_heads: int = 0    # GQA: KV heads (0 = num_heads); decode cache and
                             # k/v params shrink by num_heads/num_kv_heads
    lora_rank: int = 0       # >0: rank-r LoRA adapters (ddw_tpu.models.lora)
    lora_alpha: float = 16.0
    lora_targets: tuple[str, ...] = ("query", "value")
    pos_encoding: str = "learned"  # "learned" absolute table (bounded by
                                   # max_len) or "rope" rotary relative
                                   # positions (ddw_tpu.ops.rope)
    remat: str = "none"      # activation rematerialization per block:
                             # "none" | "full" (nothing saved — recompute the
                             # block in backward) | "dots" (save matmul
                             # outputs, recompute elementwise). Ignored in
                             # decode mode (no backward there).

    @nn.compact
    def __call__(self, tokens, train: bool = False, block_tables=None,
                 start_pos=None, adapters=None):
        # adapters: optional (stacks, idx) pair for heterogeneous-adapter
        # batched serving (ddw_tpu.serve.adapters.AdapterPool). ``stacks`` is
        # {f"backbone_block{i}": {target: (a_stack [S+1,*in,r],
        # b_stack [S+1,r,*feats])}} with slot 0 all-zeros (the null adapter);
        # ``idx`` is a per-row [B] int32 slot vector. The gather happens ONCE
        # here; each block then applies its row-wise delta. Passed as a call
        # ARGUMENT (like block_tables) so adapter churn never retraces.
        if self.lora_rank:
            from ddw_tpu.models.lora import validate_lora_targets

            validate_lora_targets(self.lora_targets)
        if self.pos_encoding not in ("learned", "rope"):
            raise ValueError(f"unknown pos_encoding {self.pos_encoding!r}; "
                             f"use 'learned' or 'rope'")
        if self.pos_encoding == "rope" and (self.hidden // self.num_heads) % 2:
            raise ValueError("RoPE needs an even head_dim")
        b, s_local = tokens.shape
        x = nn.Embed(self.vocab_size, self.hidden, dtype=self.dtype,
                     name="tok_embed")(tokens)
        if self.pos_encoding == "learned":
            pos_table = self.param("pos_embed", nn.initializers.normal(0.02),
                                   (self.max_len, self.hidden), jnp.float32)
        if self.decode and self.paged_decode:
            # paged mode: depth is per-request HOST state (the BlockPool's
            # stream records), handed in per call — no pos_index variable, so
            # the same cache tree serves a G-row prefill group and the
            # R-row decode batch without re-init.
            if start_pos is None:
                start_pos = jnp.zeros((b,), jnp.int32)
            offset = start_pos
        elif self.decode:
            # position = number of tokens already decoded (the attention layers
            # keep per-layer indices; this top-level one feeds the pos embed).
            # Past max_len the attention layers NaN-poison the output (loud
            # failure); generate() additionally raises host-side up front.
            # Slot mode keeps one position per pool row ([B] vector).
            pos_idx = self.variable(
                "cache", "pos_index",
                lambda: jnp.zeros((b,) if self.slot_decode else (),
                                  jnp.int32))
            offset = pos_idx.value
            pos_idx.value = offset + s_local
        elif self.seq_axis is not None:
            # Global length = s_local * axis_size must fit the position table:
            # dynamic_slice clamps out-of-range offsets, which would silently
            # reuse the last positions on trailing shards instead of failing.
            # (RoPE has no table — positions extrapolate, so SP sequences may
            # exceed max_len; only the decode cache stays bounded by it.)
            n_shards = axis_size(self.seq_axis)
            if (self.pos_encoding == "learned"
                    and s_local * n_shards > self.max_len):
                raise ValueError(
                    f"global sequence {s_local}*{n_shards} exceeds max_len "
                    f"{self.max_len}")
            offset = lax.axis_index(self.seq_axis) * s_local
        else:
            offset = 0
        if self.pos_encoding == "learned":
            if self.decode and (self.slot_decode or self.paged_decode):
                # per-row gather: row i reads the table at its own depth
                # (jnp.take clamps out-of-range rows — harmless, attention
                # NaN-poisons those rows anyway)
                rows = offset[:, None] + jnp.arange(s_local)  # [B, S]
                pos = jnp.take(pos_table, rows, axis=0)       # [B, S, hidden]
                x = x + pos.astype(self.dtype)
            else:
                pos = lax.dynamic_slice_in_dim(pos_table, offset, s_local,
                                               axis=0)
                x = x + pos.astype(self.dtype)[None]
            positions = None
        else:
            # RoPE: absolute positions feed the per-layer q/k rotation; no
            # table, no additive embedding. Works unchanged under SP (offset
            # = shard_index * s_local, K rotated before the ring) and decode
            # (offset = tokens already written to the cache; [B]-shaped in
            # slot mode, giving [B, S] per-row positions).
            if self.decode and (self.slot_decode or self.paged_decode):
                positions = offset[:, None] + jnp.arange(s_local)
            else:
                positions = offset + jnp.arange(s_local)
        if self.remat not in ("none", "full", "dots"):
            raise ValueError(f"unknown remat {self.remat!r}; use 'none', "
                             f"'full' or 'dots'")
        if self.remat != "none" and not self.decode:
            # Rematerialized blocks: backward recomputes the block forward
            # instead of keeping its activations resident — O(depth) fewer
            # live activations for ~1/3 more FLOPs ('full' keeps nothing;
            # 'dots' keeps matmul outputs, recomputing only elementwise ops).
            # The decode path never differentiates, so it stays un-wrapped.
            policy = (jax.checkpoint_policies.nothing_saveable
                      if self.remat == "full"
                      else jax.checkpoint_policies.checkpoint_dots)
            Block = nn.remat(DecoderBlock, static_argnums=(2,), policy=policy)
        else:
            Block = DecoderBlock
        paged_kw = (dict(block_tables=block_tables, start_pos=start_pos)
                    if self.paged_decode else {})
        row_adapters = None
        if adapters is not None:
            stacks, aidx = adapters
            aidx = jnp.asarray(aidx, jnp.int32)
            row_adapters = jax.tree.map(lambda st: jnp.asarray(st)[aidx],
                                        stacks)
        for i in range(self.depth):
            blk_kw = dict(paged_kw)
            if row_adapters is not None:
                blk_kw["adapters"] = row_adapters.get(f"backbone_block{i}")
            x = Block(self.num_heads, self.mlp_dim, self.dropout,
                      self.dtype, None if self.decode else self.seq_axis,
                      self.decode, self.max_len,
                      slot_decode=self.slot_decode,
                      num_experts=self.num_experts,
                      expert_axis=None if self.decode else self.expert_axis,
                      capacity_factor=self.capacity_factor,
                      moe_router=self.moe_router,
                      num_kv_heads=self.num_kv_heads,
                      lora_rank=self.lora_rank,
                      lora_alpha=self.lora_alpha,
                      lora_targets=self.lora_targets,
                      paged_decode=self.paged_decode,
                      kv_cache_blocks=self.kv_cache_blocks,
                      kv_block_size=self.kv_block_size,
                      name=f"backbone_block{i}")(x, train, positions,
                                                 **blk_kw)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        # vocab head in f32: logits feed a softmax CE, keep full precision
        return nn.Dense(self.vocab_size, dtype=jnp.float32, name="head")(x)

    @staticmethod
    def frozen_prefixes(freeze_base: bool) -> tuple[str, ...]:
        return ()


def build_lm(cfg, seq_axis: str | None = None,
             expert_axis: str | None = None) -> TransformerLM:
    """Construct from an :class:`ddw_tpu.utils.config.LMCfg`."""
    return TransformerLM(
        vocab_size=cfg.vocab_size, max_len=cfg.max_len, hidden=cfg.hidden,
        depth=cfg.depth, num_heads=cfg.num_heads, mlp_dim=cfg.mlp_dim,
        dropout=cfg.dropout, dtype=jnp.dtype(cfg.dtype), seq_axis=seq_axis,
        num_experts=cfg.num_experts, expert_axis=expert_axis,
        capacity_factor=cfg.capacity_factor,
        moe_router=getattr(cfg, "moe_router", "top1"),
        num_kv_heads=getattr(cfg, "num_kv_heads", 0),
        lora_rank=getattr(cfg, "lora_rank", 0),
        lora_alpha=getattr(cfg, "lora_alpha", 16.0),
        lora_targets=tuple(getattr(cfg, "lora_targets", ("query", "value"))),
        pos_encoding=getattr(cfg, "pos_encoding", "learned"),
        remat=getattr(cfg, "remat", "none"))


def init_cache(decode_model: TransformerLM, batch: int):
    """Fresh zeroed KV cache for ``decode_model`` (constructed with
    ``decode=True``). Shapes come from ``eval_shape`` (no param allocation or
    forward run; ``init`` itself would *run* a decode step and leave the dummy
    token in the returned cache)."""
    shapes = jax.eval_shape(
        lambda: decode_model.init({"params": jax.random.PRNGKey(0)},
                                  jnp.zeros((batch, 1), jnp.int32)))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"])


def set_cache_lengths(cache, length):
    """Rewrite every per-layer ``cache_index`` and the top-level ``pos_index``
    in a decode cache to ``length`` (broadcast to the leaf's shape). Used by
    padded-bucket prefill: the prompt is right-padded to a bucket, prefilled
    in one forward, then the indices snap back to the TRUE length so decode
    overwrites the pad garbage row by row (never attends it — positions past
    a query are causally masked, and the row at the write position is
    replaced before attention runs)."""
    def fix(path, leaf):
        name = getattr(path[-1], "key", None) if path else None
        if name in ("cache_index", "pos_index"):
            return jnp.full(leaf.shape, length, leaf.dtype)
        return leaf
    return jax.tree_util.tree_map_with_path(fix, cache)


def generate(model: TransformerLM, params, prompt, num_steps: int,
             rng: jax.Array | None = None, temperature: float = 0.0,
             top_k: int = 0, top_p: float = 0.0, prompt_len=None):
    """Autoregressive continuation via the KV-cached decode path.

    ``prompt`` is int32 ``[B, P]``; returns ``[B, num_steps]`` continuation
    tokens. Greedy when ``temperature == 0``, else categorical sampling with
    ``rng``; ``top_k > 0`` restricts sampling to the k highest logits and
    ``top_p > 0`` to the smallest nucleus whose probability mass reaches p
    (both masks compose: k first, then p). Total length ``P + num_steps``
    must fit ``model.max_len``. Prefill is one batched causal forward (bulk
    K/V cache write); decode is a ``lax.scan`` with O(1) per-token cost
    against the static-shape cache — the whole thing jits to one XLA program.

    ``prompt_len`` (optional, may be a traced scalar): the TRUE shared prompt
    length when ``prompt`` is right-padded to a shape bucket — continuation
    starts after position ``prompt_len - 1`` and decode overwrites the pad
    region. This is what lets callers jit one program per bucket instead of
    one per prompt length (:class:`ddw_tpu.serving.LMPackagedModel`).
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, plen = prompt.shape
    if plen > model.max_len or (
            prompt_len is None and plen + num_steps > model.max_len):
        raise ValueError(f"prompt {plen} + steps {num_steps} exceeds "
                         f"max_len {model.max_len}")
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if temperature != 0.0 and rng is None:
        raise ValueError("sampling (temperature > 0) requires rng")
    if top_k < 0:
        raise ValueError(f"top_k must be >= 0, got {top_k}")
    if not 0.0 <= top_p <= 1.0:
        raise ValueError(f"top_p must be in [0, 1], got {top_p}")
    if (top_k or top_p) and temperature == 0.0:
        raise ValueError("top_k/top_p require temperature > 0 (greedy decode "
                         "ignores them silently otherwise)")
    dm = model.clone(decode=True, seq_axis=None, dropout=0.0)
    cache = init_cache(dm, b)

    def run(cache, toks):
        logits, vars_ = dm.apply({"params": params, "cache": cache},
                                 toks, mutable=["cache"])
        return vars_["cache"], logits

    # Prefill: one batched causal forward writes the prompt's K/V in bulk.
    cache, prefill_logits = run(cache, prompt)
    if prompt_len is None:
        last_logits = prefill_logits[:, -1]
    else:
        # padded-bucket prefill: continue from the last REAL token and snap
        # the cache indices back so decode overwrites the pad region
        last_logits = jnp.take(prefill_logits,
                               jnp.asarray(prompt_len) - 1, axis=1)
        cache = set_cache_lengths(cache, jnp.asarray(prompt_len, jnp.int32))

    def pick(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits.astype(jnp.float32) / temperature
        if top_k:
            # keep the k highest logits per row; everything else -> -inf
            kth = lax.top_k(logits, min(top_k, logits.shape[-1]))[0][..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p:
            # nucleus: smallest prefix of the sorted distribution with
            # cumulative probability >= top_p stays; rest -> -inf
            srt = jnp.sort(logits, axis=-1)[..., ::-1]
            probs = jax.nn.softmax(srt, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # number of kept entries: first index where cum >= p, inclusive
            keep = jnp.sum((cum - probs) < top_p, axis=-1, keepdims=True)
            cutoff = jnp.take_along_axis(srt, keep - 1, axis=-1)
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    keys = (jax.random.split(rng, num_steps) if rng is not None
            else jnp.zeros((num_steps, 2), jnp.uint32))

    def step(carry, key):
        cache, logits = carry
        tok = pick(logits, key)
        cache, logits = run(cache, tok[:, None])
        return (cache, logits[:, 0]), tok

    (_, _), toks = lax.scan(step, (cache, last_logits), keys)
    return toks.T  # [B, num_steps]
