"""ConvNeXt — the third CNN family of the model zoo.

The reference's zoo is one architecture (MobileNetV2 transfer,
``Part 1 - Distributed Training/02_model_training_single_node.py:159-178``);
ConvNeXt joins ResNet as proof the trainer / serving / HPO stack is
model-agnostic, and it exercises the zoo paths the other CNNs cannot:

- **no BatchNorm** — LayerNorm only, so the model carries NO
  ``batch_stats`` collection: the stats-free branches of the train step,
  checkpoints, packaging, and feature cache run for a real conv family
  (previously only ViT/LM hit them);
- **7×7 depthwise** convolutions — a second depthwise consumer at a kernel
  size the in-tree Pallas 3×3 kernel deliberately does not claim
  (``ops/depthwise_conv.py``), so it rides XLA's grouped-conv lowering: the
  honest A/B partner for the Pallas kernel's scope decision.

Architecture follows the ConvNeXt **V2** recipe (patchify stem, per-stage
``LN + 2×2/2 conv`` downsampling, blocks of 7×7 depthwise → LN →
pointwise 4× expand → GELU → GRN → project, residual) — V2's global
response normalization replaces V1's 1e-6 layer scale, which trains
unstably under the zoo's plain-Adam contract (see ``_GRN``). Stochastic
depth is omitted — the zoo's regularization knob is the head dropout the
reference's transfer contract defines. Same ``backbone``/``head`` naming +
``frozen_prefixes`` protocol as the rest of the zoo, so transfer mode,
checkpoints, packaging, and the cached-feature path work unchanged.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

# variant -> (blocks per stage, channels per stage)
_CONFIGS = {
    "tiny": ((3, 3, 9, 3), (96, 192, 384, 768)),
    "small": ((3, 3, 27, 3), (96, 192, 384, 768)),
}


class _GRN(nn.Module):
    """Global response normalization (ConvNeXt V2): per-channel spatial L2
    energy, normalized by the cross-channel mean, gates the features —
    ``gamma * (x * nx) + beta + x``. Replaces V1's 1e-6 layer scale, whose
    tiny params take violently large *relative* Adam steps the first
    post-warmup epochs (observed: loss 1.75 → 7+ spikes on the flowers
    fit); GRN's params start at 0 with O(1) dynamics and the residual term
    keeps init an identity. Runs in f32 like the zoo's other norms."""

    features: int

    @nn.compact
    def __call__(self, x):
        xf = x.astype(jnp.float32)
        gx = jnp.sqrt(jnp.sum(xf * xf, axis=(1, 2), keepdims=True) + 1e-6)
        nx = gx / (jnp.mean(gx, axis=-1, keepdims=True) + 1e-6)
        gamma = self.param("gamma", nn.initializers.zeros, (self.features,),
                           jnp.float32)
        beta = self.param("beta", nn.initializers.zeros, (self.features,),
                          jnp.float32)
        return (gamma * (xf * nx) + beta + xf).astype(x.dtype)


class _Block(nn.Module):
    """7×7 depthwise → LN → 4× pointwise expand → GELU → GRN → project →
    residual (the ConvNeXt V2 block). LayerNorm/GRN run in f32 (same policy
    as the BN layers elsewhere in the zoo); convs/MLP in the compute
    dtype."""

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = nn.Conv(self.features, (7, 7), padding="SAME",
                    feature_group_count=self.features, dtype=self.dtype,
                    name="dwconv")(x)
        h = nn.LayerNorm(dtype=jnp.float32)(h)
        h = nn.Dense(4 * self.features, dtype=self.dtype, name="expand")(h)
        h = nn.gelu(h)
        h = _GRN(4 * self.features, name="grn")(h)
        # zero-init the projection: every block is an identity at init, so
        # the 18-deep residual stream starts perfectly conditioned (the
        # role V1's 1e-6 layer scale played, without its pathological
        # Adam dynamics — observed as loss 1.6 → 7 spikes in the first
        # post-warmup epoch with default init)
        h = nn.Dense(self.features, dtype=self.dtype, name="project",
                     kernel_init=nn.initializers.zeros)(h)
        return x + h


class ConvNeXtBackbone(nn.Module):
    variant: str = "tiny"
    width_mult: float = 1.0
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train  # no BN, no stochastic depth: inference == training graph
        depths, dims = _CONFIGS[self.variant]
        dims = [max(8, int(d * self.width_mult)) for d in dims]
        # patchify stem: 4×4 stride-4 conv + LN
        x = nn.Conv(dims[0], (4, 4), strides=(4, 4), dtype=self.dtype,
                    name="stem")(x)
        # cast back after the f32 norm: stage 0's residual carrier (the
        # highest-resolution stream) must run in the compute dtype, or
        # `x + h` promotes the whole stage to f32 (2x activation bytes)
        x = nn.LayerNorm(dtype=jnp.float32, name="stem_norm")(x).astype(
            self.dtype)
        for stage, (n_blocks, feats) in enumerate(zip(depths, dims)):
            if stage > 0:
                x = nn.LayerNorm(dtype=jnp.float32,
                                 name=f"down{stage}_norm")(x)
                x = nn.Conv(feats, (2, 2), strides=(2, 2), dtype=self.dtype,
                            name=f"down{stage}")(x)
            for i in range(n_blocks):
                x = _Block(feats, dtype=self.dtype,
                           name=f"stage{stage}_block{i}")(x)
        # The recipe's final LN lives in the BACKBONE (per-position, pre-GAP
        # — the paper applies it post-GAP; per-position is the map-shaped
        # equivalent) so the head stays the zoo-standard Dropout→Dense and
        # the residual stream is normalized before it reaches the head /
        # feature cache. Without it the un-normalized sum of 18 residual
        # branches destabilizes training within an epoch.
        return nn.LayerNorm(dtype=jnp.float32, name="final_norm")(x)


class ConvNeXt(nn.Module):
    """Backbone + the zoo-standard transfer head (GAP → Dropout → Dense).

    Deviation from the paper recipe: the final LayerNorm lives in the
    backbone (per-position, pre-GAP) instead of post-GAP in the head, so
    the head is byte-compatible with the zoo contract
    (``train.transfer.TransferHead``: ``head_dropout``/``head`` params) —
    one feature cache and one head-merge path serve every family."""

    num_classes: int = 5
    variant: str = "tiny"
    width_mult: float = 1.0
    dropout: float = 0.5
    freeze_base: bool = False
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        feats = ConvNeXtBackbone(self.variant, self.width_mult, self.dtype,
                                 name="backbone")(x, train and not self.freeze_base)
        if self.freeze_base:
            # Keras trainable=False semantics (same contract as the other
            # zoo families; XLA drops the backbone backward entirely).
            feats = jax.lax.stop_gradient(feats)
        h = jnp.mean(feats.astype(jnp.float32), axis=(1, 2))
        h = nn.Dropout(self.dropout, deterministic=not train,
                       name="head_dropout")(h)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(h)

    @staticmethod
    def frozen_prefixes(freeze_base: bool) -> tuple[str, ...]:
        return ("backbone",) if freeze_base else ()
