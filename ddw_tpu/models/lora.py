"""LoRA — low-rank adaptation, the parameter-efficient transfer path.

The reference's transfer story is "freeze the backbone, train the head"
(``02_model_training_single_node.py:164-178``). LoRA (Hu et al. 2021) is that
idea generalized to attention-era models: the base weights stay frozen and
each targeted projection learns a rank-``r`` update ``ΔW = A B · α/r``. This
module brings it to the LM family the same way ``freeze_base`` serves the CNN
families — and it is a natural fit for the TPU step: the adapter matmuls are
tiny, XLA fuses them into the existing projection, and the optimizer state
shrinks from O(params) to O(r·(d_in+d_out)) per target, which matters exactly
where ZeRO/TP matter.

Design constraints:

- **Param-path compatibility.** :class:`LoRADenseGeneral` declares ``kernel``
  / ``bias`` with the same names, shapes, and dtypes as the
  ``nn.DenseGeneral`` it replaces, and adds ``lora_a`` / ``lora_b`` beside
  them. A base (non-LoRA) checkpoint grafts into a LoRA model with
  :func:`merge_base_params`; at init the adapted output EQUALS the base
  output (``lora_b`` starts at zero), so fine-tuning starts from exactly the
  pretrained function.
- **Freezing via the same optimizer-masking idiom** the CNN transfer mode
  uses (``ddw_tpu.train.step.make_optimizer``): :func:`lora_optimizer` wraps
  any optax transform with ``set_to_zero`` on every non-adapter leaf.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax

LORA_PARAM_NAMES = ("lora_a", "lora_b")


class LoRADenseGeneral(nn.Module):
    """``nn.DenseGeneral`` plus a rank-``rank`` adapter. ``features`` may be
    an int (Dense) or a tuple (DenseGeneral, e.g. ``(heads, head_dim)``);
    ``contract_ndim`` is how many trailing input dims the projection
    contracts (2 for the attention output projection's ``axis=(-2, -1)``).
    """

    features: int | Sequence[int]
    rank: int
    alpha: float = 16.0
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    contract_ndim: int = 1

    @nn.compact
    def __call__(self, x):
        feats = (tuple(self.features) if isinstance(self.features, (tuple, list))
                 else (int(self.features),))
        cn = self.contract_ndim
        in_dims = tuple(x.shape[-cn:])
        if self.rank <= 0:
            raise ValueError(f"rank must be positive, got {self.rank}")
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (*in_dims, *feats), jnp.float32)
        bias = (self.param("bias", nn.initializers.zeros, feats, jnp.float32)
                if self.use_bias else None)
        # LoRA init (Hu et al. §4.1): A random, B zero — ΔW starts at 0 and
        # the module computes exactly the base projection until training moves
        # lora_b.
        lora_a = self.param("lora_a", nn.initializers.lecun_normal(),
                            (*in_dims, self.rank), jnp.float32)
        lora_b = self.param("lora_b", nn.initializers.zeros,
                            (self.rank, *feats), jnp.float32)

        x, kernel, bias, lora_a, lora_b = nn.dtypes.promote_dtype(
            x, kernel, bias, lora_a, lora_b, dtype=self.dtype)
        n_feat = len(feats)
        cdims_x = tuple(range(x.ndim - cn, x.ndim))
        contract = ((cdims_x, tuple(range(cn))), ((), ()))
        y = jax.lax.dot_general(x, kernel, contract)
        a = jax.lax.dot_general(x, lora_a, contract)  # [..., rank]
        delta = jax.lax.dot_general(
            a, lora_b, (((a.ndim - 1,), (0,)), ((), ())))
        y = y + delta * (self.alpha / self.rank)
        if bias is not None:
            y = y + jnp.reshape(bias, (1,) * (y.ndim - n_feat) + feats)
        return y


# Projections the attention families (LM, ViT) route through
# maybe_lora_dense. Anything else in lora_targets is a config error.
LM_LORA_TARGETS = ("query", "key", "value", "out", "fc1", "fc2")


def row_lora_delta(x, a, b, contract_ndim: int = 1):
    """Per-ROW adapter delta for heterogeneous-adapter batched serving
    (S-LoRA, arXiv 2311.03285): each batch row carries its OWN ``(A, B)``
    pair, gathered from an adapter stack by the row's adapter index, so one
    decode tick serves many adapters (and the base model) at once.

    ``x`` is ``[B, S, *in_dims]``; ``a`` is ``[B, *in_dims, r]``; ``b`` is
    ``[B, r, *feats]`` with any ``alpha/rank`` scaling already folded in
    (:class:`ddw_tpu.serve.adapters.AdapterPool` pre-scales at load).
    Returns ``[B, S, *feats]``. A zero ``b`` row (the reserved null adapter)
    contributes exactly ``+0.0`` — the base-model row in a mixed batch stays
    token-identical to an adapter-free engine.
    """
    a = a.astype(x.dtype)
    b = b.astype(x.dtype)
    cn = contract_ndim
    xdims = tuple(range(2, 2 + cn))          # trailing input dims of [B,S,*]
    adims = tuple(range(1, 1 + cn))          # matching dims of [B,*in,r]
    h = jax.lax.dot_general(x, a, ((xdims, adims), ((0,), (0,))))  # [B, S, r]
    return jax.lax.dot_general(h, b, (((2,), (1,)), ((0,), (0,))))


def validate_lora_targets(targets: Sequence[str],
                          known: Sequence[str] = LM_LORA_TARGETS) -> None:
    """Raise on a target name the model does not route through
    :func:`maybe_lora_dense` — a typo would otherwise silently adapt
    nothing."""
    bad = set(targets) - set(known)
    if bad:
        raise ValueError(f"unknown lora_targets {sorted(bad)}; this model "
                         f"can adapt {list(known)}")


def maybe_lora_dense(features, name: str, *, rank: int, alpha: float,
                     targets: Sequence[str], dtype, contract_ndim: int = 1):
    """The one dispatch point between a plain projection and its LoRA
    version: returns ``LoRADenseGeneral`` when ``name`` is targeted, else the
    equivalent ``nn.DenseGeneral`` — identical param paths either way, so the
    checkpoint format does not fork on the flag."""
    if rank and name in tuple(targets):
        return LoRADenseGeneral(features, rank=rank, alpha=alpha, dtype=dtype,
                                contract_ndim=contract_ndim, name=name)
    return nn.DenseGeneral(features, axis=tuple(range(-contract_ndim, 0)),
                           dtype=dtype, name=name)


def lora_mask(params, extra_trainable: Sequence[str] = ("head",)):
    """Bool pytree over ``params``: True where the optimizer should update —
    adapter leaves (``lora_a``/``lora_b``) anywhere in the tree, plus every
    leaf under a top-level key in ``extra_trainable`` (the task head, by the
    same logic the CNN transfer mode trains the head over a frozen base)."""
    def walk(node, path):
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        trainable = (any(p in LORA_PARAM_NAMES for p in path)
                     or (path and path[0] in tuple(extra_trainable)))
        return trainable
    return walk(params, ())


def lora_optimizer(tx: optax.GradientTransformation, params=None,
                   extra_trainable: Sequence[str] = ("head",)):
    """Wrap ``tx`` so only adapter (+``extra_trainable``) leaves update —
    the ``make_optimizer(frozen_prefixes=...)`` idiom at leaf granularity.

    ``params`` may be omitted: the labels are then resolved lazily from the
    param tree at ``tx.init`` time (optax accepts a callable), which lets the
    training stack wrap the optimizer before any parameters exist — how
    ``ddw_tpu.train.lm_step`` applies the mask automatically for a model
    built with ``lora_rank > 0``."""
    def label(p):
        return jax.tree.map(lambda t: "train" if t else "frozen",
                            lora_mask(p, extra_trainable))
    labels = label(params) if params is not None else label
    return optax.multi_transform(
        {"train": tx, "frozen": optax.set_to_zero()}, labels)


def merge_base_params(lora_params, base_params, _path=""):
    """Graft a base (non-LoRA) checkpoint into a freshly initialized LoRA
    param tree: every base leaf replaces its counterpart; adapter leaves keep
    their init. Raises on a base key missing from the LoRA tree or a shape
    mismatch — a silent partial graft would fine-tune from garbage."""
    if not isinstance(base_params, dict):
        if (getattr(lora_params, "shape", None) is not None
                and lora_params.shape != base_params.shape):
            raise ValueError(f"shape mismatch at {_path!r}: "
                             f"{lora_params.shape} vs {base_params.shape}")
        return base_params
    if not isinstance(lora_params, dict):
        raise ValueError(f"base has subtree at {_path!r}, LoRA tree has leaf")
    out = dict(lora_params)
    for k, v in base_params.items():
        if k not in lora_params:
            raise ValueError(f"base key {_path + '/' + k!r} absent from the "
                             f"LoRA param tree")
        out[k] = merge_base_params(lora_params[k], v, _path + "/" + k)
    return out


def count_trainable(params, extra_trainable: Sequence[str] = ("head",)) -> tuple[int, int]:
    """(trainable, total) parameter counts under the LoRA mask — the headline
    LoRA economy number."""
    mask = lora_mask(params, extra_trainable)
    sizes = jax.tree.map(lambda a: int(jnp.size(a)), params)
    flat_m = jax.tree.leaves(mask)
    flat_s = jax.tree.leaves(sizes)
    total = sum(flat_s)
    trainable = sum(s for s, m in zip(flat_s, flat_m) if m)
    return trainable, total
