"""MobileNetV2 in flax.linen — the reference's flagship backbone.

The reference builds ``MobileNetV2(include_top=False, weights='imagenet')`` frozen,
plus GlobalAveragePooling -> Dropout(0.5) -> Dense(num_classes) head
(``Part 1 - Distributed Training/02_model_training_single_node.py:159-178``). This is
that architecture (Sandler et al. 2018: inverted residuals, linear bottlenecks,
ReLU6) implemented TPU-first:

- NHWC layout with channel counts rounded to multiples of 8 (the standard
  divisible-by-8 rule — also what XLA tiles best onto the MXU);
- compute dtype bfloat16 (params float32) so convs hit the MXU at full rate;
- transfer-learning mode: ``backbone``/``head`` are separate top-level param
  subtrees, so the trainer freezes the base by masking optimizer updates on the
  ``backbone`` prefix and running its BatchNorm in inference mode — the
  ``base_model.trainable = False`` semantics of Keras (reference ``:169``, which
  also stops BN statistic updates).

Pretrained ImageNet weights are an optional artifact (``ModelCfg.pretrained_path``,
converted offline); absent weights, the architecture trains from scratch (SURVEY.md
§7 hard-part 1 option b).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

# (expansion t, out channels c, repeats n, stride s) — Sandler et al. Table 2.
_INVERTED_RESIDUAL_CFG: Sequence[tuple[int, int, int, int]] = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


def _make_divisible(v: float, divisor: int = 8) -> int:
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBN(nn.Module):
    features: int
    kernel: tuple[int, int] = (3, 3)
    strides: int = 1
    groups: int = 1
    act: bool = True
    bn_momentum: float = 0.9
    dtype: Any = jnp.bfloat16
    s2d: bool = False  # stem trick: identical math, MXU-friendly channel depth
    dw_impl: str = "xla"  # depthwise layers: "xla" grouped conv, "pallas"
                          # (ddw_tpu.ops.depthwise_conv — auto-dispatch: Pallas
                          # for stride-1 on TPU, XLA elsewhere), or
                          # "pallas_interpret" (test-only CPU interpreter)

    @nn.compact
    def __call__(self, x, train: bool):
        if self.dw_impl not in ("xla", "pallas", "pallas_interpret"):
            raise ValueError(f"unknown dw_impl {self.dw_impl!r}")
        depthwise = self.groups > 1 and self.groups == x.shape[-1]
        if (depthwise and self.dw_impl != "xla" and self.kernel == (3, 3)):
            from ddw_tpu.ops.depthwise_conv import DepthwiseConv3x3

            interp = self.dw_impl == "pallas_interpret" and self.strides == 1
            # Same param path/shape as the nn.Conv branch (see module doc).
            x = DepthwiseConv3x3(self.features, strides=self.strides,
                                 dtype=self.dtype,
                                 impl="pallas" if interp else "auto",
                                 interpret=interp,
                                 name="Conv_0")(x)
        else:
            from ddw_tpu.ops.s2d_conv import conv_or_s2d

            x = conv_or_s2d(self.features, self.kernel, strides=self.strides,
                            groups=self.groups, dtype=self.dtype,
                            s2d=self.s2d)(x)
        # Default momentum 0.9, not Keras's 0.99: the reference only ever runs
        # BN with a pretrained FROZEN base (stats never update, momentum
        # irrelevant); for from-scratch training 0.99 needs ~500 steps before
        # running stats are usable, leaving eval broken for entire short runs.
        # ModelCfg.bn_momentum=0.99 restores the Keras value for parity runs
        # that finetune an unfrozen pretrained base. epsilon stays at Keras's
        # 1e-3 so converted pretrained weights reproduce exactly.
        x = nn.BatchNorm(use_running_average=not train,
                         momentum=self.bn_momentum, epsilon=1e-3,
                         dtype=jnp.float32)(x)
        if self.act:
            x = jnp.minimum(nn.relu(x), 6.0).astype(self.dtype)  # ReLU6
        return x


class InvertedResidual(nn.Module):
    out_ch: int
    stride: int
    expand: int
    bn_momentum: float = 0.9
    dtype: Any = jnp.bfloat16
    dw_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool):
        in_ch = x.shape[-1]
        bn = self.bn_momentum
        h = x
        if self.expand != 1:
            h = ConvBN(in_ch * self.expand, (1, 1), bn_momentum=bn,
                       dtype=self.dtype)(h, train)
        # depthwise
        h = ConvBN(h.shape[-1], (3, 3), strides=self.stride, groups=h.shape[-1],
                   bn_momentum=bn, dtype=self.dtype,
                   dw_impl=self.dw_impl)(h, train)
        # linear bottleneck projection (no activation)
        h = ConvBN(self.out_ch, (1, 1), act=False, bn_momentum=bn,
                   dtype=self.dtype)(h, train)
        if self.stride == 1 and in_ch == self.out_ch:
            h = h + x
        return h


class MobileNetV2Backbone(nn.Module):
    width_mult: float = 1.0
    bn_momentum: float = 0.9
    dtype: Any = jnp.bfloat16
    stem_s2d: bool = False
    dw_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool):
        bn = self.bn_momentum
        x = x.astype(self.dtype)
        x = ConvBN(_make_divisible(32 * self.width_mult), (3, 3), strides=2,
                   bn_momentum=bn, dtype=self.dtype, s2d=self.stem_s2d)(x, train)
        for t, c, n, s in _INVERTED_RESIDUAL_CFG:
            out_ch = _make_divisible(c * self.width_mult)
            for i in range(n):
                x = InvertedResidual(out_ch, s if i == 0 else 1, t,
                                     bn_momentum=bn, dtype=self.dtype,
                                     dw_impl=self.dw_impl)(x, train)
        last = _make_divisible(1280 * max(1.0, self.width_mult))
        x = ConvBN(last, (1, 1), bn_momentum=bn, dtype=self.dtype)(x, train)
        return x


class MobileNetV2(nn.Module):
    """Backbone + transfer head. ``freeze_base`` reproduces Keras
    ``base_model.trainable=False`` (reference ``:169``): backbone BN runs in
    inference mode; the trainer additionally masks backbone param updates."""

    num_classes: int = 5
    width_mult: float = 1.0
    dropout: float = 0.5
    freeze_base: bool = True
    bn_momentum: float = 0.9
    dtype: Any = jnp.bfloat16
    stem_s2d: bool = False
    dw_impl: str = "xla"

    @nn.compact
    def __call__(self, x, train: bool = False):
        base_train = train and not self.freeze_base
        feats = MobileNetV2Backbone(self.width_mult, self.bn_momentum,
                                    self.dtype, stem_s2d=self.stem_s2d,
                                    dw_impl=self.dw_impl,
                                    name="backbone")(x, base_train)
        if self.freeze_base:
            # Keras trainable=False computes no base gradients: the tape stops at
            # the head input. stop_gradient guarantees XLA drops the backbone
            # backward pass instead of relying on DCE of the masked updates.
            feats = jax.lax.stop_gradient(feats)
        # GAP -> Dropout -> Dense logits (reference :171-178; logits, not softmax —
        # loss is SparseCategoricalCrossentropy(from_logits=True), :202)
        h = jnp.mean(feats.astype(jnp.float32), axis=(1, 2))
        h = nn.Dropout(self.dropout, deterministic=not train, name="head_dropout")(h)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(h)
        return logits

    @staticmethod
    def frozen_prefixes(freeze_base: bool) -> tuple[str, ...]:
        """Top-level param-tree keys the optimizer must not update in transfer mode."""
        return ("backbone",) if freeze_base else ()
