"""Pretrained-weight conversion: torch/Keras MobileNetV2 or torch ResNet -> flax variables.

The reference's accuracy comes from a *frozen ImageNet-pretrained* MobileNetV2
base (``Part 1 - Distributed Training/02_model_training_single_node.py:164-169``);
SURVEY.md §7 hard-part 1 chooses option (a): convert pretrained weights into our
JAX module once, as a data artifact. This module is that converter. Two source
layouts are accepted, covering both public distributions of these weights:

- **torchvision** ``mobilenet_v2`` state_dict (``features.N...`` naming) —
  :func:`convert_torch_mobilenet_v2`;
- **Keras applications** ``MobileNetV2(include_top=False)`` weights (``Conv1`` /
  ``block_N_expand`` / ``Conv_1`` layer naming — the exact format the reference
  itself downloads at ``02_model_training_single_node.py:164``) —
  :func:`convert_keras_mobilenet_v2`, fed from an ``.h5`` weights file or an
  ``.npz`` of ``layer/weight`` arrays via :func:`load_keras_weights`.

Both emit the flax param/batch_stats trees of
:class:`ddw_tpu.models.mobilenet_v2.MobileNetV2Backbone`. For the second CNN
family, :func:`convert_torch_resnet` maps torchvision ``resnet18/34/50``
state_dicts onto :class:`ddw_tpu.models.resnet.ResNetBackbone` (the CLI
auto-detects the depth from the block counts).

Exactness notes:
- conv kernels: torch ``[out, in, kh, kw]`` -> flax ``[kh, kw, in, out]``; the
  same transpose handles depthwise convs (torch ``[C,1,kh,kw]`` -> flax
  ``[kh,kw,1,C]`` with ``feature_group_count=C``);
- our BatchNorm runs with the Keras epsilon (1e-3) while torch uses 1e-5; the
  difference is folded *exactly* into the scale:
  ``scale' = scale * sqrt((var + eps_ours) / (var + eps_src))``;
- padding: our convs use TF/Keras "SAME" semantics. For stride-2 3x3 convs on
  even inputs this pads (0,1) where torch pads (1,1) — a one-pixel spatial
  shift identical to the Keras-vs-torch difference, irrelevant for transfer
  learning (and zero for odd spatial sizes, which the equivalence test uses).

Artifact format: ``.npz`` with flattened keys ``params/backbone/...`` and
``batch_stats/backbone/...`` — loaded into a model's variables by
:func:`load_pretrained` (wired into ``train.step.init_state`` via
``ModelCfg.pretrained_path``).

CLI: ``python -m ddw_tpu.models.convert weights.{pt,h5,npz} out.npz`` —
``.pt`` is a ``torch.save``-d state_dict (e.g. ``torchvision.models.
mobilenet_v2(weights='IMAGENET1K_V1').state_dict()``); ``.h5``/``.npz`` is a
Keras weights file (e.g. ``tf.keras.applications.MobileNetV2(include_top=False,
weights='imagenet').save_weights('w.h5')``), each exported on any machine.
"""

from __future__ import annotations

import numpy as np

from ddw_tpu.models.mobilenet_v2 import _INVERTED_RESIDUAL_CFG

_EPS_FLAX = 1e-3   # our BatchNorm epsilon (Keras convention)
_EPS_TORCH = 1e-5  # torchvision BatchNorm epsilon


def _np(x) -> np.ndarray:
    # torch tensors expose .numpy(); plain arrays pass through.
    return np.asarray(x.detach().cpu().numpy() if hasattr(x, "detach") else x,
                      dtype=np.float32)


def _conv(sd: dict, prefix: str) -> np.ndarray:
    return _np(sd[f"{prefix}.weight"]).transpose(2, 3, 1, 0)


def _bn(sd: dict, prefix: str, eps_src: float,
        eps_dst: float = _EPS_FLAX) -> tuple[dict, dict]:
    scale = _np(sd[f"{prefix}.weight"])
    bias = _np(sd[f"{prefix}.bias"])
    mean = _np(sd[f"{prefix}.running_mean"])
    var = _np(sd[f"{prefix}.running_var"])
    scale = scale * np.sqrt((var + eps_dst) / (var + eps_src))
    return {"scale": scale, "bias": bias}, {"mean": mean, "var": var}


def _convbn(sd: dict, conv_prefix: str, bn_prefix: str, eps_src: float,
            eps_dst: float = _EPS_FLAX):
    bn_params, bn_stats = _bn(sd, bn_prefix, eps_src, eps_dst)
    params = {"Conv_0": {"kernel": _conv(sd, conv_prefix)}, "BatchNorm_0": bn_params}
    stats = {"BatchNorm_0": bn_stats}
    return params, stats


def convert_torch_mobilenet_v2(state_dict: dict, eps_src: float = _EPS_TORCH
                               ) -> dict[str, dict]:
    """torchvision-layout state_dict -> ``{"params": ..., "batch_stats": ...}``
    trees of ``MobileNetV2Backbone`` (width_mult 1.0 — the only width torchvision
    distributes)."""
    params: dict = {}
    stats: dict = {}

    def put(name, sub):
        params[name], stats[name] = sub

    put("ConvBN_0", _convbn(state_dict, "features.0.0", "features.0.1", eps_src))
    block = 0
    for t, _c, n, _s in _INVERTED_RESIDUAL_CFG:
        for _ in range(n):
            f = f"features.{block + 1}"
            sub_p: dict = {}
            sub_s: dict = {}
            if t == 1:
                pairs = [(f"{f}.conv.0.0", f"{f}.conv.0.1"),   # depthwise
                         (f"{f}.conv.1", f"{f}.conv.2")]       # projection
            else:
                pairs = [(f"{f}.conv.0.0", f"{f}.conv.0.1"),   # expand 1x1
                         (f"{f}.conv.1.0", f"{f}.conv.1.1"),   # depthwise
                         (f"{f}.conv.2", f"{f}.conv.3")]       # projection
            for i, (cp, bp) in enumerate(pairs):
                sub_p[f"ConvBN_{i}"], sub_s[f"ConvBN_{i}"] = _convbn(
                    state_dict, cp, bp, eps_src)
            params[f"InvertedResidual_{block}"] = sub_p
            stats[f"InvertedResidual_{block}"] = sub_s
            block += 1
    put("ConvBN_1", _convbn(state_dict, "features.18.0", "features.18.1", eps_src))
    return {"params": params, "batch_stats": stats}


_EPS_RESNET = 1e-5  # our ResNet BatchNorm epsilon == torch's: the fold is identity


def convert_torch_resnet(state_dict: dict, depth: int = 50,
                         eps_src: float = _EPS_TORCH) -> dict[str, dict]:
    """torchvision-layout ResNet state_dict -> ``{"params", "batch_stats"}``
    trees of :class:`ddw_tpu.models.resnet.ResNetBackbone` (width_mult 1.0).

    torchvision layout (``resnet18/34/50().state_dict()``): stem ``conv1`` /
    ``bn1``; stage blocks ``layer{1..4}.{i}.conv{1..3}`` + ``bn{1..3}``
    (``conv3`` only for Bottleneck); optional ``downsample.0/.1`` projection.
    torchvision's Bottleneck strides the 3x3 (``conv2``) — the same v1.5
    placement this tree's :class:`BottleneckBlock` uses, so the mapping is
    positional. BN epsilons agree (1e-5) so the scale fold is the identity.
    The ``fc`` head is ignored (transfer mode re-heads)."""
    from ddw_tpu.models.resnet import _CONFIGS

    if depth not in _CONFIGS:
        raise KeyError(f"unsupported resnet depth {depth} (have {sorted(_CONFIGS)})")
    counts, bottleneck = _CONFIGS[depth]

    def cb(conv_prefix, bn_prefix):
        return _convbn(state_dict, conv_prefix, bn_prefix, eps_src,
                       eps_dst=_EPS_RESNET)

    params: dict = {}
    stats: dict = {}
    params["stem"], stats["stem"] = cb("conv1", "bn1")
    n_convs = 3 if bottleneck else 2
    for stage, n_blocks in enumerate(counts):
        for i in range(n_blocks):
            t = f"layer{stage + 1}.{i}"
            sub_p: dict = {}
            sub_s: dict = {}
            for j in range(n_convs):
                sub_p[f"_ConvBN_{j}"], sub_s[f"_ConvBN_{j}"] = cb(
                    f"{t}.conv{j + 1}", f"{t}.bn{j + 1}")
            if f"{t}.downsample.0.weight" in state_dict:
                sub_p["proj"], sub_s["proj"] = cb(
                    f"{t}.downsample.0", f"{t}.downsample.1")
            params[f"stage{stage}_block{i}"] = sub_p
            stats[f"stage{stage}_block{i}"] = sub_s
    return {"params": params, "batch_stats": stats}


def infer_torch_resnet_depth(state_dict: dict) -> int:
    """Depth from block counts + block type — lets the CLI auto-detect which
    torchvision resnet a ``.pt`` holds."""
    from ddw_tpu.models.resnet import _CONFIGS

    counts = tuple(
        len({k.split(".")[1] for k in state_dict
             if k.startswith(f"layer{s}.")}) for s in range(1, 5))
    bottleneck = any(".conv3." in k for k in state_dict)
    for depth, (c, b) in _CONFIGS.items():
        if c == counts and b == bottleneck:
            return depth
    raise ValueError(f"unrecognized resnet layout: blocks {counts}, "
                     f"bottleneck={bottleneck}")


_EPS_KERAS = 1e-3  # Keras BatchNorm epsilon == ours: the eps fold is identity


def _keras_bn(w: dict, layer: str, eps_src: float) -> tuple[dict, dict]:
    scale = _np(w[f"{layer}/gamma"])
    bias = _np(w[f"{layer}/beta"])
    mean = _np(w[f"{layer}/moving_mean"])
    var = _np(w[f"{layer}/moving_variance"])
    scale = scale * np.sqrt((var + _EPS_FLAX) / (var + eps_src))
    return {"scale": scale, "bias": bias}, {"mean": mean, "var": var}


def _keras_convbn(w: dict, conv: str, bn: str, eps_src: float, depthwise: bool):
    if depthwise:
        # Keras depthwise_kernel [kh, kw, C, mult=1] -> flax grouped-conv
        # kernel [kh, kw, 1, C] (feature_group_count=C).
        kernel = _np(w[f"{conv}/depthwise_kernel"]).transpose(0, 1, 3, 2)
    else:
        kernel = _np(w[f"{conv}/kernel"])  # [kh, kw, in, out] — already flax layout
    bn_params, bn_stats = _keras_bn(w, bn, eps_src)
    return ({"Conv_0": {"kernel": kernel}, "BatchNorm_0": bn_params},
            {"BatchNorm_0": bn_stats})


def convert_keras_mobilenet_v2(weights: dict, eps_src: float = _EPS_KERAS
                               ) -> dict[str, dict]:
    """Keras-applications-layout weights -> ``{"params", "batch_stats"}`` trees
    of ``MobileNetV2Backbone`` (width_mult 1.0).

    ``weights`` maps ``"layer_name/weight_name"`` (``:0`` suffixes stripped —
    see :func:`load_keras_weights`) to arrays. Keras MobileNetV2 layer naming:
    stem ``Conv1``/``bn_Conv1``; block 0 (expansion 1, no expand conv)
    ``expanded_conv_{depthwise,project}``; blocks 1-16
    ``block_N_{expand,depthwise,project}`` each with a ``..._BN`` twin; top
    ``Conv_1``/``Conv_1_bn``.
    """
    params: dict = {}
    stats: dict = {}
    params["ConvBN_0"], stats["ConvBN_0"] = _keras_convbn(
        weights, "Conv1", "bn_Conv1", eps_src, depthwise=False)
    block = 0
    for t, _c, n, _s in _INVERTED_RESIDUAL_CFG:
        for _ in range(n):
            pfx = "expanded_conv" if block == 0 else f"block_{block}"
            stages = []
            if t != 1:
                stages.append((f"{pfx}_expand", f"{pfx}_expand_BN", False))
            stages += [(f"{pfx}_depthwise", f"{pfx}_depthwise_BN", True),
                       (f"{pfx}_project", f"{pfx}_project_BN", False)]
            sub_p: dict = {}
            sub_s: dict = {}
            for i, (conv, bn, dw) in enumerate(stages):
                sub_p[f"ConvBN_{i}"], sub_s[f"ConvBN_{i}"] = _keras_convbn(
                    weights, conv, bn, eps_src, depthwise=dw)
            params[f"InvertedResidual_{block}"] = sub_p
            stats[f"InvertedResidual_{block}"] = sub_s
            block += 1
    params["ConvBN_1"], stats["ConvBN_1"] = _keras_convbn(
        weights, "Conv_1", "Conv_1_bn", eps_src, depthwise=False)
    return {"params": params, "batch_stats": stats}


def load_keras_weights(path: str) -> dict[str, np.ndarray]:
    """Read a Keras weights file into a flat ``"layer/weight"`` dict.

    ``.h5``: walks every dataset under the file (handles both
    ``save_weights`` layout ``layer/layer/weight:0`` and full-model
    ``model_weights/...``), keying by the last two non-duplicate path parts.
    ``.npz``: keys pass through. ``:0`` tensor suffixes are stripped either way.
    """
    flat: dict[str, np.ndarray] = {}

    def put(parts: list[str], arr: np.ndarray):
        parts = [p for p in parts if p not in ("model_weights", "")]
        # save_weights h5 nests layer/layer/weight — collapse the duplicate
        dedup = [p for i, p in enumerate(parts) if i == 0 or p != parts[i - 1]]
        name = "/".join(dedup[-2:]).removesuffix(":0")
        flat[name] = np.asarray(arr, np.float32)

    if path.endswith(".npz"):
        with np.load(path) as z:
            for k in z.files:
                put(k.split("/"), z[k])
        return flat

    import h5py

    with h5py.File(path, "r") as f:
        def visit(name, obj):
            if isinstance(obj, h5py.Dataset):
                put(name.split("/"), obj[()])
        f.visititems(visit)
    return flat


def save_pretrained(path: str, backbone_vars: dict, scope: str = "backbone") -> None:
    """Write the converted backbone as the ``.npz`` artifact ``ModelCfg.
    pretrained_path`` points at, keys fully qualified under ``scope``."""
    import flax

    tree = {"params": {scope: backbone_vars["params"]},
            "batch_stats": {scope: backbone_vars["batch_stats"]}}
    flat = {k: np.asarray(v)
            for k, v in flax.traverse_util.flatten_dict(tree, sep="/").items()}
    np.savez(path, **flat)


def load_pretrained(variables: dict, path: str) -> dict:
    """Merge a pretrained ``.npz`` artifact into freshly-initialized model
    variables. Every artifact entry must match an existing path and shape —
    a mismatch means the architecture and the artifact diverged, which must
    fail loudly, not train silently from partial garbage."""
    import flax

    flat_vars = dict(flax.traverse_util.flatten_dict(variables, sep="/"))
    loaded = np.load(path)
    for key in loaded.files:
        if key not in flat_vars:
            raise KeyError(f"{path}: artifact key {key!r} not in model variables "
                           f"(architecture/artifact mismatch)")
        have = flat_vars[key]
        arr = loaded[key]
        if tuple(have.shape) != tuple(arr.shape):
            raise ValueError(f"{path}: shape mismatch at {key!r}: "
                             f"model {tuple(have.shape)} vs artifact {arr.shape}")
        flat_vars[key] = arr.astype(np.asarray(have).dtype)
    return flax.traverse_util.unflatten_dict(flat_vars, sep="/")


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("weights", help="torch state_dict (.pt) or Keras weights "
                                    "(.h5 / .npz of layer/weight arrays)")
    ap.add_argument("out", help="output .npz artifact path")
    args = ap.parse_args(argv)

    if args.weights.endswith((".h5", ".hdf5")):
        converted = convert_keras_mobilenet_v2(load_keras_weights(args.weights))
    elif args.weights.endswith(".npz"):
        w = load_keras_weights(args.weights)
        if not any(k.startswith("Conv1/") for k in w):
            raise SystemExit(f"{args.weights}: no Conv1/* keys — not a Keras "
                             f"MobileNetV2 weights archive")
        converted = convert_keras_mobilenet_v2(w)
    else:
        import torch

        sd = torch.load(args.weights, map_location="cpu", weights_only=True)
        if "features.0.0.weight" in sd and "features.18.0.weight" in sd:
            # 18 feature stages with the Conv/BN/ReLU6 stem+top: mobilenet_v2
            # specifically (e.g. efficientnet also has features.0.0 but a
            # different stage count -> falls to the friendly error below)
            converted = convert_torch_mobilenet_v2(sd)
        elif "conv1.weight" in sd and any(k.startswith("layer1.") for k in sd):
            depth = infer_torch_resnet_depth(sd)
            print(f"detected torchvision resnet{depth}")
            converted = convert_torch_resnet(sd, depth)
        else:
            raise SystemExit(f"{args.weights}: unrecognized state_dict layout "
                             f"(expected torchvision mobilenet_v2 or resnet)")
    save_pretrained(args.out, converted)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
