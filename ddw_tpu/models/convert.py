"""Pretrained-weight conversion: torch MobileNetV2 state_dict -> flax variables.

The reference's accuracy comes from a *frozen ImageNet-pretrained* MobileNetV2
base (``Part 1 - Distributed Training/02_model_training_single_node.py:164-169``);
SURVEY.md §7 hard-part 1 chooses option (a): convert pretrained weights into our
JAX module once, as a data artifact. This module is that converter. It accepts a
state_dict in torchvision's ``mobilenet_v2`` naming scheme (``features.N...``) —
the de-facto public distribution format for these weights — and emits the flax
param/batch_stats trees of :class:`ddw_tpu.models.mobilenet_v2.MobileNetV2Backbone`.

Exactness notes:
- conv kernels: torch ``[out, in, kh, kw]`` -> flax ``[kh, kw, in, out]``; the
  same transpose handles depthwise convs (torch ``[C,1,kh,kw]`` -> flax
  ``[kh,kw,1,C]`` with ``feature_group_count=C``);
- our BatchNorm runs with the Keras epsilon (1e-3) while torch uses 1e-5; the
  difference is folded *exactly* into the scale:
  ``scale' = scale * sqrt((var + eps_ours) / (var + eps_src))``;
- padding: our convs use TF/Keras "SAME" semantics. For stride-2 3x3 convs on
  even inputs this pads (0,1) where torch pads (1,1) — a one-pixel spatial
  shift identical to the Keras-vs-torch difference, irrelevant for transfer
  learning (and zero for odd spatial sizes, which the equivalence test uses).

Artifact format: ``.npz`` with flattened keys ``params/backbone/...`` and
``batch_stats/backbone/...`` — loaded into a model's variables by
:func:`load_pretrained` (wired into ``train.step.init_state`` via
``ModelCfg.pretrained_path``).

CLI: ``python -m ddw_tpu.models.convert weights.pt out.npz`` (``weights.pt`` is
a ``torch.save``-d state_dict, e.g. ``torchvision.models.mobilenet_v2(
weights='IMAGENET1K_V1').state_dict()`` exported on any machine).
"""

from __future__ import annotations

import numpy as np

from ddw_tpu.models.mobilenet_v2 import _INVERTED_RESIDUAL_CFG

_EPS_FLAX = 1e-3   # our BatchNorm epsilon (Keras convention)
_EPS_TORCH = 1e-5  # torchvision BatchNorm epsilon


def _np(x) -> np.ndarray:
    # torch tensors expose .numpy(); plain arrays pass through.
    return np.asarray(x.detach().cpu().numpy() if hasattr(x, "detach") else x,
                      dtype=np.float32)


def _conv(sd: dict, prefix: str) -> np.ndarray:
    return _np(sd[f"{prefix}.weight"]).transpose(2, 3, 1, 0)


def _bn(sd: dict, prefix: str, eps_src: float) -> tuple[dict, dict]:
    scale = _np(sd[f"{prefix}.weight"])
    bias = _np(sd[f"{prefix}.bias"])
    mean = _np(sd[f"{prefix}.running_mean"])
    var = _np(sd[f"{prefix}.running_var"])
    scale = scale * np.sqrt((var + _EPS_FLAX) / (var + eps_src))
    return {"scale": scale, "bias": bias}, {"mean": mean, "var": var}


def _convbn(sd: dict, conv_prefix: str, bn_prefix: str, eps_src: float):
    bn_params, bn_stats = _bn(sd, bn_prefix, eps_src)
    params = {"Conv_0": {"kernel": _conv(sd, conv_prefix)}, "BatchNorm_0": bn_params}
    stats = {"BatchNorm_0": bn_stats}
    return params, stats


def convert_torch_mobilenet_v2(state_dict: dict, eps_src: float = _EPS_TORCH
                               ) -> dict[str, dict]:
    """torchvision-layout state_dict -> ``{"params": ..., "batch_stats": ...}``
    trees of ``MobileNetV2Backbone`` (width_mult 1.0 — the only width torchvision
    distributes)."""
    params: dict = {}
    stats: dict = {}

    def put(name, sub):
        params[name], stats[name] = sub

    put("ConvBN_0", _convbn(state_dict, "features.0.0", "features.0.1", eps_src))
    block = 0
    for t, _c, n, _s in _INVERTED_RESIDUAL_CFG:
        for _ in range(n):
            f = f"features.{block + 1}"
            sub_p: dict = {}
            sub_s: dict = {}
            if t == 1:
                pairs = [(f"{f}.conv.0.0", f"{f}.conv.0.1"),   # depthwise
                         (f"{f}.conv.1", f"{f}.conv.2")]       # projection
            else:
                pairs = [(f"{f}.conv.0.0", f"{f}.conv.0.1"),   # expand 1x1
                         (f"{f}.conv.1.0", f"{f}.conv.1.1"),   # depthwise
                         (f"{f}.conv.2", f"{f}.conv.3")]       # projection
            for i, (cp, bp) in enumerate(pairs):
                sub_p[f"ConvBN_{i}"], sub_s[f"ConvBN_{i}"] = _convbn(
                    state_dict, cp, bp, eps_src)
            params[f"InvertedResidual_{block}"] = sub_p
            stats[f"InvertedResidual_{block}"] = sub_s
            block += 1
    put("ConvBN_1", _convbn(state_dict, "features.18.0", "features.18.1", eps_src))
    return {"params": params, "batch_stats": stats}


def save_pretrained(path: str, backbone_vars: dict, scope: str = "backbone") -> None:
    """Write the converted backbone as the ``.npz`` artifact ``ModelCfg.
    pretrained_path`` points at, keys fully qualified under ``scope``."""
    import flax

    tree = {"params": {scope: backbone_vars["params"]},
            "batch_stats": {scope: backbone_vars["batch_stats"]}}
    flat = {k: np.asarray(v)
            for k, v in flax.traverse_util.flatten_dict(tree, sep="/").items()}
    np.savez(path, **flat)


def load_pretrained(variables: dict, path: str) -> dict:
    """Merge a pretrained ``.npz`` artifact into freshly-initialized model
    variables. Every artifact entry must match an existing path and shape —
    a mismatch means the architecture and the artifact diverged, which must
    fail loudly, not train silently from partial garbage."""
    import flax

    flat_vars = dict(flax.traverse_util.flatten_dict(variables, sep="/"))
    loaded = np.load(path)
    for key in loaded.files:
        if key not in flat_vars:
            raise KeyError(f"{path}: artifact key {key!r} not in model variables "
                           f"(architecture/artifact mismatch)")
        have = flat_vars[key]
        arr = loaded[key]
        if tuple(have.shape) != tuple(arr.shape):
            raise ValueError(f"{path}: shape mismatch at {key!r}: "
                             f"model {tuple(have.shape)} vs artifact {arr.shape}")
        flat_vars[key] = arr.astype(np.asarray(have).dtype)
    return flax.traverse_util.unflatten_dict(flat_vars, sep="/")


def main(argv=None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("state_dict", help="torch.save-d mobilenet_v2 state_dict (.pt)")
    ap.add_argument("out", help="output .npz artifact path")
    args = ap.parse_args(argv)

    import torch

    sd = torch.load(args.state_dict, map_location="cpu", weights_only=True)
    save_pretrained(args.out, convert_torch_mobilenet_v2(sd))
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
