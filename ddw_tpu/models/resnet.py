"""ResNet (v1.5) — the second CNN family of the model zoo.

The reference's zoo is exactly one architecture (MobileNetV2 transfer,
``02_model_training_single_node.py:159-178``); ResNet exists so the trainer /
serving / HPO stack is demonstrably model-agnostic beyond that contract. Same
head shape as the other families (features -> GAP -> Dropout -> Dense logits)
and the same ``backbone_*`` naming + ``frozen_prefixes`` protocol, so transfer
mode, checkpoints, and packaging work unchanged.

v1.5 detail: the stride-2 downsample sits on the 3x3 conv (not the first 1x1)
— the variant every modern benchmark suite ships. BN statistics are
``batch_stats`` collections; the DP train step pmean's them across the mesh
(world-consistent BN, ddw_tpu.train.step).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

# depth -> (block counts per stage, bottleneck?)
_CONFIGS = {
    18: ((2, 2, 2, 2), False),
    34: ((3, 4, 6, 3), False),
    50: ((3, 4, 6, 3), True),
}


class _ConvBN(nn.Module):
    features: int
    kernel: tuple[int, int] = (3, 3)
    strides: int = 1
    act: bool = True
    dtype: Any = jnp.bfloat16
    s2d: bool = False  # stem trick: identical math, MXU-friendly channel depth

    @nn.compact
    def __call__(self, x, train: bool):
        from ddw_tpu.ops.s2d_conv import conv_or_s2d

        x = conv_or_s2d(self.features, self.kernel, strides=self.strides,
                        dtype=self.dtype, s2d=self.s2d)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=jnp.float32)(x)
        return nn.relu(x) if self.act else x


class BasicBlock(nn.Module):
    features: int
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        h = _ConvBN(self.features, strides=self.strides, dtype=self.dtype)(x, train)
        h = _ConvBN(self.features, act=False, dtype=self.dtype)(h, train)
        if x.shape[-1] != self.features or self.strides != 1:
            x = _ConvBN(self.features, (1, 1), strides=self.strides, act=False,
                        dtype=self.dtype, name="proj")(x, train)
        return nn.relu(x + h)


class BottleneckBlock(nn.Module):
    features: int  # bottleneck width; output is 4x
    strides: int = 1
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool):
        out_ch = self.features * 4
        h = _ConvBN(self.features, (1, 1), dtype=self.dtype)(x, train)
        # v1.5: stride on the 3x3
        h = _ConvBN(self.features, strides=self.strides, dtype=self.dtype)(h, train)
        h = _ConvBN(out_ch, (1, 1), act=False, dtype=self.dtype)(h, train)
        if x.shape[-1] != out_ch or self.strides != 1:
            x = _ConvBN(out_ch, (1, 1), strides=self.strides, act=False,
                        dtype=self.dtype, name="proj")(x, train)
        return nn.relu(x + h)


class ResNetBackbone(nn.Module):
    depth: int = 50
    width_mult: float = 1.0
    dtype: Any = jnp.bfloat16
    stem_s2d: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        counts, bottleneck = _CONFIGS[self.depth]
        block = BottleneckBlock if bottleneck else BasicBlock
        width = int(64 * self.width_mult)
        x = _ConvBN(width, (7, 7), strides=2, dtype=self.dtype,
                    s2d=self.stem_s2d, name="stem")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(counts):
            feats = width * (2 ** stage)
            for i in range(n_blocks):
                x = block(feats, strides=2 if (stage > 0 and i == 0) else 1,
                          dtype=self.dtype,
                          name=f"stage{stage}_block{i}")(x, train)
        return x


class ResNet(nn.Module):
    """Backbone + the zoo-standard transfer head (GAP -> Dropout -> Dense)."""

    num_classes: int = 5
    depth: int = 50
    width_mult: float = 1.0
    dropout: float = 0.5
    freeze_base: bool = False
    dtype: Any = jnp.bfloat16
    stem_s2d: bool = False

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        base_train = train and not self.freeze_base
        feats = ResNetBackbone(self.depth, self.width_mult, self.dtype,
                               stem_s2d=self.stem_s2d,
                               name="backbone")(x, base_train)
        if self.freeze_base:
            # Keras trainable=False semantics: no gradients through the base
            # (same contract as MobileNetV2; XLA drops the backbone backward).
            feats = jax.lax.stop_gradient(feats)
        h = jnp.mean(feats.astype(jnp.float32), axis=(1, 2))
        h = nn.Dropout(self.dropout, deterministic=not train, name="head_dropout")(h)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(h)

    @staticmethod
    def frozen_prefixes(freeze_base: bool) -> tuple[str, ...]:
        return ("backbone",) if freeze_base else ()
