"""Mixture-of-Experts MLP with expert parallelism — Switch top-1 and GShard
top-2 routing.

Not a reference-parity item (the reference has no MoE — SURVEY.md §2d covers
DP/trial/HPO/batch-inference parallelism only); this is the expert-parallel
axis of the framework, same tier as TP (``parallel/sharding.py``) and SP
(``parallel/ring_attention.py``).

TPU-first formulation (Switch Transformer, Fedus et al. 2101.03961; GShard,
Lepikhin et al. 2006.16668):

- **token-choice routing** (``router="top1"`` Switch, ``router="top2"``
  GShard with renormalized pair gates) with a *static* per-expert capacity
  ``C = ceil(cf * k * T / E)`` — XLA needs fixed shapes, so routing builds
  dense dispatch/combine tensors ``[T, E, C]`` instead of data-dependent
  gathers; tokens past capacity fall through the residual connection
  (standard Switch/GShard semantics, first choices claiming capacity before
  second).
- **expert parallelism** over a named mesh axis: tokens stay sharded by the
  enclosing data/seq axes; each rank routes its local tokens against ALL ``E``
  experts, one ``lax.all_to_all`` ships the per-expert token blocks to the
  expert's owner rank, the owner applies its ``E_local = E / n`` expert FFNs,
  and a second ``all_to_all`` ships results back. The two all_to_alls ride ICI
  — this is THE canonical EP communication pattern.
- expert weights live as stacked tensors ``[E, D, H]`` (einsum over the expert
  dim hits the MXU batched); under EP each rank slices its own ``E_local``
  experts at apply time, so the parameter tree is identical with and without
  the axis (checkpoints are layout-stable; pair with ZeRO-1
  (``parallel/zero.py``) to shard the optimizer moments).
- the Switch **load-balance auxiliary loss** ``E * Σ_e f_e · p_e`` is sown
  under ``("intermediates", "moe_aux_loss")``; the LM train step adds it with
  coefficient ``aux_loss_weight`` when the model routes.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from ddw_tpu.utils.compat import axis_size


def collect_sown(mods: dict, name: str) -> list:
    """Every value sown under ``name`` anywhere in an ``intermediates``
    collection (flax stores sows as tuples). MoE blocks sow several keys
    (aux loss, routing telemetry, raw gate logits) — consumers MUST select by
    name rather than summing all leaves, or telemetry leaks into the loss."""
    from flax import traverse_util

    flat = traverse_util.flatten_dict(mods.get("intermediates", mods))
    return [x for path, leaf in flat.items() if name in path
            for x in (leaf if isinstance(leaf, (tuple, list)) else (leaf,))]


def top1_routing(gate_logits: jnp.ndarray, capacity: int):
    """Switch top-1 routing with static capacity.

    ``gate_logits`` [T, E] (f32) -> (dispatch [T, E, C] one-hot, combine
    [T, E, C] gate-weighted, aux_loss scalar, stats dict). Tokens beyond an
    expert's capacity get an all-zero dispatch row (they skip the expert; the
    caller's residual carries them).

    ``stats`` telemetry (all scalars except ``expert_frac`` [E]):
    ``drop_rate`` — fraction of tokens past capacity; ``balance_entropy`` —
    entropy of the expert-assignment distribution normalized by ``log E``
    (1.0 = perfectly balanced, 0.0 = collapsed onto one expert).
    """
    t, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)              # [T, E]
    expert_idx = jnp.argmax(probs, axis=-1)                   # [T]
    onehot = jax.nn.one_hot(expert_idx, e, dtype=probs.dtype)  # [T, E]
    gate = jnp.sum(probs * onehot, axis=-1)                   # [T]

    # Position of each token in its chosen expert's queue (arrival order).
    pos_in_expert = jnp.sum((jnp.cumsum(onehot, axis=0) - 1.0) * onehot,
                            axis=-1)                          # [T]
    keep = pos_in_expert < capacity
    cap_oh = jax.nn.one_hot(pos_in_expert.astype(jnp.int32), capacity,
                            dtype=probs.dtype)                # [T, C]
    dispatch = (onehot * keep[:, None])[:, :, None] * cap_oh[:, None, :]
    combine = dispatch * gate[:, None, None]

    # Switch aux loss: E * sum_e (fraction of tokens to e) * (mean prob of e).
    frac = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    stats = {
        "drop_rate": 1.0 - jnp.mean(keep.astype(probs.dtype)),
        "balance_entropy": (-jnp.sum(frac * jnp.log(frac + 1e-9))
                            / jnp.log(float(e))),
        "expert_frac": frac,
    }
    return dispatch, combine, aux, stats


def top2_routing(gate_logits: jnp.ndarray, capacity: int):
    """GShard-style top-2 routing with static capacity (Lepikhin et al.
    2006.16668): each token dispatches to its two highest-probability experts
    with gates renormalized over the pair; first choices claim expert
    capacity before second choices (arrival order within each choice).
    Same ``[T, E, C]`` dispatch/combine contract as :func:`top1_routing`, so
    the expert-parallel all_to_all path is identical.

    Aux loss is the GShard/Switch form over FIRST-choice assignments
    (``E * Σ_e f_e · p_e``). ``drop_rate`` counts dropped (token, choice)
    slots over ``2T``; ``balance_entropy`` is over the combined assignment
    distribution of both choices.
    """
    t, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)              # [T, E]
    top_p, top_i = lax.top_k(probs, 2)                        # [T, 2]
    gates = top_p / jnp.sum(top_p, axis=-1, keepdims=True)    # renormalized

    dispatch = jnp.zeros((t, e, capacity), probs.dtype)
    combine = jnp.zeros((t, e, capacity), probs.dtype)
    counts = jnp.zeros((e,), probs.dtype)   # capacity already claimed
    kept_slots = 0.0
    assign_frac = jnp.zeros((e,), probs.dtype)
    for choice in range(2):
        onehot = jax.nn.one_hot(top_i[:, choice], e, dtype=probs.dtype)
        # queue position among THIS choice's tokens, offset by earlier choices
        pos = (jnp.sum((jnp.cumsum(onehot, axis=0) - 1.0) * onehot, axis=-1)
               + onehot @ counts)                             # [T]
        keep = pos < capacity
        cap_oh = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                dtype=probs.dtype)
        d_c = (onehot * keep[:, None])[:, :, None] * cap_oh[:, None, :]
        dispatch = dispatch + d_c
        combine = combine + d_c * gates[:, choice][:, None, None]
        counts = counts + jnp.sum(onehot, axis=0)
        kept_slots = kept_slots + jnp.sum(keep.astype(probs.dtype))
        assign_frac = assign_frac + jnp.mean(onehot, axis=0) / 2.0

    first_frac = jnp.mean(jax.nn.one_hot(top_i[:, 0], e, dtype=probs.dtype),
                          axis=0)
    aux = e * jnp.sum(first_frac * jnp.mean(probs, axis=0))
    stats = {
        "drop_rate": 1.0 - kept_slots / (2.0 * t),
        "balance_entropy": (-jnp.sum(assign_frac * jnp.log(assign_frac + 1e-9))
                            / jnp.log(float(e))),
        "expert_frac": assign_frac,
    }
    return dispatch, combine, aux, stats


def router_fn(router: str):
    """(routing fn, choices-per-token k) for a router name — the one place
    that maps names to semantics (MoEMlp and the characterization sweep both
    resolve through it, so they cannot diverge)."""
    if router == "top1":
        return top1_routing, 1
    if router == "top2":
        return top2_routing, 2
    raise ValueError(f"unknown router {router!r}; use 'top1' or 'top2'")


def expert_capacity(cf: float, k: int, tokens: int, experts: int) -> int:
    """Static per-expert capacity ``ceil(cf * k * T / E)`` (>= 1)."""
    return max(1, int(-(-cf * k * tokens // experts)))


class MoEMlp(nn.Module):
    """Drop-in MoE replacement for a transformer's dense MLP block.

    ``expert_axis=None``: every expert computed locally (dense MoE).
    ``expert_axis='data'`` (inside shard_map): expert parallelism — experts
    partitioned across the axis, tokens exchanged via ``lax.all_to_all``. The
    axis size must divide ``num_experts``.
    """

    num_experts: int
    mlp_dim: int
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    expert_axis: str | None = None
    no_drop: bool = False    # inference/decode: capacity = T, never drop — a
                             # generated continuation must not depend on which
                             # other batch entries route to the same expert
    router: str = "top1"     # "top1" (Switch) or "top2" (GShard, renormalized
                             # pair gates; cf is per-choice, so effective
                             # capacity doubles relative to top1 at equal cf)

    @nn.compact
    def __call__(self, x):
        route, k = router_fn(self.router)
        b, s, d = x.shape
        t = b * s
        e = self.num_experts
        if k > e:
            raise ValueError(f"{self.router} routing needs at least {k} "
                             f"experts, got {e}")
        xt = x.reshape(t, d)

        gate_logits = nn.Dense(e, dtype=jnp.float32, name="gate")(
            xt.astype(jnp.float32))
        capacity = (t if self.no_drop
                    else expert_capacity(self.capacity_factor, k, t, e))
        dispatch, combine, aux, stats = route(gate_logits, capacity)
        self.sow("intermediates", "moe_aux_loss", aux)
        # Routing telemetry for characterization (tools/moe_capacity_sweep.py)
        # and observability; reductions over these are cheap next to the FFNs.
        self.sow("intermediates", "moe_drop_rate", stats["drop_rate"])
        self.sow("intermediates", "moe_balance_entropy",
                 stats["balance_entropy"])
        # Raw router scores for offline capacity sweeps; unused sows are
        # dead-code-eliminated by XLA in training steps.
        self.sow("intermediates", "gate_logits", gate_logits)

        # Stacked expert weights: one batched einsum per matmul (MXU-friendly),
        # identical param layout with and without EP.
        k_init = nn.initializers.lecun_normal()
        w1 = self.param("w1", k_init, (e, d, self.mlp_dim), jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros, (e, self.mlp_dim),
                        jnp.float32)
        w2 = self.param("w2", k_init, (e, self.mlp_dim, d), jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros, (e, d), jnp.float32)

        # [T, E, C] x [T, D] -> per-expert token blocks [E, C, D]
        expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(self.dtype),
                               xt.astype(self.dtype))

        def ffn(blocks, w1_, b1_, w2_, b2_):
            # blocks [..., E?, C', D] with matching leading expert dim in w/b
            h = jnp.einsum("...ecd,edh->...ech", blocks,
                           w1_.astype(self.dtype))
            h = nn.gelu(h + b1_.astype(self.dtype)[..., None, :])
            out = jnp.einsum("...ech,ehd->...ecd", h, w2_.astype(self.dtype))
            return out + b2_.astype(self.dtype)[..., None, :]

        if self.expert_axis is None:
            expert_out = ffn(expert_in, w1, b1, w2, b2)        # [E, C, D]
        else:
            n = axis_size(self.expert_axis)
            if e % n:
                raise ValueError(f"num_experts {e} not divisible by "
                                 f"{self.expert_axis!r} axis size {n}")
            e_local = e // n
            me = lax.axis_index(self.expert_axis)
            # Ship each expert's token block to its owner rank: regroup the
            # expert dim by owner, all_to_all over the owner dim. Result on
            # rank r: [n_src, E_local, C, D] — r's experts' tokens from every
            # source rank.
            grouped = expert_in.reshape(n, e_local, capacity, d)
            received = lax.all_to_all(grouped, self.expert_axis,
                                      split_axis=0, concat_axis=0, tiled=False)
            sl = lambda p: lax.dynamic_slice_in_dim(  # noqa: E731
                p, me * e_local, e_local, axis=0)
            out_blocks = ffn(received, sl(w1), sl(b1), sl(w2), sl(b2))
            # Inverse exchange: results back to the tokens' source ranks.
            returned = lax.all_to_all(out_blocks, self.expert_axis,
                                      split_axis=0, concat_axis=0, tiled=False)
            expert_out = returned.reshape(e, capacity, d)

        out = jnp.einsum("tec,ecd->td", combine.astype(self.dtype),
                         expert_out)
        return out.reshape(b, s, d)
