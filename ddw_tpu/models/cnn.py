"""SmallCNN — a fast from-scratch CNN for tests and CPU-capable runs.

Fills the "small CNN, flowers JPEG subset, CPU, 1 epoch" baseline config
(/root/repo/BASELINE.json configs[0]) and keeps the unit-test suite fast. Same
head contract as MobileNetV2 (GAP -> Dropout -> Dense logits) so the trainer and
serving paths are model-agnostic. Stateless normalization (GroupNorm) — no
batch_stats collection — so seeded 1-device vs N-device equivalence tests are exact.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class SmallCNN(nn.Module):
    num_classes: int = 5
    width: int = 32
    dropout: float = 0.5
    freeze_base: bool = False  # accepted for API parity; no pretrained base to freeze
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for i, mult in enumerate((1, 2, 4)):
            x = nn.Conv(self.width * mult, (3, 3), strides=2 if i else 1,
                        padding="SAME", use_bias=False, dtype=self.dtype, name=f"backbone_conv{i}")(x)
            x = nn.GroupNorm(num_groups=8, dtype=jnp.float32)(x)
            x = nn.relu(x).astype(self.dtype)
        h = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
        h = nn.Dropout(self.dropout, deterministic=not train, name="head_dropout")(h)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(h)

    @staticmethod
    def frozen_prefixes(freeze_base: bool) -> tuple[str, ...]:
        return ()
