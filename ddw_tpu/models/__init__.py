from ddw_tpu.models.registry import build_model, register_model, MODEL_REGISTRY  # noqa: F401
from ddw_tpu.models.cnn import SmallCNN  # noqa: F401
from ddw_tpu.models.mobilenet_v2 import MobileNetV2  # noqa: F401
from ddw_tpu.models.vit import ViT  # noqa: F401
