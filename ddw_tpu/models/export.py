"""Export a MobileNetV2Backbone to torchvision / Keras weight layouts.

The exact inverse of the two import paths in :mod:`ddw_tpu.models.convert` —
which exists so the full transfer contract can be *proved* in-repo, not just
unit-tested against synthetic dicts: pretrain a backbone here, export it in
the same layouts the reference's pretrained artifacts ship in (torchvision
``mobilenet_v2`` state_dict; Keras-applications weights, the format the
reference downloads at ``Part 1 - Distributed Training/
02_model_training_single_node.py:164``), then run it back through
``convert.py`` and the frozen-base head-training chain. Round-trip is exact:
``convert_torch_mobilenet_v2(export_torch_mobilenet_v2(v)) == v`` up to the
BN-epsilon fold, which both directions apply symmetrically.

Layout mirrors (see the converter for the forward mapping):
- conv kernels: flax ``[kh, kw, in, out]`` -> torch ``[out, in, kh, kw]``
  (same transpose handles depthwise: flax ``[kh,kw,1,C]`` -> torch ``[C,1,kh,kw]``);
  Keras keeps flax layout for regular convs, ``[kh,kw,C,1]`` for depthwise.
- BatchNorm: our scale carries the Keras epsilon (1e-3); exporting to torch
  (eps 1e-5) inverts the fold ``scale' = scale * sqrt((var+eps_dst)/(var+eps_src))``
  so a subsequent import reproduces the original values exactly. Keras shares
  our epsilon, so its fold is the identity.

Any ``width_mult`` exports fine — both layouts are name-positional, and the
converter validates shapes against the target model on load.
"""

from __future__ import annotations

import numpy as np

from ddw_tpu.models.convert import _EPS_FLAX, _EPS_TORCH
from ddw_tpu.models.mobilenet_v2 import _INVERTED_RESIDUAL_CFG


def _t(kernel: np.ndarray) -> np.ndarray:
    """flax conv kernel -> torch layout."""
    return np.asarray(kernel, np.float32).transpose(3, 2, 0, 1)


def _bn_out(sub_p: dict, sub_s: dict, eps_dst: float) -> tuple[np.ndarray, ...]:
    """(weight, bias, mean, var) with the epsilon fold inverted for eps_dst."""
    var = np.asarray(sub_s["var"], np.float32)
    scale = np.asarray(sub_p["scale"], np.float32)
    scale = scale * np.sqrt((var + eps_dst) / (var + _EPS_FLAX))
    return (scale, np.asarray(sub_p["bias"], np.float32),
            np.asarray(sub_s["mean"], np.float32), var)


def export_torch_mobilenet_v2(backbone_vars: dict,
                              eps_dst: float = _EPS_TORCH) -> dict[str, np.ndarray]:
    """Backbone ``{"params", "batch_stats"}`` trees -> torchvision-layout
    state_dict (numpy values; ``torch.save``-able as-is)."""
    params, stats = backbone_vars["params"], backbone_vars["batch_stats"]
    sd: dict[str, np.ndarray] = {}

    def put(conv_prefix: str, bn_prefix: str, p: dict, s: dict):
        sd[f"{conv_prefix}.weight"] = _t(p["Conv_0"]["kernel"])
        w, b, m, v = _bn_out(p["BatchNorm_0"], s["BatchNorm_0"], eps_dst)
        sd[f"{bn_prefix}.weight"] = w
        sd[f"{bn_prefix}.bias"] = b
        sd[f"{bn_prefix}.running_mean"] = m
        sd[f"{bn_prefix}.running_var"] = v
        sd[f"{bn_prefix}.num_batches_tracked"] = np.asarray(0, np.int64)

    put("features.0.0", "features.0.1", params["ConvBN_0"], stats["ConvBN_0"])
    block = 0
    for t, _c, n, _s in _INVERTED_RESIDUAL_CFG:
        for _ in range(n):
            f = f"features.{block + 1}"
            if t == 1:
                pairs = [(f"{f}.conv.0.0", f"{f}.conv.0.1"),
                         (f"{f}.conv.1", f"{f}.conv.2")]
            else:
                pairs = [(f"{f}.conv.0.0", f"{f}.conv.0.1"),
                         (f"{f}.conv.1.0", f"{f}.conv.1.1"),
                         (f"{f}.conv.2", f"{f}.conv.3")]
            p = params[f"InvertedResidual_{block}"]
            s = stats[f"InvertedResidual_{block}"]
            for i, (cp, bp) in enumerate(pairs):
                put(cp, bp, p[f"ConvBN_{i}"], s[f"ConvBN_{i}"])
            block += 1
    put("features.18.0", "features.18.1", params["ConvBN_1"], stats["ConvBN_1"])
    return sd


def export_keras_mobilenet_v2(backbone_vars: dict) -> dict[str, np.ndarray]:
    """Backbone trees -> flat Keras-applications ``layer/weight`` dict (save
    with ``np.savez`` to feed ``convert.load_keras_weights``)."""
    params, stats = backbone_vars["params"], backbone_vars["batch_stats"]
    w: dict[str, np.ndarray] = {}

    def put(conv: str, bn: str, p: dict, s: dict, depthwise: bool):
        kernel = np.asarray(p["Conv_0"]["kernel"], np.float32)
        if depthwise:
            # flax grouped [kh,kw,1,C] -> keras depthwise [kh,kw,C,1]
            w[f"{conv}/depthwise_kernel"] = kernel.transpose(0, 1, 3, 2)
        else:
            w[f"{conv}/kernel"] = kernel
        gamma, beta, mean, var = _bn_out(p["BatchNorm_0"], s["BatchNorm_0"],
                                         _EPS_FLAX)  # identity fold
        w[f"{bn}/gamma"] = gamma
        w[f"{bn}/beta"] = beta
        w[f"{bn}/moving_mean"] = mean
        w[f"{bn}/moving_variance"] = var

    put("Conv1", "bn_Conv1", params["ConvBN_0"], stats["ConvBN_0"], False)
    block = 0
    for t, _c, n, _s in _INVERTED_RESIDUAL_CFG:
        for _ in range(n):
            pfx = "expanded_conv" if block == 0 else f"block_{block}"
            stages = []
            if t != 1:
                stages.append((f"{pfx}_expand", f"{pfx}_expand_BN", False))
            stages += [(f"{pfx}_depthwise", f"{pfx}_depthwise_BN", True),
                       (f"{pfx}_project", f"{pfx}_project_BN", False)]
            p = params[f"InvertedResidual_{block}"]
            s = stats[f"InvertedResidual_{block}"]
            for i, (conv, bn, dw) in enumerate(stages):
                put(conv, bn, p[f"ConvBN_{i}"], s[f"ConvBN_{i}"], dw)
            block += 1
    put("Conv_1", "Conv_1_bn", params["ConvBN_1"], stats["ConvBN_1"], False)
    return w
