"""Speculative decoding: draft-model proposals verified by the target in one
batched decode call (Leviathan et al. 2211.17192, greedy acceptance).

Beyond-parity serving feature for the LM family (the reference has no LM —
SURVEY.md §5 "Long-context ... Absent"). Autoregressive decode is
latency-bound by one target forward per token; a small draft model proposes
``k`` tokens and the target scores all of them in a single ``S=k+1`` decode
call (the KV-cached path accepts multi-token blocks with intra-block
causality — ``models/lm.py`` CausalSelfAttention decode tiling), so each
round costs one target forward + k cheap draft forwards and yields between 1
and k+1 confirmed tokens.

Greedy acceptance: drafts are accepted while they equal the target's own
argmax, and the first disagreement is replaced by the target's choice — the
output is therefore EXACTLY the target's greedy continuation (pinned by
``test_spec_decode.py``); the draft only changes latency, never content.

Cache bookkeeping: both models' KV caches advance during drafting/verification
and are rewound over rejected positions by resetting the ``cache_index`` /
``pos_index`` scalars (stale K/V rows beyond the index are never attended —
the decode mask bounds keys by query position — and are overwritten by the
next write at that position).

This module is the OFFLINE kernel (one sequence, dense cache). The live
batched serving graft — per-tick draft/verify over paged KV with block-level
rollback — lives in :meth:`ddw_tpu.serve.ServingEngine._spec_tick` +
:class:`ddw_tpu.serve.BlockPool` (``spec_draft`` / ``spec_verify`` /
``commit_spec``); both share the :func:`match_length` acceptance rule, which
is what makes spec-on output bit-identical to spec-off.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ddw_tpu.models.lm import TransformerLM, init_cache

_REWIND_KEYS = ("cache_index", "pos_index")


def match_length(drafts, picks) -> int:
    """Exact-match acceptance: the number of leading draft proposals that
    equal the verifier's own picks at the same positions. Position ``j``'s
    pick is conditioned on drafts ``0..j-1`` all having been accepted, so
    the emitted block ``drafts[:m] + [picks[m]]`` is — by induction —
    exactly what step-by-step decode with the same picker (argmax, or
    seeded sampling keyed per step) would have produced. Shared by the
    offline kernel below and the serving engine's ``_spec_tick``."""
    m = 0
    k = min(len(drafts), len(picks))
    while m < k and int(picks[m]) == int(drafts[m]):
        m += 1
    return m


def _rewind(cache, n: int):
    """Roll a decode cache back ``n`` positions (index scalars only)."""
    if n == 0:
        return cache

    def fix(path, leaf):
        if path[-1].key in _REWIND_KEYS:
            return leaf - n
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.partial(jax.jit, static_argnames=("_dm",))
def _run(dm_params, cache, toks, *, _dm):
    """One decode call; module-level so the jit cache (keyed on the static
    module + shapes) amortizes across generate_speculative invocations."""
    logits, vars_ = _dm.apply({"params": dm_params, "cache": cache},
                              toks, mutable=["cache"])
    return vars_["cache"], logits


@functools.partial(jax.jit, static_argnames=("_dm", "k"))
def _draft_round(dm_params, cache, lag_toks, *, _dm, k):
    """One whole drafting round as ONE dispatch: consume the lag block, then
    greedy-decode k tokens via lax.scan inside the jit. A per-token host loop
    would pay k dispatch+fetch round-trips per round — on a TPU that latency
    is exactly what speculative decoding exists to amortize, so the draft
    must not reintroduce it. Returns (cache, drafts[k])."""
    def step(cache, tok):
        logits, vars_ = _dm.apply({"params": dm_params, "cache": cache},
                                  tok, mutable=["cache"])
        nxt = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)
        return vars_["cache"], nxt

    cache, tok = step(cache, lag_toks)  # d_1 from the lag block

    def body(carry, _):
        cache, tok = carry
        new_cache, nxt = step(cache, tok)
        return (new_cache, nxt), tok[0, 0]

    (cache, last), emitted = lax.scan(body, (cache, tok), None, length=k - 1)
    drafts = jnp.concatenate([emitted, last[0]])  # d_1..d_{k-1} + d_k
    return cache, drafts


def generate_speculative(model: TransformerLM, params,
                         draft_model: TransformerLM, draft_params,
                         prompt, num_steps: int, k: int = 4):
    """Greedy continuation of ``prompt`` equal to ``generate(model, ...,
    temperature=0)``, produced with draft-verified rounds.

    ``prompt`` is int32 ``[1, P]`` (speculative decoding is a latency
    optimization — per-row acceptance lengths diverge, so batching is out of
    scope and B>1 raises). Returns ``(tokens[1, num_steps], stats)`` where
    ``stats`` reports rounds, draft tokens proposed/accepted and the
    acceptance rate.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    b, plen = prompt.shape
    if b != 1:
        raise ValueError(f"speculative decoding is per-sequence (B=1), "
                         f"got batch {b}")
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if model.vocab_size != draft_model.vocab_size:
        raise ValueError("target and draft must share a vocabulary "
                         f"({model.vocab_size} vs {draft_model.vocab_size})")
    # Verification writes up to k unaccepted rows past the confirmed prefix
    # before the rewind; they must stay inside the cache or the overflow
    # NaN-poison fires on rows that would later be rolled back.
    if plen + num_steps + k + 1 > model.max_len:
        raise ValueError(f"prompt {plen} + steps {num_steps} + lookahead "
                         f"{k + 1} exceeds target max_len {model.max_len}")
    if plen + num_steps + k + 1 > draft_model.max_len:
        raise ValueError(f"prompt {plen} + steps {num_steps} + lookahead "
                         f"{k + 1} exceeds draft max_len {draft_model.max_len}")

    dm_t = model.clone(decode=True, seq_axis=None, dropout=0.0)
    dm_d = draft_model.clone(decode=True, seq_axis=None, dropout=0.0)
    run_t = functools.partial(_run, _dm=dm_t)
    run_d = functools.partial(_run, _dm=dm_d)

    cache_t = init_cache(dm_t, 1)
    cache_d = init_cache(dm_d, 1)

    # Prefill the target; its last-position argmax is the first confirmed
    # token (identical to greedy generate's first pick). The draft prefills
    # everything except the last prompt token — that token is its first
    # drafting input next round.
    cache_t, logits = run_t(params, cache_t, prompt)
    first = int(jnp.argmax(logits[0, -1]))
    if plen > 1:
        cache_d, _ = run_d(draft_params, cache_d, prompt[:, :-1])

    # H = confirmed sequence; invariant between rounds: the target cache has
    # processed H[:-1], the draft cache H[:p_d] with p_d <= len(H)-1.
    H = list(np.asarray(prompt[0])) + [first]
    p_d = plen - 1
    rounds = proposed = accepted_drafts = 0

    while len(H) - plen < num_steps:
        rounds += 1
        # -- draft k greedy proposals (one dispatch, one fetch) ------------
        lag = H[p_d:]  # unprocessed confirmed tokens, ending with H[-1]
        cache_d, draft_arr = _draft_round(draft_params, cache_d,
                                          jnp.asarray([lag], jnp.int32),
                                          _dm=dm_d, k=k)
        drafts = [int(t) for t in np.asarray(draft_arr)]
        p_d = len(H) + k - 1  # processed: lag + drafts[:-1]

        # -- verify: one target call over [t_cur, d_1..d_k] ---------------
        block = jnp.asarray([[H[-1]] + drafts], jnp.int32)
        cache_t, tlogits = run_t(params, cache_t, block)
        preds = np.asarray(jnp.argmax(tlogits[0], axis=-1))  # [k+1]
        m = match_length(drafts, preds)
        t_new = int(preds[m])

        # -- bookkeeping + rewinds ----------------------------------------
        proposed += k
        accepted_drafts += m
        H.extend(drafts[:m] + [t_new])
        cache_t = _rewind(cache_t, k - m)      # keep inputs t_cur, d_1..d_m
        # Draft processed t_cur, d_1..d_{k-1}; its valid prefix is
        # t_cur..d_m. Full acceptance (m == k) rewinds nothing — d_k simply
        # stays unprocessed and rides in next round's lag.
        rew_d = (k - 1) - m if m < k else 0
        if rew_d:
            cache_d = _rewind(cache_d, rew_d)
            p_d -= rew_d

    gen = H[plen:plen + num_steps]
    target_calls = rounds + 1  # verification rounds + the prefill call
    stats = {"rounds": rounds, "target_calls": target_calls,
             "drafts_proposed": proposed,
             "drafts_accepted": accepted_drafts,
             "acceptance_rate": (accepted_drafts / proposed if proposed
                                 else 0.0),
             # returned tokens over target forwards — plain greedy decode
             # would be 1.0 by this same accounting
             "tokens_per_target_call": len(gen) / target_calls}
    return jnp.asarray([gen], jnp.int32), stats
