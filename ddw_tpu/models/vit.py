"""ViT — attention model family exercising tensor/sequence parallelism.

The reference has no attention model (SURVEY.md §2d: TP/SP "not required for
parity"), but long-context and model sharding are first-class axes of this
framework: ViT is the in-tree model whose attention runs through
``ddw_tpu.parallel.ring_attention`` when the mesh has a ``seq`` axis and whose
MLP/attention projections shard over ``model``. Patch-embed -> pre-LN transformer
blocks -> GAP head (same head contract as the CNNs, so trainer/serving are
model-agnostic).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from ddw_tpu.ops.flash_attention import flash_mha


class FlashMHA(nn.Module):
    """Self-attention over the in-tree Pallas flash kernel.

    Param layout matches ``nn.MultiHeadDotProductAttention`` —
    ``{query,key,value}/kernel [embed, heads, head_dim]``, ``out/kernel
    [heads, head_dim, embed]`` — so :data:`ddw_tpu.parallel.sharding
    .VIT_TP_RULES` shards it unchanged and checkpoints stay layout-stable.
    The kernel pads ViT's 196-patch sequences to a block multiple internally
    (:func:`ddw_tpu.ops.flash_attention.flash_mha`). ``lora_rank > 0`` puts
    adapters on the targeted projections (ddw_tpu.models.lora — base param
    paths unchanged)."""

    num_heads: int
    dtype: Any = jnp.bfloat16
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple[str, ...] = ("query", "value")

    @nn.compact
    def __call__(self, x):
        from ddw_tpu.models.lora import maybe_lora_dense

        d = x.shape[-1]
        if d % self.num_heads:
            raise ValueError(f"hidden {d} not divisible by heads {self.num_heads}")
        head_dim = d // self.num_heads

        def dense(name):
            return maybe_lora_dense((self.num_heads, head_dim), name,
                                    rank=self.lora_rank, alpha=self.lora_alpha,
                                    targets=self.lora_targets, dtype=self.dtype)

        q = dense("query")(x)   # [B, S, H, hd]
        k = dense("key")(x)
        v = dense("value")(x)
        qh, kh, vh = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        out = flash_mha(qh, kh, vh, causal=False)
        out = out.transpose(0, 2, 1, 3)  # [B, S, H, hd]
        return maybe_lora_dense(d, "out", rank=self.lora_rank,
                                alpha=self.lora_alpha,
                                targets=self.lora_targets, dtype=self.dtype,
                                contract_ndim=2)(out)


class MlpBlock(nn.Module):
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple[str, ...] = ("query", "value")

    @nn.compact
    def __call__(self, x):
        from ddw_tpu.models.lora import maybe_lora_dense

        d = x.shape[-1]

        def dense(feats, name):
            return maybe_lora_dense(feats, name, rank=self.lora_rank,
                                    alpha=self.lora_alpha,
                                    targets=self.lora_targets,
                                    dtype=self.dtype)

        h = dense(self.mlp_dim, "fc1")(x)
        h = nn.gelu(h)
        return dense(d, "fc2")(h)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any = jnp.bfloat16
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple[str, ...] = ("query", "value")

    @nn.compact
    def __call__(self, x, train: bool):
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = FlashMHA(num_heads=self.num_heads, dtype=self.dtype,
                     lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
                     lora_targets=self.lora_targets, name="attn")(h)
        x = x + h
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        h = MlpBlock(self.mlp_dim, dtype=self.dtype,
                     lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
                     lora_targets=self.lora_targets, name="mlp")(h)
        return x + h


class ViT(nn.Module):
    num_classes: int = 5
    patch: int = 16
    hidden: int = 192
    depth: int = 6
    # 4 heads (not 3): TP shards heads over the `model` axis, so the count must
    # divide small axis sizes. Changing this default changes q/k/v param shapes
    # — checkpoints/packages saved with another head count need num_heads set
    # explicitly at restore.
    num_heads: int = 4
    mlp_dim: int = 768
    dropout: float = 0.1
    freeze_base: bool = False
    dtype: Any = jnp.bfloat16
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: tuple[str, ...] = ("query", "value")

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.lora_rank:
            from ddw_tpu.models.lora import validate_lora_targets

            validate_lora_targets(self.lora_targets)
        x = x.astype(self.dtype)
        x = nn.Conv(self.hidden, (self.patch, self.patch), strides=self.patch,
                    name="backbone_patch_embed", dtype=self.dtype)(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)
        pos = self.param("pos_embed", nn.initializers.normal(0.02), (1, h * w, c), jnp.float32)
        x = x + pos.astype(self.dtype)
        for i in range(self.depth):
            x = EncoderBlock(self.num_heads, self.mlp_dim, dtype=self.dtype,
                             lora_rank=self.lora_rank,
                             lora_alpha=self.lora_alpha,
                             lora_targets=self.lora_targets,
                             name=f"backbone_block{i}")(x, train)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        hfeat = jnp.mean(x.astype(jnp.float32), axis=1)
        hfeat = nn.Dropout(self.dropout, deterministic=not train, name="head_dropout")(hfeat)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(hfeat)

    @staticmethod
    def frozen_prefixes(freeze_base: bool) -> tuple[str, ...]:
        return ()
