"""Weight-only int8 quantization for packaged models.

The reference ships full-precision weights inside its MLflow pyfunc artifact
(``03_pyfunc_distributed_inference.py:157-184``); at fleet scale the artifact
size is what every scorer worker downloads and every registry version stores.
Per-output-channel symmetric int8 cuts that ~4x with sub-percent logit error:

    scale[c] = max(|W[..., c]|) / 127          (one f32 per output channel)
    Q[..., c] = round(W[..., c] / scale[c])    (int8)

Serving dequantizes at load (``W ≈ Q * scale``) and predicts with the normal
f32/bf16 path — the claim is storage + artifact-transfer bandwidth, NOT int8
compute (that would need activation quantization and per-op calibration; on
one v5e chip the predict path is nowhere near MXU-bound at sub-batch 128).

Only floating leaves with ``ndim >= 2`` quantize (conv/dense kernels, where
the bytes are); 1-D leaves (biases, BN stats) and integer leaves pass
through. The quantized tree serializes through the same flax msgpack path as
the plain one — each quantized leaf becomes a ``{_Q8_VALUES, _Q8_SCALE}``
dict, restored transparently by :func:`dequantize_tree`.
"""

from __future__ import annotations

import numpy as np

_Q8_VALUES = "__q8_values__"
_Q8_SCALE = "__q8_scale__"
MODE_INT8 = "int8_weight_only"


def _is_quantizable(leaf) -> bool:
    a = np.asarray(leaf)
    return a.ndim >= 2 and np.issubdtype(a.dtype, np.floating)


def quantize_tree(tree):
    """Per-output-channel symmetric int8 on every quantizable leaf. Returns a
    tree serializable by ``flax.serialization`` exactly like the input."""
    if isinstance(tree, dict):
        if set(tree) == {_Q8_VALUES, _Q8_SCALE}:
            raise ValueError("tree is already quantized")
        return {k: quantize_tree(v) for k, v in tree.items()}
    if not _is_quantizable(tree):
        return np.asarray(tree)
    w = np.asarray(tree, np.float32)
    absmax = np.max(np.abs(w), axis=tuple(range(w.ndim - 1)), keepdims=True)
    scale = (absmax / 127.0).astype(np.float32)
    scale = np.where(scale == 0.0, np.float32(1.0), scale)  # all-zero channel
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return {_Q8_VALUES: q, _Q8_SCALE: np.squeeze(scale, tuple(range(w.ndim - 1)))}


def dequantize_tree(tree):
    """Inverse of :func:`quantize_tree`: int8 leaves back to f32."""
    if isinstance(tree, dict):
        if set(tree) == {_Q8_VALUES, _Q8_SCALE}:
            q = np.asarray(tree[_Q8_VALUES])
            scale = np.asarray(tree[_Q8_SCALE])
            return q.astype(np.float32) * scale
        return {k: dequantize_tree(v) for k, v in tree.items()}
    return tree


def is_quantized_tree(tree) -> bool:
    if isinstance(tree, dict):
        if set(tree) == {_Q8_VALUES, _Q8_SCALE}:
            return True
        return any(is_quantized_tree(v) for v in tree.values())
    return False
