from ddw_tpu.serving.package import PackagedModel, save_packaged_model, load_packaged_model  # noqa: F401
from ddw_tpu.serving.batch import BatchScorer  # noqa: F401
