from ddw_tpu.serving.package import PackagedModel, save_packaged_model, load_packaged_model  # noqa: F401
from ddw_tpu.serving.batch import BatchScorer, LMBatchScorer  # noqa: F401
from ddw_tpu.serving.lm_package import (  # noqa: F401
    LMPackagedModel,
    load_lm_package,
    save_lm_package,
)
