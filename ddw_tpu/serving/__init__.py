from ddw_tpu.serving.package import (  # noqa: F401
    ImageEngineHandle,
    PackagedModel,
    load_packaged_model,
    save_packaged_model,
)
from ddw_tpu.serving.batch import BatchScorer, LMBatchScorer  # noqa: F401
from ddw_tpu.serving.lm_package import (  # noqa: F401
    LMEngineHandle,
    LMPackagedModel,
    load_lm_package,
    save_lm_package,
)
