"""Packaged LM artifacts: self-contained scoring + generation directories.

The image side packages a classifier (``serving/package.py`` — the
``mlflow.pyfunc`` role, reference ``03_pyfunc_distributed_inference.py:
157-184``); this is the same contract for the LM family (beyond parity — the
reference has no LM): one directory holding config + weights that any worker
can load and drive without the training code path.

Layout (mirrors the image package):

    package.json     lm config, format/version metadata, optional quantization
    params.msgpack   flax params — full precision or int8 weight-only
                     (``ddw_tpu.serving.quantize``, ~4x smaller artifact)

``LMPackagedModel`` exposes:

- ``score(tokens[B, S+1]) -> nll[B]`` — mean next-token negative
  log-likelihood per sequence (the batch-scoring primitive; perplexity is
  ``exp(nll)``);
- ``generate(prompt, num_steps, ...)`` — the KV-cached decode path with the
  same sampling controls as :func:`ddw_tpu.models.lm.generate`;
- ``generate_speculative(draft, prompt, num_steps, k)`` — draft-verified
  decoding against another packaged model, exact greedy output.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ddw_tpu.models.lm import build_lm, generate
from ddw_tpu.utils.config import LMCfg

_LM_FORMAT_VERSION = 1
_LM_FORMAT_VERSION_QUANT = 2
_SUPPORTED = (_LM_FORMAT_VERSION, _LM_FORMAT_VERSION_QUANT)


def sequence_nll(model, params, tokens, lengths=None):
    """Per-sequence mean next-token NLL of ``tokens [B, S+1]`` — THE single
    scoring definition, jitted by both :class:`LMPackagedModel` and
    ``serving.batch.LMBatchScorer`` so the two paths cannot drift. Callers
    must bounds-check token ids first (:func:`check_token_ids`): jnp gathers
    clamp out-of-range indices, which would silently score the nearest
    vocab row.

    ``lengths`` (optional ``[B]``) gives each row's TRUE target count when
    ``tokens`` is right-padded to a shape bucket — padded positions drop out
    of the mean (causal masking already keeps them out of real positions'
    logits). Zero-length pad rows return 0, to be sliced off by the caller.
    """
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    logits = model.apply({"params": params}, inp, train=False)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    tok_ll = jnp.take_along_axis(logp, tgt[..., None], -1)[..., 0]
    if lengths is None:
        return -jnp.mean(tok_ll, axis=-1)
    mask = jnp.arange(tgt.shape[1])[None, :] < lengths[:, None]
    return -jnp.sum(tok_ll * mask, axis=-1) / jnp.maximum(lengths, 1)


def check_token_ids(tokens, vocab_size: int) -> None:
    """Refuse out-of-vocab ids before any gather sees them."""
    if tokens.min() < 0 or tokens.max() >= vocab_size:
        raise ValueError(f"token ids outside [0, {vocab_size}): "
                         f"min={tokens.min()}, max={tokens.max()}")


def save_lm_package(out_dir: str, lm_cfg: LMCfg, params,
                    extra_meta: dict | None = None,
                    quantize: str | None = None) -> str:
    """Write a packaged-LM directory. ``quantize="int8"`` stores kernels as
    per-output-channel int8 (transparent dequantize at load)."""
    from ddw_tpu.serving.package import write_package_dir

    reserved = {"kind", "format_version", "lm_cfg", "quantization"}
    clash = reserved & set(extra_meta or {})
    if clash:
        # loud at save time: a clobbered kind/format_version would only be
        # discovered when the artifact fails to load
        raise ValueError(f"extra_meta must not override reserved keys "
                         f"{sorted(clash)}")
    meta = {
        "kind": "lm",
        "format_version": _LM_FORMAT_VERSION,
        "lm_cfg": dataclasses.asdict(lm_cfg),
        **(extra_meta or {}),
    }
    tree = {"params": jax.device_get(params)}
    return write_package_dir(out_dir, meta, tree, quantize,
                             _LM_FORMAT_VERSION_QUANT)


@dataclasses.dataclass
class LMEngineHandle:
    """What :class:`ddw_tpu.serve.ServingEngine` needs from an LM package:
    the bare model/params plus the config that bounds admission validation.
    A handle, not the package object, so any weight source (a fresh
    ``init``, a checkpoint restore) can serve through the engine too."""

    model: object               # TransformerLM (decode clones built inside)
    params: object
    cfg: LMCfg
    content_digest: str = ""


class LMPackagedModel:
    """Self-contained LM scorer/generator loaded from a package directory.

    Variable request shapes are padded to the shared serving buckets
    (:mod:`ddw_tpu.serve.bucketing`) before hitting jit, so scoring or
    generating over arbitrary prompt lengths compiles O(log max_len)
    programs instead of one per observed length — the same discipline the
    online engine applies, here on the single-request path."""

    def __init__(self, model_dir: str):
        from ddw_tpu.serving.package import read_package_dir

        self.meta, restored, self.content_digest = read_package_dir(
            model_dir, "lm", _SUPPORTED,
            "image packages load via ddw_tpu.serving.PackagedModel")
        self.lm_cfg = LMCfg(**{k: (tuple(v) if isinstance(v, list) else v)
                               for k, v in self.meta["lm_cfg"].items()})
        self.model = build_lm(self.lm_cfg)
        self.params = restored["params"]

        self._nll = jax.jit(
            lambda tokens, lengths: sequence_nll(self.model, self.params,
                                                 tokens, lengths))
        self._gen_cache: dict[tuple, object] = {}

    def engine_handle(self) -> LMEngineHandle:
        return LMEngineHandle(self.model, self.params, self.lm_cfg,
                              self.content_digest)

    def score(self, tokens) -> np.ndarray:
        """Mean next-token NLL per sequence; perplexity = exp(score)."""
        from ddw_tpu.serve.bucketing import bucket_len, pad_to_bucket

        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim != 2 or tokens.shape[1] < 2:
            raise ValueError(f"tokens must be [B, S+1], got {tokens.shape}")
        if tokens.shape[1] - 1 > self.lm_cfg.max_len:
            raise ValueError(f"sequence {tokens.shape[1] - 1} exceeds "
                             f"max_len {self.lm_cfg.max_len}")
        check_token_ids(tokens, self.lm_cfg.vocab_size)
        b, width = tokens.shape
        padded = pad_to_bucket(
            tokens, bucket_len(width, self.lm_cfg.max_len + 1))
        lengths = np.full((b,), width - 1, np.int32)
        return np.asarray(self._nll(padded, lengths))

    def generate(self, prompt, num_steps: int, rng=None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 0.0) -> np.ndarray:
        from ddw_tpu.serve.bucketing import bucket_len, pad_to_bucket

        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 2 or prompt.shape[1] < 1:
            raise ValueError(f"prompt must be [B, P], got {prompt.shape}")
        b, plen = prompt.shape
        if plen + num_steps > self.lm_cfg.max_len:
            raise ValueError(f"prompt {plen} + steps {num_steps} exceeds "
                             f"max_len {self.lm_cfg.max_len}")
        bucket = bucket_len(plen, self.lm_cfg.max_len)
        padded = pad_to_bucket(prompt, bucket)
        # one compiled program per (bucket, batch, steps, sampling config) —
        # sampling controls are static python scalars inside the trace
        key = (bucket, b, num_steps, float(temperature), int(top_k),
               float(top_p), rng is not None)
        fn = self._gen_cache.get(key)
        if fn is None:
            if rng is None:
                fn = jax.jit(lambda p, n: generate(
                    self.model, self.params, p, num_steps,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    prompt_len=n))
            else:
                fn = jax.jit(lambda p, n, r: generate(
                    self.model, self.params, p, num_steps, rng=r,
                    temperature=temperature, top_k=top_k, top_p=top_p,
                    prompt_len=n))
            self._gen_cache[key] = fn
        args = (jnp.asarray(padded), jnp.int32(plen))
        if rng is not None:
            args += (rng,)
        return np.asarray(fn(*args))

    def generate_speculative(self, draft: "LMPackagedModel", prompt,
                             num_steps: int, k: int = 4):
        from ddw_tpu.models.spec_decode import generate_speculative

        out, stats = generate_speculative(
            self.model, self.params, draft.model, draft.params,
            np.asarray(prompt, np.int32), num_steps, k=k)
        return np.asarray(out), stats


def load_lm_package(model_dir: str) -> LMPackagedModel:
    return LMPackagedModel(model_dir)
