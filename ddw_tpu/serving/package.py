"""Packaged-model format — the MLflow pyfunc role.

The reference bundles preprocessing + weights + label decoding into an MLflow pyfunc
(``Part 2 - Distributed Tuning & Inference/03_pyfunc_distributed_inference.py:
157-234``): ``load_context`` restores image-size params and the keras model from
artifacts (``:161-184``); ``predict`` decodes JPEG bytes, resizes, runs the model in
sub-batches of 128, argmaxes and maps to class names (``:186-212``, batch size
``:64,206``); ``preprocess`` coerces str->bytes for the UDF path (``:228-229``).

In-tree equivalent: a self-contained directory —

    package.json     model name/config, image size, sorted class list, versions
    params.msgpack   flax params (+ batch_stats) serialized with flax.serialization

:class:`PackagedModel` restores it anywhere (driver, batch-scorer worker) and
predicts from raw JPEG bytes / file paths / pre-decoded arrays. Preprocessing is
*shared with the training loader* (``ddw_tpu.data.loader.preprocess_image``) —
deliberately fixing the reference's train/serve skew (PIL at serve vs tf.image at
train, SURVEY.md §7 step 7).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import serialization

from ddw_tpu.data.loader import active_decoder, preprocess_image
from ddw_tpu.models.registry import build_model
from ddw_tpu.utils.config import ModelCfg

_FORMAT_VERSION = 1
# Version 2 == version 1 + int8-quantized params blob. Quantized packages
# write 2 so readers that predate quantization reject them cleanly at the
# version gate instead of half-loading marker dicts as params.
_FORMAT_VERSION_QUANT = 2
_SUPPORTED_VERSIONS = (1, 2)
_PREDICT_BATCH = 128  # reference :64


def write_package_dir(out_dir: str, meta: dict, tree, quantize: str | None,
                      quant_version: int) -> str:
    """Shared artifact-writing protocol (image + LM packages): quantization
    gate, package.json, params.msgpack. ``meta`` must already carry
    ``kind``/``format_version``; int8 rewrites ``format_version`` to
    ``quant_version`` so pre-quantization readers reject cleanly."""
    if quantize not in (None, "int8"):
        raise ValueError(f"unknown quantize mode {quantize!r}; use 'int8'")
    os.makedirs(out_dir, exist_ok=True)
    if quantize == "int8":
        from ddw_tpu.serving.quantize import MODE_INT8, quantize_tree

        meta = dict(meta, quantization=MODE_INT8,
                    format_version=quant_version)
        tree = quantize_tree(tree)
    with open(os.path.join(out_dir, "package.json"), "w") as f:
        json.dump(meta, f, indent=2)
    with open(os.path.join(out_dir, "params.msgpack"), "wb") as f:
        f.write(serialization.to_bytes(tree))
    return out_dir


def read_package_dir(model_dir: str, expected_kind: str,
                     supported_versions: tuple,
                     other_kind_hint: str) -> tuple[dict, dict, str]:
    """Shared artifact-reading protocol: kind/version gates, sha256 content
    digest over blob+meta, msgpack restore, transparent dequantize. ``kind``
    is absent from pre-round-3 image packages — treated as 'image'.
    Returns ``(meta, restored_tree, content_digest)``."""
    import hashlib

    with open(os.path.join(model_dir, "package.json")) as f:
        meta = json.load(f)
    kind = meta.get("kind", "image")
    if kind != expected_kind:
        raise ValueError(f"not an {expected_kind} package (kind={kind!r}); "
                         f"{other_kind_hint}")
    if meta["format_version"] not in supported_versions:
        raise ValueError(
            f"unsupported package format {meta['format_version']}")
    with open(os.path.join(model_dir, "params.msgpack"), "rb") as f:
        blob = f.read()
    h = hashlib.sha256(blob)
    h.update(json.dumps(meta, sort_keys=True).encode())
    restored = serialization.msgpack_restore(blob)
    quant = meta.get("quantization")
    if quant is not None:
        from ddw_tpu.serving.quantize import MODE_INT8, dequantize_tree

        if quant != MODE_INT8:
            raise ValueError(f"unsupported quantization mode {quant!r}")
        restored = dequantize_tree(restored)
    return meta, restored, h.hexdigest()[:16]


def save_packaged_model(
    out_dir: str,
    model_cfg: ModelCfg,
    classes: Sequence[str],
    params,
    batch_stats=None,
    img_height: int = 224,
    img_width: int = 224,
    extra_meta: dict | None = None,
    quantize: str | None = None,
) -> str:
    """Write the packaged-model directory (the ``mlflow.pyfunc.log_model`` role,
    reference ``:349-363``). ``classes`` must be index-ordered (label_to_idx
    order). ``quantize="int8"`` stores kernels as per-channel int8 (~4x
    smaller artifact; see :mod:`ddw_tpu.serving.quantize`) — loading
    dequantizes transparently."""
    reserved = {"kind", "format_version", "model_cfg", "classes",
                "quantization", "img_height", "img_width",
                "preprocess_impl"}
    clash = reserved & set(extra_meta or {})
    if clash:
        raise ValueError(f"extra_meta must not override reserved keys "
                         f"{sorted(clash)}")
    meta = {
        "kind": "image",
        "format_version": _FORMAT_VERSION,
        "model_cfg": dataclasses.asdict(model_cfg),
        "classes": list(classes),
        "img_height": img_height,
        "img_width": img_width,
        # decode impl the training side used; load warns if serving resolves
        # differently (native point-bilinear vs PIL filtered-bilinear skew)
        "preprocess_impl": active_decoder(),
        **(extra_meta or {}),
    }
    tree = {"params": jax.device_get(params),
            "batch_stats": jax.device_get(batch_stats or {})}
    return write_package_dir(out_dir, meta, tree, quantize,
                             _FORMAT_VERSION_QUANT)


def load_packaged_model(model_dir: str) -> "PackagedModel":
    return PackagedModel(model_dir)


@dataclasses.dataclass
class ImageEngineHandle:
    """What :class:`ddw_tpu.serve.ServingEngine` needs from an image
    package: model/params plus the input-coercion callable (shared with
    :meth:`PackagedModel.predict` — same preprocessing, no train/serve or
    offline/online skew)."""

    model: object
    params: object
    batch_stats: object
    classes: list
    height: int
    width: int
    decode_one: object          # item -> [H, W, 3] float array
    content_digest: str = ""


class PackagedModel:
    """Self-contained predictor (the ``FlowerPyFunc`` role).

    ``predict`` accepts: list/array of JPEG byte strings, list of file paths, or a
    pre-decoded float array [N, H, W, 3]; returns class-name strings (or indices
    with ``return_indices=True``).
    """

    def __init__(self, model_dir: str):
        # content_digest: identity of this packaged model (weights + meta) —
        # lets shared-nothing scorers agree on a run token without
        # communicating.
        self.meta, restored, self.content_digest = read_package_dir(
            model_dir, "image", _SUPPORTED_VERSIONS,
            "LM packages load via ddw_tpu.serving.load_lm_package")
        self.model_cfg = ModelCfg(**self.meta["model_cfg"])
        self.classes: list[str] = self.meta["classes"]
        self.height, self.width = self.meta["img_height"], self.meta["img_width"]
        trained_with = self.meta.get("preprocess_impl")
        if trained_with and trained_with != active_decoder():
            import warnings

            warnings.warn(
                f"packaged model was trained with the {trained_with!r} image "
                f"decoder but this environment resolves {active_decoder()!r}; "
                f"decoded pixels differ slightly (train/serve preprocessing "
                f"skew)", stacklevel=2)
        self.model = build_model(self.model_cfg)
        self.params = restored["params"]
        self.batch_stats = restored.get("batch_stats") or {}
        self._apply = jax.jit(self._apply_fn)

    def _apply_fn(self, images):
        variables = {"params": self.params}
        if self.batch_stats:
            variables["batch_stats"] = self.batch_stats
        return self.model.apply(variables, images, train=False)

    def engine_handle(self) -> ImageEngineHandle:
        return ImageEngineHandle(
            self.model, self.params, self.batch_stats, self.classes,
            self.height, self.width, self._decode_one, self.content_digest)

    # -- input coercion (the reference's bytes-vs-str handling, :214-234) -------
    def _decode_one(self, item) -> np.ndarray:
        if isinstance(item, np.ndarray) and item.ndim == 3:
            return item.astype(np.float32)
        if isinstance(item, str):
            if os.path.exists(item):
                with open(item, "rb") as f:
                    item = f.read()
            else:
                # stringified bytes from a text serialization boundary
                # (reference :228-229 uses ast.literal_eval)
                import ast

                item = ast.literal_eval(item)
        if isinstance(item, (bytes, bytearray)):
            return preprocess_image(bytes(item), self.height, self.width)
        raise TypeError(f"cannot decode input of type {type(item)}")

    def predict_logits(self, inputs) -> np.ndarray:
        if isinstance(inputs, np.ndarray) and inputs.ndim == 4:
            imgs = inputs.astype(np.float32)
        elif len(inputs) == 0:
            return np.zeros((0, len(self.classes)), np.float32)
        else:
            imgs = np.stack([self._decode_one(x) for x in inputs])
        outs = []
        # fixed sub-batch with padding: one compiled shape regardless of N
        for i in range(0, len(imgs), _PREDICT_BATCH):
            chunk = imgs[i : i + _PREDICT_BATCH]
            pad = _PREDICT_BATCH - len(chunk)
            if pad:
                chunk = np.concatenate([chunk, np.zeros((pad, *chunk.shape[1:]), np.float32)])
            logits = np.asarray(self._apply(jnp.asarray(chunk)))
            outs.append(logits[: _PREDICT_BATCH - pad])
        return np.concatenate(outs) if outs else np.zeros((0, len(self.classes)))

    def predict(self, inputs, return_indices: bool = False):
        """argmax -> class name (reference ``:208-212``)."""
        idx = np.argmax(self.predict_logits(inputs), axis=-1)
        if return_indices:
            return idx
        return [self.classes[i] for i in idx]
