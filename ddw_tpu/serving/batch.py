"""Distributed batch scorer — the ``mlflow.pyfunc.spark_udf`` role.

The reference scores a table by wrapping the pyfunc in a Spark UDF applied to the
``content`` column over table partitions; executors each load the model once and
stream arrow batches through it
(``Part 2 - Distributed Tuning & Inference/03_pyfunc_distributed_inference.py:
466-472``; stack in SURVEY.md §3.5).

TPU-native equivalent: shards of the input table are the unit of work. Across
*hosts*, shards split by ``process_index`` (each host loads the packaged model
once); within a host, records are decoded on the loader thread pool and scored in
fixed-size device batches sharded across the host's **local** devices — model
replicated, batch split (batch-inference parallelism, SURVEY.md §2d). Scoring is
embarrassingly parallel, so no cross-host collectives are compiled in: each host's
jitted apply spans only addressable devices (a global-mesh program would force
every host to run the same number of batches — a deadlock when shard counts
differ). Results are written as a predictions table (path, label=prediction) via
the store: one table single-process, per-process table names multi-host.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddw_tpu.data.loader import bounded_map, preprocess_image
from ddw_tpu.data.store import Record, Table, TableStore, read_shard
from ddw_tpu.runtime.mesh import DATA_AXIS, make_mesh, MeshSpec
from ddw_tpu.serving.package import PackagedModel


def _scoring_run_id(table: Table, content_digest: str) -> str:
    """Deterministic scoring-run token — identical on every process for the
    same (input table version, packaged model), without communication.
    Shared by the image and LM scorers' part writes AND merge waits."""
    return TableStore.run_token(table.manifest["name"],
                                table.manifest["version"],
                                content_digest)


def _process_shards(table: Table) -> list[str]:
    """This process's disjoint shard subset (round-robin by rank); small
    tables fall to rank 0 — shared by the image and LM scorers."""
    shards = table.shard_paths
    n_proc = jax.process_count()
    if len(shards) >= n_proc:
        return shards[jax.process_index()::n_proc]
    return shards if jax.process_index() == 0 else []


def _local_mesh(mesh: Mesh | None) -> Mesh:
    """A 1-D data mesh over THIS process's addressable devices (scoring is
    shared-nothing: a global-mesh program would deadlock on unequal shard
    counts — module docstring)."""
    if mesh is None:
        mesh = make_mesh(MeshSpec(((DATA_AXIS, -1),)))
    local = [d for d in np.asarray(mesh.devices).flat
             if d.process_index == jax.process_index()]
    return Mesh(np.asarray(local), (DATA_AXIS,))


def _write_scored_table(out_store: TableStore, out_name: str, records,
                        meta: dict, table: Table, content_digest: str,
                        merge: bool) -> None:
    """The multi-host scores-table protocol, shared by both scorer families:
    per-process ``{out_name}_pN`` parts stamped with the run token, rank-0
    merge wait."""
    n_proc = jax.process_count()
    run_id = _scoring_run_id(table, content_digest)
    name = out_name if n_proc == 1 else f"{out_name}_p{jax.process_index()}"
    out_store.write(name, records,
                    meta={**meta, "source_table": table.manifest["name"],
                          "run_id": run_id})
    if merge and n_proc > 1 and jax.process_index() == 0:
        merge_predictions(out_store, out_name, n_proc, run_id)


class BatchScorer:
    """Score a table of JPEG-bytes records with a packaged model over the local
    devices of each participating host."""

    def __init__(self, model: PackagedModel | str, mesh: Mesh | None = None,
                 batch_per_device: int = 128, workers: int = 4):
        self.model = model if isinstance(model, PackagedModel) else PackagedModel(model)
        self.mesh = _local_mesh(mesh)
        self.n_devices = self.mesh.devices.size
        self.batch = batch_per_device * self.n_devices
        self.workers = workers
        self._sharding = NamedSharding(self.mesh, P(DATA_AXIS))

        pm = self.model

        def apply_fn(images):
            variables = {"params": pm.params}
            if pm.batch_stats:
                variables["batch_stats"] = pm.batch_stats
            return pm.model.apply(variables, images, train=False)

        self._apply = jax.jit(apply_fn,
                              in_shardings=self._sharding,
                              out_shardings=NamedSharding(self.mesh, P()))

    def score_table(self, table: Table, out_store: TableStore | None = None,
                    out_name: str = "predictions",
                    merge: bool = True) -> list[tuple[str, str]]:
        """Returns [(path, predicted_class)] for this process's shard subset; when
        ``out_store`` is given also writes them as a table (path, label=prediction).

        Decode runs the same hot path the training loader uses: one native C++
        thread-pool call per device batch (``decode_batch_native``), per-image
        PIL fallback — not one ctypes call per image. Multi-host with ``merge``:
        each process writes ``{out_name}_pN`` stamped with a run token derived
        from (input table version, packaged-model content digest); process 0
        waits for every part carrying that token and merges them into one
        ``out_name`` table (the reference's single spark_udf result table,
        ``03_pyfunc_distributed_inference.py:466-472``). The token keeps a
        re-score with a newer model or table from silently merging a previous
        run's parts for slower processes.
        """
        from ddw_tpu.native.decode import decode_batch_native, native_available

        h, w = self.model.height, self.model.width
        results: list[tuple[str, str]] = []

        raw_u8 = table.meta.get("encoding") == "raw_u8"
        if raw_u8 and (table.meta.get("height"), table.meta.get("width")) != (h, w):
            raise ValueError(
                f"materialized table is {table.meta.get('height')}x"
                f"{table.meta.get('width')} but the packaged model expects "
                f"{h}x{w} — re-materialize at the model's size or score the "
                f"JPEG silver table")

        def records():
            for sp in _process_shards(table):
                yield from read_shard(sp)

        def score(imgs: np.ndarray, n: int, paths: list[str]):
            pad = self.batch - n
            if pad:
                imgs = np.concatenate(
                    [imgs[:n], np.zeros((pad, h, w, 3), np.float32)])
            dev = jax.device_put(imgs, self._sharding)  # local-mesh sharding
            logits = np.asarray(self._apply(dev))[:n]
            idx = np.argmax(logits, axis=-1)
            results.extend((p, self.model.classes[i]) for p, i in zip(paths, idx))

        if raw_u8:
            # Pre-decoded pixels (prep.materialize_decoded): no JPEG work,
            # just reinterpret + dequantize — the loader's fast path,
            # serving-side, through the same shared scheme definition.
            from ddw_tpu.data.loader import dequantize_raw_u8, raw_u8_view

            imgs = np.empty((self.batch, h, w, 3), np.float32)
            paths: list[str] = []
            i = 0
            for rec in records():
                imgs[i] = raw_u8_view(rec.content, h, w)
                paths.append(rec.path)
                i += 1
                if i == self.batch:
                    dequantize_raw_u8(imgs)
                    score(imgs, i, paths)
                    paths, i = [], 0
            if i:
                dequantize_raw_u8(imgs[:i])
                score(imgs, i, paths)
        elif native_available():
            # Double-buffered pipeline: one background thread decodes batch
            # N+1 (C++ pool, GIL released) while the device scores batch N —
            # per-batch wall time ~max(decode, score) instead of their sum,
            # the same overlap the training loader gets from prefetch_to.
            from concurrent.futures import ThreadPoolExecutor

            bufs = [np.empty((self.batch, h, w, 3), np.float32)
                    for _ in range(2)]

            def decode_into(contents: list[bytes], buf: np.ndarray) -> int:
                n = len(contents)
                _, ok = decode_batch_native(contents, h, w,
                                            threads=self.workers, out=buf[:n])
                for j in np.nonzero(~ok)[0]:
                    buf[j] = preprocess_image(contents[j], h, w)
                return n

            def batches():
                paths: list[str] = []
                contents: list[bytes] = []
                for rec in records():
                    paths.append(rec.path)
                    contents.append(rec.content)
                    if len(contents) == self.batch:
                        yield paths, contents
                        paths, contents = [], []
                if contents:
                    yield paths, contents

            with ThreadPoolExecutor(max_workers=1) as decoder:
                in_flight = None  # (future, buffer, paths) of the decoding batch
                for i, (paths, contents) in enumerate(batches()):
                    submitted = (decoder.submit(decode_into, contents,
                                                bufs[i % 2]),
                                 bufs[i % 2], paths)
                    if in_flight is not None:
                        fut, buf, prev_paths = in_flight
                        score(buf, fut.result(), prev_paths)
                    in_flight = submitted
                if in_flight is not None:
                    fut, buf, prev_paths = in_flight
                    score(buf, fut.result(), prev_paths)
        else:
            from concurrent.futures import ThreadPoolExecutor

            def decode(rec: Record):
                return rec.path, preprocess_image(rec.content, h, w)

            buf_paths: list[str] = []
            buf_imgs: list[np.ndarray] = []
            with ThreadPoolExecutor(max_workers=self.workers) as pool:
                for path, img in bounded_map(pool, decode, records(),
                                             self.workers * 4):
                    buf_paths.append(path)
                    buf_imgs.append(img)
                    if len(buf_imgs) == self.batch:
                        score(np.stack(buf_imgs), len(buf_imgs), buf_paths)
                        buf_paths, buf_imgs = [], []
                if buf_imgs:
                    score(np.stack(buf_imgs), len(buf_imgs), buf_paths)

        if out_store is not None:
            _write_scored_table(
                out_store, out_name,
                (Record(path=p, content=b"", label=pred)
                 for p, pred in results),
                {"model_classes": self.model.classes}, table,
                self.model.content_digest, merge)
        return results


class LMBatchScorer:
    """Score a ``tokens_i32`` table with a packaged LM over the local devices
    — per-sequence mean next-token NLL (the ``spark_udf`` scoring role for
    the language family; the tokens analog of :class:`BatchScorer`, same
    shared-nothing host split and run-token part merge)."""

    def __init__(self, model, mesh: Mesh | None = None,
                 batch_per_device: int = 64):
        from ddw_tpu.serving.lm_package import load_lm_package

        self.model = (load_lm_package(model) if isinstance(model, str)
                      else model)
        self.mesh = _local_mesh(mesh)
        self.batch = batch_per_device * self.mesh.devices.size
        self._sharding = NamedSharding(self.mesh, P(DATA_AXIS))
        from ddw_tpu.serving.lm_package import sequence_nll

        pm = self.model
        self._nll = jax.jit(
            lambda tokens: sequence_nll(pm.model, pm.params, tokens),
            in_shardings=self._sharding,
            out_shardings=NamedSharding(self.mesh, P()))

    def score_table(self, table: Table, out_store: TableStore | None = None,
                    out_name: str = "lm_scores",
                    merge: bool = True) -> list[tuple[str, float]]:
        """Returns [(path, nll)] for this process's shard subset; with
        ``out_store`` also writes a scores table (label = formatted NLL,
        content = f32 bytes) and process 0 merges the per-process parts
        under the same run-token discipline as the image scorer."""
        if table.meta.get("encoding") != "tokens_i32":
            raise ValueError(f"LMBatchScorer needs a tokens_i32 table, got "
                             f"encoding {table.meta.get('encoding')!r} — "
                             f"materialize with prep.write_token_table")
        t = table.meta["seq_plus_one"]
        if t - 1 > self.model.lm_cfg.max_len:
            raise ValueError(f"table sequences ({t - 1}) exceed the packaged "
                             f"model's max_len {self.model.lm_cfg.max_len}")
        results: list[tuple[str, float]] = []
        buf = np.zeros((self.batch, t), np.int32)
        paths: list[str] = []

        from ddw_tpu.serving.lm_package import check_token_ids

        def flush():
            if not paths:
                return
            n = len(paths)
            buf[n:] = 0  # padded rows: valid ids, sliced off below
            check_token_ids(buf[:n], self.model.lm_cfg.vocab_size)
            dev = jax.device_put(buf, self._sharding)
            nll = np.asarray(self._nll(dev))[:n]
            results.extend((p, float(v)) for p, v in zip(paths, nll))
            paths.clear()

        for sp in _process_shards(table):
            for rec in read_shard(sp):
                buf[len(paths)] = np.frombuffer(rec.content, np.int32,
                                                count=t)
                paths.append(rec.path)
                if len(paths) == self.batch:
                    flush()
        flush()

        if out_store is not None:
            _write_scored_table(
                out_store, out_name,
                (Record(path=p, content=np.float32(v).tobytes(),
                        label=f"{v:.6f}") for p, v in results),
                {"metric": "mean_next_token_nll"}, table,
                self.model.content_digest, merge)
        return results


def merge_predictions(out_store: TableStore, out_name: str, n_parts: int,
                      run_id: str, timeout_s: float = 300.0) -> Table:
    """Merge per-process ``{out_name}_pN`` tables into one ``out_name`` table.

    The spark_udf contract yields ONE result table (reference
    ``03_pyfunc_distributed_inference.py:466-472``); per-part tables are an
    implementation detail of shared-nothing scoring. Waits for every part
    stamped with this run's token (:meth:`TableStore.await_parts` — a bare
    existence check would match a previous run's parts), then commits the
    merged table by zero-copy manifest concat.
    """
    part_names = [f"{out_name}_p{i}" for i in range(n_parts)]
    parts = out_store.await_parts(part_names, run_id, timeout_s)
    return out_store.merge_shards(
        out_name, parts,
        meta={**parts[0].meta, "merged_from": part_names, "run_id": run_id})
