"""Trial pruning — early-stop hopeless HPO trials on intermediate metrics.

Beyond the Hyperopt parity contract (hyperopt has no pruning; every trial runs
its full budget — the reference's 20-eval search at
``01_hyperopt_single_machine_model.py:226-238`` pays full training cost for
every config, good or bad). The median rule here is the standard one
(popularized by Google Vizier and Optuna's ``MedianPruner``): at each
reporting step, a trial whose intermediate objective is worse than the median
of what other trials reported at the same step is stopped.

Protocol: pruning-aware objectives accept ``(params, trial)`` and call
``trial.report(step, value)`` once per epoch (typically via
``Trainer(..., on_epoch=...)``); ``report`` raises :class:`Pruned` when the
rule fires, ``fmin`` records the trial with ``STATUS_PRUNED`` and moves on.
Pruned trials never enter the TPE good/bad split (``Trials.completed`` filters
on ``STATUS_OK``) — a half-trained loss is not comparable to a final one.

Thread-safe: parallel ``fmin`` reports from worker threads concurrently.
"""

from __future__ import annotations

import math
import threading

STATUS_PRUNED = "pruned"


class Pruned(Exception):
    """A pruner decided this trial is not worth finishing."""

    def __init__(self, step: int, value: float):
        super().__init__(f"pruned at step {step} (value {value:g})")
        self.step = step
        self.value = value


class Trial:
    """Per-trial reporting handle handed to pruning-aware objectives."""

    def __init__(self, pruner, trial_id: int, params: dict):
        self._pruner = pruner
        self.trial_id = trial_id
        self.params = params

    def report(self, step: int, value: float) -> None:
        """Record an intermediate objective value (lower is better, same
        orientation as the trial loss). Raises :class:`Pruned` when the rule
        says stop."""
        if self._pruner.should_prune(self.trial_id, step, float(value)):
            raise Pruned(step, float(value))


class _BasePruner:
    """Shared trial-id bookkeeping for the pruning rules."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next_id = 0

    def make_trial(self, params: dict) -> Trial:
        with self._lock:
            tid = self._next_id
            self._next_id += 1
            self._register(tid)
        return Trial(self, tid, params)

    def _register(self, trial_id: int) -> None:  # hook for per-trial state
        pass


class MedianPruner(_BasePruner):
    """Median rule with warmup: at reporting step ``s``, prune when the
    trial's value is strictly worse than the median of all OTHER trials'
    values at the same step.

    ``warmup_steps``: never prune at steps below this (early epochs are noisy).
    ``min_trials``: need at least this many other trials reporting at the step
    before the median is trusted.
    """

    def __init__(self, warmup_steps: int = 1, min_trials: int = 3):
        super().__init__()
        self.warmup_steps = warmup_steps
        self.min_trials = min_trials
        self._history: dict[int, dict[int, float]] = {}

    def _register(self, trial_id: int) -> None:
        self._history[trial_id] = {}

    def should_prune(self, trial_id: int, step: int, value: float) -> bool:
        if not math.isfinite(value):
            # A NaN/inf objective never recovers — prune unconditionally
            # (warmup/min-trial guards exist for noisy-but-finite curves).
            # NaN must also never enter the history: `nan > median` is False
            # and a NaN at the median index would disable pruning for peers.
            return True
        with self._lock:
            self._history[trial_id][step] = value
            if step < self.warmup_steps:
                return False
            others = [h[step] for tid, h in self._history.items()
                      if tid != trial_id and step in h]
            if len(others) < self.min_trials:
                return False
            others.sort()
            n = len(others)
            median = (others[n // 2] if n % 2
                      else 0.5 * (others[n // 2 - 1] + others[n // 2]))
            return value > median


class ASHAPruner(_BasePruner):
    """Asynchronous Successive Halving (Li et al. 1810.05934) — the modern
    default for parallel HPO pruning, beside the median rule.

    ``step`` is 0-indexed like the Trainer's epoch number (the examples
    report ``row["epoch"]``), so ``step + 1`` is the resource consumed. A
    rung sits where the consumed resource reaches
    ``min_resource * reduction_factor**k`` — with the defaults the FIRST
    reported epoch is rung 0, so bad configs stop after one epoch. A trial
    at a rung continues only if its value is within the top
    ``1/reduction_factor`` fraction of everything recorded AT that rung so
    far (asynchronous: decisions use whatever has been recorded, no waiting
    for a full bracket — exactly what a constant-liar parallel ``fmin``
    needs). Lower is better, same orientation as the trial loss.

    Same ``make_trial`` / ``should_prune`` protocol as :class:`MedianPruner`,
    so ``fmin``/``Trainer(on_epoch=...)`` plumbing is shared.
    """

    def __init__(self, min_resource: int = 1, reduction_factor: int = 3):
        if min_resource < 1 or reduction_factor < 2:
            raise ValueError(f"need min_resource >= 1 and reduction_factor "
                             f">= 2, got {min_resource}, {reduction_factor}")
        super().__init__()
        self.min_resource = min_resource
        self.reduction_factor = reduction_factor
        # rung -> {trial_id: value}: keyed so a re-reported step (resume,
        # double-firing hook) overwrites instead of double-counting a trial
        # in the rung population
        self._rungs: dict[int, dict[int, float]] = {}

    def _rung_of(self, step: int) -> int | None:
        """Rung index when ``step + 1`` units of resource are consumed, or
        None between rungs."""
        consumed = step + 1
        r = self.min_resource
        k = 0
        while r <= consumed:
            if r == consumed:
                return k
            r *= self.reduction_factor
            k += 1
        return None

    def should_prune(self, trial_id: int, step: int, value: float) -> bool:
        if not math.isfinite(value):
            return True  # same rationale as MedianPruner: never recovers
        rung = self._rung_of(step)
        if rung is None:
            return False
        with self._lock:
            recorded = self._rungs.setdefault(rung, {})
            recorded[trial_id] = value
            if len(recorded) < self.reduction_factor:
                return False  # too few at this rung to cut anything
            srt = sorted(recorded.values())
            # continue only in the top 1/eta fraction (at least one survives)
            keep = max(1, len(srt) // self.reduction_factor)
            return value > srt[keep - 1]


def make_pruner(tune_cfg):
    """The one ``TuneCfg -> pruner`` dispatch every consumer shares (examples
    04/05 and any future script): ``tune.prune=false`` -> None;
    ``tune.pruner`` selects the rule; unknown names refuse loudly."""
    if not tune_cfg.prune:
        return None
    if tune_cfg.pruner == "median":
        return MedianPruner(tune_cfg.prune_warmup_epochs,
                            tune_cfg.prune_min_trials)
    if tune_cfg.pruner == "asha":
        return ASHAPruner(tune_cfg.asha_min_resource,
                          tune_cfg.asha_reduction_factor)
    raise ValueError(f"unknown tune.pruner {tune_cfg.pruner!r}; "
                     f"use 'median' or 'asha'")
