"""Trial pruning — early-stop hopeless HPO trials on intermediate metrics.

Beyond the Hyperopt parity contract (hyperopt has no pruning; every trial runs
its full budget — the reference's 20-eval search at
``01_hyperopt_single_machine_model.py:226-238`` pays full training cost for
every config, good or bad). The median rule here is the standard one
(popularized by Google Vizier and Optuna's ``MedianPruner``): at each
reporting step, a trial whose intermediate objective is worse than the median
of what other trials reported at the same step is stopped.

Protocol: pruning-aware objectives accept ``(params, trial)`` and call
``trial.report(step, value)`` once per epoch (typically via
``Trainer(..., on_epoch=...)``); ``report`` raises :class:`Pruned` when the
rule fires, ``fmin`` records the trial with ``STATUS_PRUNED`` and moves on.
Pruned trials never enter the TPE good/bad split (``Trials.completed`` filters
on ``STATUS_OK``) — a half-trained loss is not comparable to a final one.

Thread-safe: parallel ``fmin`` reports from worker threads concurrently.
"""

from __future__ import annotations

import math
import threading

STATUS_PRUNED = "pruned"


class Pruned(Exception):
    """A pruner decided this trial is not worth finishing."""

    def __init__(self, step: int, value: float):
        super().__init__(f"pruned at step {step} (value {value:g})")
        self.step = step
        self.value = value


class Trial:
    """Per-trial reporting handle handed to pruning-aware objectives."""

    def __init__(self, pruner: "MedianPruner", trial_id: int, params: dict):
        self._pruner = pruner
        self.trial_id = trial_id
        self.params = params

    def report(self, step: int, value: float) -> None:
        """Record an intermediate objective value (lower is better, same
        orientation as the trial loss). Raises :class:`Pruned` when the rule
        says stop."""
        if self._pruner.should_prune(self.trial_id, step, float(value)):
            raise Pruned(step, float(value))


class MedianPruner:
    """Median rule with warmup: at reporting step ``s``, prune when the
    trial's value is strictly worse than the median of all OTHER trials'
    values at the same step.

    ``warmup_steps``: never prune at steps below this (early epochs are noisy).
    ``min_trials``: need at least this many other trials reporting at the step
    before the median is trusted.
    """

    def __init__(self, warmup_steps: int = 1, min_trials: int = 3):
        self.warmup_steps = warmup_steps
        self.min_trials = min_trials
        self._lock = threading.Lock()
        self._history: dict[int, dict[int, float]] = {}
        self._next_id = 0

    def make_trial(self, params: dict) -> Trial:
        with self._lock:
            tid = self._next_id
            self._next_id += 1
            self._history[tid] = {}
        return Trial(self, tid, params)

    def should_prune(self, trial_id: int, step: int, value: float) -> bool:
        if not math.isfinite(value):
            # A NaN/inf objective never recovers — prune unconditionally
            # (warmup/min-trial guards exist for noisy-but-finite curves).
            # NaN must also never enter the history: `nan > median` is False
            # and a NaN at the median index would disable pruning for peers.
            return True
        with self._lock:
            self._history[trial_id][step] = value
            if step < self.warmup_steps:
                return False
            others = [h[step] for tid, h in self._history.items()
                      if tid != trial_id and step in h]
            if len(others) < self.min_trials:
                return False
            others.sort()
            n = len(others)
            median = (others[n // 2] if n % 2
                      else 0.5 * (others[n // 2 - 1] + others[n // 2]))
            return value > median
