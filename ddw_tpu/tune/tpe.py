"""TPE hyperparameter search — the Hyperopt ``fmin(..., tpe.suggest)`` role.

Reimplements Tree-structured Parzen Estimator search (Bergstra et al. 2011,
"Algorithms for Hyper-Parameter Optimization") against the reference's usage
contract (``Part 2 - Distributed Tuning & Inference/01_hyperopt_single_machine_
model.py:223-238``): ``fmin(objective, space, algo=tpe, max_evals=N, trials)``
where the objective returns ``{'loss': float, 'status': STATUS_OK}`` and the
reference negates accuracy into a loss (``:178-181``).

Algorithm (per dimension, factored like hyperopt):
1. First ``n_startup_trials`` draws are random (rng seeded — deterministic).
2. Afterwards, completed trials are split by the ``gamma`` quantile of loss into
   *good* (lowest) and *bad* sets.
3. Continuous dims: 1-D Parzen (Gaussian-mixture) estimators l(x) over good and
   g(x) over bad observations in the internal space (log-space for loguniform),
   bandwidths from neighbor spacing, plus a uniform prior component; draw
   ``n_ei_candidates`` from l and keep the candidate maximizing l(x)/g(x).
4. choice dims: categorical estimators with add-one smoothing; same EI ratio.

Two execution modes mirror the reference (SURVEY.md §2d):
- ``parallelism > 1`` — the SparkTrials role: up to N objectives in flight on a
  thread pool; suggestions use the trials completed so far (async TPE).
- ``parallelism = 1`` — sequential driver loop; required when each trial owns the
  whole device mesh (the documented SparkTrials/Horovod incompatibility,
  ``02_hyperopt_distributed_model.py:341-344``).
"""

from __future__ import annotations

import threading
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Any, Callable

import numpy as np

from ddw_tpu.tune.space import ChoiceOf, Dim, sample_space, validate_space

STATUS_OK = "ok"
STATUS_FAIL = "fail"


class Trials:
    """Trial bookkeeping (hyperopt ``Trials`` role). Thread-safe appends."""

    def __init__(self):
        self._lock = threading.Lock()
        self.results: list[dict[str, Any]] = []

    def record(self, params: dict, loss: float | None, status: str, extra: dict | None = None):
        with self._lock:
            self.results.append({"params": params, "loss": loss, "status": status,
                                 **(extra or {})})

    def completed(self) -> list[dict]:
        with self._lock:
            return [t for t in self.results if t["status"] == STATUS_OK and t["loss"] is not None]

    @property
    def best(self) -> dict | None:
        done = self.completed()
        return min(done, key=lambda t: t["loss"]) if done else None

    def __len__(self):
        return len(self.results)


# ---------------------------------------------------------------------------
# Parzen estimators
# ---------------------------------------------------------------------------

def _parzen_logpdf(x: np.ndarray, obs: np.ndarray, lo: float, hi: float) -> np.ndarray:
    """Log-density of a 1-D Gaussian-mixture Parzen estimator with a uniform prior
    component over [lo, hi] (hyperopt's adaptive-Parzen flavor, simplified)."""
    span = hi - lo
    if len(obs) == 0:
        return np.full_like(x, -np.log(span))
    srt = np.sort(obs)
    # bandwidth per observation: max neighbor gap, floored
    if len(srt) > 1:
        gaps = np.diff(srt)
        left = np.concatenate([[gaps[0]], gaps])
        right = np.concatenate([gaps, [gaps[-1]]])
        sigma = np.maximum(left, right)
    else:
        sigma = np.array([span / 2.0])
    sigma = np.clip(sigma, span / 100.0, span)
    # mixture: each obs + one uniform prior pseudo-component
    k = len(srt)
    x_ = x[:, None]
    comp = -0.5 * ((x_ - srt[None, :]) / sigma[None, :]) ** 2 - np.log(sigma[None, :] * np.sqrt(2 * np.pi))
    prior = np.full((len(x), 1), -np.log(span))
    all_comp = np.concatenate([comp, prior], axis=1)
    return np.logaddexp.reduce(all_comp, axis=1) - np.log(k + 1)


def _parzen_sample(rng: np.random.RandomState, obs: np.ndarray, lo: float, hi: float,
                   n: int) -> np.ndarray:
    """Draw from the good-set mixture (uniform prior component included)."""
    out = np.empty(n)
    srt = np.sort(obs)
    span = hi - lo
    if len(srt) > 1:
        gaps = np.diff(srt)
        left = np.concatenate([[gaps[0]], gaps])
        right = np.concatenate([gaps, [gaps[-1]]])
        sigma = np.clip(np.maximum(left, right), span / 100.0, span)
    elif len(srt) == 1:
        sigma = np.array([span / 2.0])
    for i in range(n):
        j = rng.randint(len(srt) + 1)
        if j == len(srt) or len(srt) == 0:  # prior component
            out[i] = rng.uniform(lo, hi)
        else:
            out[i] = np.clip(rng.normal(srt[j], sigma[j]), lo, hi)
    return out


def _suggest_dim(rng: np.random.RandomState, dim: Dim, good: list, bad: list,
                 n_candidates: int) -> Any:
    if dim.kind == "choice":
        k = len(dim.options)
        gc = np.bincount([dim.options.index(v) for v in good], minlength=k) + 1.0
        bc = np.bincount([dim.options.index(v) for v in bad], minlength=k) + 1.0
        score = np.log(gc / gc.sum()) - np.log(bc / bc.sum())
        # sample candidates from the good distribution, keep the best EI score
        probs = gc / gc.sum()
        cands = rng.choice(k, size=n_candidates, p=probs)
        best = cands[np.argmax(score[cands])]
        return dim.options[int(best)]
    lo, hi = dim.bounds_internal()
    g_obs = np.array([dim.to_internal(v) for v in good])
    b_obs = np.array([dim.to_internal(v) for v in bad])
    cands = _parzen_sample(rng, g_obs, lo, hi, n_candidates)
    ei = _parzen_logpdf(cands, g_obs, lo, hi) - _parzen_logpdf(cands, b_obs, lo, hi)
    return dim.from_internal(float(cands[np.argmax(ei)]))


def suggest(space: dict[str, Dim], trials: Trials, rng: np.random.RandomState,
            n_startup_trials: int = 5, gamma: float = 0.25,
            n_ei_candidates: int = 24,
            pending: list[dict] | None = None) -> dict[str, Any]:
    """One TPE suggestion given completed history.

    ``pending`` are the param dicts of trials currently in flight (async mode).
    They join the *bad* Parzen set — the "constant liar" strategy — so the EI
    ratio is depressed around points already being evaluated and concurrent
    workers don't pile onto the same proposal (round-1 advisor note on
    duplicate concurrent proposals).
    """
    validate_space(space)
    done = trials.completed()
    pending = pending or []
    if len(done) < n_startup_trials:
        draw = sample_space(space, rng)
        # Startup draws are uniform; only all-categorical spaces can collide
        # with an in-flight draw with non-zero probability — reroll a few times.
        for _ in range(8):
            if draw not in pending:
                break
            draw = sample_space(space, rng)
        return draw
    losses = np.array([t["loss"] for t in done])
    # Elitist split: ceil(gamma * sqrt(n)) capped at 25 — hyperopt's split, which
    # keeps the good set small; a linear gamma*n fraction lets mediocre trials
    # crowd out the few excellent ones and stalls convergence.
    n_good = max(1, min(int(np.ceil(gamma * np.sqrt(len(done)))), 25))
    order = np.argsort(losses)
    good_idx, bad_idx = set(order[:n_good].tolist()), set(order[n_good:].tolist())
    def histories(name: str) -> tuple[list, list]:
        """(good, bad) observed values for one dim; trials without the dim
        (other branches of a ChoiceOf) simply don't contribute — which is how
        conditional dims condition on their branch."""
        good = [done[i]["params"][name] for i in good_idx if name in done[i]["params"]]
        bad = [done[i]["params"][name] for i in bad_idx if name in done[i]["params"]]
        bad += [p[name] for p in pending if name in p]
        return good, bad

    out = {}
    for name, dim in space.items():
        if isinstance(dim, ChoiceOf):
            # two-stage TPE on the tree: pick the branch by EI over branch
            # values, then suggest the selected branch's sub-dims from the
            # sub-histories (only trials that took this branch have them)
            val = _suggest_dim(rng, dim.branch_dim(), *histories(name),
                               n_ei_candidates)
            out[name] = val
            for sub_name, sub_dim in dim.subspace(val).items():
                out[sub_name] = _suggest_dim(rng, sub_dim, *histories(sub_name),
                                             n_ei_candidates)
        else:
            out[name] = _suggest_dim(rng, dim, *histories(name), n_ei_candidates)
    return out


# ---------------------------------------------------------------------------
# fmin
# ---------------------------------------------------------------------------

def fmin(
    objective: Callable[..., dict | float],
    space: dict[str, Dim],
    max_evals: int = 20,
    algo: str = "tpe",
    parallelism: int = 1,
    trials: Trials | None = None,
    seed: int = 0,
    n_startup_trials: int = 5,
    gamma: float = 0.25,
    pruner=None,
) -> dict[str, Any]:
    """Minimize ``objective`` over ``space``; returns the best param dict.

    ``objective`` returns ``{'loss': float, 'status': STATUS_OK, ...}`` (hyperopt
    contract; a bare float is accepted too). A raised exception records a failed
    trial (STATUS_FAIL) and the search continues.

    ``pruner`` (e.g. :class:`ddw_tpu.tune.pruner.MedianPruner`) enables
    early-stopping of hopeless trials — beyond the hyperopt contract. With a
    pruner set, the objective is called as ``objective(params, trial)`` and
    should call ``trial.report(step, value)`` per epoch; a fired rule raises
    ``Pruned``, the trial records as ``STATUS_PRUNED``, and the search
    continues (pruned trials never enter the TPE good/bad split).
    """
    validate_space(space)
    trials = trials if trials is not None else Trials()
    rng = np.random.RandomState(seed)

    def propose(pending: list[dict] | None = None) -> dict:
        if algo == "random":
            return sample_space(space, rng)
        return suggest(space, trials, rng, n_startup_trials, gamma,
                       pending=pending)

    def run_one(params: dict) -> None:
        from ddw_tpu.tune.pruner import Pruned, STATUS_PRUNED

        try:
            if pruner is not None:
                res = objective(params, pruner.make_trial(params))
            else:
                res = objective(params)
            if isinstance(res, (int, float)):
                res = {"loss": float(res), "status": STATUS_OK}
            if res.get("status", STATUS_OK) == STATUS_OK:
                trials.record(params, float(res["loss"]), STATUS_OK,
                              {k: v for k, v in res.items() if k not in ("loss", "status")})
            else:
                trials.record(params, None, res.get("status", STATUS_FAIL))
        except Pruned as p:
            trials.record(params, None, STATUS_PRUNED,
                          {"pruned_at": p.step, "last_value": p.value})
        except Exception as e:  # failed trial, keep searching
            trials.record(params, None, STATUS_FAIL, {"error": repr(e)})

    if parallelism <= 1:
        for _ in range(max_evals):
            run_one(propose())
    else:
        # SparkTrials role: up to `parallelism` objectives in flight; each new
        # proposal sees the trials completed so far (async TPE).
        submitted = 0
        with ThreadPoolExecutor(max_workers=parallelism) as pool:
            inflight: dict = {}  # future -> its proposed params (the pending set)
            while submitted < max_evals or inflight:
                while submitted < max_evals and len(inflight) < parallelism:
                    params = propose(pending=list(inflight.values()))
                    inflight[pool.submit(run_one, params)] = params
                    submitted += 1
                done, _ = wait(set(inflight), return_when=FIRST_COMPLETED)
                for f in done:
                    del inflight[f]
    best = trials.best
    if best is None:
        raise RuntimeError(f"all {max_evals} trials failed; last errors: "
                           f"{[t.get('error') for t in trials.results[-3:]]}")
    return dict(best["params"])
