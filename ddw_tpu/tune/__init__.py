from ddw_tpu.tune.space import uniform, loguniform, quniform, choice, choice_of, ChoiceOf, sample_space  # noqa: F401
from ddw_tpu.tune.tpe import fmin, Trials, STATUS_OK, STATUS_FAIL  # noqa: F401
from ddw_tpu.tune.pruner import (ASHAPruner, MedianPruner, Pruned,  # noqa: F401
                                 STATUS_PRUNED, Trial, make_pruner)
