"""Search-space primitives — the hyperopt ``hp.*`` role.

The reference's spaces (SURVEY.md §6):
``{'optimizer': hp.choice(['Adadelta','Adam']), 'learning_rate':
hp.loguniform(-5, 0), 'dropout': hp.uniform(0.1, 0.9)}``
(``Part 2 - Distributed Tuning & Inference/01_hyperopt_single_machine_model.py:
194-198``) and ``batch_size: hp.choice([32, 64, 128])``
(``02_hyperopt_distributed_model.py:322-326``).

Each primitive describes one dimension; internally every dimension maps to a
continuous *unit space* where the TPE Parzen estimators operate:
uniform -> affine, loguniform -> log-space, quniform -> rounded affine,
choice -> categorical (handled discretely).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class Dim:
    """One search dimension. ``kind`` in {uniform, loguniform, quniform, choice}."""

    label: str
    kind: str
    low: float = 0.0
    high: float = 1.0
    q: float = 1.0
    options: tuple = ()

    # -- transformed (internal) space: continuous dims become unbounded-ish reals --
    def to_internal(self, value: Any) -> float:
        if self.kind == "choice":
            return float(self.options.index(value))
        if self.kind == "loguniform":
            return math.log(value)
        return float(value)

    def from_internal(self, x: float) -> Any:
        if self.kind == "choice":
            return self.options[int(np.clip(round(x), 0, len(self.options) - 1))]
        if self.kind == "loguniform":
            x = math.exp(x)
        if self.kind == "quniform":
            x = round(x / self.q) * self.q
        return float(np.clip(x, *self.bounds_natural()))

    def bounds_natural(self) -> tuple[float, float]:
        if self.kind == "loguniform":
            return (math.exp(self.low), math.exp(self.high))
        if self.kind == "choice":
            return (0, len(self.options) - 1)
        return (self.low, self.high)

    def bounds_internal(self) -> tuple[float, float]:
        """Bounds in the internal space (log-space for loguniform)."""
        if self.kind == "choice":
            return (0.0, float(len(self.options) - 1))
        return (self.low, self.high)

    def sample(self, rng: np.random.RandomState) -> Any:
        if self.kind == "choice":
            return self.options[rng.randint(len(self.options))]
        x = rng.uniform(self.low, self.high)
        if self.kind == "loguniform":
            return math.exp(x)
        if self.kind == "quniform":
            return round(x / self.q) * self.q
        return x


def uniform(label: str, low: float, high: float) -> Dim:
    return Dim(label, "uniform", low=low, high=high)


def loguniform(label: str, low: float, high: float) -> Dim:
    """Bounds are in log space, hyperopt-style: value in [e^low, e^high]."""
    return Dim(label, "loguniform", low=low, high=high)


def quniform(label: str, low: float, high: float, q: float) -> Dim:
    return Dim(label, "quniform", low=low, high=high, q=q)


def choice(label: str, options: Sequence[Any]) -> Dim:
    return Dim(label, "choice", options=tuple(options))


def sample_space(space: dict[str, Dim], rng: np.random.RandomState) -> dict[str, Any]:
    """One random draw from every dimension (startup / random-search mode)."""
    return {name: dim.sample(rng) for name, dim in space.items()}
