"""Search-space primitives — the hyperopt ``hp.*`` role.

The reference's spaces (SURVEY.md §6):
``{'optimizer': hp.choice(['Adadelta','Adam']), 'learning_rate':
hp.loguniform(-5, 0), 'dropout': hp.uniform(0.1, 0.9)}``
(``Part 2 - Distributed Tuning & Inference/01_hyperopt_single_machine_model.py:
194-198``) and ``batch_size: hp.choice([32, 64, 128])``
(``02_hyperopt_distributed_model.py:322-326``).

Each primitive describes one dimension; internally every dimension maps to a
continuous *unit space* where the TPE Parzen estimators operate:
uniform -> affine, loguniform -> log-space, quniform -> rounded affine,
choice -> categorical (handled discretely).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class Dim:
    """One search dimension. ``kind`` in {uniform, loguniform, quniform, choice}."""

    label: str
    kind: str
    low: float = 0.0
    high: float = 1.0
    q: float = 1.0
    options: tuple = ()

    # -- transformed (internal) space: continuous dims become unbounded-ish reals --
    def to_internal(self, value: Any) -> float:
        if self.kind == "choice":
            return float(self.options.index(value))
        if self.kind == "loguniform":
            return math.log(value)
        return float(value)

    def from_internal(self, x: float) -> Any:
        if self.kind == "choice":
            return self.options[int(np.clip(round(x), 0, len(self.options) - 1))]
        if self.kind == "loguniform":
            x = math.exp(x)
        if self.kind == "quniform":
            x = round(x / self.q) * self.q
        return float(np.clip(x, *self.bounds_natural()))

    def bounds_natural(self) -> tuple[float, float]:
        if self.kind == "loguniform":
            return (math.exp(self.low), math.exp(self.high))
        if self.kind == "choice":
            return (0, len(self.options) - 1)
        return (self.low, self.high)

    def bounds_internal(self) -> tuple[float, float]:
        """Bounds in the internal space (log-space for loguniform)."""
        if self.kind == "choice":
            return (0.0, float(len(self.options) - 1))
        return (self.low, self.high)

    def sample(self, rng: np.random.RandomState) -> Any:
        if self.kind == "choice":
            return self.options[rng.randint(len(self.options))]
        x = rng.uniform(self.low, self.high)
        if self.kind == "loguniform":
            return math.exp(x)
        if self.kind == "quniform":
            return round(x / self.q) * self.q
        return x


def uniform(label: str, low: float, high: float) -> Dim:
    return Dim(label, "uniform", low=low, high=high)


def loguniform(label: str, low: float, high: float) -> Dim:
    """Bounds are in log space, hyperopt-style: value in [e^low, e^high]."""
    return Dim(label, "loguniform", low=low, high=high)


def quniform(label: str, low: float, high: float, q: float) -> Dim:
    return Dim(label, "quniform", low=low, high=high, q=q)


def choice(label: str, options: Sequence[Any]) -> Dim:
    return Dim(label, "choice", options=tuple(options))


@dataclasses.dataclass(frozen=True)
class ChoiceOf:
    """Conditional (tree-structured) dimension — hyperopt's ``hp.choice`` over
    *sub-spaces* rather than scalar options (the idiom behind the reference's
    optimizer choice, ``Part 2 - Distributed Tuning & Inference/
    01_hyperopt_single_machine_model.py:194-198``, generalized: each optimizer
    can carry its own LR range). Drawing the branch value activates that
    branch's own dims; dims of unselected branches are *absent* from the
    trial's params — which is exactly how the TPE estimators condition on the
    branch (a sub-dim's history only contains trials that took its branch).

    Sub-dim names must be unique across branches (enforced by
    :func:`choice_of`): presence of the name in a trial's params then implies
    which branch that trial took, so no extra bookkeeping is needed.
    """

    label: str
    branches: tuple  # ((value, ((name, Dim), ...)), ...)

    def branch_dim(self) -> Dim:
        """The categorical over branch values."""
        return Dim(self.label, "choice",
                   options=tuple(v for v, _ in self.branches))

    def subspace(self, value) -> dict[str, Dim]:
        for v, sub in self.branches:
            if v == value:
                return dict(sub)
        raise KeyError(f"{self.label}: unknown branch {value!r}")

    def sample(self, rng: np.random.RandomState) -> dict[str, Any]:
        v = self.branch_dim().sample(rng)
        out = {self.label: v}
        for name, dim in self.subspace(v).items():
            out[name] = dim.sample(rng)
        return out


def choice_of(label: str, branches: dict[Any, dict[str, Dim] | None]) -> ChoiceOf:
    """``hp.choice`` over sub-spaces: ``choice_of('optimizer', {'adam':
    {'adam_lr': loguniform(...)}, 'sgd': {'sgd_lr': ..., 'momentum': ...}})``.
    A branch with no extra dims may map to ``None``/``{}``."""
    if not branches:
        raise ValueError(f"{label}: at least one branch required")
    seen = {label}
    norm = []
    for value, sub in branches.items():
        sub = dict(sub or {})
        for name in sub:
            if name in seen:
                raise ValueError(
                    f"{label}: sub-dimension {name!r} appears in more than one "
                    f"branch (or collides with the branch label) — conditional "
                    f"dims must have branch-unique names")
            seen.add(name)
        norm.append((value, tuple(sub.items())))
    return ChoiceOf(label, tuple(norm))


def validate_space(space: dict[str, Any]) -> None:
    """Reject dimension-name collisions across the WHOLE space — including a
    ``ChoiceOf`` sub-dim shadowing a top-level dim, which ``choice_of`` alone
    cannot see. A collision would silently clobber params in a draw and merge
    unrelated TPE histories (different bounds!) under one name."""
    seen: set[str] = set()
    for name, dim in space.items():
        names = [name]
        if isinstance(dim, ChoiceOf):
            names += [sub_name for _, sub in dim.branches for sub_name, _ in sub]
        for n in names:
            if n in seen:
                raise ValueError(
                    f"search space: dimension name {n!r} appears more than "
                    f"once — every dim (conditional sub-dims included) needs "
                    f"a space-unique name")
            seen.add(n)


def sample_space(space: dict[str, Any], rng: np.random.RandomState) -> dict[str, Any]:
    """One random draw from every dimension (startup / random-search mode).
    ``ChoiceOf`` dims contribute their branch value plus the selected branch's
    sub-dims only."""
    out: dict[str, Any] = {}
    for name, dim in space.items():
        if isinstance(dim, ChoiceOf):
            out.update(dim.sample(rng))
        else:
            out[name] = dim.sample(rng)
    return out
