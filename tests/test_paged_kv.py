"""Paged KV cache (ddw_tpu.serve.blocks): block tables, prefix reuse, CoW.

The tentpole pins, all on the 8-fake-CPU-device backend:

- token identity: the paged engine (default ``EngineCfg.paged``) with
  prefix reuse and copy-on-write enabled reproduces the sequential
  ``generate`` path bit-for-bit, greedy AND seeded sampling, including
  CoW-divergence fuzz around block boundaries and preemption-resume;
- no leaks: every block returns to free/cached across completion,
  eviction (failure reset), recycle generations and ``reset()``;
- admission on blocks: a pool too small for the offered concurrency
  queues (head-of-line) instead of failing, and every request completes;
- out-of-blocks mid-decode (``block_overcommit > 1``): the youngest
  stream preempts by recompute, re-queues at the head, resumes
  bit-identically and never re-emits a streamed token;
- block/prefix/CoW observability flows through snapshot, fleet merge and
  Prometheus rendering;
- at EQUAL KV memory the paged pool holds strictly more resident streams
  than the slot baseline (the capacity claim; the serving_curve smoke
  re-pins it with throughput on the wide package).
"""

import threading
import time

import jax
import numpy as np
import pytest

from ddw_tpu.models.lm import build_lm
from ddw_tpu.serve import BlockPool, EngineCfg, ServingEngine
from ddw_tpu.serve.blocks import OutOfBlocks
from ddw_tpu.serving.lm_package import load_lm_package, save_lm_package
from ddw_tpu.utils.config import LMCfg

VOCAB = 64
BS = 16     # kv_block_size under test (divides tile = min(256, 96))


def _lm_pkg(out_dir, seed=0, **cfg_kw):
    kw = dict(vocab_size=VOCAB, max_len=96, hidden=32, depth=2, num_heads=2,
              mlp_dim=64, dropout=0.0, dtype="float32")
    kw.update(cfg_kw)
    cfg = LMCfg(**kw)
    model = build_lm(cfg)
    params = model.init({"params": jax.random.PRNGKey(seed)},
                        np.zeros((1, 8), np.int32))["params"]
    d = save_lm_package(str(out_dir), cfg, params, quantize=None)
    return load_lm_package(d)


@pytest.fixture(scope="module")
def pm(tmp_path_factory):
    return _lm_pkg(tmp_path_factory.mktemp("paged_pkg") / "pkg")


@pytest.fixture(scope="module")
def eng2(pm):
    """One shared paged engine (n_slots=2, k=2) for the identity/reuse/
    metrics pins — its compiled prefill/decode programs and prefix-cache
    warmth amortize across tests (counter asserts below are monotone, so
    shared state only ever helps them)."""
    with ServingEngine(lm=pm, cfg=EngineCfg(n_slots=2, steps_per_tick=2,
                                            default_timeout_s=600.0)) as e:
        yield e


def _prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, size=(n,)).astype(np.int32)
            for n in lengths]


def _pool_clean(pool: BlockPool) -> None:
    """The leak pin: all rows free, no block in use, free + cached spans
    the whole pool, every refcount zero."""
    g = pool.gauges()
    assert g["resident_streams"] == 0
    assert g["blocks_used"] == 0, g
    assert g["blocks_free"] + g["blocks_cached"] == g["blocks_total"], g
    assert int(pool._ref.sum()) == 0
    assert pool._committed == 0
    assert pool.free_slots == pool.max_resident


# -- prefix reuse + CoW ------------------------------------------------------

def test_prefix_reuse_skips_prefill_and_stays_token_identical(eng2, pm):
    """Identical prompt -> full + tail hits (CoW clone); shared prefix with
    a divergent suffix -> full-block hits only; both bit-identical to the
    sequential path, with the skips visible in the metrics."""
    (pa,) = _prompts([24], seed=1)
    pb = pa.copy()
    pb[20] = (pb[20] + 1) % VOCAB          # diverges inside the tail block
    ra = pm.generate(pa[None, :], 8)[0]
    rb = pm.generate(pb[None, :], 8)[0]
    assert isinstance(eng2.pool, BlockPool)      # paged is the default
    assert np.array_equal(eng2.generate(pa, 8).tokens, ra)   # seeds cache
    f1 = eng2.submit_generate(pa, 8)             # exact repeat: tail CoW
    f2 = eng2.submit_generate(pb, 8)             # shared 16-token prefix
    assert np.array_equal(f1.result(timeout=120).tokens, ra)
    assert np.array_equal(f2.result(timeout=120).tokens, rb)
    snap = eng2.snapshot()
    assert snap["serve.prefix_hit_tokens"] >= 16 + 16
    assert snap["serve.prefix_hit_blocks"] >= 2
    assert snap["serve.cow_copies"] >= 1
    assert 0.0 < snap["serve.prefix_hit_rate"] <= 1.0


@pytest.mark.slow   # tier-1 budget (review adds the expired-head / drain /
#                     block-size regression pins below): the boundary fuzz is
#                     the slow sweep of the CoW-identity class whose tier-1
#                     representative is test_prefix_reuse_... above
def test_cow_divergence_fuzz_around_block_boundaries(eng2, pm):
    """Prompt pairs sharing prefixes that land on, just before, and just
    after block boundaries — every divergence point must reproduce the
    sequential tokens exactly (the CoW clone isolates the writer)."""
    rng = np.random.RandomState(3)
    before = eng2.snapshot()
    for plen in (BS - 1, BS + 1, 2 * BS, 2 * BS + 5):
        base = rng.randint(0, VOCAB, size=(plen,)).astype(np.int32)
        for div in sorted({0, plen - 1}):
            var = base.copy()
            var[div] = (var[div] + 1) % VOCAB
            for p in (base, var):
                ref = pm.generate(p[None, :], 5)[0]
                got = eng2.generate(p, 5).tokens
                assert np.array_equal(got, ref), (plen, div)
    snap = eng2.snapshot()
    _pool_clean(eng2.pool)
    assert (snap["serve.prefix_hit_tokens"]
            > before["serve.prefix_hit_tokens"])   # repeats hit the cache
    assert snap["serve.cow_copies"] > before["serve.cow_copies"]


def test_sampled_and_greedy_neighbors_with_prefix_reuse(eng2, pm):
    """Seeded sampling through the paged pool (per-request key schedule,
    prefix hits active) matches the sequential path; greedy neighbors in
    the same decode batch are unperturbed."""
    ps, pg = _prompts([19, 23], seed=5)
    sref = pm.generate(ps[None, :], 10, rng=jax.random.PRNGKey(11),
                       temperature=0.7)[0]
    gref = pm.generate(pg[None, :], 10)[0]
    eng2.generate(ps, 4)                       # seed the prefix cache
    before = eng2.snapshot()["serve.prefix_hit_tokens"]
    f1 = eng2.submit_generate(ps, 10, rng=jax.random.PRNGKey(11),
                              temperature=0.7)
    f2 = eng2.submit_generate(pg, 10)
    assert np.array_equal(f1.result(timeout=120).tokens, sref)
    assert np.array_equal(f2.result(timeout=120).tokens, gref)
    assert eng2.snapshot()["serve.prefix_hit_tokens"] > before


# -- allocator invariants ----------------------------------------------------

def test_block_leak_pin_across_generations(pm):
    """alloc/free accounting survives completion, a recoverable-error pool
    reset, restart() generations, and explicit reset() — nothing leaks,
    nothing double-frees."""
    prompts = _prompts([5, 21, 33, 9], seed=7)
    eng = ServingEngine(lm=pm, cfg=EngineCfg(n_slots=2, steps_per_tick=2))
    with eng:
        futs = [eng.submit_generate(p, 6) for p in prompts]
        [f.result(timeout=120) for f in futs]
        _pool_clean(eng.pool)
    # generation 1: restart resets the pool; serve again, still clean
    eng.restart()
    try:
        futs = [eng.submit_generate(p, 6) for p in prompts]
        [f.result(timeout=120) for f in futs]
        _pool_clean(eng.pool)
        snap = eng.snapshot()
        assert snap["serve.blocks_used"] == 0.0
        assert (snap["serve.blocks_free"] + snap["serve.blocks_cached"]
                == snap["serve.blocks_total"])
    finally:
        eng.stop()
    # explicit reset(): everything free, prefix cache empty
    pool = eng.pool
    pool.reset()
    _pool_clean(pool)
    assert pool.free_blocks == pool.n_blocks
    assert not pool._full_map and not pool._tail_map


def test_pool_unit_admit_release_refcounts(pm):
    """BlockPool unit behavior: shared blocks refcount up/down, the cached
    LRU is reclaimed under pressure, and a failed admit unwinds cleanly."""
    pool = BlockPool(pm.model, pm.params, n_blocks=6, block_size=BS,
                     max_resident=3, steps_per_tick=1)
    (p,) = _prompts([2 * BS + 4], seed=9)    # 3 prompt blocks
    row, hit = pool.admit(p, 4)
    assert hit == 0 and len(pool._streams[row].blocks) == 3
    pool.prefill([row], p[None, :], np.array([len(p)], np.int32),
                 np.zeros((1,), np.float32), np.zeros((1, 2), np.uint32))
    pool.register(row, p)
    pool.note_prefilled(row)
    # same prompt again: 2 full blocks shared (ref 2), tail cloned
    row2, hit2 = pool.admit(p, 4)
    assert hit2 == len(p) - 1
    st1, st2 = pool._streams[row], pool._streams[row2]
    assert st2.blocks[:2] == st1.blocks[:2]          # shared by reference
    assert st2.blocks[2] != st1.blocks[2]            # CoW clone
    assert pool._ref[st1.blocks[0]] == 2
    assert pool.stats["cow_copies"] == 1
    pool.release(row2)
    assert pool._ref[st1.blocks[0]] == 1
    pool.release(row)
    # registered blocks park in the cached LRU, not the free list
    assert pool.gauges()["blocks_cached"] == 3
    # allocation pressure reclaims them (admit needing more than free)
    (big,) = _prompts([5 * BS], seed=10)
    row3, hit3 = pool.admit(big, 2)
    assert hit3 == 0 and len(pool._streams[row3].blocks) == 5
    pool.release(row3)
    # over-budget admit raises OutOfBlocks and unwinds
    pool2 = BlockPool(pm.model, pm.params, n_blocks=2, block_size=BS,
                      max_resident=2, steps_per_tick=1)
    with pytest.raises(OutOfBlocks):
        pool2.admit(_prompts([5 * BS], seed=11)[0], 2)
    _pool_clean(pool2)


# -- admission on blocks -----------------------------------------------------

def test_admission_on_blocks_backpressures_and_completes(pm):
    """A pool with fewer blocks than the offered concurrency queues the
    overflow (head-of-line, no failure) and serves everything as releases
    free blocks; a request that can NEVER fit is refused at submission."""
    prompts = _prompts([17, 18, 19, 20, 21, 22], seed=13)
    refs = [pm.generate(p[None, :], 6)[0] for p in prompts]
    cfg = EngineCfg(n_slots=2, steps_per_tick=2, kv_cache_blocks=4,
                    max_resident=6)   # each request needs 2 blocks
    with ServingEngine(lm=pm, cfg=cfg) as eng:
        with pytest.raises(ValueError, match="KV blocks"):
            eng.submit_generate(prompts[0], 70)   # needs 6 > 4 blocks
        futs = [eng.submit_generate(p, 6) for p in prompts]
        out = [f.result(timeout=120) for f in futs]
        snap = eng.snapshot()
        _pool_clean(eng.pool)
    for i, (r, ref) in enumerate(zip(out, refs)):
        assert np.array_equal(r.tokens, ref), i
    assert snap["serve.completed"] == 6.0
    assert snap["serve.preemptions"] == 0.0   # conservative budget: never


def test_overloaded_retry_hint_from_block_release(pm):
    """Once the paged engine has a service estimate, a queue-full refusal
    carries a retry_after_ms derived from the earliest stream's projected
    block release (a finite positive hint)."""
    from ddw_tpu.serve import Overloaded

    (p,) = _prompts([8], seed=15)
    cfg = EngineCfg(n_slots=1, steps_per_tick=1, queue_depth=1,
                    max_resident=1)
    with ServingEngine(lm=pm, cfg=cfg) as eng:
        eng.generate(p, 4)                     # learn the service rate
        slow = []
        f1 = eng.submit_generate(
            p, 30, on_token=lambda i, t: time.sleep(0.01))
        deadline = time.monotonic() + 60
        while not eng.health()["busy_slots"] and time.monotonic() < deadline:
            time.sleep(0.002)                  # in a row: queue is empty
        f2 = eng.submit_generate(p, 4)         # queued (depth 1)
        with pytest.raises(Overloaded) as exc:
            eng.submit_generate(p, 4)          # queue full -> structured
        assert exc.value.retry_after_ms and exc.value.retry_after_ms > 0
        f1.result(timeout=120), f2.result(timeout=120)
        assert slow == []


# -- out-of-blocks mid-decode: preemption policy -----------------------------

@pytest.mark.slow   # tier-1 budget: the preempt-by-recompute identity
#                     class's tier-1 representative is (PR 17)
#                     test_kv_migration.py::test_disagg_identity_through_mid_decode_preemption,
#                     which drives the same requeue-front + fold-emitted
#                     machinery through the migrated-stream path; the
#                     spec-rollback composition
#                     (test_spec_engine.py::test_spec_preempt_resume_bit_identical_exactly_once)
#                     and this spec-off variant are the tier-2 sweeps
def test_out_of_blocks_preemption_resumes_token_identically(pm):
    """block_overcommit oversubscribes admission, so decode runs out of
    blocks mid-flight: the youngest stream is evicted, re-queued at the
    HEAD, and resumes BIT-identically — streamed tokens are never
    duplicated, outputs match the sequential path, nothing leaks."""
    prompts = _prompts([30, 31, 33, 34], seed=17)
    steps = 40                                 # forces growth past prompts
    refs = [pm.generate(p[None, :], steps)[0] for p in prompts]
    streamed: dict[int, list] = {i: [] for i in range(len(prompts))}
    cfg = EngineCfg(n_slots=2, steps_per_tick=4, kv_cache_blocks=12,
                    max_resident=4, block_overcommit=3.0,
                    default_timeout_s=600.0)
    with ServingEngine(lm=pm, cfg=cfg) as eng:
        futs = [eng.submit_generate(
            p, steps, on_token=lambda i, t, j=j: streamed[j].append((i, t)))
            for j, p in enumerate(prompts)]
        out = [f.result(timeout=300) for f in futs]
        snap = eng.snapshot()
        _pool_clean(eng.pool)
    assert snap["serve.preemptions"] > 0, "overcommit never ran out"
    for j, (r, ref) in enumerate(zip(out, refs)):
        assert np.array_equal(r.tokens, ref), j
        # the stream saw every token exactly once, in order
        assert [i for i, _ in streamed[j]] == list(range(steps)), j
        assert [t for _, t in streamed[j]] == list(r.tokens), j


# -- capacity: equal memory, more streams ------------------------------------

@pytest.mark.slow   # tier-1 budget (PR 16): block admission keeps its
#                     tier-1 reps in test_admission_on_blocks_backpressures
#                     _and_completes + the pool-unit refcount test; this
#                     equal-memory capacity A/B rides tier-2 with the
#                     serving-curve capacity arms
def test_equal_memory_admits_2x_resident_streams(pm):
    """Same KV bytes (paged default derives blocks from n_slots * cap):
    the slot pool tops out at n_slots resident; the paged pool holds the
    whole burst because short requests only take the blocks they use."""
    prompts = _prompts([8, 9, 10, 11], seed=19)
    steps = 24
    peaks = {}
    for name, paged in (("slot", False), ("paged", True)):
        cfg = EngineCfg(n_slots=2, steps_per_tick=2, paged=paged,
                        default_timeout_s=600.0)
        with ServingEngine(lm=pm, cfg=cfg) as eng:
            peak, stop = [0], threading.Event()

            def sampler():
                while not stop.is_set():
                    peak[0] = max(peak[0], eng.health()["busy_slots"])
                    time.sleep(0.001)

            th = threading.Thread(target=sampler)
            th.start()
            futs = [eng.submit_generate(p, steps) for p in prompts]
            [f.result(timeout=300) for f in futs]
            stop.set()
            th.join()
            peaks[name] = peak[0]
    assert peaks["slot"] <= 2
    assert peaks["paged"] >= 2 * peaks["slot"], peaks
    assert peaks["paged"] > 2    # strictly more than n_slots


# -- observability -----------------------------------------------------------

def test_paged_metrics_through_snapshot_merge_prometheus(eng2, pm):
    """Block gauges + prefix/CoW counters flow through the engine
    snapshot, the fleet merge, and the Prometheus exposition."""
    from ddw_tpu.serve import EngineMetrics, render_prometheus
    from ddw_tpu.serve.metrics import merge_metrics

    (p,) = _prompts([20], seed=21)
    eng = eng2
    eng.generate(p, 5)
    eng.generate(p, 5)           # exact repeat -> hits + CoW
    snap = eng.snapshot()
    met = eng.metrics
    for key in ("serve.blocks_total", "serve.blocks_free",
                "serve.blocks_cached", "serve.blocks_used",
                "serve.prefix_hit_tokens", "serve.prefix_hit_rate",
                "serve.cow_copies", "serve.preemptions"):
        assert key in snap, key
    assert snap["serve.blocks_total"] > 0
    assert snap["serve.prefix_hit_tokens"] > 0
    text = render_prometheus([met])
    for frag in ("ddw_serve_blocks_free ", "ddw_serve_blocks_total ",
                 "ddw_serve_prefix_hit_tokens_total ",
                 "ddw_serve_cow_copies_total ",
                 "ddw_serve_prefix_hit_rate ",
                 "ddw_serve_preemptions_total "):
        assert frag in text, frag
    # fleet merge SUMS gauges and counters
    other = EngineMetrics()
    other.set_gauges({"blocks_free": 3.0, "blocks_total": 4.0})
    other.count("cow_copies", 2)
    merged = merge_metrics([met, other]).snapshot()
    assert merged["serve.blocks_total"] == snap["serve.blocks_total"] + 4.0
    assert merged["serve.cow_copies"] == snap["serve.cow_copies"] + 2.0


# -- admission edge cases ----------------------------------------------------

def test_expired_head_admission_prefills_the_popped_request(pm):
    """The queue head's deadline passes while it waits; take() sheds it
    and returns the LIVE request behind it. Admission must prefill THAT
    request's prompt and budget — the regression prefilled the survivor
    with the dead head's prompt, answering it with the wrong tokens."""
    from ddw_tpu.serve import DeadlineExceeded

    p1, p2 = _prompts([10, 24], seed=23)
    steps = 4
    ref = pm.generate(p2[None, :], steps)[0]
    eng = ServingEngine(lm=pm, cfg=EngineCfg(n_slots=2, steps_per_tick=2))
    # engine NOT started: drive admission by hand, so the expired head is
    # still queued when admission peeks it (the live loop's shed_expired
    # pass usually hides that window)
    f1 = eng.submit_generate(p1, steps, timeout_s=0.01)
    f2 = eng.submit_generate(p2, steps)
    time.sleep(0.05)                    # head's deadline passes in-queue
    assert eng._admit_lm()
    for _ in range(64):
        if f2.done():
            break
        eng._decode_tick()
    with pytest.raises(DeadlineExceeded):
        f1.result(timeout=5)
    assert np.array_equal(f2.result(timeout=5).tokens, ref)
    _pool_clean(eng.pool)


@pytest.mark.slow  # tier-1 budget (PR 18): preempt-resume identity keeps its
                   # tier-1 rep in test_kv_migration's mid-decode preemption
                   # drill; drain-to-completion keeps the gateway drain pins.
def test_drain_completes_preempted_streams(pm):
    """A stream preempted for blocks MID-DRAIN (block_overcommit > 1) is
    already-claimed in-flight work: drain keeps re-admitting it while
    fresh queued work stays queued, and only reports clean once every
    claimed stream finished — the regression stranded it in the paused
    queue and reported a clean drain."""
    prompts = _prompts([30, 31, 33, 34], seed=25)
    steps = 40
    refs = [pm.generate(p[None, :], steps)[0] for p in prompts]
    cfg = EngineCfg(n_slots=2, steps_per_tick=4, kv_cache_blocks=12,
                    max_resident=4, block_overcommit=3.0,
                    default_timeout_s=600.0)
    with ServingEngine(lm=pm, cfg=cfg) as eng:
        futs = [eng.submit_generate(p, steps) for p in prompts]
        deadline = time.monotonic() + 60
        while (eng.health()["busy_slots"] < len(prompts)
               and time.monotonic() < deadline):
            time.sleep(0.001)
        assert eng.health()["busy_slots"] == len(prompts)  # all claimed
        assert eng.drain_slots(timeout_s=120.0)
        # clean drain -> every claimed request already finished
        out = [f.result(timeout=5) for f in futs]
        snap = eng.snapshot()
        eng.resume_admission()
        _pool_clean(eng.pool)
    assert snap["serve.preemptions"] > 0, "never ran out of blocks"
    for j, (r, ref) in enumerate(zip(out, refs)):
        assert np.array_equal(r.tokens, ref), j


def test_indivisible_kv_block_size_shrinks_with_warning(tmp_path):
    """max_len=100 -> attention tile 100, which the default block size 16
    does not divide: the engine shrinks it to the largest divisor (10)
    and serves, instead of failing construction where the slot pool
    worked."""
    pm100 = _lm_pkg(tmp_path / "pkg100", max_len=100)
    (p,) = _prompts([12], seed=27)
    ref = pm100.generate(p[None, :], 4)[0]
    with pytest.warns(RuntimeWarning, match="kv_block_size"):
        eng = ServingEngine(lm=pm100,
                            cfg=EngineCfg(n_slots=2, steps_per_tick=2))
    assert isinstance(eng.pool, BlockPool)
    assert eng.pool.block_size == 10
    with eng:
        assert np.array_equal(eng.generate(p, 4).tokens, ref)
        _pool_clean(eng.pool)
