"""The transfer contract on a REAL weights artifact (VERDICT r2 item 3).

Pretrains a MobileNetV2 on a generated corpus whose classes are disjoint from
flowers, exports the backbone in BOTH public layouts (torchvision state_dict,
Keras weights archive), converts each through the real import paths in
``models/convert.py``, then trains a frozen-base head on flowers from the
artifact — which must beat a frozen-RANDOM backbone by a wide margin AND clear
a pinned accuracy bar, then package+score end-to-end. This is the
reference's headline chain (``02_model_training_single_node.py:164-169``)
exercised from a weights file, not a synthetic dict.

Calibration (single run, 8-dev CPU mesh, width 0.35 @ 32px): pretrained-frozen
0.61 vs random-frozen 0.20 — the bars below leave ~2x margin on the gap.
"""

import pytest
import numpy as np

from ddw_tpu.data.prep import generate_synthetic_flowers, prepare_flowers
from ddw_tpu.data.store import TableStore
from ddw_tpu.models.convert import (
    convert_keras_mobilenet_v2,
    convert_torch_mobilenet_v2,
    load_keras_weights,
    save_pretrained,
)
from ddw_tpu.models.export import (
    export_keras_mobilenet_v2,
    export_torch_mobilenet_v2,
)
from ddw_tpu.train.trainer import Trainer
from ddw_tpu.utils.config import DataCfg, ModelCfg, TrainCfg

# end-to-end pretrain+convert+transfer chain — beyond the tier-1 wall-clock budget
pytestmark = pytest.mark.slow

WIDTH = 0.35
DATA = DataCfg(img_height=32, img_width=32)


def _fit(mcfg, tcfg, train_tbl, val_tbl):
    return Trainer(DATA, mcfg, tcfg).fit(train_tbl, val_tbl)


def test_pretrain_export_convert_transfer_package(tmp_path, silver):
    import jax

    from ddw_tpu.serving.batch import BatchScorer
    from ddw_tpu.serving.package import save_packaged_model

    store = TableStore(str(tmp_path / "tables"))
    pre_src = generate_synthetic_flowers(
        str(tmp_path / "pre_raw"), images_per_class=40, size=40,
        classes=[f"shape_{i}" for i in range(8)], seed=123)
    pre_train, pre_val, _ = prepare_flowers(
        pre_src, store, sample_fraction=1.0, shard_size=64,
        bronze_name="pre_bronze", train_name="pre_train", val_name="pre_val")

    # -- pretrain the backbone on the disjoint corpus
    pre_m = ModelCfg(name="mobilenet_v2", num_classes=8, dropout=0.1,
                     width_mult=WIDTH, freeze_base=False, dtype="float32")
    pre_t = TrainCfg(batch_size=8, epochs=6, warmup_epochs=0,
                     learning_rate=2e-3)
    pre_res = _fit(pre_m, pre_t, pre_train, pre_val)

    params = jax.device_get(pre_res.state.params)
    stats = jax.device_get(pre_res.state.batch_stats)
    backbone = {"params": params["backbone"], "batch_stats": stats["backbone"]}

    # -- export both public layouts, convert back through the real importers
    art_torch = str(tmp_path / "art_torch.npz")
    art_keras = str(tmp_path / "art_keras.npz")
    save_pretrained(art_torch,
                    convert_torch_mobilenet_v2(export_torch_mobilenet_v2(backbone)))
    keras_npz = str(tmp_path / "keras_w.npz")
    np.savez(keras_npz, **export_keras_mobilenet_v2(backbone))
    save_pretrained(art_keras,
                    convert_keras_mobilenet_v2(load_keras_weights(keras_npz)))
    with np.load(art_torch) as a, np.load(art_keras) as b:
        assert set(a.files) == set(b.files)
        for k in a.files:
            np.testing.assert_allclose(a[k], b[k], atol=1e-6,
                                       err_msg=f"layouts disagree at {k}")

    # -- frozen transfer on flowers: artifact vs random
    train_tbl, val_tbl, label_to_idx = silver
    tcfg = TrainCfg(batch_size=8, epochs=4, warmup_epochs=0,
                    learning_rate=5e-3)
    m_pre = ModelCfg(name="mobilenet_v2", num_classes=5, dropout=0.1,
                     width_mult=WIDTH, freeze_base=True, dtype="float32",
                     pretrained_path=art_torch)
    m_rnd = ModelCfg(name="mobilenet_v2", num_classes=5, dropout=0.1,
                     width_mult=WIDTH, freeze_base=True, dtype="float32",
                     allow_frozen_random=True)
    res_pre = _fit(m_pre, tcfg, train_tbl, val_tbl)
    acc_pre = res_pre.val_accuracy
    acc_rnd = _fit(m_rnd, tcfg, train_tbl, val_tbl).val_accuracy

    # the transfer contract: pretrained frozen >> random frozen, above a bar
    assert acc_pre >= 0.45, (acc_pre, acc_rnd)
    assert acc_pre >= acc_rnd + 0.10, (acc_pre, acc_rnd)

    # -- package + batch-score the pretrained model end-to-end
    classes = [c for c, _ in sorted(label_to_idx.items(), key=lambda kv: kv[1])]
    pkg = str(tmp_path / "pkg")
    save_packaged_model(pkg, m_pre, classes, res_pre.state.params,
                        res_pre.state.batch_stats,
                        img_height=DATA.img_height, img_width=DATA.img_width)
    rows = BatchScorer(pkg, batch_per_device=8).score_table(val_tbl)
    assert len(rows) == val_tbl.num_records
    truth = {r.path: r.label for r in val_tbl.iter_records()}
    agree = sum(truth[p] == pred for p, pred in rows) / len(rows)
    assert agree >= 0.45, agree
