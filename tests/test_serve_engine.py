"""Online serving engine (ddw_tpu.serve): continuous-batching determinism,
admission control, SLO metrics, int8 engine path, throughput-over-sequential.

Runs on the 8-fake-CPU-device backend like every tier-1 test. The core
acceptance pins: (1) engine LM outputs are token-identical to the sequential
single-request generate path for ANY admission interleaving, across slot
counts and eviction orders; (2) over-capacity requests get a structured
``Overloaded`` (never a hang) and expired requests are shed before device
work; (3) a quantized package served through the engine matches its direct
apply; (4) batched continuous decoding beats sequential generation in
aggregate tokens/sec at concurrency 8.

One LM package is module-scoped: the sequential reference path
(``LMPackagedModel.generate``) caches one compiled program per
(bucket, steps) across every test here, so the tier-1 cost is the engine's
own programs, not repeated reference compiles. The widest arms (extra slot
configs, the throughput bench) carry the ``slow`` marker — the tier-2 suite
runs them; tier-1 keeps one full determinism pin.
"""

import os
import time

import jax
import numpy as np
import pytest

from ddw_tpu.models.lm import build_lm
from ddw_tpu.serve import (
    DeadlineExceeded,
    EngineCfg,
    Overloaded,
    ServingEngine,
    SlotPool,
    batch_bucket,
    bucket_len,
    length_buckets,
    pad_to_bucket,
)
from ddw_tpu.serving.lm_package import load_lm_package, save_lm_package
from ddw_tpu.utils.config import LMCfg

VOCAB = 64


def _lm_pkg(out_dir, quantize=None, seed=0, **cfg_kw):
    kw = dict(vocab_size=VOCAB, max_len=96, hidden=32, depth=2, num_heads=2,
              mlp_dim=64, dropout=0.0, dtype="float32")
    kw.update(cfg_kw)
    cfg = LMCfg(**kw)
    model = build_lm(cfg)
    params = model.init({"params": jax.random.PRNGKey(seed)},
                        np.zeros((1, 8), np.int32))["params"]
    d = save_lm_package(str(out_dir), cfg, params, quantize=quantize)
    return load_lm_package(d)


@pytest.fixture(scope="module")
def pm(tmp_path_factory):
    """The shared f32 LM package — its generate/score program caches
    persist across every test in this module."""
    return _lm_pkg(tmp_path_factory.mktemp("serve_pkg") / "pkg")


def _prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, size=(n,)).astype(np.int32)
            for n in lengths]


# -- bucketing --------------------------------------------------------------

def test_bucketing_ladder():
    assert length_buckets(96, 8) == (8, 16, 32, 64, 96)
    assert bucket_len(5, 96) == 8 and bucket_len(9, 96) == 16
    assert bucket_len(96, 96) == 96
    with pytest.raises(ValueError, match="exceeds"):
        bucket_len(97, 96)
    padded = pad_to_bucket(np.ones((1, 5), np.int32), 8)
    assert padded.shape == (1, 8) and padded[0, 5:].sum() == 0
    assert batch_bucket(3, 8) == 4 and batch_bucket(9, 8) == 8


# -- determinism: engine == sequential generate -----------------------------

@pytest.mark.slow   # tier-1 determinism reps for the engine==sequential
#                     class live in tests/test_paged_kv.py (greedy +
#                     seeded on the default paged pool) and
#                     tests/test_spec_engine.py (same pins through the
#                     speculative tick); this matrix re-pins it across
#                     slot counts / chain lengths / eviction orders in
#                     tier-2
@pytest.mark.parametrize("n_slots,steps_per_tick", [(1, 1), (2, 4), (4, 3)])
def test_engine_matches_sequential_across_slot_counts(pm, n_slots,
                                                      steps_per_tick):
    """More requests than slots, varied prompt lengths and step counts:
    every eviction order / slot reuse pattern must reproduce the sequential
    path token-for-token."""
    prompts = _prompts([3, 9, 14, 5, 21, 7])
    steps = [11, 4, 8, 1, 6, 13]
    refs = [pm.generate(p[None, :], s)[0] for p, s in zip(prompts, steps)]
    cfg = EngineCfg(n_slots=n_slots, steps_per_tick=steps_per_tick)
    with ServingEngine(lm=pm, cfg=cfg) as eng:
        futs = [eng.submit_generate(p, s) for p, s in zip(prompts, steps)]
        out = [f.result(timeout=120) for f in futs]
    for i, (r, ref) in enumerate(zip(out, refs)):
        assert np.array_equal(r.tokens, ref), i
        assert r.ttft_ms >= 0 and r.total_ms >= r.ttft_ms


@pytest.mark.slow   # tier-1 budget (PR 12): mid-decode admission with
#                     mixed greedy/sampled neighbors is pinned tier-1 by
#                     tests/test_paged_kv.py and the spec drills in
#                     tests/test_spec_engine.py (requests admitted while
#                     residents decode on the default paged pool); this
#                     staggered two-phase sweep rides tier-2
def test_engine_matches_sequential_with_staggered_admissions(pm):
    """Admissions arriving WHILE other slots decode (the continuous-batching
    case) — greedy requests interleaved with per-request temperature
    sampling on the generate() key schedule: outputs stay token-identical
    to the sequential path, and sampled/greedy neighbors don't perturb
    each other. One engine serves both phases (one compile set)."""
    prompts = _prompts([4, 12, 6, 17, 9, 3, 25, 8], seed=3)
    refs = [pm.generate(p[None, :], 10)[0] for p in prompts]
    ps1, ps2 = _prompts([9, 6], seed=5)
    sref1 = pm.generate(ps1[None, :], 12, rng=jax.random.PRNGKey(11),
                        temperature=0.7)[0]
    sref2 = pm.generate(ps2[None, :], 12)[0]
    with ServingEngine(lm=pm,
                       cfg=EngineCfg(n_slots=3, steps_per_tick=2)) as eng:
        futs = []
        for p in prompts:
            futs.append(eng.submit_generate(p, 10))
            time.sleep(0.01)  # land mid-flight of earlier requests
        out = [f.result(timeout=120) for f in futs]
        f1 = eng.submit_generate(ps1, 12, rng=jax.random.PRNGKey(11),
                                 temperature=0.7)
        f2 = eng.submit_generate(ps2, 12)
        assert np.array_equal(f1.result(120).tokens, sref1)
        assert np.array_equal(f2.result(120).tokens, sref2)
    for i, (r, ref) in enumerate(zip(out, refs)):
        assert np.array_equal(r.tokens, ref), i


# -- admission control ------------------------------------------------------

def test_overloaded_is_structured_not_a_hang(pm):
    """Submissions past queue_depth refuse IMMEDIATELY with the structured
    reply (engine not even started — a wedged engine must also refuse)."""
    eng = ServingEngine(lm=pm, cfg=EngineCfg(n_slots=1, queue_depth=2))
    p = _prompts([5])[0]
    eng.submit_generate(p, 4)
    eng.submit_generate(p, 4)
    t0 = time.monotonic()
    with pytest.raises(Overloaded) as exc:
        eng.submit_generate(p, 4)
    assert time.monotonic() - t0 < 1.0
    d = exc.value.to_dict()
    assert d["error"] == "overloaded"
    assert d["capacity"] == 2 and d["depth"] == 2
    assert eng.metrics.snapshot()["serve.shed_overloaded"] == 1.0
    eng.stop()


def test_expired_requests_shed_before_device_work(pm):
    """A request whose deadline passes while queued completes with
    DeadlineExceeded — and the engine never prefilled it."""
    eng = ServingEngine(lm=pm, cfg=EngineCfg(n_slots=1))
    p = _prompts([5])[0]
    fut = eng.submit_generate(p, 4, timeout_s=0.05)
    time.sleep(0.2)       # expire while the engine is not running
    eng.start()
    with pytest.raises(DeadlineExceeded) as exc:
        fut.result(timeout=30)
    assert exc.value.to_dict()["error"] == "deadline_exceeded"
    snap = eng.metrics.snapshot()
    assert snap["serve.shed_deadline"] == 1.0
    assert snap["serve.prefills"] == 0.0   # no device work was spent
    eng.stop()


def test_engine_rejects_invalid_requests(pm):
    eng = ServingEngine(lm=pm)
    p = _prompts([5])[0]
    with pytest.raises(ValueError, match="token ids outside"):
        eng.submit_generate(p + VOCAB, 4)
    with pytest.raises(ValueError, match="exceeds max_len"):
        eng.submit_generate(p, 96)
    with pytest.raises(ValueError, match="num_steps"):
        eng.submit_generate(p, 0)
    with pytest.raises(ValueError, match="requires rng"):
        eng.submit_generate(p, 4, temperature=0.5)
    with pytest.raises(ValueError, match="image"):
        eng.submit_predict(np.zeros((8, 8, 3), np.float32))


# -- quantized packages through the engine ----------------------------------

@pytest.mark.slow   # tier-1 budget (PR 7 adds tests/test_paged_kv.py): the
#                     quantized-ENGINE-parity class keeps the image test
#                     below as its tier-1 representative; this arm builds a
#                     second LM package + a full engine program set and
#                     re-pins the same contract in tier-2
def test_int8_lm_package_through_engine_matches_direct(pm, tmp_path):
    """serving/quantize.py engine-path coverage: an int8 LM package served
    by the engine is token-identical to its own direct (dequantized) apply,
    and close to the f32 package."""
    pm8 = _lm_pkg(tmp_path / "i8", quantize="int8")
    prompts = _prompts([6, 11, 4, 15], seed=9)
    direct = [pm8.generate(p[None, :], 8)[0] for p in prompts]
    with ServingEngine(lm=pm8,
                       cfg=EngineCfg(n_slots=2, steps_per_tick=3)) as eng:
        futs = [eng.submit_generate(p, 8) for p in prompts]
        out = [f.result(timeout=120) for f in futs]
    for i, (r, ref) in enumerate(zip(out, direct)):
        assert np.array_equal(r.tokens, ref), i
    # scores stay close to full precision (the quantization contract)
    toks = np.stack([np.concatenate([prompts[0], direct[0]])])
    np.testing.assert_allclose(pm8.score(toks), pm.score(toks),
                               rtol=0.05, atol=0.05)


def test_int8_image_package_through_engine_matches_direct(tmp_path):
    from ddw_tpu.serving.package import (load_packaged_model,
                                         save_packaged_model)
    from ddw_tpu.utils.config import ModelCfg

    mcfg = ModelCfg(name="small_cnn", num_classes=5, dropout=0.0,
                    dtype="float32")
    from ddw_tpu.models.registry import build_model

    model = build_model(mcfg)
    rng = np.random.RandomState(0)
    imgs = rng.rand(5, 32, 32, 3).astype(np.float32) * 2 - 1
    variables = model.init({"params": jax.random.PRNGKey(0)}, imgs[:1],
                           train=False)
    d = save_packaged_model(
        str(tmp_path / "img8"), mcfg, [f"c{i}" for i in range(5)],
        variables["params"], variables.get("batch_stats"),
        img_height=32, img_width=32, quantize="int8")
    pkg = load_packaged_model(d)
    ref = pkg.predict_logits(imgs)
    with ServingEngine(image=pkg, cfg=EngineCfg(max_batch=4,
                                                max_wait_ms=1.0)) as eng:
        out = eng.predict(list(imgs))
    got = np.stack([r.logits for r in out])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
    assert [r.label for r in out] == pkg.predict(imgs)
    assert eng.metrics.snapshot()["serve.image_batches"] >= 1.0


# -- cancellation -----------------------------------------------------------

def test_cancel_queued_request_dropped_before_device_work(pm):
    """Future.cancel() on a still-queued request drops it without any
    prefill and counts it; a request already claimed by a slot runs to
    completion (cancel() returns False)."""
    import concurrent.futures

    eng = ServingEngine(lm=pm, cfg=EngineCfg(n_slots=2, steps_per_tick=2))
    p1, p2 = _prompts([5, 6], seed=2)
    f1 = eng.submit_generate(p1, 4)
    f2 = eng.submit_generate(p2, 4)
    assert f2.cancel()                 # still queued: drop is guaranteed
    eng.start()
    r1 = f1.result(timeout=120)
    assert len(r1.tokens) == 4
    with pytest.raises(concurrent.futures.CancelledError):
        f2.result(timeout=10)
    snap = eng.snapshot()
    assert snap["serve.cancelled"] == 1.0
    assert snap["serve.completed"] == 1.0
    assert snap["serve.prefills"] == 1.0   # the cancelled one never ran
    # once admitted to a slot, cancel() is refused and the request finishes
    got = []
    f3 = eng.submit_generate(p1, 6, on_token=lambda i, t: got.append(t))
    deadline = time.monotonic() + 60
    while not got and time.monotonic() < deadline:
        time.sleep(0.002)
    assert got, "first token never streamed"
    assert not f3.cancel()
    r3 = f3.result(timeout=120)
    assert np.array_equal(r3.tokens, pm.generate(p1[None, :], 6)[0])
    assert got == list(r3.tokens)      # on_token streamed every token
    eng.stop()


# -- SLO metrics + tracker export -------------------------------------------

def test_metrics_snapshot_and_tracker_export(pm, tmp_path):
    import json

    from ddw_tpu.tracking.tracker import Tracker

    run = Tracker(str(tmp_path / "mlruns"), "serving").start_run("engine")
    prompts = _prompts([5, 9, 7, 12])
    with ServingEngine(lm=pm, cfg=EngineCfg(n_slots=2, steps_per_tick=2),
                       run=run) as eng:
        futs = [eng.submit_generate(p, 6) for p in prompts]
        [f.result(timeout=120) for f in futs]
        snap = eng.snapshot()
        # the jsonl artifact streams incrementally: all completed rows are
        # already on disk (flushed) while the engine is still live — a
        # SIGKILL here would lose nothing
        live = os.path.join(run.run_dir, "artifacts", "serving",
                            "serve_requests.jsonl")
        rows_live = [json.loads(ln) for ln in open(live)]
        assert len(rows_live) == 4
    run.end()
    assert snap["serve.completed"] == 4.0
    for key in ("serve.queue_ms_p50", "serve.queue_ms_p95",
                "serve.queue_ms_p99", "serve.ttft_ms_p95",
                "serve.total_ms_p99", "serve.tokens_per_sec"):
        assert key in snap and snap[key] >= 0.0
    assert snap["serve.tokens_out"] == 24.0
    # p-order sanity
    assert snap["serve.total_ms_p99"] >= snap["serve.total_ms_p50"]
    # exported through the tracker on stop()
    m = run.final_metrics()
    assert m["serve.completed"] == 4.0
    art = os.path.join(run.run_dir, "artifacts", "serving",
                       "serve_requests.jsonl")
    rows = [json.loads(ln) for ln in open(art)]
    assert len(rows) == 4 and all(r["kind"] == "lm" for r in rows)


# -- Prometheus text exposition (pure unit: synthetic records) ---------------

def test_prometheus_rendering_and_fleet_merge():
    from ddw_tpu.serve import EngineMetrics, RequestRecord, render_prometheus
    from ddw_tpu.serve.metrics import merge_metrics

    a, b = EngineMetrics(), EngineMetrics()
    t0 = 100.0
    for m, offs, tokens in ((a, 0.0, 6), (a, 0.004, 8), (b, 0.030, 4)):
        m.record(RequestRecord("lm", t0 + offs, t0 + offs + 0.001,
                               t0 + offs + 0.003, t0 + offs + 0.008,
                               tokens=tokens))
    a.count_overloaded()
    b.count_deadline()
    b.count_cancelled()
    a.count("prefills", 2)
    b.count("decode_ticks", 5)

    text = render_prometheus([a, b])
    lines = dict(ln.rsplit(" ", 1) for ln in text.splitlines()
                 if ln and not ln.startswith("#"))
    assert lines["ddw_serve_completed_total"] == "3"
    assert lines["ddw_serve_tokens_out_total"] == "18"
    assert lines["ddw_serve_shed_overloaded_total"] == "1"
    assert lines["ddw_serve_shed_deadline_total"] == "1"
    assert lines["ddw_serve_cancelled_total"] == "1"
    assert lines["ddw_serve_prefills_total"] == "2"
    assert lines["ddw_serve_decode_ticks_total"] == "5"
    # histogram: all three total_ms values are 8 ms -> cumulative counts
    # 0 below the 10 ms bucket, 3 from it onward, +Inf == count
    assert lines['ddw_serve_total_ms_bucket{le="5"}'] == "0"
    assert lines['ddw_serve_total_ms_bucket{le="10"}'] == "3"
    assert lines['ddw_serve_total_ms_bucket{le="+Inf"}'] == "3"
    assert lines["ddw_serve_total_ms_count"] == "3"
    assert float(lines["ddw_serve_total_ms_sum"]) == pytest.approx(24.0)
    # busy-window throughput spans the union of both replicas' windows:
    # first admit 100.001, last done 100.038 -> 18 tokens / 0.037 s
    assert float(lines["ddw_serve_tokens_per_sec"]) == pytest.approx(
        18 / 0.037, rel=1e-4)      # %g renders 6 significant digits
    # the merged snapshot agrees with the exposition
    snap = merge_metrics([a, b]).snapshot()
    assert snap["serve.completed"] == 3.0
    assert snap["serve.tokens_out"] == 18.0
    assert snap["serve.cancelled"] == 1.0
    # labeled extra gauges get exactly one TYPE line per family
    text2 = render_prometheus([a], extra_gauges={
        'ddw_gateway_outstanding{replica="0"}': 1.0,
        'ddw_gateway_outstanding{replica="1"}': 2.0})
    assert text2.count("# TYPE ddw_gateway_outstanding gauge") == 1
    assert 'ddw_gateway_outstanding{replica="1"} 2' in text2


# -- continuous batching beats sequential -----------------------------------

@pytest.mark.slow
def test_engine_throughput_beats_sequential_at_concurrency_8(tmp_path):
    """The continuous-batching claim, on CPU at smoke scale: aggregate
    engine tokens/sec at concurrency 8 strictly above one-at-a-time
    sequential generation of the same requests on the same package. The
    package is wide enough (hidden 256) that decode is weight-stream-bound
    — the regime batching exists for; at toy widths sequential's single
    fused scan program wins on pure dispatch count (measured ~1.8x engine
    win here, so CI noise has margin). The serving_curve smoke pins the
    same win at hidden 384 through the bench path."""
    wide = _lm_pkg(tmp_path / "wide", vocab_size=256, max_len=128,
                   hidden=256, depth=3, num_heads=4, mlp_dim=1024)
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, 256, size=(8,)).astype(np.int32)
               for _ in range(8)]
    steps = 24
    # warm both paths (compile time out of the measurement)
    wide.generate(prompts[0][None, :], steps)
    cfg = EngineCfg(n_slots=8, steps_per_tick=8)
    with ServingEngine(lm=wide, cfg=cfg) as eng:
        eng.warmup([8])
        eng.generate(prompts[0], steps)
        t0 = time.perf_counter()
        futs = [eng.submit_generate(p, steps) for p in prompts]
        [f.result(timeout=300) for f in futs]
        engine_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for p in prompts:
        wide.generate(p[None, :], steps)
    seq_s = time.perf_counter() - t0
    engine_tps = len(prompts) * steps / engine_s
    seq_tps = len(prompts) * steps / seq_s
    assert engine_tps > seq_tps, (engine_tps, seq_tps)


# -- slot pool unit behavior ------------------------------------------------

def test_slot_pool_acquire_release_cycle(pm):
    pool = SlotPool(pm.model, pm.params, n_slots=2, steps_per_tick=1)
    a, b = pool.acquire(), pool.acquire()
    assert pool.free_slots == 0
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.acquire()
    pool.release(a)
    assert pool.free_slots == 1
    with pytest.raises(ValueError, match="already free"):
        pool.release(a)
    pool.release(b)
    assert sorted([pool.acquire(), pool.acquire()]) == [0, 1]


def test_engine_stop_fails_pending_cleanly(pm):
    """stop() with queued work completes the futures with an error instead
    of leaving callers blocked forever."""
    eng = ServingEngine(lm=pm, cfg=EngineCfg(n_slots=1))  # never started
    fut = eng.submit_generate(_prompts([5])[0], 4)
    eng.stop()
    with pytest.raises(RuntimeError, match="engine stopped"):
        fut.result(timeout=10)
