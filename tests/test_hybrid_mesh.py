"""DCN-aware hybrid mesh: slice-major layout, refusals, 2-process slices.

The reference actually spans machines (Spark workers + MPI,
``03_model_training_distributed.py:258-263``); the TPU-native completion of
that role is a mesh whose axes know which network they ride: per-layer
collectives (model/seq) confined to a slice's ICI, amortized ones
(data/pipe) allowed across the DCN. Real pods can't be tested here — the
layout algebra and refusals are pinned on the virtual CPU mesh, with two
launcher processes standing in for two slices.
"""

import jax
import numpy as np
import pytest

from ddw_tpu.runtime.launcher import Launcher
from ddw_tpu.runtime.mesh import (
    DATA_AXIS,
    HybridMeshSpec,
    make_hybrid_mesh,
)

TWO_SLICES = lambda d: d.id // 4  # 8 virtual devices -> 2 fake slices of 4


def _slice_of(dev):
    return dev.id // 4


def test_slice_major_layout():
    """data = 2 slices x 2 chips, model = 2 chips in-slice: along `model`
    every pair shares a slice; along `data` same-slice entries are
    consecutive and the slice boundary is the outermost stride."""
    mesh = make_hybrid_mesh(
        ((DATA_AXIS, 2, 2), ("model", 1, 2)),
        devices=jax.devices()[:8], slice_index_fn=TWO_SLICES)
    assert dict(mesh.shape) == {"data": 4, "model": 2}
    arr = mesh.devices
    # model axis never crosses a slice
    for i in range(4):
        assert _slice_of(arr[i, 0]) == _slice_of(arr[i, 1])
    # data axis: positions 0-1 one slice, 2-3 the other (slice-major)
    col = [_slice_of(arr[i, 0]) for i in range(4)]
    assert col[0] == col[1] and col[2] == col[3] and col[0] != col[2]


def test_wildcards_resolve_over_slices_and_chips():
    mesh = make_hybrid_mesh(
        ((DATA_AXIS, -1, 1), ("model", 1, -1)),
        devices=jax.devices()[:8], slice_index_fn=TWO_SLICES)
    assert dict(mesh.shape) == {"data": 2, "model": 4}
    # default spec: one big data axis over everything
    mesh2 = make_hybrid_mesh(devices=jax.devices()[:8],
                             slice_index_fn=TWO_SLICES)
    assert dict(mesh2.shape) == {"data": 8}


def test_cross_slice_tp_refused():
    with pytest.raises(ValueError, match="refused"):
        make_hybrid_mesh(((DATA_AXIS, 1, 4), ("model", 2, 1)),
                         devices=jax.devices()[:8],
                         slice_index_fn=TWO_SLICES)
    with pytest.raises(ValueError, match="refused"):
        make_hybrid_mesh((("seq", 2, 4),), devices=jax.devices()[:8],
                         slice_index_fn=TWO_SLICES)
    # pipe may span slices (the classic weak-link axis)
    mesh = make_hybrid_mesh((("pipe", 2, 1), (DATA_AXIS, 1, 4)),
                            devices=jax.devices()[:8],
                            slice_index_fn=TWO_SLICES)
    assert dict(mesh.shape) == {"pipe": 2, "data": 4}
    # a -1 that resolves to 1 slice is legal on any axis
    one_slice = make_hybrid_mesh(((DATA_AXIS, 1, 4), ("model", -1, 2)),
                                 devices=jax.devices()[:8],
                                 slice_index_fn=lambda d: 0)
    assert dict(one_slice.shape) == {"data": 4, "model": 2}


def test_bad_shapes_refused():
    with pytest.raises(ValueError, match="unequal slices"):
        make_hybrid_mesh(devices=jax.devices()[:7], slice_index_fn=TWO_SLICES)
    with pytest.raises(ValueError, match="dcn"):
        make_hybrid_mesh(((DATA_AXIS, 3, 4),), devices=jax.devices()[:8],
                         slice_index_fn=TWO_SLICES)
    with pytest.raises(ValueError, match="ici"):
        make_hybrid_mesh(((DATA_AXIS, 2, 3),), devices=jax.devices()[:8],
                         slice_index_fn=TWO_SLICES)


def test_hybrid_mesh_trains_like_flat_mesh():
    """The hybrid mesh is a drop-in Mesh: one DP train step over it matches
    the flat-mesh step bit-for-bit (layout changes device placement, not
    math)."""
    import optax

    from ddw_tpu.models.registry import build_model
    from ddw_tpu.runtime.mesh import MeshSpec, make_mesh
    from ddw_tpu.train.step import init_state, make_train_step
    from ddw_tpu.utils.config import ModelCfg, TrainCfg

    mcfg = ModelCfg(name="small_cnn", num_classes=5, dropout=0.0,
                    dtype="float32")
    tcfg = TrainCfg(batch_size=8, learning_rate=1e-2, optimizer="sgd")
    model = build_model(mcfg)
    state, tx = init_state(model, mcfg, tcfg, (16, 16, 3),
                           jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    imgs = rng.randn(8, 16, 16, 3).astype(np.float32)
    lbls = rng.randint(0, 5, size=(8,)).astype(np.int32)

    hyb = make_hybrid_mesh(((DATA_AXIS, 2, 4),), devices=jax.devices()[:8],
                           slice_index_fn=TWO_SLICES)
    flat = make_mesh(MeshSpec(((DATA_AXIS, 8),)), devices=jax.devices()[:8])
    outs = []
    for mesh in (hyb, flat):
        step = make_train_step(model, tx, mesh, DATA_AXIS, donate=False)
        _, m = step(state, imgs, lbls, jax.random.PRNGKey(1))
        outs.append(float(m["loss"]))
    assert outs[0] == pytest.approx(outs[1], abs=1e-6)


def test_make_data_mesh_auto_detects_slices():
    """The trainers' default mesh: slice-major when devices span slices,
    plain when they don't, flat fallback when slices are unequal."""
    from ddw_tpu.runtime.mesh import make_data_mesh

    devs = jax.devices()[:8]
    # interleaving slice fn: the flat id-order layout would NOT be
    # slice-major, so this assertion only passes via the hybrid path
    interleaved = lambda d: d.id % 2
    multi = make_data_mesh(devices=devs, slice_index_fn=interleaved)
    assert dict(multi.shape) == {"data": 8}
    order = [interleaved(d) for d in multi.devices.ravel()]
    assert order == sorted(order)  # slice-major (0,0,0,0,1,1,1,1)

    single = make_data_mesh(devices=devs, slice_index_fn=lambda d: 0)
    assert dict(single.shape) == {"data": 8}

    # 6 devices over the 4-per-slice fn: unequal slices -> flat fallback
    uneven = make_data_mesh(devices=jax.devices()[:6],
                            slice_index_fn=TWO_SLICES)
    assert dict(uneven.shape) == {"data": 6}


def test_device_slice_index_tpu_without_attr_is_single_slice():
    """A TPU device lacking slice_index must map to slice 0, not its host:
    a multi-host single-slice pod on a jax build without the attribute
    would otherwise silently lose mesh_utils' pod-wide ICI ordering to a
    host-major hybrid layout. CPU devices keep the process-index fallback
    (the launcher gang stand-in depends on it)."""
    from types import SimpleNamespace

    from ddw_tpu.runtime.mesh import device_slice_index

    tpu_no_attr = SimpleNamespace(platform="tpu", process_index=3)
    assert device_slice_index(tpu_no_attr) == 0
    tpu_with = SimpleNamespace(platform="tpu", process_index=3, slice_index=2)
    assert device_slice_index(tpu_with) == 2
    cpu = SimpleNamespace(platform="cpu", process_index=3)
    assert device_slice_index(cpu) == 3


def _slice_report():
    """Runs inside each launcher worker: two processes = two slices."""
    import jax

    from ddw_tpu.runtime.mesh import DATA_AXIS, make_hybrid_mesh

    mesh = make_hybrid_mesh(((DATA_AXIS, -1, -1),))  # default slice fn:
    arr = mesh.devices                               # process_index
    return {
        "shape": dict(mesh.shape),
        "slice_of": [int(d.process_index) for d in arr.ravel()],
    }


def _hybrid_fsdp_worker():
    """FSDP over the slice-aware mesh in a real gang: the multi-node claim
    (VERDICT r3 missing-item 3) with sharded state on top of the DCN-aware
    layout — each process is one 'slice', params shard over the hybrid
    data axis."""
    import jax
    import numpy as np

    from ddw_tpu.models.registry import build_model
    from ddw_tpu.parallel.zero import fsdp_state_shardings, make_fsdp_train_step
    from ddw_tpu.runtime.mesh import make_hybrid_mesh
    from ddw_tpu.train.step import init_state
    from ddw_tpu.utils.config import ModelCfg, TrainCfg

    mesh = make_hybrid_mesh()  # data = slices x local devices, slice-major
    n = mesh.shape["data"]
    mcfg = ModelCfg(name="small_cnn", num_classes=5, dropout=0.0,
                    dtype="float32")
    model = build_model(mcfg)
    state, tx = init_state(model, mcfg, TrainCfg(batch_size=8,
                                                 learning_rate=1e-2),
                           (16, 16, 3), jax.random.PRNGKey(0))
    step = make_fsdp_train_step(model, tx, mesh, donate=False)

    host = jax.tree.map(np.asarray, state)
    sh = fsdp_state_shardings(state, mesh)
    gstate = jax.tree.map(
        lambda x, s: jax.make_array_from_callback(x.shape, s,
                                                  lambda idx: x[idx]),
        host, sh)
    rng = np.random.RandomState(0)
    imgs = rng.randn(32, 16, 16, 3).astype(np.float32)
    lbls = rng.randint(0, 5, size=(32,)).astype(np.int32)
    gi = jax.make_array_from_callback(imgs.shape, step.batch_sharding,
                                      lambda idx: imgs[idx])
    gl = jax.make_array_from_callback(lbls.shape, step.batch_sharding,
                                      lambda idx: lbls[idx])
    losses = []
    for i in range(6):
        gstate, m = step(gstate, gi, gl, jax.random.PRNGKey(i))
        losses.append(float(jax.device_get(m["loss"])))
    sharded = sum(1 for leaf in jax.tree.leaves(gstate.params)
                  if any(ax for ax in leaf.sharding.spec))
    return {"world": n, "processes": jax.process_count(),
            "slice_major": [int(d.process_index)
                            for d in mesh.devices.ravel()],
            "losses": losses, "n_sharded": sharded}


@pytest.mark.slow   # 2-process gang train run — the ROADMAP's
#                     "multi-process training" tier-2 class
def test_two_process_hybrid_fsdp(worker_pythonpath):
    out = Launcher(np=2, devices_per_proc=2, timeout_s=540).run(
        _hybrid_fsdp_worker)
    assert out["processes"] == 2 and out["world"] == 4
    assert out["slice_major"] in ([0, 0, 1, 1], [1, 1, 0, 0])
    assert out["n_sharded"] > 0
    assert np.isfinite(out["losses"]).all()
    assert out["losses"][-1] < out["losses"][0]


def test_two_process_groups_stand_in_for_slices(worker_pythonpath):
    """A real 2-process gang: each process's devices form one 'slice'
    (default device_slice_index falls back to process_index); the hybrid
    data axis comes out slice-major across the gang."""
    out = Launcher(np=2, devices_per_proc=2, timeout_s=300).run(_slice_report)
    assert out["shape"] == {"data": 4}
    assert out["slice_of"] in ([0, 0, 1, 1], [1, 1, 0, 0])
