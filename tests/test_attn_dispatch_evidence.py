"""tools/attn_dispatch_evidence.py: structural remat evidence, no chip.

Smoke shapes exercise the mechanism: per-arm lowering, tier report, the
[B,H]-batched attention-dot count, and the ckpt-vs-plain structural delta
(a checkpointed attention must carry exactly 2 extra attention dots per
layer — the recomputed QKᵀ and PV forwards inside the backward).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow   # subprocess bench smoke — the ROADMAP's "benches"
#                     tier-2 class
def test_smoke_arms_and_remat_delta():
    env = dict(os.environ, DDW_BENCH_SMOKE="1", PALLAS_AXON_POOL_IPS="",
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=1",
               PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/attn_dispatch_evidence.py"),
         "--configs", "lm_flash", "--arms", "default,ckpt_force"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    d = json.loads(out.stdout.strip().splitlines()[-1])
    rows = d["configs"]["lm_flash"]
    base, ckpt = rows["default"], rows["ckpt_force"]
    assert "error" not in base and "error" not in ckpt, rows
    # smoke shapes are tiny -> default is the plain tier
    assert base["tier"] == "xla" and ckpt["tier"] == "xla_ckpt"
    depth = 2  # smoke lm config
    # plain: 6 attention dots per layer (2 fwd + 4 bwd)
    assert base["attn_dot_general"] == 6 * depth, base
    # checkpointed backward recomputes the 2 forward dots per layer
    assert ckpt["attn_dot_general"] == base["attn_dot_general"] + 2 * depth
    assert ckpt["dot_general"] > base["dot_general"]
    assert ckpt["no_op_vs_default"] is False
