"""Pallas flash attention + ring attention + TP sharding tests (8-dev CPU mesh;
pallas runs in interpret mode off-TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddw_tpu.utils.compat import shard_map

from ddw_tpu.ops.flash_attention import flash_attention, mha_reference
from ddw_tpu.parallel.ring_attention import ring_attention
from ddw_tpu.parallel.sharding import (
    VIT_TP_RULES,
    make_sharded_train_step,
    shardings_for_params,
)
from ddw_tpu.runtime.mesh import make_mesh, MeshSpec


def _qkv(b=2, h=2, s=256, d=64, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, s, d).astype(np.float32), dtype=dtype)
    return mk(), mk(), mk()


def test_flash_matches_reference():
    q, k, v = _qkv()
    out = flash_attention(q, k, v)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_flash_causal():
    q, k, v = _qkv(s=256)
    out = flash_attention(q, k, v, True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    # causality: output at position 0 must not depend on later keys
    v2 = v.at[:, :, 128:, :].set(0.0)
    out2 = flash_attention(q, k, v2, True)
    np.testing.assert_allclose(np.asarray(out[:, :, :128]), np.asarray(out2[:, :, :128]),
                               rtol=1e-5, atol=1e-5)


def test_flash_bf16():
    q, k, v = _qkv(dtype=jnp.bfloat16)
    out = flash_attention(q, k, v)
    ref = mha_reference(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref, np.float32),
                               rtol=3e-2, atol=3e-2)


def test_flash_gradients():
    q, k, v = _qkv(b=1, h=1, s=128, d=32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_flash_offsets():
    """q_offset/k_offset shift the causal mask to global positions (ring case)."""
    q, k, v = _qkv(s=128)
    # k block globally BEFORE q block: fully visible
    out_past = flash_attention(q, k, v, True, 128, 0)
    ref_full = mha_reference(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out_past), np.asarray(ref_full),
                               rtol=2e-5, atol=2e-5)
    # k block globally AFTER q block: fully masked -> uniform-ish? No: all -inf
    # rows normalize over zero mass; guard returns zeros
    out_future = flash_attention(q, k, v, True, 0, 128)
    assert np.isfinite(np.asarray(out_future)).all()


def test_flash_misaligned_offset_masked_rows_zero():
    """Rows fully masked by a NON-block-aligned offset must emit zeros.

    With k_offset=64 and block_k=128, query rows 0-63 have every key masked but
    the k block kb=0 still passes the block-level visibility check — the kernel
    must not let exp(s - m_new) == 1 give masked keys weight (regression test)."""
    q2, k2, v2 = _qkv(s=256, seed=3)
    out = flash_attention(q2[:, :, :128, :], k2, v2, True, 0, 64)
    arr = np.asarray(out)
    # rows 0-63: zero visible keys -> zeros
    np.testing.assert_array_equal(arr[:, :, :64, :], 0.0)
    # rows 64-127: match reference on the visible prefix
    ref = np.asarray(mha_reference(q2[:, :, :128, :], k2, v2, causal=True,
                                   q_offset=0, k_offset=64))
    np.testing.assert_allclose(arr[:, :, 64:, :], ref[:, :, 64:, :],
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    n_seq = 4
    mesh = make_mesh(MeshSpec((("seq", n_seq),)), devices=jax.devices()[:n_seq])
    b, h, s, d = 2, 2, 64 * n_seq, 32
    rng = np.random.RandomState(1)
    q = rng.randn(b, h, s, d).astype(np.float32)
    k = rng.randn(b, h, s, d).astype(np.float32)
    v = rng.randn(b, h, s, d).astype(np.float32)

    def f(q, k, v):
        return ring_attention(q, k, v, "seq", causal=causal)

    smapped = jax.jit(shard_map(
        f, mesh=mesh,
        in_specs=(P(None, None, "seq", None),) * 3,
        out_specs=P(None, None, "seq", None), check_vma=False))
    out = smapped(q, k, v)
    ref = mha_reference(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_tp_rules_spec_resolution():
    from ddw_tpu.models.registry import build_model
    from ddw_tpu.utils.config import ModelCfg

    model = build_model(ModelCfg(name="vit", num_classes=5, dtype="float32"))
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 32, 32, 3)), train=False)["params"]
    mesh = make_mesh(MeshSpec((("data", 4), ("model", 2))))
    sh = shardings_for_params(params, mesh, VIT_TP_RULES)
    flat = jax.tree_util.tree_flatten_with_path(sh)[0]
    by_key = {"/".join(str(getattr(p, "key", p)) for p in path): s for path, s in flat}
    mlp1 = next(v for k, v in by_key.items() if "mlp/fc1/kernel" in k)
    assert mlp1.spec == P(None, "model")
    attn_q = next(v for k, v in by_key.items() if "attn/query/kernel" in k)
    assert attn_q.spec == P(None, "model", None)
    patch = next(v for k, v in by_key.items() if "patch_embed/kernel" in k)
    assert patch.spec == P()


@pytest.mark.slow  # tier-1 budget (PR 18): TP-in-training keeps tier-1 reps
                   # in test_tp_rules_spec_resolution (rules unit) +
                   # test_fsdp.py::test_fsdp_tp_learns_on_2x4 (composition).
def test_tp_train_step_vit():
    """dp=4 x tp=2 GSPMD train step on ViT: runs, loss drops, params shard."""
    import optax

    from ddw_tpu.models.registry import build_model
    from ddw_tpu.train.step import TrainState
    from ddw_tpu.utils.config import ModelCfg

    mesh = make_mesh(MeshSpec((("data", 4), ("model", 2))))
    model = build_model(ModelCfg(name="vit", num_classes=5, dropout=0.0, dtype="float32"))
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 32, 32, 3)), train=False)["params"]
    tx = optax.adam(1e-3)
    state = TrainState(params, {}, tx.init(params), jnp.zeros((), jnp.int32))
    step = make_sharded_train_step(model, tx, mesh, VIT_TP_RULES)
    state = step.place_state(state)

    # param actually sharded over model axis
    fc1 = state.params["backbone_block0"]["mlp"]["fc1"]["kernel"]
    assert fc1.sharding.spec == P(None, "model")

    rng = np.random.RandomState(0)
    images = jax.device_put(rng.randn(16, 32, 32, 3).astype(np.float32),
                            step.batch_sharding)
    labels = jax.device_put(rng.randint(0, 5, (16,)).astype(np.int32),
                            step.batch_sharding)
    losses = []
    for _ in range(8):
        state, metrics = step(state, images, labels, jax.random.PRNGKey(1))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    # adam moments sharded like their params (rules matched on path suffix)
    mu_fc1 = state.opt_state[0].mu["backbone_block0"]["mlp"]["fc1"]["kernel"]
    assert mu_fc1.sharding.spec == P(None, "model")


def test_flash_gradients_noncausal_and_offsets():
    """Pallas backward == reference backward without causal masking and with
    ring-style global offsets (the cross-shard case)."""
    q, k, v = _qkv(b=2, h=2, s=256, d=32, seed=5)

    # q_offset > k_offset keeps every q row partially visible; rows with ZERO
    # visible keys diverge from the reference by design (its all-masked softmax
    # degenerates to uniform) — that case is pinned by
    # test_flash_gradients_fully_masked_rows_zero instead.
    for kwargs in ({"causal": False}, {"causal": True, "q_offset": 256},
                   {"causal": True, "q_offset": 64, "k_offset": 0}):
        def lf(q, k, v):
            return jnp.sum(flash_attention(q, k, v, kwargs.get("causal", False),
                                           kwargs.get("q_offset", 0),
                                           kwargs.get("k_offset", 0)) ** 2)

        def lr(q, k, v):
            return jnp.sum(mha_reference(q, k, v, kwargs.get("causal", False),
                                         kwargs.get("q_offset", 0),
                                         kwargs.get("k_offset", 0)) ** 2)

        gf = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4, err_msg=str(kwargs))


def test_flash_gradients_bf16_multiblock():
    """bf16 grads across multiple q/k blocks stay close to the f32 reference."""
    q, k, v = _qkv(b=1, h=2, s=384, d=32, seed=7)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))

    def lf(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True).astype(jnp.float32) ** 2)

    def lr(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True) ** 2)

    gf = jax.grad(lf, argnums=(0, 1, 2))(qb, kb, vb)
    gr = jax.grad(lr, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a).astype(np.float32),
                                   np.asarray(b), rtol=0.1, atol=0.1)


def test_flash_gradients_fully_masked_rows_zero():
    """Rows with zero visible keys must get zero dQ (and contribute nothing to
    dK/dV), not NaNs from the masked-softmax residuals."""
    q, k, v = _qkv(s=128, seed=9)

    def lf(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, 0, 64) ** 2)

    gq, gk, gv = jax.grad(lf, argnums=(0, 1, 2))(q, k, v)
    assert np.isfinite(np.asarray(gq)).all()
    assert np.isfinite(np.asarray(gk)).all()
    assert np.isfinite(np.asarray(gv)).all()
    np.testing.assert_array_equal(np.asarray(gq)[:, :, :64, :], 0.0)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gradients_match_full(causal):
    """SP ring backward (with per-hop remat) == full-attention backward."""
    n_seq = 4
    mesh = make_mesh(MeshSpec((("seq", n_seq),)), devices=jax.devices()[:n_seq])
    b, h, s, d = 1, 2, 32 * n_seq, 16
    rng = np.random.RandomState(2)
    q = rng.randn(b, h, s, d).astype(np.float32)
    k = rng.randn(b, h, s, d).astype(np.float32)
    v = rng.randn(b, h, s, d).astype(np.float32)

    def ring_loss(q, k, v):
        out = shard_map(
            lambda q, k, v: ring_attention(q, k, v, "seq", causal=causal),
            mesh=mesh, in_specs=(P(None, None, "seq", None),) * 3,
            out_specs=P(None, None, "seq", None), check_vma=False)(q, k, v)
        return jnp.sum(out ** 2)

    def full_loss(q, k, v):
        return jnp.sum(mha_reference(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal=causal) ** 2)

    gr = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    gf = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gr, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-3, atol=2e-3)


def test_flash_lse_matches_logsumexp():
    """flash_attention_lse's second output == logsumexp of the scaled scores."""
    from ddw_tpu.ops.flash_attention import flash_attention_lse

    q, k, v = _qkv(b=1, h=2, s=256, d=32, seed=4)
    out, lse = flash_attention_lse(q, k, v)
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
    ref_lse = jax.scipy.special.logsumexp(scores, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               rtol=1e-5, atol=1e-5)


def test_flash_lse_split_combine_gradients():
    """Splitting keys in two flash_attention_lse calls and softmax-combining
    them must match full attention in value AND gradients — the exact contract
    ring attention relies on per hop (exercises the lse cotangent path)."""
    from ddw_tpu.ops.flash_attention import flash_attention_lse
    from ddw_tpu.parallel.ring_attention import _combine

    q, k, v = _qkv(b=1, h=1, s=128, d=32, seed=5)
    k2, v2 = jnp.concatenate([k, k], 2), jnp.concatenate([v, v + 1.0], 2)

    def split_loss(q, k2, v2):
        o1, l1 = flash_attention_lse(q, k2[:, :, :128], v2[:, :, :128])
        o2, l2 = flash_attention_lse(q, k2[:, :, 128:], v2[:, :, 128:])
        out, _ = _combine(o1.astype(jnp.float32), l1,
                          o2.astype(jnp.float32), l2)
        return jnp.sum(out ** 2)

    def full_loss(q, k2, v2):
        return jnp.sum(mha_reference(q, k2, v2) ** 2)

    gs = jax.grad(split_loss, argnums=(0, 1, 2))(q, k2, v2)
    gf = jax.grad(full_loss, argnums=(0, 1, 2))(q, k2, v2)
    np.testing.assert_allclose(split_loss(q, k2, v2), full_loss(q, k2, v2),
                               rtol=1e-4)
    for a, b in zip(gs, gf):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_mha_padded_seq():
    """flash_mha(impl='pallas') pads non-block-multiple lengths (ViT's 196) and
    matches the reference on the unpadded region, fwd and grad."""
    from ddw_tpu.ops.flash_attention import flash_mha

    q, k, v = _qkv(b=1, h=2, s=196, d=48, seed=6)
    out = flash_mha(q, k, v, impl="pallas")
    ref = mha_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    gq = jax.grad(lambda q: jnp.sum(flash_mha(q, k, v, impl="pallas") ** 2))(q)
    gr = jax.grad(lambda q: jnp.sum(mha_reference(q, k, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(gq), np.asarray(gr),
                               rtol=1e-4, atol=1e-4)


def test_attention_impl_dispatch_equivalence():
    """Every dispatch arm (xla, xla_ckpt, pallas) computes the same attention
    — out, lse, and grads — so the auto rule can never change results."""
    from ddw_tpu.ops.flash_attention import _attn_impl, flash_mha_lse

    q, k, v = _qkv(b=2, h=2, s=160, d=32, seed=8)
    outs = {}
    for impl in ("xla", "xla_ckpt", "pallas"):
        o, lse = flash_mha_lse(q, k, v, causal=True, impl=impl)
        g = jax.grad(lambda q: jnp.sum(
            flash_mha_lse(q, k, v, causal=True, impl=impl)[0] ** 2))(q)
        outs[impl] = (np.asarray(o), np.asarray(lse), np.asarray(g))
    for impl in ("xla_ckpt", "pallas"):
        for a, b, what in zip(outs["xla"], outs[impl], ("out", "lse", "gq")):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-4,
                                       err_msg=f"{impl} {what}")

    # auto picks by score-matrix footprint
    small = jnp.zeros((1, 1, 128, 16))      # 64 KiB of scores -> plain xla
    big = jnp.zeros((8, 8, 2048, 16))       # 1 GiB -> checkpointed xla
    huge = jnp.zeros((8, 8, 65536, 16))     # 1 TiB -> pallas flash
    assert _attn_impl(small, small, "auto") == "xla"
    assert _attn_impl(big, big, "auto") == "xla_ckpt"
    assert _attn_impl(huge, huge, "auto") == "pallas"
    assert _attn_impl(huge, huge, "xla") == "xla"


def test_vit_flash_mha_matches_flax_attention():
    """FlashMHA (same param layout) must reproduce
    nn.MultiHeadDotProductAttention to tolerance — the ViT swap is a drop-in."""
    import flax.linen as nn

    from ddw_tpu.models.vit import FlashMHA

    b, s, d, heads = 2, 196, 64, 4
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(b, s, d).astype(np.float32))
    mod = FlashMHA(num_heads=heads, dtype=jnp.float32)
    params = mod.init(jax.random.PRNGKey(0), x)
    out = mod.apply(params, x)
    ref_mod = nn.MultiHeadDotProductAttention(num_heads=heads, dtype=jnp.float32,
                                              name=None)
    ref = ref_mod.apply(params, x, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_pallas_arm_matches_full():
    """Ring attention with the Pallas kernel forced per hop (the long-context
    configuration) still matches full attention fwd AND grads — the dispatch
    change must not unpin the kernel-in-ring path."""
    from ddw_tpu.parallel.ring_attention import ring_attention
    from jax.sharding import PartitionSpec as P

    from ddw_tpu.runtime.mesh import make_mesh, MeshSpec

    n = 4
    mesh = make_mesh(MeshSpec((("seq", n),)), devices=jax.devices()[:n])
    q, k, v = _qkv(b=1, h=2, s=32 * n, d=32, seed=11)

    def ring_loss(q, k, v):
        fn = shard_map(
            lambda q, k, v: ring_attention(q, k, v, "seq", causal=True,
                                           impl="pallas"),
            mesh=mesh, in_specs=(P(None, None, "seq", None),) * 3,
            out_specs=P(None, None, "seq", None), check_vma=False)
        return jnp.sum(fn(q, k, v).astype(jnp.float32) ** 2)

    def full_loss(q, k, v):
        return jnp.sum(mha_reference(q, k, v, causal=True
                                     ).astype(jnp.float32) ** 2)

    np.testing.assert_allclose(float(ring_loss(q, k, v)),
                               float(full_loss(q, k, v)), rtol=1e-4)
    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_full):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)
