"""MoE / expert parallelism: routing invariants, EP==dense equivalence, training.

The EP equivalence tests use a capacity factor large enough that no token
drops; routing and combine weights are then identical between the dense path
and the all_to_all expert-parallel path, so outputs must match to float
tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from ddw_tpu.utils.compat import shard_map

from ddw_tpu.models.lm import TransformerLM
from ddw_tpu.models.moe import MoEMlp, top1_routing
from ddw_tpu.runtime.mesh import make_mesh, MeshSpec, DATA_AXIS
from ddw_tpu.train.lm_step import init_lm_state, make_lm_train_step

VOCAB = 32


def moe_lm(expert_axis=None, num_experts=4, cf=8.0):
    return TransformerLM(vocab_size=VOCAB, max_len=64, hidden=32, depth=2,
                         num_heads=2, mlp_dim=64, dropout=0.0,
                         dtype=jnp.float32, num_experts=num_experts,
                         expert_axis=expert_axis, capacity_factor=cf)


def test_top1_routing_invariants():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(64, 4).astype(np.float32))
    dispatch, combine, aux, stats = top1_routing(logits, capacity=64)
    # no drops at full capacity: every token dispatched exactly once
    np.testing.assert_allclose(np.asarray(dispatch.sum((1, 2))), 1.0)
    # combine = gate prob of the chosen expert
    probs = jax.nn.softmax(np.asarray(logits), -1)
    np.testing.assert_allclose(np.asarray(combine.sum((1, 2))),
                               probs.max(-1), rtol=1e-6)
    # each (expert, slot) holds at most one token
    assert float(np.asarray(dispatch.sum(0)).max()) <= 1.0 + 1e-6
    assert np.isfinite(float(aux)) and float(aux) >= 1.0 - 1e-6
    assert float(stats["drop_rate"]) == 0.0

    # tight capacity: overflow tokens get empty dispatch rows, never doubled
    dispatch2, _, _, stats2 = top1_routing(logits, capacity=2)
    per_tok = np.asarray(dispatch2.sum((1, 2)))
    assert set(np.round(per_tok, 6)) <= {0.0, 1.0}
    assert float(np.asarray(dispatch2.sum((0, 2))).max()) <= 2.0 + 1e-6
    # telemetry agrees with the dispatch tensor
    np.testing.assert_allclose(float(stats2["drop_rate"]),
                               1.0 - per_tok.mean(), rtol=1e-6)


def test_no_drop_at_capacity_one_with_balanced_routing():
    """The Switch contract pinned (VERDICT r2 item 7): with perfectly balanced
    routing, capacity factor 1.0 (C = T/E exactly) drops nothing; entropy
    telemetry reads 1.0. A fully collapsed router at cf=1 drops 1 - C/T."""
    t, e = 64, 4
    balanced = jax.nn.one_hot(jnp.arange(t) % e, e) * 10.0  # T/E tokens each
    cap = t // e  # ceil(1.0 * T / E)
    dispatch, _, _, stats = top1_routing(balanced, capacity=cap)
    np.testing.assert_allclose(np.asarray(dispatch.sum((1, 2))), 1.0)
    assert float(stats["drop_rate"]) == 0.0
    np.testing.assert_allclose(float(stats["balance_entropy"]), 1.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats["expert_frac"]), 1.0 / e)

    collapsed = jnp.zeros((t, e)).at[:, 0].set(10.0)  # everyone -> expert 0
    _, _, _, s2 = top1_routing(collapsed, capacity=cap)
    np.testing.assert_allclose(float(s2["drop_rate"]), 1.0 - cap / t, rtol=1e-6)
    assert float(s2["balance_entropy"]) < 0.01


def test_moe_layer_ep_matches_dense():
    """MoEMlp under shard_map(expert axis over 4 devices) == dense MoEMlp,
    same params, tokens sharded over the same axis."""
    n = 4
    mesh = make_mesh(MeshSpec(((DATA_AXIS, n),)), devices=jax.devices()[:n])
    dense = MoEMlp(num_experts=4, mlp_dim=32, capacity_factor=16.0,
                   dtype=jnp.float32, expert_axis=None)
    ep = MoEMlp(num_experts=4, mlp_dim=32, capacity_factor=16.0,
                dtype=jnp.float32, expert_axis=DATA_AXIS)
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 6, 16).astype(np.float32))
    params = dense.init(jax.random.PRNGKey(0), x)["params"]

    ref = dense.apply({"params": params}, x)
    ep_fwd = jax.jit(shard_map(
        lambda p, x: ep.apply({"params": p}, x),
        mesh=mesh, in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS), check_vma=False))
    out = ep_fwd(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_moe_lm_ep_train_step_matches_dense():
    """One DPxEP train step (experts over the data axis) == the same step with
    dense (all-local) experts: same params, grads, metrics."""
    n = 4
    mesh = make_mesh(MeshSpec(((DATA_AXIS, n),)), devices=jax.devices()[:n])
    tx = optax.sgd(1e-1)
    rng = np.random.RandomState(2)
    tokens = rng.randint(0, VOCAB, size=(8, 17)).astype(np.int32)
    inputs, targets = tokens[:, :-1], tokens[:, 1:]

    results = {}
    for name, axis in (("dense", None), ("ep", DATA_AXIS)):
        model = moe_lm(expert_axis=axis)
        state = init_lm_state(model, tx, jax.random.PRNGKey(3))
        step = make_lm_train_step(model, tx, mesh, DATA_AXIS, seq_axis=None,
                                  donate=False)
        new, m = step(state, inputs, targets, jax.random.PRNGKey(4))
        results[name] = (new, m)

    m_d, m_e = results["dense"][1], results["ep"][1]
    # Routing is per-shard under EP (each rank's token block routes
    # independently) but with no drops at cf=8 the expert computation is
    # identical; CE/accuracy must match, aux differs only by shard averaging.
    assert abs(float(m_d["loss"]) - float(m_e["loss"])) < 1e-5
    assert abs(float(m_d["accuracy"]) - float(m_e["accuracy"])) < 1e-6
    for a, b in zip(jax.tree.leaves(results["dense"][0].params),
                    jax.tree.leaves(results["ep"][0].params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # tier-1 budget (PR 16): MoE train math keeps its tier-1
#                    pin in test_moe_lm_ep_train_step_matches_dense (+ the
#                    top2 EP-vs-dense arm); this learning soak rides tier-2
#                    with test_top2_lm_trains_and_validates
def test_moe_lm_learns():
    """A few MoE LM steps memorize a repeating pattern; aux loss stays near 1
    (balanced) rather than collapsing to one expert."""
    n = 4
    mesh = make_mesh(MeshSpec(((DATA_AXIS, n),)), devices=jax.devices()[:n])
    model = moe_lm(expert_axis=DATA_AXIS, cf=2.0)
    tx = optax.adam(5e-3)
    state = init_lm_state(model, tx, jax.random.PRNGKey(0))
    step = make_lm_train_step(model, tx, mesh, DATA_AXIS, seq_axis=None)

    seq = np.tile(np.arange(16, dtype=np.int32) % VOCAB, (8, 1))
    inputs, targets = seq[:, :-1][:, :12], seq[:, 1:][:, :12]
    first = None
    for i in range(30):
        state, metrics = step(state, inputs, targets, jax.random.PRNGKey(i))
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first / 3
    assert float(metrics["aux_loss"]) < 2.5  # not collapsed (1.0 = perfect)


def test_moe_expert_axis_must_divide():
    n = 4
    mesh = make_mesh(MeshSpec(((DATA_AXIS, n),)), devices=jax.devices()[:n])
    ep = MoEMlp(num_experts=6, mlp_dim=16, dtype=jnp.float32,
                expert_axis=DATA_AXIS)
    x = jnp.zeros((4, 2, 8), jnp.float32)
    params = MoEMlp(num_experts=6, mlp_dim=16, dtype=jnp.float32).init(
        jax.random.PRNGKey(0), x)["params"]
    fwd = jax.jit(shard_map(
        lambda p, x: ep.apply({"params": p}, x),
        mesh=mesh, in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS), check_vma=False))
    with pytest.raises(ValueError, match="not divisible"):
        fwd(params, x)


def test_moe_step_rejects_foreign_expert_axis():
    mesh = make_mesh(MeshSpec(((DATA_AXIS, 2),)), devices=jax.devices()[:2])
    model = moe_lm(expert_axis="nonexistent")
    with pytest.raises(ValueError, match="expert_axis"):
        make_lm_train_step(model, optax.adam(1e-3), mesh, DATA_AXIS,
                           seq_axis=None)


@pytest.mark.slow  # ~9s; tier-1 reps: test_moe_lm_ep_train_step_matches_dense
# (moe train math)
# + test_lm.py::test_decode_path_matches_full_forward (decode identity)
def test_moe_decode_path_matches_full_forward():
    """KV-cached decode of an MoE LM (dense experts, per-call routing) ==
    full-sequence forward at no-drop capacity — prefill and per-token both."""
    from ddw_tpu.models.lm import init_cache

    model = TransformerLM(vocab_size=VOCAB, max_len=64, hidden=32, depth=2,
                          num_heads=2, mlp_dim=64, dropout=0.0,
                          dtype=jnp.float32, num_experts=4,
                          capacity_factor=8.0)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, VOCAB, size=(2, 12)).astype(np.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    full = model.apply({"params": params}, tokens)

    dm = model.clone(decode=True, seq_axis=None)
    cache = init_cache(dm, 2)
    prefill, vars_ = dm.apply({"params": params, "cache": cache}, tokens,
                              mutable=["cache"])
    np.testing.assert_allclose(np.asarray(prefill), np.asarray(full),
                               rtol=1e-5, atol=1e-5)

    cache = init_cache(dm, 2)
    outs = []
    for t in range(12):
        lg, vars_ = dm.apply({"params": params, "cache": cache},
                             tokens[:, t:t + 1], mutable=["cache"])
        cache = vars_["cache"]
        outs.append(lg[:, 0])
    np.testing.assert_allclose(np.asarray(jnp.stack(outs, 1)),
                               np.asarray(full), rtol=1e-5, atol=1e-5)


def test_top2_routing_invariants():
    """Ample capacity: every token reaches exactly its two top experts with
    renormalized gates summing to 1; capacity pressure drops second choices
    after first choices claimed their slots."""
    from ddw_tpu.models.moe import top2_routing

    rng = np.random.RandomState(0)
    t, e, cap = 12, 4, 12
    logits = jnp.asarray(rng.randn(t, e).astype(np.float32) * 2)
    dispatch, combine, aux, stats = top2_routing(logits, cap)
    assert dispatch.shape == combine.shape == (t, e, cap)
    # two dispatch slots per token, combine mass 1 per token
    np.testing.assert_allclose(np.asarray(dispatch.sum((1, 2))),
                               np.full(t, 2.0), atol=1e-6)
    np.testing.assert_allclose(np.asarray(combine.sum((1, 2))),
                               np.ones(t), atol=1e-6)
    assert float(stats["drop_rate"]) == 0.0
    # the two chosen experts match top_k of the softmax
    probs = jax.nn.softmax(logits, -1)
    top2 = np.asarray(jax.lax.top_k(probs, 2)[1])
    got = np.asarray(dispatch.sum(-1))  # [T, E] 0/1
    for i in range(t):
        assert set(np.nonzero(got[i])[0]) == set(top2[i])
    # no expert queue exceeds its claimed count; per-slot uniqueness
    assert np.all(np.asarray(dispatch.sum((0, 2))) <= cap + 1e-6)
    assert np.all(np.asarray(dispatch.sum(0)) <= 1.0 + 1e-6)

    # capacity 1: each expert serves one slot; first choices outrank second
    d1, c1, _, s1 = top2_routing(logits, 1)
    assert float(s1["drop_rate"]) > 0
    assert np.all(np.asarray(d1.sum((0, 2))) <= 1.0 + 1e-6)


def test_top2_moe_lm_ep_matches_dense():
    """The EP all_to_all path is router-agnostic: top2 EP == top2 dense."""
    n = 4
    mesh = make_mesh(MeshSpec(((DATA_AXIS, n),)), devices=jax.devices()[:n])
    dense = MoEMlp(num_experts=4, mlp_dim=32, capacity_factor=16.0,
                   dtype=jnp.float32, expert_axis=None, router="top2")
    ep = MoEMlp(num_experts=4, mlp_dim=32, capacity_factor=16.0,
                dtype=jnp.float32, expert_axis=DATA_AXIS, router="top2")
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(8, 6, 16).astype(np.float32))
    params = dense.init(jax.random.PRNGKey(0), x)["params"]
    ref = dense.apply({"params": params}, x)
    ep_fwd = jax.jit(shard_map(
        lambda p, x: ep.apply({"params": p}, x),
        mesh=mesh, in_specs=(P(), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS), check_vma=False))
    np.testing.assert_allclose(np.asarray(ep_fwd(params, x)),
                               np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.slow  # ~8s; top2 keeps tier-1 reps in routing invariants +
#                    EP-matches-dense, the MoE train-math pin in
#                    test_moe_lm_ep_train_step_matches_dense
def test_top2_lm_trains_and_validates():
    model = TransformerLM(vocab_size=VOCAB, max_len=64, hidden=32, depth=2,
                          num_heads=2, mlp_dim=64, dropout=0.0,
                          dtype=jnp.float32, num_experts=4,
                          capacity_factor=2.0, moe_router="top2")
    mesh = make_mesh(MeshSpec(((DATA_AXIS, -1),)))
    state = init_lm_state(model, optax.adam(3e-3), jax.random.PRNGKey(0))
    step = make_lm_train_step(model, optax.adam(3e-3), mesh, DATA_AXIS,
                              seq_axis=None, donate=False)
    rng = np.random.RandomState(3)
    start = rng.randint(0, VOCAB, (8, 1))
    toks = jnp.asarray((start + np.arange(17)) % VOCAB)
    first = last = None
    for i in range(40):
        state, m = step(state, toks[:, :-1], toks[:, 1:], jax.random.PRNGKey(i))
        first = first or float(m["loss"])
        last = float(m["loss"])
    assert last < 0.7 * first, (first, last)

    with pytest.raises(ValueError, match="unknown router"):
        MoEMlp(num_experts=4, mlp_dim=8, router="top3").init(
            jax.random.PRNGKey(0), jnp.zeros((1, 2, 8)))
    with pytest.raises(ValueError, match="at least 2 experts"):
        MoEMlp(num_experts=1, mlp_dim=8, router="top2").init(
            jax.random.PRNGKey(0), jnp.zeros((1, 2, 8)))
