"""Pipeline parallelism: PP == single-device equivalence, training, DPxPP.

The strongest check: the GPipe schedule over 4 stages with stacked stage
params must produce the SAME loss and the SAME per-parameter gradients/updates
as the plain single-device TransformerLM with the corresponding unstacked
params — microbatching + masking + ppermute hops are pure plumbing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ddw_tpu.models.lm import TransformerLM
import pytest

from ddw_tpu.parallel.pipeline import (
    bubble_fraction,
    init_pp_state,
    lm_params_from_pp,
    make_pp_lm_train_step,
    pp_params_from_lm,
)
from ddw_tpu.runtime.mesh import make_mesh, MeshSpec, DATA_AXIS
from ddw_tpu.train.lm_step import init_lm_state, make_lm_train_step

VOCAB = 32


def tiny_lm(depth=4):
    return TransformerLM(vocab_size=VOCAB, max_len=64, hidden=32, depth=depth,
                         num_heads=2, mlp_dim=64, dropout=0.0,
                         dtype=jnp.float32)


def _batch(rng, b, s):
    tokens = rng.randint(0, VOCAB, size=(b, s + 1)).astype(np.int32)
    return tokens[:, :-1], tokens[:, 1:]


def test_pp_params_roundtrip():
    model = tiny_lm(depth=4)
    base = init_lm_state(model, optax.sgd(0.1), jax.random.PRNGKey(0))
    pp = pp_params_from_lm(base.params, 4, 4)
    back = lm_params_from_pp(pp, 4, 4)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 base.params, back)


@pytest.mark.parametrize("schedule,m,v", [
    # microbatch scaling: gpipe m=2 is the tier-1 equivalence rep; the
    # scaling sweep AND the interleaved arms ride in the slow tier (the
    # interleaved schedule keeps tier-1 layout/bubble coverage below)
    ("gpipe", 2, 1),
    pytest.param("gpipe", 4, 1, marks=pytest.mark.slow),
    pytest.param("gpipe", 8, 1, marks=pytest.mark.slow),
    pytest.param("interleaved", 2, 2, marks=pytest.mark.slow),
    pytest.param("interleaved", 4, 2, marks=pytest.mark.slow),
])
def test_pp_train_step_matches_single_device(schedule, m, v):
    """One pipelined step == one plain DP=1 step: identical loss, accuracy,
    and updated params — across microbatch counts (m in {2,4,8}, GPipe) and
    the interleaved virtual-stage schedule. Microbatching + masking +
    ppermute hops are pure plumbing whatever the schedule."""
    n = 4
    mesh_pp = make_mesh(MeshSpec((("pipe", n),)), devices=jax.devices()[:n])
    mesh_1 = make_mesh(MeshSpec(((DATA_AXIS, 1),)), devices=jax.devices()[:1])
    model = tiny_lm(depth=8)
    tx = optax.sgd(1e-1)
    rng = np.random.RandomState(0)
    inputs, targets = _batch(rng, b=8, s=16)

    ref_state = init_lm_state(model, tx, jax.random.PRNGKey(1))
    ref_step = make_lm_train_step(model, tx, mesh_1, DATA_AXIS, seq_axis=None,
                                  donate=False)
    ref_new, ref_m = ref_step(ref_state, inputs, targets, jax.random.PRNGKey(2))

    pp_state = init_pp_state(model, tx, mesh_pp, jax.random.PRNGKey(1),
                             virtual_stages=v)
    step = make_pp_lm_train_step(model, tx, mesh_pp, num_microbatches=m,
                                 donate=False, schedule=schedule,
                                 virtual_stages=v)
    pp_state = step.place_state(pp_state)
    pp_new, pp_m = step(pp_state, inputs, targets)

    assert abs(float(pp_m["loss"]) - float(ref_m["loss"])) < 1e-5
    assert abs(float(pp_m["accuracy"]) - float(ref_m["accuracy"])) < 1e-6
    assert float(pp_m["pp_bubble_fraction"]) == pytest.approx(
        bubble_fraction(n, m, v))
    got = lm_params_from_pp(jax.device_get(pp_new.params), n, model.depth, v)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        got, jax.device_get(ref_new.params))


def test_interleaved_roundtrip_and_layout():
    """[v, n, bpc, ...] round-robin chunk layout round-trips exactly, and the
    placed stage leaves shard P(None, 'pipe')."""
    n, v = 4, 2
    model = tiny_lm(depth=8)
    base = init_lm_state(model, optax.sgd(0.1), jax.random.PRNGKey(0))
    pp = pp_params_from_lm(base.params, n, 8, virtual_stages=v)
    back = lm_params_from_pp(pp, n, 8, virtual_stages=v)
    jax.tree.map(lambda a, b: np.testing.assert_array_equal(a, b),
                 base.params, back)

    mesh = make_mesh(MeshSpec((("pipe", n),)), devices=jax.devices()[:n])
    tx = optax.adam(1e-3)
    state = init_pp_state(model, tx, mesh, jax.random.PRNGKey(0),
                          virtual_stages=v)
    step = make_pp_lm_train_step(model, tx, mesh, num_microbatches=2,
                                 donate=False, schedule="interleaved",
                                 virtual_stages=v)
    state = step.place_state(state)
    leaf = jax.tree.leaves(state.params["stages"])[0]
    assert leaf.sharding.spec == jax.sharding.PartitionSpec(None, "pipe")


def test_interleaved_bubble_smaller_and_refusals():
    """The interleaved schedule's analytic bubble beats GPipe's at equal m;
    m > n and schedule typos refuse loudly."""
    assert bubble_fraction(4, 4, 2) == pytest.approx(3 / 11)
    assert bubble_fraction(4, 4, 1) == pytest.approx(3 / 7)
    assert bubble_fraction(4, 4, 2) < bubble_fraction(4, 4, 1)
    # more virtual stages -> smaller bubble, monotonically
    assert (bubble_fraction(4, 4, 4) < bubble_fraction(4, 4, 2)
            < bubble_fraction(4, 4, 1))

    n = 4
    mesh = make_mesh(MeshSpec((("pipe", n),)), devices=jax.devices()[:n])
    model = tiny_lm(depth=8)
    tx = optax.sgd(0.1)
    with pytest.raises(ValueError, match="stall-free"):
        make_pp_lm_train_step(model, tx, mesh, num_microbatches=8,
                              schedule="interleaved", virtual_stages=2)
    with pytest.raises(ValueError, match="schedule"):
        make_pp_lm_train_step(model, tx, mesh, schedule="1f1b")
    with pytest.raises(ValueError, match="virtual_stages"):
        make_pp_lm_train_step(model, tx, mesh, num_microbatches=2,
                              schedule="interleaved", virtual_stages=3)
    # the analytic helper shares the constructor's validity domain
    with pytest.raises(ValueError, match="stall-free"):
        bubble_fraction(4, 20, 2)
    # a v=1 state fed to an interleaved step refuses at placement, not with
    # an opaque sharding error deep inside the schedule
    state_v1 = init_pp_state(model, tx, mesh, jax.random.PRNGKey(0))
    istep = make_pp_lm_train_step(model, tx, mesh, num_microbatches=2,
                                  schedule="interleaved", virtual_stages=2)
    with pytest.raises(ValueError, match="layout mismatch"):
        istep.place_state(state_v1)


def test_pp_stage_params_actually_sharded():
    n = 4
    mesh = make_mesh(MeshSpec((("pipe", n),)), devices=jax.devices()[:n])
    model = tiny_lm(depth=4)
    tx = optax.adam(1e-3)
    state = init_pp_state(model, tx, mesh, jax.random.PRNGKey(0))
    step = make_pp_lm_train_step(model, tx, mesh, donate=False)
    state = step.place_state(state)
    leaf = jax.tree.leaves(state.params["stages"])[0]
    assert leaf.sharding.spec == jax.sharding.PartitionSpec("pipe")
    emb = jax.tree.leaves(state.params["embed"])[0]
    assert emb.sharding.spec == jax.sharding.PartitionSpec()


def test_pp_learns_fixed_sequence():
    n = 4
    mesh = make_mesh(MeshSpec((("pipe", n),)), devices=jax.devices()[:n])
    model = tiny_lm(depth=4)
    tx = optax.adam(5e-3)
    state = init_pp_state(model, tx, mesh, jax.random.PRNGKey(0))
    step = make_pp_lm_train_step(model, tx, mesh, num_microbatches=2)
    state = step.place_state(state)

    seq = np.tile(np.arange(16, dtype=np.int32) % VOCAB, (4, 1))
    inputs, targets = seq[:, :-1][:, :12], seq[:, 1:][:, :12]
    first = None
    for _ in range(30):
        state, metrics = step(state, inputs, targets)
        if first is None:
            first = float(metrics["loss"])
    assert float(metrics["loss"]) < first / 3
    assert float(metrics["accuracy"]) > 0.9


@pytest.mark.slow   # tier-1 budget (PR 16): pp-matches-single-device stays
#                     tier-1 above (both schedules) and dp averaging keeps
#                     test_lm.py's dpxsp-vs-pure-dp pin; the dp x pp
#                     COMPOSITION rides tier-2 like the rope-pp arm
def test_dp_x_pp_matches_pure_pp():
    """(data=2, pipe=4) == (pipe=4) on the same global batch: DP replicas of
    the pipeline average to the same gradients."""
    devs = jax.devices()
    mesh_dpp = make_mesh(MeshSpec(((DATA_AXIS, 2), ("pipe", 4))),
                         devices=devs[:8])
    mesh_pp = make_mesh(MeshSpec((("pipe", 4),)), devices=devs[:4])
    model = tiny_lm(depth=4)
    tx = optax.sgd(1e-1)
    rng = np.random.RandomState(3)
    inputs, targets = _batch(rng, b=8, s=16)

    s1 = init_pp_state(model, tx, mesh_pp, jax.random.PRNGKey(1))
    st1 = make_pp_lm_train_step(model, tx, mesh_pp, num_microbatches=2,
                                donate=False)
    s1 = st1.place_state(s1)
    n1, m1 = st1(s1, inputs, targets)

    s2 = init_pp_state(model, tx, mesh_dpp, jax.random.PRNGKey(1))
    st2 = make_pp_lm_train_step(model, tx, mesh_dpp, data_axis=DATA_AXIS,
                                num_microbatches=2, donate=False)
    s2 = st2.place_state(s2)
    n2, m2 = st2(s2, inputs, targets)

    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        jax.device_get(n1.params), jax.device_get(n2.params))


def test_pp_moe_dense_experts_aux_loss():
    """MoE under PP (dense experts): the Switch aux loss flows into training
    and is reported; an expert_axis is rejected up front."""
    import pytest

    n = 4
    mesh = make_mesh(MeshSpec((("pipe", n),)), devices=jax.devices()[:n])
    model = TransformerLM(vocab_size=VOCAB, max_len=64, hidden=32, depth=4,
                          num_heads=2, mlp_dim=64, dropout=0.0,
                          dtype=jnp.float32, num_experts=4,
                          capacity_factor=4.0)
    tx = optax.adam(1e-3)
    state = init_pp_state(model, tx, mesh, jax.random.PRNGKey(0))
    step = make_pp_lm_train_step(model, tx, mesh, num_microbatches=2,
                                 donate=False)
    state = step.place_state(state)
    rng = np.random.RandomState(5)
    inputs, targets = _batch(rng, b=4, s=12)
    state, metrics = step(state, inputs, targets)
    assert np.isfinite(float(metrics["loss"]))
    assert float(metrics["aux_loss"]) >= 1.0 - 1e-5  # Switch aux lower bound

    ep_model = model.clone(expert_axis="data")
    with pytest.raises(ValueError, match="expert parallelism"):
        make_pp_lm_train_step(ep_model, tx, mesh)


def test_pp_batch_divisibility_error():
    import pytest

    n = 4
    mesh = make_mesh(MeshSpec((("pipe", n),)), devices=jax.devices()[:n])
    model = tiny_lm(depth=4)
    tx = optax.sgd(0.1)
    state = init_pp_state(model, tx, mesh, jax.random.PRNGKey(0))
    step = make_pp_lm_train_step(model, tx, mesh, num_microbatches=4,
                                 donate=False)
    state = step.place_state(state)
    rng = np.random.RandomState(6)
    inputs, targets = _batch(rng, b=6, s=12)  # 6 % 4 != 0
    with pytest.raises(ValueError, match="num_microbatches"):
        step(state, inputs, targets)
