"""Fleet-wide prefix cache (ddw_tpu.gateway.prefix_index + cache-aware
routing + warm replay) and live-row bucketed decode.

The acceptance pins, all deterministic on CPU:

1. **index units over fakes** — register/evict/holder-loss/reset feeds
   update holders correctly; token prefixes survive total holder loss
   (that is what warm replay restores); the hot list dedups covered
   prefixes and the key bound drops the coldest entries;
2. **routing picks the holder until projected wait flips it** — over
   scripted load() fakes: equal wait routes to the longest-prefix holder
   (``routed_cache_hit``), piling wait onto the holder flips the route to
   a cold sibling (``routed_wait_override``);
3. **bit-identity is routing-independent** — routed answers AND a forced
   cold generate on the non-holder reproduce the sequential path
   bit-for-bit (routing changes WHERE a request runs, never WHAT it
   computes);
4. **live-row bucketed decode** — staggered admissions/evictions on one
   engine dispatch pow2 row buckets (``decode_rows_skipped`` > 0, bucket
   within the ladder) and stay token-identical to both the sequential
   path and the same engine re-run with ``decode_buckets`` off (the
   full-``max_resident`` path). Preemption identity under buckets rides
   the existing overcommit drills in tests/test_paged_kv.py, which now
   run with the bucketed default;
5. **recycle warm replay** — after shared-prefix traffic, a drained+
   restarted replica rejoins holding a non-empty prefix cache
   (``warm_replays`` > 0) and serves the hot prompt with prefix hits from
   its first request. The process-replica variant (child pools followed
   over the ``/v1/prefix/events`` relay, recycle = full respawn) rides
   tier-2.

Tier-1 cost discipline: the pure index/routing tests never touch jax; the
jax tests share ONE module-scoped package and ONE 2-replica thread fleet
(the recycle drill restarts in place, keeping compiled programs).
"""

import concurrent.futures
import time

import jax
import numpy as np
import pytest

from ddw_tpu.gateway import (
    Gateway,
    GatewayClient,
    PrefixIndex,
    ReplicaSet,
    ReplicaSupervisor,
    chain_hash_hexes,
)
from ddw_tpu.serve import EngineCfg, ServingEngine
from ddw_tpu.serve.metrics import EngineMetrics
from ddw_tpu.serving.lm_package import load_lm_package, save_lm_package
from ddw_tpu.utils.config import LMCfg

VOCAB = 64


def _reg(key, toks):
    return ["register", key, list(toks)]


def _ev(key):
    return ["evict", key]


def _feed(seq, *events, reset=False):
    return {"seq": seq, "reset": reset, "events": list(events)}


# -- index units over scripted feeds (pure) ----------------------------------

def test_chain_hash_hexes_prefix_property():
    """The helper's hashes chain: a longer prompt's per-block keys extend
    the shorter prefix's keys unchanged — the property the whole index
    keys on."""
    short, long = list(range(1, 9)), list(range(1, 17))
    hs, hl = chain_hash_hexes(short, 4), chain_hash_hexes(long, 4)
    assert len(hs) == 2 and len(hl) == 4
    assert hl[:2] == hs
    # int32 content-addressed: same tokens, same keys, different run
    assert chain_hash_hexes(np.asarray(long, np.int32), 4) == hl
    # a single diverging token changes every key from its block on
    div = list(long)
    div[5] = 63
    hd = chain_hash_hexes(div, 4)
    assert hd[0] == hl[0] and all(a != b for a, b in zip(hd[1:], hl[1:]))


def test_index_register_evict_holder_loss_reset():
    idx = PrefixIndex(hot_k=4)
    toks = [1, 2, 3, 4]
    key = chain_hash_hexes(toks, 4)[0]
    idx.observe(0, _feed(1, _reg(key, toks)))
    idx.observe(1, _feed(1, _reg(key, toks)))
    assert idx.match([1, 2, 3, 4, 9], count_hit=False) == {0: 4, 1: 4}
    # savings are capped at p-1: the pool always prefills one real token
    assert idx.match(toks, count_hit=False) == {0: 3, 1: 3}
    # one holder evicts: the other keeps serving the key
    idx.observe(0, _feed(2, _ev(key)))
    assert idx.match([1, 2, 3, 4, 9], count_hit=False) == {1: 4}
    # TOTAL holder loss: no routing match, but the tokens survive — that
    # is exactly what warm replay restores into a recycled replica
    idx.observe(1, _feed(2, _ev(key)))
    assert idx.match([1, 2, 3, 4, 9], count_hit=False) == {}
    assert idx.hot() == [toks]
    # a reset feed replaces everything believed about the slot
    toks_b = [7, 8, 9, 10]
    key_b = chain_hash_hexes(toks_b, 4)[0]
    idx.observe(0, _feed(1, _reg(key_b, toks_b), reset=True))
    assert idx.match([7, 8, 9, 10, 1], count_hit=False) == {0: 4}
    assert idx.summary()["keys"] == 2
    # drop_replica forgets holdings (replacement replica starts cold)
    idx.drop_replica(0)
    assert idx.match([7, 8, 9, 10, 1], count_hit=False) == {}


def test_index_hot_list_dedup_hit_order_and_bound():
    # a two-block chain collapses to its longest retained prefix
    idx = PrefixIndex(hot_k=8)
    long = list(range(1, 9))
    hexes = chain_hash_hexes(long, 4)
    idx.observe(0, _feed(2, _reg(hexes[0], long[:4]), _reg(hexes[1], long)))
    assert idx.hot() == [long]
    # match credits reorder the hot list: the chased prefix rises
    idx2 = PrefixIndex(hot_k=8)
    a, b = [1, 2, 3, 4], [5, 6, 7, 8]
    idx2.observe(0, _feed(2, _reg(chain_hash_hexes(a, 4)[0], a),
                          _reg(chain_hash_hexes(b, 4)[0], b)))
    for _ in range(3):
        idx2.match([5, 6, 7, 8, 1])
    assert idx2.hot()[0] == b
    # bounded: past MAX_KEYS the coldest (fewest hits, oldest) key drops
    idx2.MAX_KEYS = 2
    c = [9, 10, 11, 12]
    idx2.observe(0, _feed(3, _reg(chain_hash_hexes(c, 4)[0], c)))
    assert idx2.summary()["keys"] == 2
    assert a not in idx2.hot()
    assert idx2.match([1, 2, 3, 4, 9], count_hit=False) == {}


def test_index_summary_shape():
    idx = PrefixIndex(hot_k=2)
    toks = list(range(1, 9))
    hexes = chain_hash_hexes(toks, 4)
    idx.observe(1, _feed(2, _reg(hexes[0], toks[:4]), _reg(hexes[1], toks)))
    s = idx.summary()
    assert s["keys"] == 2 and s["block_size"] == 4
    assert s["holders"] == {"1": 2}
    assert len(s["hot"]) == 2
    assert {"key", "tokens", "hits", "holders"} <= set(s["hot"][0])


# -- cache-aware routing over scripted load() fakes (pure) --------------------

class _FakeLoadEngine:
    """Replica with a scriptable load() — depth/service/prefill EWMAs are
    set by the test, so the routing arithmetic is exact."""

    def __init__(self, depth=0, service_ms=10.0, prefill_token_ms=1.0):
        self.depth = depth
        self.service_ms = service_ms
        self.prefill_token_ms = prefill_token_ms
        self.metrics = EngineMetrics()
        self.futures = []

    def start(self):
        return self

    def stop(self):
        pass

    def load(self):
        return {"depth": self.depth, "busy": 0,
                "service_ms": self.service_ms,
                "prefill_token_ms": self.prefill_token_ms}

    def submit_generate(self, prompt, num_steps, **kw):
        f = concurrent.futures.Future()
        self.futures.append(f)
        return f


def test_routing_picks_holder_until_wait_flips():
    cold, warm = _FakeLoadEngine(), _FakeLoadEngine()
    rs = ReplicaSet([cold, warm])
    toks = list(range(1, 9))
    hexes = chain_hash_hexes(toks, 4)
    rs.prefix_index.observe(
        1, _feed(2, _reg(hexes[0], toks[:4]), _reg(hexes[1], toks)))
    prompt = toks + [42]
    # equal projected wait: the 8-token holder wins on the prefill credit
    fut = rs.submit_generate(prompt, 4)
    assert fut in warm.futures
    assert warm.metrics.routed_cache_hit == 1
    assert warm.metrics.routed_wait_override == 0
    fut.set_result(None)
    # pile wait onto the holder: 3 deep x 10 ms = 30 ms against an
    # 8-token x 1 ms/token credit — a cold prefill elsewhere is cheaper
    warm.depth = 3
    fut = rs.submit_generate(prompt, 4)
    assert fut in cold.futures
    assert cold.metrics.routed_cache_hit == 0
    assert cold.metrics.routed_wait_override == 1
    fut.set_result(None)
    # an empty index routes purely on projected wait, and non-generate
    # submissions never consult it
    assert rs.outstanding() == [0, 0]


# -- jax fixtures -------------------------------------------------------------

@pytest.fixture(scope="module")
def pm(tmp_path_factory):
    cfg = LMCfg(vocab_size=VOCAB, max_len=96, hidden=32, depth=2,
                num_heads=2, mlp_dim=64, dropout=0.0, dtype="float32")
    from ddw_tpu.models.lm import build_lm

    model = build_lm(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 8), np.int32))["params"]
    out = str(tmp_path_factory.mktemp("fleet_prefix_pkg") / "pkg")
    return load_lm_package(save_lm_package(out, cfg, params))


def _prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, size=(n,)).astype(np.int32)
            for n in lengths]


@pytest.fixture(scope="module")
def fleet(pm):
    """One 2-replica thread fleet shared by the routed-identity, ladder
    and recycle drills (in-place restarts keep compiled programs)."""
    engines = [ServingEngine(lm=pm, cfg=EngineCfg(n_slots=2,
                                                  steps_per_tick=2,
                                                  default_timeout_s=600.0))
               for _ in range(2)]
    rs = ReplicaSet(engines, cooldown_s=30.0)
    rs.prefix_index.poll_interval_s = 0.0   # poll on every submit: the
    #                                         drills below are deterministic
    rs.start()
    yield rs, engines
    rs.stop()


# -- feed + routing + bit-identity over real engines --------------------------

def test_fleet_feed_and_routed_bit_identity(fleet, pm):
    """Traffic teaches the index who holds what (the keys are exactly the
    pool's own chain hashes); the repeat request chases its prefix and the
    answer stays bit-identical to the sequential path."""
    rs, engines = fleet
    (pa,) = _prompts([24], seed=3)
    ref = np.asarray(pm.generate(pa[None, :], 8))[0]
    assert np.array_equal(rs.generate(pa, 8, timeout_s=120.0).tokens, ref)
    assert np.array_equal(rs.generate(pa, 8, timeout_s=120.0).tokens, ref)
    m = rs.prefix_index.match(pa, count_hit=False)
    assert m, "feed never reached the index"
    holder = max(m, key=m.get)
    # index keys ARE the holder pool's full-block hashes (bit-compat pin)
    pool = engines[holder].pool
    hexes = chain_hash_hexes(pa, pool.block_size)
    assert set(hexes) <= {h.hex() for h in pool._full_map}
    assert rs.snapshot()["serve.routed_cache_hit"] >= 1
    # /stats-shaped summary reflects the holdings
    s = rs.prefix_index.summary()
    assert s["keys"] >= 1 and s["block_size"] == pool.block_size
    assert str(holder) in s["holders"]


def test_routed_vs_forced_cold_identity(fleet, pm):
    """Routing changes WHERE, never WHAT: a forced cold generate on the
    non-holder reproduces the routed (warm) answer bit-for-bit."""
    rs, engines = fleet
    (pb,) = _prompts([20], seed=7)
    ref = np.asarray(pm.generate(pb[None, :], 6))[0]
    warm = rs.generate(pb, 6, timeout_s=120.0).tokens
    rs.prefix_index.poll(rs.replicas)   # pick up pb's registration now
    m = rs.prefix_index.match(pb, count_hit=False)
    assert m
    holder = max(m, key=m.get)
    cold = engines[1 - holder].generate(pb, 6, timeout_s=120.0).tokens
    assert np.array_equal(warm, ref)
    assert np.array_equal(cold, ref)


# -- live-row bucketed decode -------------------------------------------------

def test_bucketed_decode_ladder_token_identity(fleet, pm):
    """Staggered admissions/evictions ride the pow2 bucket ladder and stay
    token-identical to the sequential path AND to the same engine re-run
    with buckets off (the always-max_resident path)."""
    rs, engines = fleet
    eng = engines[0]
    pool = eng.pool
    assert pool.decode_buckets
    ladder = pool.resident_ladder()
    assert ladder[-1] == pool.max_resident
    assert all(b & (b - 1) == 0 for b in ladder[:-1])   # pow2 rungs
    assert list(ladder) == sorted(set(ladder))
    prompts = _prompts([8, 12, 16, 20, 24], seed=11)
    refs = [np.asarray(pm.generate(p[None, :], 6))[0] for p in prompts]
    futs = [eng.submit_generate(p, 6) for p in prompts]   # churn: rows
    for f, r in zip(futs, refs):                          # come and go
        assert np.array_equal(f.result(timeout=120).tokens, r)
    assert pool.last_decode_bucket in ladder
    # the control: same engine, buckets off -> always max_resident, same
    # tokens (bucketed decode is a dispatch-shape change, not a math one)
    pool.decode_buckets = False
    try:
        futs = [eng.submit_generate(p, 6) for p in prompts]
        for f, r in zip(futs, refs):
            assert np.array_equal(f.result(timeout=120).tokens, r)
        assert pool.last_decode_bucket == pool.max_resident
    finally:
        pool.decode_buckets = True


@pytest.mark.slow   # tier-1 budget (PR 16): the bucketed-ladder identity
#                     class keeps its tier-1 rep in
#                     test_bucketed_decode_ladder_token_identity above
#                     (same pow2 dispatch arithmetic under live traffic);
#                     this shrink/regrow arithmetic sweep rides tier-2
def test_bucket_ladder_shrinks_and_regrows_deterministically(pm):
    """Pool-level bucket arithmetic across admissions/releases: the tick
    dispatches exactly the smallest pow2 bucket covering the highest live
    row, skips the rest, and regrows as freed rows are recycled."""
    from ddw_tpu.serve.blocks import BlockPool

    pool = BlockPool(pm.model, pm.params, n_blocks=16, block_size=16,
                     max_resident=4, steps_per_tick=1)
    assert tuple(pool.resident_ladder()) == (1, 2, 4)

    def _admit(p):
        r, _hit = pool.admit(p, 4)
        pool.prefill([r], p[None, :], np.array([len(p)], np.int32),
                     np.zeros((1,), np.float32),
                     np.zeros((1, 2), np.uint32))
        pool.register(r, p)
        pool.note_prefilled(r)
        return r

    def _tick():
        out = pool.decode(np.ones((4,), np.int32),
                          np.zeros((4,), np.float32),
                          np.zeros((4, 1, 2), np.uint32))
        assert out.shape == (4, 1)      # engine view never changes shape

    assert [_admit(p) for p in _prompts([17, 18, 19], seed=21)] == [0, 1, 2]
    _tick()
    assert pool.last_decode_bucket == 4         # 3 live rows -> pow2 4
    assert pool.stats["decode_rows_skipped"] == 0
    pool.release(1)
    pool.release(2)
    _tick()                                     # row 0 alone -> bucket 1
    assert pool.last_decode_bucket == 1
    assert pool.stats["decode_rows_skipped"] == 3
    # re-admission recycles the last-freed row (2) and regrows the bucket
    r = _admit(_prompts([20], seed=22)[0])
    assert r == 2
    _tick()
    assert pool.last_decode_bucket == 4
    assert pool.stats["decode_rows_skipped"] == 3   # dense again: no skip


# -- recycle warm replay ------------------------------------------------------

def test_recycle_warm_replay_rejoins_with_warm_cache(fleet, pm):
    """The drill: shared-prefix traffic, then drain+restart replica 0 —
    it must rejoin holding a non-empty prefix cache (warm_replays > 0)
    and serve the hot prompt with prefix hits from its first request."""
    rs, engines = fleet
    e0 = engines[0]
    sup = ReplicaSupervisor(rs, warmup_prompt_lens=(8,), warm_replay_k=4,
                            backoff_base_s=0.05, jitter=0.0)
    (pc,) = _prompts([24], seed=13)
    ref = np.asarray(pm.generate(pc[None, :], 6))[0]
    for _ in range(2):      # traffic teaches the index its hot set
        assert np.array_equal(rs.generate(pc, 6, timeout_s=120.0).tokens,
                              ref)
    assert rs.prefix_index.hot(), "hot set empty before the drill"
    assert sup.recycle(0, kind="drill")
    att = sup.attempts[-1]
    assert att.action == "drained_restarted"
    assert att.readmit == "probed_closed"
    # non-empty prefix cache at rejoin — the acceptance pin
    assert e0.health()["prefix_cache"]["keys"] > 0
    assert rs.snapshot()["serve.warm_replays"] > 0
    # the replayed blocks are REAL: the hot prompt's first post-recycle
    # request on this replica prefills with hits, bit-identically
    hits0 = e0.snapshot()["serve.prefix_hit_tokens"]
    assert np.array_equal(e0.generate(pc, 6, timeout_s=120.0).tokens, ref)
    assert e0.snapshot()["serve.prefix_hit_tokens"] > hits0


# -- process-replica variant (tier-2: two child boots + a respawn) ------------

@pytest.mark.slow
def test_process_fleet_prefix_relay_and_recycle_warm(tmp_path_factory):
    """The same story across process boundaries: the parent's index
    follows child pools over the /v1/prefix/events relay, /stats carries
    the prefix_index summary, and a recycled (respawned) child rejoins
    with a warm, non-empty prefix cache."""
    import optax

    from ddw_tpu.deploy import ProcessReplica
    from ddw_tpu.models.lm import TransformerLM
    from ddw_tpu.train.lm_step import init_lm_state

    cfg = LMCfg(vocab_size=VOCAB, max_len=64, hidden=32, depth=1,
                num_heads=2, mlp_dim=128, dropout=0.0, dtype="float32")
    model = TransformerLM(vocab_size=VOCAB, max_len=64, hidden=32, depth=1,
                          num_heads=2, mlp_dim=128, dropout=0.0,
                          dtype="float32")
    state = init_lm_state(model, optax.sgd(0.0), jax.random.PRNGKey(0))
    out = str(tmp_path_factory.mktemp("fleet_prefix_proc") / "pkg")
    save_lm_package(out, cfg, state.params)
    pkg = load_lm_package(out)
    prompt = list(range(1, 25))
    ref = [int(t) for t in
           np.asarray(pkg.generate(np.asarray(prompt)[None, :], 4))[0]]
    reps = [ProcessReplica(out, replica_id=i,
                           engine_cfg={"n_slots": 2, "kv_block_size": 8,
                                       "default_timeout_s": 600.0},
                           warmup_lens=(4,), spawn_timeout_s=150.0)
            for i in range(2)]
    gw = Gateway(reps, supervisor_kw={"poll_interval_s": 0.1,
                                      "backoff_base_s": 0.1,
                                      "backoff_max_s": 0.5, "jitter": 0.0,
                                      "warm_replay_k": 4})
    gw.start(warmup_prompt_lens=(4,))
    rs = gw.replica_set
    rs.prefix_index.poll_interval_s = 0.0
    cli = GatewayClient("127.0.0.1", gw.port, timeout_s=90.0, max_retries=8)
    try:
        for _ in range(4):
            assert cli.generate(prompt, 4)["tokens"] == ref
        deadline = time.monotonic() + 30.0
        while (not rs.prefix_index.match(prompt, count_hit=False)
               and time.monotonic() < deadline):
            cli.generate(prompt, 4)     # each submit polls the relay
            time.sleep(0.1)
        assert rs.prefix_index.match(prompt, count_hit=False), \
            "relay never fed the parent index"
        stats = cli.stats()
        assert stats["prefix_index"]["keys"] >= 1
        assert stats["prefix_index"]["holders"]
        # recycle = SIGTERM + respawn; warm replay runs against the new
        # child before the shadow probe readmits it
        assert gw.supervisor.recycle(0, kind="drill")
        # the parent's child-health cache (0.2s) may still hold a
        # pre-replay snapshot right after recycle returns — let it lapse
        deadline = time.monotonic() + 10.0
        h0 = rs.fleet_health()[0]
        while (h0.get("prefix_cache", {}).get("keys", 0) == 0
               and time.monotonic() < deadline):
            time.sleep(0.2)
            h0 = rs.fleet_health()[0]
        assert h0["state"] == "alive" and h0["circuit"] == "closed"
        assert h0["prefix_cache"]["keys"] > 0
        assert rs.snapshot()["serve.warm_replays"] > 0
        assert cli.generate(prompt, 4)["tokens"] == ref
    finally:
        gw.drain(grace_s=10.0)
