"""KV block migration (serve/blocks.py wire format) + disaggregated
prefill/decode (EngineCfg.role, the router's TTFT-aware splitter).

The acceptance pins, all deterministic on the 8-fake-CPU-device backend:

- **wire round-trip is bit-exact**: export → import into a cold pool →
  re-export reproduces the ORIGINAL wire byte-for-byte (base64 payload
  equality IS K/V byte identity), fuzzed across block-boundary prompt
  lengths; a second import dedupes (``skipped``), and sub-block prompts
  export ``None`` (nothing worth migrating);
- **the prefix directory names skip blocks**: ``skip_hashes`` ships a
  warm prefix hash-only (``start_block`` > 0, shorter payload) and the
  receiver — already holding that prefix — lands only the tail, after
  which its re-export matches the donor's full wire;
- **rejection is atomic**: version / block-size / geometry / hash-chain /
  truncation defects each raise a structured ``KVWireError`` BEFORE the
  pool changes at all (free blocks, registered hashes, gauges pinned
  before/after), an over-budget import raises ``OutOfBlocks`` equally
  unchanged, and the same pool still lands the clean wire afterwards;
- **equal-tp transfer**: tp=2 → tp=2 round-trips bit-exactly under the
  model-axis mesh, and the SAME wire lands in a tp=1 pool (payloads are
  full-shape; ``tp`` on the wire is advisory) — layout-independence;
- **disaggregation is invisible in tokens**: a prefill-role + decode-role
  ReplicaSet answers bit-identically to the sequential path, greedy AND
  seeded, THROUGH out-of-blocks mid-decode preemption on the decode
  replica and an in-place prefill-replica restart (handoffs resume with
  fresh migrations); the prefill replica never runs a decode tick, warm
  repeats migrate zero blocks, and handoffs / kv_blocks_migrated /
  kv_bytes_migrated / handoff_ms flow through the fleet snapshot;
- **role config + match(with_hashes)**: structured EngineCfg.role errors
  at construction; PrefixIndex.match returns the chain-hex transfer
  directory alongside matches (pure, no jax).

Tier-1 cost discipline: pool-level tests pad suffix prefills to ONE
shape (one compiled program per pool), the disagg drills share one
module-scoped 2-replica fleet, and the process-level disagg chaos drill
(supervisor restart of a crashed prefill replica under DDW_FAULT) rides
tools/load_gen.py --disagg / tier-2 with the other process-fleet boots.
"""

import json

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from ddw_tpu.gateway import PrefixIndex, ReplicaSet, chain_hash_hexes
from ddw_tpu.models.lm import build_lm
from ddw_tpu.runtime.mesh import MODEL_AXIS
from ddw_tpu.serve import BlockPool, EngineCfg, ServingEngine
from ddw_tpu.serve.blocks import KV_WIRE_VERSION, KVWireError, OutOfBlocks
from ddw_tpu.serving.lm_package import load_lm_package, save_lm_package
from ddw_tpu.utils.config import LMCfg

VOCAB = 64
BS = 8          # kv_block_size under test (divides tile = min(256, 96))
PAD = 40        # one suffix-prefill shape for every pool-level seed


def _lm_pkg(out_dir, seed=0):
    cfg = LMCfg(vocab_size=VOCAB, max_len=96, hidden=32, depth=2,
                num_heads=2, mlp_dim=64, dropout=0.0, dtype="float32")
    model = build_lm(cfg)
    params = model.init({"params": jax.random.PRNGKey(seed)},
                        np.zeros((1, 8), np.int32))["params"]
    d = save_lm_package(str(out_dir), cfg, params, quantize=None)
    return load_lm_package(d)


@pytest.fixture(scope="module")
def pm(tmp_path_factory):
    return _lm_pkg(tmp_path_factory.mktemp("kv_mig_pkg") / "pkg")


def _prompts(lengths, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, size=(n,)).astype(np.int32)
            for n in lengths]


def _pool(pm, n_blocks=32, block_size=BS, max_resident=2, mesh=None):
    return BlockPool(pm.model, pm.params, n_blocks=n_blocks,
                     block_size=block_size, max_resident=max_resident,
                     steps_per_tick=1, decode_buckets=False, mesh=mesh)


def _seed(pool, p):
    """Prefill + register + release ``p`` so its full blocks are parked
    registered in the cached LRU — the donor state export reads. One PAD
    shape keeps the whole module on a single compiled prefill program."""
    row, _ = pool.admit(p, 2)
    suf = np.zeros((1, PAD), np.int32)
    suf[0, :len(p)] = p
    pool.prefill([row], suf, np.array([len(p)], np.int32),
                 np.zeros((1,), np.float32), np.zeros((1, 2), np.uint32))
    pool.register(row, p)
    pool.note_prefilled(row)
    pool.release(row)


def _state(pool):
    """The atomicity witness: anything an import could touch."""
    g = pool.gauges()
    return (pool.free_blocks_effective, len(pool._full_map),
            g["blocks_used"], g["blocks_cached"], g["blocks_free"])


# -- wire round-trip ----------------------------------------------------------

def test_wire_roundtrip_fuzz_across_block_boundaries(pm):
    """export → cold import → re-export is byte-identical for prompt
    lengths straddling every block boundary; re-import dedupes."""
    donor = _pool(pm)
    for n, p in zip([BS - 1, BS, BS + 1, 2 * BS, 3 * BS - 1, 3 * BS],
                    _prompts([BS - 1, BS, BS + 1, 2 * BS, 3 * BS - 1,
                              3 * BS], seed=3)):
        _seed(donor, p)
        wire = donor.export_blocks(p)
        full = n // BS
        if full == 0:
            assert wire is None      # sub-block: nothing worth migrating
            continue
        assert wire["version"] == KV_WIRE_VERSION
        assert wire["block_size"] == BS and wire["start_block"] == 0
        assert len(wire["hashes"]) == full == len(wire["payload"])
        assert wire["tokens"] == [int(t) for t in p[:full * BS]]
        recv = _pool(pm)
        res = recv.import_blocks(wire)
        assert res == {"imported": full, "skipped": 0,
                       "bytes": res["bytes"]} and res["bytes"] > 0
        # re-export from the receiver: the SAME wire, byte for byte
        # (base64 payload equality is K/V byte identity)
        assert recv.export_blocks(p) == wire
        # a second import is a pure dedupe — nothing lands twice
        assert recv.import_blocks(wire) == {"imported": 0, "skipped": full,
                                            "bytes": 0}


def test_skip_hashes_ship_warm_prefix_hash_only(pm):
    """The transfer directory's contract: blocks the receiver already
    holds cross the wire as hashes alone, and the landed tail completes
    the chain — the receiver's re-export equals the donor's FULL wire."""
    (p,) = _prompts([3 * BS], seed=5)
    donor = _pool(pm)
    _seed(donor, p)
    full = donor.export_blocks(p)
    assert len(full["payload"]) == 3
    skip = full["hashes"][:1]
    thin = donor.export_blocks(p, skip_hashes=skip)
    assert thin["start_block"] == 1 and len(thin["payload"]) == 2
    assert thin["hashes"] == full["hashes"]   # chain still fully named
    # receiver holds exactly the skipped prefix warm already
    recv = _pool(pm)
    _seed(recv, p[:BS + 1])                   # one full block registered
    res = recv.import_blocks(thin)
    assert res["imported"] == 2 and res["skipped"] == 0
    assert res["bytes"] > 0
    assert recv.export_blocks(p) == full


def test_rejection_is_structured_and_atomic(pm):
    """Every malformed wire raises KVWireError BEFORE the pool changes;
    an over-budget import raises OutOfBlocks equally unchanged; the same
    pool still lands the clean wire afterwards (never poisoned)."""
    (p,) = _prompts([3 * BS], seed=7)
    donor = _pool(pm)
    _seed(donor, p)
    wire = donor.export_blocks(p)
    recv = _pool(pm)

    def corrupt(**mut):
        w = json.loads(json.dumps(wire))   # deep copy, JSON-clean by spec
        w.update(mut)
        return w

    bad_tokens = list(wire["tokens"])
    bad_tokens[BS + 2] ^= 1
    short_leaf = corrupt()
    short_leaf["payload"][1][0] = short_leaf["payload"][1][0][:8]
    thin_row = corrupt()
    thin_row["payload"][0] = thin_row["payload"][0][:-1]
    cases = [
        ("version", corrupt(version=KV_WIRE_VERSION + 1)),
        ("block_size", corrupt(block_size=BS * 2)),
        ("leaf geometry", corrupt(leaves=[[s, d] for s, d in
                                          [( [1, 2, 3], "float32")]])),
        ("chain hash mismatch", corrupt(tokens=bad_tokens)),
        ("token list length", corrupt(tokens=wire["tokens"][:-1])),
        ("truncated payload", corrupt(payload=wire["payload"][:-1])),
        ("truncated leaf payload", short_leaf),
        ("truncated payload row", thin_row),
        ("start_block", corrupt(start_block=7)),
        ("must be a dict", "not-a-wire"),
        ("no chain hashes", corrupt(hashes=[])),
    ]
    before = _state(recv)
    for why, bad in cases:
        with pytest.raises(KVWireError):
            recv.import_blocks(bad)
        assert _state(recv) == before, why
    # over-budget: validation passes, capacity check refuses PRE-landing
    tiny = _pool(pm, n_blocks=2, max_resident=1)
    t_before = _state(tiny)
    with pytest.raises(OutOfBlocks):
        tiny.import_blocks(wire)
    assert _state(tiny) == t_before
    # the receiver was never poisoned: the clean wire still lands whole
    assert recv.import_blocks(wire)["imported"] == 3
    assert recv.export_blocks(p) == wire


def test_equal_tp_roundtrip_and_layout_independence(pm):
    """tp=2 → tp=2 round-trips bit-exactly (per-shard copy under the
    mesh); the SAME wire lands in a tp=1 pool — payloads are full-shape,
    so the wire is layout-independent and ``tp`` is advisory."""
    mesh = Mesh(np.asarray(jax.devices()[:2]), (MODEL_AXIS,))
    (p,) = _prompts([2 * BS], seed=9)
    donor = _pool(pm, n_blocks=8, mesh=mesh)
    _seed(donor, p)
    wire = donor.export_blocks(p)
    assert wire["tp"] == 2
    recv2 = _pool(pm, n_blocks=8, mesh=mesh)
    assert recv2.import_blocks(wire)["imported"] == 2
    assert recv2.export_blocks(p) == wire
    recv1 = _pool(pm, n_blocks=8)
    assert recv1.import_blocks(wire)["imported"] == 2
    out = recv1.export_blocks(p)
    assert out.pop("tp") == 1 and dict(wire, tp=None) == dict(out, tp=None)


# -- role config + transfer directory (pure / cheap) --------------------------

def test_role_validation_messages():
    with pytest.raises(ValueError, match="role must be"):
        EngineCfg(role="draft")
    with pytest.raises(ValueError, match="requires the paged pool"):
        EngineCfg(role="prefill", paged=False)
    with pytest.raises(ValueError, match="requires the paged pool"):
        EngineCfg(role="decode", paged=False)
    assert EngineCfg(role="both", paged=False).role == "both"


def test_match_with_hashes_is_the_transfer_directory():
    """match(with_hashes=True) hands the router matches AND the prompt's
    chain-hex list in one walk — the names kv_export skips by."""
    idx = PrefixIndex(hot_k=4)
    toks = list(range(1, 9))
    hexes = chain_hash_hexes(toks, 4)
    idx.observe(0, {"seq": 1, "reset": False, "events": [
        ["register", hexes[0], toks[:4]], ["register", hexes[1], toks]]})
    m, hx = idx.match(toks + [9], count_hit=False, with_hashes=True)
    assert m == {0: 8} and hx == chain_hash_hexes(toks + [9], 4)
    assert hx[:2] == hexes
    # the matched depth in blocks names exactly the skippable prefix
    assert hx[:m[0] // idx.block_size] == hexes
    # impossible match still shapes the tuple
    assert idx.match([1], count_hit=False, with_hashes=True) == ({}, [])


# -- disaggregated fleet: tokens never change ---------------------------------

@pytest.fixture(scope="module")
def disagg(pm):
    """One prefill-role + one decode-role replica behind the router's
    splitter. The decode replica's pool is deliberately tight with
    overcommit so the preemption drill runs out of blocks mid-decode."""
    P = ServingEngine(lm=pm, cfg=EngineCfg(
        n_slots=2, steps_per_tick=4, role="prefill", kv_block_size=BS,
        decode_buckets=False, default_timeout_s=600.0))
    D = ServingEngine(lm=pm, cfg=EngineCfg(
        n_slots=2, steps_per_tick=4, role="decode", kv_block_size=BS,
        kv_cache_blocks=10, max_resident=4, block_overcommit=3.0,
        decode_buckets=False, default_timeout_s=600.0))
    rs = ReplicaSet([P, D], cooldown_s=30.0)
    rs.prefix_index.poll_interval_s = 0.0
    rs.start()
    yield rs, P, D
    rs.stop()


def test_disagg_greedy_identity_counters_and_warm_skip(disagg, pm):
    """A routed request hands off prefill→decode yet answers exactly the
    sequential path; the prefill replica never decodes; a warm repeat
    re-migrates NOTHING (the directory skipped every full block)."""
    rs, P, D = disagg
    (p,) = _prompts([2 * BS + 4], seed=11)
    ref = np.asarray(pm.generate(p[None, :], 8))[0]
    assert np.array_equal(rs.generate(p, 8, timeout_s=120.0).tokens, ref)
    snap = rs.snapshot()
    assert snap["serve.handoffs"] >= 1
    assert snap["serve.handoff_ms"] > 0
    assert snap["serve.kv_blocks_migrated"] >= 2
    assert snap["serve.kv_bytes_migrated"] > 0
    assert P.snapshot()["serve.decode_ticks"] == 0.0   # a PURE prefiller
    migrated = D.snapshot()["serve.kv_blocks_migrated"]
    assert np.array_equal(rs.generate(p, 8, timeout_s=120.0).tokens, ref)
    assert D.snapshot()["serve.kv_blocks_migrated"] == migrated
    assert rs.snapshot()["serve.handoffs"] >= 2


def test_disagg_seeded_identity_crosses_the_handoff(disagg, pm):
    """Seeded sampling is handoff-invariant: the migrated run reproduces
    a direct run on the decode engine under the same key, twice."""
    rs, _, D = disagg
    (p,) = _prompts([2 * BS + 2], seed=13)
    a = rs.generate(p, 8, temperature=0.7, rng=jax.random.PRNGKey(17),
                    timeout_s=120.0).tokens
    b = rs.generate(p, 8, temperature=0.7, rng=jax.random.PRNGKey(17),
                    timeout_s=120.0).tokens
    direct = D.generate(p, 8, temperature=0.7, rng=jax.random.PRNGKey(17),
                        timeout_s=120.0).tokens
    assert np.array_equal(a, b) and np.array_equal(a, direct)


def test_disagg_identity_through_mid_decode_preemption(disagg, pm):
    """The decode pool runs OUT of blocks mid-flight (overcommit admits
    more growth than it holds): the youngest migrated stream preempts,
    recomputes, and every answer still matches the sequential path."""
    rs, _, D = disagg
    prompts = _prompts([18, 19, 21], seed=17)
    steps = 24
    refs = [np.asarray(pm.generate(p[None, :], steps))[0] for p in prompts]
    base = D.snapshot()["serve.preemptions"]
    futs = [rs.submit_generate(p, steps, timeout_s=300.0) for p in prompts]
    out = [f.result(timeout=300) for f in futs]
    assert D.snapshot()["serve.preemptions"] > base, \
        "overcommit never ran out — the drill lost its teeth"
    for j, (r, ref) in enumerate(zip(out, refs)):
        assert np.array_equal(r.tokens, ref), j


def test_disagg_identity_through_prefill_replica_restart(disagg, pm):
    """An in-place prefill-replica restart (the supervisor's recovery
    path) drops its pool cold; the very next request hands off again with
    a FRESH migration and tokens never change. The process-level variant
    (DDW_FAULT crash + supervisor respawn) rides load_gen --disagg."""
    rs, P, D = disagg
    before = rs.snapshot()["serve.handoffs"]
    migrated = D.snapshot()["serve.kv_blocks_migrated"]
    P.stop()
    P.restart()                       # warm rejoin, device state re-init
    rs.prefix_index.drop_replica(0)   # a fresh pool holds nothing
    (p,) = _prompts([3 * BS + 2], seed=19)
    ref = np.asarray(pm.generate(p[None, :], 6))[0]
    assert np.array_equal(rs.generate(p, 6, timeout_s=120.0).tokens, ref)
    assert rs.snapshot()["serve.handoffs"] > before
    assert D.snapshot()["serve.kv_blocks_migrated"] > migrated
    assert P.snapshot()["serve.decode_ticks"] == 0.0
