"""Fault-injection harness unit tests (ddw_tpu.runtime.faults) plus the
in-process trainer integration: the step-loop hooks fire deterministically,
graceful preemption checkpoints mid-epoch and resumes, and the harness is a
no-op when DDW_FAULT is unset."""

import os
import signal

import numpy as np
import pytest

from ddw_tpu.runtime import faults
from ddw_tpu.runtime.faults import (
    FaultInjected,
    FaultSpec,
    Preempted,
    maybe_fault,
    parse_fault,
)


@pytest.fixture()
def preemption_cleanup():
    """Restore signal disposition + flag after tests that exercise SIGTERM."""
    yield
    faults.reset_preemption()
    signal.signal(signal.SIGTERM, signal.SIG_DFL)


# -- spec parsing ----------------------------------------------------------

def test_parse_full_spec():
    spec = parse_fault("crash:rank=1:step=3")
    assert spec == FaultSpec(kind="crash", rank=1, step=3, gen=0, attempt=0)


def test_parse_defaults_and_wildcards():
    assert parse_fault("") is None
    assert parse_fault("stall") == FaultSpec("stall", None, None, 0, 0)
    spec = parse_fault("preempt:rank=*:gen=*:attempt=*:step=5")
    assert spec.rank is None and spec.gen is None and spec.attempt is None
    assert spec.step == 5


@pytest.mark.parametrize("bad", ["explode", "crash:when=3", "crash:rank=x"])
def test_parse_malformed_raises(bad):
    with pytest.raises(ValueError):
        parse_fault(bad)


# -- matching --------------------------------------------------------------

def test_matching_matrix():
    spec = FaultSpec("crash", rank=1, step=3, gen=0, attempt=0)
    ok = dict(rank=1, step=3, gen=0, attempt=0)
    assert spec.matches("step", **ok)
    assert not spec.matches("coord_bind", **ok)
    assert not spec.matches("step", **{**ok, "rank": 0})
    assert not spec.matches("step", **{**ok, "step": 2})
    assert not spec.matches("step", **{**ok, "gen": 1})  # restarted gang runs clean
    wild = FaultSpec("crash", rank=None, step=None, gen=None, attempt=None)
    assert wild.matches("step", rank=7, step=99, gen=4, attempt=2)


def test_maybe_fault_noop_without_env(monkeypatch):
    monkeypatch.delenv("DDW_FAULT", raising=False)
    maybe_fault("step", step=0)  # must not raise or exit


def test_raise_kind_fires_only_on_matching_step(monkeypatch):
    monkeypatch.setenv("DDW_FAULT", "raise:step=2")
    monkeypatch.delenv("DDW_PROCESS_ID", raising=False)
    monkeypatch.delenv("DDW_RESTART_GEN", raising=False)
    maybe_fault("step", step=1)
    with pytest.raises(FaultInjected, match="injected fault"):
        maybe_fault("step", step=2)
    monkeypatch.setenv("DDW_RESTART_GEN", "1")
    maybe_fault("step", step=2)  # next generation: clean


def test_preempt_kind_sets_flag_via_sigterm(monkeypatch, preemption_cleanup):
    monkeypatch.setenv("DDW_FAULT", "preempt:step=0")
    assert not faults.preemption_requested()
    maybe_fault("step", step=0)
    assert faults.preemption_requested()
    faults.reset_preemption()
    assert not faults.preemption_requested()


def test_request_preemption_signal_free():
    faults.request_preemption()
    assert faults.preemption_requested()
    faults.reset_preemption()


def test_torn_step_dir_writer(tmp_path):
    d = faults._write_torn_step_dir(str(tmp_path), 7)
    assert os.path.isdir(d)
    assert os.path.getsize(os.path.join(d, "state.msgpack")) == 4
    assert not os.path.exists(os.path.join(d, "metadata.json"))


# -- trainer integration (in-process, np=-1 semantics) ---------------------

def _lm_trainer(tmp_path, epochs=3):
    from ddw_tpu.train.lm_trainer import LMTrainer
    from ddw_tpu.utils.config import LMCfg, TrainCfg

    lm = LMCfg(vocab_size=32, max_len=16, hidden=16, depth=1, num_heads=2,
               mlp_dim=32, dropout=0.0, dtype="float32")
    tr = TrainCfg(batch_size=2, epochs=epochs, warmup_epochs=0, seed=0,
                  learning_rate=1e-2, num_devices=2,
                  checkpoint_dir=str(tmp_path / "ck"),
                  checkpoint_every_epochs=1)
    return LMTrainer(lm, tr)


def _toy_tokens():
    rng = np.random.RandomState(0)
    starts = rng.randint(0, 32, size=(44, 1))
    return ((starts + np.arange(17)[None]) % 32).astype(np.int32)


@pytest.mark.faults
def test_lm_trainer_graceful_preemption_checkpoints_then_resumes(
        tmp_path, monkeypatch, preemption_cleanup):
    """SIGTERM mid-epoch -> the step loop checkpoints the live state and
    raises Preempted; a resume run completes all epochs from that point."""
    from ddw_tpu.checkpoint.ckpt import latest_step

    toks = _toy_tokens()
    monkeypatch.setenv("DDW_FAULT", "preempt:step=4")
    with pytest.raises(Preempted) as exc:
        _lm_trainer(tmp_path).fit(toks, val_fraction=0.1)
    assert exc.value.step == 4

    ck = str(tmp_path / "ck")
    assert latest_step(ck) == 4  # mid-epoch durable checkpoint
    import json
    with open(os.path.join(ck, "step_0000000004", "metadata.json")) as f:
        assert json.load(f)["preempted"] is True

    monkeypatch.delenv("DDW_FAULT")
    faults.reset_preemption()
    res = _lm_trainer(tmp_path).fit(toks, val_fraction=0.1, resume=True)
    assert res.epochs_run == 3
    assert np.isfinite(res.val_loss)


@pytest.mark.faults
def test_vision_trainer_step_hook_fires(silver, monkeypatch):
    """The vision Trainer's per-step hook is live: an injected 'raise' fault
    at global step 0 propagates out of fit before any step executes."""
    from ddw_tpu.train.trainer import Trainer
    from ddw_tpu.utils.config import DataCfg, ModelCfg, TrainCfg

    train_tbl, val_tbl, _ = silver
    data = DataCfg(img_height=24, img_width=24, loader_workers=2,
                   shuffle_buffer=32)
    model = ModelCfg(name="small_cnn", num_classes=5, dropout=0.0,
                     dtype="float32")
    tr = TrainCfg(batch_size=4, epochs=1, warmup_epochs=0, seed=0,
                  learning_rate=1e-2)
    monkeypatch.setenv("DDW_FAULT", "raise:step=0")
    with pytest.raises(FaultInjected):
        Trainer(data, model, tr).fit(train_tbl, val_tbl)


@pytest.mark.slow
@pytest.mark.faults
def test_vision_trainer_graceful_preemption(silver, tmp_path, monkeypatch,
                                            preemption_cleanup):
    """Full vision-trainer preemption drill: checkpoint-on-SIGTERM mid-run,
    then a resumed fit completes the remaining epochs."""
    from ddw_tpu.checkpoint.ckpt import latest_step
    from ddw_tpu.train.trainer import Trainer
    from ddw_tpu.utils.config import DataCfg, ModelCfg, TrainCfg

    train_tbl, val_tbl, _ = silver
    data = DataCfg(img_height=24, img_width=24, loader_workers=2,
                   shuffle_buffer=32)
    model = ModelCfg(name="small_cnn", num_classes=5, dropout=0.0,
                     dtype="float32")

    def cfg(epochs):
        return TrainCfg(batch_size=4, epochs=epochs, warmup_epochs=0, seed=0,
                        learning_rate=1e-2,
                        checkpoint_dir=str(tmp_path / "ck"),
                        checkpoint_every_epochs=1)

    monkeypatch.setenv("DDW_FAULT", "preempt:step=3")
    with pytest.raises(Preempted):
        Trainer(data, model, cfg(epochs=4)).fit(train_tbl, val_tbl)
    assert (latest_step(str(tmp_path / "ck")) or 0) > 0

    monkeypatch.delenv("DDW_FAULT")
    faults.reset_preemption()
    res = Trainer(data, model, cfg(epochs=4)).fit(train_tbl, val_tbl,
                                                  resume=True)
    assert res.epochs_run == 4
    assert np.isfinite(res.val_loss)
