"""GangSupervisor failure-path tests — every scenario driven by the
deterministic DDW_FAULT env hooks (ddw_tpu.runtime.faults), on CPU, with real
OS-process gangs.

The worker is a minimal supervised train loop with the trainers' exact
contract: restore from the latest durable checkpoint, per-step fault hook +
preemption check, a cross-process psum barrier per step (so a dead rank
leaves the others blocked in a collective — the case the gang kill exists
for), and a checkpoint after every step."""

import functools
import threading
import time

import pytest

from ddw_tpu.runtime.launcher import GangError, Launcher
from ddw_tpu.runtime.supervisor import GangFailure, GangSupervisor

TOTAL_STEPS = 6


def _supervised_worker(ckpt_dir: str, total_steps: int) -> dict:
    """Runs inside each rank. Checkpoints under ``ckpt_dir`` (rank-0 writer),
    resumes from the newest good step, steps through a psum gang barrier."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from ddw_tpu.checkpoint.ckpt import CheckpointManager
    from ddw_tpu.runtime.faults import (Preempted, maybe_fault,
                                        preemption_requested)

    psum = jax.pmap(lambda x: lax.psum(x, "i"), axis_name="i")
    mgr = CheckpointManager(ckpt_dir)
    state = {"w": np.zeros((4,), np.float32), "step": np.asarray(0, np.int32)}
    start = 0
    if mgr.latest_step() is not None:
        state, start = mgr.restore(state)
        start = int(start)
    for step in range(start, total_steps):
        maybe_fault("step", step=step, ckpt_dir=ckpt_dir)
        if preemption_requested():
            mgr.save(state, step, metadata={"preempted": True})
            mgr.wait()
            raise Preempted(step)
        total = psum(jnp.ones((jax.local_device_count(),)))  # gang barrier
        state = {"w": state["w"] + float(total[0]),
                 "step": np.asarray(step + 1, np.int32)}
        mgr.save(state, step + 1)
    mgr.close()
    return {"final_step": int(state["step"]), "resume_step": start,
            "generation": int(os.environ.get("DDW_RESTART_GEN", "0"))}


def _gang(timeout_s=300, **kw):
    # short preemption grace: peers wedged in a collective are killed fast
    # (test speed), but the SIGTERM forward still reaches live ranks
    kw.setdefault("preempt_grace_s", 2.0)
    return Launcher(np=2, devices_per_proc=1, timeout_s=timeout_s, **kw)


def _supervisor(launcher, **kw):
    kw.setdefault("backoff_base_s", 0.05)
    kw.setdefault("jitter", 0.0)
    return GangSupervisor(launcher, **kw)


# -- acceptance: crash -> bounded auto-restart-from-checkpoint -------------

@pytest.mark.faults
def test_crash_restart_resumes_from_checkpoint_and_completes(
        tmp_path, monkeypatch, worker_pythonpath):
    """DDW_FAULT=crash:rank=1:step=3 with max_restarts=2: rank 1 dies at
    step 3 of generation 0, the supervisor relaunches the gang, generation 1
    resumes from the durable checkpoint (resume step > 0, not step 0) and
    finishes with the same final step count as a no-fault run."""
    baseline = Launcher(np=-1).run(
        functools.partial(_supervised_worker, str(tmp_path / "base"),
                          TOTAL_STEPS))
    assert baseline["final_step"] == TOTAL_STEPS

    monkeypatch.setenv("DDW_FAULT", "crash:rank=1:step=3")
    sup = _supervisor(_gang(), max_restarts=2)
    out = sup.run(functools.partial(_supervised_worker,
                                    str(tmp_path / "ck"), TOTAL_STEPS))
    assert out["final_step"] == baseline["final_step"] == TOTAL_STEPS
    assert out["resume_step"] > 0          # resumed from a checkpoint...
    assert out["resume_step"] == 3         # ...exactly the last durable step
    assert out["generation"] == 1
    assert len(sup.attempts) == 1 and sup.attempts[0].kind == "crash"
    from ddw_tpu.runtime.faults import EXIT_FAULT_CRASH

    assert EXIT_FAULT_CRASH in sup.attempts[0].exit_codes
    # forensics: which rank died, how, and which recovery mode engaged
    assert sup.attempts[0].dead_rank == 1
    assert sup.attempts[0].exit_signal is None      # exit(77), not a signal
    assert sup.attempts[0].recovery == "whole-world"


@pytest.mark.faults
def test_max_restarts_zero_raises_gangfailure_with_exit_codes(
        tmp_path, monkeypatch, worker_pythonpath):
    monkeypatch.setenv("DDW_FAULT", "crash:rank=1:step=1")
    sup = _supervisor(_gang(), max_restarts=0)
    with pytest.raises(GangFailure, match="failed permanently") as exc:
        sup.run(functools.partial(_supervised_worker,
                                  str(tmp_path / "ck"), TOTAL_STEPS))
    from ddw_tpu.runtime.faults import EXIT_FAULT_CRASH

    assert len(exc.value.attempts) == 1
    assert EXIT_FAULT_CRASH in exc.value.attempts[0].exit_codes
    assert exc.value.exit_codes == [exc.value.attempts[0].exit_codes]


@pytest.mark.faults
def test_gangfailure_carries_rank0_traceback(tmp_path, monkeypatch,
                                             worker_pythonpath):
    """A rank-0 exception survives budget exhaustion: the GangFailure carries
    the formatted traceback, not just exit codes."""
    monkeypatch.setenv("DDW_FAULT", "raise:rank=0:step=1")
    sup = _supervisor(_gang(), max_restarts=0)
    with pytest.raises(GangFailure, match="injected fault") as exc:
        sup.run(functools.partial(_supervised_worker,
                                  str(tmp_path / "ck"), TOTAL_STEPS))
    assert "FaultInjected" in exc.value.rank0_traceback
    assert "injected fault" in exc.value.rank0_traceback


# -- graceful preemption ---------------------------------------------------

@pytest.mark.faults
def test_preemption_restarts_outside_crash_budget(tmp_path, monkeypatch,
                                                  worker_pythonpath):
    """SIGTERM-driven preemption: the worker checkpoints and exits cleanly
    (EXIT_PREEMPTED); the supervisor restarts it even with max_restarts=0 —
    preemption is restartable progress, not failure."""
    monkeypatch.setenv("DDW_FAULT", "preempt:rank=0:step=2")
    sup = _supervisor(_gang(), max_restarts=0)
    out = sup.run(functools.partial(_supervised_worker,
                                    str(tmp_path / "ck"), TOTAL_STEPS))
    assert out["final_step"] == TOTAL_STEPS
    assert out["resume_step"] == 2
    assert out["generation"] == 1
    assert len(sup.attempts) == 1 and sup.attempts[0].kind == "preempted"


@pytest.mark.faults
@pytest.mark.slow   # two full gang generations; preemption class keeps
#                     test_preemption_restarts_outside_crash_budget in tier-1
def test_preemption_budget_exhaustion_raises(tmp_path, monkeypatch,
                                             worker_pythonpath):
    """A preemption *storm* (every generation preempted) still terminates:
    gen=* makes the fault re-fire after restart until the preemption budget
    runs out."""
    monkeypatch.setenv("DDW_FAULT", "preempt:rank=0:step=0:gen=*")
    sup = _supervisor(_gang(), max_restarts=0, max_preemption_restarts=1)
    with pytest.raises(GangFailure) as exc:
        sup.run(functools.partial(_supervised_worker,
                                  str(tmp_path / "ck"), TOTAL_STEPS))
    assert [a.kind for a in exc.value.attempts] == ["preempted", "preempted"]


def _slow_supervised_worker(ckpt_dir: str, total_steps: int,
                            started_path: str) -> dict:
    """The supervised-worker contract with a slow (0.25 s) step, so a
    driver-side SIGTERM broadcast lands while every rank is mid-loop (not
    wedged in a collective) and all of them preempt gracefully. Rank 0
    drops ``started_path`` after the first full step of generation 0."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from ddw_tpu.checkpoint.ckpt import CheckpointManager
    from ddw_tpu.runtime.faults import Preempted, preemption_requested

    psum = jax.pmap(lambda x: lax.psum(x, "i"), axis_name="i")
    mgr = CheckpointManager(ckpt_dir)
    state = {"w": np.zeros((4,), np.float32), "step": np.asarray(0, np.int32)}
    start = 0
    if mgr.latest_step() is not None:
        state, start = mgr.restore(state)
        start = int(start)
    for step in range(start, total_steps):
        if preemption_requested():
            mgr.save(state, step, metadata={"preempted": True})
            mgr.wait()
            raise Preempted(step)
        total = psum(jnp.ones((jax.local_device_count(),)))
        state = {"w": state["w"] + float(total[0]),
                 "step": np.asarray(step + 1, np.int32)}
        mgr.save(state, step + 1)
        if (step >= 1 and os.environ.get("DDW_PROCESS_ID") == "0"
                and os.environ.get("DDW_RESTART_GEN", "0") == "0"
                and not os.path.exists(started_path)):
            with open(started_path, "w") as f:
                f.write("started")
        import time as _time

        _time.sleep(0.25)
    mgr.close()
    return {"final_step": int(state["step"]), "resume_step": start,
            "generation": int(os.environ.get("DDW_RESTART_GEN", "0"))}


@pytest.mark.faults
@pytest.mark.slow   # two full gang generations of slow steps — tier-2 drill
def test_broadcast_preemption_reaches_every_rank(tmp_path, worker_pythonpath):
    """Driver-side preemption (the cluster manager SIGTERMs the allocation):
    broadcast_preemption() forwards SIGTERM to ALL ranks, every rank
    checkpoints and exits EXIT_PREEMPTED — nobody dies as collective-error
    collateral — and the supervisor resumes to completion without touching
    the crash budget."""
    from ddw_tpu.runtime.faults import EXIT_PREEMPTED

    started = tmp_path / "started"
    launcher = _gang(preempt_grace_s=30.0)
    sup = _supervisor(launcher, max_restarts=0)

    def trigger():
        while not started.exists():
            time.sleep(0.05)
        time.sleep(0.1)  # land mid-sleep of the next step, on both ranks
        assert launcher.broadcast_preemption() == 2

    t = threading.Thread(target=trigger, daemon=True)
    t.start()
    out = sup.run(functools.partial(_slow_supervised_worker,
                                    str(tmp_path / "ck"), 12, str(started)))
    t.join(timeout=10)
    assert out["final_step"] == 12
    assert out["generation"] == 1
    assert len(sup.attempts) == 1 and sup.attempts[0].kind == "preempted"
    # the whole point: EVERY rank got the signal and left gracefully
    assert sup.attempts[0].exit_codes == [EXIT_PREEMPTED, EXIT_PREEMPTED]


# -- attempt reports into the tracker --------------------------------------

@pytest.mark.faults
def test_supervisor_reports_attempts_to_tracker(tmp_path, monkeypatch,
                                                worker_pythonpath):
    """With tracker_run set, the recovery story lands in the tracker: totals
    + per-generation attempt series as metrics, outcome as a tag, and the
    full forensic record as a supervisor_attempts.json artifact."""
    import json
    import os

    from ddw_tpu.tracking.tracker import Tracker

    monkeypatch.setenv("DDW_FAULT", "crash:rank=1:step=2")
    run = Tracker(str(tmp_path / "mlruns"), "gang").start_run("supervised")
    sup = _supervisor(_gang(), max_restarts=2, tracker_run=run)
    out = sup.run(functools.partial(_supervised_worker,
                                    str(tmp_path / "ck"), TOTAL_STEPS))
    run.end()
    assert out["final_step"] == TOTAL_STEPS
    m = run.final_metrics()
    assert m["supervisor.generations"] == 2.0
    assert m["supervisor.failed_attempts"] == 1.0
    assert m["supervisor.crash_restarts"] == 1.0
    assert m["supervisor.preemption_restarts"] == 0.0
    assert run.metric_history("supervisor.attempt_elapsed_s")[0][0] == 0
    assert run.meta()["tags"]["supervisor.outcome"] == "completed"
    art = os.path.join(run.run_dir, "artifacts", "supervisor",
                       "supervisor_attempts.json")
    with open(art) as f:
        data = json.load(f)
    assert data["outcome"] == "completed"
    assert data["attempts"][0]["kind"] == "crash"
    assert data["attempts"][0]["generation"] == 0


@pytest.mark.faults
@pytest.mark.slow   # tracker-reporting class keeps
#                     test_supervisor_reports_attempts_to_tracker in tier-1
def test_supervisor_reports_failed_outcome(tmp_path, monkeypatch,
                                           worker_pythonpath):
    from ddw_tpu.tracking.tracker import Tracker

    monkeypatch.setenv("DDW_FAULT", "crash:rank=1:step=1")
    run = Tracker(str(tmp_path / "mlruns"), "gang").start_run("supervised")
    sup = _supervisor(_gang(), max_restarts=0, tracker_run=run)
    with pytest.raises(GangFailure):
        sup.run(functools.partial(_supervised_worker,
                                  str(tmp_path / "ck"), TOTAL_STEPS))
    assert run.meta()["tags"]["supervisor.outcome"] == "failed"
    assert run.final_metrics()["supervisor.failed_attempts"] == 1.0


# -- silent early exit + torn checkpoint + deadline ------------------------

@pytest.mark.faults
def test_exit0_early_surfaces_missing_result(tmp_path, monkeypatch,
                                             worker_pythonpath):
    """Every rank exits 0 before writing the result: the driver must surface
    'result missing', not unpickle garbage or crash with FileNotFoundError."""
    monkeypatch.setenv("DDW_FAULT", "exit0_early:step=1")
    with pytest.raises(GangError, match="missing or unreadable") as exc:
        _gang().run(functools.partial(_supervised_worker,
                                      str(tmp_path / "ck"), TOTAL_STEPS))
    assert exc.value.kind == "result-missing"
    assert exc.value.exit_codes == [0, 0]


@pytest.mark.faults
def test_ckpt_torn_crash_quarantined_on_restart(tmp_path, monkeypatch,
                                                worker_pythonpath):
    """Rank 0 drops a torn (newer-numbered, partial) step dir and crashes:
    the restarted generation must quarantine it and resume from the previous
    good step — a kill mid-write never poisons resume."""
    import os

    ckpt_dir = str(tmp_path / "ck")
    monkeypatch.setenv("DDW_FAULT", "ckpt_torn:rank=0:step=3")
    sup = _supervisor(_gang(), max_restarts=2)
    out = sup.run(functools.partial(_supervised_worker, ckpt_dir,
                                    TOTAL_STEPS))
    assert out["final_step"] == TOTAL_STEPS
    assert out["resume_step"] == 3  # fell back past the torn step_1003 dir
    torn = [d for d in os.listdir(ckpt_dir) if ".torn" in d]
    assert torn, "torn step dir was not quarantined"
    assert not os.path.exists(os.path.join(ckpt_dir, "step_0000001003"))


@pytest.mark.faults
def test_stall_hits_gang_deadline(tmp_path, monkeypatch, worker_pythonpath):
    """A stalled rank trips the shared gang deadline (classified 'deadline',
    not 'crash') instead of hanging the driver forever."""
    monkeypatch.setenv("DDW_FAULT", "stall:rank=1:step=2")
    # the stalled rank never exits, so ANY deadline classifies correctly —
    # keep it short; the driver spends the whole window waiting
    with pytest.raises(GangError, match="deadline") as exc:
        _gang(timeout_s=6).run(
            functools.partial(_supervised_worker, str(tmp_path / "ck"),
                              TOTAL_STEPS))
    assert exc.value.kind == "deadline"


@pytest.mark.slow
@pytest.mark.faults
def test_stall_deadline_then_restart_completes(tmp_path, monkeypatch,
                                               worker_pythonpath):
    """Deadline -> supervisor restart -> resume-from-checkpoint completes
    (the multi-restart stall variant; excluded from tier-1 by `slow`)."""
    monkeypatch.setenv("DDW_FAULT", "stall:rank=1:step=2")
    sup = _supervisor(_gang(timeout_s=15), max_restarts=1)
    out = sup.run(functools.partial(_supervised_worker,
                                    str(tmp_path / "ck"), TOTAL_STEPS))
    assert out["final_step"] == TOTAL_STEPS
    assert out["resume_step"] == 2
    assert sup.attempts[0].kind == "deadline"


# -- pure classification logic --------------------------------------------

def test_gangerror_preemption_classification():
    from ddw_tpu.runtime.faults import EXIT_PREEMPTED

    mk = lambda codes: GangError("x", kind="crash", exit_codes=codes)  # noqa: E731
    assert mk([EXIT_PREEMPTED, -9]).is_preemption
    assert mk([EXIT_PREEMPTED, 0]).is_preemption
    assert mk([EXIT_PREEMPTED, EXIT_PREEMPTED]).is_preemption
    # collateral death of a peer (collective error -> exit 1) doesn't mask it
    assert mk([EXIT_PREEMPTED, 1]).is_preemption
    assert not mk([0, 1]).is_preemption
    assert not mk([None, -9]).is_preemption
