"""tools/step_trace.py contract: traces land on disk, JSON line reports them.

A typo'd queue item must fail in CI, not burn a tunnel-window attempt.
"""

import pytest
import json
import os
import subprocess
import sys

# profiler-trace tool smoke — beyond the tier-1 wall-clock budget
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_step_trace_smoke(tmp_path):
    env = dict(os.environ, DDW_BENCH_SMOKE="1", PALLAS_AXON_POOL_IPS="",
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/step_trace.py"),
         "vit", "lm_flash", "--out", str(tmp_path)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    d = json.loads(out.stdout.strip().splitlines()[-1])
    for name in ("vit", "lm_flash"):
        assert d[name]["steps"] > 0 and d[name]["seconds"] > 0
        assert os.listdir(d[name]["dir"])  # profiler wrote something

    bad = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/step_trace.py"), "nope"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert bad.returncode != 0 and "unknown configs" in bad.stderr

    # the offline decomposition pass reads the capture back
    summ = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/trace_summary.py"),
         d["lm_flash"]["dir"], "--top", "5"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert summ.returncode == 0, summ.stderr[-2000:]
    s = json.loads(summ.stdout.strip().splitlines()[-1])
    assert s["processes"], s
    proc = next(iter(s["processes"].values()))
    assert proc["busy_ms"] > 0 and proc["top_ops"]
    assert abs(sum(proc["buckets_pct"].values()) - 100) < 1

    missing = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/trace_summary.py"),
         str(tmp_path / "empty")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert missing.returncode != 0 and "trace.json.gz" in missing.stderr


def test_trace_summary_filters_to_op_lane(tmp_path):
    """TPU Chrome traces nest lanes under the device pid ('XLA Modules' /
    'Steps' spans ENCLOSE the 'XLA Ops' events): only the op lane may be
    aggregated, or busy_ms double-counts past wall time. And pids are only
    unique per trace FILE — one file's op-lane filter must not drop another
    file's events for the same pid (multi-host captures reuse pids)."""
    import gzip

    def write(name, events):
        p = tmp_path / name
        p.write_bytes(gzip.compress(json.dumps(
            {"traceEvents": events}).encode()))

    meta = lambda pid, tid, kind, nm: {
        "ph": "M", "pid": pid, "tid": tid, "name": kind, "args": {"name": nm}}
    ev = lambda pid, tid, nm, dur: {
        "ph": "X", "pid": pid, "tid": tid, "name": nm, "ts": 0, "dur": dur}

    write("a.trace.json.gz", [
        meta(1, 0, "process_name", "/device:TPU:0"),
        meta(1, 10, "thread_name", "XLA Modules"),
        meta(1, 11, "thread_name", "XLA Ops"),
        meta(1, 12, "thread_name", "Steps"),
        ev(1, 10, "jit_step", 100_000),          # enclosing module span
        ev(1, 12, "train_step 3", 100_000),      # enclosing step span
        ev(1, 11, "fusion.1", 40_000),
        ev(1, 11, "dot_general.2", 30_000),
    ])
    # same pid, different file: a host process with no op lane — all kept
    write("b.trace.json.gz", [
        meta(1, 0, "process_name", "host python"),
        meta(1, 7, "thread_name", "python"),
        ev(1, 7, "np.copy", 50_000),
    ])
    # a SECOND host's device with the same display name: must stay a
    # separate entry, not be summed into file a's device
    write("c.trace.json.gz", [
        meta(1, 0, "process_name", "/device:TPU:0"),
        meta(1, 11, "thread_name", "XLA Ops"),
        ev(1, 11, "fusion.9", 20_000),
    ])

    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/trace_summary.py"),
         str(tmp_path)], capture_output=True, text=True, cwd=REPO,
        timeout=120)
    assert out.returncode == 0, out.stderr[-2000:]
    s = json.loads(out.stdout.strip().splitlines()[-1])
    dev = s["processes"]["/device:TPU:0 [file0]"]
    assert dev["busy_ms"] == 70.0, dev  # 40+30 ms, enclosing spans excluded
    assert dev["lanes"] == ["XLA Ops"]
    assert {r["op"] for r in dev["top_ops"]} == {"fusion.1", "dot_general.2"}
    host = s["processes"]["host python"]
    assert host["busy_ms"] == 50.0, host  # file A's filter must not leak in
    dev2 = s["processes"]["/device:TPU:0 [file2]"]
    assert dev2["busy_ms"] == 20.0, dev2
