"""tools/step_trace.py contract: traces land on disk, JSON line reports them.

A typo'd queue item must fail in CI, not burn a tunnel-window attempt.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_step_trace_smoke(tmp_path):
    env = dict(os.environ, DDW_BENCH_SMOKE="1", PALLAS_AXON_POOL_IPS="",
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               PYTHONPATH=REPO)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/step_trace.py"),
         "vit", "lm_flash", "--out", str(tmp_path)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    d = json.loads(out.stdout.strip().splitlines()[-1])
    for name in ("vit", "lm_flash"):
        assert d[name]["steps"] > 0 and d[name]["seconds"] > 0
        assert os.listdir(d[name]["dir"])  # profiler wrote something

    bad = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/step_trace.py"), "nope"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert bad.returncode != 0 and "unknown configs" in bad.stderr

    # the offline decomposition pass reads the capture back
    summ = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/trace_summary.py"),
         d["lm_flash"]["dir"], "--top", "5"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert summ.returncode == 0, summ.stderr[-2000:]
    s = json.loads(summ.stdout.strip().splitlines()[-1])
    assert s["processes"], s
    proc = next(iter(s["processes"].values()))
    assert proc["busy_ms"] > 0 and proc["top_ops"]
    assert abs(sum(proc["buckets_pct"].values()) - 100) < 1

    missing = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools/trace_summary.py"),
         str(tmp_path / "empty")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert missing.returncode != 0 and "trace.json.gz" in missing.stderr
