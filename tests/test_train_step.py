"""Train-step tests: DP gradient averaging, LR dynamics, frozen-base masking,
1-vs-N-device equivalence (the reference's equivalence-by-construction idiom,
SURVEY §4.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddw_tpu.models.registry import build_model
from ddw_tpu.runtime.mesh import make_mesh, MeshSpec
from ddw_tpu.train.step import (
    get_lr,
    init_state,
    make_eval_step,
    make_train_step,
    set_lr,
)
from ddw_tpu.utils.config import ModelCfg, TrainCfg

IMG = (16, 16, 3)


def _setup(mesh, dropout=0.0, model="small_cnn", lr=1e-2):
    mcfg = ModelCfg(name=model, num_classes=5, dropout=dropout, dtype="float32")
    tcfg = TrainCfg(batch_size=8, learning_rate=lr, optimizer="adam")
    m = build_model(mcfg)
    state, tx = init_state(m, mcfg, tcfg, IMG, jax.random.PRNGKey(0))
    step = make_train_step(m, tx, mesh, donate=False)
    return m, state, tx, step


def _batch(n, seed=0):
    rng = np.random.RandomState(seed)
    imgs = rng.randn(n, *IMG).astype(np.float32)
    lbls = rng.randint(0, 5, size=(n,)).astype(np.int32)
    return imgs, lbls


def test_step_runs_and_reduces_loss():
    mesh = make_mesh(MeshSpec((("data", 8),)))
    _, state, _, step = _setup(mesh)
    imgs, lbls = _batch(64)
    rng = jax.random.PRNGKey(1)
    losses = []
    for _ in range(12):
        state, metrics = step(state, imgs, lbls, rng)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 12


def test_one_vs_eight_device_equivalence():
    """Same global batch, same seed: 8-device DP step == 1-device step (dropout off,
    float32). The gradient-pmean contract."""
    mesh8 = make_mesh(MeshSpec((("data", 8),)))
    mesh1 = make_mesh(MeshSpec((("data", 1),)), devices=jax.devices()[:1])
    _, s8, _, step8 = _setup(mesh8)
    _, s1, _, step1 = _setup(mesh1)
    rng = jax.random.PRNGKey(2)
    imgs, lbls = _batch(64)
    for _ in range(3):
        s8, m8 = step8(s8, imgs, lbls, rng)
        s1, m1 = step1(s1, imgs, lbls, rng)
    np.testing.assert_allclose(float(m8["loss"]), float(m1["loss"]), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(s8.params), jax.tree.leaves(s1.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-5)


def test_metrics_are_world_averaged():
    """Metric psum/pmean: replicated output must be a scalar equal across devices
    (MetricAverageCallback role)."""
    mesh = make_mesh(MeshSpec((("data", 4),)), devices=jax.devices()[:4])
    _, state, _, step = _setup(mesh)
    imgs, lbls = _batch(32)
    _, metrics = step(state, imgs, lbls, jax.random.PRNGKey(0))
    assert metrics["loss"].shape == ()
    assert metrics["accuracy"].shape == ()
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_dynamic_lr_get_set():
    mesh = make_mesh(MeshSpec((("data", 2),)), devices=jax.devices()[:2])
    _, state, _, step = _setup(mesh, lr=1e-3)
    assert get_lr(state) == pytest.approx(1e-3)
    state = set_lr(state, 5e-4)
    assert get_lr(state) == pytest.approx(5e-4)
    imgs, lbls = _batch(16)
    state, _ = step(state, imgs, lbls, jax.random.PRNGKey(0))
    assert get_lr(state) == pytest.approx(5e-4)  # survives a step


@pytest.mark.slow   # tier-1 budget (PR 12): optimizer leaf-masking keeps
#                     its tier-1 reps — test_lora.py's lora-mask step/
#                     graft pins and test_transfer.py's frozen-base
#                     end-to-end training path; this unit sweep rides
#                     tier-2
def test_frozen_base_masking():
    """freeze_base: backbone params must not change; head must (Keras
    trainable=False semantics, reference 02_model_training_single_node.py:169)."""
    mesh = make_mesh(MeshSpec((("data", 2),)), devices=jax.devices()[:2])
    mcfg = ModelCfg(name="mobilenet_v2", num_classes=5, dropout=0.0,
                    freeze_base=True, allow_frozen_random=True,
                    dtype="float32", width_mult=0.35)
    tcfg = TrainCfg(batch_size=4, learning_rate=1e-2)
    m = build_model(mcfg)
    state, tx = init_state(m, mcfg, tcfg, (32, 32, 3), jax.random.PRNGKey(0))
    step = make_train_step(m, tx, mesh, donate=False)
    rng = np.random.RandomState(0)
    imgs = rng.randn(8, 32, 32, 3).astype(np.float32)
    lbls = rng.randint(0, 5, size=(8,)).astype(np.int32)
    before_bb = jax.tree.map(np.asarray, state.params["backbone"])
    before_head = np.asarray(state.params["head"]["kernel"])
    state, _ = step(state, imgs, lbls, jax.random.PRNGKey(1))
    after_bb = jax.tree.map(np.asarray, state.params["backbone"])
    for a, b in zip(jax.tree.leaves(before_bb), jax.tree.leaves(after_bb)):
        np.testing.assert_array_equal(a, b)
    assert not np.array_equal(before_head, np.asarray(state.params["head"]["kernel"]))


def test_eval_step_deterministic():
    mesh = make_mesh(MeshSpec((("data", 4),)), devices=jax.devices()[:4])
    m, state, _, _ = _setup(mesh, dropout=0.5)
    ev = make_eval_step(m, mesh)
    imgs, lbls = _batch(32)
    m1 = ev(state, imgs, lbls)
    m2 = ev(state, imgs, lbls)
    assert float(m1["loss"]) == float(m2["loss"])  # dropout off in eval


@pytest.mark.parametrize("name", [
    # tier-1 budget (PR 16): the resnet pair rides tier-2 (~20s/~30s of
    # conv compile); conv train-step pins stay tier-1 in
    # test_step_runs_and_reduces_loss + test_one_vs_eight_device_
    # equivalence, and deep-backbone builds in test_transfer's arms
    pytest.param("resnet18", marks=pytest.mark.slow),
    pytest.param("resnet50", marks=pytest.mark.slow),
])
def test_resnet_family_trains(name):
    """ResNet zoo entries: init, DP step with BN stats pmean, loss decreases,
    frozen-base protocol present."""
    from ddw_tpu.models.resnet import ResNet

    mesh = make_mesh(MeshSpec((("data", 2),)), devices=jax.devices()[:2])
    mcfg = ModelCfg(name=name, num_classes=5, dropout=0.0, width_mult=0.25,
                    dtype="float32", freeze_base=False)
    tcfg = TrainCfg(batch_size=4, learning_rate=1e-2, optimizer="adam")
    m = build_model(mcfg)
    assert isinstance(m, ResNet)
    state, tx = init_state(m, mcfg, tcfg, IMG, jax.random.PRNGKey(0))
    assert state.batch_stats, "resnet must carry BN batch_stats"
    step = make_train_step(m, tx, mesh, donate=False)
    imgs, lbls = _batch(8)
    losses = []
    for i in range(8):
        state, metrics = step(state, imgs, lbls, jax.random.PRNGKey(i))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert ResNet.frozen_prefixes(True) == ("backbone",)


@pytest.mark.slow  # ~35s of depthwise-conv compile on the CPU stand-in
def test_convnext_family_trains():
    """ConvNeXt zoo entry: init, DP step, loss decreases — and, unlike the
    BN families, NO batch_stats collection (the stats-free train-step path
    for a conv model; only ViT/LM exercised it before)."""
    from ddw_tpu.models.convnext import ConvNeXt

    mesh = make_mesh(MeshSpec((("data", 2),)), devices=jax.devices()[:2])
    mcfg = ModelCfg(name="convnext_tiny", num_classes=5, dropout=0.0,
                    width_mult=0.25, dtype="float32", freeze_base=False)
    tcfg = TrainCfg(batch_size=4, learning_rate=1e-3, optimizer="adam")
    m = build_model(mcfg)
    assert isinstance(m, ConvNeXt)
    state, tx = init_state(m, mcfg, tcfg, IMG, jax.random.PRNGKey(0))
    assert not state.batch_stats, "convnext is LayerNorm-only"
    step = make_train_step(m, tx, mesh, donate=False)
    imgs, lbls = _batch(8)
    losses = []
    for i in range(8):
        state, metrics = step(state, imgs, lbls, jax.random.PRNGKey(i))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert ConvNeXt.frozen_prefixes(True) == ("backbone",)
    # 7x7 depthwise + GRN actually present in the tree
    p0 = state.params["backbone"]["stage0_block0"]
    assert p0["dwconv"]["kernel"].shape[:2] == (7, 7)
    assert "grn" in p0


def test_grad_accum_equivalence():
    """grad_accum_steps=2 on the same per-device batch == one full-batch step
    (mean of equal microbatch means is the full mean; GroupNorm is per-example
    so no batch-statistics coupling). Dropout off, float32."""
    mesh = make_mesh(MeshSpec((("data", 8),)))
    mcfg = ModelCfg(name="small_cnn", num_classes=5, dropout=0.0, dtype="float32")
    tcfg = TrainCfg(batch_size=8, learning_rate=1e-2, optimizer="adam")
    m = build_model(mcfg)
    state0, tx = init_state(m, mcfg, tcfg, IMG, jax.random.PRNGKey(0))
    step1 = make_train_step(m, tx, mesh, donate=False)
    step2 = make_train_step(m, tx, mesh, donate=False, grad_accum_steps=2)
    imgs, lbls = _batch(64)
    rng = jax.random.PRNGKey(3)
    s1, m1 = step1(state0, imgs, lbls, rng)
    s2, m2 = step2(state0, imgs, lbls, rng)
    assert np.allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        # summation-order fp noise passes through Adam's normalization; observed
        # max |Δ| ≈ 5e-6 on 2/73k elements
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4,
                                   atol=1e-5)


def test_grad_accum_indivisible_batch_raises():
    mesh = make_mesh(MeshSpec((("data", 8),)))
    mcfg = ModelCfg(name="small_cnn", num_classes=5, dropout=0.0, dtype="float32")
    tcfg = TrainCfg(batch_size=8, learning_rate=1e-2)
    m = build_model(mcfg)
    state, tx = init_state(m, mcfg, tcfg, IMG, jax.random.PRNGKey(0))
    step = make_train_step(m, tx, mesh, donate=False, grad_accum_steps=3)
    imgs, lbls = _batch(64)  # per-device 8, not divisible by 3
    with pytest.raises(ValueError, match="not divisible"):
        step(state, imgs, lbls, jax.random.PRNGKey(0))
