"""RoPE (ddw_tpu.ops.rope): the relative-position property, and the LM
family's three execution modes (full, SP ring, KV-cached decode) agreeing
under pos_encoding='rope'."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddw_tpu.models.lm import TransformerLM, generate
from ddw_tpu.ops.rope import apply_rope


def test_rotation_preserves_norm_and_zero_position_is_identity():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 3, 8, 16).astype(np.float32))
    pos = jnp.arange(8)
    y = apply_rope(x, pos)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(y), axis=-1),
                               np.linalg.norm(np.asarray(x), axis=-1),
                               rtol=1e-5)
    y0 = apply_rope(x, jnp.zeros(8, jnp.int32))
    np.testing.assert_allclose(np.asarray(y0), np.asarray(x), atol=1e-6)


def test_scores_depend_on_relative_position():
    """<rope(q, p+i), rope(k, p+j)> is invariant in p — the defining RoPE
    property that makes cached/ring K position-free."""
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 1, 4, 16).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 1, 4, 16).astype(np.float32))

    def scores(base):
        pos = base + jnp.arange(4)
        qr, kr = apply_rope(q, pos), apply_rope(k, pos)
        return np.asarray(jnp.einsum("bhqd,bhkd->bhqk", qr, kr))

    np.testing.assert_allclose(scores(0), scores(1000), rtol=1e-4, atol=1e-4)
    # and rotation by different positions actually changes the scores
    assert not np.allclose(
        scores(0),
        np.asarray(jnp.einsum("bhqd,bhkd->bhqk", q, k)), atol=1e-3)


def test_seq_axis_layouts_agree():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 6, 4, 8).astype(np.float32))  # [B,S,H,hd]
    pos = jnp.arange(6) + 3
    a = apply_rope(x, pos, seq_axis=1)
    b = apply_rope(x.transpose(0, 2, 1, 3), pos).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_apply_rope_validation():
    x = jnp.zeros((1, 4, 2, 8))
    with pytest.raises(ValueError, match="positions"):
        apply_rope(x, jnp.arange(3), seq_axis=1)
    with pytest.raises(ValueError, match="even head_dim"):
        apply_rope(jnp.zeros((1, 4, 2, 7)), jnp.arange(4), seq_axis=1)
    with pytest.raises(ValueError, match="seq_axis cannot"):
        apply_rope(x, jnp.arange(8), seq_axis=-1)


def _rope_lm(depth=2, **kw):
    return TransformerLM(vocab_size=32, max_len=64, hidden=16, depth=depth,
                         num_heads=2, dtype=jnp.float32, mlp_dim=32,
                         pos_encoding="rope", **kw)


def test_rope_lm_has_no_pos_table_and_validates():
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 8)))
    params = _rope_lm().init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    assert "pos_embed" not in params
    with pytest.raises(ValueError, match="unknown pos_encoding"):
        TransformerLM(vocab_size=8, hidden=16, num_heads=2,
                      pos_encoding="alibi").init(
            {"params": jax.random.PRNGKey(0)}, toks)
    with pytest.raises(ValueError, match="even head_dim"):
        TransformerLM(vocab_size=8, hidden=6, num_heads=2,
                      pos_encoding="rope").init(
            {"params": jax.random.PRNGKey(0)}, toks)


def test_rope_position_sensitivity():
    """The model distinguishes token order without any pos table."""
    rng = np.random.RandomState(3)
    model = _rope_lm()
    toks = jnp.asarray(rng.randint(0, 32, (1, 8)))
    params = model.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    swapped = np.asarray(toks).copy()
    swapped[0, [2, 5]] = swapped[0, [5, 2]]
    out1 = model.apply({"params": params}, toks)
    out2 = model.apply({"params": params}, jnp.asarray(swapped))
    assert not np.allclose(np.asarray(out1[:, -1]), np.asarray(out2[:, -1]),
                           atol=1e-5)


@pytest.mark.slow   # tier-1 budget (PR 16): rope math keeps the unit pins
#                     above tier-1 and decode-vs-full identity keeps
#                     test_lm.py::test_decode_path_matches_full_forward
#                     (learned-pos twin); this rope decode sweep rides
#                     tier-2 with the sp-ring / pp composition arms
def test_rope_decode_matches_full_forward():
    """Prefill + per-token decode through the rotated KV cache reproduces the
    full causal forward (the rope analog of
    test_lm.py::test_decode_path_matches_full_forward)."""
    rng = np.random.RandomState(4)
    model = _rope_lm()
    toks = jnp.asarray(rng.randint(0, 32, (2, 10)))
    params = model.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    full = model.apply({"params": params}, toks)

    from ddw_tpu.models.lm import init_cache

    dm = model.clone(decode=True)
    cache = init_cache(dm, 2)
    logits_steps = []
    for t in range(10):
        lg, vars_ = dm.apply({"params": params, "cache": cache},
                             toks[:, t:t + 1], mutable=["cache"])
        cache = vars_["cache"]
        logits_steps.append(lg[:, 0])
    stepwise = jnp.stack(logits_steps, axis=1)
    np.testing.assert_allclose(np.asarray(stepwise), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow   # 8-device ring-attention equivalence (~14 s) — newly
#                     green via utils.compat.shard_map; tier-2 keeps it
def test_rope_sp_ring_matches_single_device():
    """Ring attention with per-shard pre-rotated K equals the full forward
    (K needs no position plumbing through the ring)."""
    from jax.sharding import Mesh, PartitionSpec as P

    from ddw_tpu.utils.compat import shard_map

    rng = np.random.RandomState(5)
    toks = jnp.asarray(rng.randint(0, 32, (2, 32)))
    base = _rope_lm()
    params = base.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    full = base.apply({"params": params}, toks)

    sp_model = _rope_lm(seq_axis="seq")
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("seq",))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(None, "seq")), out_specs=P(None, "seq", None),
        check_vma=False)
    def sharded_fwd(p, t):
        return sp_model.apply({"params": p}, t)

    out = sharded_fwd(params, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow  # ~8s composition drill like the sp-ring one above;
# tier-1 reps: rope units here + test_pipeline's gpipe equivalence arm
def test_rope_pp_step_matches_single_device():
    """The pipeline step threads RoPE positions through its stages: one
    4-stage PP step == one plain step, loss and params (the rope analog of
    test_pipeline.py::test_pp_train_step_matches_single_device)."""
    import optax

    from ddw_tpu.parallel.pipeline import (init_pp_state, lm_params_from_pp,
                                           make_pp_lm_train_step)
    from ddw_tpu.runtime.mesh import DATA_AXIS, MeshSpec, make_mesh
    from ddw_tpu.train.lm_step import init_lm_state, make_lm_train_step

    n = 4
    mesh_pp = make_mesh(MeshSpec((("pipe", n),)), devices=jax.devices()[:n])
    mesh_1 = make_mesh(MeshSpec(((DATA_AXIS, 1),)), devices=jax.devices()[:1])
    model = _rope_lm(depth=4)
    tx = optax.sgd(1e-1)
    rng = np.random.RandomState(7)
    toks = jnp.asarray(rng.randint(0, 32, (8, 17)))
    inputs, targets = toks[:, :-1], toks[:, 1:]

    ref_state = init_lm_state(model, tx, jax.random.PRNGKey(1))
    ref_step = make_lm_train_step(model, tx, mesh_1, DATA_AXIS, seq_axis=None,
                                  donate=False)
    ref_new, ref_m = ref_step(ref_state, inputs, targets, jax.random.PRNGKey(2))

    pp_state = init_pp_state(model, tx, mesh_pp, jax.random.PRNGKey(1))
    step = make_pp_lm_train_step(model, tx, mesh_pp, num_microbatches=4,
                                 donate=False)
    pp_state = step.place_state(pp_state)
    pp_new, pp_m = step(pp_state, inputs, targets)
    assert abs(float(pp_m["loss"]) - float(ref_m["loss"])) < 1e-5
    got = lm_params_from_pp(jax.device_get(pp_new.params), n, model.depth)
    assert "pos_embed" not in got
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        got, jax.device_get(ref_new.params))


def test_rope_generate_runs():
    model = _rope_lm()
    toks = jnp.asarray(np.random.RandomState(6).randint(0, 32, (2, 4)))
    params = model.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    out = generate(model, params, toks, num_steps=5)
    assert out.shape == (2, 5)
    assert not np.any(np.isnan(np.asarray(out)))
