"""Sharded checkpointing: per-process shard files, exactly-once bytes,
reshard-on-restore, the ZeRO-1 integration (VERDICT r2 item 4), and the
crash-consistency audit (proc_bytes completeness record + torn-dir
quarantine — the classic format's discipline ported, PR 2)."""

import json
import os

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ddw_tpu.checkpoint.sharded import (
    ShardedCheckpointManager,
    latest_complete_step,
    restore_sharded,
    save_sharded,
)
from ddw_tpu.models.registry import build_model
from ddw_tpu.parallel.zero import make_zero_train_step, zero_state_shardings
from ddw_tpu.runtime.mesh import make_mesh, MeshSpec, DATA_AXIS
from ddw_tpu.train.step import init_state
from ddw_tpu.utils.config import ModelCfg, TrainCfg

IMG = (16, 16, 3)


def _zero_state(n_dev, seed=0):
    mesh = make_mesh(MeshSpec(((DATA_AXIS, n_dev),)),
                     devices=jax.devices()[:n_dev])
    mcfg = ModelCfg(name="small_cnn", num_classes=5, dropout=0.0,
                    dtype="float32")
    tcfg = TrainCfg(batch_size=8, learning_rate=1e-2)
    m = build_model(mcfg)
    state, tx = init_state(m, mcfg, tcfg, IMG, jax.random.PRNGKey(seed))
    step = make_zero_train_step(m, tx, mesh, donate=False)
    state = step.place_state(state)
    rng = np.random.RandomState(seed)
    imgs = rng.randn(16, *IMG).astype(np.float32)
    lbls = rng.randint(0, 5, size=(16,)).astype(np.int32)
    state, _ = step(state, imgs, lbls, jax.random.PRNGKey(1))
    return mesh, state


def _state_bytes(state) -> int:
    return sum(l.size * l.dtype.itemsize for l in jax.tree.leaves(state)
               if hasattr(l, "size"))


def _assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert jax.tree.structure(a) == jax.tree.structure(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype, (x.dtype, y.dtype)  # bit-exact incl. dtype
        np.testing.assert_array_equal(x, y)


def test_save_restore_roundtrip_zero_sharded(tmp_path):
    mesh, state = _zero_state(8)
    d = str(tmp_path / "ck")
    path = save_sharded(d, state, step=7, metadata={"epoch": 1})
    assert os.path.isdir(path) and path.endswith("step_0000000007")

    # exactly-once bytes: shard files together hold each element once,
    # replicated leaves included (no per-device duplication)
    bin_bytes = sum(os.path.getsize(os.path.join(path, f))
                    for f in os.listdir(path) if f.endswith(".bin"))
    assert bin_bytes == _state_bytes(state)

    sh = zero_state_shardings(state, mesh)
    restored, at = restore_sharded(d, jax.tree.map(np.asarray, state), sh)
    assert at == 7
    _assert_trees_equal(state, restored)
    # restored optimizer state actually lives sharded
    specs = [l.sharding.spec for l in jax.tree.leaves(restored.opt_state)]
    assert any(DATA_AXIS in (ax for ax in spec if ax) for spec in specs)


def test_restore_onto_different_mesh_reshards(tmp_path):
    """Saved on {'data': 8}, restored onto {'data': 4}: slices are assembled
    from overlapping shards, values identical."""
    _, state = _zero_state(8)
    d = str(tmp_path / "ck")
    save_sharded(d, state, step=1)

    mesh4 = make_mesh(MeshSpec(((DATA_AXIS, 4),)), devices=jax.devices()[:4])
    sh4 = zero_state_shardings(state, mesh4)
    restored, at = restore_sharded(d, jax.tree.map(np.asarray, state), sh4)
    assert at == 1
    _assert_trees_equal(state, restored)
    assert all(l.sharding.mesh.shape[DATA_AXIS] == 4
               for l in jax.tree.leaves(restored.opt_state))


def test_manager_latest_metadata_retention(tmp_path):
    _, state = _zero_state(4)
    mgr = ShardedCheckpointManager(str(tmp_path / "ck"), keep=2)
    for s in (3, 6, 9):
        mgr.save(state, s, metadata={"s": s})
    assert mgr.latest_step() == 9
    assert mgr.read_metadata() == {"s": 9}
    # retention kept the newest two only
    dirs = sorted(os.listdir(tmp_path / "ck"))
    assert dirs == ["step_0000000006", "step_0000000009"]

    mesh = make_mesh(MeshSpec(((DATA_AXIS, 4),)), devices=jax.devices()[:4])
    sh = zero_state_shardings(state, mesh)
    _, at = mgr.restore(jax.tree.map(np.asarray, state), sh, step=6)
    assert at == 6


def test_missing_checkpoint_returns_none(tmp_path):
    _, state = _zero_state(2)
    mesh = make_mesh(MeshSpec(((DATA_AXIS, 2),)), devices=jax.devices()[:2])
    sh = zero_state_shardings(state, mesh)
    out, at = restore_sharded(str(tmp_path / "nope"), state, sh)
    assert at is None and out is state


def test_structure_mismatch_raises(tmp_path):
    mesh, state = _zero_state(2)
    d = str(tmp_path / "ck")
    save_sharded(d, state, step=1)
    sh = zero_state_shardings(state, mesh)
    with pytest.raises(ValueError, match="structure"):
        restore_sharded(d, state, sh.params)  # wrong pytree

    repl = NamedSharding(mesh, P())
    bad_target = jax.tree.map(
        lambda l: np.zeros((3,) + tuple(l.shape), l.dtype), state)
    bad_sh = jax.tree.map(lambda _: repl, state)
    with pytest.raises(ValueError, match="shape"):
        restore_sharded(d, bad_target, bad_sh)


def test_index_records_proc_bytes(tmp_path):
    """The completeness record: index.json carries every process's exact
    shard-file byte count, matching what is on disk."""
    _, state = _zero_state(2)
    path = save_sharded(str(tmp_path), state, step=1)
    with open(os.path.join(path, "index.json")) as f:
        index = json.load(f)
    assert index["proc_bytes"] == {
        "0": os.path.getsize(os.path.join(path, "proc_0.bin"))}


def test_torn_shard_file_quarantined_and_falls_back(tmp_path):
    """A truncated shard file (non-atomic copy, filesystem loss) fails the
    proc_bytes audit: latest_step/restore quarantine the torn dir and fall
    back to the previous good step instead of poisoning resume."""
    mesh, state = _zero_state(4)
    mgr = ShardedCheckpointManager(str(tmp_path / "ck"))
    mgr.save(state, 5)
    mgr.save(state, 9)
    binp = tmp_path / "ck" / "step_0000000009" / "proc_0.bin"
    with open(binp, "r+b") as f:
        f.truncate(os.path.getsize(binp) // 2)

    assert mgr.latest_step() == 5
    # torn dir moved aside, kept for forensics, invisible to the step scan
    assert any(d.startswith("step_0000000009.torn")
               for d in os.listdir(tmp_path / "ck"))
    sh = zero_state_shardings(state, mesh)
    restored, at = mgr.restore(jax.tree.map(np.asarray, state), sh)
    assert at == 5
    _assert_trees_equal(state, restored)


def test_missing_index_quarantined(tmp_path):
    """A step dir without index.json (killed before the publish rename could
    never produce one — this simulates a partial copy) is quarantined."""
    _, state = _zero_state(2)
    save_sharded(str(tmp_path), state, step=3)
    save_sharded(str(tmp_path), state, step=7)
    os.remove(os.path.join(str(tmp_path), "step_0000000007", "index.json"))
    assert latest_complete_step(str(tmp_path)) == 3
    assert any(d.startswith("step_0000000007.torn")
               for d in os.listdir(tmp_path))


def test_explicit_torn_step_raises(tmp_path):
    """Explicitly requesting a torn step raises (the caller named a
    checkpoint that does not usably exist) rather than returning garbage."""
    mesh, state = _zero_state(2)
    save_sharded(str(tmp_path), state, step=4)
    os.remove(os.path.join(str(tmp_path), "step_0000000004", "proc_0.json"))
    sh = zero_state_shardings(state, mesh)
    with pytest.raises(FileNotFoundError, match="missing or torn"):
        restore_sharded(str(tmp_path), jax.tree.map(np.asarray, state), sh,
                        step=4)


def test_pre_audit_checkpoint_still_restores(tmp_path):
    """Backward compat: a checkpoint whose index predates proc_bytes (older
    writer) still passes the audit on file presence alone and restores."""
    mesh, state = _zero_state(2)
    path = save_sharded(str(tmp_path), state, step=2)
    idx = os.path.join(path, "index.json")
    with open(idx) as f:
        index = json.load(f)
    del index["proc_bytes"]
    with open(idx, "w") as f:
        json.dump(index, f)
    assert latest_complete_step(str(tmp_path)) == 2
    sh = zero_state_shardings(state, mesh)
    restored, at = restore_sharded(str(tmp_path),
                                   jax.tree.map(np.asarray, state), sh)
    assert at == 2
    _assert_trees_equal(state, restored)


def _random_tree(rng, n_leaves):
    """Random nested pytree of arrays: mixed ranks, dtypes, odd shapes."""
    dtypes = [np.float32, np.float16, np.int32, np.uint8]
    tree = {}
    for i in range(n_leaves):
        rank = rng.randint(0, 4)
        shape = tuple(int(rng.choice([1, 2, 3, 4, 6, 8, 12, 16]))
                      for _ in range(rank))
        dt = dtypes[rng.randint(len(dtypes))]
        arr = (rng.randn(*shape) * 10).astype(dt) if shape else \
            np.asarray(rng.randn() * 10, dt)
        # nest every third leaf one level deeper
        if i % 3 == 2:
            tree.setdefault(f"sub{i % 5}", {})[f"leaf{i}"] = arr
        else:
            tree[f"leaf{i}"] = arr
    return tree


def _random_shardings(rng, tree, mesh, axis):
    """Random per-leaf shardings: shard a random divisible dim or replicate."""
    n = mesh.shape[axis]

    def sh(leaf):
        shape = tuple(leaf.shape)
        cands = [d for d, s in enumerate(shape) if s % n == 0 and s >= n]
        if cands and rng.rand() < 0.7:
            spec = [None] * len(shape)
            spec[cands[rng.randint(len(cands))]] = axis
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree.map(sh, tree)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fuzz_roundtrip_random_trees_and_shardings(tmp_path, seed):
    """Property: ANY pytree under ANY mix of replicated/sharded leaves
    round-trips bit-exact, including restoring onto a different mesh size
    and different (re-randomized) shardings."""
    rng = np.random.RandomState(seed)
    mesh8 = make_mesh(MeshSpec(((DATA_AXIS, 8),)), devices=jax.devices()[:8])
    tree = _random_tree(rng, n_leaves=12)
    sh8 = _random_shardings(rng, tree, mesh8, DATA_AXIS)
    placed = jax.tree.map(
        lambda x, s: jax.make_array_from_callback(x.shape, s,
                                                  lambda idx: x[idx]),
        tree, sh8)
    save_sharded(str(tmp_path), placed, step=seed)

    # restore 1: same mesh, same shardings
    r1, at = restore_sharded(str(tmp_path), tree, sh8)
    assert at == seed
    _assert_trees_equal(tree, r1)

    # restore 2: half the devices, fresh random shardings (elastic reshard)
    mesh4 = make_mesh(MeshSpec(((DATA_AXIS, 4),)), devices=jax.devices()[:4])
    sh4 = _random_shardings(np.random.RandomState(seed + 100), tree, mesh4,
                            DATA_AXIS)
    r2, _ = restore_sharded(str(tmp_path), tree, sh4)
    _assert_trees_equal(tree, r2)
    for leaf, s in zip(jax.tree.leaves(r2), jax.tree.leaves(sh4)):
        assert leaf.sharding == s


# -- shrink restore: an N-process checkpoint read by a smaller world ---------

def _split_snapshot(snap, nproc):
    """Fabricate what N cooperating processes would each have snapshotted:
    round-robin the one-process snapshot's shard entries into N per-process
    snapshots (offsets rebased per shard file). Written through the real
    commit protocol this produces a genuine N-process checkpoint layout —
    proc_0..proc_{N-1} shard files plus markers — in one test process."""
    from ddw_tpu.checkpoint.sharded import ShardSnapshot

    parts = []
    for pid in range(nproc):
        entries, blobs, off = [], [], 0
        for j, (e, raw) in enumerate(zip(snap.entries, snap.blobs)):
            if j % nproc != pid:
                continue
            e2 = dict(e)
            e2["offset"], e2["nbytes"] = off, len(raw)
            entries.append(e2)
            blobs.append(raw)
            off += len(raw)
        parts.append(ShardSnapshot(entries, snap.leaves_meta, blobs,
                                   pid, nproc))
    return parts


def _write_multiproc_ckpt(ckpt_dir, placed, step, nproc):
    """Run the real cross-process commit protocol with ``nproc`` writer
    threads (pid 0 creates the tmp dir, gathers markers, publishes)."""
    from concurrent.futures import ThreadPoolExecutor

    from ddw_tpu.checkpoint.sharded import snapshot_shards, write_snapshot

    snaps = _split_snapshot(snapshot_shards(placed), nproc)
    with ThreadPoolExecutor(max_workers=nproc) as ex:
        futs = [ex.submit(write_snapshot, ckpt_dir, s, step) for s in snaps]
        return [f.result() for f in futs][0]


@pytest.mark.parametrize("seed,nproc,n_dev", [(0, 3, 4), (1, 3, 2),
                                              (2, 4, 2)])
def test_fuzz_multiproc_checkpoint_restores_onto_shrunken_world(
        tmp_path, seed, nproc, n_dev):
    """The shrink live-recovery property (N -> N-1 and N -> N-2): a
    checkpoint whose shard bytes are spread across N per-process files
    restores bit-identical onto a smaller world under fresh random
    shardings — every requested slice is assembled from ALL overlapping
    saved shards, whichever process wrote them — and matches the
    single-process ground truth exactly."""
    rng = np.random.RandomState(seed)
    mesh8 = make_mesh(MeshSpec(((DATA_AXIS, 8),)), devices=jax.devices()[:8])
    tree = _random_tree(rng, n_leaves=12)
    sh8 = _random_shardings(rng, tree, mesh8, DATA_AXIS)
    placed = jax.tree.map(
        lambda x, s: jax.make_array_from_callback(x.shape, s,
                                                  lambda idx: x[idx]),
        tree, sh8)
    path = _write_multiproc_ckpt(str(tmp_path), placed, seed, nproc)
    with open(os.path.join(path, "index.json")) as f:
        index = json.load(f)
    assert index["n_processes"] == nproc
    assert set(index["proc_bytes"]) == {str(i) for i in range(nproc)}

    # survivor-side restore: fewer devices, fresh random shardings
    mesh_s = make_mesh(MeshSpec(((DATA_AXIS, n_dev),)),
                       devices=jax.devices()[:n_dev])
    sh_s = _random_shardings(np.random.RandomState(seed + 100), tree,
                             mesh_s, DATA_AXIS)
    restored, at = restore_sharded(str(tmp_path), tree, sh_s)
    assert at == seed
    _assert_trees_equal(tree, restored)

    # single-process ground truth: host-side read of every leaf
    host_sh = jax.tree.map(lambda _: object(), tree)
    ground, _ = restore_sharded(str(tmp_path), tree, host_sh)
    _assert_trees_equal(ground, restored)


def test_torn_multiproc_shard_quarantined_at_new_size(tmp_path):
    """The proc_bytes audit runs at the SAVING world's process count: a
    3-process checkpoint torn in proc_1.bin is quarantined no matter that
    the (shrunken) reader runs single-process."""
    rng = np.random.RandomState(1)
    mesh8 = make_mesh(MeshSpec(((DATA_AXIS, 8),)), devices=jax.devices()[:8])
    tree = _random_tree(rng, n_leaves=9)
    sh8 = _random_shardings(rng, tree, mesh8, DATA_AXIS)
    placed = jax.tree.map(
        lambda x, s: jax.make_array_from_callback(x.shape, s,
                                                  lambda idx: x[idx]),
        tree, sh8)
    _write_multiproc_ckpt(str(tmp_path), placed, 2, 3)
    path = _write_multiproc_ckpt(str(tmp_path), placed, 5, 3)
    binp = os.path.join(path, "proc_1.bin")
    with open(binp, "r+b") as f:
        f.truncate(max(0, os.path.getsize(binp) - 1))
    assert latest_complete_step(str(tmp_path)) == 2
    assert any(d.startswith("step_0000000005.torn")
               for d in os.listdir(tmp_path))


# -- async sharded writer (snapshot at boundary, commit in background) -------

def _simple_state(x: float):
    import jax.numpy as jnp

    return {"w": jnp.full((8, 4), x, jnp.float32),
            "n": np.asarray(3, np.int32)}


def test_async_sharded_save_matches_sync(tmp_path):
    """Byte-identical shard files + index whichever thread ran the commit
    protocol, and the async-written step restores bit-exact."""
    s = _simple_state(1.5)
    sync = ShardedCheckpointManager(str(tmp_path / "sync"))
    asyn = ShardedCheckpointManager(str(tmp_path / "async"),
                                    async_write=True, max_inflight=2)
    sync.save(s, 5, metadata={"epoch": 2})
    asyn.save(s, 5, metadata={"epoch": 2})
    asyn.wait()
    assert sync.latest_step() == asyn.latest_step() == 5
    for name in ("proc_0.bin", "proc_0.json"):
        with open(os.path.join(str(tmp_path / "sync"), "step_0000000005",
                               name), "rb") as f1, \
             open(os.path.join(str(tmp_path / "async"), "step_0000000005",
                               name), "rb") as f2:
            assert f1.read() == f2.read(), name
    assert asyn.read_metadata(5)["epoch"] == 2
    target = {"w": np.zeros((8, 4), np.float32), "n": np.asarray(0, np.int32)}
    shardings = {"w": s["w"].sharding, "n": object()}  # host leaf sentinel
    restored, at = asyn.restore(target, shardings)
    assert at == 5
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.full((8, 4), 1.5, np.float32))
    assert int(restored["n"]) == 3


def test_async_sharded_snapshot_is_donation_safe(tmp_path):
    """The host copy happens inside save() (snapshot_shards -> tobytes):
    dropping/overwriting the state right after must not corrupt the write."""
    mgr = ShardedCheckpointManager(str(tmp_path), async_write=True)
    s = _simple_state(2.0)
    mgr.save(s, 1)
    del s
    mgr.save(_simple_state(-1.0), 2)
    mgr.wait()
    target = {"w": np.zeros((8, 4), np.float32), "n": np.asarray(0, np.int32)}
    restored, at = mgr.restore(target, {"w": object(), "n": object()}, step=1)
    assert at == 1
    np.testing.assert_array_equal(restored["w"],
                                  np.full((8, 4), 2.0, np.float32))


def test_async_sharded_deferred_error_surfaces(tmp_path, monkeypatch):
    """Satellite pin: a background commit failure propagates at the NEXT
    boundary (save/wait) instead of being lost on the writer thread."""
    import ddw_tpu.checkpoint.sharded as sh_mod

    mgr = ShardedCheckpointManager(str(tmp_path), async_write=True)
    orig = sh_mod.write_snapshot
    monkeypatch.setattr(sh_mod, "write_snapshot",
                        lambda *a, **kw: (_ for _ in ()).throw(
                            OSError("disk gone mid-commit")))
    mgr.save(_simple_state(1.0), 1)
    with pytest.raises(OSError, match="disk gone"):
        mgr.save(_simple_state(2.0), 2)     # next boundary surfaces it
    monkeypatch.setattr(sh_mod, "write_snapshot", orig)
    mgr.save(_simple_state(3.0), 3)         # manager keeps working
    mgr.wait()
    assert mgr.latest_step() == 3
    mgr.close()
