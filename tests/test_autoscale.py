"""Traffic-driven fleet autoscaler (ddw_tpu.autoscale) — tier-1.

What is pinned here, and why it matters:

- **policy math is pure**: burn-rate/queue/TTFT/occupancy in -> ONE
  desired replica count out, with the hysteresis band (in strictly below
  out), per-direction cooldowns (both stamped by any event — an out can
  never be chased by an instant in), min/max clamps, and the two window
  speeds (scale-OUT judged on the fast inputs, scale-IN quiescence on the
  slow ones). Everything clock-injected: no fleet, no threads, no sleeps;
- **the reconcile drill**: an injected burst scales a 1-replica fleet to
  the policy max with SURGE semantics (the candidate is started, warmed,
  and shadow-probed while provably NOT yet routed), idle scales it back
  to min with drain-first retirement, zero client-visible failures and
  bit-identical greedy outputs across every membership change;
- **the journal closes the crash window**: ``crash_mid_scale`` kills the
  scale event between admission and finalize; the journal is left
  non-terminal and :meth:`AutoscaleController.reconcile` (the
  ``Gateway.start`` path) finalizes it and counts ``journal_resumes``;
- **rollouts and scale events exclude each other**: a tick under a held
  deploy lock DEFERS and counts ``serve.autoscale_blocked`` — blocked is
  counted, never raced — and leaves the rollout's status untouched;
- **membership changes leak nothing**: ``fleet_metrics`` counters survive
  add/remove cycles (they are fleet-owned, not per-slot), and ten scale
  cycles leave no per-slot residue in ``PrefixIndex`` / ``FleetTelemetry``;
- **the HTTP surface**: ``/readyz`` + ``/stats`` autoscale blocks,
  ``POST /admin/autoscale`` (enable/disable/bounds) with the same
  409-under-deploy-lock semantics as ``/admin/deploy``, and the new
  counters/gauges in the Prometheus exposition.
"""

import concurrent.futures
import json
import os
import threading

import pytest

from ddw_tpu.autoscale import (AutoscaleController, PolicyInputs,
                               ScalePolicy, inputs_from_windows, max_burn)
from ddw_tpu.deploy.journal import RolloutJournal
from ddw_tpu.gateway import Gateway, GatewayClient, ReplicaSet
from ddw_tpu.gateway.client import GatewayError
from ddw_tpu.obs.telemetry import FleetTelemetry
from ddw_tpu.runtime.faults import (AutoscaleCrash, AutoscaleFaultSpec,
                                    FaultInjected, parse_autoscale_fault,
                                    parse_fault)
from ddw_tpu.serve.metrics import EngineMetrics


class _Clock:
    """Injectable monotonic clock — cooldown/drain tests never sleep."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class _Eng:
    """Scripted replica with a DETERMINISTIC greedy generate (a pure
    function of the prompt), so bit-identity across membership changes is
    checkable, plus start/warmup/probe/stop recording for the surge-order
    pin."""

    def __init__(self, rs_ref=None):
        self.metrics = EngineMetrics()
        self.events: list[str] = []
        self.started = False
        self.stopped = False
        self._rs_ref = rs_ref       # surge pin: probe asserts not-yet-routed

    def start(self):
        self.started = True
        self.events.append("start")
        return self

    def stop(self):
        self.stopped = True
        self.events.append("stop")

    def warmup(self, prompt_lens=(8,)):
        self.events.append("warmup")

    def probe(self, timeout_s=None):
        self.events.append("probe")
        if self._rs_ref is not None:
            # THE surge guarantee: shadow-probed while not yet admitted
            assert self not in self._rs_ref.replicas, \
                "candidate was routed before its probe"

    def submit_generate(self, prompt, num_steps, **kw):
        f = concurrent.futures.Future()
        f.set_result([(sum(prompt) * 31 + k) % 50257
                      for k in range(num_steps)])
        return f


def _merged_fn(state):
    """Synthetic FleetTelemetry.merged() shape driven by a mutable dict —
    the test's pressure knob."""
    def merged():
        sig = {"serve.queue_depth": {"kind": "gauge",
                                     "last_sum": state.get("queue", 0.0)}}
        win = {"signals": sig}
        return {"windows": {"10s": win, "60s": win}}
    return merged


def _policy(clk, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 3)
    kw.setdefault("queue_out", 8.0)
    kw.setdefault("queue_in", 1.0)
    kw.setdefault("out_cooldown_s", 0.0)
    kw.setdefault("in_cooldown_s", 0.0)
    return ScalePolicy(clock=clk, **kw)


# -- policy math (pure units: burn-rate in -> desired count out) --------------


def test_policy_construction_validates():
    with pytest.raises(ValueError):
        ScalePolicy(min_replicas=0)
    with pytest.raises(ValueError):
        ScalePolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        ScalePolicy(step=0)
    with pytest.raises(ValueError):            # thresholds set together
        ScalePolicy(queue_out=8.0, queue_in=None)
    with pytest.raises(ValueError):            # in strictly below out
        ScalePolicy(queue_out=8.0, queue_in=8.0)


def test_policy_burn_scales_out_and_clamps_at_max():
    p = ScalePolicy(max_replicas=4)
    d = p.decide(PolicyInputs(replicas=1, burn=3.0))
    assert (d.action, d.desired, d.current) == ("out", 2, 1)
    assert "burn" in d.reason and "(fast)" in d.reason
    # at the max bound, pressure holds instead of overshooting
    d = p.decide(PolicyInputs(replicas=4, burn=3.0))
    assert d.action == "hold" and "max_replicas=4" in d.reason


def test_policy_hysteresis_band_holds():
    clk = _Clock()
    p = _policy(clk)
    # queue/replica of 4 sits between in(1) and out(8): the band holds
    d = p.decide(PolicyInputs(replicas=2, queue_depth=8.0))
    assert d.action == "hold" and "hysteresis band" in d.reason
    # scale-in needs EVERY signal below its in-threshold on the SLOW window
    fast = PolicyInputs(replicas=3)
    slow = PolicyInputs(replicas=3, queue_depth=9.0)   # 3/replica >= 1
    d = p.decide(fast, slow)
    assert d.action == "hold" and "(slow)" in d.reason
    d = p.decide(fast, PolicyInputs(replicas=3))
    assert (d.action, d.desired) == ("in", 2)


def test_policy_out_judged_on_fast_window_only():
    clk = _Clock()
    p = _policy(clk)
    fast = PolicyInputs(replicas=1, queue_depth=100.0)
    slow = PolicyInputs(replicas=1)          # 60s window still quiet
    d = p.decide(fast, slow)
    assert d.action == "out"                 # the burst answers in seconds


def test_policy_cooldowns_stamp_both_directions():
    clk = _Clock()
    p = _policy(clk, out_cooldown_s=10.0, in_cooldown_s=30.0)
    p.note_scaled("out")                     # t=0: an out event lands
    clk.advance(5.0)
    d = p.decide(PolicyInputs(replicas=2, queue_depth=100.0))
    assert d.action == "hold" and "cooldown" in d.reason
    assert d.cooldown_remaining_s == pytest.approx(5.0)
    # the IN clock restarted too: an out chased by an instant in is flap
    d = p.decide(PolicyInputs(replicas=2))
    assert d.action == "hold" and "cooldown" in d.reason
    assert d.cooldown_remaining_s == pytest.approx(25.0)
    clk.advance(5.0)                         # t=10: out cooldown expired
    assert p.decide(PolicyInputs(replicas=2, queue_depth=100.0)).action \
        == "out"
    clk.advance(20.0)                        # t=30: in cooldown expired
    assert p.decide(PolicyInputs(replicas=2)).action == "in"


def test_policy_min_clamp_and_describe():
    clk = _Clock()
    p = _policy(clk)
    d = p.decide(PolicyInputs(replicas=1))
    assert d.action == "hold" and "min_replicas=1" in d.reason
    desc = p.describe()
    assert desc["min_replicas"] == 1 and desc["max_replicas"] == 3
    assert desc["queue_per_replica_out"] == 8.0
    assert desc["burn_out"] == 2.0 and desc["burn_in"] == 0.5


def test_max_burn_handles_full_slo_status_dict():
    status = {"objectives": {
        "ttft": {"burn": {"fast/1m": {"burn": 3.5, "ratio": 0.9},
                          "slow/30m": {"burn": 1.1}}},
        "availability": {"burn": {"fast/1m": {"burn": 0.2}}}},
        "evals": 7, "history": [], "dumps": []}      # non-dict values ride
    assert max_burn(status) == pytest.approx(3.5)
    assert max_burn(None) == 0.0
    assert max_burn({}) == 0.0
    # a bare objectives map (no wrapper) also reads
    assert max_burn({"o": {"burn": {"w": {"burn": 2.0}}}}) == 2.0


def test_inputs_from_windows_extraction():
    merged = {"windows": {"10s": {"signals": {
        "serve.queue_depth": {"kind": "gauge", "last_sum": 12.0},
        "serve.ttft_ms": {"kind": "dist", "p95": 850.0},
        "serve.blocks_total": {"kind": "gauge", "last_sum": 100.0},
        "serve.blocks_free": {"kind": "gauge", "last_sum": 25.0}}}}}
    inp = inputs_from_windows(merged, "10s", replicas=3)
    assert inp.queue_depth == 12.0
    assert inp.queue_per_replica == pytest.approx(4.0)
    assert inp.ttft_p95_ms == 850.0
    assert inp.occupancy_pct == pytest.approx(75.0)
    # an absent window reads as no pressure (and 0/0 occupancy is 0)
    empty = inputs_from_windows({}, "10s", replicas=1)
    assert empty.queue_depth == 0.0 and empty.occupancy_pct == 0.0


# -- the autoscale fault scope ------------------------------------------------


def test_autoscale_fault_parsing_and_sites():
    spec = parse_autoscale_fault("autoscale:spawn_fail")
    assert spec == AutoscaleFaultSpec("spawn_fail") and spec.site == "spawn"
    spec = parse_autoscale_fault("autoscale:flap:after=3")
    assert spec.after == 3 and spec.site == "decide"
    assert spec.matches("decide", n=3) and not spec.matches("decide", n=2)
    assert not spec.matches("spawn", n=99)
    assert parse_autoscale_fault("deploy:crash_mid_roll") is None
    with pytest.raises(ValueError):
        parse_autoscale_fault("autoscale:meteor")
    with pytest.raises(ValueError):
        parse_autoscale_fault("autoscale:flap:jitter=1")
    # the shared parse_fault router validates the scope (typos fail
    # loudly at the first gang hook) but ignores it at gang sites
    assert parse_fault("autoscale:stall_drain") is None
    with pytest.raises(ValueError):
        parse_fault("autoscale:meteor")


# -- membership: the fleet-owned counters + no per-slot leaks -----------------


def test_fleet_metrics_survive_membership_changes():
    """Canary/handoff/journal counters are FLEET-owned: scale events must
    not lose them (the per-slot lists are replaced; fleet_metrics never
    is)."""
    rs = ReplicaSet([_Eng(), _Eng()])
    rs.fleet_metrics.count("handoffs", 5)
    rs.fleet_metrics.count("journal_resumes", 2)
    rs.fleet_metrics.count("warm_replays", 7)
    for _ in range(3):
        i = rs.add_replica(_Eng())
        rs.remove_replica(i)
    rs.remove_replica(0)
    rs.add_replica(_Eng())
    assert rs.fleet_metrics.handoffs == 5
    assert rs.fleet_metrics.journal_resumes == 2
    snap = rs.snapshot()                    # merged through the fleet view
    assert snap["serve.handoffs"] == 5.0
    assert snap["serve.warm_replays"] == 7.0
    assert snap["gateway.replicas"] == 2.0


def test_remove_replica_refuses_last_and_bounds():
    rs = ReplicaSet([_Eng()])
    with pytest.raises(ValueError):
        rs.remove_replica(0)
    rs.add_replica(_Eng())
    with pytest.raises(IndexError):
        rs.remove_replica(5)


def test_ten_scale_cycles_leak_no_per_slot_state():
    """PrefixIndex slot maps and FleetTelemetry per-source caches are
    dropped with the slot — ten scale cycles leave the router-side
    structures exactly as a never-scaled fleet."""
    rs = ReplicaSet([_Eng()])
    rs.telemetry = FleetTelemetry()
    for cycle in range(10):
        i = rs.add_replica(_Eng())
        rs.telemetry.ingest(
            f"replica{i}",
            {"samples": [{"seq": 1, "t": 0.0, "signals": {}}],
             "last_seq": 1})
        rs.prefix_index.observe(
            i, {"seq": 1, "events": [
                ("register", f"k{cycle}", [1, 2, 3, 4])]})
        rs.remove_replica(i)
    assert rs.telemetry.sources() == []            # every source dropped
    with rs.prefix_index._lock:
        assert set(rs.prefix_index._seq) <= {0}
        assert set(rs.prefix_index._last_poll) <= {0}
        held = set().union(*rs.prefix_index._holders.values()) \
            if rs.prefix_index._holders else set()
    assert held == set()                           # no ghost holders
    assert len(rs.replicas) == 1 and rs.outstanding() == [0]


# -- the reconciler: burst out, idle in, surge semantics ----------------------


def _controller(rs, clk, state, tmp_path=None, **kw):
    spawned = []

    def spawn():
        e = _Eng(rs_ref=rs)
        spawned.append(e)
        return e

    kw.setdefault("policy", _policy(clk))
    ctrl = AutoscaleController(
        rs, spawn_fn=spawn, merged_fn=_merged_fn(state),
        journal_dir=str(tmp_path / "scale-journal") if tmp_path else None,
        clock=clk, drain_timeout_s=kw.pop("drain_timeout_s", 5.0), **kw)
    ctrl._spawned = spawned
    return ctrl


def test_burst_scales_out_idle_scales_in_zero_failures(tmp_path):
    """THE acceptance drill: injected queue pressure takes 1 -> 3 with
    surge admission (warm + probe provably before routing), idle drains
    back to 1, every in-flight submission succeeds and greedy outputs are
    bit-identical across every membership change, and every event left a
    terminal journal."""
    clk = _Clock()
    first = _Eng()
    rs = ReplicaSet([first])
    state = {"queue": 100.0}
    ctrl = _controller(rs, clk, state, tmp_path)
    prompt, steps = [5, 6, 7], 4
    expected = rs.submit_generate(prompt, steps).result(1.0)

    sizes = []
    for _ in range(3):                       # out, out, hold-at-max
        ctrl.tick()
        sizes.append(len(rs.replicas))
        assert rs.submit_generate(prompt, steps).result(1.0) == expected
    assert sizes == [2, 3, 3]
    assert ctrl.last_decision["reason"].startswith("out pressed") \
        or "max_replicas" in ctrl.last_decision["reason"]
    for e in ctrl._spawned:                  # surge order, per candidate
        assert e.events[:3] == ["start", "warmup", "probe"]
    assert rs.fleet_metrics.scale_outs == 2

    state["queue"] = 0.0                     # the burst ends
    for _ in range(3):                       # in, in, hold-at-min
        ctrl.tick()
        assert rs.submit_generate(prompt, steps).result(1.0) == expected
    assert len(rs.replicas) == 1
    assert rs.fleet_metrics.scale_ins == 2
    assert first.stopped                     # retired victims were stopped
    assert ctrl.scale_events == 4 and ctrl.last_error is None

    # gauges track the converged fleet; journal is terminal and stepped
    g = rs.fleet_metrics.gauges_view()
    assert g["fleet_size"] == 1.0 and g["desired_replicas"] == 1.0
    assert rs.snapshot()["serve.scale_outs"] == 2.0
    jdir = str(tmp_path / "scale-journal")
    assert RolloutJournal.load(jdir) is None         # nothing left open
    with open(os.path.join(jdir, "steps.jsonl")) as f:
        steps_rows = [json.loads(line) for line in f]
    assert [r["step"] for r in steps_rows] == ["drained", "removed"]


def test_scale_out_prefers_spawn_fn_then_clone_fresh():
    class _Cloner(_Eng):
        def clone_fresh(self):
            return _Eng()

    clk = _Clock()
    rs = ReplicaSet([_Cloner()])
    ctrl = AutoscaleController(rs, policy=_policy(clk),
                               merged_fn=_merged_fn({"queue": 100.0}),
                               clock=clk)
    ctrl.tick()
    assert len(rs.replicas) == 2             # clone_fresh carried the spawn
    rs2 = ReplicaSet([_Eng()])               # no spawn_fn, no clone_fresh
    ctrl2 = AutoscaleController(rs2, policy=_policy(clk),
                                merged_fn=_merged_fn({"queue": 100.0}),
                                clock=clk)
    ctrl2.tick()
    assert len(rs2.replicas) == 1 and "spawn_fn" in ctrl2.last_error


def test_disabled_and_draining_controllers_hold_still():
    clk = _Clock()
    rs = ReplicaSet([_Eng()])
    ctrl = _controller(rs, clk, {"queue": 100.0}, enabled=False)
    assert ctrl.tick() is None and len(rs.replicas) == 1
    assert ctrl.configure(enabled=True)["enabled"] is True
    with pytest.raises(ValueError):
        ctrl.configure(min_replicas=0)
    with pytest.raises(ValueError):
        ctrl.configure(min_replicas=3, max_replicas=2)
    ctrl.configure(max_replicas=2)
    ctrl.tick()
    ctrl.tick()
    assert len(rs.replicas) == 2             # the moved bound clamps


# -- injected faults: spawn failure, stuck drain, mid-scale crash, flap -------


def test_spawn_fail_costs_the_fleet_nothing(monkeypatch, tmp_path):
    monkeypatch.setenv("DDW_FAULT", "autoscale:spawn_fail")
    clk = _Clock()
    rs = ReplicaSet([_Eng()])
    ctrl = _controller(rs, clk, {"queue": 100.0}, tmp_path)
    d = ctrl.tick()                          # decision out; actuation fails
    assert d.action == "out"
    assert len(rs.replicas) == 1             # candidate never joined
    assert ctrl.scale_events == 0
    assert rs.fleet_metrics.scale_outs == 0
    assert "spawn" in ctrl.last_error
    assert RolloutJournal.load(str(tmp_path / "scale-journal")) is None
    monkeypatch.delenv("DDW_FAULT")          # cleared: the next tick lands
    ctrl.tick()
    assert len(rs.replicas) == 2


def test_stall_drain_aborts_scale_in_replica_keeps_serving(
        monkeypatch, tmp_path):
    monkeypatch.setenv("DDW_FAULT", "autoscale:stall_drain")
    clk = _Clock()
    rs = ReplicaSet([_Eng(), _Eng()])
    ctrl = _controller(rs, clk, {"queue": 0.0}, tmp_path,
                       drain_timeout_s=0.0)  # deadline at once: the stall's
    d = ctrl.tick()                          # should_abort fires immediately
    assert d.action == "in"
    assert len(rs.replicas) == 2             # the victim was NOT removed
    assert rs.breakers[0].state == "closed"  # ...and re-admitted to routing
    assert rs.fleet_metrics.scale_ins == 0
    assert "drain stall" in ctrl.last_error
    assert RolloutJournal.load(str(tmp_path / "scale-journal")) is None


def test_crash_mid_scale_leaves_journal_for_reconcile(monkeypatch, tmp_path):
    """Gateway killed between admission and finalize: the journal stays
    non-terminal; a restarted controller's reconcile() finalizes it and
    counts journal_resumes — the crash window the journal exists for."""
    monkeypatch.setenv("DDW_FAULT", "autoscale:crash_mid_scale")
    clk = _Clock()
    rs = ReplicaSet([_Eng()])
    ctrl = _controller(rs, clk, {"queue": 100.0}, tmp_path)
    with pytest.raises(AutoscaleCrash):
        ctrl.tick()
    assert len(rs.replicas) == 2             # admitted before the crash
    assert ctrl._deploy_status["deploying"] is False   # flag restored
    jdir = str(tmp_path / "scale-journal")
    left = RolloutJournal.load(jdir)
    assert left is not None and left["meta"]["direction"] == "out"
    assert [r["step"] for r in left["steps"]] == [
        "warmed", "probed", "admitted"]

    monkeypatch.delenv("DDW_FAULT")          # "restart": a fresh controller
    ctrl2 = _controller(rs, clk, {"queue": 0.0}, tmp_path)
    got = ctrl2.reconcile()
    assert got is not None and got["meta"]["direction"] == "out"
    assert RolloutJournal.load(jdir) is None             # finalized
    assert rs.fleet_metrics.journal_resumes == 1
    assert ctrl2.reconcile() is None         # idempotent: clean journal


def test_flap_fault_is_damped_by_cooldowns(monkeypatch):
    """Alternating synthetic pressure (the flap arm) against real
    cooldowns: 20 decide ticks move the fleet at most once — the policy's
    anti-thrash machinery, exercised end to end."""
    monkeypatch.setenv("DDW_FAULT", "autoscale:flap")
    clk = _Clock()
    rs = ReplicaSet([_Eng()])
    ctrl = _controller(
        rs, clk, {"queue": 0.0},
        policy=_policy(clk, out_cooldown_s=100.0, in_cooldown_s=100.0))
    for _ in range(20):
        ctrl.tick()
        clk.advance(1.0)                     # 20s elapse: inside cooldown
    assert ctrl.scale_events == 1            # the first out; nothing since
    assert len(rs.replicas) == 2
    assert ctrl.ticks == 20


# -- mutual exclusion with rolling deploys ------------------------------------


def test_autoscale_blocked_while_deploy_lock_held():
    clk = _Clock()
    rs = ReplicaSet([_Eng()])
    lock = threading.Lock()
    status = {"deploying": True, "status": "rolling"}
    ctrl = AutoscaleController(
        rs, policy=_policy(clk), merged_fn=_merged_fn({"queue": 100.0}),
        deploy_lock=lock, deploy_status=status, clock=clk,
        spawn_fn=lambda: _Eng())
    d = ctrl.tick()
    assert d.action == "hold" and "rollout holds the deploy lock" in d.reason
    assert ctrl.blocked == 1
    assert rs.fleet_metrics.autoscale_blocked == 1
    assert len(rs.replicas) == 1
    assert status == {"deploying": True, "status": "rolling"}   # untouched
    status["deploying"] = False              # rollout finished
    assert ctrl.tick().action == "out" and len(rs.replicas) == 2
    assert status["status"] == "rolling"     # scale event restored it


# -- the HTTP surface: /readyz, /stats, POST /admin/autoscale -----------------


def test_gateway_autoscale_http_surface(tmp_path):
    clk = _Clock()
    rs = ReplicaSet([_Eng()])
    state = {"queue": 0.0}
    gw = Gateway(rs, supervise=False, autoscale=True,
                 autoscale_journal_dir=str(tmp_path / "scale-journal"),
                 autoscale_kw=dict(
                     policy=_policy(clk), clock=clk,
                     spawn_fn=lambda: _Eng(),
                     merged_fn=_merged_fn(state),
                     slo_status_fn=None,
                     tick_interval_s=3600.0))   # ticks only when WE tick
    gw.start(warmup_prompt_lens=())
    try:
        cli = GatewayClient("127.0.0.1", gw.port, max_retries=0)
        _status, ready = cli.readyz()
        assert ready["autoscale"]["enabled"] is True
        assert ready["autoscale"]["actual"] == 1

        state["queue"] = 100.0
        gw.autoscaler.tick()
        stats = cli.stats()
        a = stats["autoscale"]
        assert a["actual"] == 2 and a["scale_events"] == 1
        assert a["last_decision"]["action"] == "out"
        assert a["policy"]["max_replicas"] == 3
        text = cli.metrics_text()
        assert "ddw_serve_scale_outs_total 1" in text
        assert "ddw_serve_desired_replicas" in text
        assert "ddw_serve_fleet_size" in text

        # the admin surface: bounds move, bad bounds 400, disable sticks
        out = cli._json_call("POST", "/admin/autoscale",
                             {"max_replicas": 2})
        assert out["policy"]["max_replicas"] == 2
        with pytest.raises(GatewayError) as ei:
            cli._json_call("POST", "/admin/autoscale", {"min_replicas": 0})
        assert ei.value.status == 400
        with pytest.raises(GatewayError) as ei:
            cli._json_call("POST", "/admin/autoscale",
                           {"enabled": "sideways"})
        assert ei.value.status == 400
        out = cli._json_call("POST", "/admin/autoscale", {"enabled": False})
        assert out["enabled"] is False and gw.autoscaler.tick() is None

        # 409 under the deploy lock — same semantics as /admin/deploy
        with gw._deploy_lock:
            gw.deploy_status["deploying"] = True
        try:
            with pytest.raises(GatewayError) as ei:
                cli._json_call("POST", "/admin/autoscale", {"enabled": True})
            assert ei.value.status == 409
            assert ei.value.body["error"] == "deploy_in_progress"
        finally:
            with gw._deploy_lock:
                gw.deploy_status["deploying"] = False

        # no autoscaler -> 404 (the discoverable off switch)
        saved, gw.autoscaler = gw.autoscaler, None
        try:
            with pytest.raises(GatewayError) as ei:
                cli._json_call("POST", "/admin/autoscale", {"enabled": True})
            assert ei.value.status == 404
        finally:
            gw.autoscaler = saved
    finally:
        gw.stop()
    assert gw.autoscaler is None             # drain stopped the reconciler
