"""C++ shard codec: build, parity with the Python codec, error paths, speed sanity."""

import os
import time

import pytest

from ddw_tpu.data.store import Record, TableStore
from ddw_tpu.native.codec import native_available, read_shard_native


@pytest.fixture(scope="module")
def shard(tmp_path_factory):
    store = TableStore(str(tmp_path_factory.mktemp("nat")))
    recs = [Record(f"/img/{i:04d}.jpg", os.urandom(200 + i), "roses", i % 5)
            for i in range(500)]
    tbl = store.write("t", recs, shard_size=500)
    return tbl.shard_paths[0], recs


def test_native_builds():
    assert native_available(), "g++ build of the codec failed"


def test_native_matches_python(shard, monkeypatch):
    path, recs = shard
    native = read_shard_native(path)
    # force the python path for comparison
    monkeypatch.setenv("DDW_NATIVE_CODEC", "0")
    from ddw_tpu.data.store import read_shard

    python = list(read_shard(path))
    assert len(native) == len(python) == 500
    for a, b in zip(native, python):
        assert (a.path, a.content, a.label, a.label_idx) == \
               (b.path, b.content, b.label, b.label_idx)


def test_store_uses_native_by_default(shard):
    path, recs = shard
    from ddw_tpu.data.store import read_shard

    got = list(read_shard(path))
    assert [r.content for r in got] == [r.content for r in recs]


def test_native_rejects_garbage(tmp_path):
    bad = tmp_path / "bad.ddws"
    bad.write_bytes(b"NOPE" + b"\x00" * 100)
    with pytest.raises(RuntimeError, match="header error"):
        read_shard_native(str(bad))


def test_native_rejects_truncated(shard, tmp_path):
    path, _ = shard
    data = open(path, "rb").read()
    trunc = tmp_path / "trunc.ddws"
    trunc.write_bytes(data[: len(data) // 2])
    with pytest.raises(RuntimeError, match="parse error"):
        read_shard_native(str(trunc))


def test_contents_fast_path_matches(shard, monkeypatch):
    """(content, label_idx) hot path: native == python fallback == full records."""
    path, recs = shard
    from ddw_tpu.data.store import read_shard_contents
    from ddw_tpu.native.codec import read_shard_contents_native

    native = read_shard_contents_native(path)
    monkeypatch.setenv("DDW_NATIVE_CODEC", "0")
    python = list(read_shard_contents(path))
    assert native == python
    assert [c for c, _ in native] == [r.content for r in recs]
    assert [i for _, i in native] == [r.label_idx for r in recs]


def test_contents_native_not_slower(shard, monkeypatch):
    """Non-regression: both paths are memory-bound on the content copy (measured
    ~parity at 3KB records); the native path should not be far slower. Wall-clock
    under CI load is noisy, so take best-of-5 batches and allow 3x slack."""
    path, _ = shard
    from ddw_tpu.data.store import read_shard_contents
    from ddw_tpu.native.codec import read_shard_contents_native

    read_shard_contents_native(path)  # warm (build + page cache)

    def best_of(fn, batches=5, reps=10):
        best = float("inf")
        for _ in range(batches):
            t0 = time.perf_counter()
            for _ in range(reps):
                fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t_native = best_of(lambda: read_shard_contents_native(path))
    monkeypatch.setenv("DDW_NATIVE_CODEC", "0")
    t_python = best_of(lambda: list(read_shard_contents(path)))
    assert t_native < t_python * 3.0, (t_native, t_python)
