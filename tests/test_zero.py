"""ZeRO-1 sharded-optimizer step: sharding coverage, DP equivalence, learning."""

import jax
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from ddw_tpu.models.registry import build_model
from ddw_tpu.parallel.zero import (
    make_zero_train_step,
    zero_fraction_sharded,
    zero_state_shardings,
)
from ddw_tpu.runtime.mesh import make_mesh, MeshSpec, DATA_AXIS
from ddw_tpu.train.step import init_state, make_train_step
from ddw_tpu.utils.config import ModelCfg, TrainCfg

IMG = (16, 16, 3)


def _setup(n_dev, model="small_cnn", opt="adam", lr=1e-2):
    mesh = make_mesh(MeshSpec(((DATA_AXIS, n_dev),)),
                     devices=jax.devices()[:n_dev])
    mcfg = ModelCfg(name=model, num_classes=5, dropout=0.0, dtype="float32")
    tcfg = TrainCfg(batch_size=8, learning_rate=lr, optimizer=opt)
    m = build_model(mcfg)
    state, tx = init_state(m, mcfg, tcfg, IMG, jax.random.PRNGKey(0))
    return mesh, m, state, tx


def _batch(n, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, *IMG).astype(np.float32),
            rng.randint(0, 5, size=(n,)).astype(np.int32))


def test_opt_state_actually_shards():
    mesh, m, state, tx = _setup(4)
    sh = zero_state_shardings(state, mesh)
    specs = [s.spec for s in jax.tree.leaves(sh.opt_state)]
    assert any(DATA_AXIS in (ax for ax in spec if ax) for spec in specs), specs
    # params stay replicated
    assert all(s.spec == P() for s in jax.tree.leaves(sh.params))
    assert zero_fraction_sharded(state, mesh) > 0.5


def test_zero_step_matches_plain_dp():
    """One step with sharded moments == one plain-DP step (same global batch)."""
    mesh, m, state, tx = _setup(4)
    imgs, lbls = _batch(32)

    plain = make_train_step(m, tx, mesh, donate=False)
    zero = make_zero_train_step(m, tx, mesh, donate=False)
    zstate = zero.place_state(state)

    s1, m1 = plain(state, imgs, lbls, jax.random.PRNGKey(1))
    s2, m2 = zero(zstate, imgs, lbls, jax.random.PRNGKey(1))
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), abs=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)
    # moments remain sharded after the step
    mu_leaves = jax.tree.leaves(
        jax.tree.map(lambda x: x.sharding.spec, s2.opt_state))
    assert any(DATA_AXIS in (ax for ax in spec if ax) for spec in mu_leaves)


@pytest.mark.slow   # tier-1 budget (PR 13): BN-model training keeps its
#                     tier-1 rep in test_train_step's resnet-family drill,
#                     and ZeRO semantics keep matches-plain-dp / learns /
#                     sharded-resume tier-1 above; this BN-under-ZeRO
#                     composition smoke rides tier-2
def test_zero_step_batchnorm_model_runs_syncbn():
    """BN models run under ZeRO with sync-BN semantics (global-batch stats);
    documented divergence from the per-shard DP step, so no equivalence assert."""
    import warnings

    mesh = make_mesh(MeshSpec(((DATA_AXIS, 2),)), devices=jax.devices()[:2])
    mcfg = ModelCfg(name="resnet18", num_classes=5, dropout=0.0,
                    width_mult=0.25, dtype="float32", freeze_base=False)
    tcfg = TrainCfg(batch_size=4, learning_rate=1e-2, optimizer="adam")
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # freeze_base=False: no random-frozen warning
        m = build_model(mcfg)
    state, tx = init_state(m, mcfg, tcfg, IMG, jax.random.PRNGKey(0))
    zero = make_zero_train_step(m, tx, mesh, donate=False)
    state = zero.place_state(state)
    imgs, lbls = _batch(8)
    state, metrics = zero(state, imgs, lbls, jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
    assert state.batch_stats  # running stats updated and carried


def test_resnet_frozen_random_backbone_warns():
    mcfg = ModelCfg(name="resnet18", num_classes=5, freeze_base=True,
                    allow_frozen_random=True)
    with pytest.warns(UserWarning, match="randomly initialized backbone"):
        build_model(mcfg)


def test_zero_step_learns():
    mesh, m, state, tx = _setup(8)
    zero = make_zero_train_step(m, tx, mesh)
    state = zero.place_state(state)
    imgs, lbls = _batch(64)
    losses = []
    for i in range(10):
        state, metrics = zero(state, imgs, lbls, jax.random.PRNGKey(i))
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]


def test_trainer_zero_fit_and_sharded_resume(tmp_path, silver):
    """TrainCfg.zero end-to-end: Trainer trains with sharded moments, writes
    sharded per-process checkpoints (no step_*/state.msgpack full-state file),
    and resumes from them to the same continuation."""
    import os

    from ddw_tpu.train.trainer import Trainer
    from ddw_tpu.utils.config import DataCfg, ModelCfg, TrainCfg

    train_tbl, val_tbl, _ = silver
    data = DataCfg(img_height=24, img_width=24)
    model = ModelCfg(name="small_cnn", num_classes=5, dropout=0.0,
                     dtype="float32")
    ckpt_dir = str(tmp_path / "zck")

    def cfg(epochs):
        return TrainCfg(batch_size=4, epochs=epochs, warmup_epochs=0,
                        learning_rate=1e-2, seed=0, zero=True,
                        checkpoint_dir=ckpt_dir, checkpoint_every_epochs=1)

    res = Trainer(data, model, cfg(2)).fit(train_tbl, val_tbl)
    assert res.epochs_run == 2 and np.isfinite(res.val_loss)
    # moments actually live sharded through the fit
    specs = [l.sharding.spec for l in jax.tree.leaves(res.state.opt_state)]
    assert any(DATA_AXIS in (ax for ax in s if ax) for s in specs)
    # checkpoints are the sharded format, not a rank-0 msgpack
    steps = sorted(d for d in os.listdir(ckpt_dir) if d.startswith("step_"))
    assert steps, ckpt_dir
    latest = os.path.join(ckpt_dir, steps[-1])
    assert os.path.exists(os.path.join(latest, "index.json"))
    assert os.path.exists(os.path.join(latest, "proc_0.bin"))
    assert not os.path.exists(os.path.join(latest, "state.msgpack"))

    # resume continues the step count
    res2 = Trainer(data, model, cfg(4)).fit(train_tbl, val_tbl, resume=True)
    assert res2.epochs_run == 4
    assert int(jax.device_get(res2.state.step)) == 2 * int(
        jax.device_get(res.state.step))


def test_zero_grad_accum_matches_single_shot():
    """ZeRO-1 with grad_accum_steps=2 == ZeRO-1 single-shot on the same
    global batch (the newly-permitted train.zero + accumulation combo)."""
    mesh, m, state, tx = _setup(4)
    imgs, lbls = _batch(32)

    one = make_zero_train_step(m, tx, mesh, donate=False)
    two = make_zero_train_step(m, tx, mesh, donate=False, grad_accum_steps=2)
    s1, m1 = one(one.place_state(state), imgs, lbls, jax.random.PRNGKey(1))
    s2, m2 = two(two.place_state(state), imgs, lbls, jax.random.PRNGKey(1))
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), abs=1e-5)
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_zero_accum_microbatch_must_divide_data_axis():
    """batch % accum == 0 is not enough: the microbatch (b//accum) must also
    divide the data axis, else the P(None, axis) constraint pads unevenly and
    the device-local-transpose property silently breaks. Refuse loudly."""
    mesh, m, state, tx = _setup(4)
    step = make_zero_train_step(m, tx, mesh, donate=False, grad_accum_steps=4)
    imgs, lbls = _batch(8)  # 8 % 4 == 0 but microbatch 2 < 4 devices
    with pytest.raises(ValueError, match="axis size 4"):
        step(step.place_state(state), imgs, lbls, jax.random.PRNGKey(1))


def test_trainer_zero_with_ema(tmp_path, silver):
    """train.zero=true + ema_decay (refusal removed): the Polyak shadow is
    param-shaped opt_state, so the generic ZeRO leaf sharding covers it —
    the fit runs, eval reads the shadow, and the shadow lives sharded."""
    from ddw_tpu.train.step import ema_params
    from ddw_tpu.train.trainer import Trainer
    from ddw_tpu.utils.config import DataCfg, ModelCfg, TrainCfg

    train_tbl, val_tbl, _ = silver
    data = DataCfg(img_height=24, img_width=24)
    model = ModelCfg(name="small_cnn", num_classes=5, dropout=0.0,
                     dtype="float32")
    cfg = TrainCfg(batch_size=4, epochs=2, warmup_epochs=0,
                   learning_rate=1e-2, seed=0, zero=True, ema_decay=0.5)
    res = Trainer(data, model, cfg).fit(train_tbl, val_tbl)
    assert res.epochs_run == 2 and np.isfinite(res.val_loss)
    shadow = ema_params(res.state)
    assert shadow is not None
    specs = [l.sharding.spec for l in jax.tree.leaves(shadow)]
    assert any(DATA_AXIS in (ax for ax in s if ax) for s in specs), specs
