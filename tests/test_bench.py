"""bench.py contract: one JSON line, full matrix, MFU fields present.

Runs the benchmark in DDW_BENCH_SMOKE mode (tiny shapes, 2 measured steps) on
whatever backend the test session uses — the assertions check structure and
positivity, not absolute performance.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(**extra_env):
    """Smoke-run bench.py on the pinned virtual-CPU backend and parse its
    one-line JSON (PALLAS_AXON_POOL_IPS="" skips the axon sitecustomize so
    the assertions never depend on the TPU tunnel; same recipe as the root
    conftest)."""
    env = dict(os.environ, DDW_BENCH_SMOKE="1", PALLAS_AXON_POOL_IPS="",
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               **extra_env)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.fixture(scope="module")
def bench_json():
    return _run_bench()


def test_headline_contract(bench_json):
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in bench_json
    assert bench_json["value"] > 0
    assert bench_json["unit"] == "images/sec/chip"


def test_matrix_rows(bench_json):
    configs = bench_json["configs"]
    for name in ("mobilenet_v2_frozen", "mobilenet_v2_frozen_feature_cache",
                 "mobilenet_v2_unfrozen", "resnet50",
                 "vit", "lm_flash", "lm_moe",
                 "e2e_raw_u8", "e2e_feature_cache"):
        row = configs[name]
        assert "error" not in row, f"{name}: {row}"
        assert row["rate_per_chip"] > 0
        assert row["step_time_ms"] > 0
        # XLA cost analysis may be unavailable on some backends; when present
        # the derived fields must be populated.
        if row["step_flops"]:
            assert row["achieved_tflops_per_chip"] > 0
    assert configs["lm_flash"]["unit"] == "tokens/sec/chip"
    # e2e rows measure the loader-fed system: always host-loop, and they
    # must say what fed them (encoding + table size, for the honest caveat).
    for name in ("e2e_raw_u8", "e2e_feature_cache"):
        row = configs[name]
        assert row["chain"] == "loop"
        assert row["pipeline"] == "loader_prefetch"
        assert row["table_records"] > 0


def test_flops_ordering(bench_json):
    """Unfrozen backward must cost more FLOPs than frozen (backbone skipped),
    and the cached-feature head step must cost far less than either (the whole
    backbone forward is gone)."""
    c = bench_json["configs"]
    fro = c["mobilenet_v2_frozen"]["step_flops"]
    unf = c["mobilenet_v2_unfrozen"]["step_flops"]
    head = c["mobilenet_v2_frozen_feature_cache"]["step_flops"]
    if fro and unf:
        assert unf > fro * 1.5
    if fro and head:
        assert head < fro / 50


def test_host_pipeline(bench_json):
    host = bench_json["host_pipeline"]
    if "error" in host:
        pytest.skip(host["error"])
    assert host["pil_images_per_sec"] > 0
    if host["native_images_per_sec"] is not None:
        assert host["native_images_per_sec"] > 0
        assert host["native_ok_fraction"] == 1.0


def test_scan_chained_rows():
    """DDW_BENCH_CHAIN=scan: the lax.scan megastep arm produces valid rows
    tagged "chain": "scan" for vision, feature-cache and LM families — the
    arm chip_queue.sh's mn_frozen_scan item relies on during scarce tunnel
    windows must not regress silently in CI."""
    d = _run_bench(
        DDW_BENCH_CHAIN="scan",
        DDW_BENCH_ONLY=("mobilenet_v2_frozen,"
                        "mobilenet_v2_frozen_feature_cache,lm_flash"))
    assert set(d["configs"]) == {"mobilenet_v2_frozen",
                                 "mobilenet_v2_frozen_feature_cache",
                                 "lm_flash"}
    for name, row in d["configs"].items():
        assert row["chain"] == "scan", (name, row)
        assert row["rate_per_chip"] > 0, (name, row)
