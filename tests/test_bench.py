"""bench.py contract: one JSON line, full matrix, MFU fields present.

Runs the benchmark in DDW_BENCH_SMOKE mode (tiny shapes, 2 measured steps) on
whatever backend the test session uses — the assertions check structure and
positivity, not absolute performance.
"""

import json
import os
import subprocess
import sys

import pytest

# bench arms run full training sweeps — beyond the tier-1 wall-clock budget
pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(**extra_env):
    """Smoke-run bench.py on the pinned virtual-CPU backend and parse its
    one-line JSON (PALLAS_AXON_POOL_IPS="" skips the axon sitecustomize so
    the assertions never depend on the TPU tunnel; same recipe as the root
    conftest)."""
    env = dict(os.environ, DDW_BENCH_SMOKE="1", PALLAS_AXON_POOL_IPS="",
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               **extra_env)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=900)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.fixture(scope="module")
def bench_json():
    return _run_bench()


def test_headline_contract(bench_json):
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in bench_json
    assert bench_json["value"] > 0
    assert bench_json["unit"] == "images/sec/chip"


def test_matrix_rows(bench_json):
    configs = bench_json["configs"]
    for name in ("mobilenet_v2_frozen", "mobilenet_v2_frozen_feature_cache",
                 "mobilenet_v2_unfrozen", "resnet50",
                 "vit", "lm_flash", "lm_moe",
                 "e2e_raw_u8", "e2e_feature_cache"):
        row = configs[name]
        assert "error" not in row, f"{name}: {row}"
        assert row["rate_per_chip"] > 0
        assert row["step_time_ms"] > 0
        # XLA cost analysis may be unavailable on some backends; when present
        # the derived fields must be populated.
        if row["step_flops"]:
            assert row["achieved_tflops_per_chip"] > 0
    assert configs["lm_flash"]["unit"] == "tokens/sec/chip"
    # e2e rows measure the loader-fed system: always host-loop, and they
    # must say what fed them (encoding + table size, for the honest caveat).
    for name in ("e2e_raw_u8", "e2e_feature_cache"):
        row = configs[name]
        assert row["chain"] == "loop"
        assert row["pipeline"] == "loader_prefetch"
        assert row["table_records"] > 0


def test_flops_ordering(bench_json):
    """Unfrozen backward must cost more FLOPs than frozen (backbone skipped),
    and the cached-feature head step must cost far less than either (the whole
    backbone forward is gone)."""
    c = bench_json["configs"]
    fro = c["mobilenet_v2_frozen"]["step_flops"]
    unf = c["mobilenet_v2_unfrozen"]["step_flops"]
    head = c["mobilenet_v2_frozen_feature_cache"]["step_flops"]
    if fro and unf:
        assert unf > fro * 1.5
    if fro and head:
        assert head < fro / 50


def test_host_pipeline(bench_json):
    host = bench_json["host_pipeline"]
    if "error" in host:
        pytest.skip(host["error"])
    assert host["pil_images_per_sec"] > 0
    if host["native_images_per_sec"] is not None:
        assert host["native_images_per_sec"] > 0
        assert host["native_ok_fraction"] == 1.0


def test_banked_window_fallback(tmp_path, monkeypatch):
    """When the tunnel is down at capture, bench.py falls back to the
    default-knob measurements this round's queue windows banked under
    benchruns/ — merged newest-wins per config, headline value from the
    frozen row, honestly labeled — and NEVER merges A/B-arm outputs (same
    config names, overridden knobs)."""
    import time as _time

    import bench

    now = _time.time()

    def write(name, configs, mtime):
        p = tmp_path / f"{name}.out"
        p.write_text(json.dumps({
            "device": {"kind": "TPU v5 lite", "n": 1},
            "configs": configs}) + "\n")
        os.utime(p, (mtime, mtime))

    write("resnet50", {"resnet50": {"rate_per_chip": 2000.0}}, now - 3000)
    # an older window measured the frozen row slower; the newer wins
    write("mn_frozen_repeat",
          {"mobilenet_v2_frozen": {"rate_per_chip": 26000.0}}, now - 3500)
    write("e2e_loader", {"mobilenet_v2_frozen": {"rate_per_chip": 39000.0},
                         "e2e_raw_u8": {"error": "wedged"}}, now - 2000)
    # A/B arm at overridden knobs: must NOT appear as lm_flash
    write("ab_lm_plain", {"lm_flash": {"rate_per_chip": 9e9}}, now - 1000)
    # a previous round's leftover: outside the 24 h staleness bound
    write("lm_moe", {"lm_moe": {"rate_per_chip": 5.0}}, now - 30 * 3600)
    monkeypatch.setenv("DDW_BENCH_RUNDIR", str(tmp_path))

    got = bench._banked_window_fallback()
    assert got["live_measurement"] is False
    assert got["value"] == 39000.0  # newest frozen row wins
    assert got["vs_baseline"] == round(39000.0 / bench.BASELINE_IPS, 3)
    assert got["configs"]["resnet50"]["rate_per_chip"] == 2000.0
    assert "lm_flash" not in got["configs"]
    assert "e2e_raw_u8" not in got["configs"]  # error rows never merge
    assert "lm_moe" not in got["configs"]  # stale rounds never merge
    assert got["config_sources"]["mobilenet_v2_frozen"].startswith(
        "benchruns/e2e_loader.out @ ")
    assert got["device"]["kind"] == "TPU v5 lite"

    # a banked payload that leaked into an .out must never re-enter the merge
    write("vit", {"mobilenet_v2_frozen": {"rate_per_chip": 1.0}}, now - 500)
    p = tmp_path / "vit.out"
    leaked = json.loads(p.read_text())
    leaked["live_measurement"] = False
    p.write_text(json.dumps(leaked) + "\n")
    os.utime(p, (now - 500, now - 500))
    assert bench._banked_window_fallback()["value"] == 39000.0

    monkeypatch.setenv("DDW_BENCH_RUNDIR", str(tmp_path / "empty"))
    assert bench._banked_window_fallback() is None  # honest-null path


def test_default_knob_items_match_queue_script():
    """bench._DEFAULT_KNOB_ITEMS must track tools/chip_queue.sh: every queue
    item that invokes bench.py at default knobs (only the stall budget and
    the config selector set) belongs in the fallback merge, and every
    overridden-knob arm (ab_*, scan-chained, int8) must stay out. Guards the
    two-file pairing the same way the matrix/_CONFIG_NAMES check guards
    bench.py internally."""
    import re

    import bench

    script = open(os.path.join(REPO, "tools", "chip_queue.sh")).read()
    default_knob = set()
    for name, cmd in re.findall(
            r'run_item\s+(\S+)\s+"([^"]*bench\.py[^"]*)"', script):
        env_keys = set(re.findall(r"(DDW_[A-Z0-9_]+)=", cmd))
        if env_keys <= {"DDW_BENCH_STALL_S", "DDW_BENCH_ONLY"}:
            default_knob.add(name)
    assert default_knob == set(bench._DEFAULT_KNOB_ITEMS)


def test_vit_hidden_override_builds_tile_geometry():
    """ModelCfg.hidden=256 + num_heads=2 (the ab_vit_tile geometry) must
    reach the ViT: encoder width, mlp_dim 4x ratio, and head_dim 128 — the
    full-tile shape tools/mxu_roofline.py shows lifts the MFU ceiling from
    59% to 94%."""
    import jax
    import jax.numpy as jnp

    from ddw_tpu.models.registry import build_model
    from ddw_tpu.utils.config import ModelCfg

    model = build_model(ModelCfg(name="vit", num_classes=5, hidden=256,
                                 num_heads=2))
    assert model.hidden == 256
    assert model.mlp_dim == 1024
    assert model.num_heads == 2
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 32, 32, 3)), train=False)["params"]
    q = params["backbone_block0"]["attn"]["query"]["kernel"]
    assert q.shape == (256, 2, 128)  # (hidden, heads, head_dim=128)


def test_tile_geometry_arm_rows():
    """The ab_lm_tile / ab_vit_tile knobs must produce valid rows tagged
    with the non-default geometry they measured (the chip arms' outputs are
    read by humans folding them into BASELINE.md — a silently-default row
    would record the wrong experiment)."""
    d = _run_bench(DDW_BENCH_LM_HEADS="2",
                   DDW_BENCH_VIT_HIDDEN="64", DDW_BENCH_VIT_HEADS="2",
                   DDW_BENCH_ONLY="vit,lm_flash")
    vit, lm = d["configs"]["vit"], d["configs"]["lm_flash"]
    assert vit["rate_per_chip"] > 0
    assert vit["model_shape"] == {"hidden": 64, "num_heads": 2}
    assert lm["rate_per_chip"] > 0
    assert lm["num_heads"] == 2


def test_scan_chained_rows():
    """DDW_BENCH_CHAIN=scan: the lax.scan megastep arm produces valid rows
    tagged "chain": "scan" for vision, feature-cache and LM families — the
    arm chip_queue.sh's mn_frozen_scan item relies on during scarce tunnel
    windows must not regress silently in CI."""
    d = _run_bench(
        DDW_BENCH_CHAIN="scan",
        DDW_BENCH_ONLY=("mobilenet_v2_frozen,"
                        "mobilenet_v2_frozen_feature_cache,lm_flash"))
    assert set(d["configs"]) == {"mobilenet_v2_frozen",
                                 "mobilenet_v2_frozen_feature_cache",
                                 "lm_flash"}
    for name, row in d["configs"].items():
        assert row["chain"] == "scan", (name, row)
        assert row["rate_per_chip"] > 0, (name, row)


def test_fused_chain_arm_reports_dispatch_overhead():
    """DDW_BENCH_CHAIN=K (the steps_per_dispatch A/B arm): rows must carry
    the fused-chain tag AND the measured host-loop delta
    (dispatch_overhead_ms_per_step) — the number the amortization claim
    rests on — in smoke mode on CPU, so the arm can't regress silently."""
    d = _run_bench(DDW_BENCH_CHAIN="2",
                   DDW_BENCH_ONLY=("mobilenet_v2_frozen_feature_cache,"
                                   "lm_flash"))
    for name in ("mobilenet_v2_frozen_feature_cache", "lm_flash"):
        row = d["configs"][name]
        assert "error" not in row, (name, row)
        assert row["chain"] == 2 and row["chain_k"] == 2, (name, row)
        assert row["rate_per_chip"] > 0, (name, row)
        assert row["loop_step_time_ms"] > 0, (name, row)
        # the delta is a measurement — sign depends on backend noise; the
        # contract is that it was measured and reported
        assert "dispatch_overhead_ms_per_step" in row, (name, row)


def test_chain_env_validation():
    """A typo'd DDW_BENCH_CHAIN must refuse loudly at import, not silently
    bench the loop arm (same contract as the other knob parsers)."""
    import subprocess

    for bad in ("chain", "1", "-3"):
        out = subprocess.run(
            [sys.executable, "-c", "import bench"],
            capture_output=True, text=True, cwd=REPO,
            env=dict(os.environ, DDW_BENCH_CHAIN=bad, PALLAS_AXON_POOL_IPS="",
                     JAX_PLATFORMS="cpu"))
        assert out.returncode != 0, bad
        assert "DDW_BENCH_CHAIN" in out.stderr, out.stderr[-500:]
