"""Serving tests: packaged-model round trip, input coercion (bytes/path/str/array),
train/serve consistency, distributed batch scorer, registry integration."""

import os

import numpy as np
import pytest

from ddw_tpu.data.loader import ShardedLoader
from ddw_tpu.models.registry import build_model
from ddw_tpu.runtime.mesh import make_mesh, MeshSpec
from ddw_tpu.serving import BatchScorer, PackagedModel, save_packaged_model
from ddw_tpu.tracking.registry import ModelRegistry
from ddw_tpu.train.step import init_state
from ddw_tpu.train.trainer import Trainer
from ddw_tpu.utils.config import DataCfg, ModelCfg, TrainCfg

CLASSES = ["daisy", "dandelion", "roses", "sunflowers", "tulips"]


@pytest.fixture(scope="module")
def trained_package(tmp_path_factory, silver):
    """Train SmallCNN briefly, package it. Shared across serving tests."""
    train_tbl, val_tbl, label_to_idx = silver
    data = DataCfg(img_height=32, img_width=32)
    model = ModelCfg(name="small_cnn", num_classes=5, dropout=0.1, dtype="float32")
    train = TrainCfg(batch_size=8, epochs=3, warmup_epochs=0, learning_rate=2e-3)
    mesh = make_mesh(MeshSpec((("data", 8),)))
    tr = Trainer(data, model, train, mesh=mesh)
    res = tr.fit(train_tbl, val_tbl)
    out = str(tmp_path_factory.mktemp("pkg") / "model")
    classes = [c for c, _ in sorted(label_to_idx.items(), key=lambda kv: kv[1])]
    save_packaged_model(out, model, classes, res.state.params,
                        res.state.batch_stats, img_height=32, img_width=32)
    return out, res.val_accuracy


def test_package_roundtrip_and_predict_bytes(trained_package, silver):
    out, val_acc = trained_package
    pm = PackagedModel(out)
    assert pm.classes == sorted(CLASSES)
    _, val_tbl, _ = silver
    recs = val_tbl.take(10)
    preds = pm.predict([r.content for r in recs])
    assert len(preds) == 10
    assert all(p in CLASSES for p in preds)
    # trained model should beat chance on val records
    acc = np.mean([p == r.label for p, r in zip(preds, recs)])
    assert acc > 0.2, (acc, val_acc)


def test_predict_input_coercion(trained_package, silver, flowers_dir):
    out, _ = trained_package
    pm = PackagedModel(out)
    _, val_tbl, _ = silver
    rec = val_tbl.take(1)[0]
    by_bytes = pm.predict([rec.content])
    by_path = pm.predict([rec.path])            # file path input
    by_str = pm.predict([str(rec.content)])     # stringified bytes (UDF boundary)
    arr = pm.predict(np.stack([np.zeros((32, 32, 3), np.float32)]))  # decoded array
    assert by_bytes == by_path == by_str
    assert len(arr) == 1


def test_predict_batch_padding(trained_package, silver):
    """N not divisible by the 128 sub-batch: padding must not leak into results."""
    out, _ = trained_package
    pm = PackagedModel(out)
    _, val_tbl, _ = silver
    contents = [r.content for r in val_tbl.take(5)]
    p5 = pm.predict(contents)
    p1 = [pm.predict([c])[0] for c in contents]
    assert p5 == p1
    assert pm.predict([]) == []


def test_train_serve_preprocessing_shared(trained_package, silver):
    """The packaged model and the training loader must produce identical tensors
    for the same record (the skew the reference had; SURVEY §7 step 7)."""
    out, _ = trained_package
    pm = PackagedModel(out)
    _, val_tbl, _ = silver
    rec = val_tbl.take(1)[0]
    from ddw_tpu.data.loader import preprocess_image

    train_side = preprocess_image(rec.content, 32, 32)
    serve_side = pm._decode_one(rec.content)
    np.testing.assert_array_equal(train_side, serve_side)


def test_batch_scorer_distributed(trained_package, silver, tmp_path):
    """Sharded batch scoring over the 8-device mesh scores every record exactly
    once and matches single-process predictions."""
    out, _ = trained_package
    _, val_tbl, _ = silver
    mesh = make_mesh(MeshSpec((("data", 8),)))
    scorer = BatchScorer(out, mesh=mesh, batch_per_device=4)
    from ddw_tpu.data.store import TableStore

    store = TableStore(str(tmp_path / "preds"))
    rows = scorer.score_table(val_tbl, out_store=store, out_name="predictions")
    assert len(rows) == val_tbl.num_records
    assert {p for p, _ in rows} == {r.path for r in val_tbl.iter_records()}
    # written predictions table round-trips
    ptbl = store.table("predictions")
    assert ptbl.num_records == val_tbl.num_records
    # consistency with the in-process pyfunc path
    pm = PackagedModel(out)
    recs = val_tbl.take(6)
    direct = pm.predict([r.content for r in recs])
    by_path = dict(rows)
    assert [by_path[r.path] for r in recs] == direct


def test_registry_stage_flow(trained_package, tmp_path):
    """register -> Production -> load-by-stage; archiving previous Production
    (reference 01_hyperopt_single_machine_model.py:282-293)."""
    out, _ = trained_package
    reg = ModelRegistry(str(tmp_path / "registry"))
    v1 = reg.register("flowers", out, run_id="r1", metrics={"val_accuracy": 0.5})
    v2 = reg.register("flowers", out, run_id="r2", metrics={"val_accuracy": 0.6})
    reg.transition("flowers", v1, "Production")
    reg.transition("flowers", v2, "Production")
    metas = {m["version"]: m for m in reg.list_versions("flowers")}
    assert metas[v1]["stage"] == "Archived"
    assert metas[v2]["stage"] == "Production"
    path = reg.model_path("flowers", stage="production")
    pm = PackagedModel(path)
    assert pm.classes == sorted(CLASSES)


def test_merge_predictions_combines_parts(tmp_path):
    """Rank-0 merge of per-process prediction parts into the single result
    table the spark_udf contract implies (reference 03_pyfunc:466-472)."""
    from ddw_tpu.data.store import Record, TableStore
    from ddw_tpu.serving.batch import merge_predictions

    store = TableStore(str(tmp_path / "preds"))
    store.write("predictions_p0",
                [Record(path="a.jpg", content=b"", label="daisy"),
                 Record(path="b.jpg", content=b"", label="roses")],
                meta={"model_classes": CLASSES, "run_id": "r1"})
    store.write("predictions_p1",
                [Record(path="c.jpg", content=b"", label="tulips")],
                meta={"model_classes": CLASSES, "run_id": "r1"})

    merged = merge_predictions(store, "predictions", 2, "r1", timeout_s=5)
    rows = [(r.path, r.label) for r in merged.iter_records()]
    assert rows == [("a.jpg", "daisy"), ("b.jpg", "roses"), ("c.jpg", "tulips")]
    assert merged.meta["merged_from"] == ["predictions_p0", "predictions_p1"]
    assert merged.meta["run_id"] == "r1"


def test_merge_predictions_times_out_on_missing_part(tmp_path):
    from ddw_tpu.data.store import Record, TableStore
    from ddw_tpu.serving.batch import merge_predictions

    store = TableStore(str(tmp_path / "preds"))
    store.write("predictions_p0",
                [Record(path="a.jpg", content=b"", label="daisy")],
                meta={"run_id": "r1"})
    with pytest.raises(TimeoutError, match="predictions_p1"):
        merge_predictions(store, "predictions", 2, "r1", timeout_s=0.5)


def test_merge_predictions_rejects_stale_parts(tmp_path):
    """A part left over from a previous run (different run token) must not be
    merged — the coordinator keeps waiting for the current run's version."""
    from ddw_tpu.data.store import Record, TableStore
    from ddw_tpu.serving.batch import merge_predictions

    store = TableStore(str(tmp_path / "preds"))
    store.write("predictions_p0",
                [Record(path="a.jpg", content=b"", label="daisy")],
                meta={"run_id": "r2"})
    store.write("predictions_p1",
                [Record(path="c.jpg", content=b"", label="tulips")],
                meta={"run_id": "r1"})  # stale: previous run
    with pytest.raises(TimeoutError, match="stale run_id"):
        merge_predictions(store, "predictions", 2, "r2", timeout_s=0.5)
    # once the current run's part lands (new version), the merge goes through
    store.write("predictions_p1",
                [Record(path="c.jpg", content=b"", label="roses")],
                meta={"run_id": "r2"})
    merged = merge_predictions(store, "predictions", 2, "r2", timeout_s=5)
    assert [(r.path, r.label) for r in merged.iter_records()] == \
        [("a.jpg", "daisy"), ("c.jpg", "roses")]


def test_batch_scorer_on_materialized_table(trained_package, silver, tmp_path):
    """Scoring a pre-decoded raw_u8 table skips JPEG work and agrees with
    scoring the JPEG silver table (pixels differ only by uint8 quantization)."""
    from ddw_tpu.data.prep import materialize_decoded
    from ddw_tpu.data.store import TableStore

    out, _ = trained_package
    _, val_tbl, _ = silver
    store = TableStore(str(tmp_path / "gold"))
    gold = materialize_decoded(val_tbl, store, "gold_val", 32, 32, 16)

    mesh = make_mesh(MeshSpec((("data", 8),)))
    scorer = BatchScorer(out, mesh=mesh, batch_per_device=4)
    silver_rows = dict(scorer.score_table(val_tbl))
    gold_rows = dict(scorer.score_table(gold))
    assert set(gold_rows) == set(silver_rows)
    agree = np.mean([gold_rows[p] == silver_rows[p] for p in silver_rows])
    assert agree >= 0.9, f"only {agree:.0%} prediction agreement"


def test_batch_scorer_materialized_size_mismatch_raises(trained_package, silver,
                                                        tmp_path):
    from ddw_tpu.data.prep import materialize_decoded
    from ddw_tpu.data.store import TableStore

    out, _ = trained_package
    _, val_tbl, _ = silver
    store = TableStore(str(tmp_path / "gold"))
    gold = materialize_decoded(val_tbl, store, "gold_val64", 64, 64, 16)
    scorer = BatchScorer(out, mesh=make_mesh(MeshSpec((("data", 8),))),
                         batch_per_device=4)
    with pytest.raises(ValueError, match="re-materialize"):
        scorer.score_table(gold)
