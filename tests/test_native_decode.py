"""Native JPEG decode pipeline (pipeline.cpp): correctness, fallback, loader use."""

from io import BytesIO

import numpy as np
import pytest
from PIL import Image

from ddw_tpu.data.loader import _preprocess_image_pil, preprocess_image
from ddw_tpu.native.decode import (
    decode_batch_native,
    decode_one_native,
    native_available,
)


def _jpeg(arr: np.ndarray, mode: str | None = None) -> bytes:
    b = BytesIO()
    Image.fromarray(arr, mode).save(b, "JPEG", quality=90)
    return b.getvalue()


@pytest.fixture(scope="module")
def images():
    rng = np.random.RandomState(0)
    y, x = np.mgrid[0:90, 0:120]
    out = []
    for i in range(8):
        arr = np.stack([(np.sin(x / 20 + i) + 1) * 120,
                        (np.cos(y / 15) + 1) * 120,
                        (x + y + 10 * i) % 255], -1).astype(np.uint8)
        out.append(_jpeg(arr))
    return out


needs_native = pytest.mark.skipif(not native_available(),
                                  reason="native pipeline did not build")


@needs_native
def test_decode_one_matches_pil_closely(images):
    """Same decode, point-bilinear vs PIL's filtered bilinear: close on smooth
    images, identical range/shape contract."""
    native = decode_one_native(images[0], 48, 64)
    pil = _preprocess_image_pil(images[0], 48, 64)
    assert native.shape == pil.shape == (48, 64, 3)
    assert native.min() >= -1.0 and native.max() <= 1.0
    assert np.abs(native - pil).mean() < 0.08


@needs_native
def test_decode_batch_matches_single(images):
    imgs, ok = decode_batch_native(images, 32, 32, threads=4)
    assert ok.all() and imgs.shape == (8, 32, 32, 3)
    for i in (0, 3, 7):
        np.testing.assert_array_equal(imgs[i], decode_one_native(images[i], 32, 32))


@needs_native
def test_decode_grayscale_and_failures(images):
    gray = _jpeg(np.random.RandomState(1).randint(0, 255, (50, 60), np.uint8), "L")
    g = decode_one_native(gray, 32, 32)
    assert g is not None and g.shape == (32, 32, 3)
    # grayscale -> identical channels
    np.testing.assert_array_equal(g[..., 0], g[..., 1])

    assert decode_one_native(b"not a jpeg", 32, 32) is None
    imgs, ok = decode_batch_native([images[0], b"junk", gray], 32, 32)
    assert ok.tolist() == [True, False, True]


@needs_native
def test_decode_upscale_small_image():
    tiny = _jpeg(np.full((8, 8, 3), 128, np.uint8))
    out = decode_one_native(tiny, 64, 64)
    assert out is not None and out.shape == (64, 64, 3)
    # constant image stays constant through bilinear upscale
    assert float(np.ptp(out)) < 0.05


def test_preprocess_image_dispatch(images):
    """The shared train/serve preprocess path returns the contract shape/range
    whether or not the native library built."""
    arr = preprocess_image(images[0], 40, 56)
    assert arr.shape == (40, 56, 3) and arr.dtype == np.float32
    assert arr.min() >= -1.0 and arr.max() <= 1.0


def test_loader_native_and_pil_paths_agree(silver):
    """ShardedLoader yields identical record sets through the native-batch and
    PIL thread-pool paths (order is seed-deterministic, payloads decode-close)."""
    from unittest import mock

    from ddw_tpu.data.loader import ShardedLoader

    train, _, _ = silver

    def batches(force_pil: bool):
        loader = ShardedLoader(train, batch_size=16, image_size=(32, 32),
                               num_epochs=1, shuffle=True, seed=5, workers=2)
        if force_pil:
            with mock.patch("ddw_tpu.native.decode.native_available",
                            return_value=False), \
                 mock.patch("ddw_tpu.native.decode.decode_one_native",
                            return_value=None):
                return list(loader)
        return list(loader)

    a = batches(force_pil=False)
    b = batches(force_pil=True)
    assert len(a) == len(b) > 0
    for (ia, la), (ib, lb) in zip(a, b):
        np.testing.assert_array_equal(la, lb)  # same records, same order
        assert np.abs(ia - ib).mean() < 0.1    # decoders agree closely
