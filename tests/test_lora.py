"""LoRA adapters (ddw_tpu.models.lora): init identity, grafting, masking,
and an end-to-end parameter-efficient fine-tune of the LM family."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddw_tpu.models.lm import TransformerLM, generate
from ddw_tpu.models.lora import (LoRADenseGeneral, count_trainable,
                                 lora_mask, lora_optimizer, merge_base_params)


def test_init_equals_base_dense():
    """lora_b starts at zero, so the adapted projection IS the base one."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 8).astype(np.float32))
    base = nn.DenseGeneral((2, 3), dtype=jnp.float32)
    lora = LoRADenseGeneral((2, 3), rank=2, dtype=jnp.float32)
    vb = base.init(jax.random.PRNGKey(0), x)
    vl = lora.init(jax.random.PRNGKey(0), x)
    assert vl["params"]["kernel"].shape == vb["params"]["kernel"].shape
    assert vl["params"]["bias"].shape == vb["params"]["bias"].shape
    # same kernel/bias values -> same output at init
    grafted = merge_base_params(vl["params"], vb["params"])
    np.testing.assert_allclose(
        np.asarray(lora.apply({"params": grafted}, x)),
        np.asarray(base.apply(vb, x)), rtol=1e-6, atol=1e-6)
    # moving lora_b changes the function (the adapter is actually wired in)
    moved = dict(grafted)
    moved["lora_b"] = jnp.ones_like(grafted["lora_b"])
    assert not np.allclose(np.asarray(lora.apply({"params": moved}, x)),
                           np.asarray(base.apply(vb, x)))


def test_int_features_matches_dense():
    x = jnp.asarray(np.random.RandomState(1).randn(4, 8).astype(np.float32))
    dense = nn.Dense(5, dtype=jnp.float32)
    lora = LoRADenseGeneral(5, rank=2, dtype=jnp.float32)
    vd = dense.init(jax.random.PRNGKey(0), x)
    vl = lora.init(jax.random.PRNGKey(0), x)
    grafted = merge_base_params(vl["params"], vd["params"])
    np.testing.assert_allclose(
        np.asarray(lora.apply({"params": grafted}, x)),
        np.asarray(dense.apply(vd, x)), rtol=1e-6, atol=1e-6)


def test_mask_and_merge_errors():
    params = {
        "backbone": {"attn": {"kernel": jnp.zeros((2, 2)),
                              "lora_a": jnp.zeros((2, 1)),
                              "lora_b": jnp.zeros((1, 2))}},
        "head": {"kernel": jnp.zeros((2, 2))},
    }
    mask = lora_mask(params)
    assert mask["backbone"]["attn"] == {"kernel": False, "lora_a": True,
                                        "lora_b": True}
    assert mask["head"]["kernel"] is True
    with pytest.raises(ValueError, match="absent"):
        merge_base_params(params, {"nonexistent": jnp.zeros(1)})
    with pytest.raises(ValueError, match="shape mismatch"):
        merge_base_params(params, {"head": {"kernel": jnp.zeros((3, 3))}})


def _tiny_lm(**kw):
    return TransformerLM(vocab_size=32, max_len=32, hidden=16, depth=2,
                         num_heads=2, mlp_dim=32, dtype=jnp.float32, **kw)


def test_lm_lora_graft_preserves_function():
    """Base LM params graft into the LoRA LM; logits agree at init."""
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 8)))
    base = _tiny_lm()
    lora = _tiny_lm(lora_rank=4, lora_targets=("query", "value", "fc1"))
    vb = base.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    vl = lora.init({"params": jax.random.PRNGKey(1)}, toks)["params"]
    grafted = merge_base_params(vl, vb)
    np.testing.assert_allclose(
        np.asarray(lora.apply({"params": grafted}, toks)),
        np.asarray(base.apply({"params": vb}, toks)), rtol=1e-5, atol=1e-5)
    # economy: adapters (+head) are a small fraction of the model
    trainable, total = count_trainable(grafted)
    assert trainable < total / 2
    assert trainable > 0


def test_lm_lora_finetune_moves_only_adapters_and_head():
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 32, (4, 9)))
    inputs, targets = toks[:, :-1], toks[:, 1:]
    model = _tiny_lm(lora_rank=4)
    params = model.init({"params": jax.random.PRNGKey(0)}, inputs)["params"]
    tx = lora_optimizer(optax.adam(1e-2), params)
    opt_state = tx.init(params)

    def loss_fn(p):
        logits = model.apply({"params": p}, inputs, train=True)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    @jax.jit
    def step(p, s):
        loss, grads = jax.value_and_grad(loss_fn)(p)
        updates, s = tx.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    p, losses = params, []
    for _ in range(20):
        p, opt_state, loss = step(p, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses

    mask = lora_mask(params)
    changed = jax.tree.map(lambda a, b: bool(jnp.any(a != b)), params, p)
    for path, ch in jax.tree_util.tree_flatten_with_path(changed)[0]:
        m = mask
        for k in path:
            m = m[k.key] if isinstance(m, dict) else m
        keys = "/".join(k.key for k in path)
        if m:
            assert ch, f"trainable leaf {keys} never moved"
        else:
            assert not ch, f"frozen leaf {keys} moved"


def test_out_projection_target_and_validation():
    """'out' adapts through the 2-dim contraction; unknown targets are loud."""
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 8)))
    base = _tiny_lm()
    lora = _tiny_lm(lora_rank=4, lora_targets=("out",))
    vb = base.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    vl = lora.init({"params": jax.random.PRNGKey(1)}, toks)["params"]
    attn0 = vl["backbone_block0"]["attn"]["out"]
    assert attn0["lora_a"].shape == (2, 8, 4)   # (heads, head_dim, rank)
    assert attn0["lora_b"].shape == (4, 16)     # (rank, hidden)
    grafted = merge_base_params(vl, vb)
    np.testing.assert_allclose(
        np.asarray(lora.apply({"params": grafted}, toks)),
        np.asarray(base.apply({"params": vb}, toks)), rtol=1e-5, atol=1e-5)
    with pytest.raises(ValueError, match="unknown lora_targets"):
        _tiny_lm(lora_rank=4, lora_targets=("querry",)).init(
            {"params": jax.random.PRNGKey(0)}, toks)


def test_lm_step_applies_lora_mask_automatically():
    """The shared LM training layer freezes the base when the model carries
    lora_rank — a plain optax transform must not full-fine-tune it."""
    from ddw_tpu.runtime.mesh import make_mesh, MeshSpec
    from ddw_tpu.train.lm_step import init_lm_state, make_lm_train_step

    model = _tiny_lm(lora_rank=2)
    mesh = make_mesh(MeshSpec((("data", -1),)))
    state = init_lm_state(model, optax.adam(1e-2), jax.random.PRNGKey(0))
    step = make_lm_train_step(model, optax.adam(1e-2), mesh, "data",
                              seq_axis=None, donate=False)
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 32, (8, 9)))
    new_state, metrics = step(state, toks[:, :-1], toks[:, 1:],
                              jax.random.PRNGKey(1))
    mask = lora_mask(state.params)
    changed = jax.tree.map(lambda a, b: bool(jnp.any(a != b)),
                           state.params, new_state.params)
    flat = jax.tree_util.tree_flatten_with_path(changed)[0]
    moved_frozen = []
    moved_trainable = 0
    for path, ch in flat:
        m = mask
        for k in path:
            m = m[k.key]
        if ch and not m:
            moved_frozen.append("/".join(k.key for k in path))
        if ch and m:
            moved_trainable += 1
    assert not moved_frozen, moved_frozen
    assert moved_trainable > 0


@pytest.mark.slow  # ~11s; trainer-side masking keeps its tier-1 rep in
#                    test_lm_step_applies_lora_mask_automatically
def test_vit_lora_through_trainer_path():
    """ViT LoRA rides the standard vision stack: build_model + init_state
    apply the mask (plain TrainCfg optimizer), only adapters+head move."""
    from ddw_tpu.models.registry import build_model
    from ddw_tpu.runtime.mesh import make_mesh, MeshSpec
    from ddw_tpu.train.step import init_state, make_train_step
    from ddw_tpu.utils.config import ModelCfg, TrainCfg

    cfg = ModelCfg(name="vit", num_classes=5, dropout=0.0, freeze_base=False,
                   dtype="float32", lora_rank=2,
                   lora_targets=("query", "value", "out", "fc1"))
    model = build_model(cfg)
    train_cfg = TrainCfg(batch_size=8, optimizer="adam", learning_rate=1e-2,
                         warmup_epochs=0)
    mesh = make_mesh(MeshSpec((("data", 8),)))
    state, tx = init_state(model, cfg, train_cfg, (32, 32, 3),
                           jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh, "data", donate=False)
    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.rand(8, 32, 32, 3).astype(np.float32) * 2 - 1)
    labels = jnp.asarray(rng.randint(0, 5, 8).astype(np.int32))
    new_state, metrics = step(state, imgs, labels, jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))
    mask = lora_mask(state.params)
    moved = jax.tree.map(lambda a, b: bool(jnp.any(a != b)),
                         state.params, new_state.params)
    frozen_moved, trainable_moved = [], 0
    for path, ch in jax.tree_util.tree_flatten_with_path(moved)[0]:
        m = mask
        for k in path:
            m = m[k.key]
        if ch and not m:
            frozen_moved.append("/".join(k.key for k in path))
        if ch and m:
            trainable_moved += 1
    assert not frozen_moved, frozen_moved
    assert trainable_moved > 0


def test_registry_lora_guards():
    """Families without LoRA support refuse the flag; LoRA over a random
    backbone warns (same footgun class as frozen-random freeze_base)."""
    from ddw_tpu.models.registry import build_model
    from ddw_tpu.utils.config import ModelCfg

    with pytest.raises(ValueError, match="does not support LoRA"):
        build_model(ModelCfg(name="resnet50", freeze_base=False, lora_rank=4))
    with pytest.warns(UserWarning, match="randomly initialized backbone"):
        build_model(ModelCfg(name="vit", freeze_base=False, lora_rank=4))


@pytest.mark.slow  # tier-1 budget (PR 18): LoRA validation/error paths keep
                   # their tier-1 reps in test_mask_and_merge_errors +
                   # test_registry_lora_guards (this one builds a full ViT
                   # trainer just to hit the conflict).
def test_vit_lora_freeze_base_conflict_raises():
    from ddw_tpu.models.mobilenet_v2 import MobileNetV2
    from ddw_tpu.train.step import init_state
    from ddw_tpu.utils.config import ModelCfg, TrainCfg

    # a model whose frozen_prefixes is non-empty AND lora_rank set must refuse
    class _FakeLoRACNN(MobileNetV2):
        lora_rank: int = 4

    model = _FakeLoRACNN(num_classes=5, freeze_base=True)
    with pytest.raises(ValueError, match="mutually exclusive"):
        init_state(model,
                   ModelCfg(name="mobilenet_v2", allow_frozen_random=True),
                   TrainCfg(batch_size=4), (32, 32, 3), jax.random.PRNGKey(0))


def test_lora_decode_generate_runs():
    """The KV-cached decode path works unchanged with adapters present."""
    model = _tiny_lm(lora_rank=2)
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 4)))
    params = model.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    out = generate(model, params, toks, num_steps=3)
    assert out.shape == (2, 3)
    assert not np.any(np.isnan(np.asarray(out)))
