"""Transformer LM + DPxSP train step: causality, SP equivalence, learning."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from ddw_tpu.utils.compat import shard_map

from ddw_tpu.models.lm import TransformerLM
from ddw_tpu.parallel.sharding import LM_TP_RULES, make_sharded_train_step
from ddw_tpu.runtime.mesh import make_mesh, MeshSpec, DATA_AXIS, MODEL_AXIS, SEQ_AXIS
from ddw_tpu.train.lm_step import (
    init_lm_state,
    lm_loss,
    make_lm_eval_step,
    make_lm_train_step,
)
from ddw_tpu.train.step import TrainState

VOCAB = 32  # divisible by the model axis: vocab-sharded embed/head in the TP test


def tiny_lm(seq_axis=None, dropout=0.0):
    return TransformerLM(vocab_size=VOCAB, max_len=128, hidden=32, depth=2,
                         num_heads=2, mlp_dim=64, dropout=dropout,
                         dtype=jnp.float32, seq_axis=seq_axis)


def make_batch(rng, batch, seq):
    tokens = rng.randint(0, VOCAB, size=(batch, seq + 1)).astype(np.int32)
    return tokens[:, :-1], tokens[:, 1:]


def test_forward_shape_and_causality():
    model = tiny_lm()
    inputs = np.arange(16, dtype=np.int32).reshape(1, 16) % VOCAB
    params = model.init({"params": jax.random.PRNGKey(0)}, inputs)["params"]
    logits = model.apply({"params": params}, inputs)
    assert logits.shape == (1, 16, VOCAB)
    # causality: perturbing token t must not change logits at positions < t
    t = 9
    perturbed = inputs.copy()
    perturbed[0, t] = (perturbed[0, t] + 1) % VOCAB
    logits2 = model.apply({"params": params}, perturbed)
    np.testing.assert_allclose(logits[0, :t], logits2[0, :t], atol=1e-5)
    assert not np.allclose(logits[0, t:], logits2[0, t:], atol=1e-5)


def test_sp_forward_matches_single_device():
    """Ring-attention LM under shard_map(seq=4) == full-attention LM, same params."""
    n = 4
    mesh = make_mesh(MeshSpec(((SEQ_AXIS, n),)), devices=jax.devices()[:n])
    full = tiny_lm()
    sp = tiny_lm(seq_axis=SEQ_AXIS)
    rng = np.random.RandomState(0)
    inputs, _ = make_batch(rng, batch=2, seq=32)
    params = full.init({"params": jax.random.PRNGKey(1)}, inputs)["params"]

    ref = full.apply({"params": params}, inputs)
    sp_fwd = jax.jit(shard_map(
        lambda p, x: sp.apply({"params": p}, x),
        mesh=mesh, in_specs=(P(), P(None, SEQ_AXIS)),
        out_specs=P(None, SEQ_AXIS, None), check_vma=False))
    out = sp_fwd(params, inputs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_dpxsp_train_step_matches_pure_dp():
    """One train step on a (data=2, seq=4) mesh == the same step on (data=2)."""
    devs = jax.devices()
    mesh_sp = make_mesh(MeshSpec(((DATA_AXIS, 2), (SEQ_AXIS, 4))), devices=devs[:8])
    mesh_dp = make_mesh(MeshSpec(((DATA_AXIS, 2),)), devices=devs[:2])
    # SGD: updates are linear in the gradients, so the tiny numeric differences
    # between the flash (DP) and ring (SP) attention paths stay tiny in params
    # (Adam's sign-like normalization would amplify them for near-zero grads).
    tx = optax.sgd(1e-1)
    rng = np.random.RandomState(1)
    inputs, targets = make_batch(rng, batch=4, seq=32)

    model_sp = tiny_lm(seq_axis=SEQ_AXIS)
    state_sp = init_lm_state(model_sp, tx, jax.random.PRNGKey(2))
    step_sp = make_lm_train_step(model_sp, tx, mesh_sp, seq_axis=SEQ_AXIS,
                                 donate=False)

    model_dp = tiny_lm()
    state_dp = init_lm_state(model_dp, tx, jax.random.PRNGKey(2))
    step_dp = make_lm_train_step(model_dp, tx, mesh_dp, seq_axis=None,
                                 donate=False)

    new_sp, m_sp = step_sp(state_sp, inputs, targets, jax.random.PRNGKey(3))
    new_dp, m_dp = step_dp(state_dp, inputs, targets, jax.random.PRNGKey(3))
    assert abs(float(m_sp["loss"]) - float(m_dp["loss"])) < 1e-4
    flat_sp = jax.tree.leaves(new_sp.params)
    flat_dp = jax.tree.leaves(new_dp.params)
    for a, b in zip(flat_sp, flat_dp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


@pytest.mark.slow  # tier-1 budget (PR 16): the LM learn pin keeps its
#                    tier-1 rep in test_lm_trainer.py::test_fit_learns_dp
#                    (same model through the fit loop); this model-level
#                    soak rides tier-2
def test_lm_learns_fixed_sequence():
    """A few steps of the DPxSP step memorize a constant next-token pattern."""
    n = 4
    mesh = make_mesh(MeshSpec(((DATA_AXIS, 2), (SEQ_AXIS, 2))),
                     devices=jax.devices()[:n])
    model = tiny_lm(seq_axis=SEQ_AXIS)
    tx = optax.adam(5e-3)
    state = init_lm_state(model, tx, jax.random.PRNGKey(0))
    step = make_lm_train_step(model, tx, mesh, seq_axis=SEQ_AXIS)
    eval_step = make_lm_eval_step(model, mesh, seq_axis=SEQ_AXIS)

    seq = np.tile(np.arange(16, dtype=np.int32) % VOCAB, (4, 1))
    inputs, targets = seq[:, :-1][:, :12], seq[:, 1:][:, :12]
    first = None
    for i in range(30):
        state, metrics = step(state, inputs, targets, jax.random.PRNGKey(i))
        if first is None:
            first = float(metrics["loss"])
    final = eval_step(state, inputs, targets)
    assert float(final["loss"]) < first / 3
    assert float(final["accuracy"]) > 0.9


def test_sp_global_seq_exceeding_max_len_raises():
    """dynamic_slice would silently clamp trailing shards' position offsets —
    the model must reject global seq > max_len at trace time instead."""
    n = 4
    mesh = make_mesh(MeshSpec(((SEQ_AXIS, n),)), devices=jax.devices()[:n])
    sp = tiny_lm(seq_axis=SEQ_AXIS)  # max_len=128
    inputs = np.zeros((1, 256), np.int32)  # global 256 > 128
    params = tiny_lm().init({"params": jax.random.PRNGKey(0)},
                            inputs[:, :8])["params"]
    fwd = jax.jit(shard_map(
        lambda p, x: sp.apply({"params": p}, x),
        mesh=mesh, in_specs=(P(), P(None, SEQ_AXIS)),
        out_specs=P(None, SEQ_AXIS, None), check_vma=False))
    with pytest.raises(ValueError, match="max_len"):
        fwd(params, inputs)


def test_lm_seq_axis_mismatch_raises():
    mesh = make_mesh(MeshSpec(((DATA_AXIS, 2),)), devices=jax.devices()[:2])
    model = tiny_lm(seq_axis=SEQ_AXIS)
    with pytest.raises(ValueError, match="seq_axis"):
        make_lm_train_step(model, optax.adam(1e-3), mesh, seq_axis=None)


def test_lm_tensor_parallel_gspmd_step():
    """LM under the GSPMD TP path: params shard per LM_TP_RULES, loss finite."""
    mesh = make_mesh(MeshSpec(((DATA_AXIS, 2), (MODEL_AXIS, 2))),
                     devices=jax.devices()[:4])
    model = tiny_lm()
    tx = optax.adam(1e-3)
    state = init_lm_state(model, tx, jax.random.PRNGKey(0))
    step = make_sharded_train_step(model, tx, mesh, LM_TP_RULES)
    state = step.place_state(state)
    emb = state.params["tok_embed"]["embedding"]
    assert emb.sharding.spec == P(MODEL_AXIS, None), emb.sharding
    rng = np.random.RandomState(2)
    inputs, targets = make_batch(rng, batch=4, seq=16)
    inputs = jax.device_put(inputs, step.batch_sharding)
    targets = jax.device_put(targets, step.batch_sharding)
    state, metrics = step(state, inputs, targets, jax.random.PRNGKey(1))
    assert np.isfinite(float(metrics["loss"]))


def test_decode_path_matches_full_forward():
    """KV-cached one-token-at-a-time logits == full-sequence forward logits."""
    from ddw_tpu.models.lm import generate  # noqa: F401 (import sanity)
    import jax.numpy as jnp
    from jax import lax

    model = tiny_lm()
    rng = np.random.RandomState(4)
    tokens = rng.randint(0, VOCAB, size=(2, 12)).astype(np.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    full_logits = model.apply({"params": params}, tokens)

    from ddw_tpu.models.lm import init_cache

    dm = model.clone(decode=True)
    cache = init_cache(dm, batch=2)

    def one(cache, tok):
        logits, vars_ = dm.apply({"params": params, "cache": cache},
                                 tok[:, None], mutable=["cache"])
        return vars_["cache"], logits[:, 0]

    _, step_logits = lax.scan(one, cache, jnp.asarray(tokens).T)
    step_logits = jnp.transpose(step_logits, (1, 0, 2))  # [B, S, V]
    np.testing.assert_allclose(np.asarray(step_logits),
                               np.asarray(full_logits), atol=2e-4)


@pytest.mark.slow  # ~12s; learn pin stays tier-1 in
#                    test_lm_trainer.py::test_fit_learns_dp,
#                    generate identity in test_decode_path_matches_full_forward
def test_generate_continues_memorized_pattern():
    """Train on the arange successor pattern, then greedy-generate continues it."""
    from ddw_tpu.models.lm import generate

    mesh = make_mesh(MeshSpec(((DATA_AXIS, 2),)), devices=jax.devices()[:2])
    model = tiny_lm()
    tx = optax.adam(5e-3)
    state = init_lm_state(model, tx, jax.random.PRNGKey(0))
    step = make_lm_train_step(model, tx, mesh, seq_axis=None)
    seq = np.tile(np.arange(24, dtype=np.int32) % VOCAB, (4, 1))
    inputs, targets = seq[:, :-1], seq[:, 1:]
    for i in range(60):
        state, metrics = step(state, inputs, targets, jax.random.PRNGKey(i))
    assert float(metrics["accuracy"]) > 0.95

    prompt = np.arange(6, dtype=np.int32)[None] % VOCAB   # 0..5
    cont = np.asarray(generate(model, state.params, prompt, num_steps=8))
    expected = (np.arange(6, 14) % VOCAB).astype(np.int32)
    np.testing.assert_array_equal(cont[0], expected)


def test_generate_rejects_overflow_and_sampling_without_rng():
    from ddw_tpu.models.lm import generate

    model = tiny_lm()  # max_len=128
    params = model.init({"params": jax.random.PRNGKey(0)},
                        np.zeros((1, 4), np.int32))["params"]
    with pytest.raises(ValueError, match="exceeds"):
        generate(model, params, np.zeros((1, 100), np.int32), num_steps=60)
    with pytest.raises(ValueError, match="requires rng"):
        generate(model, params, np.zeros((1, 4), np.int32), num_steps=2,
                 temperature=0.8)


def test_decode_work_scales_with_position():
    """Tiled decode attention must skip unfilled cache tiles: the per-call tile
    count (cache['tiles_computed'] delta, summed over layers) grows with the
    filled position instead of always paying O(max_len)."""
    model = TransformerLM(vocab_size=16, max_len=1024, hidden=16, depth=2,
                          num_heads=2, mlp_dim=32, dtype=jnp.float32,
                          decode=True)
    # tile=256, max_len=1024 -> 4 tiles per layer available
    rng = np.random.RandomState(0)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 1), jnp.int32))["params"]
    from ddw_tpu.models.lm import init_cache

    cache = init_cache(model, 1)

    def step_at(cache):
        tok = jnp.asarray(rng.randint(0, 16, size=(1, 1)), jnp.int32)
        _, vars_ = model.apply({"params": params, "cache": cache}, tok,
                               mutable=["cache"])
        return vars_["cache"]

    def total_tiles(cache):
        import jax as _jax
        flat = _jax.tree_util.tree_flatten_with_path(cache)[0]
        return sum(int(v) for k, v in flat if "tiles_computed" in str(k))

    c = cache
    before = total_tiles(c)
    c = step_at(c)                      # pos 0: 1 active tile per layer
    early = total_tiles(c) - before
    assert early == 2                   # depth=2 layers x 1 tile

    # fast-forward the index to tile 3 (simulate 800 generated tokens)
    c = jax.tree_util.tree_map_with_path(
        lambda k, v: jnp.asarray(800, jnp.int32)
        if "cache_index" in str(k) or "pos_index" in str(k) else v, c)
    before = total_tiles(c)
    c = step_at(c)                      # pos 800 -> tiles 0..3 active
    late = total_tiles(c) - before
    assert late == 8                    # depth=2 layers x 4 tiles
    assert late > early


def test_decode_overflow_poisons_output():
    """Driving the decode model past max_len must fail loudly (NaN logits),
    not silently clamp-overwrite the cache."""
    model = TransformerLM(vocab_size=16, max_len=8, hidden=16, depth=1,
                          num_heads=2, mlp_dim=32, dtype=jnp.float32,
                          decode=True)
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 1), jnp.int32))["params"]
    from ddw_tpu.models.lm import init_cache

    cache = init_cache(model, 1)
    tok = jnp.zeros((1, 1), jnp.int32)
    for i in range(8):
        logits, vars_ = model.apply({"params": params, "cache": cache}, tok,
                                    mutable=["cache"])
        cache = vars_["cache"]
        assert np.isfinite(np.asarray(logits)).all(), f"step {i} not finite"
    logits, _ = model.apply({"params": params, "cache": cache}, tok,
                            mutable=["cache"])
    assert np.isnan(np.asarray(logits)).all()


@pytest.mark.slow  # ~12s; filter-edge pins (top_k=1==greedy etc.) move to
# the slow tier; seeded/greedy sampling identity keeps tier-1 reps in
# test_lanes.py::test_batch_matches_direct_greedy_and_seeded and
# tests/test_paged_kv.py's sampled+greedy neighbor test
def test_generate_top_k_top_p():
    """top_k=1 (or a vanishing nucleus) at ANY temperature must reproduce the
    greedy continuation; top_k/top_p compose with sampling and error-check."""
    from ddw_tpu.models.lm import generate

    model = tiny_lm()
    params = model.init({"params": jax.random.PRNGKey(0)},
                        jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    prompt = np.arange(8, dtype=np.int32)[None] % model.vocab_size
    rng = jax.random.PRNGKey(7)

    greedy = np.asarray(generate(model, params, prompt, num_steps=12))
    k1 = np.asarray(generate(model, params, prompt, num_steps=12, rng=rng,
                             temperature=5.0, top_k=1))
    np.testing.assert_array_equal(k1, greedy)
    p_tiny = np.asarray(generate(model, params, prompt, num_steps=12, rng=rng,
                                 temperature=5.0, top_p=1e-9))
    np.testing.assert_array_equal(p_tiny, greedy)

    # full nucleus == plain categorical at the same key
    plain = np.asarray(generate(model, params, prompt, num_steps=12, rng=rng,
                                temperature=1.0))
    p_full = np.asarray(generate(model, params, prompt, num_steps=12, rng=rng,
                                 temperature=1.0, top_p=1.0))
    np.testing.assert_array_equal(p_full, plain)

    # composed sampling stays in-vocab and actually varies with the key
    s1 = np.asarray(generate(model, params, prompt, num_steps=24, rng=rng,
                             temperature=2.0, top_k=8, top_p=0.9))
    s2 = np.asarray(generate(model, params, prompt, num_steps=24,
                             rng=jax.random.PRNGKey(8),
                             temperature=2.0, top_k=8, top_p=0.9))
    assert s1.min() >= 0 and s1.max() < model.vocab_size
    assert (s1 != s2).any()

    with pytest.raises(ValueError, match="top_p must be in"):
        generate(model, params, prompt, 4, rng=rng, temperature=1.0, top_p=1.5)
    with pytest.raises(ValueError, match="top_k must be"):
        generate(model, params, prompt, 4, rng=rng, temperature=1.0, top_k=-3)
    with pytest.raises(ValueError, match="require temperature"):
        generate(model, params, prompt, 4, top_k=5)


@pytest.mark.slow  # tier-1 budget (PR 16): grad-accum equivalence keeps
#                    tier-1 reps in test_train_step.py (vision twin),
#                    test_chain's grad-accum chain arm and test_zero's
#                    accum-vs-single-shot pin; the LM variant rides tier-2
def test_lm_grad_accum_equivalence():
    """grad_accum_steps=2 == one full-batch LM step (dropout off, SGD so the
    update is linear in the gradients)."""
    mesh = make_mesh(MeshSpec(((DATA_AXIS, 2),)), devices=jax.devices()[:2])
    model = tiny_lm()
    tx = optax.sgd(1e-1)
    state0 = init_lm_state(model, tx, jax.random.PRNGKey(2))
    step1 = make_lm_train_step(model, tx, mesh, seq_axis=None, donate=False)
    step2 = make_lm_train_step(model, tx, mesh, seq_axis=None, donate=False,
                               grad_accum_steps=2)
    rng = np.random.RandomState(4)
    inputs, targets = make_batch(rng, batch=8, seq=32)
    s1, m1 = step1(state0, inputs, targets, jax.random.PRNGKey(5))
    s2, m2 = step2(state0, inputs, targets, jax.random.PRNGKey(5))
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    for a, b in zip(jax.tree.leaves(s1.params), jax.tree.leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
