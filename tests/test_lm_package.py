"""Packaged LM artifacts: roundtrip identity, int8 variant, generation,
speculative decode from packages, format guards."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddw_tpu.models.lm import build_lm, generate
from ddw_tpu.serving.lm_package import (
    LMPackagedModel,
    load_lm_package,
    save_lm_package,
)
from ddw_tpu.utils.config import LMCfg

VOCAB = 32


def _trained(seed=0):
    cfg = LMCfg(vocab_size=VOCAB, max_len=64, hidden=32, depth=2,
                num_heads=2, mlp_dim=64, dropout=0.0, dtype="float32")
    model = build_lm(cfg)
    params = model.init({"params": jax.random.PRNGKey(seed)},
                        np.zeros((1, 8), np.int32))["params"]
    return cfg, model, params


def _tokens(n=4, seq=16, seed=0):
    rng = np.random.RandomState(seed)
    return rng.randint(0, VOCAB, size=(n, seq + 1)).astype(np.int32)


def test_roundtrip_scores_and_generation_match(tmp_path):
    cfg, model, params = _trained()
    d = save_lm_package(str(tmp_path / "pkg"), cfg, params)
    pm = load_lm_package(d)
    toks = _tokens()

    # score == direct NLL from the source model
    inp, tgt = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    logits = model.apply({"params": params}, inp, train=False)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    ref = -np.mean(np.take_along_axis(np.asarray(logp),
                                      toks[:, 1:, None], -1)[..., 0], -1)
    np.testing.assert_allclose(pm.score(toks), ref, rtol=1e-6, atol=1e-6)

    # generation == source-model greedy
    ref_gen = np.asarray(generate(model, params, toks[:1, :8], num_steps=8))
    np.testing.assert_array_equal(pm.generate(toks[:1, :8], 8), ref_gen)
    assert len(pm.content_digest) == 16


def test_int8_package_close_and_smaller(tmp_path):
    cfg, model, params = _trained()
    d32 = save_lm_package(str(tmp_path / "f32"), cfg, params)
    d8 = save_lm_package(str(tmp_path / "i8"), cfg, params, quantize="int8")
    s32 = os.path.getsize(os.path.join(d32, "params.msgpack"))
    s8 = os.path.getsize(os.path.join(d8, "params.msgpack"))
    assert s8 < 0.45 * s32, (s8, s32)
    toks = _tokens()
    nll32 = load_lm_package(d32).score(toks)
    nll8 = load_lm_package(d8).score(toks)
    np.testing.assert_allclose(nll8, nll32, rtol=0.05, atol=0.05)


@pytest.mark.slow   # tier-1 budget (PR 16): package roundtrip keeps its
#                     tier-1 rep in test_roundtrip_scores_and_generation_
#                     match above, and spec-decode identity keeps
#                     test_spec_engine's greedy A/B; this packaged
#                     draft+target composition rides tier-2
def test_speculative_from_packages(tmp_path):
    cfg, model, params = _trained(seed=0)
    dcfg, dmodel, dparams = _trained(seed=7)
    t = save_lm_package(str(tmp_path / "t"), cfg, params)
    d = save_lm_package(str(tmp_path / "d"), dcfg, dparams)
    target, draft = load_lm_package(t), load_lm_package(d)
    prompt = _tokens(1, 8)[:, :8]
    out, stats = target.generate_speculative(draft, prompt, num_steps=8, k=3)
    np.testing.assert_array_equal(out, target.generate(prompt, 8))
    assert stats["rounds"] >= 1


def test_format_guards(tmp_path):
    cfg, model, params = _trained()
    d = save_lm_package(str(tmp_path / "pkg"), cfg, params)
    # image loader must not open LM packages and vice versa — both sides
    # diagnose by the 'kind' field
    from ddw_tpu.serving.package import PackagedModel

    with pytest.raises(ValueError, match="not an image package"):
        PackagedModel(d)
    with pytest.raises(ValueError, match="reserved keys"):
        save_lm_package(str(tmp_path / "z"), cfg, params,
                        extra_meta={"kind": "my-lm"})
    meta = json.load(open(os.path.join(d, "package.json")))
    meta["kind"] = "image"
    json.dump(meta, open(os.path.join(d, "package.json"), "w"))
    with pytest.raises(ValueError, match="not an lm package"):
        LMPackagedModel(d)
    with pytest.raises(ValueError, match="quantize"):
        save_lm_package(str(tmp_path / "x"), cfg, params, quantize="int4")
    pm = load_lm_package(save_lm_package(str(tmp_path / "y"), cfg, params))
    with pytest.raises(ValueError, match="exceeds"):
        pm.score(_tokens(1, 128))


@pytest.mark.slow   # tier-1 budget (PR 12): bucket-padding correctness
#                     keeps test_score_bucketing_matches_unpadded below
#                     and the engine-side compile-ladder counts are pinned
#                     in tests/test_fleet_prefix.py; this generate-path
#                     program-count sweep rides tier-2
def test_generate_bucketing_no_per_length_programs(tmp_path):
    """Prompt lengths sharing a bucket share ONE jitted program (the
    engine's bucketing applied to the single-request path), and the padded
    path is token-identical to the unbucketed models.lm.generate."""
    cfg, model, params = _trained()
    pm = load_lm_package(save_lm_package(str(tmp_path / "pkg"), cfg, params))
    rng = np.random.RandomState(2)
    for plen in (3, 8):     # both in the 8-bucket (pad and exact)
        prompt = rng.randint(0, VOCAB, size=(1, plen)).astype(np.int32)
        ref = np.asarray(generate(model, params, prompt, num_steps=6))
        np.testing.assert_array_equal(pm.generate(prompt, 6), ref)
    assert len(pm._gen_cache) == 1     # one program for the whole bucket
    # sampling composes with bucketing (same key schedule as the raw path)
    prompt = rng.randint(0, VOCAB, size=(1, 5)).astype(np.int32)
    ref = np.asarray(generate(model, params, prompt, num_steps=6,
                              rng=jax.random.PRNGKey(4), temperature=0.9,
                              top_k=7))
    got = pm.generate(prompt, 6, rng=jax.random.PRNGKey(4), temperature=0.9,
                      top_k=7)
    np.testing.assert_array_equal(got, ref)


def test_score_bucketing_matches_unpadded(tmp_path):
    """Padded-bucket scoring == the exact per-length NLL (padded positions
    masked out of the mean)."""
    from ddw_tpu.serving.lm_package import sequence_nll

    cfg, model, params = _trained()
    pm = load_lm_package(save_lm_package(str(tmp_path / "pkg"), cfg, params))
    for seq in (5, 16):   # pad-to-bucket and exact-bucket widths
        toks = _tokens(n=3, seq=seq, seed=seq)
        ref = np.asarray(sequence_nll(model, params, jnp.asarray(toks)))
        np.testing.assert_allclose(pm.score(toks), ref, rtol=1e-5, atol=1e-6)


def test_lm_batch_scorer_over_token_table(tmp_path):
    """LMBatchScorer: per-sequence NLL over a tokens_i32 table matches the
    package's own score() exactly (padding sliced off), order preserved,
    scores table written with the run-token meta; encoding mismatches and
    over-length sequences refuse loudly."""
    from ddw_tpu.data.prep import write_token_table
    from ddw_tpu.data.store import TableStore
    from ddw_tpu.serving.batch import LMBatchScorer

    cfg, model, params = _trained()
    d = save_lm_package(str(tmp_path / "pkg"), cfg, params)
    pm = load_lm_package(d)

    store = TableStore(str(tmp_path / "store"))
    toks = _tokens(n=22, seq=16)  # 22 % batch != 0: padding path exercised
    tbl = write_token_table(store, "toks", toks, shard_size=8)

    scorer = LMBatchScorer(d, batch_per_device=2)  # 8 devices -> batch 16
    rows = scorer.score_table(tbl, out_store=store)
    assert len(rows) == 22
    want = pm.score(toks)
    got = np.array([v for _, v in rows])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert [p for p, _ in rows] == [r.path for r in tbl.iter_records()]

    out = store.table("lm_scores")
    assert out.num_records == 22
    assert out.meta["metric"] == "mean_next_token_nll"
    assert out.meta["run_id"]
    rec = next(out.iter_records())
    assert float(rec.label) == pytest.approx(
        np.frombuffer(rec.content, np.float32)[0], abs=1e-5)

    with pytest.raises(ValueError, match="tokens_i32"):
        from ddw_tpu.data.store import Record

        bad = store.write("bad", [Record(path="x", content=b"12")], meta={})
        scorer.score_table(bad)
    with pytest.raises(ValueError, match="max_len"):
        long = write_token_table(store, "long", _tokens(n=4, seq=100))
        scorer.score_table(long)


def test_lm_batch_scorer_rejects_out_of_vocab(tmp_path):
    """The batch scorer shares score()'s bounds discipline: out-of-vocab ids
    refuse instead of silently clamping into the nearest vocab row."""
    from ddw_tpu.data.prep import write_token_table
    from ddw_tpu.data.store import TableStore
    from ddw_tpu.serving.batch import LMBatchScorer

    cfg, _, params = _trained()
    d = save_lm_package(str(tmp_path / "pkg"), cfg, params)
    store = TableStore(str(tmp_path / "store"))
    bad = _tokens(n=4, seq=16)
    bad[0, 3] = VOCAB + 5
    tbl = write_token_table(store, "bad", bad)
    with pytest.raises(ValueError, match="token ids outside"):
        LMBatchScorer(d, batch_per_device=1).score_table(tbl)
