"""Shared fixtures: synthetic flowers tree, prepared silver tables, small configs."""

import os

import pytest

from ddw_tpu.data.prep import generate_synthetic_flowers, prepare_flowers
from ddw_tpu.data.store import TableStore
from ddw_tpu.utils.config import DataCfg, ModelCfg, TrainCfg


@pytest.fixture(scope="session")
def flowers_dir(tmp_path_factory):
    root = tmp_path_factory.mktemp("flowers_src")
    return generate_synthetic_flowers(str(root), images_per_class=24, size=40)


@pytest.fixture(scope="session")
def store(tmp_path_factory):
    return TableStore(str(tmp_path_factory.mktemp("tables")))


@pytest.fixture(scope="session")
def silver(flowers_dir, store):
    """(train_table, val_table, label_to_idx) over the synthetic tree."""
    return prepare_flowers(flowers_dir, store, sample_fraction=1.0, shard_size=16)


@pytest.fixture()
def worker_pythonpath(monkeypatch):
    """Launcher workers import shipped fns by module name; put repo + tests on
    their path (used by the multi-process launcher/trainer tests)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    existing = os.environ.get("PYTHONPATH", "")
    parts = [repo, os.path.join(repo, "tests")] + ([existing] if existing else [])
    monkeypatch.setenv("PYTHONPATH", os.pathsep.join(parts))


@pytest.fixture()
def small_cfgs(tmp_path):
    data = DataCfg(img_height=32, img_width=32, shard_size=16, shuffle_buffer=64,
                   loader_workers=2)
    model = ModelCfg(name="small_cnn", num_classes=5, dropout=0.1, dtype="float32")
    train = TrainCfg(batch_size=8, epochs=2, learning_rate=1e-3, warmup_epochs=0,
                     seed=0, checkpoint_dir=str(tmp_path / "ckpt"))
    return data, model, train
