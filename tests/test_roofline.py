"""Pin the analytic roofline math BASELINE.md's published ceilings rest on.

The measured tool (conv_profile) shares the ConvSpec FLOP/byte models, so
these tests guard both the analysis doc and the on-chip tool's `vs_bound`
column from silent drift.
"""

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", "tools"))

from conv_profile import ConvSpec, mobilenet_v2_convs, resnet50_convs  # noqa: E402
from roofline import model_floor, transformer_floor, PARAMS  # noqa: E402


def test_conv_spec_arithmetic():
    """Hand-checked 1x1 conv: flops and minimal bytes."""
    s = ConvSpec("x", in_hw=8, cin=16, cout=32, k=1, stride=1)
    b = 2
    # fwd MACs*2 = 2*B*HW^2*K^2*Cin*Cout
    assert s.fwd_flops(b) == 2 * b * 64 * 16 * 32
    assert s.flops(b) == 3 * s.fwd_flops(b)
    act_in, act_out = b * 64 * 16 * 2, b * 64 * 32 * 2
    w = 16 * 32 * 2
    assert s.bytes_fwd(b) == act_in + act_out + w
    # bwd: in 3x (2 reads + din), out 2x (write + dout read), w 3x
    assert s.bytes_moved(b) == 3 * act_in + 2 * act_out + 3 * w


def test_depthwise_is_deeply_memory_bound():
    s = ConvSpec("dw", in_hw=56, cin=144, cout=144, k=3, stride=1,
                 groups=144)
    ai = s.flops(256) / s.bytes_moved(256)
    assert ai < 10  # ~1 flop/byte territory; v5e needs 241 to be MXU-bound


def test_published_model_floors():
    """The BASELINE.md table values (rounded) regenerate from the code."""
    mn = model_floor("mn", mobilenet_v2_convs(224), 256, "fwdbwd",
                     PARAMS["mobilenet_v2"])
    rn = model_floor("rn", resnet50_convs(224), 256, "fwdbwd",
                     PARAMS["resnet50"])
    assert abs(mn["floor_ms"] - 21.2) < 0.5, mn["floor_ms"]
    assert 0.09 < mn["mfu_ceiling"] < 0.13
    assert mn["mem_bound_frac"] > 0.95  # "99% memory-bound"
    assert abs(rn["floor_ms"] - 45.8) < 1.0, rn["floor_ms"]
    assert 0.65 < rn["mfu_ceiling"] < 0.75


def test_published_transformer_floors():
    vit = transformer_floor("vit", batch=256, seq=196, hidden=192, depth=6,
                            mlp_dim=768, vocab=5)
    lm = transformer_floor("lm", batch=8, seq=2048, hidden=512, depth=6,
                           mlp_dim=2048, vocab=8192)
    assert vit["bound"] == "mxu" and lm["bound"] == "mxu"
    assert vit["mfu_ceiling"] > 0.9 and lm["mfu_ceiling"] > 0.9
    # cross-checks against XLA's own step counts (BASELINE.md): analytic
    # totals within ~15% of the compiled-step numbers
    assert abs(vit["flops"] - 986e9) / 986e9 < 0.15
    assert abs(lm["flops"] - 3.98e12) / 3.98e12 < 0.15


def test_conv_layer_counts():
    """Model tables enumerate the architectures they claim."""
    mn = mobilenet_v2_convs(224)
    rn = resnet50_convs(224)
    # MobileNetV2: stem + 17 blocks (16 with expand) + top conv
    assert sum(1 for s in mn if s.groups > 1) == 17   # one dw per block
    assert mn[0].name == "stem" and mn[-1].cout == 1280
    # ResNet50: stem + 16 bottlenecks x3 + 4 projections = 53 convs
    assert len(rn) == 53
    assert sum(1 for s in rn if s.k == 3) == 16
