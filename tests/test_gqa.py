"""Grouped-query attention (lm.num_kv_heads): cache economy + the decode and
training paths agreeing with the full forward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ddw_tpu.models.lm import TransformerLM, generate, init_cache

# GQA decode sweeps — beyond the tier-1 wall-clock budget
pytestmark = pytest.mark.slow


def _lm(depth=2, **kw):
    return TransformerLM(vocab_size=32, max_len=64, hidden=32, depth=depth,
                         num_heads=4, dtype=jnp.float32, mlp_dim=64, **kw)


def test_kv_heads_equal_heads_is_mha():
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 8)))
    a = _lm()
    b = _lm(num_kv_heads=4)
    va = a.init({"params": jax.random.PRNGKey(0)}, toks)
    vb = b.init({"params": jax.random.PRNGKey(0)}, toks)
    assert (jax.tree_util.tree_map(lambda x: x.shape, va)
            == jax.tree_util.tree_map(lambda x: x.shape, vb))
    np.testing.assert_allclose(np.asarray(a.apply(va, toks)),
                               np.asarray(b.apply(vb, toks)), rtol=1e-6)


def test_gqa_param_and_cache_economy():
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (2, 8)))
    model = _lm(num_kv_heads=1)  # MQA extreme: 4 query heads share 1 KV head
    params = model.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    attn = params["backbone_block0"]["attn"]
    assert attn["query"]["kernel"].shape == (32, 4, 8)
    assert attn["key"]["kernel"].shape == (32, 1, 8)
    assert attn["value"]["kernel"].shape == (32, 1, 8)
    cache = init_cache(model.clone(decode=True), batch=2)
    ck = cache["backbone_block0"]["attn"]["cached_key"]
    assert ck.shape[2] == 1  # KV heads only: 4x smaller decode cache


def test_gqa_decode_matches_full_forward():
    rng = np.random.RandomState(1)
    model = _lm(num_kv_heads=2)
    toks = jnp.asarray(rng.randint(0, 32, (2, 10)))
    params = model.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    full = model.apply({"params": params}, toks)
    dm = model.clone(decode=True)
    cache = init_cache(dm, 2)
    outs = []
    for t in range(10):
        lg, vars_ = dm.apply({"params": params, "cache": cache},
                             toks[:, t:t + 1], mutable=["cache"])
        cache = vars_["cache"]
        outs.append(lg[:, 0])
    np.testing.assert_allclose(np.asarray(jnp.stack(outs, axis=1)),
                               np.asarray(full), rtol=2e-4, atol=2e-4)


def test_gqa_with_rope_generate():
    model = _lm(num_kv_heads=2, pos_encoding="rope")
    toks = jnp.asarray(np.random.RandomState(2).randint(0, 32, (2, 4)))
    params = model.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    out = generate(model, params, toks, num_steps=4)
    assert out.shape == (2, 4)
    assert not np.any(np.isnan(np.asarray(out)))


def test_gqa_trains():
    import optax

    from ddw_tpu.runtime.mesh import make_mesh, MeshSpec
    from ddw_tpu.train.lm_step import init_lm_state, make_lm_train_step

    model = _lm(num_kv_heads=2)
    mesh = make_mesh(MeshSpec((("data", -1),)))
    state = init_lm_state(model, optax.adam(3e-3), jax.random.PRNGKey(0))
    step = make_lm_train_step(model, optax.adam(3e-3), mesh, "data",
                              seq_axis=None, donate=False)
    rng = np.random.RandomState(3)
    start = rng.randint(0, 32, (8, 1))
    toks = jnp.asarray((start + np.arange(17)) % 32)
    first = last = None
    for i in range(40):
        state, m = step(state, toks[:, :-1], toks[:, 1:], jax.random.PRNGKey(i))
        first = first or float(m["loss"])
        last = float(m["loss"])
    assert last < 0.6 * first


def test_gqa_tp_rules_refuse_loudly():
    """MQA k/v head dims that don't divide the model axis raise a clear
    error at sharding time, not an opaque GSPMD failure at compile time."""
    from ddw_tpu.parallel.sharding import LM_TP_RULES, shardings_for_params
    from ddw_tpu.runtime.mesh import MODEL_AXIS, make_mesh, MeshSpec

    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (1, 4)))
    model = _lm(num_kv_heads=1)
    params = model.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    mesh = make_mesh(MeshSpec((("data", 2), (MODEL_AXIS, 4))))
    with pytest.raises(ValueError, match="not divisible.*GQA"):
        shardings_for_params(params, mesh, LM_TP_RULES)
    # a divisible configuration still shards
    ok = _lm(num_kv_heads=4)
    params_ok = ok.init({"params": jax.random.PRNGKey(0)}, toks)["params"]
    sh = shardings_for_params(params_ok, mesh, LM_TP_RULES)
    q = sh["backbone_block0"]["attn"]["query"]["kernel"]
    assert q.spec == jax.sharding.PartitionSpec(None, MODEL_AXIS, None)


def test_gqa_pp_step_matches_single_device():
    """The pipeline step forwards num_kv_heads to its stage blocks: one
    4-stage PP step == one plain step on a GQA model."""
    import optax

    from ddw_tpu.parallel.pipeline import (init_pp_state, lm_params_from_pp,
                                           make_pp_lm_train_step)
    from ddw_tpu.runtime.mesh import DATA_AXIS, MeshSpec, make_mesh
    from ddw_tpu.train.lm_step import init_lm_state, make_lm_train_step

    n = 4
    mesh_pp = make_mesh(MeshSpec((("pipe", n),)), devices=jax.devices()[:n])
    mesh_1 = make_mesh(MeshSpec(((DATA_AXIS, 1),)), devices=jax.devices()[:1])
    model = _lm(depth=4, dropout=0.0, num_kv_heads=2)
    tx = optax.sgd(1e-1)
    rng = np.random.RandomState(5)
    toks = jnp.asarray(rng.randint(0, 32, (8, 17)))
    ref_state = init_lm_state(model, tx, jax.random.PRNGKey(1))
    ref_step = make_lm_train_step(model, tx, mesh_1, DATA_AXIS, seq_axis=None,
                                  donate=False)
    ref_new, ref_m = ref_step(ref_state, toks[:, :-1], toks[:, 1:],
                              jax.random.PRNGKey(2))
    pp_state = init_pp_state(model, tx, mesh_pp, jax.random.PRNGKey(1))
    step = make_pp_lm_train_step(model, tx, mesh_pp, num_microbatches=4,
                                 donate=False)
    pp_state = step.place_state(pp_state)
    pp_new, pp_m = step(pp_state, toks[:, :-1], toks[:, 1:])
    assert abs(float(pp_m["loss"]) - float(ref_m["loss"])) < 1e-5
    got = lm_params_from_pp(jax.device_get(pp_new.params), n, model.depth)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        got, jax.device_get(ref_new.params))


def test_gqa_validation():
    toks = jnp.asarray(np.random.RandomState(0).randint(0, 32, (1, 4)))
    with pytest.raises(ValueError, match="not divisible by num_kv_heads"):
        _lm(num_kv_heads=3).init({"params": jax.random.PRNGKey(0)}, toks)
