"""The transfer-learning accuracy mechanism end-to-end (VERDICT r1 missing #1).

The reference's entire accuracy story is a frozen *pretrained* backbone
(``02_model_training_single_node.py:164-169``). This test proves the machinery
delivers that story: a backbone pretrained on a task, frozen, then re-headed,
must beat a frozen *random* backbone on the same task.

The task is built so GAP-of-features only helps if the features encode spatial
structure: classes are sinusoidal gratings differing in orientation with
identical per-image mean/variance, so color statistics (which survive any
random conv into global average pooling) carry no label signal.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ddw_tpu.models.convert import save_pretrained
from ddw_tpu.models.registry import build_model
from ddw_tpu.runtime.mesh import make_mesh, MeshSpec
from ddw_tpu.train.step import init_state, make_eval_step, make_train_step
from ddw_tpu.utils.config import ModelCfg, TrainCfg

HW = 32
N_CLASSES = 5


def _gratings(rng: np.random.RandomState, n: int):
    """Per-class orientation gratings, random phase/frequency jitter + noise."""
    labels = rng.randint(0, N_CLASSES, size=n).astype(np.int32)
    ii, jj = np.meshgrid(np.arange(HW), np.arange(HW), indexing="ij")
    imgs = np.empty((n, HW, HW, 3), np.float32)
    for k in range(n):
        theta = labels[k] * np.pi / N_CLASSES
        freq = 0.55 + 0.1 * rng.rand()
        phase = rng.rand() * 2 * np.pi
        wave = np.sin(freq * (ii * np.cos(theta) + jj * np.sin(theta)) + phase)
        img = wave[..., None] + 0.25 * rng.randn(HW, HW, 3)
        img -= img.mean()
        img /= img.std() + 1e-6
        imgs[k] = img
    return imgs, labels


def _run(model_cfg: ModelCfg, imgs, labels, val_imgs, val_labels, steps: int,
         lr: float = 3e-3, seed: int = 0):
    """Train `steps` minibatch steps on a 1-device mesh; return final val acc
    and the trained state."""
    import warnings

    mesh = make_mesh(MeshSpec((("data", 1),)), devices=jax.devices()[:1])
    tcfg = TrainCfg(batch_size=64, optimizer="adam", learning_rate=lr, seed=seed)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        model = build_model(model_cfg)
    state, tx = init_state(model, model_cfg, tcfg, (HW, HW, 3),
                           jax.random.PRNGKey(seed))
    step = make_train_step(model, tx, mesh, donate=False)
    eval_step = make_eval_step(model, mesh)
    key = jax.random.PRNGKey(seed + 1)
    n = len(imgs)
    rng = np.random.RandomState(seed)
    for s in range(steps):
        idx = rng.randint(0, n, size=64)
        state, _ = step(state, jnp.asarray(imgs[idx]),
                        jnp.asarray(labels[idx]), key)
    metrics = eval_step(state, jnp.asarray(val_imgs), jnp.asarray(val_labels))
    return float(metrics["accuracy"]), state, model


def test_frozen_pretrained_beats_frozen_random(tmp_path):
    rng = np.random.RandomState(0)
    imgs, labels = _gratings(rng, 512)
    val_imgs, val_labels = _gratings(np.random.RandomState(99), 128)

    base_cfg = dict(name="mobilenet_v2", num_classes=N_CLASSES, dropout=0.0,
                    width_mult=0.35, dtype="float32")

    # 1. pretrain unfrozen from scratch — the "ImageNet" stand-in
    pre_acc, pre_state, _ = _run(
        ModelCfg(freeze_base=False, **base_cfg), imgs, labels,
        val_imgs, val_labels, steps=80)
    assert pre_acc > 0.8, f"pretraining itself failed to learn ({pre_acc})"

    art = str(tmp_path / "pretrained.npz")
    save_pretrained(art, {"params": pre_state.params["backbone"],
                          "batch_stats": pre_state.batch_stats["backbone"]})

    # 2. frozen-pretrained: new head over the pretrained features
    tuned_acc, _, m = _run(
        ModelCfg(freeze_base=True, pretrained_path=art, **base_cfg),
        imgs, labels, val_imgs, val_labels, steps=80, seed=7)
    assert m.freeze_base is True

    # 3. frozen-random: the footgun configuration, explicitly opted into
    random_acc, _, m = _run(
        ModelCfg(freeze_base=True, allow_frozen_random=True, **base_cfg),
        imgs, labels, val_imgs, val_labels, steps=80, seed=7)
    assert m.freeze_base is True

    assert tuned_acc >= random_acc + 0.15, (
        f"frozen-pretrained {tuned_acc:.3f} must beat frozen-random "
        f"{random_acc:.3f} decisively")
    assert tuned_acc > 0.6
